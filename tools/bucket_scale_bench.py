#!/usr/bin/env python
"""Bucket scale evidence: a synthetic 1M-entry ledger flows through the
disk-tier BucketList and back out of a catchup-style streaming read with
bounded RSS.  Since r06 the run exercises the REAL close configuration:
background merges on a worker pool (FutureBucket promise chain) with the
native streaming merge kernel, so close_ms_max measures what a validator
would stall, not the synchronous worst case.  Since r07 every bucket is
indexed at creation/merge time (bloom + key/offset table,
bucket/index.py), so close_ms_p50 carries the index-build cost the
BucketListDB read path pays — the acceptance bar is <10% over r06's
69.1ms.  Writes BUCKET_SCALE_r07.json including the merge-pipeline
counters (sync_fallback_merges must be 0).

Usage: python tools/bucket_scale_bench.py [n_entries] [per_close]
"""
import json
import os
import resource
import statistics
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ["JAX_PLATFORMS"] = "cpu"


def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def main():
    n_entries = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    per_close = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000

    from stellar_core_tpu.bucket.bucket_list import BucketList
    from stellar_core_tpu.bucket.disk_bucket import DiskBucket
    from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
    from stellar_core_tpu.transactions import utils as U

    from concurrent.futures import ThreadPoolExecutor

    def build(indexed):
        tmp = tempfile.mkdtemp(prefix="bucket-scale-")
        executor = ThreadPoolExecutor(max_workers=2,
                                      thread_name_prefix="bucket-merge")
        bl = BucketList(executor=executor, disk_dir=tmp, disk_level=2)
        bl.index_enabled = indexed
        t_start = time.time()
        close_times = []
        seq = 1
        made = 0
        while made < n_entries:
            seq += 1
            changes = []
            for j in range(min(per_close, n_entries - made)):
                i = made + j
                e = U.make_account_entry(
                    i.to_bytes(4, "big") * 8, 10_000_000 + i)
                changes.append((key_bytes(entry_to_key(e)), e, False))
            made += len(changes)
            t0 = time.perf_counter()
            bl.add_batch(seq, changes)
            close_times.append(time.perf_counter() - t0)
            if seq % 50 == 0:
                print(f"seq {seq} (indexed={indexed}): {made} entries, "
                      f"rss {rss_mb():.0f}MB", flush=True)
        build_s = time.time() - t_start
        executor.shutdown(wait=True)
        return bl, tmp, close_times, build_s, seq

    # index-off baseline FIRST (same session, same machine state): the
    # r07 acceptance bar is "index build adds <10% to close_ms_p50",
    # which only a same-run A/B can attribute honestly
    rss_start = rss_mb()
    bl0, tmp0, close_times_noidx, build_s_noidx, _ = build(False)
    import shutil

    del bl0
    shutil.rmtree(tmp0, ignore_errors=True)

    bl, tmp, close_times, build_s, seq = build(True)
    rss_after_build = rss_mb()

    # catchup-style streaming read of the full live set
    t0 = time.time()
    count = 0
    for _ in bl.iter_live_entries():
        count += 1
    stream_s = time.time() - t0
    rss_after_stream = rss_mb()
    assert count == n_entries, (count, n_entries)

    disk_files = [f for f in os.listdir(tmp)
                  if f.startswith("bucket-") and f.endswith(".xdr")]
    disk_bytes = sum(
        os.path.getsize(os.path.join(tmp, f)) for f in disk_files)
    disk_levels = sum(
        1 for lv in bl.levels for b in (lv.curr, lv.snap)
        if isinstance(b, DiskBucket) and not b.is_empty())

    out = {
        "n_entries": n_entries,
        "per_close": per_close,
        "closes": seq - 1,
        "build_seconds": round(build_s, 1),
        "close_ms_p50": round(
            statistics.median(close_times) * 1000, 1),
        "close_ms_max": round(max(close_times) * 1000, 1),
        "close_ms_p50_noindex": round(
            statistics.median(close_times_noidx) * 1000, 1),
        "close_ms_max_noindex": round(
            max(close_times_noidx) * 1000, 1),
        "index_overhead_pct": round(
            (statistics.median(close_times)
             / statistics.median(close_times_noidx) - 1) * 100, 1),
        "stream_read_seconds": round(stream_s, 1),
        "streamed_entries": count,
        "rss_mb_start": round(rss_start, 1),
        "rss_mb_after_build": round(rss_after_build, 1),
        "rss_mb_after_stream": round(rss_after_stream, 1),
        "disk_bucket_files": len(disk_files),
        "disk_bucket_bytes": disk_bytes,
        "disk_backed_buckets_live": disk_levels,
        "bucket_hash": bl.hash().hex(),
        "merge_pipeline": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in bl.stats.items()},
        "background_merges": True,
        "index_build_ms_per_close": round(
            bl.stats["index_build_s"] * 1000 / (seq - 1), 3),
        "index_memory_bytes": bl.index_memory_bytes(),
    }
    with open(os.path.join(REPO, "BUCKET_SCALE_r07.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
