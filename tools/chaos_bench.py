#!/usr/bin/env python
"""Chaos scenario bench: the scripted fault suite at network scale.

Runs every standard chaos scenario (simulation/chaos.py) on two tiers —
a quick fully-connected core-4 and a tiered/org ``hierarchical_quorum``
network of >= 50 validators — and persists per-scenario evidence to
``CHAOS_BENCH_r11.json``:

- close latency over the whole hostile run (network-wide externalize
  spread in virtual ms, wall ms per round, virtual cadence p50/p99),
- time-to-heal: virtual seconds from the last fault clearing until the
  LAST honest survivor externalized the convergence target,
- fault counters (drops/damage/duplicates/cuts/reconnects,
  equivocations emitted, stale envelopes replayed and discarded),
- fork check: header-chain AND bucket-hash agreement over every pair of
  honest survivors (the run aborts on the first divergence),
- the determinism contract: every scenario re-runs under the SAME chaos
  seed and must reproduce its fingerprint (one hash over every honest
  node's (seq, header-hash) externalize sequence) byte-for-byte.

Usage:
    python -m tools.chaos_bench                 # full suite (~15 min)
    python -m tools.chaos_bench --tier core4    # quick tier only
    python -m tools.chaos_bench --scenario partition_heal --tier tiered50
    python -m tools.chaos_bench --no-rerun      # skip determinism reruns
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellar_core_tpu.simulation.chaos import (  # noqa: E402
    STANDARD_SCENARIOS, run_standard_scenario)
from stellar_core_tpu.simulation.simulation import (  # noqa: E402
    core, hierarchical_quorum)

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "CHAOS_BENCH_r11.json")

TIERS = {
    # label -> (factory(persist_dir), n_nodes, scenario duration s)
    # core4 runs PIPELINED_CLOSE on (4 tail workers): every chaos
    # scenario — partitions, mid-close kill-restore, Byzantine twins —
    # then exercises the overlap contract (write-ahead overlay, depth-1
    # barrier, seal-to-commit crash window), not just the synchronous
    # close.  tiered50 stays pipeline-off: 50 tail workers in one
    # process would add ~50 threads and the tier's wall budget
    # (~13 s/virtual-second, dominated by quorum evaluation) predates
    # the pipeline; re-budget before flipping it.
    "core4": (lambda d: core(4, persist_dir=d, MANUAL_CLOSE=False,
                             PIPELINED_CLOSE=True), 4, 18.0),
    "tiered50": (lambda d: hierarchical_quorum(
        10, 5, persist_dir=d, MANUAL_CLOSE=False), 50, 12.0),
}


def run_one(tier: str, scenario: str, seed: int, rerun: bool) -> dict:
    factory, n, duration = TIERS[tier]
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as d:
        rep = run_standard_scenario(
            lambda: factory(d), scenario, seed=seed, n_nodes=n,
            duration=duration)
    rep["bench_wall_s"] = round(time.monotonic() - t0, 1)
    rep["tier"] = tier
    if rerun:
        with tempfile.TemporaryDirectory() as d:
            rep2 = run_standard_scenario(
                lambda: factory(d), scenario, seed=seed, n_nodes=n,
                duration=duration)
        assert rep2["fingerprint"] == rep["fingerprint"], (
            f"[{tier}/{scenario}] chaos seed {seed} NOT deterministic: "
            f"{rep['fingerprint']} vs {rep2['fingerprint']}")
        rep["rerun_identical"] = True
    del rep["events"]  # scripted, identical across runs; keep JSON lean
    return rep


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tier", choices=sorted(TIERS), action="append",
                    help="run only this tier (repeatable; default all)")
    ap.add_argument("--scenario", choices=STANDARD_SCENARIOS,
                    action="append",
                    help="run only this scenario (repeatable; default all)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--no-rerun", action="store_true",
                    help="skip the same-seed determinism rerun")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()

    tiers = args.tier or sorted(TIERS)
    scenarios = args.scenario or list(STANDARD_SCENARIOS)
    results = []
    for tier in tiers:
        for scenario in scenarios:
            print(f"[chaos_bench] {tier}/{scenario} (seed {args.seed}) ...",
                  flush=True)
            rep = run_one(tier, scenario, args.seed, not args.no_rerun)
            results.append(rep)
            print(f"[chaos_bench]   ledgers={rep['ledgers_closed']} "
                  f"heal={rep['time_to_heal_s']}s "
                  f"spread_p99={rep['close_spread_virtual_ms']['p99']}ms "
                  f"fork={rep['fork_check']} "
                  f"rerun_identical={rep.get('rerun_identical', 'skipped')} "
                  f"wall={rep['bench_wall_s']}s", flush=True)

    doc = {
        "bench": "chaos scenario suite",
        "seed": args.seed,
        "tiers": {t: {"nodes": TIERS[t][1], "duration_s": TIERS[t][2]}
                  for t in tiers},
        "scenarios": results,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"[chaos_bench] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
