#!/usr/bin/env python
"""Chaos scenario bench: the scripted fault suite at network scale.

Runs every standard chaos scenario (simulation/chaos.py) on two tiers —
a quick fully-connected core-4 and a tiered/org ``hierarchical_quorum``
network of >= 50 validators — and persists per-scenario evidence to
``CHAOS_BENCH_r11.json``:

- close latency over the whole hostile run (network-wide externalize
  spread in virtual ms, wall ms per round, virtual cadence p50/p99),
- time-to-heal: virtual seconds from the last fault clearing until the
  LAST honest survivor externalized the convergence target,
- fault counters (drops/damage/duplicates/cuts/reconnects,
  equivocations emitted, stale envelopes replayed and discarded),
- fork check: header-chain AND bucket-hash agreement over every pair of
  honest survivors (the run aborts on the first divergence),
- the determinism contract: every scenario re-runs under the SAME chaos
  seed and must reproduce its fingerprint (one hash over every honest
  node's (seq, header-hash) externalize sequence) byte-for-byte.

Usage:
    python -m tools.chaos_bench                 # full suite (~15 min)
    python -m tools.chaos_bench --tier core4    # quick tier only
    python -m tools.chaos_bench --scenario partition_heal --tier tiered50
    python -m tools.chaos_bench --no-rerun      # skip determinism reruns
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellar_core_tpu.simulation.chaos import (  # noqa: E402
    STANDARD_SCENARIOS, run_standard_scenario)
from stellar_core_tpu.simulation.simulation import (  # noqa: E402
    core, hierarchical_quorum)

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "CHAOS_BENCH_r11.json")

TIERS = {
    # label -> (factory(persist_dir), n_nodes, scenario duration s)
    # core4 runs PIPELINED_CLOSE on (4 tail workers): every chaos
    # scenario — partitions, mid-close kill-restore, Byzantine twins —
    # then exercises the overlap contract (write-ahead overlay, depth-1
    # barrier, seal-to-commit crash window), not just the synchronous
    # close.  tiered50 stays pipeline-off: 50 tail workers in one
    # process would add ~50 threads and the tier's wall budget
    # (~13 s/virtual-second, dominated by quorum evaluation) predates
    # the pipeline; re-budget before flipping it.
    "core4": (lambda d: core(4, persist_dir=d, MANUAL_CLOSE=False,
                             PIPELINED_CLOSE=True), 4, 18.0),
    "tiered50": (lambda d: hierarchical_quorum(
        10, 5, persist_dir=d, MANUAL_CLOSE=False), 50, 12.0),
}


def _dump_rerun_mismatch(tier: str, scenario: str, seed: int,
                         rep: dict, rep2: dict,
                         forensics_dir: str) -> str:
    """The rerun-mismatch oracle's forensic artifact: both runs'
    per-node externalize maps plus the first (node, seq) whose hash
    differed between the runs — determinism bugs get named, not just
    detected."""
    first = None
    a, b = rep["per_node_externalized"], rep2["per_node_externalized"]
    for node in sorted(set(a) | set(b)):
        for s in sorted(set(a.get(node, {})) | set(b.get(node, {})),
                        key=int):
            ha, hb = a.get(node, {}).get(s), b.get(node, {}).get(s)
            if ha != hb and first is None:
                first = {"node": node, "slot": int(s),
                         "run1": ha, "run2": hb}
    doc = {"forensics_schema": 1,
           "scenario": f"rerun_{tier}_{scenario}",
           "seed": seed,
           "reason": "same-seed rerun fingerprint mismatch",
           "first_divergence": first,
           "run1": {"fingerprint": rep["fingerprint"],
                    "per_node_externalized": a},
           "run2": {"fingerprint": rep2["fingerprint"],
                    "per_node_externalized": b}}
    os.makedirs(forensics_dir, exist_ok=True)
    path = os.path.join(
        forensics_dir,
        f"FORENSICS_rerun_{tier}_{scenario}_seed{seed}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def run_one(tier: str, scenario: str, seed: int, rerun: bool,
            forensics_dir: str) -> dict:
    factory, n, duration = TIERS[tier]
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as d:
        rep = run_standard_scenario(
            lambda: factory(d), scenario, seed=seed, n_nodes=n,
            duration=duration, forensics_dir=forensics_dir)
    rep["bench_wall_s"] = round(time.monotonic() - t0, 1)
    rep["tier"] = tier
    if rerun:
        with tempfile.TemporaryDirectory() as d:
            rep2 = run_standard_scenario(
                lambda: factory(d), scenario, seed=seed, n_nodes=n,
                duration=duration, forensics_dir=forensics_dir)
        if rep2["fingerprint"] != rep["fingerprint"]:
            path = _dump_rerun_mismatch(tier, scenario, seed, rep, rep2,
                                        forensics_dir)
            raise AssertionError(
                f"[{tier}/{scenario}] chaos seed {seed} NOT "
                f"deterministic: {rep['fingerprint']} vs "
                f"{rep2['fingerprint']}\n[forensics] {path}")
        rep["rerun_identical"] = True
    # scripted events + raw externalize maps are identical across runs
    # (or dumped above on mismatch); keep the persisted JSON lean
    del rep["events"]
    del rep["per_node_externalized"]
    return rep


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tier", choices=sorted(TIERS), action="append",
                    help="run only this tier (repeatable; default all)")
    ap.add_argument("--scenario", choices=STANDARD_SCENARIOS,
                    action="append",
                    help="run only this scenario (repeatable; default all)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--no-rerun", action="store_true",
                    help="skip the same-seed determinism rerun")
    ap.add_argument("--out", default=OUT)
    ap.add_argument("--forensics-dir",
                    default=os.path.dirname(OUT),
                    help="where oracle failures dump FORENSICS_*.json")
    args = ap.parse_args()

    tiers = args.tier or sorted(TIERS)
    scenarios = args.scenario or list(STANDARD_SCENARIOS)
    results = []
    for tier in tiers:
        for scenario in scenarios:
            print(f"[chaos_bench] {tier}/{scenario} (seed {args.seed}) ...",
                  flush=True)
            rep = run_one(tier, scenario, args.seed, not args.no_rerun,
                          args.forensics_dir)
            results.append(rep)
            print(f"[chaos_bench]   ledgers={rep['ledgers_closed']} "
                  f"heal={rep['time_to_heal_s']}s "
                  f"spread_p99={rep['close_spread_virtual_ms']['p99']}ms "
                  f"fork={rep['fork_check']} "
                  f"rerun_identical={rep.get('rerun_identical', 'skipped')} "
                  f"wall={rep['bench_wall_s']}s", flush=True)

    doc = {
        "bench": "chaos scenario suite",
        "seed": args.seed,
        "tiers": {t: {"nodes": TIERS[t][1], "duration_s": TIERS[t][2]}
                  for t in tiers},
        "scenarios": results,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"[chaos_bench] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
