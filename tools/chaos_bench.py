#!/usr/bin/env python
"""Chaos scenario bench: the scripted fault suite at network scale.

Runs every standard chaos scenario (simulation/chaos.py) on two tiers —
a quick fully-connected core-4 and a tiered/org ``hierarchical_quorum``
network of >= 50 validators — and persists per-scenario evidence to
``CHAOS_BENCH_r11.json``:

- close latency over the whole hostile run (network-wide externalize
  spread in virtual ms, wall ms per round, virtual cadence p50/p99),
- time-to-heal: virtual seconds from the last fault clearing until the
  LAST honest survivor externalized the convergence target,
- fault counters (drops/damage/duplicates/cuts/reconnects,
  equivocations emitted, stale envelopes replayed and discarded),
- fork check: header-chain AND bucket-hash agreement over every pair of
  honest survivors (the run aborts on the first divergence),
- the determinism contract: every scenario re-runs under the SAME chaos
  seed and must reproduce its fingerprint (one hash over every honest
  node's (seq, header-hash) externalize sequence) byte-for-byte.

Usage:
    python -m tools.chaos_bench                 # full suite (~15 min)
    python -m tools.chaos_bench --tier core4    # quick tier only
    python -m tools.chaos_bench --scenario partition_heal --tier tiered50
    python -m tools.chaos_bench --no-rerun      # skip determinism reruns
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellar_core_tpu.simulation.chaos import (  # noqa: E402
    STANDARD_SCENARIOS, run_standard_scenario)
from stellar_core_tpu.simulation.simulation import (  # noqa: E402
    core, hierarchical_quorum)

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "CHAOS_BENCH_r11.json")
NETOBS_OUT = os.path.join(os.path.dirname(OUT), "NET_OBS_r19.json")

TIERS = {
    # label -> (factory(persist_dir), n_nodes, scenario duration s)
    # core4 runs PIPELINED_CLOSE on (4 tail workers): every chaos
    # scenario — partitions, mid-close kill-restore, Byzantine twins —
    # then exercises the overlap contract (write-ahead overlay, depth-1
    # barrier, seal-to-commit crash window), not just the synchronous
    # close.  tiered50 stays pipeline-off: 50 tail workers in one
    # process would add ~50 threads and the tier's wall budget
    # (~13 s/virtual-second, dominated by quorum evaluation) predates
    # the pipeline; re-budget before flipping it.
    "core4": (lambda d: core(4, persist_dir=d, MANUAL_CLOSE=False,
                             PIPELINED_CLOSE=True), 4, 18.0),
    "tiered50": (lambda d: hierarchical_quorum(
        10, 5, persist_dir=d, MANUAL_CLOSE=False), 50, 12.0),
}


def _dump_rerun_mismatch(tier: str, scenario: str, seed: int,
                         rep: dict, rep2: dict,
                         forensics_dir: str) -> str:
    """The rerun-mismatch oracle's forensic artifact: both runs'
    per-node externalize maps plus the first (node, seq) whose hash
    differed between the runs — determinism bugs get named, not just
    detected."""
    first = None
    a, b = rep["per_node_externalized"], rep2["per_node_externalized"]
    for node in sorted(set(a) | set(b)):
        for s in sorted(set(a.get(node, {})) | set(b.get(node, {})),
                        key=int):
            ha, hb = a.get(node, {}).get(s), b.get(node, {}).get(s)
            if ha != hb and first is None:
                first = {"node": node, "slot": int(s),
                         "run1": ha, "run2": hb}
    doc = {"forensics_schema": 1,
           "scenario": f"rerun_{tier}_{scenario}",
           "seed": seed,
           "reason": "same-seed rerun fingerprint mismatch",
           "first_divergence": first,
           "run1": {"fingerprint": rep["fingerprint"],
                    "per_node_externalized": a},
           "run2": {"fingerprint": rep2["fingerprint"],
                    "per_node_externalized": b}}
    os.makedirs(forensics_dir, exist_ok=True)
    path = os.path.join(
        forensics_dir,
        f"FORENSICS_rerun_{tier}_{scenario}_seed{seed}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def run_one(tier: str, scenario: str, seed: int, rerun: bool,
            forensics_dir: str) -> dict:
    factory, n, duration = TIERS[tier]
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as d:
        rep = run_standard_scenario(
            lambda: factory(d), scenario, seed=seed, n_nodes=n,
            duration=duration, forensics_dir=forensics_dir)
    rep["bench_wall_s"] = round(time.monotonic() - t0, 1)
    rep["tier"] = tier
    if rerun:
        with tempfile.TemporaryDirectory() as d:
            rep2 = run_standard_scenario(
                lambda: factory(d), scenario, seed=seed, n_nodes=n,
                duration=duration, forensics_dir=forensics_dir)
        if rep2["fingerprint"] != rep["fingerprint"]:
            path = _dump_rerun_mismatch(tier, scenario, seed, rep, rep2,
                                        forensics_dir)
            raise AssertionError(
                f"[{tier}/{scenario}] chaos seed {seed} NOT "
                f"deterministic: {rep['fingerprint']} vs "
                f"{rep2['fingerprint']}\n[forensics] {path}")
        rep["rerun_identical"] = True
    # scripted events + raw externalize maps are identical across runs
    # (or dumped above on mismatch); keep the persisted JSON lean
    del rep["events"]
    del rep["per_node_externalized"]
    return rep


# ---------------------------------------------------------------------------
# network-observatory bench (r19): propagation percentiles + per-link
# redundancy + crank wall attribution under chaos + loadgen rate mode,
# with the tracing on/off overhead + inertness gates
# ---------------------------------------------------------------------------

NETOBS_TIERS = {
    # label -> (factory(persist_dir, **config_kw), n_nodes,
    #           loadgen tx/s, load window virtual s)
    "core4": (lambda d, **kw: core(4, persist_dir=d, MANUAL_CLOSE=False,
                                   PIPELINED_CLOSE=True, **kw),
              4, 20.0, 6.0),
    "tiered50": (lambda d, **kw: hierarchical_quorum(
        10, 5, persist_dir=d, MANUAL_CLOSE=False, **kw), 50, 5.0, 4.0),
}


def _median(xs):
    s = sorted(xs)
    return s[len(s) // 2] if s else None


def _run_digests(sim) -> dict:
    """Deterministic digests of everything consensus produced: one hash
    over every node's (seq, header hash, bucket hash) chain and one over
    every node's LedgerCloseMeta stream — the on/off inertness oracle."""
    import hashlib

    from stellar_core_tpu.xdr import types as T

    hh = hashlib.sha256()
    hm = hashlib.sha256()
    for nid in sorted(sim.nodes):
        chain = sim.header_chain(nid)
        hh.update(nid)
        for seq in sorted(chain):
            header_hash, bucket_hash = chain[seq]
            hh.update(seq.to_bytes(4, "big"))
            hh.update(header_hash)
            hh.update(bucket_hash)
        hm.update(nid)
        for meta in sim.nodes[nid]._meta_stream:
            hm.update(T.LedgerCloseMeta.encode(meta))
    return {"hashes": hh.hexdigest(), "meta": hm.hexdigest()}


def netobs_run(tier: str, seed: int, trace_on: bool) -> dict:
    """One instrumented run: core-N under loadgen rate mode with a
    partition/heal fault window and 50 ms of injected minority-link
    latency (so propagation percentiles measure something), the
    observatory + crank profiler armed throughout."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.simulation.chaos import ChaosEngine
    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    factory, n, rate, load_s = NETOBS_TIERS[tier]
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as d:
        sim = factory(d, FLOOD_TRACE_ENABLED=trace_on)
        sim.attach_observatory()
        sim.start_all_nodes()
        while sim.crank():
            pass  # handshakes settle at t=0
        sim.enable_crank_profiler()
        ids = sorted(sim.nodes)
        app0 = sim.nodes[ids[0]]
        assert sim.crank_until(lambda: sim.have_all_externalized(2), 120)

        # seed loadgen accounts THROUGH consensus — a direct ledger
        # write on one node of a live network would be a fork
        lg = LoadGenerator(app0)
        for env in lg.create_account_envelopes(8):
            assert app0.herder.recv_transaction(env) == 0

        def _seeded():
            with LedgerTxn(app0.ledger_manager.root) as ltx:
                e = ltx.load_account(lg.accounts[-1].public_key().raw)
                ltx.rollback()
            return e is not None

        assert sim.crank_until(_seeded, 120), "account seeding stalled"

        chaos = ChaosEngine(sim, seed=seed)
        chaos.start_maintenance()  # cut links need periodic re-dials
        lg.start_rate_run("pay", rate=rate, duration=load_s)
        minority = ids[: max(1, (n - 1) // 3)]
        majority = [i for i in ids if i not in minority]
        # latency on the minority's links: nonzero hop deltas for the
        # coverage percentiles even while the partition is open
        chaos.lag(minority[0], 0.05)
        sim.crank_for(1.5)
        chaos.partition([minority, majority])
        sim.crank_for(load_s / 2.0)
        chaos.heal()
        chaos.clear_links()
        chaos.maintain_links_once()
        sim.crank_for(load_s / 2.0)
        lg.stop_rate_run()
        target = max(a.ledger_manager.last_closed_seq()
                     for a in sim.alive_nodes().values()) + 2
        assert sim.crank_until(
            lambda: sim.have_all_externalized(target), 240), \
            "post-heal convergence stalled"
        sim.assert_no_forks()

        obs = sim.observatory.summary()
        n_items = sum(a.floodtracer.stats()["live"]
                      + a.floodtracer.stats()["retired"]
                      for a in sim.nodes.values())
        crank = sim.crank_report()
        close_p50 = _median(
            [a.metrics.timer("ledger.ledger.close").summary()["p50"]
             for a in sim.nodes.values()])
        # flood stamp volume, for the disabled-cost scaling: inbound
        # flood copies (counted tracing on or off) and closes per node
        flood_events = sum(
            a.metrics.counter("overlay.flood.unique").count
            + a.metrics.counter("overlay.flood.duplicate").count
            for a in sim.nodes.values())
        closes = sum(
            a.metrics.timer("ledger.ledger.close").summary()["count"]
            for a in sim.nodes.values())
        digests = _run_digests(sim)
        rate_rep = lg.rate_status()
        chaos.stop()
        for app in sim.nodes.values():
            app.stop_node()
    return {
        "tier": tier,
        "trace_enabled": trace_on,
        "n_nodes": n,
        "hop_records_total": n_items,
        "observatory": obs,
        "crank_attribution": crank,
        "close_p50_wall_s": round(close_p50, 6) if close_p50 else None,
        "flood_events_total": flood_events,
        "closes_total": closes,
        "loadgen": {"submitted": rate_rep.get("submitted", 0),
                    "ticks": rate_rep.get("ticks", 0)},
        "digests": digests,
        "bench_wall_s": round(time.monotonic() - t0, 1),
    }


def _disabled_stamp_cost_s() -> float:
    """Per-site cost of a DISABLED tracker stamp.  Every flood site is
    guard-shaped — `ft = app.floodtracer; if ft.enabled: ...` — so the
    disabled path executes two attribute loads and a branch, nothing
    else.  Measure exactly that (loop overhead subtracted, floored at
    10ns so the gate never divides by a measurement artifact), then
    scale by the run's observed flood volume for the <2% gate."""
    from stellar_core_tpu.utils.floodtrace import FloodPropagationTracker
    from stellar_core_tpu.utils.metrics import MetricsRegistry

    app = type("_App", (), {})()
    app.floodtracer = FloodPropagationTracker(metrics=MetricsRegistry(),
                                              enabled=False)
    n = 500_000

    def _site_loop(count):
        t0 = time.perf_counter()
        for _ in range(count):
            ft = app.floodtracer
            if ft.enabled:
                ft.note_recv(b"", "", True, "tx", 1)
        return time.perf_counter() - t0

    def _empty_loop(count):
        t0 = time.perf_counter()
        for _ in range(count):
            pass
        return time.perf_counter() - t0

    _site_loop(n // 10)  # warm
    _empty_loop(n // 10)
    site = min(_site_loop(n) for _ in range(3))
    empty = min(_empty_loop(n) for _ in range(3))
    return max((site - empty) / n, 1e-8)


def run_netobs(tiers, seed: int, out: str) -> dict:
    """The NET_OBS_r19 evidence run: per tier, tracing ON for the
    observatory evidence and OFF for the inertness A/B.  The <2%-of-
    close-p50 overhead gate is the DISABLED cost (the PR-13 bar: the
    attribute check per flood site, microbenched and scaled by the
    run's measured flood volume per close); the enabled on/off close
    delta is reported honestly but not gated — at sim scale it measures
    allocator/GC pressure on millisecond closes, not the per-site cost
    a production node pays."""
    results = {}
    stamp_s = _disabled_stamp_cost_s()
    for tier in tiers:
        print(f"[netobs] {tier} trace=on (seed {seed}) ...", flush=True)
        on = netobs_run(tier, seed, True)
        print(f"[netobs] {tier} trace=off ...", flush=True)
        off = netobs_run(tier, seed, False)

        inert = on["digests"] == off["digests"]
        p_on, p_off = on["close_p50_wall_s"], off["close_p50_wall_s"]
        enabled_pct = round((p_on - p_off) / p_off * 100.0, 2) \
            if p_on and p_off else None
        # disabled cost: ~2 stamp sites per inbound copy (recv stamp +
        # the broadcast-site enabled checks), per close, vs close p50
        sites_per_close = (2.0 * off["flood_events_total"]
                           / max(1, off["closes_total"]))
        disabled_pct = round(
            stamp_s * sites_per_close / p_off * 100.0, 4) \
            if p_off else None
        prop = on["observatory"]["propagation"]
        results[tier] = {
            "on": on, "off": {k: off[k] for k in
                              ("close_p50_wall_s", "digests",
                               "crank_attribution", "bench_wall_s",
                               "flood_events_total", "closes_total")},
            "gates": {
                "hop_records_nonzero": on["hop_records_total"] > 0,
                "coverage_percentiles_present":
                    prop["time_to_90pct"] is not None,
                "disabled_stamp_us": round(stamp_s * 1e6, 3),
                "stamp_sites_per_close": round(sites_per_close, 1),
                "tracing_overhead_pct": disabled_pct,
                "tracing_overhead_ok": disabled_pct is not None
                and disabled_pct < 2.0,
                "enabled_overhead_pct": enabled_pct,
                "inert_hashes_and_meta": inert,
                "attributed_pct":
                    on["crank_attribution"]["attributed_pct"],
                "attribution_ok":
                    on["crank_attribution"]["attributed_pct"] >= 90.0,
            },
        }
        g = results[tier]["gates"]
        print(f"[netobs]   hop_records={on['hop_records_total']} "
              f"t90={prop['time_to_90pct']} "
              f"disabled={g['tracing_overhead_pct']}% "
              f"(enabled A/B {g['enabled_overhead_pct']}%) "
              f"inert={g['inert_hashes_and_meta']} "
              f"attributed={g['attributed_pct']}% "
              f"wall={on['bench_wall_s']}+{off['bench_wall_s']}s",
              flush=True)
        assert g["hop_records_nonzero"], f"{tier}: no hop records"
        assert g["coverage_percentiles_present"], \
            f"{tier}: no coverage percentiles"
        assert g["inert_hashes_and_meta"], \
            f"{tier}: tracing on/off NOT bit-identical"
        assert g["tracing_overhead_ok"], \
            f"{tier}: disabled cost {g['tracing_overhead_pct']}% >= 2%"
        assert g["attribution_ok"], \
            f"{tier}: only {g['attributed_pct']}% of wall attributed"

    doc = {"bench": "network observatory (r19)", "seed": seed,
           "tiers": results}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"[netobs] wrote {out}")
    return doc


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tier", choices=sorted(TIERS), action="append",
                    help="run only this tier (repeatable; default all)")
    ap.add_argument("--scenario", choices=STANDARD_SCENARIOS,
                    action="append",
                    help="run only this scenario (repeatable; default all)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--no-rerun", action="store_true",
                    help="skip the same-seed determinism rerun")
    ap.add_argument("--netobs", action="store_true",
                    help="run the network-observatory bench instead "
                         "(NET_OBS_r19.json): propagation percentiles, "
                         "crank wall attribution, on/off overhead + "
                         "inertness gates under chaos + loadgen")
    ap.add_argument("--out", default=None)
    ap.add_argument("--forensics-dir",
                    default=os.path.dirname(OUT),
                    help="where oracle failures dump FORENSICS_*.json")
    args = ap.parse_args()

    if args.netobs:
        run_netobs(args.tier or sorted(NETOBS_TIERS), args.seed,
                   args.out or NETOBS_OUT)
        return 0

    args.out = args.out or OUT
    tiers = args.tier or sorted(TIERS)
    scenarios = args.scenario or list(STANDARD_SCENARIOS)
    results = []
    for tier in tiers:
        for scenario in scenarios:
            print(f"[chaos_bench] {tier}/{scenario} (seed {args.seed}) ...",
                  flush=True)
            rep = run_one(tier, scenario, args.seed, not args.no_rerun,
                          args.forensics_dir)
            results.append(rep)
            print(f"[chaos_bench]   ledgers={rep['ledgers_closed']} "
                  f"heal={rep['time_to_heal_s']}s "
                  f"spread_p99={rep['close_spread_virtual_ms']['p99']}ms "
                  f"fork={rep['fork_check']} "
                  f"rerun_identical={rep.get('rerun_identical', 'skipped')} "
                  f"wall={rep['bench_wall_s']}s", flush=True)

    doc = {
        "bench": "chaos scenario suite",
        "seed": args.seed,
        "tiers": {t: {"nodes": TIERS[t][1], "duration_s": TIERS[t][2]}
                  for t in tiers},
        "scenarios": results,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"[chaos_bench] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
