#!/usr/bin/env python
"""All-round opportunistic TPU capture daemon (VERDICT r4 task 2).

Runs for the WHOLE builder session: probe the device -> on success run
bench_device.py at BENCH_N signatures -> persist BENCH_BEST.json -> exit.
Two straight rounds lost the flagship number to a driver-time tunnel
wedge; a round-long capture window multiplies the odds of success.

Discipline (round-3 postmortem): the TPU relay is exclusive and a KILLED
client re-wedges it for every later client, so this daemon starts ONE
probe subprocess at a time and NEVER kills it — if the probe hangs, we
wait on the same child indefinitely with heartbeat logs.  Only if the
probe exits cleanly without a device do we sleep and start another.

The log (tools/capture_loop.log) is the committed evidence that the loop
ran throughout the round even if the tunnel stays dead.

Ref seam: /root/reference/src/crypto/SecretKey.cpp:428 (verifySig — the
function the Pallas kernel replaces).
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LOG = os.path.join(REPO, "tools", "capture_loop.log")
BEST = os.path.join(REPO, "BENCH_BEST.json")
NPZ = os.path.join(REPO, "tools", "capture_workload.npz")
N = int(os.environ.get("BENCH_N", "100000"))


def log(msg):
    line = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {msg}"
    with open(LOG, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


def build_workload():
    """Sign N random 32-byte digests on CPU; same tensor shapes the
    herder's collect_signature_batch produces."""
    import numpy as np

    if os.path.exists(NPZ):
        d = np.load(NPZ)
        if d["pk"].shape[0] == N:
            log(f"workload cached ({N} sigs)")
            return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from stellar_core_tpu.crypto.ed25519 import SecretKey

    t0 = time.time()
    n_keys = 512  # realistic: many txs share source accounts
    keys = [SecretKey(bytes([i & 0xFF, i >> 8]) + b"\x07" * 30)
            for i in range(n_keys)]
    rng = np.random.default_rng(5)
    mg = rng.integers(0, 256, size=(N, 32), dtype=np.uint8)
    pk = np.empty((N, 32), np.uint8)
    sg = np.empty((N, 64), np.uint8)
    for i in range(N):
        k = keys[i % n_keys]
        pk[i] = np.frombuffer(k.public_key().raw, np.uint8)
        sg[i] = np.frombuffer(k.sign(bytes(mg[i])), np.uint8)
    np.savez(NPZ, pk=pk, sg=sg, mg=mg)
    log(f"workload built: {N} sigs in {time.time()-t0:.0f}s")


def cpu_baseline():
    import numpy as np

    from stellar_core_tpu.crypto.ed25519 import raw_verify

    d = np.load(NPZ)
    pk, sg, mg = d["pk"], d["sg"], d["mg"]
    nb = min(2000, N)
    t0 = time.perf_counter()
    for i in range(nb):
        assert raw_verify(bytes(pk[i]), bytes(sg[i]), bytes(mg[i]))
    rate = nb / (time.perf_counter() - t0)
    log(f"cpu baseline: {rate:.0f}/s")
    return rate


def run_device_stage(cpu_rate):
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench_device.py"), NPZ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    t0 = time.time()
    # no kill, ever: poll with heartbeats
    while proc.poll() is None:
        time.sleep(30)
        log(f"device stage running ({time.time()-t0:.0f}s)")
        if time.time() - t0 > 3600:
            log("device stage >1h; continuing to wait (never kill)")
    out = proc.stdout.read()
    log(f"device stage exited rc={proc.returncode}")
    for ln in out.strip().splitlines():
        log(f"  | {ln}")
    if proc.returncode != 0:
        return None
    try:
        res = json.loads(out.strip().splitlines()[-1])
    except Exception as e:
        log(f"unparseable device output: {e!r}")
        return None
    capture = {
        "rate": res["rate"],
        "kernel": res["kernel"],
        "device": res["device"],
        "n_signatures": res["n"],
        "cpu_rate": round(cpu_rate, 1),
        "vs_cpu": round(res["rate"] / cpu_rate, 2),
        "captured_unix": int(time.time()),
        "captured_by": "tools/tpu_capture_loop.py",
    }
    best = None
    try:
        with open(BEST) as f:
            best = json.load(f)
    except Exception:
        pass
    better = (best is None or capture["rate"] >= best.get("rate", 0)
              or (best.get("kernel") != "pallas"
                  and capture["kernel"] == "pallas"))
    if better:
        with open(BEST, "w") as f:
            json.dump(capture, f, indent=1)
        log(f"PERSISTED {BEST}: {capture}")
    return capture


def main():
    log(f"=== capture loop starting (pid {os.getpid()}, N={N}) ===")
    build_workload()
    cpu_rate = cpu_baseline()
    sys.path.insert(0, REPO)
    from stellar_core_tpu.utils.device import DeviceProbe

    attempt = 0
    while True:
        attempt += 1
        probe = DeviceProbe()
        log(f"probe #{attempt} started (pid "
            f"{probe.proc.pid if probe.proc else '?'})")
        status = None
        while status is None:
            status = probe.wait(120)
            if status is None:
                log(f"probe #{attempt} still pending "
                    f"({time.monotonic()-probe.started:.0f}s; waiting, "
                    "never killing)")
        if status:
            log(f"probe #{attempt} SUCCESS after "
                f"{time.monotonic()-probe.started:.0f}s — device alive")
            cap = run_device_stage(cpu_rate)
            if cap and cap["kernel"] == "pallas":
                log("pallas capture secured; exiting")
                return
            if cap:
                log("capture secured with xla kernel; retrying for pallas"
                    " in 300s")
                time.sleep(300)
            else:
                log("device stage failed; re-probing in 300s")
                time.sleep(300)
        else:
            log(f"probe #{attempt} exited without device; retry in 180s")
            time.sleep(180)


if __name__ == "__main__":
    main()
