#!/usr/bin/env python
"""BucketListDB read-path evidence (ISSUE r7 acceptance artifact): a
1M-entry disk-tier BucketList serves point reads through the per-bucket
bloom-filtered indexes, and the numbers prove

- >=10x fewer bucket probes per point read than the linear-scan
  baseline (the same list with index_enabled=False),
- zero SQL queries on the point-lookup path (LedgerTxnRoot in
  BucketListDB mode, measured on a live node),
- a bucket-list hash bit-identical between an indexed and an unindexed
  build of the same workload,
- index build cost per close (index_build_s) small against close p50.

Schema follows BUCKET_SCALE_r06.json.  Writes READ_BENCH_r07.json.

Usage: python tools/read_bench.py [n_entries] [per_close] [n_reads]
"""
import json
import os
import resource
import statistics
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ["JAX_PLATFORMS"] = "cpu"


def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def build_list(n_entries, per_close, tmp, indexed=True):
    from concurrent.futures import ThreadPoolExecutor

    from stellar_core_tpu.bucket.bucket_list import BucketList
    from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
    from stellar_core_tpu.transactions import utils as U

    executor = ThreadPoolExecutor(max_workers=2,
                                  thread_name_prefix="bucket-merge")
    bl = BucketList(executor=executor, disk_dir=tmp, disk_level=2)
    bl.index_enabled = indexed
    close_times = []
    seq = 1
    made = 0
    while made < n_entries:
        seq += 1
        changes = []
        for j in range(min(per_close, n_entries - made)):
            i = made + j
            e = U.make_account_entry(
                i.to_bytes(4, "big") * 8, 10_000_000 + i)
            changes.append((key_bytes(entry_to_key(e)), e, False))
        made += len(changes)
        t0 = time.perf_counter()
        bl.add_batch(seq, changes)
        close_times.append(time.perf_counter() - t0)
        if seq % 50 == 0:
            print(f"[build indexed={indexed}] seq {seq}: {made} entries, "
                  f"rss {rss_mb():.0f}MB", flush=True)
    executor.shutdown(wait=True)
    return bl, close_times


def sample_keys(n_entries, n_reads):
    from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
    from stellar_core_tpu.transactions import utils as U

    present = []
    step = max(1, n_entries // (n_reads * 4 // 5))
    for i in range(0, n_entries, step):
        present.append(key_bytes(entry_to_key(U.make_account_entry(
            i.to_bytes(4, "big") * 8, 1))))
        if len(present) >= n_reads * 4 // 5:
            break
    absent = [key_bytes(entry_to_key(U.make_account_entry(
        (0x7F000000 + i).to_bytes(4, "big") * 8, 1)))
        for i in range(n_reads - len(present))]
    return present, absent


def measure_reads(bl, present, absent, label):
    base = dict(bl.stats)
    lat = []
    for kb in present:
        t0 = time.perf_counter()
        e = bl.get_entry(kb)
        lat.append(time.perf_counter() - t0)
        assert e is not None, kb.hex()
    for kb in absent:
        t0 = time.perf_counter()
        e = bl.get_entry(kb)
        lat.append(time.perf_counter() - t0)
        assert e is None
    reads = bl.stats["point_reads"] - base["point_reads"]
    probes = bl.stats["bucket_probes"] - base["bucket_probes"]
    checks = bl.stats["bloom_checks"] - base["bloom_checks"]
    fps = bl.stats["bloom_false_positives"] - base["bloom_false_positives"]
    lat.sort()
    out = {
        "reads": reads,
        "probes": probes,
        "probes_per_read": round(probes / reads, 4),
        "bloom_false_positive_rate": round(fps / checks, 6) if checks
        else 0.0,
        "read_us_p50": round(lat[len(lat) // 2] * 1e6, 1),
        "read_us_p99": round(lat[int(len(lat) * 0.99)] * 1e6, 1),
    }
    print(f"[{label}] {json.dumps(out)}", flush=True)
    return out


def sql_free_node_check():
    """A live node in BucketListDB mode: point lookups + prefetch issue
    ZERO SQL queries (measured on the Database wrapper's query counter)."""
    from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.main.http_server import CommandHandler
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.transactions import utils as U
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    app.start()
    handler = CommandHandler(app)
    # one 100-op batch tx per close: the default tx-set cap is 100 ops
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "100"})
    assert code == 200 and body["status_counts"] == {0: 1}, body
    app.herder.manual_close()
    code, body = handler.handle("generateload",
                                {"mode": "pay", "txs": "100"})
    assert code == 200 and body["status_counts"] == {0: 100}, body
    app.herder.manual_close()
    root = app.ledger_manager.root
    assert root.bucket_reads_enabled
    kbs = [key_bytes(entry_to_key(U.make_account_entry(
        LoadGenerator.account_key(i).public_key().raw, 0)))
        for i in range(100)]
    root._entry_cache.clear()
    q0 = app.database.queries
    for kb in kbs:
        assert root.get(kb) is not None
    root._entry_cache.clear()
    root.prefetch(kbs)
    sql_queries = app.database.queries - q0
    served = {"bucket": root.reads_from_buckets,
              "overlay": root.reads_from_overlay,
              "sql": root.reads_from_sql}
    app.graceful_stop()
    return sql_queries, served


def main():
    n_entries = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    per_close = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    n_reads = int(sys.argv[3]) if len(sys.argv) > 3 else 20_000

    import shutil

    # indexed build + reads
    tmp = tempfile.mkdtemp(prefix="read-bench-")
    t0 = time.time()
    bl, close_times = build_list(n_entries, per_close, tmp, indexed=True)
    build_s = time.time() - t0
    present, absent = sample_keys(n_entries, n_reads)
    indexed = measure_reads(bl, present, absent, "indexed")
    indexed_hash = bl.hash().hex()
    index_build_s = bl.stats["index_build_s"]
    index_mem = bl.index_memory_bytes()
    n_buckets = sum(1 for _ in bl._buckets_shallow_first())

    # linear-scan baseline on the SAME list (fewer reads: each one scans
    # every bucket), then a full unindexed REBUILD for hash parity
    bl.index_enabled = False
    lin_reads = max(200, n_reads // 20)
    linear = measure_reads(bl, present[:lin_reads * 4 // 5],
                           absent[:lin_reads // 5], "linear")
    del bl
    shutil.rmtree(tmp, ignore_errors=True)

    tmp2 = tempfile.mkdtemp(prefix="read-bench-noidx-")
    bl2, close_times_noidx = build_list(n_entries, per_close, tmp2,
                                        indexed=False)
    unindexed_hash = bl2.hash().hex()
    del bl2
    shutil.rmtree(tmp2, ignore_errors=True)

    sql_queries, served = sql_free_node_check()

    closes = len(close_times)
    out = {
        "n_entries": n_entries,
        "per_close": per_close,
        "closes": closes,
        "build_seconds": round(build_s, 1),
        "close_ms_p50": round(statistics.median(close_times) * 1000, 1),
        "close_ms_max": round(max(close_times) * 1000, 1),
        "close_ms_p50_noindex": round(
            statistics.median(close_times_noidx) * 1000, 1),
        "index_build_ms_per_close": round(
            index_build_s * 1000 / closes, 3),
        "index_memory_bytes": index_mem,
        "live_buckets": n_buckets,
        "point_reads": indexed["reads"],
        "read_us_p50": indexed["read_us_p50"],
        "read_us_p99": indexed["read_us_p99"],
        "probes_per_read": indexed["probes_per_read"],
        "bloom_false_positive_rate":
            indexed["bloom_false_positive_rate"],
        "linear_probes_per_read": linear["probes_per_read"],
        "linear_read_us_p50": linear["read_us_p50"],
        "probe_reduction_x": round(
            linear["probes_per_read"] / indexed["probes_per_read"], 1),
        "sql_queries_point_lookup": sql_queries,
        "point_reads_served_by": served,
        "bucket_hash_indexed": indexed_hash,
        "bucket_hash_unindexed": unindexed_hash,
        "hash_bit_identical": indexed_hash == unindexed_hash,
        "rss_mb_peak": round(rss_mb(), 1),
    }
    with open(os.path.join(REPO, "READ_BENCH_r07.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    assert out["hash_bit_identical"], "index changed the bucket hash!"
    assert out["sql_queries_point_lookup"] == 0, "SQL on the point path"
    # the >=10x probe-reduction acceptance bar applies at the 1M-entry
    # artifact scale; toy validation runs have too few buckets to scan
    if n_entries >= 500_000:
        assert out["probe_reduction_x"] >= 10, "probe reduction below 10x"


if __name__ == "__main__":
    main()
