#!/usr/bin/env python
"""Pipelined-close A/B bench (ISSUE 11 acceptance): mixed and pay-heavy
1000-tx closes through the full node close path, alternating
pipeline-on and pipeline-off closes IN THE SAME SESSION so ledger-state
drift (book growth, bucket spills) hits both arms equally.  Persists
PIPELINE_BENCH_r12.json.

What the pipeline must prove (and this bench measures):

- close-phase p50 drops >= 20% with the commit/meta/tx-history/gc
  tail staged on the worker (``tail_ms_reclaimed``: close-thread ms
  the off arm pays inline; ``tail_deferred_ms``: flight-recorder span
  time ledger N's tail spent running AFTER N's close root ended,
  concurrent with the next cycle on the main thread);
- the footprint prefetch staged at nomination serves the close from
  the bucket tier: prefetch hit rate reported, close-thread SQL point
  reads must be 0 in BucketListDB mode;
- hashes are BIT-IDENTICAL pipeline-on vs pipeline-off: a separate
  parity pass runs the same deterministic workload twice (on vs off)
  and compares every per-close (ledger hash, bucket hash, meta bytes).

Env knobs: BENCH_CLOSES (per arm, default 8), BENCH_CLOSE_TXS
(default 1000), BENCH_DEX_PCT (default 30), BENCH_WORKERS (parallel
apply workers, default 2).
"""
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _note(msg):
    print(f"[pipeline-bench] {msg}", file=sys.stderr, flush=True)


def _pct(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(q * len(xs)))], 2)


def _p50(xs):
    return round(statistics.median(xs), 2) if xs else None


def _mk_app(workers: int, node_dir=None):
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
    kw = {}
    if node_dir is not None:
        # production-shaped durability: a real SQLite file + on-disk
        # bucket store.  The tail the pipeline defers is exactly this
        # node's durable-commit work — benching it on :memory: would
        # understate the tail (and overlap I/O is the point)
        os.makedirs(os.path.join(node_dir, "buckets"), exist_ok=True)
        kw["DATABASE"] = os.path.join(node_dir, "node.db")
        kw["BUCKET_DIR_PATH_REAL"] = os.path.join(node_dir, "buckets")
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        UPGRADE_DESIRED_MAX_TX_SET_SIZE=max(100, close_txs),
        DEFERRED_GC=True,
        PIPELINED_CLOSE=True,
        PIPELINED_CLOSE_EAGER_DRAIN=False,  # measure the real overlap
        PARALLEL_APPLY_WORKERS=workers,
        NATIVE_APPLY_INLINE=workers < 2,
        **kw))
    app.start()
    app.herder.manual_close()  # applies the max-tx-set-size upgrade
    return app


def _tail_overlap_from_ring(app) -> tuple:
    """Flight-recorder proof of the overlap, per ledger N with a
    committed record: (deferred_ms, next_close_overlap_ms) where
    deferred = tail-span time spent AFTER N's close root ended (ran
    concurrently with the next cycle's admission/nomination/close on
    the main thread) and next_close_overlap = the part of that which
    coincided with ledger N+1's close root specifically (nonzero only
    when the tail outlives the whole inter-close gap)."""
    recs = {rec.seq: rec for rec in app.tracer.closes()}
    deferred, next_overlap = [], []
    for seq, rec in recs.items():
        root_n = next((sp for sp in rec.spans
                       if sp.name == "ledger.close"), None)
        if root_n is None:
            continue
        tails = [sp for sp in rec.spans
                 if sp.name in ("ledger.close.commit",
                                "ledger.close.meta", "ledger.close.gc")]
        if not tails:
            continue
        deferred.append(round(sum(
            max(0.0, sp.t1 - max(sp.t0, root_n.t1))
            for sp in tails) * 1000.0, 3))
        nxt = recs.get(seq + 1)
        root_next = None if nxt is None else next(
            (sp for sp in nxt.spans if sp.name == "ledger.close"), None)
        if root_next is not None:
            next_overlap.append(round(sum(
                max(0.0, min(sp.t1, root_next.t1)
                    - max(sp.t0, root_next.t0))
                for sp in tails) * 1000.0, 3))
    return deferred, next_overlap


def _seed_and_fold(app, lg, n: int, close_txs: int) -> None:
    """Bulk-seed ``n`` accounts, then run one UNTIMED payment rotation
    over every slice so each account's state is written by a real
    close — folding it off the sql-ahead overlay into the BUCKET tier,
    where the footprint prefetch (and cold reads) can find it."""
    lg.create_accounts(n)
    for lo in range(0, n, close_txs):
        accts = lg.accounts[lo:lo + close_txs]
        envs = lg.generate_payments(len(accts), accounts=accts)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == len(accts), "seeding fold under-admitted"
        app.herder.manual_close()
    root = app.ledger_manager.root
    # only never-closed stragglers (the genesis root) may remain
    assert len(root._sql_ahead) < 4, \
        f"{len(root._sql_ahead)} seeded keys still on the sql-ahead " \
        f"overlay — the fold failed"


def lockdep_probe(n_closes: int, close_txs: int, workers: int) -> dict:
    """Per-close witness accounting for the --lockdep-smoke overhead
    gate: run ``n_closes`` pipelined pay closes on one app and report
    the lock acquisitions + guarded-field checks the witness performed
    PER CLOSE (lockdep.stats() delta across the timed loop only —
    seeding excluded) alongside the round-trip close p50.  Meaningful
    under LOCKDEP=1; with the witness disabled the counts are zero and
    the report says so."""
    import shutil
    import tempfile

    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.utils import lockdep

    node_dir = tempfile.mkdtemp(prefix="lockdep-probe-")
    app = _mk_app(workers, node_dir=node_dir)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    n_accounts = max(2 * close_txs, 4 * close_txs)
    _seed_and_fold(app, lg, n_accounts, close_txs)
    n_slices = max(1, n_accounts // close_txs)
    before = lockdep.stats()
    walls = []
    for i in range(n_closes):
        lo = (i % n_slices) * close_txs
        hi = ((i + 1) % n_slices) * close_txs
        envs = lg.generate_payments(
            close_txs, accounts=lg.accounts[lo:lo + close_txs],
            dest_accounts=lg.accounts[hi:hi + close_txs])
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        t0 = time.perf_counter()
        app.herder.manual_close()
        walls.append((time.perf_counter() - t0) * 1000.0)
    app.ledger_manager.pipeline.drain()
    after = lockdep.stats()
    app.graceful_stop()
    shutil.rmtree(node_dir, ignore_errors=True)
    return {
        "enabled": after["enabled"],
        "closes": n_closes,
        "close_txs": close_txs,
        "close_p50_ms": _p50(walls),
        "acquires_per_close": round(
            (after["acquires"] - before["acquires"]) / n_closes, 1),
        "guard_checks_per_close": round(
            (after["guard_checks"] - before["guard_checks"]) / n_closes,
            1),
        "inversions": after["inversions"],
        "guard_violations": after["guard_violations"],
    }


def bench_workload(shape: str, n_closes: int, close_txs: int,
                   dex_pct: int, workers: int) -> dict:
    import shutil
    import tempfile

    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    node_dir = tempfile.mkdtemp(prefix=f"pipeline-bench-{shape}-")
    app = _mk_app(workers, node_dir=node_dir)
    lm = app.ledger_manager
    pipeline = lm.pipeline
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    # an account pool MANY closes wide, seeded through real closes into
    # the bucket tier: each bench close draws a rotating slice whose
    # keys fell out of the 8k-entry root cache since their last touch,
    # so the footprint prefetch has real work (the 1M-entry production
    # shape scaled down)
    n_accounts = int(os.environ.get(
        "BENCH_ACCOUNTS", str(12 * close_txs)))
    _seed_and_fold(app, lg, n_accounts, close_txs)
    if shape == "mixed":
        lg.setup_dex(lg.accounts[:close_txs])
    n_slices = max(1, n_accounts // close_txs)
    arms = {"off": [], "on": []}
    phases = {"off": [], "on": []}
    sql_reads = {"off": 0, "on": 0}
    for i in range(2 * n_closes):
        arm = "on" if i % 2 else "off"
        if arm == "off":
            pipeline.drain()
        pipeline.enabled = (arm == "on")
        # sources from slice i, destinations from slice i+1: the
        # recipients-aren't-senders shape — admission pre-warms only
        # the sources, so the destination entries are the close's (and
        # the staged prefetch's) to load from the bucket tier
        lo = (i % n_slices) * close_txs
        hi = ((i + 1) % n_slices) * close_txs
        accts = lg.accounts[lo:lo + close_txs]
        dests = lg.accounts[hi:hi + close_txs]
        envs = (lg.generate_mixed(close_txs, dex_percent=dex_pct,
                                  accounts=accts, dest_accounts=dests)
                if shape == "mixed"
                else lg.generate_payments(close_txs, accounts=accts,
                                          dest_accounts=dests))
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        sql0 = lm.root.reads_from_sql
        t0 = time.perf_counter()
        app.herder.manual_close()
        arms[arm].append((time.perf_counter() - t0) * 1000.0)
        sql_reads[arm] += lm.root.reads_from_sql - sql0
        phases[arm].append(dict(lm.last_close_phases))
    pipeline.drain()
    deferred_ms, next_overlap_ms = _tail_overlap_from_ring(app)
    stats = dict(pipeline.stats)
    apply_stats = {k: v for k, v in app.parallel_apply.stats.items()
                   if not isinstance(v, list)}
    app.graceful_stop()
    shutil.rmtree(node_dir, ignore_errors=True)

    def phase_p50(arm, name):
        vals = [row.get(name, 0.0) for row in phases[arm]
                if isinstance(row.get(name, 0.0), (int, float))]
        return round(statistics.median(vals), 2) if vals else None

    off_p50, on_p50 = _p50(arms["off"]), _p50(arms["on"])
    close_only = {
        arm: _p50([row.get("total") for row in phases[arm]
                   if isinstance(row.get("total"), (int, float))])
        for arm in ("off", "on")}
    # tail ms reclaimed per close = deferred phases that no longer sit
    # on the close thread (the pipeline-off arm pays them inline)
    tail_off = sum(filter(None, (phase_p50("off", n)
                                 for n in ("commit", "meta", "gc"))))
    staged = stats["prefetch_staged"]
    row = {
        "shape": shape,
        "close_txs": close_txs,
        "closes_per_arm": n_closes,
        "workers": workers,
        "off_close_p50_ms": off_p50,
        "on_close_p50_ms": on_p50,
        "close_phase_p50_ms": {
            "off": close_only["off"], "on": close_only["on"],
            "on_vs_off_pct": (
                round((close_only["on"] - close_only["off"])
                      / close_only["off"] * 100.0, 1)
                if close_only["off"] else None)},
        "off_close_p99_ms": _pct(arms["off"], 0.99),
        "on_close_p99_ms": _pct(arms["on"], 0.99),
        "on_vs_off_pct": (round((on_p50 - off_p50) / off_p50 * 100.0, 1)
                          if off_p50 else None),
        "tail_ms_reclaimed_p50": round(tail_off, 2),
        "tail_deferred_ms": {
            "p50": _p50(deferred_ms), "max": _pct(deferred_ms, 1.0),
            "samples": len(deferred_ms)},
        "tail_overlap_next_close_ms": {
            "p50": _p50(next_overlap_ms),
            "max": _pct(next_overlap_ms, 1.0),
            "samples": len(next_overlap_ms)},
        "tail_wait_p50_ms": phase_p50("on", "tail_wait"),
        "stage_p50_ms": phase_p50("on", "stage"),
        "prefetch_phase_p50_ms": {
            "off": phase_p50("off", "prefetch"),
            "on": phase_p50("on", "prefetch")},
        "prefetch": {
            "staged": staged,
            "keys": stats["prefetch_keys"],
            "adopted": stats["prefetch_adopted"],
            "hit_rate": (round(stats["prefetch_adopted"]
                               / stats["prefetch_keys"], 4)
                         if stats["prefetch_keys"] else None)},
        "close_thread_sql_point_reads": sql_reads,
        "pipeline_stats": {k: (round(v, 4) if isinstance(v, float)
                               else v) for k, v in stats.items()},
        "batched_clusters": apply_stats.get("batched_clusters", 0),
        "native_hits": apply_stats.get("native_hits", 0),
    }
    _note(f"{shape}: round-trip off/on p50 {off_p50}/{on_p50}ms "
          f"({row['on_vs_off_pct']}%)  close-phase off/on p50 "
          f"{close_only['off']}/{close_only['on']}ms "
          f"({row['close_phase_p50_ms']['on_vs_off_pct']}%)  "
          f"tail reclaimed "
          f"{row['tail_ms_reclaimed_p50']}ms  deferred p50 "
          f"{row['tail_deferred_ms']['p50']}ms  prefetch hit "
          f"{row['prefetch']['hit_rate']}")
    return row


def parity_pass(close_txs: int, dex_pct: int, workers: int) -> dict:
    """Same deterministic workload, pipeline on (overlapping) vs off:
    every per-close (ledger hash, bucket hash, meta bytes) must match."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from tests.test_pipelined_close import run_workload

    on, _ = run_workload(True, eager=False,
                         PARALLEL_APPLY_WORKERS=workers)
    off, _ = run_workload(False, PARALLEL_APPLY_WORKERS=workers)
    ok = len(on) == len(off) and all(
        a[0] == b[0] and a[1] == b[1] and a[2] == b[2]
        for a, b in zip(on, off))
    row = {"closes": len(on), "hashes_identical": ok,
           "meta_bytes_identical": ok}
    _note(f"parity: {len(on)} closes, identical={ok}")
    if not ok:
        raise SystemExit("pipeline on/off parity FAILED")
    return row


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n_closes = int(os.environ.get("BENCH_CLOSES", "8"))
    close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
    dex_pct = int(os.environ.get("BENCH_DEX_PCT", "30"))
    workers = int(os.environ.get("BENCH_WORKERS", "2"))

    if "--lockdep-probe" in sys.argv:
        row = lockdep_probe(max(4, n_closes), close_txs, workers)
        _note(f"lockdep probe: {row}")
        path = os.environ.get("PIPELINE_BENCH_OUT",
                              "/tmp/_lockdep_probe.json")
        with open(path, "w") as f:
            json.dump(row, f, indent=2)
        return

    rows = [bench_workload(shape, n_closes, close_txs, dex_pct, workers)
            for shape in ("pay", "mixed")]
    parity = parity_pass(close_txs, dex_pct, workers)

    out = {
        "bench": "pipelined-close",
        "rev": "r12",
        "device": "cpu-fallback",
        "workloads": rows,
        "parity": parity,
        "notes": (
            "alternating same-session A/B on a disk-backed node; 'on' "
            "arm overlaps the commit/meta/gc tail with the next "
            "cycle's admission/trigger/close (eager drain off); "
            "tail_deferred_ms = flight-recorder tail-span time past "
            "the close root's end (the overlap proof; "
            "tail_overlap_next_close_ms is nonzero only when a tail "
            "outlives the whole inter-close gap); round-trip = "
            "manual_close wall incl. SCP/nomination, close_phase = "
            "the close-only span; parity pass compares per-close "
            "header/bucket hashes AND meta bytes pipeline-on vs off"),
    }
    path = os.environ.get(
        "PIPELINE_BENCH_OUT", os.path.join(REPO, "PIPELINE_BENCH_r12.json"))
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    _note(f"persisted {path}")


if __name__ == "__main__":
    main()
