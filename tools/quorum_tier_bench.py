#!/usr/bin/env python
"""Quorum-intersection tier shoot-out (VERDICT r4 task 9): native C++
enumerator vs Python+numpy enumerator vs Python+device-batch contractor
at growing SCC sizes.  One number decides which tier earns the default.

Topologies are flat majority cliques with a per-node twist (every node's
qset drops a different neighbour) so the org-collapse reduction cannot
fire and the enumerator genuinely runs.

Writes QUORUM_TIER_BENCH.json.  JAX pinned to CPU: the device tier's
number on this host is the XLA-on-CPU rate; on a real TPU chip the same
code path is the one the artifact's "device" row would re-measure.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ["JAX_PLATFORMS"] = "cpu"  # hard pin: sitecustomize forces axon

import jax

jax.config.update("jax_platforms", "cpu")


def build_qmap(n):
    from stellar_core_tpu.scp.local_node import make_qset

    nodes = [b"%02d" % i + b"\x00" * 30 for i in range(n)]
    thr = 2 * n // 3 + 1
    qmap = {}
    for i, x in enumerate(nodes):
        members = [v for j, v in enumerate(nodes) if j != (i + 1) % n]
        qmap[x] = make_qset(min(thr, len(members)), members, [])
    return qmap


def main():
    from stellar_core_tpu.herder.quorum_intersection import (
        check_quorum_intersection,
    )

    sizes = [int(x) for x in (sys.argv[1:] or ["16", "24", "32", "48"])]
    rows = []
    for n in sizes:
        qmap = build_qmap(n)
        row = {"scc_size": n}
        for label, kw in (
                ("native", dict(use_native=True, use_device=False)),
                ("python_numpy", dict(use_native=False, use_device=False)),
                ("python_device", dict(use_native=False, use_device=True))):
            t0 = time.perf_counter()
            res = check_quorum_intersection(qmap, max_seconds=120, **kw)
            dt = time.perf_counter() - t0
            row[label] = {"seconds": round(dt, 3), "ok": res.ok,
                          "scanned": res.scanned,
                          "aborted": bool(res.aborted)}
            assert res.ok is True or res.aborted, (label, n, res.ok)
        rows.append(row)
        print(json.dumps(row), flush=True)

    out = {
        "jax_platform": "cpu",
        "note": ("device tier = XLA batch contractor on host CPU here; "
                 "the native C++ tier needs no device at all"),
        "rows": rows,
    }
    with open(os.path.join(REPO, "QUORUM_TIER_BENCH.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
