"""Interprocedural determinism taint (detlint v2 layer 1).

The v1 rules are strictly intra-function: ``det-wallclock`` flags a
time read *in a consensus module*, ``det-unsorted-iter`` flags unsorted
iteration *in the same function* as a hash/serialize/tally sink.  The
structural escape both share: a nondeterministic value produced in one
helper — possibly outside the consensus directories entirely — and fed
through a call into a consensus sink function.  This pass closes it:

1. every function in the package gets a summary (callgraph.py) listing
   its direct nondeterminism sources (wall-clock/RNG/uuid reads,
   os.environ, ``id()``, order-carrying unsorted dict/set iteration,
   float math on ledger values) and resolved call sites;
2. taint propagates callee -> caller up to ``MAX_TAINT_DEPTH`` edges
   (a function is tainted when it contains a source or calls a tainted
   function — the return-value/argument flow approximation);
3. a finding fires at each call site inside a consensus-directory
   function that feeds a hash/serialize/tally sink and calls a tainted
   callee.  The message carries the full source->sink chain so the fix
   is one look:

     close_hash -> _mix -> _stamp (wallclock time.time() at
     stellar_core_tpu/scp/helpers.py:12)

Suppression composes with v1: a pragma at the SOURCE line for the
matching v1 rule (or ``det-interproc-taint``) sanctions every chain
from that source; a pragma at the call site suppresses just that sink.
A source directly inside the sink function is NOT reported here — the
v1 intra-function rules own that case.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from . import callgraph
from .callgraph import INTERPROC_RULE, MAX_TAINT_DEPTH, Graph
from .engine import (CONSENSUS_DIRS, PACKAGE, FileInfo, Finding,
                     path_under)


def _in_consensus(path: str) -> bool:
    return path_under(path, CONSENSUS_DIRS)


class Taint:
    """Per-function taint verdict with the shortest witness chain."""

    __slots__ = ("depth", "via", "source")

    def __init__(self, depth: int, via: Optional[str],
                 source: Tuple[str, str, int]):
        self.depth = depth       # call edges from the direct source
        self.via = via           # callee key one step toward the source
        self.source = source     # (kind, detail, line) at the origin


def propagate(graph: Graph) -> Dict[str, Taint]:
    """Breadth-first from every source-bearing function along REVERSE
    call edges, bounded by MAX_TAINT_DEPTH; keeps the shallowest chain
    per function (ties broken deterministically by key order)."""
    callers: Dict[str, List[str]] = {}
    for caller, edges in graph.edges.items():
        for callee, _line in edges:
            callers.setdefault(callee, []).append(caller)

    from .callgraph import SANCTIONED_MODULES

    tainted: Dict[str, Taint] = {}
    frontier: List[str] = []
    for key in sorted(graph.funcs):
        f = graph.funcs[key]
        if f.sources:
            src = min(f.sources, key=lambda s: (s[2], s[0]))
            tainted[key] = Taint(0, None, src)
            frontier.append(key)
    # the sink's own call edge is the +1: propagating to depth
    # MAX_TAINT_DEPTH - 1 bounds the full reported chain (sink -> ...
    # -> source) at MAX_TAINT_DEPTH call edges
    depth = 0
    while frontier and depth < MAX_TAINT_DEPTH - 1:
        depth += 1
        nxt: List[str] = []
        for key in frontier:
            for caller in sorted(callers.get(key, ())):
                if caller in tainted:
                    continue
                if graph.path_of[caller] in SANCTIONED_MODULES:
                    # sanctioned modules are neither sources NOR
                    # carriers: a chain laundered through clock/
                    # tracing/config is observability or config
                    # plumbing, not a consensus value flow (documented
                    # blind spot in COVERAGE.md)
                    continue
                tainted[caller] = Taint(depth, key, tainted[key].source)
                nxt.append(caller)
        frontier = nxt
    return tainted


def _chain_text(graph: Graph, start: str,
                tainted: Dict[str, Taint]) -> str:
    names: List[str] = []
    key: Optional[str] = start
    while key is not None:
        f = graph.funcs[key]
        names.append(f.context)
        key = tainted[key].via
    t = tainted[start]
    kind, detail, line = t.source
    origin_path = graph.path_of[_chain_end(graph, start, tainted)]
    return (" -> ".join(names)
            + f" ({kind} {detail} at {origin_path}:{line})")


def _chain_end(graph: Graph, start: str,
               tainted: Dict[str, Taint]) -> str:
    key = start
    while tainted[key].via is not None:
        key = tainted[key].via
    return key


def check(infos: List[FileInfo],
          summaries: Optional[Dict[str, List[callgraph.FuncSummary]]]
          = None,
          aux_infos: "tuple" = ()) -> List[Finding]:
    """Whole-program pass over the given files.  ``summaries`` lets the
    --changed cache substitute precomputed per-file summaries; files in
    ``infos`` are (re)summarized from their ASTs.  ``aux_infos`` carries
    tree-less FileInfo objects for cache-hit files so findings landing
    there still render real line text."""
    merged: Dict[str, List[callgraph.FuncSummary]] = dict(summaries or {})
    by_path = {i.path: i for i in aux_infos}
    by_path.update({i.path: i for i in infos})
    for info in infos:
        merged[info.path] = callgraph.summarize_file(info)
    graph = callgraph.build(merged)
    tainted = propagate(graph)

    findings: List[Finding] = []
    seen = set()
    for key in sorted(graph.funcs):
        path = graph.path_of[key]
        f = graph.funcs[key]
        if not f.sink or not _in_consensus(path):
            continue
        for callee, line in graph.edges[key]:
            t = tainted.get(callee)
            if t is None:
                continue
            kind = t.source[0]
            dedupe = (key, callee, kind)
            if dedupe in seen:
                continue
            seen.add(dedupe)
            chain = f.context + " -> " + _chain_text(graph, callee,
                                                     tainted)
            info = by_path.get(path)
            line_text = info.line_text(line) if info is not None else ""
            findings.append(Finding(
                rule=INTERPROC_RULE, file=path, line=line, col=0,
                context=f.context,
                message=("nondeterministic value reaches a hash/"
                         f"serialize/tally scope: {chain}"),
                line_text=line_text))
    return findings
