"""Exception-safety & resource-discipline rules (detlint v2 layer 3).

Rules
-----
safety-swallow-except
    In a consensus module: a bare ``except:`` (always), or an
    ``except Exception/BaseException:`` whose handler body does NOTHING
    — only ``pass``/``continue``/``...``/bare ``return``/``return
    None``.  A decode guard that returns an error *value*
    (``return ADD_STATUS_ERROR``) or falls back to another code path is
    legitimate robustness; a silent swallow of every exception class in
    consensus scope can hide a fork in progress.  Narrow the type
    (``except XdrError``) or make the handler act (counter/log/raise).
safety-resource-ctx
    In ``bucket/``: a builtin ``open()`` / ``os.open()`` / ``os.fdopen``
    / ``mmap.mmap()`` whose handle is neither (a) a ``with`` context
    item, nor (b) stored to an attribute somewhere in the enclosing
    function (long-lived cached handles like DiskBucket's pread fd have
    lifecycle management by design).  Everything else leaks the fd on
    the first exception between open and close — under the merge worker
    pool that is an fd-exhaustion outage, not a warning.
safety-mutable-default
    A mutable default argument (``[]``/``{}``/``set()``/``dict()``/
    ``list()``) on a function in a consensus module: call-to-call state
    bleed in consensus scope is a determinism hazard, not a style nit.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from .engine import ContextVisitor, FileInfo, Finding, dotted_name as _dotted

_BROAD = {"Exception", "BaseException"}
_OPENERS_NAME = {"open"}
_OPENERS_DOTTED = {"os.open", "os.fdopen", "io.open", "mmap.mmap"}


def _is_swallow_body(body: List[ast.stmt]) -> bool:
    """True when the handler does nothing observable."""
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis
        if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or (isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is None)):
            continue
        return False
    return True


class _ExceptVisitor(ContextVisitor):
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.add("safety-swallow-except", node,
                     "bare 'except:' in consensus scope — name the "
                     "exception types this handler is licensed to eat")
        else:
            name = None
            if isinstance(node.type, ast.Name):
                name = node.type.id
            elif isinstance(node.type, ast.Attribute):
                name = node.type.attr
            if name in _BROAD and _is_swallow_body(node.body):
                self.add("safety-swallow-except", node,
                         f"'except {name}:' silently swallowed in "
                         "consensus scope — narrow the type or make "
                         "the handler act (log/counter/raise)")
        self.generic_visit(node)


class _ResourceVisitor(ContextVisitor):
    """Per-function: collect with-item opens and attribute-stored
    handles first, then flag the rest."""

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self._scan(node)
        self.stack.pop()
        ContextVisitor._visit_func(self, node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _scan(self, func) -> None:
        from .determinism import _shallow_walk

        ctx_opens = set()
        attr_stored_names = set()
        for node in _shallow_walk(func):
            if isinstance(node, ast.With):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if self._is_open(sub):
                            ctx_opens.add(id(sub))
            elif isinstance(node, ast.Assign):
                # self.x = fd / self.x = open(...): lifecycle-managed
                stores_attr = any(isinstance(t, ast.Attribute)
                                  for t in node.targets)
                if stores_attr:
                    if self._is_open(node.value):
                        ctx_opens.add(id(node.value))
                    d = _dotted(node.value)
                    if d is not None:
                        attr_stored_names.add(d)
        for node in _shallow_walk(func):
            if not self._is_open(node) or id(node) in ctx_opens:
                continue
            assigned = self._assigned_name(func, node)
            if assigned is not None and assigned in attr_stored_names:
                continue
            self.add("safety-resource-ctx", node,
                     "file/mmap opened outside a context manager (and "
                     "never stored to an attribute) — the handle leaks "
                     "on the first exception before close")

    @staticmethod
    def _is_open(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if isinstance(node.func, ast.Name):
            return node.func.id in _OPENERS_NAME
        d = _dotted(node.func)
        return d in _OPENERS_DOTTED

    @staticmethod
    def _assigned_name(func, call: ast.Call) -> Optional[str]:
        from .determinism import _shallow_walk

        for node in _shallow_walk(func):
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    d = _dotted(t)
                    if d is not None:
                        return d
        return None


class _MutableDefaultVisitor(ContextVisitor):
    def _visit_func(self, node) -> None:
        args = node.args
        for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]:
            if self._is_mutable(default):
                self.stack.append(node.name)
                self.add("safety-mutable-default", default,
                         f"mutable default argument on {node.name}() in "
                         "consensus scope — one shared object across "
                         "every call (use None + in-body default)")
                self.stack.pop()
        ContextVisitor._visit_func(self, node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    @staticmethod
    def _is_mutable(node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("list", "dict", "set", "bytearray"))


def check(info: FileInfo) -> List[Finding]:
    findings: List[Finding] = []
    if info.in_consensus():
        for visitor in (_ExceptVisitor(info),
                        _MutableDefaultVisitor(info)):
            visitor.visit(info.tree)
            findings.extend(visitor.findings)
    parts = info.path.split("/")
    if "bucket" in parts:
        v = _ResourceVisitor(info)
        v.visit(info.tree)
        findings.extend(v.findings)
    return findings
