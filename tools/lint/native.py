"""Native-kernel auditor (detlint v2 layer 2): a lightweight lexer over
``native/*.cpp`` / ``*.c`` that turns three runtime-only disciplines
into static, per-commit guarantees.

Rules
-----
native-lockstep
    Every protocol constant the C++ kernels hardcode is pinned in an
    explicit manifest (tools/lint/lockstep.json) against its canonical
    value AND its Python source of truth.  Drift in EITHER file against
    the pinned value fails the gate — changing a constant legitimately
    forces touching kernel + Python + manifest in one commit, which is
    exactly the "did you port it?" question the runtime
    ``_constants_in_lockstep`` check could only ask after deploy.  A
    pattern that stops matching is itself a finding (stale manifest
    never degrades to silence).
native-gil-api
    A CPython API token (``Py*``) inside a ``Py_BEGIN_ALLOW_THREADS``
    .. ``Py_END_ALLOW_THREADS`` region — calling into the interpreter
    without the GIL is memory corruption, not an error return.
    ``Py_BLOCK_THREADS``/``Py_UNBLOCK_THREADS`` re-acquisition windows
    are honoured; type names (PyObject, Py_ssize_t) are exempt.
native-null-unchecked
    A Py allocator/constructor result (``PyList_New``, ``PyTuple_Pack``,
    ``Py_BuildValue``, ``PySequence_Fast``, ``PyMem_Malloc``, ...)
    assigned to a variable that is not NULL-checked within the next few
    lines, or nested directly into another call (leak + NULL deref on
    allocation failure — the exact bug class PR 6's review pass fixed
    by hand).  ``return <alloc>(...)`` propagates to the caller and is
    exempt.
native-srchash
    Every committed ``.so`` must carry a ``.srchash`` sidecar matching
    the sha256 of its sources (the loader's content-hash staleness
    contract, native/__init__.py) — a stale sidecar means a stale
    consensus kernel could load silently after checkout.

Comments and string literals are masked before token scanning (kernel
comments legitimately NAME Py* functions); lockstep patterns run on the
raw text because several anchor on the kernels' comment discipline.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .engine import REPO, Finding

MANIFEST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "lockstep.json")

#: .so -> sources, in the loader's digest order (native/__init__.py)
SO_SOURCES = {
    "_native.so": ["bucket_merge.cpp", "quorum_enum.cpp"],
    "_xdrpack.so": ["xdr_pack.c"],
    "_applykernel.so": ["apply_kernel.cpp"],
}
NATIVE_DIR = "stellar_core_tpu/native"

_PRAGMA_RE = re.compile(r"(?://|/\*)\s*detlint:\s*allow\(([^)]*)\)")

_GIL_BEGIN = "Py_BEGIN_ALLOW_THREADS"
_GIL_END = "Py_END_ALLOW_THREADS"
_GIL_BLOCK = "Py_BLOCK_THREADS"
_GIL_UNBLOCK = "Py_UNBLOCK_THREADS"
_PY_TOKEN_RE = re.compile(r"\bPy_?[A-Z]\w*")
_GIL_EXEMPT = {
    _GIL_BEGIN, _GIL_END, _GIL_BLOCK, _GIL_UNBLOCK,
    "PyObject", "PyTypeObject", "PyMethodDef", "PyModuleDef",
    "PyMODINIT_FUNC", "PyCFunction",
}

_ALLOC_RE = re.compile(
    r"(?:([A-Za-z_]\w*(?:(?:->|\.)\w+)*)\s*=\s*)?"   # lvalue (a, a->b, a.b)
    r"(?:\(\s*\w+[\w\s*]*\)\s*)?"                    # optional C cast
    r"\b("
    r"Py(?:List_New|Tuple_New|Tuple_Pack|Dict_New|Set_New"
    r"|Bytes_FromStringAndSize|Bytes_FromString|ByteArray_FromStringAndSize"
    r"|Unicode_From\w+|Long_From\w+|Float_From\w+|Sequence_Fast"
    r"|Mem_Malloc|Mem_Realloc|Mem_Calloc|Err_NewException"
    r"|Module_Create|Import_ImportModule|Object_Call\w*)"
    r"|Py_BuildValue)\s*\(")
_NULL_CHECK_WINDOW = 10
_SPLIT_LVALUE_RE = re.compile(r"([A-Za-z_]\w*(?:(?:->|\.)\w+)*)\s*=\s*$")


@dataclass
class NativeInfo:
    """Duck-typed stand-in for engine.FileInfo over a C/C++ source."""
    path: str
    source: str
    lines: List[str]
    masked_lines: List[str]
    pragmas: Dict[int, set] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def _mask(source: str) -> str:
    """Replace comment/string interiors with spaces, preserving line
    structure, so token scans never fire inside prose."""
    out = []
    i, n = 0, len(source)
    mode = None  # None | 'line' | 'block' | '"' | "'"
    while i < n:
        c = source[i]
        if mode is None:
            if c == "/" and i + 1 < n and source[i + 1] == "/":
                mode = "line"
                out.append("  ")
                i += 2
                continue
            if c == "/" and i + 1 < n and source[i + 1] == "*":
                mode = "block"
                out.append("  ")
                i += 2
                continue
            if c in "\"'":
                mode = c
                out.append(c)
                i += 1
                continue
            out.append(c)
            i += 1
        elif mode == "line":
            if c == "\n":
                mode = None
                out.append(c)
            else:
                out.append(" ")
            i += 1
        elif mode == "block":
            if c == "*" and i + 1 < n and source[i + 1] == "/":
                mode = None
                out.append("  ")
                i += 2
                continue
            out.append(c if c == "\n" else " ")
            i += 1
        else:  # string literal
            if c == "\\" and i + 1 < n:
                out.append("  ")
                i += 2
                continue
            if c == mode:
                mode = None
                out.append(c)
            else:
                out.append(c if c == "\n" else " ")
            i += 1
    return "".join(out)


def parse_native(relpath: str, source: str) -> NativeInfo:
    info = NativeInfo(path=relpath.replace(os.sep, "/"), source=source,
                      lines=source.splitlines(),
                      masked_lines=_mask(source).splitlines())
    for i, raw in enumerate(info.lines, start=1):
        m = _PRAGMA_RE.search(raw)
        if m:
            info.pragmas[i] = {r.strip() for r in m.group(1).split(",")
                               if r.strip()}
    return info


# ---------------------------------------------------------------------------
# native-gil-api
# ---------------------------------------------------------------------------

def _check_gil(info: NativeInfo) -> List[Finding]:
    findings: List[Finding] = []
    in_region = False
    blocked = False
    for lineno, line in enumerate(info.masked_lines, start=1):
        if _GIL_BEGIN in line:
            in_region = True
            blocked = False
            continue
        if _GIL_END in line:
            in_region = False
            continue
        if not in_region:
            continue
        if _GIL_BLOCK in line:
            blocked = True
        if _GIL_UNBLOCK in line:
            blocked = False
            continue
        if blocked:
            continue
        for m in _PY_TOKEN_RE.finditer(line):
            tok = m.group(0)
            if tok in _GIL_EXEMPT:
                continue
            findings.append(Finding(
                rule="native-gil-api", file=info.path, line=lineno,
                col=m.start(), context="<native>",
                message=(f"CPython API '{tok}' inside a "
                         "Py_BEGIN/END_ALLOW_THREADS region — the GIL "
                         "is not held here"),
                line_text=info.line_text(lineno)))
    return findings


# ---------------------------------------------------------------------------
# native-null-unchecked
# ---------------------------------------------------------------------------

def _null_checked(var: str, lines: List[str], start_idx: int) -> bool:
    v = re.escape(var)
    pat = re.compile(
        rf"(!\s*{v}\b|\b{v}\s*==\s*NULL|NULL\s*==\s*{v}\b"
        rf"|\b{v}\s*!=\s*NULL|\b{v}\s*\?"
        rf"|if\s*\(\s*{v}\b"          # plain truthiness: if (enc) / (enc &&
        rf"|return\s+{v}\s*;)")       # propagated to the caller as-is
    end = min(len(lines), start_idx + _NULL_CHECK_WINDOW)
    for i in range(start_idx, end):
        if pat.search(lines[i]):
            return True
    return False


def _check_null(info: NativeInfo) -> List[Finding]:
    findings: List[Finding] = []
    lines = info.masked_lines
    for lineno, line in enumerate(lines, start=1):
        for m in _ALLOC_RE.finditer(line):
            before = line[:m.start()].rstrip()
            if before.endswith("return"):
                continue  # caller owns the NULL
            var, fn = m.group(1), m.group(2)
            if not var and not before and lineno >= 2:
                # assignment split across lines: `KernelError =\n  PyX(...)`
                sm = _SPLIT_LVALUE_RE.search(lines[lineno - 2])
                if sm:
                    var = sm.group(1)
            if var:
                if _null_checked(var, lines, lineno - 1):
                    continue
                msg = (f"'{var} = {fn}(...)' never NULL-checked within "
                       f"{_NULL_CHECK_WINDOW} lines — allocation "
                       "failure dereferences NULL")
            elif before.endswith(("(", ",")):
                msg = (f"{fn}(...) result nested into another call — "
                       "unchecked NULL and a leak on failure")
            else:
                msg = (f"{fn}(...) result discarded or unchecked — "
                       "allocation failure is invisible here")
            findings.append(Finding(
                rule="native-null-unchecked", file=info.path, line=lineno,
                col=m.start(), context="<native>", message=msg,
                line_text=info.line_text(lineno)))
    return findings


# ---------------------------------------------------------------------------
# native-lockstep
# ---------------------------------------------------------------------------

def load_manifest(path: str = MANIFEST_PATH) -> List[dict]:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)["constants"]


def _const_eval(node: ast.AST) -> Optional[int]:
    """Tiny int-expression evaluator for Python constant definitions
    (handles ``2**63 - 1`` without importing the package)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_eval(node.operand)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        a, b = _const_eval(node.left), _const_eval(node.right)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
        if isinstance(node.op, ast.FloorDiv) and b:
            return a // b
        if isinstance(node.op, ast.Pow):
            return a ** b
        if isinstance(node.op, ast.LShift):
            return a << b
        if isinstance(node.op, ast.BitOr):
            return a | b
    return None


def _py_attr_value(source: str, attr: str) -> Optional[Tuple[int, int]]:
    """(value, line) of a module-level ``attr = <int expr>``."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == attr:
                    v = _const_eval(node.value)
                    if v is not None:
                        return v, node.lineno
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and \
                    node.target.id == attr:
                v = _const_eval(node.value)
                if v is not None:
                    return v, node.lineno
    return None


def _py_enum_value(source: str, enum_name: str,
                   member: str) -> Optional[Tuple[int, int]]:
    """(value, line) of ``Enum("<enum_name>", {"<member>": v, ...})``."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Enum" and len(node.args) >= 2):
            continue
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant)
                and arg0.value == enum_name):
            continue
        d = node.args[1]
        if not isinstance(d, ast.Dict):
            continue
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and k.value == member:
                val = _const_eval(v)
                if val is not None:
                    return val, k.lineno
    return None


def _line_of(source: str, pos: int) -> int:
    return source.count("\n", 0, pos) + 1


def _regex_values(source: str, pattern: str) -> List[Tuple[int, int]]:
    out: List[Tuple[int, int]] = []
    for m in re.finditer(pattern, source, re.M | re.S):
        try:
            out.append((int(m.group(1), 0), _line_of(source, m.start(1))))
        except (ValueError, IndexError):
            pass
    return out


def check_lockstep(sources: Dict[str, str],
                   manifest: Optional[List[dict]] = None,
                   root: str = REPO) -> List[Finding]:
    """Diff every manifest constant across kernel source, Python twin
    and the pinned canonical value.  ``sources`` provides in-scope file
    text (the test seam injects drift here); anything absent is read
    from ``root`` so a scoped run still sees both sides.  An unreadable
    manifest is itself a finding — silence is never an option here."""
    if manifest is None:
        try:
            manifest = load_manifest()
        except (OSError, ValueError, KeyError, TypeError) as e:
            return [Finding(
                rule="native-lockstep", file="tools/lint/lockstep.json",
                line=1, col=0, context="<manifest>",
                message=f"lockstep manifest unreadable: {e}",
                line_text="")]

    def text_of(rel: str) -> Optional[str]:
        if rel in sources:
            return sources[rel]
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    findings: List[Finding] = []

    def drift(rel: str, line: int, text: str, msg: str, name: str):
        lt = ""
        if text is not None:
            ls = text.splitlines()
            if 1 <= line <= len(ls):
                lt = ls[line - 1].strip()
        findings.append(Finding(
            rule="native-lockstep", file=rel, line=line, col=0,
            context=name, message=msg, line_text=lt))

    for entry in manifest:
        name = entry["name"]
        want = int(entry["value"])
        cpp = entry["cpp"]
        cpp_text = text_of(cpp["file"])
        if cpp_text is None:
            drift(cpp["file"], 1, None,
                  f"lockstep constant '{name}': kernel source missing",
                  name)
            continue
        got = _regex_values(cpp_text, cpp["pattern"])
        if not got:
            drift(cpp["file"], 1, cpp_text,
                  f"lockstep constant '{name}': manifest pattern no "
                  "longer matches the kernel source (stale manifest or "
                  "renamed constant — update tools/lint/lockstep.json)",
                  name)
        for value, line in got:
            if value != want:
                drift(cpp["file"], line, cpp_text,
                      f"lockstep constant '{name}' drifted in the C "
                      f"kernel: {value} != {want} (Python twin: "
                      f"{entry.get('py', {}).get('file', 'manifest')})",
                      name)
        py = entry.get("py")
        if not py:
            continue
        py_text = text_of(py["file"])
        if py_text is None:
            drift(py["file"], 1, None,
                  f"lockstep constant '{name}': Python twin file "
                  "missing", name)
            continue
        if "attr" in py:
            res = _py_attr_value(py_text, py["attr"])
        elif "enum" in py:
            res = _py_enum_value(py_text, py["enum"][0], py["enum"][1])
        else:
            vals = _regex_values(py_text, py["pattern"])
            res = vals[0] if vals else None
        if res is None:
            drift(py["file"], 1, py_text,
                  f"lockstep constant '{name}': Python twin not found "
                  "(stale manifest — update tools/lint/lockstep.json)",
                  name)
            continue
        pval, pline = res
        if pval != want:
            drift(py["file"], pline, py_text,
                  f"lockstep constant '{name}' drifted on the Python "
                  f"side: {pval} != {want} (kernel: {cpp['file']})",
                  name)
    return findings


# ---------------------------------------------------------------------------
# native-srchash
# ---------------------------------------------------------------------------

def check_srchash(root: str = REPO) -> List[Finding]:
    findings: List[Finding] = []
    ndir = os.path.join(root, NATIVE_DIR)
    if not os.path.isdir(ndir):
        return findings
    # reverse audit: an SO_SOURCES entry naming a source that no longer
    # exists is a stale map (kernel renamed without updating it)
    for so_name, srcs in sorted(SO_SOURCES.items()):
        for s in srcs:
            if not os.path.exists(os.path.join(ndir, s)):
                findings.append(Finding(
                    rule="native-srchash", file=f"{NATIVE_DIR}/{so_name}",
                    line=1, col=0, context="<native>",
                    message=(f"SO_SOURCES maps {so_name} to missing "
                             f"source {s} — update tools/lint/native.py"),
                    line_text=""))
    for name in sorted(os.listdir(ndir)):
        if not name.endswith(".so"):
            continue
        rel = f"{NATIVE_DIR}/{name}"
        srcs = SO_SOURCES.get(name)
        if srcs is None:
            findings.append(Finding(
                rule="native-srchash", file=rel, line=1, col=0,
                context="<native>",
                message=(f"unknown native library {name}: add it to "
                         "tools/lint/native.py SO_SOURCES so its "
                         "sidecar contract is auditable"),
                line_text=""))
            continue
        h = hashlib.sha256()
        try:
            for s in srcs:
                with open(os.path.join(ndir, s), "rb") as fh:
                    h.update(fh.read())
        except OSError:
            findings.append(Finding(
                rule="native-srchash", file=rel, line=1, col=0,
                context="<native>",
                message=f"sources of {name} unreadable: {srcs}",
                line_text=""))
            continue
        try:
            with open(os.path.join(ndir, name + ".srchash")) as fh:
                recorded = fh.read().strip()
        except OSError:
            recorded = None
        if recorded != h.hexdigest():
            findings.append(Finding(
                rule="native-srchash", file=rel, line=1, col=0,
                context="<native>",
                message=(f"{name}.srchash is "
                         f"{'missing' if recorded is None else 'stale'}"
                         " — rebuild the kernel and commit the .so with "
                         "its sidecar (a stale consensus kernel must "
                         "never load)"),
                line_text=""))
    return findings


# ---------------------------------------------------------------------------

def check_native_file(info: NativeInfo) -> List[Finding]:
    """Every rule computable from ONE native source file — the single
    dispatch list shared by the cold run and the --changed cache."""
    findings = _check_gil(info)
    findings.extend(_check_null(info))
    return findings


def check(native_infos: List[NativeInfo],
          py_sources: Optional[Dict[str, str]] = None,
          root: Optional[str] = None,
          run_lockstep: bool = True) -> List[Finding]:
    """Per-file GIL/NULL rules over ``native_infos`` plus the global
    lockstep diff.  ``root`` (when set) additionally enables the
    filesystem-backed srchash sidecar audit."""
    findings: List[Finding] = []
    for info in native_infos:
        findings.extend(check_native_file(info))
    if run_lockstep:
        sources: Dict[str, str] = dict(py_sources or {})
        for info in native_infos:
            sources[info.path] = info.source
        findings.extend(check_lockstep(sources, root=root or REPO))
    if root is not None:
        findings.extend(check_srchash(root))
    return findings
