"""Thread-context model — the substrate for detlint v3's concurrency
rules (tools/lint/concurrency.py).

Each analyzed file yields one JSON-serializable *concurrency summary*
(``FileConc``): per function, the shared-state writes, the lexical lock
acquisitions (with the held-lock prefix at each), every call site (with
the locks held around it), the THREAD ROOTS it declares, and the
thread-affine API touches; per file, the declared locks, the
``# guarded-by:`` table (class-qualified), and the class/base map.

Thread roots recognized statically:

- ``<executor>.submit(fn, ...)`` — the executor's ``thread_name_prefix``
  is resolved through a per-file map of ``ThreadPoolExecutor(...)``
  construction sites (attribute assignments AND lazy factory methods
  like ``ClosePipeline._tails``), so the root context carries the real
  thread name (``worker:close-tail``, ``worker:bucket-merge``, ...);
- ``threading.Thread(target=fn)`` — context ``thread:<fn>``;
- ``ThreadedWork.on_io`` overrides (via the cross-file subclass
  closure) — context ``worker:work-pool``;
- ``<timer>.async_wait(cb)`` — VirtualTimer callbacks fire on the
  crank thread, context ``main``;
- ``gc.callbacks.append(cb)`` — gc callbacks run on WHICHEVER thread
  triggers the collection, context ``any`` (counts as every context).

Contexts then propagate CALLER -> CALLEE through the call graph to a
fixpoint, so every function knows the set of threads it can run on.
Functions with no resolved callers that are not thread roots seed
``main`` (public API, timer/HTTP entry points, test drivers).

Call binding here is deliberately MORE aggressive than the
determinism-taint call graph (callgraph.py): in addition to its
bare-name / ``self.m()`` / ``alias.f()`` resolution, an attribute call
on an arbitrary object (``lm._store_tx_history(...)``) binds iff
exactly ONE function with that name is defined package-wide — the
unique-name (CHA-lite) rule.  Thread contexts flow across objects
(``run_close_tail`` calling LedgerManager methods is exactly how the
tail worker reaches the ledger state), so dropping those edges would
blind the whole analysis; uniqueness keeps the false-edge rate near
zero.  Ambiguous names (``get``, ``execute``, ``close``...) stay
unbound — a documented blind spot (COVERAGE.md).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import callgraph
from .engine import FileInfo, dotted_name as _dotted

MAIN = "main"
ANY = "any"   # gc callbacks: whichever thread triggers the collection

#: how many call edges a context (or a transitive lock acquisition)
#: propagates through; chains deeper than this are beyond what the
#: unique-name resolver stays precise for
MAX_CONTEXT_DEPTH = 12

_EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
#: worker-pool wrapper classes whose internal executor prefix is fixed
_POOL_CLASSES = {"WorkerPool": "work-pool"}
#: obj-attr call names never worth binding even when globally unique —
#: stdlib/vendor surface that would otherwise alias package methods
_OBJ_BIND_STOPLIST = {
    "append", "add", "discard", "remove", "pop", "update", "extend",
    "get", "put", "items", "keys", "values", "join", "split", "read",
    "write", "result", "set", "clear", "copy", "submit", "encode",
    "decode", "hex", "wait", "acquire", "release", "shutdown",
}


# ---------------------------------------------------------------------------
# per-file summary dataclasses (JSON round-trip for the --changed cache)
# ---------------------------------------------------------------------------

@dataclass
class ConcFunc:
    context: str
    line: int
    cls: str = ""
    # [{"owner": cls|"<module>", "field": str, "line": int}]
    writes: List[dict] = field(default_factory=list)
    # [{"lock": token, "line": int, "held": [token, ...]}]
    acquires: List[dict] = field(default_factory=list)
    # call descriptor (name/mod/self/obj) + {"held": [token, ...]}
    calls: List[dict] = field(default_factory=list)
    # [{"target": descriptor|None, "ctx": label, "line": int}]
    roots: List[dict] = field(default_factory=list)
    # [{"api": str, "line": int}]
    affine: List[dict] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"context": self.context, "line": self.line,
                "cls": self.cls, "writes": self.writes,
                "acquires": self.acquires, "calls": self.calls,
                "roots": self.roots, "affine": self.affine}

    @classmethod
    def from_json(cls, d: dict) -> "ConcFunc":
        return cls(context=d["context"], line=d["line"],
                   cls=d.get("cls", ""), writes=list(d["writes"]),
                   acquires=list(d["acquires"]), calls=list(d["calls"]),
                   roots=list(d["roots"]), affine=list(d["affine"]))


@dataclass
class FileConc:
    funcs: List[ConcFunc] = field(default_factory=list)
    # class -> [raw dotted base names]
    bases: Dict[str, List[str]] = field(default_factory=dict)
    # class -> def lineno (for class-level confinement pragmas)
    classes: Dict[str, int] = field(default_factory=dict)
    # "Cls.field" | "field" -> [lock_token, decl_line]
    guards: Dict[str, list] = field(default_factory=dict)
    # declared lock attributes: "Cls.attr" | "attr" -> decl_line
    locks: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"funcs": [f.to_json() for f in self.funcs],
                "bases": self.bases, "classes": self.classes,
                "guards": self.guards, "locks": self.locks}

    @classmethod
    def from_json(cls, d: dict) -> "FileConc":
        return cls(funcs=[ConcFunc.from_json(f) for f in d["funcs"]],
                   bases=dict(d["bases"]), classes=dict(d["classes"]),
                   guards=dict(d["guards"]), locks=dict(d["locks"]))


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def _last_seg(dotted: str) -> str:
    return dotted.rpartition(".")[2]


def _lock_ctor_of(node: ast.AST, imports) -> bool:
    """Is this expression a threading.Lock()/RLock()/... construction
    (possibly wrapped in ``lockdep.register_lock(threading.Lock(), ...)``)?"""
    if not isinstance(node, ast.Call):
        return False
    target = imports.resolve_call(node.func)
    if target is not None:
        if _last_seg(target) == "register_lock" and node.args:
            return _lock_ctor_of(node.args[0], imports)
        if _last_seg(target) in _LOCK_CTORS and "threading" in target:
            return True
    # unresolved attribute spellings like `_threading.Lock()` where the
    # alias map missed: fall back on the ctor name itself
    name = None
    if isinstance(node.func, ast.Attribute):
        name = node.func.attr
    elif isinstance(node.func, ast.Name):
        name = node.func.id
    if name == "register_lock" and node.args:
        return _lock_ctor_of(node.args[0], imports)
    return name in _LOCK_CTORS


def _executor_prefix_of(node: ast.AST, imports) -> Optional[str]:
    """thread_name_prefix of a ThreadPoolExecutor(...) construction (or
    the fixed prefix of a known pool wrapper like WorkerPool)."""
    if not isinstance(node, ast.Call):
        return None
    name = None
    target = imports.resolve_call(node.func)
    if target is not None:
        name = _last_seg(target)
    elif isinstance(node.func, ast.Name):
        name = node.func.id
    elif isinstance(node.func, ast.Attribute):
        name = node.func.attr
    if name in _POOL_CLASSES:
        return _POOL_CLASSES[name]
    if name not in _EXECUTOR_CTORS:
        return None
    for kw in node.keywords:
        if kw.arg == "thread_name_prefix" and \
                isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
    return "pool"


class _FuncConc:
    """One function's concurrency summary (shallow body — nested defs
    are their own summaries, but they share the enclosing class for
    ``self`` attribution: closures over self are common worker bodies)."""

    _MUTATING = {
        "add", "discard", "remove", "pop", "popitem", "clear", "update",
        "append", "extend", "insert", "setdefault", "appendleft",
    }
    _LTXROOT_MUTATORS = {
        "commit_pending_sql", "stage_sealed", "clear_pending",
        "note_bucket_applied", "load_sql_ahead", "enable_bucket_reads",
    }
    _SQLITE_CONN_BASES = {"conn", "_conn"}
    _SQLITE_CURSOR_BASES = {"db", "database", "_db"}

    def __init__(self, info: FileInfo, imports, context: str,
                 cls: Optional[str], node, attr_prefix: Dict[str, str],
                 method_prefix: Dict[str, str]):
        self.info = info
        self.imports = imports
        self.cls = cls or ""
        self.node = node
        self.attr_prefix = attr_prefix
        self.method_prefix = method_prefix
        self.out = ConcFunc(context=context,
                            line=getattr(node, "lineno", 1),
                            cls=self.cls)
        self.held: List[str] = []
        self.globals_decl: Set[str] = set()
        self.is_init = context.rpartition(".")[2] == "__init__"
        self.is_module = context == "<module>"

    def scan(self) -> ConcFunc:
        body = self.node.body if not self.is_module else self.node
        for n in self.globals_of(body):
            self.globals_decl.update(n.names)
        for stmt in body:
            self._walk(stmt)
        return self.out

    @staticmethod
    def globals_of(body):
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Global):
                    yield n

    # -- traversal (skips nested defs; tracks the with-lock stack) ----------

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            pushed = 0
            for item in node.items:
                self._walk(item.context_expr)
                tok = self._lock_token(item.context_expr)
                if tok is not None:
                    self.out.acquires.append(
                        {"lock": tok, "line": node.lineno,
                         "held": list(self.held)})
                    self.held.append(tok)
                    pushed += 1
            for stmt in node.body:
                self._walk(stmt)
            for _ in range(pushed):
                self.held.pop()
            return
        self._inspect(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _lock_token(self, expr: ast.AST) -> Optional[str]:
        d = _dotted(expr)
        if d is None:
            return None
        name = _last_seg(d)
        if "lock" in name.lower() or "mutex" in name.lower():
            return d
        return None

    # -- node inspection -----------------------------------------------------

    def _inspect(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                self._note_write(node, t)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self._note_write(node, node.target)
        elif isinstance(node, ast.AugAssign):
            self._note_write(node, node.target)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                self._note_write(node, t)
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in self._MUTATING:
                self._note_write(node, node.func.value)
            self._note_call(node)

    def _note_write(self, node: ast.AST, target: ast.AST) -> None:
        if self.is_init or self.is_module:
            return  # construction happens-before sharing
        if isinstance(target, ast.Subscript):
            target = target.value
        d = _dotted(target)
        if d is None:
            return
        if d.startswith("self.") and "." not in d[len("self."):]:
            f = d[len("self."):]
            owner = self.cls or "<module>"
        elif "." not in d and d in self.globals_decl:
            f, owner = d, "<module>"
        else:
            return
        self.out.writes.append({"owner": owner, "field": f,
                                "line": node.lineno})

    def _note_call(self, call: ast.Call) -> None:
        func = call.func
        d = self._describe_call(call)
        if d is not None:
            d["held"] = list(self.held)
            self.out.calls.append(d)
        if isinstance(func, ast.Attribute):
            self._note_attr_roots(call, func)
            self._note_affine(call, func)
        target = self.imports.resolve_call(func)
        if target is not None and _last_seg(target) == "Thread" and \
                "threading" in target:
            self._note_thread_root(call)
        if target is not None and (
                target.startswith("jax.") or target == "jax"):
            self.out.affine.append({"api": "jax-device",
                                    "line": call.lineno})

    def _note_attr_roots(self, call: ast.Call,
                         func: ast.Attribute) -> None:
        attr = func.attr
        if attr == "submit" and call.args:
            prefix = self._submit_prefix(func.value)
            ctx = f"worker:{prefix}"
            self.out.roots.append(
                {"target": self._describe_ref(call.args[0]),
                 "ctx": ctx, "line": call.lineno})
        elif attr == "append":
            d = _dotted(func.value)
            if d is not None and _last_seg(d) == "callbacks" and \
                    d.startswith("gc") and call.args:
                self.out.roots.append(
                    {"target": self._describe_ref(call.args[0]),
                     "ctx": ANY, "line": call.lineno})
        elif attr == "async_wait" and call.args:
            # VirtualTimer callbacks fire on the crank (main) thread
            self.out.roots.append(
                {"target": self._describe_ref(call.args[0]),
                 "ctx": MAIN, "line": call.lineno})
        elif attr == "Thread":
            pass  # handled via resolve_call in _note_call

    def _note_thread_root(self, call: ast.Call) -> None:
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = self._describe_ref(kw.value)
        label = None
        for kw in call.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                label = kw.value.value
        if label is None:
            label = (target or {}).get("name", "?")
        self.out.roots.append({"target": target,
                               "ctx": f"thread:{label}",
                               "line": call.lineno})

    def _submit_prefix(self, base: ast.AST) -> str:
        if isinstance(base, ast.Call):
            # lazy factory: self._tails().submit(...)
            m = None
            if isinstance(base.func, ast.Attribute):
                m = base.func.attr
            elif isinstance(base.func, ast.Name):
                m = base.func.id
            if m is not None and m in self.method_prefix:
                return self.method_prefix[m]
            return "?"
        d = _dotted(base)
        if d is None:
            return "?"
        name = _last_seg(d)
        if name in self.attr_prefix:
            return self.attr_prefix[name]
        if "pool" in name.lower():
            return "work-pool"
        return "?"

    def _note_affine(self, call: ast.Call, func: ast.Attribute) -> None:
        attr = func.attr
        base = _dotted(func.value)
        base_name = _last_seg(base) if base else ""
        if attr in ("execute", "executemany", "executescript",
                    "commit", "rollback", "cursor") and \
                base_name in self._SQLITE_CONN_BASES:
            self.out.affine.append({"api": "sqlite-conn",
                                    "line": call.lineno})
        elif attr == "cursor" and base_name in self._SQLITE_CURSOR_BASES:
            self.out.affine.append({"api": "sqlite-cursor",
                                    "line": call.lineno})
        elif attr in self._LTXROOT_MUTATORS:
            self.out.affine.append({"api": "ltxroot-mutate",
                                    "line": call.lineno})
        elif attr == "block_until_ready":
            self.out.affine.append({"api": "jax-device",
                                    "line": call.lineno})

    # -- call / reference descriptors ---------------------------------------

    def _describe_call(self, call: ast.Call) -> Optional[dict]:
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in self.imports.module_member:
                mod, member = self.imports.module_member[name]
                return {"mod": mod, "name": member, "line": call.lineno}
            return {"name": name, "line": call.lineno}
        if isinstance(func, ast.Attribute):
            base = _dotted(func.value)
            if base == "self":
                return {"name": func.attr, "self": self.cls,
                        "line": call.lineno}
            mod = None
            if base is not None:
                mod = self.imports.mod_alias.get(base)
                if mod is None and base in self.imports.module_member:
                    pmod, member = self.imports.module_member[base]
                    mod = f"{pmod}.{member}" if pmod else member
            if mod is not None:
                return {"mod": mod, "name": func.attr,
                        "line": call.lineno}
            # unique-name (CHA-lite) candidate: bound at model time iff
            # exactly one package function carries this name
            if func.attr in _OBJ_BIND_STOPLIST or \
                    func.attr.startswith("__"):
                return None
            return {"name": func.attr, "obj": 1, "line": call.lineno}
        return None

    def _describe_ref(self, expr: ast.AST) -> Optional[dict]:
        """A function REFERENCE (submit/Thread/callback target)."""
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in self.imports.module_member:
                mod, member = self.imports.module_member[name]
                return {"mod": mod, "name": member}
            return {"name": name}
        if isinstance(expr, ast.Attribute):
            base = _dotted(expr.value)
            if base == "self":
                return {"name": expr.attr, "self": self.cls}
            if base is not None:
                mod = self.imports.mod_alias.get(base)
                if mod is None and base in self.imports.module_member:
                    pmod, member = self.imports.module_member[base]
                    mod = f"{pmod}.{member}" if pmod else member
                if mod is not None:
                    return {"mod": mod, "name": expr.attr}
            return {"name": expr.attr, "obj": 1}
        return None  # lambda / computed target: documented blind spot


class _FileConcScanner(ast.NodeVisitor):
    def __init__(self, info: FileInfo):
        self.info = info
        self.imports = callgraph._Imports(info)
        self.out = FileConc()
        self.stack: List[str] = []
        self.cls_stack: List[str] = []
        # executor construction maps (pass 1)
        self.attr_prefix: Dict[str, str] = {}
        self.method_prefix: Dict[str, str] = {}
        self._collect_file_facts()

    # -- pass 1: executors, locks, guards, classes --------------------------

    def _collect_file_facts(self) -> None:
        cls_of: Dict[int, str] = {}
        meth_of: Dict[int, str] = {}
        for node in ast.walk(self.info.tree):
            if isinstance(node, ast.ClassDef):
                self.out.classes[node.name] = node.lineno
                self.out.bases[node.name] = [
                    b for b in (_dotted(x) for x in node.bases)
                    if b is not None]
                for sub in ast.walk(node):
                    if hasattr(sub, "lineno"):
                        cls_of.setdefault(sub.lineno, node.name)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if hasattr(sub, "lineno"):
                        meth_of.setdefault(sub.lineno, node.name)
        for node in ast.walk(self.info.tree):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
                value = node.value
            else:
                continue
            prefix = _executor_prefix_of(value, self.imports)
            is_lock = value is not None and \
                _lock_ctor_of(value, self.imports)
            guard = self._guard_at(node)
            if prefix is None and not is_lock and guard is None:
                continue
            cls = cls_of.get(node.lineno)
            for t in targets:
                d = _dotted(t)
                if d is None:
                    continue
                name = d[len("self."):] if d.startswith("self.") else d
                if "." in name:
                    continue
                if prefix is not None:
                    self.attr_prefix[name] = prefix
                    meth = meth_of.get(node.lineno)
                    if meth is not None:
                        self.method_prefix[meth] = prefix
                qual = f"{cls}.{name}" if cls and \
                    d.startswith("self.") else name
                if is_lock:
                    self.out.locks[qual] = node.lineno
                if guard is not None:
                    self.out.guards[qual] = [guard, node.lineno]

    def _guard_at(self, node: ast.AST) -> Optional[str]:
        lock = self.info.guards.get(node.lineno)
        if lock is None and getattr(node, "end_lineno", None):
            for ln in range(node.lineno, node.end_lineno + 1):
                if ln in self.info.guards:
                    return self.info.guards[ln]
        return lock

    # -- pass 2: per-function detail ----------------------------------------

    def scan(self) -> FileConc:
        # module-level pseudo-function: calls + roots at import time
        mod_stmts = [s for s in self.info.tree.body
                     if not isinstance(s, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))]
        mod = _FuncConc(self.info, self.imports, "<module>", None,
                        mod_stmts, self.attr_prefix, self.method_prefix)
        for stmt in mod_stmts:
            mod._walk(stmt)
        self.out.funcs.append(mod.out)
        self.visit(self.info.tree)
        return self.out

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        context = ".".join(self.stack)
        cls = self.cls_stack[-1] if self.cls_stack else None
        self.out.funcs.append(_FuncConc(
            self.info, self.imports, context, cls, node,
            self.attr_prefix, self.method_prefix).scan())
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def summarize_conc(info: FileInfo) -> FileConc:
    """The concurrency summary of one parsed file."""
    return _FileConcScanner(info).scan()


# ---------------------------------------------------------------------------
# whole-program model (rebuilt every run over whichever summaries exist)
# ---------------------------------------------------------------------------

@dataclass
class Model:
    funcs: Dict[str, ConcFunc] = field(default_factory=dict)
    path_of: Dict[str, str] = field(default_factory=dict)
    # caller key -> [(callee key, line, frozenset(qualified held))]
    edges: Dict[str, List[Tuple[str, int, frozenset]]] = \
        field(default_factory=dict)
    rev: Dict[str, List[str]] = field(default_factory=dict)
    contexts: Dict[str, Set[str]] = field(default_factory=dict)
    root_targets: Dict[str, Set[str]] = field(default_factory=dict)
    # inventory: [{"ctx", "file", "line", "target", "resolved"}]
    roots: List[dict] = field(default_factory=list)
    # qualified locks provably held on entry from EVERY resolved caller
    held_entry: Dict[str, Set[str]] = field(default_factory=dict)
    # transitive acquisitions: key -> {qlock: (file, line, [chain ctxs])}
    acq_trans: Dict[str, Dict[str, tuple]] = field(default_factory=dict)
    conc: Dict[str, FileConc] = field(default_factory=dict)
    # lock attr name -> [qualified ids] across the package
    lock_index: Dict[str, List[str]] = field(default_factory=dict)

    def qualify_lock(self, token: str, path: str, cls: str) -> str:
        """Lock identity for an acquisition token seen in ``path``
        inside class ``cls``: ``self.X`` binds to this class's
        declaration, a bare name to the module's, and a deep attribute
        chain (``bm._gc_lock``) through the package-wide declaration
        map when the attribute name is unique — the cross-file
        resolution v1 lacked."""
        name = _last_seg(token)
        if token.startswith("self."):
            rest = token[len("self."):]
            if "." not in rest:
                return f"{path}::{cls}.{rest}" if cls \
                    else f"{path}::{rest}"
            # self.a.b._lock: fall through to the unique-name map
        elif "." not in token:
            fc = self.conc.get(path)
            if fc is not None and cls and f"{cls}.{token}" in fc.locks:
                return f"{path}::{cls}.{token}"
            return f"{path}::{token}"
        ids = self.lock_index.get(name, [])
        if len(ids) == 1:
            return ids[0]
        return f"{path}::~{name}"


def _class_closure(conc: Dict[str, FileConc]) -> Dict[str, Set[str]]:
    """class name -> transitive base-name closure (simple-name match —
    one package, collisions acceptable)."""
    direct: Dict[str, Set[str]] = {}
    for fc in conc.values():
        for cls, bases in fc.bases.items():
            direct.setdefault(cls, set()).update(
                _last_seg(b) for b in bases)
    closure: Dict[str, Set[str]] = {}

    def expand(cls: str, seen: Set[str]) -> Set[str]:
        if cls in closure:
            return closure[cls]
        if cls in seen:
            return set()
        seen.add(cls)
        out = set(direct.get(cls, ()))
        for b in list(out):
            out |= expand(b, seen)
        closure[cls] = out
        return out

    for cls in direct:
        expand(cls, set())
    return closure


def build_model(conc: Dict[str, FileConc]) -> Model:
    m = Model(conc=conc)
    summaries = {path: fc.funcs for path, fc in conc.items()}
    module_files = {callgraph.module_of(p): p for p in summaries}
    module_level, methods, any_method = \
        callgraph._index_functions(summaries)

    # unique-name index for obj-attr binding: methods and module-level
    # functions only (never nested defs)
    name_index: Dict[str, List[str]] = {}
    for path, funcs in summaries.items():
        for f in funcs:
            parts = f.context.split(".")
            if len(parts) > 2 or f.context == "<module>":
                continue
            name_index.setdefault(parts[-1], []).append(
                f"{path}::{f.context}")

    for path, fc in conc.items():
        for qual, line in fc.locks.items():
            m.lock_index.setdefault(_last_seg(qual), []).append(
                f"{path}::{qual}")
    for ids in m.lock_index.values():
        ids.sort()

    def bind(call: dict, path: str) -> List[str]:
        if call.get("obj"):
            cands = name_index.get(call["name"], ())
            return list(cands) if len(cands) == 1 else []
        return callgraph._bind(call, path, module_files, module_level,
                               methods, any_method)

    # -- edges ---------------------------------------------------------------
    for path in sorted(summaries):
        fc = conc[path]
        for f in fc.funcs:
            key = f"{path}::{f.context}"
            m.funcs[key] = f
            m.path_of[key] = path
            out: List[Tuple[str, int, frozenset]] = []
            for call in f.calls:
                held = frozenset()
                if call.get("held"):
                    held = frozenset(
                        m.qualify_lock(t, path, f.cls)
                        for t in call["held"])
                for callee in bind(call, path):
                    out.append((callee, call["line"], held))
            m.edges[key] = out
    for caller, edges in m.edges.items():
        for callee, _line, _held in edges:
            m.rev.setdefault(callee, []).append(caller)

    # -- thread roots --------------------------------------------------------
    closure = _class_closure(conc)
    for path in sorted(summaries):
        for f in conc[path].funcs:
            for r in f.roots:
                keys: List[str] = []
                tgt = r.get("target")
                if tgt is not None:
                    keys = bind(dict(tgt), path)
                    if not keys and "name" in tgt and \
                            "mod" not in tgt:
                        # nested defs (thread bodies defined inline):
                        # last-segment match within the same file
                        suffix = "." + tgt["name"]
                        cands = [k for k in m.funcs
                                 if m.path_of[k] == path
                                 and k.endswith(suffix)]
                        if len(cands) == 1:
                            keys = cands
                for key in keys:
                    m.root_targets.setdefault(key, set()).add(r["ctx"])
                m.roots.append({
                    "ctx": r["ctx"], "file": path, "line": r["line"],
                    "target": (tgt or {}).get("name", "<dynamic>"),
                    "resolved": sorted(keys)})
    # ThreadedWork subclasses: on_io runs on the work pool even when the
    # submit site's target is unresolvable across files
    for path in sorted(summaries):
        for cls in conc[path].bases:
            chain = {cls} | closure.get(cls, set())
            if "ThreadedWork" in chain:
                key = methods.get((path, cls, "on_io"))
                if key is not None:
                    m.root_targets.setdefault(key, set()).add(
                        "worker:work-pool")
                    m.roots.append({
                        "ctx": "worker:work-pool", "file": path,
                        "line": m.funcs[key].line,
                        "target": f"{cls}.on_io", "resolved": [key]})
    m.roots.sort(key=lambda r: (r["file"], r["line"], r["ctx"]))

    # -- context propagation (caller -> callee, to fixpoint) -----------------
    incoming: Dict[str, int] = {}
    for caller, edges in m.edges.items():
        for callee, _line, _held in edges:
            incoming[callee] = incoming.get(callee, 0) + 1
    contexts: Dict[str, Set[str]] = {k: set() for k in m.funcs}
    for key, ctxs in m.root_targets.items():
        if key in contexts:
            contexts[key] |= ctxs
    for key in m.funcs:
        if not incoming.get(key) and key not in m.root_targets:
            contexts[key].add(MAIN)
    frontier = [k for k in sorted(contexts) if contexts[k]]
    depth = 0
    while frontier and depth < MAX_CONTEXT_DEPTH:
        depth += 1
        nxt: List[str] = []
        for key in frontier:
            for callee, _line, _held in m.edges.get(key, ()):
                before = len(contexts[callee])
                contexts[callee] |= contexts[key]
                if len(contexts[callee]) != before:
                    nxt.append(callee)
        frontier = sorted(set(nxt))
    m.contexts = contexts

    # -- held-at-entry (intersection over all resolved callers) -------------
    TOP = None  # lattice top: "every lock" until a caller constrains it
    held: Dict[str, Optional[Set[str]]] = {}
    for key in m.funcs:
        held[key] = TOP if incoming.get(key) else set()
    for key in m.root_targets:
        held[key] = set()  # a thread root starts with nothing held
    for _ in range(MAX_CONTEXT_DEPTH):
        changed = False
        for callee in sorted(m.rev):
            if held.get(callee) == set():
                continue
            acc: Optional[Set[str]] = TOP
            for caller in m.rev[callee]:
                for ckey, _line, site_held in m.edges.get(caller, ()):
                    if ckey != callee:
                        continue
                    h = set(site_held)
                    if held.get(caller) not in (TOP, None):
                        h |= held[caller]
                    acc = h if acc is TOP else (acc & h)
            if acc is TOP:
                acc = set()
            if held.get(callee) in (TOP, None) or held[callee] != acc:
                if held[callee] is TOP or held[callee] is None or \
                        acc != held[callee]:
                    held[callee] = acc
                    changed = True
        if not changed:
            break
    m.held_entry = {k: (v if v not in (TOP, None) else set())
                    for k, v in held.items()}

    # -- transitive lock acquisitions (callee -> caller, depth-bounded) -----
    acq: Dict[str, Dict[str, tuple]] = {}
    for key, f in m.funcs.items():
        path = m.path_of[key]
        own: Dict[str, tuple] = {}
        for a in f.acquires:
            q = m.qualify_lock(a["lock"], path, f.cls)
            own.setdefault(q, (path, a["line"], [f.context]))
        acq[key] = own
    for _ in range(MAX_CONTEXT_DEPTH // 2):
        changed = False
        for caller in sorted(m.edges):
            mine = acq[caller]
            for callee, _line, _held in m.edges[caller]:
                for q, wit in acq.get(callee, {}).items():
                    if q not in mine:
                        mine[q] = (wit[0], wit[1],
                                   [m.funcs[caller].context] + wit[2])
                        changed = True
        if not changed:
            break
    m.acq_trans = acq
    return m
