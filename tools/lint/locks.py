"""Lock-discipline rules (family b) for the threaded subsystems
(bucket merge pipeline, native library loader, device probe, quorum
intersection bridge).

Convention: a shared field declares its lock with a trailing comment on
its (ann-)assignment line::

    self._bg_outputs: set = set()  # guarded-by: _bg_lock
    _lib = None                    # guarded-by: _lock

Rules
-----
lock-unguarded-write   a mutation of a guarded field (assignment,
                       augmented assignment, mutating method call like
                       .add/.pop/.update, subscript store/delete)
                       lexically outside a ``with <lock>:`` block.
                       ``__init__`` bodies and module top-level are
                       exempt: construction happens-before sharing.
lock-order             two locks acquired in opposite nesting orders
                       within one file — the classic ABBA deadlock
                       shape.  Per-file on purpose: lock names are only
                       unambiguous inside their defining module
                       (`_lock` in native/__init__.py and `_lock` in
                       utils/device.py are different objects).
lock-unknown-guard     a guarded-by annotation naming a lock that is
                       never acquired anywhere in the file (typo guard).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .engine import ContextVisitor, FileInfo, Finding, dotted_name as _dotted

_MUTATING_METHODS = {
    "add", "discard", "remove", "pop", "popitem", "clear", "update",
    "append", "extend", "insert", "setdefault", "appendleft",
}


def _field_name(node: ast.AST) -> Optional[str]:
    """Normalized field name: 'self.x' -> 'x', bare 'x' -> 'x'."""
    d = _dotted(node)
    if d is None:
        return None
    if d.startswith("self."):
        d = d[len("self."):]
    if "." in d:
        return None  # deeper chains (self.a.b) are not declarable fields
    return d


def _lock_name(node: ast.AST) -> Optional[str]:
    """Normalized lock name from a with-item expression."""
    return _field_name(node)


def _collect_guards(info: FileInfo) -> Dict[str, Tuple[str, int]]:
    """field -> (lock, decl_line) from '# guarded-by:' annotations
    attached to (ann-)assignment lines."""
    guards: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(info.tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        lock = info.guards.get(node.lineno)
        if lock is None and getattr(node, "end_lineno", None):
            for ln in range(node.lineno, node.end_lineno + 1):
                if ln in info.guards:
                    lock = info.guards[ln]
                    break
        if lock is None:
            continue
        for t in targets:
            f = _field_name(t)
            if f is not None:
                guards[f] = (lock, node.lineno)
    return guards


class _LockVisitor(ContextVisitor):
    def __init__(self, info: FileInfo, guards: Dict[str, Tuple[str, int]]):
        super().__init__(info)
        self.guards = guards
        self.held: List[str] = []          # current lock nesting
        self.acquired: Set[str] = set()    # every lock ever acquired
        # (outer, inner) -> first witness (file, line)
        self.order: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.func_depth = 0

    # -- with-block tracking ------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = _lock_name(item.context_expr)
            if lock is not None and self._looks_like_lock(lock):
                self.acquired.add(lock)
                for outer in self.held:
                    if outer != lock:
                        self.order.setdefault(
                            (outer, lock),
                            (self.info.path, node.lineno))
                self.held.append(lock)
                pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self.held.pop()

    def _looks_like_lock(self, name: str) -> bool:
        if any(name == lock for lock, _ in self.guards.values()):
            return True
        return "lock" in name.lower() or "mutex" in name.lower()

    # -- function / exemption tracking --------------------------------------

    def _visit_func(self, node) -> None:
        self.func_depth += 1
        ContextVisitor._visit_func(self, node)
        self.func_depth -= 1

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _exempt(self) -> bool:
        """Construction contexts: module top level and __init__."""
        if self.func_depth == 0:
            return True
        return bool(self.stack) and self.stack[-1] == "__init__"

    def _check_mutation(self, node: ast.AST, field_expr: ast.AST) -> None:
        f = _field_name(field_expr)
        if f is None or f not in self.guards:
            return
        lock, decl_line = self.guards[f]
        if getattr(node, "lineno", 0) == decl_line:
            return  # the declaration itself
        if self._exempt():
            return
        if lock in self.held:
            return
        self.add("lock-unguarded-write", node,
                 f"write to '{f}' (guarded-by: {lock}) outside "
                 f"'with {lock}:'")

    # -- mutations -----------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._check_mutation(node, t.value)
            else:
                self._check_mutation(node, t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            if isinstance(node.target, ast.Subscript):
                self._check_mutation(node, node.target.value)
            else:
                self._check_mutation(node, node.target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Subscript):
            self._check_mutation(node, node.target.value)
        else:
            self._check_mutation(node, node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._check_mutation(node, t.value)
            else:
                self._check_mutation(node, t)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATING_METHODS:
            self._check_mutation(node, node.func.value)
        self.generic_visit(node)


def check(infos: List[FileInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for info in infos:
        guards = _collect_guards(info)
        v = _LockVisitor(info, guards)
        v.visit(info.tree)
        findings.extend(v.findings)
        # unknown-guard: declared lock never acquired in this file
        for f, (lock, line) in sorted(guards.items()):
            if lock not in v.acquired:
                findings.append(Finding(
                    rule="lock-unknown-guard", file=info.path, line=line,
                    col=0, context="<module>",
                    message=(f"'{f}' declares guarded-by: {lock} but "
                             f"'with {lock}:' never appears in this file"),
                    line_text=info.line_text(line)))
        # ABBA within this file: both (a, b) and (b, a) witnessed —
        # same-NAMED locks in different modules are different objects,
        # so cross-file pairing would both false-positive and mask
        seen: Set[Tuple[str, str]] = set()
        for (a, b), (path, line) in sorted(v.order.items(),
                                           key=lambda kv: kv[1]):
            if (b, a) in v.order and (b, a) not in seen:
                seen.add((a, b))
                other_path, other_line = v.order[(b, a)]
                findings.append(Finding(
                    rule="lock-order", file=path, line=line, col=0,
                    context="<module>",
                    message=(f"lock order inversion: {a} -> {b} here "
                             f"but {b} -> {a} at "
                             f"{other_path}:{other_line}"),
                    line_text=info.line_text(line)))
    return findings
