"""Determinism rules (family a): consensus-critical modules must not
read ambient nondeterminism or iterate hash-ordered containers into
anything that serializes, hashes, or tallies.

Rules
-----
det-wallclock     time.*/datetime.now/random.*/uuid.*/os.environ reads in
                  a consensus module (scp/herder/ledger/bucket/
                  transactions/xdr/crypto).  The virtual clock
                  (app.clock / VirtualClock) is the sanctioned time
                  source; seeded random.Random(seed) instances are fine.
                  SANCTIONED instrumentation APIs — utils.tracing.span /
                  utils.tracing.stopwatch / Timer.time_scope — are
                  explicitly exempt: their perf_counter reads live in
                  utils/ (outside the consensus scan) and feed only
                  observability, so adding a span to a consensus module
                  never needs a new baseline entry.
det-unsorted-iter a for-loop / list-comp / generator over an unsorted
                  dict view (.items()/.values()/.keys()) or a set-typed
                  name, in a function that feeds a hash/serialize/tally
                  sink.  Set/dict comprehensions are exempt — their
                  RESULT is order-insensitive.  Wrap the iterable in
                  sorted(...) to fix.
det-float-consensus
                  float division (or float()/round() coercion) touching
                  ledger-value names (fee/price/amount/balance/stroop/
                  coin) in a consensus module — consensus math must be
                  exact int (the reference's uint128 discipline).
det-jit-host-effect
                  host-side Python effects (print/open/os/time/random/
                  np.random/environ) inside a jax.jit-decorated function
                  in ops/ — traced once, silently stale or nondeterministic
                  after compilation caching.
det-telemetry-readback
                  the SCP timeline recorder (scp/timeline.py) must stay
                  WRITE-ONLY from consensus code: a ``.timeline``
                  reference may be aliased to a local, guarded on
                  ``.enabled`` / ``is None``, and called as a bare
                  ``.record(...)`` statement — any other use (return,
                  argument, arithmetic, iteration, reading its state)
                  is a data flow from telemetry into consensus and
                  breaks the telemetry-on/off bit-identity contract.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .engine import ContextVisitor, FileInfo, Finding, dotted_name as _dotted

# module -> banned attributes (call or bare attribute access)
_WALLCLOCK_MODS: Dict[str, Set[str]] = {
    "time": {"time", "time_ns", "monotonic", "monotonic_ns",
             "perf_counter", "perf_counter_ns", "process_time",
             "process_time_ns", "localtime", "gmtime", "ctime", "asctime"},
    "random": {"random", "randrange", "randint", "choice", "choices",
               "shuffle", "sample", "uniform", "getrandbits", "betavariate",
               "gauss", "normalvariate", "triangular", "expovariate"},
    "uuid": {"uuid1", "uuid3", "uuid4", "uuid5", "getnode"},
    "os": {"getenv", "environ"},
}
_DATETIME_METHODS = {"now", "utcnow", "today"}

# sanctioned instrumentation APIs: calls through these never produce
# det-wallclock findings, whatever future rule tightening adds to the
# banned table — instrumentation must stay cheap to add (the flight
# recorder's whole point).  Matching is on the resolved call target:
# "...utils.tracing.span", bare "span"/"stopwatch" from-imported from
# the tracing module, and any ".time_scope" metric-timer scope.
_SANCTIONED_SUFFIXES = (
    "utils.tracing.span", "utils.tracing.stopwatch",
    "tracing.span", "tracing.stopwatch",
)
_SANCTIONED_ATTRS = {"time_scope"}


def is_sanctioned_timing_call(target: Optional[str]) -> bool:
    if not target:
        return False
    if target.endswith(_SANCTIONED_SUFFIXES):
        return True
    return target.rpartition(".")[2] in _SANCTIONED_ATTRS

# call names whose enclosing function marks iteration order as
# consensus-visible: hashing/serialization, federated tallies, and
# order-carried set mutation (.add in a loop whose pick depends on what
# was added so far — the nomination round-leader bug shape)
_SINKS_EXACT = {
    "sha256", "sha512", "blake2b", "digest", "hexdigest", "tally",
    "federated_accept", "federated_ratify", "is_quorum", "is_v_blocking",
    "combine_candidates", "emit_envelope", "serialize", "add", "execute",
    "sign", "add_batch",
}
_SINKS_SUFFIX = ("hash", "encode")

_LEDGER_VALUE_RE = ("fee", "price", "amount", "balance", "stroop", "coin")

_JIT_EFFECT_MODS = {"os", "time", "random"}
_JIT_EFFECT_CALLS = {"print", "open", "input"}


class _ImportMap:
    """Resolves local alias -> canonical module / member names."""

    def __init__(self, tree: ast.AST):
        self.mod_alias: Dict[str, str] = {}   # alias -> module name
        self.member: Dict[str, str] = {}      # name -> "module.member"
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_alias[a.asname or a.name] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.member[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def resolve_call(self, func: ast.AST) -> Optional[str]:
        """Canonical 'module.attr' for a call target, if resolvable."""
        if isinstance(func, ast.Attribute):
            base = _dotted(func.value)
            if base is None:
                return None
            mod = self.mod_alias.get(base, base)
            return f"{mod}.{func.attr}"
        if isinstance(func, ast.Name):
            return self.member.get(func.id, func.id)
        return None


# ---------------------------------------------------------------------------
# det-wallclock
# ---------------------------------------------------------------------------

class _WallclockVisitor(ContextVisitor):
    def __init__(self, info: FileInfo, imports: _ImportMap):
        super().__init__(info)
        self.imports = imports

    def _check_target(self, node: ast.AST, target: Optional[str]) -> None:
        if not target or "." not in target:
            # from-import resolution maps bare names to module.member
            return
        if is_sanctioned_timing_call(target):
            return
        mod, _, attr = target.rpartition(".")
        # datetime.datetime.now / date.today
        if mod in ("datetime.datetime", "datetime.date", "datetime") and \
                attr in _DATETIME_METHODS:
            self.add("det-wallclock", node,
                     f"wall-clock read {target}() in consensus module "
                     "(use the virtual clock)")
            return
        banned = _WALLCLOCK_MODS.get(mod)
        if banned and attr in banned:
            what = ("ambient environment read" if mod == "os"
                    else "unseeded RNG" if mod == "random"
                    else "wall-clock read")
            self.add("det-wallclock", node,
                     f"{what} {target} in consensus module")

    def visit_Call(self, node: ast.Call) -> None:
        self._check_target(node, self.imports.resolve_call(node.func))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # bare os.environ access (subscript, .get, iteration ...)
        base = _dotted(node.value)
        if base is not None:
            mod = self.imports.mod_alias.get(base, base)
            if mod == "os" and node.attr == "environ":
                self.add("det-wallclock", node,
                         "ambient environment read os.environ in "
                         "consensus module")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# det-unsorted-iter
# ---------------------------------------------------------------------------

_ITER_UNWRAP = {"list", "tuple", "enumerate", "reversed", "iter"}

# consumers whose RESULT does not depend on iteration order: a
# comprehension fed straight into one of these is exempt
_ORDER_INSENSITIVE_CONSUMERS = {
    "sorted", "set", "frozenset", "min", "max", "sum", "any", "all",
    "len",
}

_SET_TYPE_NAMES = {"set", "Set", "frozenset", "FrozenSet", "MutableSet"}


def _is_set_expr(node: ast.AST) -> bool:
    return isinstance(node, (ast.Set, ast.SetComp)) or (
        isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
        and node.func.id in ("set", "frozenset"))


def _set_annotation(ann: Optional[ast.AST]) -> bool:
    """True only when the OUTER type is a set (List[set] is a list)."""
    if ann is None:
        return False
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    name = None
    if isinstance(ann, ast.Name):
        name = ann.id
    elif isinstance(ann, ast.Attribute):
        name = ann.attr
    return name in _SET_TYPE_NAMES


class _FuncScope(ast.NodeVisitor):
    """Names bound to set values.  With ``self_only`` (the class-wide
    pass) only ``self.X`` attribute bindings are collected — a bare
    local in one method says nothing about other methods."""

    def __init__(self, self_only: bool = False):
        self.set_names: Set[str] = set()
        self.self_only = self_only

    def _record(self, target: ast.AST) -> None:
        d = _dotted(target)
        if d is None:
            return
        if self.self_only and not d.startswith("self."):
            return
        self.set_names.add(d)

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_set_expr(node.value):
            for t in node.targets:
                self._record(t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if _set_annotation(node.annotation) or (
                node.value is not None and _is_set_expr(node.value)):
            self._record(node.target)
        self.generic_visit(node)

    def visit_arg(self, node: ast.arg) -> None:
        if not self.self_only and _set_annotation(node.annotation):
            self.set_names.add(node.arg)


def _shallow_walk(func):
    """Walk a function's body WITHOUT descending into nested def/class
    bodies — those are visited as their own contexts, and scanning them
    here too would double-report every finding (once per context)."""
    from collections import deque

    todo = deque([func])
    while todo:
        node = todo.popleft()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            todo.append(child)


def _call_names(func) -> Set[str]:
    out: Set[str] = set()
    for node in _shallow_walk(func):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                out.add(node.func.attr)
            elif isinstance(node.func, ast.Name):
                out.add(node.func.id)
    return out


def _has_sink(names: Set[str]) -> bool:
    for n in names:
        if n in _SINKS_EXACT:
            return True
        low = n.lower()
        if any(low.endswith(s) for s in _SINKS_SUFFIX):
            return True
    return False


def _unwrap_iter(node: ast.AST) -> ast.AST:
    while isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in _ITER_UNWRAP and node.args:
        node = node.args[0]
    return node


class _UnsortedIterVisitor(ContextVisitor):
    """Runs per function: collects set-typed names for the whole class
    first (self.X = set() in any method marks self.X)."""

    def __init__(self, info: FileInfo):
        super().__init__(info)
        self.class_sets: List[Set[str]] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        scope = _FuncScope(self_only=True)
        scope.visit(node)
        self.class_sets.append(scope.set_names)
        super().visit_ClassDef(node)
        self.class_sets.pop()

    def _visit_func(self, node) -> None:
        scope = _FuncScope()
        scope.visit(node)
        known_sets = set(scope.set_names)
        for cls in self.class_sets:
            known_sets |= cls
        if _has_sink(_call_names(node)):
            self.stack.append(node.name)
            self._scan_iterations(node, known_sets)
            self.stack.pop()
        # still recurse for nested defs/classes
        ContextVisitor._visit_func(self, node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _scan_iterations(self, func, known_sets: Set[str]) -> None:
        exempt: Set[int] = set()
        for node in _shallow_walk(func):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in _ORDER_INSENSITIVE_CONSUMERS:
                for a in node.args:
                    if isinstance(a, (ast.ListComp, ast.GeneratorExp)):
                        exempt.add(id(a))
        for node in _shallow_walk(func):
            if isinstance(node, ast.For):
                self._check_iter(node.iter, node, known_sets)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp)):
                # Set/DictComp results are order-insensitive: exempt,
                # as is a comprehension fed straight into sorted()/sum()/
                # any()/... whose result ignores order
                if id(node) in exempt:
                    continue
                for gen in node.generators:
                    self._check_iter(gen.iter, node, known_sets)

    def _check_iter(self, it: ast.AST, where: ast.AST,
                    known_sets: Set[str]) -> None:
        it = _unwrap_iter(it)
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "sorted":
            return
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("items", "values", "keys") \
                and not it.args:
            self.add("det-unsorted-iter", where,
                     f"iteration over unsorted .{it.func.attr}() view in "
                     "a hash/serialize/tally-feeding function "
                     "(wrap in sorted(...))")
            return
        d = _dotted(it)
        if d is not None and d in known_sets:
            self.add("det-unsorted-iter", where,
                     f"iteration over set '{d}' in a hash/serialize/"
                     "tally-feeding function (wrap in sorted(...))")


# ---------------------------------------------------------------------------
# det-float-consensus
# ---------------------------------------------------------------------------

def _mentions_ledger_value(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name:
            low = name.lower()
            if any(k in low for k in _LEDGER_VALUE_RE):
                return True
    return False


class _FloatVisitor(ContextVisitor):
    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, ast.Div) and (
                _mentions_ledger_value(node.left)
                or _mentions_ledger_value(node.right)):
            self.add("det-float-consensus", node,
                     "float division on a ledger value (fee/price/amount) "
                     "— use exact int math (//, Fraction, or "
                     "cross-multiplication)")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name) and node.func.id == "float" \
                and node.args and _mentions_ledger_value(node.args[0]):
            self.add("det-float-consensus", node,
                     "float() coercion of a ledger value — consensus "
                     "math must stay exact int")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# det-jit-host-effect
# ---------------------------------------------------------------------------

def _is_jit_decorator(dec: ast.AST) -> bool:
    d = _dotted(dec)
    if d in ("jit", "jax.jit"):
        return True
    if isinstance(dec, ast.Call):
        f = _dotted(dec.func)
        if f in ("jit", "jax.jit"):
            return True
        if f in ("partial", "functools.partial") and dec.args:
            return _dotted(dec.args[0]) in ("jit", "jax.jit")
    return False


class _JitVisitor(ContextVisitor):
    def __init__(self, info: FileInfo, imports: _ImportMap):
        super().__init__(info)
        self.imports = imports

    def _visit_func(self, node) -> None:
        if any(_is_jit_decorator(d) for d in node.decorator_list):
            self.stack.append(node.name)
            self._scan_body(node)
            self.stack.pop()
        ContextVisitor._visit_func(self, node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _scan_body(self, func) -> None:
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                target = self.imports.resolve_call(node.func) or ""
                mod = target.split(".", 1)[0]
                if target in _JIT_EFFECT_CALLS or \
                        mod in _JIT_EFFECT_MODS or \
                        target.startswith(("np.random.", "numpy.random.")):
                    self.add("det-jit-host-effect", node,
                             f"host-side effect '{target}' inside a "
                             "jax.jit-traced kernel (runs once at trace "
                             "time, not per call)")
            elif isinstance(node, ast.Attribute) and \
                    node.attr == "environ":
                base = _dotted(node.value)
                if base and self.imports.mod_alias.get(base, base) == "os":
                    self.add("det-jit-host-effect", node,
                             "os.environ read inside a jax.jit-traced "
                             "kernel (baked in at trace time)")


# ---------------------------------------------------------------------------
# det-telemetry-readback
# ---------------------------------------------------------------------------

class _TelemetryReadback(ContextVisitor):
    """Flag any data flow FROM the slot-timeline recorder INTO
    consensus code.  Allowed shapes (everything the instrumented call
    sites need, nothing more):

      tl = <chain>.timeline          # alias to a local name
      if tl.enabled: ...             # / <chain>.timeline.enabled
      if tl is None / is not None:   # existence guard
      tl.record(...)                 # / <chain>.timeline.record(...)
                                     # as a bare expression statement

    Every other appearance of a timeline reference — returned, passed
    to another call, iterated, subscripted, read for its state — is a
    finding: the recorder must be taint-sink-free."""

    def visit_Module(self, node) -> None:
        self._scan(node)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self._scan(node)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self._scan(node)
        self.stack.pop()
        ContextVisitor._visit_func(self, node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _scan(self, scope) -> None:
        aliases: Set[str] = set()
        for n in _shallow_walk(scope):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    isinstance(n.value, ast.Attribute) and \
                    n.value.attr == "timeline":
                aliases.add(n.targets[0].id)

        def ref(n: ast.AST) -> bool:
            return (isinstance(n, ast.Attribute)
                    and n.attr == "timeline") or \
                   (isinstance(n, ast.Name) and n.id in aliases)

        ok_ids: Set[int] = set()
        for n in _shallow_walk(scope):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and ref(n.value):
                ok_ids.add(id(n.value))
            elif isinstance(n, ast.Expr) and isinstance(n.value, ast.Call):
                f = n.value.func
                if isinstance(f, ast.Attribute) and f.attr == "record" \
                        and ref(f.value):
                    ok_ids.add(id(f.value))
            elif isinstance(n, (ast.If, ast.While, ast.IfExp, ast.Assert)):
                self._mark_guard(n.test, ref, ok_ids)
        for n in _shallow_walk(scope):
            # Store/Del contexts write INTO the name (aliasing, or
            # installing the recorder attribute) — no data flows OUT
            # of the recorder there
            if isinstance(getattr(n, "ctx", None), (ast.Store, ast.Del)):
                continue
            if ref(n) and id(n) not in ok_ids:
                self.add(
                    "det-telemetry-readback", n,
                    "timeline recorder state must not flow into "
                    "consensus code (allowed: alias, .enabled / "
                    "is-None guard, bare .record(...) statement)")

    @staticmethod
    def _mark_guard(test: ast.AST, ref, ok_ids: Set[int]) -> None:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) and sub.attr == "enabled" \
                    and ref(sub.value):
                ok_ids.add(id(sub.value))
            elif isinstance(sub, ast.Compare) and ref(sub.left) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in sub.ops) and \
                    all(isinstance(c, ast.Constant) and c.value is None
                        for c in sub.comparators):
                ok_ids.add(id(sub.left))


# ---------------------------------------------------------------------------

def check(info: FileInfo) -> List[Finding]:
    findings: List[Finding] = []
    if info.in_consensus():
        imports = _ImportMap(info.tree)
        visitors = [_WallclockVisitor(info, imports),
                    _UnsortedIterVisitor(info),
                    _FloatVisitor(info)]
        if not info.path.endswith("scp/timeline.py"):
            # the recorder module itself is the one legitimate reader
            visitors.append(_TelemetryReadback(info))
        for visitor in visitors:
            visitor.visit(info.tree)
            findings.extend(visitor.findings)
    if info.in_kernels():
        imports = _ImportMap(info.tree)
        v = _JitVisitor(info, imports)
        v.visit(info.tree)
        findings.extend(v.findings)
    return findings
