"""Incremental (``--changed``) runs: a content-hash cache so the dev
loop pays only for the files it touched while keeping FULL-run
accuracy.

``.detlint-cache.json`` (repo root, gitignored) stores, per analyzed
file: its sha256, the per-file findings (determinism/safety/locks for
.py, GIL/NULL audits for .cpp/.c — everything computable from that file
alone, post-pragma), and the call-graph summaries the interprocedural
pass needs.  A ``--changed`` run hashes every discovered file (~150
small files, milliseconds), replays cached results for hash hits,
(re)parses only the misses, then recomputes the cheap global passes —
interprocedural taint binding+propagation over the merged summaries,
the lockstep manifest diff, the srchash audit — from scratch.  The
result is bit-identical to a cold full run (``--strict`` on
``--changed`` is sound); only the wall time differs.  Pragmas live in
the same file as their findings, so caching post-suppression findings
is safe: editing a pragma changes the hash and invalidates the entry.

verify_green and the tier-1 test keep the cold full run on purpose —
the cache is a dev-loop convenience, never the gate's source of truth.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from . import callgraph, threadmodel
from .engine import (
    NATIVE_EXTS, REPO, FileInfo, Finding, _parse_file, _suppressed,
    discover_files, light_info,
)
# (per-file rule dispatch lives in engine.check_py_file /
# native.check_native_file — ONE list for cold and cached paths)

CACHE_VERSION = 1
CACHE_BASENAME = ".detlint-cache.json"


def cache_path(root: str = REPO) -> str:
    return os.path.join(root, CACHE_BASENAME)


def _tools_fingerprint() -> str:
    """sha256 over the analyzer's own sources (rule modules + lockstep
    manifest).  Cached per-file findings were computed BY these rules —
    pulling a commit that changes a rule must invalidate every entry,
    or '--changed --strict' could stay green where a cold run goes red.
    baseline.json is excluded: it affects matching, not findings."""
    h = hashlib.sha256()
    lint_dir = os.path.dirname(os.path.abspath(__file__))
    for name in sorted(os.listdir(lint_dir)):
        if name == "baseline.json" or \
                not name.endswith((".py", ".json")):
            continue
        with open(os.path.join(lint_dir, name), "rb") as fh:
            h.update(name.encode("utf-8"))
            h.update(fh.read())
    return h.hexdigest()


def _empty_cache(tools_sha: str) -> dict:
    return {"version": CACHE_VERSION, "tools_sha256": tools_sha,
            "files": {}}


def _load(path: str, tools_sha: str) -> dict:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return _empty_cache(tools_sha)
    if data.get("version") != CACHE_VERSION or \
            data.get("tools_sha256") != tools_sha or \
            not isinstance(data.get("files"), dict):
        return _empty_cache(tools_sha)
    return data


def _finding_to_json(f: Finding) -> dict:
    return {"rule": f.rule, "file": f.file, "line": f.line, "col": f.col,
            "context": f.context, "message": f.message,
            "line_text": f.line_text}


def _per_file_findings(info: FileInfo) -> List[Finding]:
    from .engine import check_py_file

    return [f for f in check_py_file(info) if not _suppressed(info, f)]


def _per_native_findings(ninfo) -> List[Finding]:
    from .native import check_native_file

    return [f for f in check_native_file(ninfo)
            if not _suppressed(ninfo, f)]


def lint_changed(root: str = REPO,
                 path: Optional[str] = None
                 ) -> Tuple[List[Finding], dict]:
    """Incremental full-accuracy run.  Returns (findings, stats) where
    stats = {"changed": [...], "reused": n}."""
    from . import concurrency, interproc, native

    cpath = path or cache_path(root)
    tools_sha = _tools_fingerprint()
    cache = _load(cpath, tools_sha)
    old_files: Dict[str, dict] = cache["files"]
    new_files: Dict[str, dict] = {}

    relpaths = discover_files(root)
    texts: Dict[str, str] = {}
    changed: List[str] = []
    findings: List[Finding] = []
    parsed_py: List[FileInfo] = []
    aux_infos: List[FileInfo] = []
    summaries: Dict[str, List[callgraph.FuncSummary]] = {}
    conc_map: Dict[str, threadmodel.FileConc] = {}
    native_infos = []

    for rel in relpaths:
        with open(os.path.join(root, rel), encoding="utf-8") as fh:
            text = fh.read()
        texts[rel] = text
        digest = hashlib.sha256(text.encode("utf-8")).hexdigest()
        ent = old_files.get(rel)
        usable = ent is not None and ent.get("sha256") == digest and \
            (rel.endswith(NATIVE_EXTS) or ent.get("conc") is not None)
        if usable:
            findings.extend(Finding(**f) for f in ent["findings"])
            if not rel.endswith(NATIVE_EXTS):
                summaries[rel] = [callgraph.FuncSummary.from_json(s)
                                  for s in ent.get("summaries", [])]
                conc_map[rel] = threadmodel.FileConc.from_json(
                    ent["conc"])
                aux_infos.append(light_info(rel, text))
            else:
                aux_infos.append(native.parse_native(rel, text))
            new_files[rel] = ent
            continue
        changed.append(rel)
        if rel.endswith(NATIVE_EXTS):
            ninfo = native.parse_native(rel, text)
            native_infos.append(ninfo)
            file_findings = _per_native_findings(ninfo)
            entry = {"sha256": digest,
                     "findings": [_finding_to_json(f)
                                  for f in file_findings]}
        else:
            info = _parse_file(rel, text)
            if info is None:
                # unparseable: surface it, never cache silence
                findings.append(Finding(
                    rule="parse-error", file=rel, line=1, col=0,
                    context="<module>",
                    message="file does not parse — fix before linting",
                    line_text=""))
                continue
            parsed_py.append(info)
            file_findings = _per_file_findings(info)
            entry = {"sha256": digest,
                     "findings": [_finding_to_json(f)
                                  for f in file_findings],
                     "summaries": [s.to_json() for s in
                                   callgraph.summarize_file(info)],
                     "conc": threadmodel.summarize_conc(
                         info).to_json()}
        findings.extend(file_findings)
        new_files[rel] = entry

    # global passes, always recomputed (cheap against summaries/regex)
    global_findings: List[Finding] = []
    global_findings.extend(
        interproc.check(parsed_py, summaries, tuple(aux_infos)))
    conc_findings, exonerated = concurrency.check(
        parsed_py, conc_map, tuple(aux_infos))
    global_findings.extend(conc_findings)
    global_findings.extend(native.check_lockstep(texts, root=root))
    global_findings.extend(native.check_srchash(root))

    # the interprocedural held-on-entry proof discharges cached v1
    # lock-unguarded-write findings too — same verdict as a cold run
    findings = [f for f in findings
                if not concurrency.exonerates(f, exonerated)]

    by_path = {i.path: i for i in parsed_py}
    by_path.update({i.path: i for i in native_infos})
    by_path.update({i.path: i for i in aux_infos
                    if i.path not in by_path})
    for f in global_findings:
        info = by_path.get(f.file)
        if info is not None and _suppressed(info, f):
            continue
        findings.append(f)

    cache = {"version": CACHE_VERSION, "tools_sha256": tools_sha,
             "files": new_files}
    tmp = f"{cpath}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(cache, fh)
    os.replace(tmp, cpath)

    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    stats = {"changed": changed,
             "reused": len(relpaths) - len(changed)}
    return findings, stats
