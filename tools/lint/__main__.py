"""CLI: ``python -m tools.lint [--strict] [--json] [paths...]``.

Exit codes: 0 clean (or non-strict), 1 unbaselined findings in
--strict, 2 usage/internal error.  ``--write-baseline`` regenerates
baseline.json from the current findings, preserving justifications of
entries that still match and stamping new ones ``TODO: justify`` —
the PR author must replace every TODO before the gate goes green
(tests/test_detlint.py enforces this).
"""
from __future__ import annotations

import argparse
import json
import sys

from .engine import (
    BASELINE_PATH, REPO, baseline_entry, lint_paths, lint_repo,
    load_baseline, match_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="detlint: consensus-determinism & lock-discipline "
                    "static analyzer")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files to lint (default: the "
                         "whole stellar_core_tpu package)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any unbaselined finding")
    ap.add_argument("--changed", action="store_true",
                    help="incremental run: reuse .detlint-cache.json "
                         "for files whose content hash is unchanged, "
                         "re-analyze the rest, recompute the global "
                         "passes — full-run-identical findings in "
                         "dev-loop time")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file (default: tools/lint/baseline.json)")
    ap.add_argument("--root", default=REPO, help=argparse.SUPPRESS)
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings")
    ap.add_argument("--threads", action="store_true",
                    help="dump the inferred thread model (root "
                         "inventory + per-context function counts) "
                         "instead of linting")
    args = ap.parse_args(argv)

    if args.threads:
        return _dump_threads(args)

    if args.write_baseline and args.paths:
        print("detlint: --write-baseline requires a full-repo run — a "
              "scoped run would truncate the baseline to the given "
              "paths' findings", file=sys.stderr)
        return 2
    if args.changed and args.paths:
        print("detlint: --changed and explicit paths are mutually "
              "exclusive (--changed scopes itself by content hash)",
              file=sys.stderr)
        return 2
    if args.paths:
        try:
            findings = lint_paths(args.paths, args.root)
        except FileNotFoundError as e:
            print(f"detlint: {e}", file=sys.stderr)
            return 2
    elif args.changed:
        from .cache import lint_changed

        findings, stats = lint_changed(args.root)
        if not args.as_json:
            print(f"detlint: --changed re-analyzed "
                  f"{len(stats['changed'])} files, reused "
                  f"{stats['reused']} cached")
    else:
        findings = lint_repo(args.root)
    baseline = load_baseline(args.baseline)
    fresh, pinned, stale = match_baseline(findings, baseline)
    if args.paths:
        # a scoped run cannot see findings outside its paths — staleness
        # is only meaningful against the full repo
        stale = []

    if args.write_baseline:
        old = {(e["rule"], e["file"], e["context"], e["line_text"]):
               e.get("justification", "") for e in baseline}
        entries, seen = [], set()
        for f in findings:
            if f.identity() in seen:
                continue
            seen.add(f.identity())
            entries.append(baseline_entry(
                f, old.get(f.identity()) or "TODO: justify"))
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump({"findings": entries}, fh, indent=1, sort_keys=False)
            fh.write("\n")
        print(f"detlint: wrote {len(entries)} baseline entries to "
              f"{args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps({
            "unbaselined": [f.__dict__ for f in fresh],
            "baselined": [f.__dict__ for f in pinned],
            "stale_baseline_entries": stale,
        }, indent=1))
    else:
        for f in fresh:
            print(f.render())
        for e in stale:
            print(f"detlint: stale baseline entry (finding fixed? remove "
                  f"it): {e['file']} [{e['context']}] {e['rule']}: "
                  f"{e['line_text']!r}")
        print(f"detlint: {len(findings)} findings "
              f"({len(fresh)} unbaselined, {len(pinned)} baselined, "
              f"{len(stale)} stale baseline entries)")

    if args.strict and fresh:
        if not args.as_json:
            print("detlint: STRICT FAILURE — fix the findings above, add "
                  "a '# detlint: allow(<rule>)' pragma with a reason, or "
                  "baseline them with a justification", file=sys.stderr)
        return 1
    return 0


def _dump_threads(args) -> int:
    """The thread-root inventory and runs-on context histogram the
    concurrency rules are judging against (COVERAGE.md documents the
    model; this prints the live one)."""
    from .concurrency import build_model_for
    from .engine import _parse_file, discover_files
    import os

    infos = []
    for rel in discover_files(args.root):
        if not rel.endswith(".py"):
            continue
        with open(os.path.join(args.root, rel), encoding="utf-8") as fh:
            info = _parse_file(rel, fh.read())
        if info is not None:
            infos.append(info)
    m = build_model_for(infos)
    if args.as_json:
        print(json.dumps({
            "roots": m.roots,
            "contexts": {k: sorted(v) for k, v in
                         sorted(m.contexts.items()) if v},
        }, indent=1))
        return 0
    print("thread roots:")
    for r in m.roots:
        status = ", ".join(r["resolved"]) if r["resolved"] \
            else "UNRESOLVED"
        print(f"  {r['file']}:{r['line']}: {r['ctx']} <- "
              f"{r['target']} ({status})")
    hist = {}
    for ctxs in m.contexts.values():
        label = "+".join(sorted(ctxs)) if ctxs else "<unreached>"
        hist[label] = hist.get(label, 0) + 1
    print("runs-on histogram (functions per context set):")
    for label in sorted(hist):
        print(f"  {hist[label]:4d}  {label}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
