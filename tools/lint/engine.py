"""detlint engine: file discovery, AST parse, rule dispatch, pragma
suppression, and baseline matching.

Finding identity is (rule, file, context, line_text) — deliberately NOT
the line number, so a baseline entry survives unrelated edits shifting
code up or down.  ``context`` is the dotted class/function path
(``TallyEngine._build``) or ``<module>`` for top-level code.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PACKAGE = "stellar_core_tpu"
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

# consensus-critical module prefixes (relative to the package root):
# nondeterminism here forks validators (ISSUE 3).  Entries may be
# nested ("simulation/fuzz"): a path is covered when its leading
# components match every component of the entry — the fuzzer's
# schedule IR and executor must stay deterministic (same-seed replay
# identity is the repro contract) without dragging all of simulation/
# into the consensus ruleset.
CONSENSUS_DIRS = ("scp", "herder", "ledger", "bucket", "transactions",
                  "xdr", "crypto", "apply", "catchup", "history", "work",
                  "simulation/fuzz")
# device-kernel modules: host-side effects inside jax.jit break
# trace/replay determinism
KERNEL_DIRS = ("ops",)

_PRAGMA_RE = re.compile(r"#\s*detlint:\s*allow\(([^)]*)\)")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")


def path_under(path: str, dirs: Sequence[str]) -> bool:
    """Is ``path`` (repo-relative, '/'-separated) inside one of the
    package-relative ``dirs``?  Entries may themselves contain slashes
    ("simulation/fuzz") and match a leading component sequence."""
    parts = path.split("/")
    if PACKAGE not in parts:
        return False
    rest = parts[parts.index(PACKAGE) + 1:]
    for d in dirs:
        want = d.split("/")
        if rest[:len(want)] == want:
            return True
    return False


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None (shared by the
    determinism and lock rule modules)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str          # repo-relative path
    line: int
    col: int
    context: str       # dotted class/function path
    message: str
    line_text: str     # stripped source of the flagged line

    def identity(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.file, self.context, self.line_text)

    def render(self) -> str:
        return (f"{self.file}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message} [{self.context}]")


@dataclass
class FileInfo:
    """Parsed per-file input handed to every rule module."""
    path: str                      # repo-relative, '/'-separated
    source: str
    tree: ast.AST
    lines: List[str]
    pragmas: Dict[int, Set[str]] = field(default_factory=dict)
    # line -> lock name for "# guarded-by: <lock>" annotations
    guards: Dict[int, str] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def in_consensus(self) -> bool:
        return self._under(CONSENSUS_DIRS)

    def in_kernels(self) -> bool:
        return self._under(KERNEL_DIRS)

    def _under(self, dirs: Sequence[str]) -> bool:
        return path_under(self.path, dirs)


class ContextVisitor(ast.NodeVisitor):
    """Base visitor tracking the dotted class/function context."""

    def __init__(self, info: FileInfo):
        self.info = info
        self.stack: List[str] = []
        self.findings: List[Finding] = []

    @property
    def context(self) -> str:
        return ".".join(self.stack) if self.stack else "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def add(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        self.findings.append(Finding(
            rule=rule, file=self.info.path, line=line, col=col,
            context=self.context, message=message,
            line_text=self.info.line_text(line)))


def _scan_comments(info: FileInfo) -> None:
    for i, raw in enumerate(info.lines, start=1):
        m = _PRAGMA_RE.search(raw)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            info.pragmas[i] = rules
        g = _GUARDED_BY_RE.search(raw)
        if g:
            info.guards[i] = g.group(1)


def _suppressed(info: FileInfo, f: Finding) -> bool:
    """A pragma suppresses a finding on its own line or the line above
    (for statements whose flagged line has no room for a comment)."""
    for line in (f.line, f.line - 1):
        rules = info.pragmas.get(line)
        if rules and (f.rule in rules or "*" in rules):
            return True
    return False


def _parse_file(relpath: str, source: str) -> Optional[FileInfo]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    info = FileInfo(path=relpath.replace(os.sep, "/"), source=source,
                    tree=tree, lines=source.splitlines())
    _scan_comments(info)
    return info


#: native kernel sources audited by tools/lint/native.py
NATIVE_EXTS = (".cpp", ".c")


def discover_files(root: str = REPO) -> List[str]:
    """Repo-relative paths of every package .py file under analysis,
    plus the native kernel sources (.cpp/.c) the native auditor lexes."""
    out: List[str] = []
    pkg_root = os.path.join(root, PACKAGE)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py") or name.endswith(NATIVE_EXTS):
                full = os.path.join(dirpath, name)
                out.append(os.path.relpath(full, root))
    return sorted(out)


def light_info(relpath: str, source: str) -> FileInfo:
    """A tree-less FileInfo carrying only lines + pragmas — enough for
    line_text rendering and suppression of findings that land in a file
    the current run did not (re)parse (the --changed cache path)."""
    info = FileInfo(path=relpath.replace(os.sep, "/"), source=source,
                    tree=None, lines=source.splitlines())
    _scan_comments(info)
    return info


def _lockstep_involved(paths: Iterable[str]) -> bool:
    """Should a scoped run diff the lockstep manifest?  Yes whenever any
    kernel source or any Python twin named in the manifest is in scope
    (missing counterparts are read from disk)."""
    from .native import MANIFEST_PATH, load_manifest

    try:
        manifest = load_manifest(MANIFEST_PATH)
    except (OSError, ValueError, KeyError, TypeError):
        # unreadable manifest: RUN the pass so check_lockstep reports
        # the broken manifest — never degrade to silence
        return True
    involved = set()
    for e in manifest:
        involved.add(e["cpp"]["file"])
        if e.get("py"):
            involved.add(e["py"]["file"])
    return bool(involved & set(paths))


def check_py_file(info: FileInfo) -> List[Finding]:
    """Every rule family computable from ONE parsed .py file — the
    single dispatch list shared by the cold run (lint_sources) and the
    --changed cache (cache.py), so a new per-file rule module cannot be
    added to one path and silently missed by the other."""
    from . import determinism, locks, safety

    findings = determinism.check(info)
    findings.extend(safety.check(info))
    findings.extend(locks.check([info]))
    return findings


def lint_sources(sources: Dict[str, str],
                 root: Optional[str] = None) -> List[Finding]:
    """Analyze {repo-relative-path: source}; the seam tests use to lint
    injected/mutated source without touching the working tree.  .py
    files run the AST rule families (determinism, safety, locks) plus
    the whole-program interprocedural taint pass; .cpp/.c files run the
    native auditor.  ``root`` (set by lint_paths/lint_repo) additionally
    enables the filesystem-backed srchash sidecar audit."""
    from . import concurrency, interproc, native

    infos: List[FileInfo] = []
    native_infos: List["native.NativeInfo"] = []
    findings: List[Finding] = []
    for relpath, source in sorted(sources.items()):
        if relpath.endswith(NATIVE_EXTS):
            native_infos.append(native.parse_native(relpath, source))
            continue
        info = _parse_file(relpath, source)
        if info is not None:
            infos.append(info)
        else:
            # an unparseable file must go RED, never read as clean —
            # same verdict the --changed path gives (cache.py)
            findings.append(Finding(
                rule="parse-error", file=relpath.replace(os.sep, "/"),
                line=1, col=0, context="<module>",
                message="file does not parse — fix before linting",
                line_text=""))
    for info in infos:
        findings.extend(check_py_file(info))
    findings.extend(interproc.check(infos))
    conc_findings, exonerated = concurrency.check(infos)
    findings.extend(conc_findings)
    findings.extend(native.check(
        native_infos,
        py_sources={i.path: i.source for i in infos},
        root=root,
        run_lockstep=bool(native_infos) or _lockstep_involved(sources)))
    by_path: Dict[str, object] = {i.path: i for i in infos}
    by_path.update({i.path: i for i in native_infos})
    out = []
    for f in findings:
        info = by_path.get(f.file)
        if info is not None and _suppressed(info, f):
            continue
        if concurrency.exonerates(f, exonerated):
            # the thread model proved the lock held on entry from every
            # resolved caller — the v1 lexical miss is discharged
            continue
        out.append(f)
    return sorted(out, key=lambda f: (f.file, f.line, f.col, f.rule))


def lint_paths(relpaths: Iterable[str], root: str = REPO) -> List[Finding]:
    """Lint specific repo-relative files; raises FileNotFoundError on an
    unreadable path — a scoped run must never silently report a file it
    never analyzed as clean."""
    sources: Dict[str, str] = {}
    missing: List[str] = []
    for rel in relpaths:
        full = os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as fh:
                sources[rel] = fh.read()
        except OSError:
            missing.append(rel)
    if missing:
        raise FileNotFoundError(
            f"cannot read: {', '.join(missing)}")
    return lint_sources(sources, root=root)


def lint_repo(root: str = REPO) -> List[Finding]:
    return lint_paths(discover_files(root), root)


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str = BASELINE_PATH) -> List[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except OSError:
        return []
    return data.get("findings", [])


def match_baseline(findings: Sequence[Finding],
                   baseline: Sequence[dict]
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Split into (unbaselined, baselined, stale_entries).

    An entry matches any number of findings with the same
    (rule, file, context, line_text) — several identical metric-timer
    lines in one function are one entry.  Entries matching nothing are
    stale (reported, not fatal: the finding was fixed)."""
    table: Dict[Tuple[str, str, str, str], dict] = {}
    for entry in baseline:
        key = (entry.get("rule", ""), entry.get("file", ""),
               entry.get("context", ""), entry.get("line_text", ""))
        table[key] = entry
    used: Set[Tuple[str, str, str, str]] = set()
    fresh: List[Finding] = []
    pinned: List[Finding] = []
    for f in findings:
        if f.identity() in table:
            pinned.append(f)
            used.add(f.identity())
        else:
            fresh.append(f)
    stale = [e for k, e in table.items() if k not in used]
    return fresh, pinned, stale


def baseline_entry(f: Finding, justification: str) -> dict:
    return {"rule": f.rule, "file": f.file, "context": f.context,
            "line_text": f.line_text, "justification": justification}
