"""Module-level call graph over the package — the substrate for the
interprocedural determinism-taint pass (tools/lint/interproc.py).

Each analyzed file yields one JSON-serializable *file summary*: every
function/method it defines with (a) the call sites the resolver can
bind statically, (b) the nondeterminism SOURCES the function contains
directly, and (c) whether it calls a consensus hash/serialize/tally
sink.  Summaries are deliberately resolution-independent (raw call
descriptors, not resolved keys) so the ``--changed`` cache can reuse an
unchanged file's summary verbatim while the cross-file binding is
recomputed each run against whatever file set is in scope.

Resolution is conservative by design: bare names bind to same-module
functions or from-imports, ``self.m()`` binds within the enclosing
class (then any same-module method), ``alias.f()`` binds through the
import map (absolute and relative imports both).  Attribute calls on
arbitrary objects are dropped — a blind spot documented in COVERAGE.md,
traded for a near-zero false-positive rate.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .engine import PACKAGE, FileInfo, dotted_name as _dotted
from .determinism import (
    _DATETIME_METHODS, _ORDER_INSENSITIVE_CONSUMERS, _SINKS_EXACT,
    _SINKS_SUFFIX, _WALLCLOCK_MODS, _ImportMap, _is_set_expr,
    _mentions_ledger_value, _set_annotation, _shallow_walk, _unwrap_iter,
    is_sanctioned_timing_call,
)

#: modules whose time/env reads are sanctioned by architecture — the
#: virtual clock IS the time source, tracing/metrics/logging feed only
#: observability, the scheduler budgets wall time, device probes are
#: host-local, and main/config.py is the one sanctioned os.environ
#: boundary.  Functions here are never taint sources or carriers.
SANCTIONED_MODULES = frozenset({
    f"{PACKAGE}/utils/clock.py",
    f"{PACKAGE}/utils/tracing.py",
    f"{PACKAGE}/utils/metrics.py",
    f"{PACKAGE}/utils/logging.py",
    f"{PACKAGE}/utils/scheduler.py",
    f"{PACKAGE}/utils/device.py",
    # observation-only telemetry, same standing as tracing.py: the
    # lifecycle tracker's and vitals sampler's wallclock reads live in
    # these files and feed only histograms/gauges, never consensus
    # values (pinned by tests/test_detlint.py)
    f"{PACKAGE}/utils/txtrace.py",
    f"{PACKAGE}/utils/vitals.py",
    f"{PACKAGE}/main/config.py",
})

#: taint stops propagating after this many call edges; chains this deep
#: are beyond what a reviewer can act on and beyond what the
#: name-based resolver stays precise for (documented in COVERAGE.md)
MAX_TAINT_DEPTH = 6

#: pragma rules that sanction a taint source at its own line: the
#: specific v1 rule for that source kind, or the interproc rule itself
_SOURCE_RULE_BY_KIND = {
    "wallclock": "det-wallclock",
    "environ": "det-wallclock",
    "id": "det-interproc-taint",
    "unsorted-iter": "det-unsorted-iter",
    "float-consensus": "det-float-consensus",
}
INTERPROC_RULE = "det-interproc-taint"


def module_of(path: str) -> str:
    """'stellar_core_tpu/scp/tally.py' -> 'stellar_core_tpu.scp.tally'."""
    mod = path[:-3] if path.endswith(".py") else path
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _resolve_relative(path: str, level: int, module: Optional[str]) -> str:
    """Absolute dotted module for a level-N relative import from
    ``path`` (``from ..utils import tracing`` in scp/tally.py ->
    stellar_core_tpu.utils)."""
    pkg_parts = path.split("/")[:-1]  # containing package
    up = level - 1
    if up:
        pkg_parts = pkg_parts[:-up] if up <= len(pkg_parts) else []
    base = ".".join(pkg_parts)
    if module:
        return f"{base}.{module}" if base else module
    return base


@dataclass
class FuncSummary:
    context: str                  # dotted class/method path in the file
    line: int
    calls: List[dict] = field(default_factory=list)
    sources: List[Tuple[str, str, int]] = field(default_factory=list)
    sink: bool = False

    def to_json(self) -> dict:
        return {"context": self.context, "line": self.line,
                "calls": self.calls,
                "sources": [list(s) for s in self.sources],
                "sink": self.sink}

    @classmethod
    def from_json(cls, d: dict) -> "FuncSummary":
        return cls(context=d["context"], line=d["line"],
                   calls=list(d["calls"]),
                   sources=[tuple(s) for s in d["sources"]],
                   sink=bool(d["sink"]))


class _Imports(_ImportMap):
    """The determinism-pass import map plus absolute resolution of
    relative imports (the AST keeps the level separately)."""

    def __init__(self, info: FileInfo):
        super().__init__(info.tree)
        self.module_member: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(info.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level:
                    mod = _resolve_relative(info.path, node.level,
                                            node.module)
                else:
                    mod = node.module or ""
                for a in node.names:
                    local = a.asname or a.name
                    self.module_member[local] = (mod, a.name)


def _source_sanctioned(info: FileInfo, line: int, kind: str) -> bool:
    """A pragma at the source line (or the line above) for the matching
    v1 rule, the interproc rule, or '*' sanctions the source — one
    pragma at the origin kills every derived chain."""
    ok = {_SOURCE_RULE_BY_KIND.get(kind, ""), INTERPROC_RULE, "*"}
    for ln in (line, line - 1):
        rules = info.pragmas.get(ln)
        if rules and rules & ok:
            return True
    return False


class _FuncScanner:
    """Extracts one function's summary (shallow body only — nested defs
    are their own summaries)."""

    def __init__(self, info: FileInfo, imports: _Imports,
                 context: str, cls: Optional[str], node) -> None:
        self.info = info
        self.imports = imports
        self.summary = FuncSummary(context=context, line=node.lineno)
        self.cls = cls
        self.node = node

    def scan(self) -> FuncSummary:
        self._scan_calls_and_sources()
        self._scan_unsorted_iteration()
        return self.summary

    # -- call descriptors ---------------------------------------------------

    def _describe_call(self, call: ast.Call) -> Optional[dict]:
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in self.imports.module_member:
                mod, member = self.imports.module_member[name]
                return {"mod": mod, "name": member, "line": call.lineno}
            return {"name": name, "line": call.lineno}
        if isinstance(func, ast.Attribute):
            base = _dotted(func.value)
            if base == "self":
                return {"name": func.attr, "self": self.cls or "",
                        "line": call.lineno}
            if base is None:
                return None
            # alias.f(): plain `import x.y as alias` or a module bound
            # by `from pkg import module`
            mod = self.imports.mod_alias.get(base)
            if mod is None and base in self.imports.module_member:
                pmod, member = self.imports.module_member[base]
                mod = f"{pmod}.{member}" if pmod else member
            if mod is not None:
                return {"mod": mod, "name": func.attr, "line": call.lineno}
            return None  # unbound object attribute: dropped (blind spot)
        return None

    def _scan_calls_and_sources(self) -> None:
        s = self.summary
        sanctioned_file = self.info.path in SANCTIONED_MODULES
        for node in _shallow_walk(self.node):
            if isinstance(node, ast.Call):
                target = self.imports.resolve_call(node.func)
                if not sanctioned_file:
                    kindet = self._call_source_kind(node, target)
                    if kindet is not None:
                        kind, detail = kindet
                        if not _source_sanctioned(self.info, node.lineno,
                                                  kind):
                            s.sources.append((kind, detail, node.lineno))
                self._note_sink(node)
                d = self._describe_call(node)
                if d is not None:
                    s.calls.append(d)
            elif isinstance(node, ast.Attribute) and node.attr == "environ":
                base = _dotted(node.value)
                if base is not None and not sanctioned_file and \
                        self.imports.mod_alias.get(base, base) == "os":
                    if not _source_sanctioned(self.info, node.lineno,
                                              "environ"):
                        s.sources.append(("environ", "os.environ",
                                          node.lineno))
            elif isinstance(node, ast.BinOp) and \
                    isinstance(node.op, ast.Div) and not sanctioned_file:
                if (_mentions_ledger_value(node.left)
                        or _mentions_ledger_value(node.right)):
                    if not _source_sanctioned(self.info, node.lineno,
                                              "float-consensus"):
                        s.sources.append((
                            "float-consensus",
                            "float division on a ledger value",
                            node.lineno))

    def _call_source_kind(self, node: ast.Call,
                          target: Optional[str]) -> Optional[tuple]:
        if isinstance(node.func, ast.Name) and node.func.id == "id" \
                and node.args:
            return ("id", "id()")
        if not target or "." not in target:
            return None
        if is_sanctioned_timing_call(target):
            return None
        mod, _, attr = target.rpartition(".")
        if mod in ("datetime.datetime", "datetime.date", "datetime") and \
                attr in _DATETIME_METHODS:
            return ("wallclock", f"{target}()")
        banned = _WALLCLOCK_MODS.get(mod)
        if banned and attr in banned:
            kind = "environ" if mod == "os" else "wallclock"
            return (kind, f"{target}()")
        return None

    def _note_sink(self, call: ast.Call) -> None:
        name = None
        if isinstance(call.func, ast.Attribute):
            name = call.func.attr
        elif isinstance(call.func, ast.Name):
            name = call.func.id
        if name is None:
            return
        if name in _SINKS_EXACT or \
                any(name.lower().endswith(sfx) for sfx in _SINKS_SUFFIX):
            self.summary.sink = True

    # -- order-carrying unsorted iteration ----------------------------------

    def _scan_unsorted_iteration(self) -> None:
        """A function taints its callers through iteration order only
        when it BUILDS an order-carrying value from an unsorted dict
        view / set: a list-comp/genexp over one, a yield inside such a
        loop, or .append/.extend in its body.  Plain counting loops and
        order-insensitive consumers (sorted/sum/set/...) are exempt —
        same exemptions as the v1 intra-function rule."""
        if self.info.path in SANCTIONED_MODULES:
            return
        known_sets = self._set_names()
        exempt: Set[int] = set()
        for node in _shallow_walk(self.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in _ORDER_INSENSITIVE_CONSUMERS:
                for a in node.args:
                    if isinstance(a, (ast.ListComp, ast.GeneratorExp)):
                        exempt.add(id(a))
        for node in _shallow_walk(self.node):
            if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
                if id(node) in exempt:
                    continue
                for gen in node.generators:
                    d = self._unsorted_detail(gen.iter, known_sets)
                    if d is not None:
                        self._add_iter_source(d, node.lineno)
            elif isinstance(node, ast.For):
                d = self._unsorted_detail(node.iter, known_sets)
                if d is None:
                    continue
                if self._loop_carries_order(node):
                    self._add_iter_source(d, node.lineno)

    def _add_iter_source(self, detail: str, line: int) -> None:
        if not _source_sanctioned(self.info, line, "unsorted-iter"):
            self.summary.sources.append(("unsorted-iter", detail, line))

    def _set_names(self) -> Set[str]:
        names: Set[str] = set()
        for node in _shallow_walk(self.node):
            if isinstance(node, ast.Assign) and _is_set_expr(node.value):
                for t in node.targets:
                    d = _dotted(t)
                    if d is not None:
                        names.add(d)
            elif isinstance(node, ast.AnnAssign) and (
                    _set_annotation(node.annotation)
                    or (node.value is not None
                        and _is_set_expr(node.value))):
                d = _dotted(node.target)
                if d is not None:
                    names.add(d)
        for arg in getattr(self.node.args, "args", []):
            if _set_annotation(arg.annotation):
                names.add(arg.arg)
        return names

    def _unsorted_detail(self, it: ast.AST,
                         known_sets: Set[str]) -> Optional[str]:
        it = _unwrap_iter(it)
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "sorted":
            return None
        if isinstance(it, ast.Call) and \
                isinstance(it.func, ast.Attribute) and \
                it.func.attr in ("items", "values", "keys") and not it.args:
            return f"unsorted .{it.func.attr}() iteration"
        d = _dotted(it)
        if d is not None and d in known_sets:
            return f"unsorted set '{d}' iteration"
        return None

    @staticmethod
    def _loop_carries_order(loop: ast.For) -> bool:
        for node in ast.walk(loop):
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                return True
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("append", "extend", "insert",
                                       "appendleft"):
                return True
        return False


class _FileScanner(ast.NodeVisitor):
    def __init__(self, info: FileInfo):
        self.info = info
        self.imports = _Imports(info)
        self.stack: List[str] = []
        self.cls_stack: List[str] = []
        self.functions: List[FuncSummary] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        context = ".".join(self.stack)
        cls = self.cls_stack[-1] if self.cls_stack else None
        self.functions.append(
            _FuncScanner(self.info, self.imports, context, cls,
                         node).scan())
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def summarize_file(info: FileInfo) -> List[FuncSummary]:
    """All function summaries of one parsed file."""
    scanner = _FileScanner(info)
    scanner.visit(info.tree)
    return scanner.functions


# ---------------------------------------------------------------------------
# graph binding (recomputed every run over whichever summaries exist)
# ---------------------------------------------------------------------------

@dataclass
class Graph:
    # key = f"{path}::{context}"
    funcs: Dict[str, FuncSummary] = field(default_factory=dict)
    path_of: Dict[str, str] = field(default_factory=dict)
    # resolved call edges: key -> [(callee_key, line), ...]
    edges: Dict[str, List[Tuple[str, int]]] = field(default_factory=dict)


def _index_functions(summaries: Dict[str, List[FuncSummary]]):
    """(path, bare) -> key for module-level defs; (path, cls, meth) and
    (path, meth) for methods."""
    module_level: Dict[Tuple[str, str], str] = {}
    methods: Dict[Tuple[str, str, str], str] = {}
    any_method: Dict[Tuple[str, str], List[str]] = {}
    for path, funcs in summaries.items():
        for f in funcs:
            key = f"{path}::{f.context}"
            parts = f.context.split(".")
            if len(parts) == 1:
                module_level[(path, parts[0])] = key
            else:
                methods[(path, parts[-2], parts[-1])] = key
                any_method.setdefault((path, parts[-1]), []).append(key)
    return module_level, methods, any_method


def build(summaries: Dict[str, List[FuncSummary]]) -> Graph:
    g = Graph()
    module_files = {module_of(p): p for p in summaries}
    module_level, methods, any_method = _index_functions(summaries)
    for path, funcs in summaries.items():
        for f in funcs:
            key = f"{path}::{f.context}"
            g.funcs[key] = f
            g.path_of[key] = path
            out: List[Tuple[str, int]] = []
            for call in f.calls:
                for callee in _bind(call, path, module_files,
                                    module_level, methods, any_method):
                    out.append((callee, call["line"]))
            g.edges[key] = out
    return g


def _bind(call: dict, path: str, module_files, module_level, methods,
          any_method) -> List[str]:
    name = call["name"]
    if "mod" in call:
        target = module_files.get(call["mod"])
        if target is None:
            # either `name` is a module object (from pkg import module —
            # modules are not callables we track) or the module is
            # outside the analyzed set: unbound either way
            return []
        key = module_level.get((target, name))
        return [key] if key else []
    if "self" in call:
        key = methods.get((path, call["self"], name))
        if key:
            return [key]
        return any_method.get((path, name), [])
    key = module_level.get((path, name))
    return [key] if key else []
