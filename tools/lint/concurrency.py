"""detlint v3 concurrency rules (family c) — whole-program analyses
over the thread-context model (tools/lint/threadmodel.py).

Rules
-----
conc-unguarded-shared   a ``self.``/module attribute written from >= 2
                        inferred thread contexts without a
                        ``# guarded-by:`` annotation.  One finding per
                        writing function (anchored at its first write)
                        so a pragma sits next to the code it excuses.
                        ``__init__``/module-level writes are exempt
                        (construction happens-before sharing), as are
                        fields of classes whose ``class`` line carries
                        a ``# detlint: allow(conc-unguarded-shared)``
                        pragma — the instance-confinement marker for
                        per-task payload objects (each instance touched
                        by one thread at a time, hand-off via queue/
                        future happens-before).
conc-thread-affine-call a thread-affine API (raw sqlite connection,
                        ``db.cursor()`` escape hatch, LedgerTxnRoot
                        non-overlay mutation, JAX device calls) reached
                        from a context outside the API's owner set.
conc-lock-cycle         a cycle in the cross-file lock-order graph:
                        lock identities are package-qualified
                        (``path::Class.attr``) through the declaration
                        map, acquisition edges are collected both
                        lexically (with-stack) and interprocedurally
                        (call under held lock -> callee's transitive
                        acquisitions), and each cycle is reported once
                        with the full acquisition chain.  Two-lock
                        same-file lexical inversions stay with the v1
                        ``lock-order`` rule.

This module also EXONERATES v1 ``lock-unguarded-write`` findings whose
function provably holds the declared lock on entry from every resolved
caller — the interprocedural upgrade of the lexical discipline: callees
of ``ClosePipeline.submit_tail`` no longer need a redundant ``with``.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import re

from .engine import FileInfo, Finding
from . import threadmodel
from .threadmodel import ANY, MAIN, FileConc, Model

RULE_SHARED = "conc-unguarded-shared"
RULE_AFFINE = "conc-thread-affine-call"
RULE_CYCLE = "conc-lock-cycle"

#: owner contexts per thread-affine API.  The close tail worker is a
#: first-class owner of the commit-path APIs: in pipelined mode the
#: tail IS the ledger-state writer (ISSUE 13).
AFFINE_OWNERS: Dict[str, Set[str]] = {
    # raw sqlite3.Connection use: only Database's own serialization
    # boundary (main + close tail via Database.execute's _write_lock)
    "sqlite-conn": {MAIN, "worker:close-tail"},
    # db.cursor() escape hatch: commit paths only
    "sqlite-cursor": {MAIN, "worker:close-tail"},
    # LedgerTxnRoot non-overlay mutation
    "ltxroot-mutate": {MAIN, "worker:close-tail"},
    # JAX device dispatch: crank thread + the quorum bridge thread that
    # exists precisely to move device work off the crank
    "jax-device": {MAIN, "thread:_bridge"},
}

_V1_UNGUARDED_RE = re.compile(
    r"write to '([^']+)' \(guarded-by: ([^)]+)\)")


def _fc_guard(fc: FileConc, owner: str, fieldname: str) -> Optional[str]:
    """The declared guard lock for a field, class-qualified first."""
    if owner and owner != "<module>":
        hit = fc.guards.get(f"{owner}.{fieldname}")
        if hit is not None:
            return hit[0]
    hit = fc.guards.get(fieldname)
    return hit[0] if hit is not None else None


def _class_confined(info: Optional[FileInfo], fc: FileConc,
                    owner: str) -> bool:
    """Class-level confinement pragma on the ``class`` line (or the
    line above): every field of the class is instance-confined."""
    if info is None or owner in ("", "<module>"):
        return False
    line = fc.classes.get(owner)
    if line is None:
        return False
    for ln in (line, line - 1):
        rules = info.pragmas.get(ln)
        if rules and (RULE_SHARED in rules or "*" in rules):
            return True
    return False


def _fmt_ctxs(ctxs: Iterable[str]) -> str:
    return "{" + ", ".join(sorted(ctxs)) + "}"


def _short_lock(qlock: str) -> str:
    """'stellar_core_tpu/bucket/bucket_list.py::BucketManager._gc_lock'
    -> 'bucket_list.py::BucketManager._gc_lock' (message brevity)."""
    path, _, name = qlock.partition("::")
    return f"{path.rpartition('/')[2]}::{name}"


# ---------------------------------------------------------------------------
# rule 1: conc-unguarded-shared
# ---------------------------------------------------------------------------

def _check_shared(m: Model, by_path: Dict[str, FileInfo]
                  ) -> List[Finding]:
    # (path, owner, field) -> [(func key, first write line)]
    writers: Dict[Tuple[str, str, str], List[Tuple[str, int]]] = {}
    for key in sorted(m.funcs):
        f = m.funcs[key]
        path = m.path_of[key]
        first: Dict[Tuple[str, str, str], int] = {}
        for w in f.writes:
            fid = (path, w["owner"], w["field"])
            if fid not in first or w["line"] < first[fid]:
                first[fid] = w["line"]
        for fid, line in sorted(first.items()):
            writers.setdefault(fid, []).append((key, line))

    findings: List[Finding] = []
    for fid in sorted(writers):
        path, owner, fieldname = fid
        if "lock" in fieldname.lower() or "mutex" in fieldname.lower():
            continue  # the locks themselves are not guarded data
        fc = m.conc[path]
        if _fc_guard(fc, owner, fieldname) is not None:
            continue  # annotated: the with-lock rules own discipline
        if _class_confined(by_path.get(path), fc, owner):
            continue
        union: Set[str] = set()
        for key, _line in writers[fid]:
            union |= m.contexts.get(key, set())
        multi = len(union - {ANY}) >= 2 or ANY in union
        if not multi:
            continue
        where = owner if owner != "<module>" else "module"
        for key, line in writers[fid]:
            f = m.funcs[key]
            info = by_path.get(path)
            findings.append(Finding(
                rule=RULE_SHARED, file=path, line=line, col=0,
                context=f.context,
                message=(f"'{where}.{fieldname}' is written from "
                         f"thread contexts {_fmt_ctxs(union)} with no "
                         f"'# guarded-by:' annotation"),
                line_text=info.line_text(line) if info else ""))
    return findings


# ---------------------------------------------------------------------------
# rule 2: conc-thread-affine-call
# ---------------------------------------------------------------------------

def _check_affine(m: Model, by_path: Dict[str, FileInfo]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    for key in sorted(m.funcs):
        f = m.funcs[key]
        if not f.affine:
            continue
        path = m.path_of[key]
        ctxs = m.contexts.get(key, set())
        for site in f.affine:
            owners = AFFINE_OWNERS.get(site["api"], set())
            bad = ctxs - owners
            if ANY in ctxs and ANY not in owners:
                bad |= {ANY}
            if not bad:
                continue
            info = by_path.get(path)
            findings.append(Finding(
                rule=RULE_AFFINE, file=path, line=site["line"], col=0,
                context=f.context,
                message=(f"thread-affine API '{site['api']}' (owners "
                         f"{_fmt_ctxs(owners)}) reached from "
                         f"{_fmt_ctxs(bad)}"),
                line_text=(info.line_text(site["line"])
                           if info else "")))
    return findings


# ---------------------------------------------------------------------------
# rule 3: conc-lock-cycle
# ---------------------------------------------------------------------------

def _lock_edges(m: Model) -> Dict[Tuple[str, str], tuple]:
    """(outer qlock, inner qlock) -> first witness
    (file, line, kind, description); deterministic first-wins order."""
    edges: Dict[Tuple[str, str], tuple] = {}
    for key in sorted(m.funcs):
        f = m.funcs[key]
        path = m.path_of[key]
        for a in f.acquires:
            inner = m.qualify_lock(a["lock"], path, f.cls)
            for tok in a["held"]:
                outer = m.qualify_lock(tok, path, f.cls)
                if outer == inner:
                    continue  # RLock re-entry
                edges.setdefault(
                    (outer, inner),
                    (path, a["line"], "lexical",
                     f"{f.context} acquires {_short_lock(inner)} "
                     f"while holding {_short_lock(outer)}"))
    for key in sorted(m.edges):
        f = m.funcs[key]
        path = m.path_of[key]
        for callee, line, held in m.edges[key]:
            if not held:
                continue
            for inner, wit in sorted(m.acq_trans.get(callee, {})
                                     .items()):
                chain = " -> ".join(wit[2])
                for outer in sorted(held):
                    if outer == inner:
                        continue
                    edges.setdefault(
                        (outer, inner),
                        (path, line, "interproc",
                         f"{f.context} holds {_short_lock(outer)} and "
                         f"calls {chain} which acquires "
                         f"{_short_lock(inner)} at {wit[0]}:{wit[1]}"))
    return edges


def _sccs(nodes: Sequence[str],
          succ: Dict[str, List[str]]) -> List[List[str]]:
    """Tarjan SCCs, iterative, deterministic order."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(succ.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(sorted(comp))
    return out


def _cycle_path(comp: List[str],
                succ: Dict[str, List[str]]) -> List[str]:
    """One concrete cycle inside an SCC: walk smallest successors from
    the smallest node until revisit."""
    inside = set(comp)
    start = comp[0]
    path = [start]
    seen = {start}
    cur = start
    while True:
        nxt = None
        for w in succ.get(cur, ()):
            if w in inside and w == start and len(path) > 1:
                return path
            if w in inside and w not in seen:
                nxt = w
                break
        if nxt is None:
            # fall back: close on the first in-SCC successor
            for w in succ.get(cur, ()):
                if w in inside:
                    return path
            return path
        path.append(nxt)
        seen.add(nxt)
        cur = nxt


def _check_cycles(m: Model, by_path: Dict[str, FileInfo]
                  ) -> List[Finding]:
    edges = _lock_edges(m)
    succ: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for (a, b) in edges:
        succ.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    for v in succ.values():
        v.sort()

    findings: List[Finding] = []
    for comp in _sccs(sorted(nodes), succ):
        if len(comp) < 2:
            continue
        cycle = _cycle_path(comp, succ)
        cyc_edges = [(cycle[i], cycle[(i + 1) % len(cycle)])
                     for i in range(len(cycle))]
        wits = [edges[e] for e in cyc_edges if e in edges]
        if len(cycle) == 2 and all(w[2] == "lexical" for w in wits) \
                and len({w[0] for w in wits}) == 1:
            continue  # v1 lock-order owns same-file lexical ABBA
        wits_sorted = sorted(wits, key=lambda w: (w[0], w[1]))
        path, line = wits_sorted[0][0], wits_sorted[0][1]
        chain = "; ".join(
            f"{w[3]} ({w[0]}:{w[1]})" for w in wits)
        ring = " -> ".join(_short_lock(x) for x in cycle + [cycle[0]])
        info = by_path.get(path)
        findings.append(Finding(
            rule=RULE_CYCLE, file=path, line=line, col=0,
            context="<module>",
            message=f"lock-order cycle {ring}: {chain}",
            line_text=info.line_text(line) if info else ""))
    return findings


# ---------------------------------------------------------------------------
# v1 exoneration: interprocedural with-lock discipline
# ---------------------------------------------------------------------------

def _exonerated_ids(m: Model) -> Set[Tuple[str, str, str]]:
    """(file, context, field) triples whose v1 lock-unguarded-write
    findings are discharged: the function holds the field's declared
    lock on entry from EVERY resolved caller (and has at least one —
    a caller-less function proves nothing)."""
    out: Set[Tuple[str, str, str]] = set()
    for key in sorted(m.funcs):
        if not m.rev.get(key) or key in m.root_targets:
            continue
        held = m.held_entry.get(key)
        if not held:
            continue
        f = m.funcs[key]
        path = m.path_of[key]
        fc = m.conc[path]
        fields: Set[str] = set()
        for w in f.writes:
            lock = _fc_guard(fc, w["owner"], w["field"])
            if lock is None:
                continue
            q = m.qualify_lock(lock, path, f.cls)
            bare = f"{path}::{lock}"
            if q in held or bare in held:
                fields.add(w["field"])
        for fieldname in fields:
            out.add((path, f.context, fieldname))
    return out


def exonerates(finding: Finding,
               exonerated: Set[Tuple[str, str, str]]) -> bool:
    """Should this v1 lock-unguarded-write finding be discharged by the
    interprocedural held-on-entry proof?"""
    if finding.rule != "lock-unguarded-write":
        return False
    mobj = _V1_UNGUARDED_RE.search(finding.message)
    if mobj is None:
        return False
    return (finding.file, finding.context, mobj.group(1)) in exonerated


# ---------------------------------------------------------------------------
# entry point (mirrors interproc.check)
# ---------------------------------------------------------------------------

def check(infos: Sequence[FileInfo],
          conc: Optional[Dict[str, FileConc]] = None,
          aux_infos: Sequence[FileInfo] = ()
          ) -> Tuple[List[Finding], Set[Tuple[str, str, str]]]:
    """Run the three concurrency rules over parsed files plus any
    cached summaries; returns (findings, exonerated-v1-identities).

    ``conc`` maps repo-relative path -> FileConc for files whose
    summaries were restored from the --changed cache; freshly parsed
    ``infos`` are summarized here and take precedence.  ``aux_infos``
    carry lines/pragmas for cache-hit files so findings landing there
    render line_text and honor pragmas.
    """
    merged: Dict[str, FileConc] = dict(conc or {})
    for info in infos:
        if info.tree is not None:
            merged[info.path] = threadmodel.summarize_conc(info)
    if not merged:
        return [], set()
    m = threadmodel.build_model(merged)
    by_path: Dict[str, FileInfo] = {i.path: i for i in aux_infos}
    by_path.update({i.path: i for i in infos})
    findings: List[Finding] = []
    findings.extend(_check_shared(m, by_path))
    findings.extend(_check_affine(m, by_path))
    findings.extend(_check_cycles(m, by_path))
    return findings, _exonerated_ids(m)


def build_model_for(infos: Sequence[FileInfo]) -> Model:
    """The thread model alone (the --threads CLI dump)."""
    merged = {i.path: threadmodel.summarize_conc(i)
              for i in infos if i.tree is not None}
    return threadmodel.build_model(merged)
