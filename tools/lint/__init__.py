"""detlint v2: consensus-determinism, lock-discipline, interprocedural
taint & native-kernel lockstep static analyzer.

The reproduction's value proposition is that the TPU/native hot paths
stay bit-identical to the CPU reference — detlint is the mechanical
guard that keeps PRs from quietly breaking that.  Five rule families:

* determinism rules (determinism.py) over the consensus-critical
  modules: wall-clock/random/env reads, unsorted dict-view/set
  iteration feeding hashes/serialization/tallies, float arithmetic on
  ledger values, host-side effects inside jax.jit kernels;
* lock-discipline rules (locks.py) for the threaded subsystems:
  ``# guarded-by: <lock>`` annotated fields mutated outside a
  ``with <lock>:`` scope, and inconsistent lock-acquisition order;
* interprocedural determinism taint (callgraph.py + interproc.py):
  nondeterministic values (time/RNG/env/``id()``/unsorted iteration/
  float ledger math) propagated through up to MAX_TAINT_DEPTH call
  edges — across modules, including non-consensus helpers — into
  consensus hash/serialize/tally scopes, reported with the full
  source->sink call chain;
* native-kernel auditor (native.py + lockstep.json): C++/Python
  protocol-constant lockstep diffed against an explicit manifest,
  CPython API calls inside ``Py_BEGIN/END_ALLOW_THREADS`` regions,
  unchecked Py-allocator NULLs, and ``.srchash`` sidecar currency for
  every committed kernel ``.so``;
* exception-safety & resource rules (safety.py): silently-swallowing
  broad excepts in consensus scope, non-context-managed fd/mmap opens
  in ``bucket/``, mutable default arguments in consensus functions.

Pre-existing intentional findings live in tools/lint/baseline.json
(one-line justification each; EMPTY and pinned at zero since r09);
point cases carry an inline ``# detlint: allow(<rule>)`` pragma
(``// detlint: allow(<rule>)`` in C/C++).  ``python -m tools.lint
--strict`` exits nonzero on any unbaselined finding and is wired into
tools/verify_green.py ahead of pytest (``--lint-only`` for the fast
CI-style gate), plus tests/test_detlint.py as a tier-1 test — the gate
self-enforces on every PR.  ``python -m tools.lint --changed`` is the
<1s dev loop: a content-hash cache (.detlint-cache.json) replays
per-file results for untouched files and recomputes the global passes,
bit-identical to a cold full run.
"""
from .engine import (  # noqa: F401
    Finding, lint_paths, lint_repo, lint_sources, load_baseline,
    match_baseline,
)
