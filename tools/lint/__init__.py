"""detlint: consensus-determinism & lock-discipline static analyzer.

The reproduction's value proposition is that the TPU hot path stays
bit-identical to the CPU reference — detlint is the mechanical guard
that keeps PRs from quietly breaking that.  Two rule families:

* determinism rules (tools/lint/determinism.py) over the
  consensus-critical modules: wall-clock/random/env reads, unsorted
  dict-view/set iteration feeding hashes/serialization/tallies, float
  arithmetic on ledger values, host-side effects inside jax.jit kernels;
* lock-discipline rules (tools/lint/locks.py) for the threaded
  subsystems: ``# guarded-by: <lock>`` annotated fields mutated outside
  a ``with <lock>:`` scope, and inconsistent lock-acquisition order.

Pre-existing intentional findings live in tools/lint/baseline.json
(one-line justification each); point cases carry an inline
``# detlint: allow(<rule>)`` pragma.  ``python -m tools.lint --strict``
exits nonzero on any unbaselined finding and is wired into
tools/verify_green.py ahead of pytest, plus tests/test_detlint.py as a
tier-1 test — the gate self-enforces on every PR.
"""
from .engine import (  # noqa: F401
    Finding, lint_paths, lint_repo, lint_sources, load_baseline,
    match_baseline,
)
