#!/usr/bin/env python
"""The snapshot-must-be-green gate (VERDICT r5; ISSUE r7 satellite): run
the detlint static analyzer in --strict mode, then the tier-1 command
EXACTLY as ROADMAP.md states it, and exit nonzero on any unbaselined
lint finding, test failure OR collection error.

Lint findings are reported DISTINCTLY from test failures (separate
"verify_green: LINT RED" line) so a red gate immediately says which
discipline broke.  The tier-1 command is parsed out of ROADMAP.md
(single source of truth: the driver, the builder and this gate all run
the same line).  pytest's exit code already covers failures; collection
errors are additionally grepped out of the log because
`--continue-on-collection-errors` can leave a "green-looking" run that
silently skipped whole files.

Usage: python tools/verify_green.py            -> exit 0 iff green
       python tools/verify_green.py --timings  -> also print the 10
           slowest tier-1 test FILES (aggregated from pytest's own
           --durations accounting)
"""
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tier1_command() -> str:
    text = open(os.path.join(REPO, "ROADMAP.md")).read()
    m = re.search(r"\*\*Tier-1 verify:\*\* `(.+?)`", text, re.S)
    if not m:
        print("verify_green: no tier-1 command found in ROADMAP.md",
              file=sys.stderr)
        sys.exit(2)
    return m.group(1)


def run_detlint() -> int:
    """python -m tools.lint --strict; nonzero = unbaselined findings."""
    print("verify_green: python -m tools.lint --strict", flush=True)
    proc = subprocess.run([sys.executable, "-m", "tools.lint", "--strict"],
                          cwd=REPO)
    return proc.returncode


def print_timings(log: str, top_n: int = 10) -> None:
    """Aggregate pytest's --durations lines (``0.42s call path::test``)
    per test FILE and print the slowest."""
    totals = {}
    for m in re.finditer(
            r"^\s*([0-9.]+)s\s+(?:call|setup|teardown)\s+([^:\s]+)::",
            log, re.M):
        totals[m.group(2)] = totals.get(m.group(2), 0.0) + \
            float(m.group(1))
    if not totals:
        print("verify_green: no duration lines found in the tier-1 log",
              flush=True)
        return
    print(f"verify_green: {top_n} slowest test files:", flush=True)
    width = max(len(f) for f in totals)
    for f, s in sorted(totals.items(), key=lambda kv: -kv[1])[:top_n]:
        print(f"  {f:<{width}}  {s:8.2f}s", flush=True)


def main() -> int:
    timings = "--timings" in sys.argv
    lint_rc = run_detlint()
    if lint_rc != 0:
        # distinct from test failures: the analyzer itself printed the
        # findings; still run the tests so one gate run reports both
        print(f"verify_green: LINT RED (detlint --strict exited "
              f"{lint_rc})", flush=True)
    cmd = tier1_command()
    if timings:
        # same tier-1 line, plus pytest's own per-test durations (all of
        # them: --durations=0) so the slow tail is attributable by file
        cmd = cmd.replace("-m pytest", "-m pytest --durations=0 -vv", 1)
    print(f"verify_green: {cmd}", flush=True)
    proc = subprocess.run(["bash", "-c", cmd], cwd=REPO)
    rc = proc.returncode
    problems = []
    if rc != 0:
        problems.append(f"tier-1 command exited {rc}")
    try:
        with open("/tmp/_t1.log", errors="replace") as f:
            log = f.read()
    except OSError:
        problems.append("tier-1 log /tmp/_t1.log missing")
        log = ""
    # the summary line: "N passed", "N failed", "N errors" — failures
    # and errors both break the gate even if the shell rc lied
    tail = "\n".join(log.splitlines()[-30:])
    for pat, what in ((r"\b([1-9]\d*) failed\b", "failed tests"),
                      (r"\b([1-9]\d*) errors?\b", "collection errors")):
        m = re.search(pat, tail)
        if m:
            problems.append(f"{m.group(1)} {what}")
    if re.search(r"^=+ ERRORS =+$", log, re.M):
        problems.append("ERRORS section in pytest output")
    m = re.search(r"\b(\d+) passed\b", tail)
    passed = m.group(1) if m else "?"
    if timings:
        print_timings(log)
    if lint_rc != 0:
        problems.append("unbaselined detlint findings (see LINT RED "
                        "above)")
    if problems:
        print(f"verify_green: RED ({'; '.join(problems)}); "
              f"passed={passed}", flush=True)
        return 1
    print(f"verify_green: GREEN (passed={passed}, detlint clean)",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
