#!/usr/bin/env python
"""The snapshot-must-be-green gate (VERDICT r5; ISSUE r7 satellite): run
the detlint static analyzer in --strict mode, then the tier-1 command
EXACTLY as ROADMAP.md states it, and exit nonzero on any unbaselined
lint finding, test failure OR collection error.

Lint findings are reported DISTINCTLY from test failures (separate
"verify_green: LINT RED" line) so a red gate immediately says which
discipline broke.  The tier-1 command is parsed out of ROADMAP.md
(single source of truth: the driver, the builder and this gate all run
the same line).  pytest's exit code already covers failures; collection
errors are additionally grepped out of the log because
`--continue-on-collection-errors` can leave a "green-looking" run that
silently skipped whole files.

After the default pass, a PARALLEL-APPLY SMOKE re-runs the tier-1 line
with ``PARALLEL_APPLY_WORKERS=2`` exported (flipping every test
Application onto the apply/ planner+executor path) and reports the
aborts observed across the suite (aggregated from the per-Application
stats lines written via ``PARALLEL_APPLY_STATS_FILE``).  Bit-identity
means the same suite must stay green either way.

Usage: python tools/verify_green.py            -> exit 0 iff green
       python tools/verify_green.py --timings  -> also print the 10
           slowest tier-1 test FILES (aggregated from pytest's own
           --durations accounting)
       python tools/verify_green.py --lint-only -> CI-style fast gate:
           ONLY detlint v2 --strict (determinism + interprocedural
           taint with source->sink chains + native-kernel auditor +
           safety rules), no pytest; exit code is the lint verdict.
       --skip-parallel-smoke / --parallel-smoke-only control the second
           pass; --skip-chaos-smoke skips the chaos scenario smoke (one
           core-4 partition+heal run incl. the same-seed determinism
           rerun, via tools/chaos_bench.py); --skip-pipeline-smoke
           skips the PIPELINED_CLOSE=1 tier-1 rerun + the on/off
           hash/meta parity mini-bench (tools/pipeline_bench.py);
           --skip-soak-smoke skips the ~30 s sustained-load soak
           (tools/soak_bench.py --smoke: vitals ring populated, memory
           slope under the SLO ceiling, zero breaches, telemetry
           disabled-cost <1% and on/off hash parity);
           --skip-credit-smoke skips the kernel-complete credit gate
           (tools/parallel_apply_bench.py --credit-smoke: credit-mix +
           path-payment closes bit-identical native-vs-Python AND
           native cluster-hit rate >= 0.9 — declines on those mixes
           are bugs now); --skip-fee-smoke skips the batched fee-phase
           gate (tools/parallel_apply_bench.py --fee-smoke: NATIVE_FEE
           on/off closes bit-identical AND the charge_fees batch
           carries >= 90% of closes on the mixed workload);
           --skip-catchup-smoke skips the cold-join catchup gate
           (tools/catchup_bench.py --smoke: a cold node joins a live
           core-2 net mid-traffic, catches up via verified bucket
           apply AND full replay, both ending bit-identical to the
           validators); --skip-lockdep-smoke skips the runtime
           lockdep-witness gate; --skip-netobs-smoke skips the
           network-observatory gate (tools/chaos_bench.py --netobs
           --tier core4: hop records nonzero, coverage percentiles
           present, crank attribution >= 90%, tracing overhead < 2%,
           on/off hash+meta inertness); --skip-fuzz-smoke skips the
           fault-schedule-fuzzer gate (tools/fuzz_bench.py --smoke:
           budget-capped seeded schedules on core-4 + one tiered net
           under the full oracle stack, plus the known-bad ->
           ddmin-minimize -> replay-identical proof).
       python tools/verify_green.py --netobs-smoke -> ONLY the
           network-observatory gate above.
       python tools/verify_green.py --fuzz-smoke -> ONLY the
           fault-schedule-fuzzer gate above.
       python tools/verify_green.py --lockdep-smoke -> ONLY the
           runtime witness gate: the threaded-subsystem tier-1 subset,
           one core-4 chaos scenario and one pipelined-close bench
           iteration all under LOCKDEP=1 (every registered lock
           order-witnessed, every # guarded-by: write assert-held
           checked), zero LockOrderInversion/GuardViolation required,
           plus the <1%-of-close-p50 witness-overhead micro-gate.
"""
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tier1_command() -> str:
    text = open(os.path.join(REPO, "ROADMAP.md")).read()
    m = re.search(r"\*\*Tier-1 verify:\*\* `(.+?)`", text, re.S)
    if not m:
        print("verify_green: no tier-1 command found in ROADMAP.md",
              file=sys.stderr)
        sys.exit(2)
    return m.group(1)


def run_detlint() -> int:
    """python -m tools.lint --strict; nonzero = unbaselined findings."""
    print("verify_green: python -m tools.lint --strict", flush=True)
    proc = subprocess.run([sys.executable, "-m", "tools.lint", "--strict"],
                          cwd=REPO)
    return proc.returncode


def print_timings(log: str, top_n: int = 10) -> None:
    """Aggregate pytest's --durations lines (``0.42s call path::test``)
    per test FILE and print the slowest."""
    totals = {}
    for m in re.finditer(
            r"^\s*([0-9.]+)s\s+(?:call|setup|teardown)\s+([^:\s]+)::",
            log, re.M):
        totals[m.group(2)] = totals.get(m.group(2), 0.0) + \
            float(m.group(1))
    if not totals:
        print("verify_green: no duration lines found in the tier-1 log",
              flush=True)
        return
    print(f"verify_green: {top_n} slowest test files:", flush=True)
    width = max(len(f) for f in totals)
    for f, s in sorted(totals.items(), key=lambda kv: -kv[1])[:top_n]:
        print(f"  {f:<{width}}  {s:8.2f}s", flush=True)


def run_parallel_smoke(cmd: str, native: bool = True) -> "tuple":
    """The tier-1 line again with parallel apply forced on.  With
    ``native=False`` the NATIVE_APPLY=0 kill switch is exported too —
    the fallback-parity smoke: the Python reference apply must keep the
    suite green on its own (kernel declines land there).  Returns
    (problems, passed, abort_summary)."""
    tag = "parallel" if native else "fallback"
    log_path = f"/tmp/_t1p_{tag}.log"
    smoke_cmd = cmd.replace("/tmp/_t1.log", log_path)
    stats_path = f"/tmp/_t1p_{tag}_apply_stats.jsonl"
    try:
        os.unlink(stats_path)
    except OSError:
        pass
    env = dict(os.environ)
    env["PARALLEL_APPLY_WORKERS"] = "2"
    env["PARALLEL_APPLY_STATS_FILE"] = stats_path
    env["NATIVE_APPLY"] = "1" if native else "0"
    print(f"verify_green: [{tag} smoke] PARALLEL_APPLY_WORKERS=2 "
          f"NATIVE_APPLY={env['NATIVE_APPLY']} {smoke_cmd}", flush=True)
    proc = subprocess.run(["bash", "-c", smoke_cmd], cwd=REPO, env=env)
    problems = []
    if proc.returncode != 0:
        problems.append(f"{tag} smoke exited {proc.returncode}")
    try:
        with open(log_path, errors="replace") as f:
            log = f.read()
    except OSError:
        problems.append(f"{tag} smoke log missing")
        log = ""
    tail = "\n".join(log.splitlines()[-30:])
    for pat, what in ((r"\b([1-9]\d*) failed\b", "failed tests"),
                      (r"\b([1-9]\d*) errors?\b", "collection errors")):
        m = re.search(pat, tail)
        if m:
            problems.append(f"{tag} smoke: {m.group(1)} {what}")
    m = re.search(r"\b(\d+) passed\b", tail)
    passed = m.group(1) if m else "?"
    totals = {"parallel_closes": 0, "sequential_closes": 0, "aborts": 0,
              "unplanned": 0, "native_hits": 0, "native_declines": 0,
              "sessions": 0}
    reasons = []
    try:
        with open(stats_path, errors="replace") as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                totals["sessions"] += 1
                for k in ("parallel_closes", "sequential_closes",
                          "aborts", "unplanned", "native_hits",
                          "native_declines"):
                    totals[k] += int(row.get(k, 0))
                reasons.extend(row.get("escape_reasons", []))
    except OSError:
        pass
    summary = (f"{totals['parallel_closes']} parallel closes, "
               f"{totals['aborts']} aborts, "
               f"{totals['unplanned']} unplanned, "
               f"{totals['native_hits']} native hits, "
               f"{totals['native_declines']} declines, "
               f"{totals['sessions']} app sessions")
    if reasons:
        summary += f"; escapes: {reasons[:4]}"
    return problems, passed, summary


def run_pipelined_smoke(cmd: str) -> "tuple":
    """The tier-1 line again with PIPELINED_CLOSE=1 exported: every
    test Application closes through the pipelined engine (MANUAL_CLOSE
    rigs eager-drain per close, so post-close reads keep sequential
    semantics while the stage/tail/overlay machinery runs for real).
    Afterwards a miniature tools/pipeline_bench.py run checks the
    on/off hash+meta parity summary end to end.  Returns
    (problems, passed, summary)."""
    log_path = "/tmp/_t1p_pipeline.log"
    smoke_cmd = cmd.replace("/tmp/_t1.log", log_path)
    stats_path = "/tmp/_t1p_pipeline_stats.jsonl"
    try:
        os.unlink(stats_path)
    except OSError:
        pass
    env = dict(os.environ)
    env["PIPELINED_CLOSE"] = "1"
    env["PIPELINED_CLOSE_STATS_FILE"] = stats_path
    print(f"verify_green: [pipeline smoke] PIPELINED_CLOSE=1 "
          f"{smoke_cmd}", flush=True)
    proc = subprocess.run(["bash", "-c", smoke_cmd], cwd=REPO, env=env)
    problems = []
    if proc.returncode != 0:
        problems.append(f"pipeline smoke exited {proc.returncode}")
    try:
        with open(log_path, errors="replace") as f:
            log = f.read()
    except OSError:
        problems.append("pipeline smoke log missing")
        log = ""
    tail = "\n".join(log.splitlines()[-30:])
    for pat, what in ((r"\b([1-9]\d*) failed\b", "failed tests"),
                      (r"\b([1-9]\d*) errors?\b", "collection errors")):
        m = re.search(pat, tail)
        if m:
            problems.append(f"pipeline smoke: {m.group(1)} {what}")
    m = re.search(r"\b(\d+) passed\b", tail)
    passed = m.group(1) if m else "?"
    totals = {"sessions": 0, "tails": 0, "tail_failures": 0,
              "prefetch_adopted": 0}
    try:
        with open(stats_path, errors="replace") as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                totals["sessions"] += 1
                for k in ("tails", "tail_failures", "prefetch_adopted"):
                    totals[k] += int(row.get(k, 0))
    except OSError:
        pass
    if totals["tail_failures"]:
        problems.append(
            f"pipeline smoke: {totals['tail_failures']} tail failures")
    # the on/off parity summary: a miniature bench run (2 closes/arm of
    # 120 txs) whose parity pass compares per-close header/bucket
    # hashes AND meta bytes pipeline-on vs off
    bench_out = "/tmp/_t1p_pipeline_bench.json"
    bench_env = dict(os.environ)
    bench_env.update({"BENCH_CLOSES": "2", "BENCH_CLOSE_TXS": "120",
                      "JAX_PLATFORMS": "cpu",
                      "PIPELINE_BENCH_OUT": bench_out})
    bench = subprocess.run(
        [sys.executable, os.path.join("tools", "pipeline_bench.py")],
        cwd=REPO, env=bench_env, capture_output=True, text=True)
    parity = "unchecked"
    if bench.returncode != 0:
        problems.append("pipeline parity bench failed: "
                        + "\n".join(bench.stderr.splitlines()[-3:]))
        parity = "failed"
    else:
        try:
            with open(bench_out) as f:
                rep = json.load(f)["parity"]
            parity = ("identical" if rep.get("hashes_identical")
                      and rep.get("meta_bytes_identical") else "DIVERGED")
            if parity == "DIVERGED":
                problems.append("pipeline on/off hash parity DIVERGED")
        except (OSError, ValueError, KeyError) as e:
            problems.append(f"pipeline parity report unreadable: {e}")
            parity = "unreadable"
    summary = (f"{totals['tails']} tails over {totals['sessions']} app "
               f"sessions, {totals['tail_failures']} tail failures, "
               f"{totals['prefetch_adopted']} prefetched keys adopted, "
               f"on/off parity {parity}")
    return problems, passed, summary


def run_credit_native_smoke() -> "tuple":
    """The ISSUE-13 kernel-complete gate: a small credit-mix and
    path-payment workload must (a) close bit-identical native-vs-Python
    and (b) hit the kernel on >= 90% of clusters — declines on the
    kernel-complete mixes are bugs now, not expected coverage gaps.
    Returns (problems, summary)."""
    out = "/tmp/_t1_credit_smoke.json"
    cmd = [sys.executable, "-m", "tools.parallel_apply_bench",
           "--credit-smoke", "--out", out]
    print(f"verify_green: [credit-native smoke] {' '.join(cmd)}",
          flush=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=900)
    try:
        with open(out) as f:
            rep = json.load(f)
    except (OSError, ValueError) as e:
        return [f"credit-native smoke report unreadable: {e} "
                f"(exit {proc.returncode})"], "failed"
    problems = []
    for shape, row in sorted(rep.get("shapes", {}).items()):
        if not row.get("parity_identical"):
            problems.append(f"credit-native smoke: {shape} native/Python "
                            "parity DIVERGED")
        if row.get("aborts"):
            problems.append(
                f"credit-native smoke: {shape} {row['aborts']} aborts")
        if row.get("native_hit_rate", 0.0) < 0.9:
            problems.append(
                f"credit-native smoke: {shape} hit rate "
                f"{row.get('native_hit_rate')} < 0.9 "
                f"(declines: {row.get('decline_reasons')})")
    if proc.returncode != 0 and not problems:
        problems.append(f"credit-native smoke exited {proc.returncode}")
    summary = ", ".join(
        f"{shape} hit_rate={row.get('native_hit_rate')} "
        f"parity={'ok' if row.get('parity_identical') else 'FAILED'}"
        for shape, row in sorted(rep.get("shapes", {}).items()))
    return problems, summary or "no shapes reported"


def run_fee_native_smoke() -> "tuple":
    """The ISSUE-16 fee-phase gate: a mixed workload with the batched
    charge_fees kernel on vs NATIVE_FEE=0 must close bit-identical
    (hashes AND meta bytes), and the fee batch must carry >= 90% of
    closes (whole-batch declines on clean traffic are bugs now).
    Returns (problems, summary)."""
    out = "/tmp/_t1_fee_smoke.json"
    cmd = [sys.executable, "-m", "tools.parallel_apply_bench",
           "--fee-smoke", "--out", out]
    print(f"verify_green: [fee-native smoke] {' '.join(cmd)}",
          flush=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=900)
    try:
        with open(out) as f:
            rep = json.load(f)
    except (OSError, ValueError) as e:
        return [f"fee-native smoke report unreadable: {e} "
                f"(exit {proc.returncode})"], "failed"
    problems = []
    if not rep.get("parity_identical"):
        problems.append(
            "fee-native smoke: NATIVE_FEE on/off parity DIVERGED")
    if rep.get("fee_batch_hit_rate", 0.0) < 0.9:
        problems.append(
            f"fee-native smoke: fee-batch hit rate "
            f"{rep.get('fee_batch_hit_rate')} < 0.9 "
            f"(counters: {rep.get('fee_batch')})")
    if proc.returncode != 0 and not problems:
        problems.append(f"fee-native smoke exited {proc.returncode}")
    summary = (f"hit_rate={rep.get('fee_batch_hit_rate')} "
               f"parity={'ok' if rep.get('parity_identical') else 'FAILED'}")
    return problems, summary


def run_chaos_smoke() -> "tuple":
    """One small chaos scenario end-to-end (core-4 partition+heal, with
    the same-seed determinism rerun): the full fault-inject -> heal ->
    no-fork -> bit-identical-fingerprint contract in ~20s.  Returns
    (problems, summary)."""
    out = "/tmp/_t1_chaos_smoke.json"
    cmd = [sys.executable, "-m", "tools.chaos_bench", "--tier", "core4",
           "--scenario", "partition_heal", "--out", out]
    print(f"verify_green: [chaos smoke] {' '.join(cmd)}", flush=True)
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
    problems = []
    if proc.returncode != 0:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-6:])
        return [f"chaos smoke exited {proc.returncode}: {tail}"], "failed"
    try:
        with open(out) as f:
            rep = json.load(f)["scenarios"][0]
    except (OSError, ValueError, KeyError, IndexError) as e:
        return [f"chaos smoke report unreadable: {e}"], "failed"
    if rep.get("fork_check") != "pass":
        problems.append("chaos smoke: fork check failed")
    if rep.get("rerun_identical") is not True:
        problems.append("chaos smoke: same-seed rerun not bit-identical")
    summary = (f"{rep.get('ledgers_closed')} ledgers, "
               f"heal={rep.get('time_to_heal_s')}s, "
               f"fork={rep.get('fork_check')}, "
               f"rerun_identical={rep.get('rerun_identical')}")
    return problems, summary


def run_forensics_smoke() -> "tuple":
    """The consensus-forensics gate (tools/scp_forensics_bench.py
    --smoke): a deliberately-unsafe core-4 net with a full Byzantine
    bridge MUST fork, the FORENSICS_*.json dump must attribute the
    first divergence to the Byzantine node via equivocation evidence,
    and a same-seed rerun must reproduce the dump byte-for-byte.  The
    recorder-overhead A/B rides along (informational at smoke scale;
    the <2% acceptance gate is the full 1000-tx bench artifact).
    Returns (problems, summary)."""
    out = "/tmp/_t1_forensics_smoke.json"
    cmd = [sys.executable, "-m", "tools.scp_forensics_bench",
           "--smoke", "--out", out]
    print(f"verify_green: [forensics smoke] {' '.join(cmd)}", flush=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=600)
    try:
        with open(out) as f:
            rep = json.load(f)
    except (OSError, ValueError) as e:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-6:])
        return [f"forensics smoke report unreadable: {e}: {tail}"], \
            "failed"
    problems = []
    probe = rep.get("fork_probe", {})
    if not probe.get("attributed_to_byzantine"):
        problems.append(
            f"forensics smoke: fork NOT attributed to the Byzantine "
            f"node (first_divergence={probe.get('first_divergence')}, "
            f"byzantine={probe.get('byzantine')})")
    if probe.get("rerun_dump_identical") is not True:
        problems.append(
            "forensics smoke: same-seed FORENSICS dump not "
            "byte-identical")
    overhead = rep.get("overhead", {}).get("overhead_pct_p50")
    summary = (f"fork at slot {probe.get('divergence_slot')} attributed "
               f"to {probe.get('first_divergence', {}).get('node')} "
               f"(byz={probe.get('byzantine')}), dump deterministic="
               f"{probe.get('rerun_dump_identical')}, recorder overhead "
               f"{overhead}% (smoke scale)")
    return problems, summary


def run_catchup_smoke() -> "tuple":
    """The fast-catchup gate (tools/catchup_bench.py --smoke): a small
    cold-join scenario — seed a core-2 net with traffic, publish
    checkpoints, then a minimal-mode joiner AND a complete-mode joiner
    each sync against the live network (closes keep arriving) and must
    end bit-identical (header hash + bucketListHash) to the validators.
    The 5x minimal-vs-complete speedup assertion is full-tier only; at
    smoke scale this checks correctness, not the ratio.  Returns
    (problems, summary)."""
    out = "/tmp/_t1_catchup_smoke.json"
    cmd = [sys.executable, os.path.join("tools", "catchup_bench.py"),
           "--smoke", "--out", out]
    print(f"verify_green: [catchup smoke] {' '.join(cmd)}", flush=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=900)
    if proc.returncode != 0:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-6:])
        return [f"catchup smoke exited {proc.returncode}: {tail}"], \
            "failed"
    try:
        with open(out) as f:
            rep = json.load(f)
    except (OSError, ValueError) as e:
        return [f"catchup smoke report unreadable: {e}"], "failed"
    problems = []
    mn, cp = rep.get("minimal", {}), rep.get("complete", {})
    for tag, row in (("minimal", mn), ("complete", cp)):
        if row.get("bit_identical") is not True:
            problems.append(f"catchup smoke: {tag} joiner NOT "
                            "bit-identical to the validators")
    if mn.get("bucket_applied_entries", 0) <= 0:
        problems.append("catchup smoke: minimal joiner applied no "
                        "bucket entries")
    if cp.get("ledgers_replayed", 0) <= 0:
        problems.append("catchup smoke: complete joiner replayed no "
                        "ledgers")
    summary = (f"minimal {mn.get('time_to_synced_s')}s "
               f"(trailing {mn.get('trailing_ledgers_at_join')}, "
               f"{mn.get('bucket_apply_mb_s')} MB/s apply, "
               f"{mn.get('chain_headers_verified')} headers verified), "
               f"complete {cp.get('time_to_synced_s')}s "
               f"({cp.get('ledgers_replayed')} ledgers replayed), "
               f"speedup {rep.get('minimal_speedup_vs_complete')}x, "
               f"both bit-identical")
    return problems, summary


def run_soak_smoke() -> "tuple":
    """A ~30-clock-second sustained-load soak (tools/soak_bench.py
    --smoke): rate-mode load on a disk-backed REAL_TIME node, then the
    vitals/SLO verdicts — the ring must be populated, the RSS slope
    must sit under the watchdog ceiling (zero SLO breaches), the
    telemetry disabled-cost must stay <1% of close p50, and the
    telemetry on/off hash+meta parity must hold.  Returns
    (problems, summary)."""
    out = "/tmp/_t1_soak_smoke.json"
    cmd = [sys.executable, os.path.join("tools", "soak_bench.py"),
           "--smoke", "--out", out]
    print(f"verify_green: [soak smoke] {' '.join(cmd)}", flush=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=600)
    if proc.returncode != 0:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-6:])
        return [f"soak smoke exited {proc.returncode}: {tail}"], "failed"
    try:
        with open(out) as f:
            rep = json.load(f)
    except (OSError, ValueError) as e:
        return [f"soak smoke report unreadable: {e}"], "failed"
    problems = []
    vit = rep.get("vitals", {})
    if vit.get("samples", 0) < 10:
        problems.append(
            f"soak smoke: vitals ring underpopulated "
            f"({vit.get('samples')} samples over a ~30 s run)")
    if not rep.get("slo", {}).get("watchdog_green"):
        problems.append(
            f"soak smoke: SLO breaches {rep.get('slo', {})}")
    cost = rep.get("disabled_cost", {})
    disabled_pct = cost.get("disabled_pct")
    if disabled_pct is None or disabled_pct >= 1.0:
        problems.append(
            f"soak smoke: telemetry disabled-cost {disabled_pct}% of "
            f"close p50 (gate: <1%)")
    par = rep.get("parity", {})
    if not (par.get("hashes_identical") and
            par.get("meta_bytes_identical")):
        problems.append("soak smoke: telemetry on/off parity DIVERGED")
    summary = (f"{rep.get('sustained', {}).get('applied_tx_s')} tx/s "
               f"applied over "
               f"{rep.get('sustained', {}).get('ledgers_closed')} "
               f"ledgers, rss slope "
               f"{vit.get('rss_slope_mb_s')} MB/s, "
               f"{vit.get('samples')} vitals samples, disabled-cost "
               f"{disabled_pct}% (enabled A/B "
               f"{cost.get('enabled_overhead_pct')}%), parity "
               f"{'ok' if par.get('hashes_identical') else 'FAILED'}")
    return problems, summary


def run_netobs_smoke() -> "tuple":
    """The network-observatory gate (tools/chaos_bench.py --netobs
    --tier core4): a core-4 sim under chaos + rate-mode loadgen with
    flood tracing ON, then the same run with tracing OFF — nonzero hop
    records, coverage percentiles present, crank wall attribution
    >= 90%, tracing disabled-cost < 2% of close p50, and on/off
    hash+meta inertness.  The tiered-50 tier is full-bench only
    (NET_OBS_r19.json).  Returns (problems, summary)."""
    out = "/tmp/_t1_netobs_smoke.json"
    cmd = [sys.executable, "-m", "tools.chaos_bench", "--netobs",
           "--tier", "core4", "--out", out]
    print(f"verify_green: [netobs smoke] {' '.join(cmd)}", flush=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=600)
    if proc.returncode != 0:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-6:])
        return [f"netobs smoke exited {proc.returncode}: {tail}"], \
            "failed"
    try:
        with open(out) as f:
            rep = json.load(f)["tiers"]["core4"]
    except (OSError, ValueError, KeyError) as e:
        return [f"netobs smoke report unreadable: {e}"], "failed"
    problems = []
    g = rep.get("gates", {})
    if not g.get("hop_records_nonzero"):
        problems.append("netobs smoke: no flood hop records")
    if not g.get("coverage_percentiles_present"):
        problems.append("netobs smoke: coverage percentiles missing")
    overhead = g.get("tracing_overhead_pct")
    if not g.get("tracing_overhead_ok"):
        problems.append(f"netobs smoke: tracing disabled-cost "
                        f"{overhead}% of close p50 (gate: <2%)")
    if not g.get("inert_hashes_and_meta"):
        problems.append("netobs smoke: tracing on/off hash/meta "
                        "parity DIVERGED")
    if not g.get("attribution_ok"):
        problems.append(f"netobs smoke: only {g.get('attributed_pct')}% "
                        f"of crank wall attributed (gate: >=90%)")
    prop = rep.get("on", {}).get("observatory", {}).get("propagation", {})
    t90 = (prop.get("time_to_90pct") or {}).get("p90")
    summary = (f"{rep.get('on', {}).get('hop_records_total')} hop "
               f"records, t90 p90={t90}s, disabled-cost {overhead}% "
               f"(enabled A/B {g.get('enabled_overhead_pct')}%), "
               f"attributed {g.get('attributed_pct')}%, "
               f"inert={'ok' if g.get('inert_hashes_and_meta') else 'FAILED'}")
    return problems, summary


#: threaded-subsystem tier-1 subset the lockdep witness re-runs: every
#: file that exercises the pipelined close, the bucket background
#: merge/GC, or a registered lock directly
LOCKDEP_T1_SUBSET = [
    "tests/test_lockdep.py",
    "tests/test_pipelined_close.py",
    "tests/test_bucket_list.py",
    "tests/test_metrics.py",
    "tests/test_txtrace.py",
    "tests/test_tracing.py",
]

def run_lockdep_smoke() -> "tuple":
    """The detlint-v3 runtime witness gate, everything under LOCKDEP=1:
    (a) the threaded-subsystem tier-1 subset, (b) one core-4
    partition+heal chaos scenario, (c) one pipelined-close bench
    iteration — all with every registered lock wrapped and every
    ``# guarded-by:`` field write assert-held-checked; ANY
    LockOrderInversion or GuardViolation is red.  A per-acquire
    micro-benchmark then bounds the enabled-witness cost at <1% of the
    close p50 the bench just measured.  Returns (problems, summary)."""
    problems = []
    env = dict(os.environ)
    env["LOCKDEP"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")

    # (a) the threaded tier-1 subset
    log_path = "/tmp/_t1_lockdep.log"
    sub_cmd = (f"timeout -k 10 600 {sys.executable} -m pytest "
               f"{' '.join(LOCKDEP_T1_SUBSET)} -q -m 'not slow' "
               f"-p no:cacheprovider -p no:xdist -p no:randomly "
               f"> {log_path} 2>&1")
    print(f"verify_green: [lockdep smoke] LOCKDEP=1 {sub_cmd}",
          flush=True)
    proc = subprocess.run(["bash", "-c", sub_cmd], cwd=REPO, env=env)
    try:
        with open(log_path, errors="replace") as f:
            log = f.read()
    except OSError:
        log = ""
    if proc.returncode != 0:
        problems.append(f"lockdep smoke: subset exited {proc.returncode}")
    tail = "\n".join(log.splitlines()[-30:])
    m = re.search(r"\b([1-9]\d*) failed\b", tail)
    if m:
        problems.append(f"lockdep smoke: {m.group(1)} failed tests")
    m = re.search(r"\b(\d+) passed\b", tail)
    passed = m.group(1) if m else "?"

    # (b) one chaos scenario with the witness armed
    chaos_out = "/tmp/_t1_lockdep_chaos.json"
    chaos_cmd = [sys.executable, "-m", "tools.chaos_bench", "--tier",
                 "core4", "--scenario", "partition_heal", "--out",
                 chaos_out]
    print(f"verify_green: [lockdep smoke] LOCKDEP=1 "
          f"{' '.join(chaos_cmd)}", flush=True)
    chaos = subprocess.run(chaos_cmd, cwd=REPO, env=env,
                           capture_output=True, text=True, timeout=600)
    chaos_note = "ok"
    if chaos.returncode != 0:
        tail2 = "\n".join((chaos.stdout + chaos.stderr).splitlines()[-6:])
        problems.append(
            f"lockdep smoke: chaos exited {chaos.returncode}: {tail2}")
        chaos_note = "failed"
    log += chaos.stdout + chaos.stderr

    # (c) one pipelined-close bench iteration with the witness armed:
    # the probe mode runs pay closes on one app and reports the
    # lockdep.stats() DELTA across the timed loop — the measured
    # acquires + guarded-field checks PER CLOSE, plus the close p50
    # those closes actually achieved under the witness
    bench_out = "/tmp/_t1_lockdep_pipeline.json"
    bench_env = dict(env)
    bench_env.update({"BENCH_CLOSES": "6", "BENCH_CLOSE_TXS": "120",
                      "PIPELINE_BENCH_OUT": bench_out})
    bench_cmd = [sys.executable, os.path.join("tools",
                                              "pipeline_bench.py"),
                 "--lockdep-probe"]
    print(f"verify_green: [lockdep smoke] LOCKDEP=1 "
          f"{' '.join(bench_cmd)}", flush=True)
    bench = subprocess.run(bench_cmd, cwd=REPO, env=bench_env,
                           capture_output=True, text=True, timeout=600)
    probe = None
    if bench.returncode != 0:
        tail3 = "\n".join((bench.stdout + bench.stderr).splitlines()[-6:])
        problems.append(
            f"lockdep smoke: pipeline probe exited {bench.returncode}: "
            f"{tail3}")
    else:
        try:
            with open(bench_out) as f:
                probe = json.load(f)
            if probe.get("inversions") or probe.get("guard_violations"):
                problems.append(
                    f"lockdep smoke: probe saw "
                    f"{probe.get('inversions')} inversions / "
                    f"{probe.get('guard_violations')} guard violations")
        except (OSError, ValueError) as e:
            problems.append(
                f"lockdep smoke: probe report unreadable: {e}")
    log += bench.stdout + bench.stderr

    # zero-violations gate: inversions/guard trips raise and fail their
    # run above, but scan the combined output too so a swallowed one
    # still reds the gate with its name attached
    for marker in ("LockOrderInversion", "GuardViolation"):
        n = log.count(marker)
        if n:
            problems.append(f"lockdep smoke: {n} {marker} in output")

    # overhead gate: A/B micro-bench of the enabled witness (wrapped vs
    # raw lock, plus one guarded-field check), scaled by the per-close
    # counts the probe just MEASURED, bounded at <1% of the probe's
    # close p50
    micro = subprocess.run(
        [sys.executable, "-c", (
            "import json, threading, time\n"
            "from stellar_core_tpu.utils import lockdep\n"
            "raw = threading.Lock()\n"
            "wit = lockdep.register_lock(threading.Lock(), 'bench')\n"
            "assert isinstance(wit, lockdep.WitnessLock)\n"
            "def per_acquire(lk, n=200000):\n"
            "    for _ in range(n // 10):\n"
            "        with lk:\n"
            "            pass\n"
            "    t0 = time.perf_counter()\n"
            "    for _ in range(n):\n"
            "        with lk:\n"
            "            pass\n"
            "    return (time.perf_counter() - t0) / n\n"
            "class B:\n"
            "    pass\n"
            "b = B()\n"
            "b.__dict__['_lock'] = wit\n"
            "b.__dict__['_lockdep_enforced'] = True\n"
            "desc = lockdep._GuardedField('val', '_lock')\n"
            "def per_check(n=200000):\n"
            "    with wit:\n"
            "        t0 = time.perf_counter()\n"
            "        for i in range(n):\n"
            "            desc.__set__(b, i)\n"
            "        return (time.perf_counter() - t0) / n\n"
            "print(json.dumps({'raw_us': per_acquire(raw) * 1e6,\n"
            "                  'wit_us': per_acquire(wit) * 1e6,\n"
            "                  'check_us': per_check() * 1e6}))\n")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    overhead_note = "unmeasured"
    if micro.returncode != 0:
        problems.append("lockdep smoke: overhead micro-bench failed: "
                        + "\n".join(micro.stderr.splitlines()[-3:]))
    elif probe is not None:
        try:
            row = json.loads(micro.stdout.strip().splitlines()[-1])
            acq_us = max(0.0, row["wit_us"] - row["raw_us"])
            chk_us = row["check_us"]
            per_close_ms = (
                acq_us * probe.get("acquires_per_close", 0.0)
                + chk_us * probe.get("guard_checks_per_close", 0.0)
            ) / 1000.0
            p50 = probe.get("close_p50_ms") or 20.0
            pct = per_close_ms / p50 * 100.0
            overhead_note = (
                f"{acq_us:.2f}us x {probe.get('acquires_per_close')} "
                f"acquires + {chk_us:.2f}us x "
                f"{probe.get('guard_checks_per_close')} checks = "
                f"{per_close_ms:.3f}ms/close = {pct:.2f}% of close "
                f"p50 {p50}ms")
            if pct >= 1.0:
                problems.append(
                    f"lockdep smoke: witness overhead {overhead_note} "
                    f"(gate: <1%)")
        except (ValueError, KeyError, IndexError) as e:
            problems.append(
                f"lockdep smoke: overhead report unreadable: {e}")
    summary = (f"subset passed={passed}, chaos {chaos_note}, "
               f"0 violations, witness overhead {overhead_note}")
    return problems, summary


def run_fuzz_smoke() -> "tuple":
    """The fault-schedule fuzzer gate (tools/fuzz_bench.py --smoke): a
    budget-capped campaign of seeded schedules on the smoke grid
    (core-4 + one tiered net) under the full oracle stack, plus the
    known-bad proof — the injected fork schedule must be found,
    ddmin-minimized to its essential events, and its persisted repro
    artifact must replay to the same failure fingerprint.  Red on any
    oracle failure and on a non-reproducing minimized artifact.
    Returns (problems, summary)."""
    out = "/tmp/_t1_fuzz_smoke.json"
    cmd = [sys.executable, "-m", "tools.fuzz_bench", "--smoke",
           "--out", out]
    print(f"verify_green: [fuzz smoke] {' '.join(cmd)}", flush=True)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=900)
    try:
        with open(out) as f:
            rep = json.load(f)
    except (OSError, ValueError) as e:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-6:])
        return [f"fuzz smoke report unreadable: {e}: {tail}"], "failed"
    problems = [f"fuzz smoke: {p}" for p in rep.get("problems", [])]
    if proc.returncode != 0 and not problems:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-6:])
        problems.append(f"fuzz smoke exited {proc.returncode}: {tail}")
    camp = rep.get("campaigns", {}).get("smoke", {})
    kb = rep.get("known_bad", {})
    ab = rep.get("slice_eval_ab", {}).get("50", {})
    summary = (f"{camp.get('schedules_executed')} schedules "
               f"({camp.get('failure_count')} failures, "
               f"{camp.get('unique_novelty')} novel), known-bad "
               f"{kb.get('events_before')}->{kb.get('events_after')} "
               f"events, replay={kb.get('replay_reproduced')}, "
               f"A/B@50 {ab.get('speedup')}x")
    return problems, summary


def main() -> int:
    timings = "--timings" in sys.argv
    if "--lint-only" in sys.argv:
        # the fast CI gate: the native auditor + interprocedural taint
        # pass (with source->sink chains in every finding) run inside
        # the same strict lint; a red exit here is a LINT RED verdict
        lint_rc = run_detlint()
        if lint_rc != 0:
            print(f"verify_green: LINT RED (detlint --strict exited "
                  f"{lint_rc})", flush=True)
            return 1
        print("verify_green: LINT GREEN (detlint --strict clean)",
              flush=True)
        return 0
    if "--netobs-smoke" in sys.argv:
        # standalone network-observatory gate: core-4 chaos+load with
        # flood tracing on/off, asserting the persisted r19 gates
        no_problems, no_summary = run_netobs_smoke()
        print(f"verify_green: netobs smoke: {no_summary}", flush=True)
        if no_problems:
            print(f"verify_green: RED ({'; '.join(no_problems)})",
                  flush=True)
            return 1
        print(f"verify_green: GREEN (netobs smoke: {no_summary})",
              flush=True)
        return 0
    if "--lockdep-smoke" in sys.argv:
        # standalone runtime-witness gate: everything under LOCKDEP=1
        ld_problems, ld_summary = run_lockdep_smoke()
        print(f"verify_green: lockdep smoke: {ld_summary}", flush=True)
        if ld_problems:
            print(f"verify_green: RED ({'; '.join(ld_problems)})",
                  flush=True)
            return 1
        print(f"verify_green: GREEN (lockdep smoke: {ld_summary})",
              flush=True)
        return 0
    if "--fuzz-smoke" in sys.argv:
        # standalone fault-schedule-fuzzer gate: budget-capped seeded
        # schedules + the known-bad minimize/replay proof
        fz_problems, fz_summary = run_fuzz_smoke()
        print(f"verify_green: fuzz smoke: {fz_summary}", flush=True)
        if fz_problems:
            print(f"verify_green: RED ({'; '.join(fz_problems)})",
                  flush=True)
            return 1
        print(f"verify_green: GREEN (fuzz smoke: {fz_summary})",
              flush=True)
        return 0
    smoke_only = "--parallel-smoke-only" in sys.argv
    skip_smoke = "--skip-parallel-smoke" in sys.argv
    skip_fallback = "--skip-fallback-smoke" in sys.argv
    skip_chaos = "--skip-chaos-smoke" in sys.argv
    skip_pipeline = "--skip-pipeline-smoke" in sys.argv
    skip_soak = "--skip-soak-smoke" in sys.argv
    skip_credit = "--skip-credit-smoke" in sys.argv
    skip_fee = "--skip-fee-smoke" in sys.argv
    skip_forensics = "--skip-forensics-smoke" in sys.argv
    skip_catchup = "--skip-catchup-smoke" in sys.argv
    skip_lockdep = "--skip-lockdep-smoke" in sys.argv
    skip_netobs = "--skip-netobs-smoke" in sys.argv
    skip_fuzz = "--skip-fuzz-smoke" in sys.argv
    if smoke_only:
        cmd = tier1_command()
        problems, passed, summary = run_parallel_smoke(cmd)
        print(f"verify_green: parallel-apply smoke: {summary}", flush=True)
        if problems:
            print(f"verify_green: RED ({'; '.join(problems)}); "
                  f"passed={passed}", flush=True)
            return 1
        print(f"verify_green: GREEN (parallel smoke passed={passed})",
              flush=True)
        return 0
    lint_rc = run_detlint()
    if lint_rc != 0:
        # distinct from test failures: the analyzer itself printed the
        # findings; still run the tests so one gate run reports both
        print(f"verify_green: LINT RED (detlint --strict exited "
              f"{lint_rc})", flush=True)
    cmd = tier1_command()
    if timings:
        # same tier-1 line, plus pytest's own per-test durations (all of
        # them: --durations=0) so the slow tail is attributable by file
        cmd = cmd.replace("-m pytest", "-m pytest --durations=0 -vv", 1)
    print(f"verify_green: {cmd}", flush=True)
    proc = subprocess.run(["bash", "-c", cmd], cwd=REPO)
    rc = proc.returncode
    problems = []
    if rc != 0:
        problems.append(f"tier-1 command exited {rc}")
    try:
        with open("/tmp/_t1.log", errors="replace") as f:
            log = f.read()
    except OSError:
        problems.append("tier-1 log /tmp/_t1.log missing")
        log = ""
    # the summary line: "N passed", "N failed", "N errors" — failures
    # and errors both break the gate even if the shell rc lied
    tail = "\n".join(log.splitlines()[-30:])
    for pat, what in ((r"\b([1-9]\d*) failed\b", "failed tests"),
                      (r"\b([1-9]\d*) errors?\b", "collection errors")):
        m = re.search(pat, tail)
        if m:
            problems.append(f"{m.group(1)} {what}")
    if re.search(r"^=+ ERRORS =+$", log, re.M):
        problems.append("ERRORS section in pytest output")
    m = re.search(r"\b(\d+) passed\b", tail)
    passed = m.group(1) if m else "?"
    if timings:
        print_timings(log)
    if lint_rc != 0:
        problems.append("unbaselined detlint findings (see LINT RED "
                        "above)")
    smoke_note = "parallel smoke skipped"
    if not skip_smoke:
        smoke_problems, smoke_passed, summary = run_parallel_smoke(cmd)
        print(f"verify_green: parallel-apply smoke: {summary}",
              flush=True)
        problems.extend(smoke_problems)
        smoke_note = f"parallel smoke passed={smoke_passed}"
        if not skip_fallback:
            # NATIVE_APPLY=0 fallback parity: the Python reference
            # apply alone must keep the suite green (every kernel
            # decline lands on it in production)
            fb_problems, fb_passed, fb_summary = run_parallel_smoke(
                cmd, native=False)
            print(f"verify_green: fallback-parity smoke: {fb_summary}",
                  flush=True)
            problems.extend(fb_problems)
            smoke_note += f", fallback smoke passed={fb_passed}"
    if not skip_credit:
        cr_problems, cr_summary = run_credit_native_smoke()
        print(f"verify_green: credit-native smoke: {cr_summary}",
              flush=True)
        problems.extend(cr_problems)
        smoke_note += f", credit smoke: {cr_summary}"
    if not skip_fee:
        fee_problems, fee_summary = run_fee_native_smoke()
        print(f"verify_green: fee-native smoke: {fee_summary}",
              flush=True)
        problems.extend(fee_problems)
        smoke_note += f", fee smoke: {fee_summary}"
    if not skip_pipeline:
        pl_problems, pl_passed, pl_summary = run_pipelined_smoke(cmd)
        print(f"verify_green: pipelined-close smoke: {pl_summary}",
              flush=True)
        problems.extend(pl_problems)
        smoke_note += f", pipeline smoke passed={pl_passed}"
    if not skip_chaos:
        chaos_problems, chaos_summary = run_chaos_smoke()
        print(f"verify_green: chaos smoke: {chaos_summary}", flush=True)
        problems.extend(chaos_problems)
        smoke_note += f", chaos smoke: {chaos_summary}"
    if not skip_soak:
        soak_problems, soak_summary = run_soak_smoke()
        print(f"verify_green: soak smoke: {soak_summary}", flush=True)
        problems.extend(soak_problems)
        smoke_note += f", soak smoke: {soak_summary}"
    if not skip_forensics:
        fo_problems, fo_summary = run_forensics_smoke()
        print(f"verify_green: forensics smoke: {fo_summary}", flush=True)
        problems.extend(fo_problems)
        smoke_note += f", forensics smoke: {fo_summary}"
    if not skip_catchup:
        cu_problems, cu_summary = run_catchup_smoke()
        print(f"verify_green: catchup smoke: {cu_summary}", flush=True)
        problems.extend(cu_problems)
        smoke_note += f", catchup smoke: {cu_summary}"
    if not skip_netobs:
        no_problems, no_summary = run_netobs_smoke()
        print(f"verify_green: netobs smoke: {no_summary}", flush=True)
        problems.extend(no_problems)
        smoke_note += f", netobs smoke: {no_summary}"
    if not skip_lockdep:
        ld_problems, ld_summary = run_lockdep_smoke()
        print(f"verify_green: lockdep smoke: {ld_summary}", flush=True)
        problems.extend(ld_problems)
        smoke_note += f", lockdep smoke: {ld_summary}"
    if not skip_fuzz:
        fz_problems, fz_summary = run_fuzz_smoke()
        print(f"verify_green: fuzz smoke: {fz_summary}", flush=True)
        problems.extend(fz_problems)
        smoke_note += f", fuzz smoke: {fz_summary}"
    if problems:
        print(f"verify_green: RED ({'; '.join(problems)}); "
              f"passed={passed}", flush=True)
        return 1
    print(f"verify_green: GREEN (passed={passed}, detlint clean, "
          f"{smoke_note})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
