#!/usr/bin/env python
"""Bench-shaped multi-chip evidence (VERDICT r4 task 4): run the FULL
100k-signature sharded admission step + a 64-validator parallel ballot
tally on an 8-device mesh and record per-device throughput in
MULTICHIP_BENCH_r05.json.

On this machine the mesh is 8 virtual host-CPU devices (the TPU tunnel
exposes one chip at most), so the recorded rate is the host-CPU XLA rate
with an honest "platform: cpu" label — the artifact proves the sharded
program at bench shapes (100k sigs, real shardings, real collectives),
which is what the virtual mesh CAN prove.  Run on a real v5e-8 the same
file captures real scaling.

Usage: python tools/multichip_bench.py [n_devices] [n_sigs]
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    n_devices = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    n_sigs = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from stellar_core_tpu.models.admission import bench_sharded

    npz = os.path.join(REPO, "tools", "capture_workload.npz")
    result = bench_sharded(
        n_devices, n_sigs=n_sigs,
        workload_npz=npz if os.path.exists(npz) else None)
    # 1-device comparison at the SAME batch (same program, no sharding):
    # per-device throughput lines are only comparable when both runs
    # verify identical n_sigs (VERDICT r5 weak #5)
    result["one_device_comparison"] = bench_sharded(
        1, n_sigs=n_sigs,
        workload_npz=npz if os.path.exists(npz) else None)
    result["note"] = (
        "virtual host-CPU mesh: all devices share one host's cores, so "
        "per-device rate is a program-shape artifact, not chip scaling; "
        "the 1-device run uses the same n_sigs as the mesh run so the "
        "per-device lines are shape-matched; the XLA-on-CPU ed25519 rate "
        "is far below both libsodium and the TPU path by design (see "
        "BENCH_*.json for the device numbers)")
    out = os.path.join(REPO, "MULTICHIP_BENCH_r06.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
