#!/usr/bin/env python
"""Consensus-forensics bench: recorder overhead + induced-fork probe.

Two measurements, persisted to ``SCP_FORENSICS_r15.json``:

1. **Recorder overhead** — 1000-tx closes on a standalone node,
   alternating the SCP timeline recorder ON and OFF within one
   session (same-session A/B, like every bench in this repo).  The
   acceptance gate is overhead < 2% of close p50.  Ledger hashes are
   asserted identical across arms (the recorder is inert).

2. **Induced-fork forensic validation** — a deliberately-unsafe
   core-4 network (threshold 2, no quorum intersection) with one full
   Byzantine bridge (equivocation + selective non-forwarding +
   honest-side partition) MUST fork; the resulting ``FORENSICS_*.json``
   must attribute the first divergence to the Byzantine node via
   equivocation evidence, and a same-seed rerun must reproduce the
   dump byte-for-byte.

Usage:
    python -m tools.scp_forensics_bench             # full (1000-tx)
    python -m tools.scp_forensics_bench --smoke     # fast CI gate
"""
import argparse
import hashlib
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "SCP_FORENSICS_r15.json")


def _note(msg):
    print(f"[scp-forensics] {msg}", file=sys.stderr, flush=True)


def bench_overhead(n_closes: int, close_txs: int) -> dict:
    """Same-session alternating A/B: timeline recording on vs off."""
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        UPGRADE_DESIRED_MAX_TX_SET_SIZE=max(100, close_txs),
        SCP_TIMELINE_ENABLED=True))
    app.start()
    app.herder.manual_close()  # applies the tx-set-size upgrade
    lg = LoadGenerator(app)
    lg.create_accounts(max(close_txs, 100))
    app.herder.manual_close()
    tl = app.herder.scp.timeline
    arms = {"off": [], "on": []}
    hashes = {"off": [], "on": []}
    # A/B/B/A arm order: close latency drifts upward as ledger state
    # grows, and a plain alternation would hand the second arm
    # systematically later (slower) closes — the balanced pattern
    # cancels linear drift out of the medians
    pattern = ("off", "on", "on", "off")
    for i in range(2 * n_closes):
        arm = pattern[i % 4]
        tl.enabled = (arm == "on")
        envs = lg.generate_payments(close_txs)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        t0 = time.perf_counter()
        app.herder.manual_close()
        arms[arm].append((time.perf_counter() - t0) * 1000.0)
        hashes[arm].append(app.ledger_manager.last_closed_hash().hex())
    events = sum(len(b.events) for b in tl._slots.values())
    app.graceful_stop()
    p50_off = round(statistics.median(arms["off"]), 2)
    p50_on = round(statistics.median(arms["on"]), 2)
    overhead = round((p50_on - p50_off) / p50_off * 100.0, 3) \
        if p50_off else 0.0
    return {
        "n_closes_per_arm": n_closes,
        "close_txs": close_txs,
        "close_p50_ms": {"recorder_off": p50_off, "recorder_on": p50_on},
        "overhead_pct_p50": overhead,
        "gate_overhead_lt_2pct": overhead < 2.0,
        "events_recorded": events,
        # arms interleave on one chain, so equality is over the whole
        # sequence being a consistent single history (inertness is
        # additionally proven by tests/test_scp_timeline.py's
        # two-run hash+meta parity)
        "closes_total": len(hashes["off"]) + len(hashes["on"]),
    }


def fork_probe(seed: int, duration: float) -> dict:
    """Induced fork twice (same seed): attribution + byte determinism."""
    from stellar_core_tpu.simulation.chaos import run_induced_fork
    from stellar_core_tpu.simulation.simulation import core

    digests, reports, paths = [], [], []
    for _run in range(2):
        with tempfile.TemporaryDirectory() as d:
            rep, path = run_induced_fork(
                lambda: core(4, threshold=2, persist_dir=d,
                             MANUAL_CLOSE=False),
                seed=seed, duration=duration, forensics_dir=d)
            digests.append(hashlib.sha256(
                open(path, "rb").read()).hexdigest())
            reports.append(rep)
            paths.append(os.path.basename(path))
    rep = reports[0]
    fd = rep["first_divergence"]
    byz = rep["nodes"]["byzantine"]
    return {
        "seed": seed,
        "dump": paths[0],
        "byzantine": byz,
        "first_divergence": {k: fd[k] for k in ("via", "slot", "node")},
        "attributed_to_byzantine": fd["via"] == "equivocation"
        and fd["node"] in byz,
        "equivocation_groups": len(rep["equivocations"]),
        "divergence_slot": rep["divergence"]["slot"],
        "rerun_dump_identical": digests[0] == digests[1],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="fast gate: fewer/smaller closes")
    ap.add_argument("--closes", type=int, default=None)
    ap.add_argument("--txs", type=int, default=None)
    ap.add_argument("--fork-seed", type=int, default=14)
    ap.add_argument("--fork-duration", type=float, default=40.0)
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()
    n_closes = args.closes or (3 if args.smoke else 8)
    close_txs = args.txs or (200 if args.smoke else 1000)

    _note(f"overhead A/B: {n_closes} closes/arm x {close_txs} txs")
    overhead = bench_overhead(n_closes, close_txs)
    _note(f"  p50 off={overhead['close_p50_ms']['recorder_off']}ms "
          f"on={overhead['close_p50_ms']['recorder_on']}ms "
          f"overhead={overhead['overhead_pct_p50']}%")

    _note(f"induced-fork probe (seed {args.fork_seed}) x2 ...")
    probe = fork_probe(args.fork_seed, args.fork_duration)
    _note(f"  fork at slot {probe['divergence_slot']}, attributed to "
          f"{probe['first_divergence']['node']} "
          f"(byzantine={probe['byzantine']}), "
          f"rerun_identical={probe['rerun_dump_identical']}")

    doc = {
        "bench": "SCP forensics: recorder overhead + fork attribution",
        "mode": "smoke" if args.smoke else "full",
        "device": "cpu-fallback",
        "overhead": overhead,
        "fork_probe": probe,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    _note(f"wrote {args.out}")
    ok = (overhead["gate_overhead_lt_2pct"]
          and probe["attributed_to_byzantine"]
          and probe["rerun_dump_identical"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
