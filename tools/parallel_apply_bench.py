#!/usr/bin/env python
"""Native-apply A/B grid, rev r14 (ISSUE 13 acceptance): pay-heavy,
mixed, CREDIT-heavy and PATH-PAYMENT 1000-tx closes through the full
node close path, over a native-on/off x workers 0/2/4 grid — each grid
arm alternates with a plain-sequential close IN THE SAME SESSION so
ledger-state drift (book growth, bucket spills) hits both arms
equally.  Persists PARALLEL_APPLY_r14.json.

r10 proved the kernel thesis on native-only traffic (mixed 1000-tx
closes −50%) but the kernel declined every credit payment, trustline
op, path payment and offer modify back to Python — while real Stellar
traffic is credit-heavy.  This rev measures the kernel-complete strip:
credit payments + changeTrust (shape "credit") and 2-hop path payments
over seeded books (shape "pathpay") applied in-kernel, with the
per-op-type hit/decline taxonomy (apply.native.hit.<op> /
apply.native.decline.<op>.<reason>) persisted per row, and a parity
section holding header/bucket hashes AND meta bytes bit-identical to
the forced-Python arm across workers 0/2/4 and PYTHONHASHSEED 0/4242
(subprocess arms).

Env knobs: BENCH_CLOSES (per arm, default 6), BENCH_CLOSE_TXS
(default 1000), BENCH_DEX_PCT (default 30), BENCH_PARITY_CLOSES
(default 2).

Extra modes:
  --fingerprint SHAPE WORKERS NATIVE   print per-close fingerprints
      (subprocess arm of the parity/hash-seed evidence)
  --credit-smoke [--out PATH]          small credit+path parity smoke
      with a native hit-rate gate (verify_green's credit gate)
"""
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _note(msg):
    print(f"[parallel-apply-bench] {msg}", file=sys.stderr, flush=True)


def _mk_app(close_txs, workers, native):
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        UPGRADE_DESIRED_MAX_TX_SET_SIZE=max(100, close_txs),
        DEFERRED_GC=True,
        PARALLEL_APPLY_WORKERS=workers,
        NATIVE_APPLY=native,
        # workers<2 has no pool: the kernel applies clusters inline on
        # the close thread (the sequential-strip half of the claim)
        NATIVE_APPLY_INLINE=native and workers < 2))
    app.start()
    app.herder.manual_close()  # applies the max-tx-set-size upgrade
    return app


def _seed_shape(app, lg, shape, close_txs):
    """Workload seeding; pathpay needs maker offers closed for real."""
    lg.create_accounts(close_txs)
    if shape == "mixed":
        lg.setup_dex()
    elif shape == "credit":
        lg.setup_credit()
    elif shape == "pathpay":
        envs = lg.setup_path(hops=2, makers=8)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == len(envs), f"maker seeding: {admitted}"
        app.herder.manual_close()


def _generate(lg, shape, close_txs, dex_pct):
    if shape == "mixed":
        return lg.generate_mixed(close_txs, dex_percent=dex_pct)
    if shape == "credit":
        return lg.generate_credit_mix(close_txs, trust_pct=10)
    if shape == "pathpay":
        return lg.generate_path_payments(close_txs)
    return lg.generate_payments(close_txs)


def _native_taxonomy(app) -> dict:
    """The per-op-type hit/decline counters (executor breakout)."""
    out = {"hit": {}, "decline": {}}
    for name, m in sorted(app.metrics._metrics.items()):
        if name.startswith("apply.native.hit."):
            out["hit"][name[len("apply.native.hit."):]] = m.count
        elif name.startswith("apply.native.decline."):
            out["decline"][name[len("apply.native.decline."):]] = m.count
    return out


def bench_workload(shape: str, pattern: str, n_closes: int,
                   close_txs: int, dex_pct: int, workers: int,
                   native: bool) -> dict:
    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    app = _mk_app(close_txs, workers, native)
    lg = LoadGenerator(app)
    lg.payment_pattern = pattern
    _seed_shape(app, lg, shape, close_txs)
    arms = {"sequential": [], "grid": []}
    phases = {"sequential": [], "grid": []}
    plan_rows = []
    for i in range(2 * n_closes):
        arm = "grid" if i % 2 else "sequential"
        app.parallel_apply.enabled = (arm == "grid")
        envs = _generate(lg, shape, close_txs, dex_pct)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        t0 = time.perf_counter()
        app.herder.manual_close()
        arms[arm].append((time.perf_counter() - t0) * 1000.0)
        phases[arm].append(dict(app.ledger_manager.last_close_phases))
        if arm == "grid":
            plan_rows.append(dict(app.parallel_apply.last_plan_stats))
    stats = {k: v for k, v in app.parallel_apply.stats.items()
             if not isinstance(v, list)}
    stats["escape_reasons"] = app.parallel_apply.stats["escapes"][-4:]
    stats["decline_reasons"] = \
        app.parallel_apply.stats["native_decline_reasons"][-4:]
    taxonomy = _native_taxonomy(app)
    app.graceful_stop()

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 2)

    def p50(xs):
        return round(statistics.median(xs), 2) if xs else None

    def phase_p50(arm, name):
        vals = [row.get(name, 0.0) for row in phases[arm]
                if isinstance(row.get(name, 0.0), (int, float))]
        return round(statistics.median(vals), 2) if vals else None

    seq_p50, grid_p50 = p50(arms["sequential"]), p50(arms["grid"])
    clusters = stats["native_hits"] + stats["native_declines"] + \
        stats["native_off"]
    row = {
        "shape": shape,
        "pattern": pattern,
        "close_txs": close_txs,
        "closes_per_arm": n_closes,
        "workers": workers,
        "native": native,
        "seq_close_p50_ms": seq_p50,
        "grid_close_p50_ms": grid_p50,
        "grid_close_p99_ms": pct(arms["grid"], 0.99),
        "seq_close_p99_ms": pct(arms["sequential"], 0.99),
        "grid_vs_seq_pct": (
            round((grid_p50 - seq_p50) / seq_p50 * 100.0, 1)
            if seq_p50 else None),
        "seq_apply_p50_ms": phase_p50("sequential", "apply"),
        "grid_apply_p50_ms": phase_p50("grid", "apply"),
        "grid_plan_p50_ms": phase_p50("grid", "plan"),
        "native_hit_rate": (
            round(stats["native_hits"] / clusters, 4) if clusters else None),
        "native_taxonomy": taxonomy,
        "apply_stats": stats,
    }
    if plan_rows:
        def med(key):
            vals = [r.get(key) for r in plan_rows
                    if isinstance(r.get(key), (int, float))]
            return round(statistics.median(vals), 2) if vals else None

        row["plan"] = {
            "clusters_p50": med("clusters"),
            "kernel_clusters_p50": med("kernel_clusters"),
            "max_width_p50": med("max_width"),
            "conflict_rate_p50": med("conflict_rate"),
            "preplanned": any(r.get("preplanned") for r in plan_rows),
            "unplanned_reasons": sorted({
                r["unplanned"] for r in plan_rows if "unplanned" in r}),
        }
    _note(f"{shape}/{pattern} w={workers} native={int(native)}: "
          f"seq p50 {seq_p50}ms  grid p50 {grid_p50}ms "
          f"({row['grid_vs_seq_pct']}%)  aborts={stats['aborts']} "
          f"hit_rate={row['native_hit_rate']}")
    return row


# -- parity (fingerprints, subprocess hash-seed arms) -------------------------

def fingerprint_workload(shape: str, workers: int, native: bool,
                         n_closes: int, close_txs: int):
    """Per-close (ledger hash, bucket hash, sha256(meta)) fingerprints
    of a deterministic ``shape`` workload — the parity oracle."""
    import hashlib

    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.xdr import types as T

    app = _mk_app(close_txs, workers, native)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    _seed_shape(app, lg, shape, close_txs)
    fps = []

    def close():
        app.herder.manual_close()
        meta = app._meta_stream[-1] if app._meta_stream else None
        fps.append((
            app.ledger_manager.last_closed_hash().hex(),
            app.bucket_manager.get_bucket_list_hash().hex(),
            hashlib.sha256(T.LedgerCloseMeta.encode(meta)).hexdigest()
            if meta is not None else ""))

    for _ in range(n_closes):
        envs = _generate(lg, shape, close_txs, 30)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        close()
    stats = dict(app.parallel_apply.stats)
    app.graceful_stop()
    return fps, stats


def _subprocess_fingerprints(shape, workers, native, n_closes, close_txs,
                             hashseed) -> list:
    import subprocess

    env = dict(os.environ)
    env["PYTHONHASHSEED"] = str(hashseed)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["BENCH_PARITY_CLOSES"] = str(n_closes)
    env["BENCH_CLOSE_TXS"] = str(close_txs)
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "parallel_apply_bench.py"),
         "--fingerprint", shape, str(workers), str(int(native))],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-4000:]
    return [tuple(line.split()) for line in
            proc.stdout.strip().splitlines()]


def parity_report(shapes, n_closes, close_txs) -> dict:
    """Native-on fingerprints across workers 0/2/4 and PYTHONHASHSEED
    0/4242 (every arm a subprocess so the hash seed truly varies) must
    all equal the forced-Python baseline."""
    report = {"close_txs": close_txs, "closes": n_closes, "shapes": {}}
    identical = True
    for shape in shapes:
        base = _subprocess_fingerprints(shape, 0, False, n_closes,
                                        close_txs, 0)
        arms = {}
        for workers in (0, 2, 4):
            arms[f"native_w{workers}_seed0"] = _subprocess_fingerprints(
                shape, workers, True, n_closes, close_txs, 0)
        arms["native_w2_seed4242"] = _subprocess_fingerprints(
            shape, 2, True, n_closes, close_txs, 4242)
        arms["python_w2_seed4242"] = _subprocess_fingerprints(
            shape, 2, False, n_closes, close_txs, 4242)
        shape_ok = all(fp == base for fp in arms.values())
        identical = identical and shape_ok
        report["shapes"][shape] = {
            "identical": shape_ok,
            "arms": sorted(arms),
            "baseline_last_close": list(base[-1]) if base else None,
        }
        _note(f"parity {shape}: "
              f"{'identical' if shape_ok else 'DIVERGED'} over "
              f"{len(arms)} arms x {len(base)} closes")
    report["hashes_and_meta_identical"] = identical
    return report


# -- the verify_green credit gate ---------------------------------------------

def credit_smoke(out_path: str) -> int:
    """Small credit+path native-vs-Python parity + hit-rate gate:
    declines on the kernel-complete mixes are bugs now, so the smoke
    fails under a 0.9 native cluster-hit rate (ISSUE 13 acceptance)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n_closes = int(os.environ.get("BENCH_SMOKE_CLOSES", "2"))
    close_txs = int(os.environ.get("BENCH_SMOKE_CLOSE_TXS", "200"))
    report = {"metric": "native_credit_smoke", "close_txs": close_txs,
              "closes": n_closes, "shapes": {}}
    ok = True
    for shape in ("credit", "pathpay"):
        base, _ = fingerprint_workload(shape, 0, False, n_closes,
                                       close_txs)
        fps, stats = fingerprint_workload(shape, 2, True, n_closes,
                                          close_txs)
        clusters = stats["native_hits"] + stats["native_declines"] + \
            stats["native_off"]
        hit_rate = stats["native_hits"] / clusters if clusters else 0.0
        row = {
            "parity_identical": fps == base,
            "native_hit_rate": round(hit_rate, 4),
            "aborts": stats["aborts"],
            "native_hits": stats["native_hits"],
            "native_declines": stats["native_declines"],
            "decline_reasons":
                stats["native_decline_reasons"][-4:],
        }
        row["ok"] = (row["parity_identical"] and row["aborts"] == 0
                     and hit_rate >= 0.9)
        ok = ok and row["ok"]
        report["shapes"][shape] = row
        _note(f"credit-smoke {shape}: parity="
              f"{row['parity_identical']} hit_rate={row['native_hit_rate']}"
              f" aborts={row['aborts']} -> {'ok' if row['ok'] else 'RED'}")
    report["ok"] = ok
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    return 0 if ok else 1


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if "--fingerprint" in sys.argv:
        i = sys.argv.index("--fingerprint")
        shape, workers, native = (sys.argv[i + 1], int(sys.argv[i + 2]),
                                  bool(int(sys.argv[i + 3])))
        n_closes = int(os.environ.get("BENCH_PARITY_CLOSES", "2"))
        close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
        fps, _ = fingerprint_workload(shape, workers, native, n_closes,
                                      close_txs)
        for lh, bh, mh in fps:
            print(lh, bh, mh)
        return

    if "--credit-smoke" in sys.argv:
        out = "/tmp/_native_credit_smoke.json"
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        sys.exit(credit_smoke(out))

    n_closes = int(os.environ.get("BENCH_CLOSES", "6"))
    close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
    dex_pct = int(os.environ.get("BENCH_DEX_PCT", "30"))
    parity_closes = int(os.environ.get("BENCH_PARITY_CLOSES", "2"))

    rows = []
    # the r10 grid rides along for trend continuity
    for shape in ("pay", "mixed"):
        for workers, native in ((0, True), (2, True), (4, True),
                                (2, False), (4, False)):
            rows.append(bench_workload(shape, "pairs", n_closes,
                                       close_txs, dex_pct, workers,
                                       native))
    # the ISSUE-13 grids: native on/off x workers 0/2/4, same-session
    for shape in ("credit", "pathpay"):
        for workers, native in ((0, True), (2, True), (4, True),
                                (0, False), (2, False), (4, False)):
            rows.append(bench_workload(shape, "pairs", n_closes,
                                       close_txs, dex_pct, workers,
                                       native))
    # the adversarial shape: one fully-connected payment ring
    for workers, native in ((0, True), (2, True)):
        rows.append(bench_workload("pay", "ring", max(3, n_closes // 2),
                                   close_txs, dex_pct, workers, native))

    parity = parity_report(("credit", "pathpay"), parity_closes,
                           close_txs)

    total_aborts = sum(r["apply_stats"]["aborts"] for r in rows)

    def find(shape, workers, native):
        for r in rows:
            if (r["shape"], r["workers"], r["native"]) == \
                    (shape, workers, native):
                return r
        return None

    credit_on = find("credit", 4, True)
    credit_off = find("credit", 4, False)
    path_on = find("pathpay", 4, True)
    path_off = find("pathpay", 4, False)

    def vs(on, off, key="grid_close_p50_ms"):
        if not (on and off and on.get(key) and off.get(key)):
            return None
        return round((on[key] - off[key]) / off[key] * 100.0, 1)

    out = {
        "metric": "parallel_apply_native_ab_r14",
        "workloads": rows,
        "aborts_total": total_aborts,
        "parity": parity,
        "headline": {
            "credit_w4_native_p50_ms": credit_on["grid_close_p50_ms"],
            "credit_w4_python_p50_ms": credit_off["grid_close_p50_ms"],
            "credit_w4_native_vs_python_pct": vs(credit_on, credit_off),
            # the apply close-phase A/B (the phase the kernel owns;
            # verify/fee/bucket/hash/commit ride along unchanged in
            # the whole-close number)
            "credit_w4_apply_phase_native_vs_python_pct":
                vs(credit_on, credit_off, "grid_apply_p50_ms"),
            "credit_native_hit_rate": credit_on["native_hit_rate"],
            "pathpay_w4_native_p50_ms": path_on["grid_close_p50_ms"],
            "pathpay_w4_python_p50_ms": path_off["grid_close_p50_ms"],
            "pathpay_w4_native_vs_python_pct": vs(path_on, path_off),
            "pathpay_w4_apply_phase_native_vs_python_pct":
                vs(path_on, path_off, "grid_apply_p50_ms"),
            "pathpay_native_hit_rate": path_on["native_hit_rate"],
        },
        "honest_breakdown": {
            "kernel": "the kernel-complete strip (native+credit "
                      "payments, changeTrust create/update/delete, "
                      "manage_sell_offer create/modify/delete, path "
                      "payments strict-send/receive over declared hop "
                      "pairs) applies inside native/apply_kernel.cpp "
                      "with the GIL RELEASED; unsupported shapes "
                      "(pool-share lines, live pools on a hop, "
                      "sponsored entries, multisig...) decline back to "
                      "the Python reference apply, now attributed per "
                      "op-type x reason in native_taxonomy.",
            "parity": "header/bucket hashes and meta bytes are "
                      "bit-identical native-vs-Python across workers "
                      "0/2/4 and PYTHONHASHSEED 0/4242 (subprocess "
                      "arms; the parity section above), and "
                      "tests/test_native_apply.py holds the same "
                      "property per op family.",
            "conflict_shapes": "credit mixes plan disjoint "
                               "trustline-pair clusters (workers "
                               "spread them; batched kernel crossings "
                               "amortize dispatch); path payments "
                               "share their hop book-pairs so a close "
                               "collapses into ONE cluster applied "
                               "inline by the kernel — the win there "
                               "is the GIL-free strip itself, not "
                               "parallelism.",
            "native_off_arms": "the native=false columns run the SAME "
                               "planner/executor with Python workers — "
                               "the r09 GIL verdict reproduced on the "
                               "new workloads for comparison.",
        },
    }
    path = os.path.join(REPO, "PARALLEL_APPLY_r14.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _note(f"persisted {path}")
    print(json.dumps({"metric": out["metric"],
                      "aborts_total": total_aborts,
                      "parity_identical":
                          parity["hashes_and_meta_identical"],
                      "headline": out["headline"],
                      "workloads": [
                          {k: r[k] for k in ("shape", "pattern",
                                             "workers", "native",
                                             "seq_close_p50_ms",
                                             "grid_close_p50_ms",
                                             "grid_vs_seq_pct",
                                             "native_hit_rate")}
                          for r in rows]}))


if __name__ == "__main__":
    main()
