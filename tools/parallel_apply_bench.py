#!/usr/bin/env python
"""Same-session sequential-vs-parallel apply A/B (ISSUE 5 acceptance):
pay-heavy and mixed 1000-tx closes through the full node close path,
alternating the parallel executor on/off per close so ledger-state
drift (book growth, bucket spills) hits both arms equally.  Persists
PARALLEL_APPLY_r09.json.

The honest part: on CPython the GIL serializes the executor's Python
work, so the A/B reports WHERE the time goes (plan cost and its
nomination-time cache, the per-get speculation-guard tax inside
frame.apply, the worker-side xdrpack encode relocation and what it
saves in the hash/commit phases) rather than pretending a wall-clock
win the interpreter cannot deliver.  Abort count on the standard
workloads must be 0.

Env knobs: BENCH_CLOSES (per arm, default 10), BENCH_CLOSE_TXS
(default 1000), BENCH_DEX_PCT (default 30), BENCH_WORKERS (default 2).
"""
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _note(msg):
    print(f"[parallel-apply-bench] {msg}", file=sys.stderr, flush=True)


def bench_workload(shape: str, pattern: str, n_closes: int,
                   close_txs: int, dex_pct: int, workers: int) -> dict:
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        UPGRADE_DESIRED_MAX_TX_SET_SIZE=max(100, close_txs),
        DEFERRED_GC=True,
        PARALLEL_APPLY_WORKERS=workers))
    app.start()
    app.herder.manual_close()  # applies the max-tx-set-size upgrade
    lg = LoadGenerator(app)
    lg.payment_pattern = pattern
    lg.create_accounts(close_txs)
    if shape == "mixed":
        lg.setup_dex()
    arms = {"sequential": [], "parallel": []}
    phases = {"sequential": [], "parallel": []}
    plan_rows = []
    for i in range(2 * n_closes):
        arm = "parallel" if i % 2 else "sequential"
        app.parallel_apply.enabled = (arm == "parallel")
        envs = (lg.generate_mixed(close_txs, dex_percent=dex_pct)
                if shape == "mixed" else lg.generate_payments(close_txs))
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        t0 = time.perf_counter()
        app.herder.manual_close()
        arms[arm].append((time.perf_counter() - t0) * 1000.0)
        phases[arm].append(dict(app.ledger_manager.last_close_phases))
        if arm == "parallel":
            plan_rows.append(dict(app.parallel_apply.last_plan_stats))
    stats = {k: v for k, v in app.parallel_apply.stats.items()
             if k != "escapes"}
    stats["escape_reasons"] = app.parallel_apply.stats["escapes"][-4:]
    app.graceful_stop()

    def p50(xs):
        return round(statistics.median(xs), 2) if xs else None

    def phase_p50(arm, name):
        vals = [row.get(name, 0.0) for row in phases[arm]
                if isinstance(row.get(name, 0.0), (int, float))]
        return round(statistics.median(vals), 2) if vals else None

    seq_p50, par_p50 = p50(arms["sequential"]), p50(arms["parallel"])
    row = {
        "shape": shape,
        "pattern": pattern,
        "close_txs": close_txs,
        "closes_per_arm": n_closes,
        "workers": workers,
        "seq_close_p50_ms": seq_p50,
        "par_close_p50_ms": par_p50,
        "par_vs_seq_pct": (round((par_p50 - seq_p50) / seq_p50 * 100.0, 1)
                           if seq_p50 else None),
        "seq_apply_p50_ms": phase_p50("sequential", "apply"),
        "par_apply_p50_ms": phase_p50("parallel", "apply"),
        "par_plan_p50_ms": phase_p50("parallel", "plan"),
        "seq_hash_commit_p50_ms": round(
            (phase_p50("sequential", "hash") or 0)
            + (phase_p50("sequential", "commit") or 0), 2),
        "par_hash_commit_p50_ms": round(
            (phase_p50("parallel", "hash") or 0)
            + (phase_p50("parallel", "commit") or 0), 2),
        "apply_stats": stats,
    }
    if plan_rows:
        def med(key):
            vals = [r.get(key) for r in plan_rows
                    if isinstance(r.get(key), (int, float))]
            return round(statistics.median(vals), 2) if vals else None

        row["plan"] = {
            "clusters_p50": med("clusters"),
            "max_width_p50": med("max_width"),
            "conflict_rate_p50": med("conflict_rate"),
            "native_encode_ms_p50": med("native_encode_ms"),
            "preplanned": any(r.get("preplanned") for r in plan_rows),
            "unplanned_reasons": sorted({
                r["unplanned"] for r in plan_rows if "unplanned" in r}),
        }
    _note(f"{shape}/{pattern}: seq p50 {seq_p50}ms  par p50 {par_p50}ms "
          f"({row['par_vs_seq_pct']}%)  aborts={stats['aborts']}")
    return row


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n_closes = int(os.environ.get("BENCH_CLOSES", "10"))
    close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
    dex_pct = int(os.environ.get("BENCH_DEX_PCT", "30"))
    workers = int(os.environ.get("BENCH_WORKERS", "2"))

    rows = [
        bench_workload("pay", "pairs", n_closes, close_txs, dex_pct,
                       workers),
        bench_workload("mixed", "pairs", n_closes, close_txs, dex_pct,
                       workers),
        # the adversarial shape: one fully-connected payment ring — the
        # planner must refuse it (single cluster) and the only cost is
        # nomination-time planning
        bench_workload("pay", "ring", max(3, n_closes // 2), close_txs,
                       dex_pct, workers),
    ]
    total_aborts = sum(r["apply_stats"]["aborts"] for r in rows)
    out = {
        "metric": "parallel_apply_ab_r09",
        "workloads": rows,
        "aborts_total": total_aborts,
        "honest_breakdown": {
            "gil": "CPython's GIL serializes the executor's Python "
                   "apply work, so concurrent clusters time-slice one "
                   "interpreter; the measured parallel overhead is the "
                   "speculation guard's per-access checks plus worker "
                   "scheduling, NOT contention on ledger state "
                   "(clusters are disjoint by construction).",
            "plan_cost": "planning runs at nomination time and is "
                         "cached by (tx-set hash, LCL hash) — "
                         "preplan_hits in apply_stats shows the close "
                         "path consuming cached plans (plan phase "
                         "~0 ms).",
            "native_overlap": "workers pre-encode TransactionMeta / "
                              "TransactionResultPair / envelope bytes "
                              "(native xdrpack) during apply; the "
                              "hash phase then assembles the result-"
                              "set hash from those bytes and the "
                              "commit phase reuses them for tx-history "
                              "rows — compare seq_hash_commit_p50_ms "
                              "vs par_hash_commit_p50_ms.  xdrpack "
                              "walks Python objects and cannot drop "
                              "the GIL, so this is relocation+reuse, "
                              "not overlap; a free-threaded build "
                              "would turn the same seams into real "
                              "concurrency.",
            "bit_identity": "tests/test_parallel_apply.py holds the "
                            "byte-identity property across worker "
                            "counts and PYTHONHASHSEED values; the "
                            "escape-abort fallback is exercised there "
                            "too.",
        },
    }
    path = os.path.join(REPO, "PARALLEL_APPLY_r09.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _note(f"persisted {path}")
    print(json.dumps({"metric": out["metric"],
                      "aborts_total": total_aborts,
                      "workloads": [
                          {k: r[k] for k in ("shape", "pattern",
                                             "seq_close_p50_ms",
                                             "par_close_p50_ms",
                                             "par_vs_seq_pct")}
                          for r in rows]}))


if __name__ == "__main__":
    main()
