#!/usr/bin/env python
"""Native-apply A/B grid, rev r16 (ISSUE 16 acceptance): pay-heavy,
mixed, PATH-PAYMENT and live-POOL 1000-tx closes through the full node
close path, over a workers 0/1/2/4 x fee-kernel on/off x
PIPELINED_CLOSE on/off grid — each grid arm alternates with a
plain-sequential close IN THE SAME SESSION so ledger-state drift (book
growth, bucket spills) hits both arms equally.  Persists
PARALLEL_APPLY_r16.json.

r14 proved the kernel-complete per-op strip (100% native hit rate,
path closes −67% apply-phase) but the surrounding close phases stayed
Python, so workers=4 plateaued near −50% whole-close.  This rev
measures the ISSUE-16 strip: the batched ``charge_fees`` kernel entry
(one GIL-released call for the whole fee/seqnum phase — NATIVE_FEE=0
is the off arm), in-kernel constant-product pool quoting (shape
"pool": every path payment crosses a LIVE pool — the r14
decline-if-live-pool cliff), and the native tail encode riding the
pipelined arms.  The scaling summary reports the workers=4/workers=1
whole-close speedup per (shape, fee, pipelined) combo and FLAGS any
combo under 2x as a regression note.

Env knobs: BENCH_CLOSES (per arm, default 3), BENCH_CLOSE_TXS
(default 1000), BENCH_DEX_PCT (default 30), BENCH_PARITY_CLOSES
(default 2).

Extra modes:
  --fingerprint SHAPE WORKERS NATIVE   print per-close fingerprints
      (subprocess arm of the parity/hash-seed evidence)
  --credit-smoke [--out PATH]          small credit+path parity smoke
      with a native hit-rate gate (verify_green's credit gate)
  --fee-smoke [--out PATH]             NATIVE_FEE on/off parity smoke
      with a fee-batch hit-rate gate (verify_green's fee gate)
"""
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _note(msg):
    print(f"[parallel-apply-bench] {msg}", file=sys.stderr, flush=True)


def _mk_app(close_txs, workers, native, fee=True, pipelined=False):
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    kw = {}
    if pipelined:
        kw["PIPELINED_CLOSE"] = True
        kw["PIPELINED_CLOSE_EAGER_DRAIN"] = False  # real overlap
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        UPGRADE_DESIRED_MAX_TX_SET_SIZE=max(100, close_txs),
        DEFERRED_GC=True,
        PARALLEL_APPLY_WORKERS=workers,
        NATIVE_APPLY=native,
        NATIVE_FEE=native and fee,
        # workers<2 has no pool: the kernel applies clusters inline on
        # the close thread (the sequential-strip half of the claim)
        NATIVE_APPLY_INLINE=native and workers < 2,
        **kw))
    app.start()
    app.herder.manual_close()  # applies the max-tx-set-size upgrade
    return app


def _seed_shape(app, lg, shape, close_txs):
    """Workload seeding; pathpay needs maker offers closed for real."""
    lg.create_accounts(close_txs)
    if shape == "mixed":
        lg.setup_dex()
    elif shape == "credit":
        lg.setup_credit()
    elif shape == "pathpay":
        envs = lg.setup_path(hops=2, makers=8)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == len(envs), f"maker seeding: {admitted}"
        app.herder.manual_close()
    elif shape == "pool":
        # live constant-product pools on every hop pair — the traffic
        # the r14 kernel declined wholesale (decline-if-live-pool)
        lg.setup_pool(hops=2)


def _generate(lg, shape, close_txs, dex_pct):
    if shape == "mixed":
        return lg.generate_mixed(close_txs, dex_percent=dex_pct)
    if shape == "credit":
        return lg.generate_credit_mix(close_txs, trust_pct=10)
    if shape == "pathpay":
        return lg.generate_path_payments(close_txs)
    if shape == "pool":
        return lg.generate_pool_payments(close_txs)
    return lg.generate_payments(close_txs)


def _native_taxonomy(app) -> dict:
    """The per-op-type hit/decline counters (executor breakout)."""
    out = {"hit": {}, "decline": {}}
    for name, m in sorted(app.metrics._metrics.items()):
        if name.startswith("apply.native.hit."):
            out["hit"][name[len("apply.native.hit."):]] = m.count
        elif name.startswith("apply.native.decline."):
            out["decline"][name[len("apply.native.decline."):]] = m.count
    return out


def bench_workload(shape: str, pattern: str, n_closes: int,
                   close_txs: int, dex_pct: int, workers: int,
                   native: bool, fee: bool = True,
                   pipelined: bool = False) -> dict:
    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    app = _mk_app(close_txs, workers, native, fee=fee,
                  pipelined=pipelined)
    lg = LoadGenerator(app)
    lg.payment_pattern = pattern
    _seed_shape(app, lg, shape, close_txs)
    arms = {"sequential": [], "grid": []}
    phases = {"sequential": [], "grid": []}
    plan_rows = []
    for i in range(2 * n_closes):
        arm = "grid" if i % 2 else "sequential"
        app.parallel_apply.enabled = (arm == "grid")
        envs = _generate(lg, shape, close_txs, dex_pct)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        t0 = time.perf_counter()
        app.herder.manual_close()
        arms[arm].append((time.perf_counter() - t0) * 1000.0)
        phases[arm].append(dict(app.ledger_manager.last_close_phases))
        if arm == "grid":
            plan_rows.append(dict(app.parallel_apply.last_plan_stats))
    stats = {k: v for k, v in app.parallel_apply.stats.items()
             if not isinstance(v, list)}
    stats["escape_reasons"] = app.parallel_apply.stats["escapes"][-4:]
    stats["decline_reasons"] = \
        app.parallel_apply.stats["native_decline_reasons"][-4:]
    taxonomy = _native_taxonomy(app)
    fee_counters = {
        name[len("apply.native.fee."):]: m.count
        for name, m in sorted(app.metrics._metrics.items())
        if name.startswith("apply.native.fee.") and m.count}
    tail_hits = 0
    m = app.metrics._metrics.get("apply.native.tail_encode.hit")
    if m is not None:
        tail_hits = m.count
    app.graceful_stop()

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 2)

    def p50(xs):
        return round(statistics.median(xs), 2) if xs else None

    def phase_p50(arm, name):
        vals = [row.get(name, 0.0) for row in phases[arm]
                if isinstance(row.get(name, 0.0), (int, float))]
        return round(statistics.median(vals), 2) if vals else None

    seq_p50, grid_p50 = p50(arms["sequential"]), p50(arms["grid"])
    clusters = stats["native_hits"] + stats["native_declines"] + \
        stats["native_off"]
    row = {
        "shape": shape,
        "pattern": pattern,
        "close_txs": close_txs,
        "closes_per_arm": n_closes,
        "workers": workers,
        "native": native,
        "fee_kernel": native and fee,
        "pipelined": pipelined,
        "seq_close_p50_ms": seq_p50,
        "grid_close_p50_ms": grid_p50,
        "grid_close_p99_ms": pct(arms["grid"], 0.99),
        "seq_close_p99_ms": pct(arms["sequential"], 0.99),
        "grid_vs_seq_pct": (
            round((grid_p50 - seq_p50) / seq_p50 * 100.0, 1)
            if seq_p50 else None),
        "seq_apply_p50_ms": phase_p50("sequential", "apply"),
        "grid_apply_p50_ms": phase_p50("grid", "apply"),
        "grid_plan_p50_ms": phase_p50("grid", "plan"),
        "seq_fee_phase_p50_ms": phase_p50("sequential", "fee"),
        "grid_fee_phase_p50_ms": phase_p50("grid", "fee"),
        "grid_tail_wait_p50_ms": phase_p50("grid", "tail_wait"),
        "fee_batch": fee_counters,
        "tail_encode_hits": tail_hits,
        "native_hit_rate": (
            round(stats["native_hits"] / clusters, 4) if clusters else None),
        "native_taxonomy": taxonomy,
        "apply_stats": stats,
    }
    if plan_rows:
        def med(key):
            vals = [r.get(key) for r in plan_rows
                    if isinstance(r.get(key), (int, float))]
            return round(statistics.median(vals), 2) if vals else None

        row["plan"] = {
            "clusters_p50": med("clusters"),
            "kernel_clusters_p50": med("kernel_clusters"),
            "max_width_p50": med("max_width"),
            "conflict_rate_p50": med("conflict_rate"),
            "preplanned": any(r.get("preplanned") for r in plan_rows),
            "unplanned_reasons": sorted({
                r["unplanned"] for r in plan_rows if "unplanned" in r}),
        }
    _note(f"{shape}/{pattern} w={workers} native={int(native)} "
          f"fee={int(fee)} pipe={int(pipelined)}: "
          f"seq p50 {seq_p50}ms  grid p50 {grid_p50}ms "
          f"({row['grid_vs_seq_pct']}%)  aborts={stats['aborts']} "
          f"hit_rate={row['native_hit_rate']}")
    return row


# -- parity (fingerprints, subprocess hash-seed arms) -------------------------

def fingerprint_workload(shape: str, workers: int, native: bool,
                         n_closes: int, close_txs: int, fee: bool = True):
    """Per-close (ledger hash, bucket hash, sha256(meta)) fingerprints
    of a deterministic ``shape`` workload — the parity oracle."""
    import hashlib

    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.xdr import types as T

    app = _mk_app(close_txs, workers, native, fee=fee)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    _seed_shape(app, lg, shape, close_txs)
    fps = []

    def close():
        app.herder.manual_close()
        meta = app._meta_stream[-1] if app._meta_stream else None
        fps.append((
            app.ledger_manager.last_closed_hash().hex(),
            app.bucket_manager.get_bucket_list_hash().hex(),
            hashlib.sha256(T.LedgerCloseMeta.encode(meta)).hexdigest()
            if meta is not None else ""))

    for _ in range(n_closes):
        envs = _generate(lg, shape, close_txs, 30)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        close()
    stats = dict(app.parallel_apply.stats)
    stats["fee_batch"] = {
        name[len("apply.native.fee."):]: m.count
        for name, m in sorted(app.metrics._metrics.items())
        if name.startswith("apply.native.fee.") and m.count}
    app.graceful_stop()
    return fps, stats


def _subprocess_fingerprints(shape, workers, native, n_closes, close_txs,
                             hashseed) -> list:
    import subprocess

    env = dict(os.environ)
    env["PYTHONHASHSEED"] = str(hashseed)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["BENCH_PARITY_CLOSES"] = str(n_closes)
    env["BENCH_CLOSE_TXS"] = str(close_txs)
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "parallel_apply_bench.py"),
         "--fingerprint", shape, str(workers), str(int(native))],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-4000:]
    return [tuple(line.split()) for line in
            proc.stdout.strip().splitlines()]


def parity_report(shapes, n_closes, close_txs) -> dict:
    """Native-on fingerprints across workers 0/2/4 and PYTHONHASHSEED
    0/4242 (every arm a subprocess so the hash seed truly varies) must
    all equal the forced-Python baseline."""
    report = {"close_txs": close_txs, "closes": n_closes, "shapes": {}}
    identical = True
    for shape in shapes:
        base = _subprocess_fingerprints(shape, 0, False, n_closes,
                                        close_txs, 0)
        arms = {}
        for workers in (0, 2, 4):
            arms[f"native_w{workers}_seed0"] = _subprocess_fingerprints(
                shape, workers, True, n_closes, close_txs, 0)
        arms["native_w2_seed4242"] = _subprocess_fingerprints(
            shape, 2, True, n_closes, close_txs, 4242)
        arms["python_w2_seed4242"] = _subprocess_fingerprints(
            shape, 2, False, n_closes, close_txs, 4242)
        shape_ok = all(fp == base for fp in arms.values())
        identical = identical and shape_ok
        report["shapes"][shape] = {
            "identical": shape_ok,
            "arms": sorted(arms),
            "baseline_last_close": list(base[-1]) if base else None,
        }
        _note(f"parity {shape}: "
              f"{'identical' if shape_ok else 'DIVERGED'} over "
              f"{len(arms)} arms x {len(base)} closes")
    report["hashes_and_meta_identical"] = identical
    return report


# -- the verify_green credit gate ---------------------------------------------

def credit_smoke(out_path: str) -> int:
    """Small credit+path native-vs-Python parity + hit-rate gate:
    declines on the kernel-complete mixes are bugs now, so the smoke
    fails under a 0.9 native cluster-hit rate (ISSUE 13 acceptance)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n_closes = int(os.environ.get("BENCH_SMOKE_CLOSES", "2"))
    close_txs = int(os.environ.get("BENCH_SMOKE_CLOSE_TXS", "200"))
    report = {"metric": "native_credit_smoke", "close_txs": close_txs,
              "closes": n_closes, "shapes": {}}
    ok = True
    for shape in ("credit", "pathpay"):
        base, _ = fingerprint_workload(shape, 0, False, n_closes,
                                       close_txs)
        fps, stats = fingerprint_workload(shape, 2, True, n_closes,
                                          close_txs)
        clusters = stats["native_hits"] + stats["native_declines"] + \
            stats["native_off"]
        hit_rate = stats["native_hits"] / clusters if clusters else 0.0
        row = {
            "parity_identical": fps == base,
            "native_hit_rate": round(hit_rate, 4),
            "aborts": stats["aborts"],
            "native_hits": stats["native_hits"],
            "native_declines": stats["native_declines"],
            "decline_reasons":
                stats["native_decline_reasons"][-4:],
        }
        row["ok"] = (row["parity_identical"] and row["aborts"] == 0
                     and hit_rate >= 0.9)
        ok = ok and row["ok"]
        report["shapes"][shape] = row
        _note(f"credit-smoke {shape}: parity="
              f"{row['parity_identical']} hit_rate={row['native_hit_rate']}"
              f" aborts={row['aborts']} -> {'ok' if row['ok'] else 'RED'}")
    report["ok"] = ok
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    return 0 if ok else 1


def fee_smoke(out_path: str) -> int:
    """The ISSUE-16 fee-phase gate: a mixed workload with the batched
    ``charge_fees`` kernel on vs ``NATIVE_FEE=0`` must close
    bit-identical (hashes AND meta), and the fee batch must actually
    carry the phase — hit rate >= 0.9 of closes (a whole-batch decline
    on clean traffic is a bug now)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n_closes = int(os.environ.get("BENCH_SMOKE_CLOSES", "2"))
    close_txs = int(os.environ.get("BENCH_SMOKE_CLOSE_TXS", "200"))
    base, _ = fingerprint_workload("mixed", 2, True, n_closes,
                                   close_txs, fee=False)
    fps, stats = fingerprint_workload("mixed", 2, True, n_closes,
                                      close_txs, fee=True)
    fees = stats.get("fee_batch", {})
    hits, declines = fees.get("hit", 0), fees.get("decline", 0)
    batches = hits + declines
    hit_rate = hits / batches if batches else 0.0
    report = {
        "metric": "native_fee_smoke",
        "close_txs": close_txs,
        "closes": n_closes,
        "parity_identical": fps == base,
        "fee_batch_hit_rate": round(hit_rate, 4),
        "fee_batch": fees,
        "ok": fps == base and batches > 0 and hit_rate >= 0.9,
    }
    _note(f"fee-smoke: parity={report['parity_identical']} "
          f"hit_rate={report['fee_batch_hit_rate']} "
          f"({hits} hits / {declines} declines) -> "
          f"{'ok' if report['ok'] else 'RED'}")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    return 0 if report["ok"] else 1


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if "--fingerprint" in sys.argv:
        i = sys.argv.index("--fingerprint")
        shape, workers, native = (sys.argv[i + 1], int(sys.argv[i + 2]),
                                  bool(int(sys.argv[i + 3])))
        n_closes = int(os.environ.get("BENCH_PARITY_CLOSES", "2"))
        close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
        fps, _ = fingerprint_workload(shape, workers, native, n_closes,
                                      close_txs)
        for lh, bh, mh in fps:
            print(lh, bh, mh)
        return

    if "--credit-smoke" in sys.argv:
        out = "/tmp/_native_credit_smoke.json"
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        sys.exit(credit_smoke(out))

    if "--fee-smoke" in sys.argv:
        out = "/tmp/_native_fee_smoke.json"
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        sys.exit(fee_smoke(out))

    n_closes = int(os.environ.get("BENCH_CLOSES", "3"))
    close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
    dex_pct = int(os.environ.get("BENCH_DEX_PCT", "30"))
    parity_closes = int(os.environ.get("BENCH_PARITY_CLOSES", "2"))

    shapes = ("pay", "mixed", "pathpay", "pool")
    rows = []
    # the r16 scaling curve: workers 0/1/2/4, fee kernel on, no
    # pipeline — the "one planner pass + N GIL-free kernel calls" claim
    for shape in shapes:
        for workers in (0, 1, 2, 4):
            rows.append(bench_workload(shape, "pairs", n_closes,
                                       close_txs, dex_pct, workers,
                                       True))
    # the fee/pipeline cross at the scaling endpoints (workers 1 and
    # 4): fee-kernel on/off x PIPELINED_CLOSE on/off — the fee=on/
    # pipe=off corner is already in the curve above
    for shape in shapes:
        for fee, pipelined in ((True, True), (False, False),
                               (False, True)):
            for workers in (1, 4):
                rows.append(bench_workload(
                    shape, "pairs", n_closes, close_txs, dex_pct,
                    workers, True, fee=fee, pipelined=pipelined))
    # pipelined workers=2 arms: the PIPELINE_BENCH_r12 same-shape
    # comparison point (r12 ran its tail_wait numbers at workers=2)
    for shape in ("pay", "mixed"):
        rows.append(bench_workload(shape, "pairs", n_closes, close_txs,
                                   dex_pct, 2, True, pipelined=True))
    # forced-Python reference arms at workers=4 (the r14 A/B column)
    for shape in shapes:
        rows.append(bench_workload(shape, "pairs", n_closes, close_txs,
                                   dex_pct, 4, False))

    parity = parity_report(("pathpay", "pool"), parity_closes,
                           close_txs)

    total_aborts = sum(r["apply_stats"]["aborts"] for r in rows)

    def find(shape, workers, native, fee=True, pipelined=False):
        for r in rows:
            if (r["shape"], r["workers"], r["native"], r["fee_kernel"],
                    r["pipelined"]) == (shape, workers, native,
                                        native and fee, pipelined):
                return r
        return None

    def vs(on, off, key="grid_close_p50_ms"):
        if not (on and off and on.get(key) and off.get(key)):
            return None
        return round((on[key] - off[key]) / off[key] * 100.0, 1)

    # workers=4 / workers=1 whole-close speedup per combo; < 2x is a
    # flagged regression note (the ISSUE-16 scaling gate)
    scaling = []
    regression_notes = []
    for shape in shapes:
        for fee, pipelined in ((True, False), (True, True),
                               (False, False), (False, True)):
            w1 = find(shape, 1, True, fee, pipelined)
            w4 = find(shape, 4, True, fee, pipelined)
            if not (w1 and w1.get("grid_close_p50_ms")
                    and w4 and w4.get("grid_close_p50_ms")):
                continue
            speedup = round(
                w1["grid_close_p50_ms"] / w4["grid_close_p50_ms"], 2)
            # raw cross-arm p50s drift (later arms in one process run
            # on a warmer, bigger heap) — normalising each arm by its
            # OWN same-session sequential baseline keeps the scaling
            # claim honest
            norm = None
            if (w1.get("seq_close_p50_ms") and w4.get("seq_close_p50_ms")
                    and w4["grid_close_p50_ms"]):
                norm = round(
                    (w1["grid_close_p50_ms"] / w1["seq_close_p50_ms"])
                    / (w4["grid_close_p50_ms"]
                       / w4["seq_close_p50_ms"]), 2)
            entry = {"shape": shape, "fee_kernel": fee,
                     "pipelined": pipelined,
                     "w1_close_p50_ms": w1["grid_close_p50_ms"],
                     "w4_close_p50_ms": w4["grid_close_p50_ms"],
                     "w4_vs_w1_speedup": speedup,
                     "w4_vs_w1_speedup_seq_normalized": norm,
                     "under_2x": speedup < 2.0}
            scaling.append(entry)
            if speedup < 2.0:
                regression_notes.append(
                    f"{shape} fee={int(fee)} pipe={int(pipelined)}: "
                    f"workers=4/workers=1 speedup {speedup}x < 2x "
                    f"(w1 {w1['grid_close_p50_ms']}ms -> "
                    f"w4 {w4['grid_close_p50_ms']}ms, "
                    f"seq-normalized {norm}x)")

    if regression_notes and (os.cpu_count() or 1) < 4:
        regression_notes.insert(0, (
            f"context: host has cpu_count={os.cpu_count()} — "
            f"workers=4 cannot out-schedule workers=1 on wall-clock "
            f"here; judge the kernel by grid_vs_seq_pct per arm"))

    # trend vs the r14 artifact (same-session seq baselines in both
    # revs keep machine drift honest: compare grid_vs_seq_pct too)
    r14_cmp = {}
    try:
        with open(os.path.join(REPO, "PARALLEL_APPLY_r14.json")) as f:
            r14 = json.load(f)
        for shape in ("pay", "mixed", "pathpay"):
            old = next((r for r in r14["workloads"]
                        if (r["shape"], r["workers"], r["native"],
                            r.get("pattern")) ==
                        (shape, 4, True, "pairs")), None)
            new = find(shape, 4, True)
            if old and new and old.get("grid_close_p50_ms"):
                r14_cmp[shape] = {
                    "r14_w4_close_p50_ms": old["grid_close_p50_ms"],
                    "r16_w4_close_p50_ms": new["grid_close_p50_ms"],
                    "r14_grid_vs_seq_pct": old.get("grid_vs_seq_pct"),
                    "r16_grid_vs_seq_pct": new.get("grid_vs_seq_pct"),
                    "delta_pct": vs(new, old),
                }
    except (OSError, ValueError, KeyError) as e:
        r14_cmp["unavailable"] = str(e)

    # the pipelined tail: tail_wait at workers=2 vs PIPELINE_BENCH_r12
    r12_cmp = {}
    try:
        with open(os.path.join(REPO, "PIPELINE_BENCH_r12.json")) as f:
            r12 = json.load(f)
        for shape in ("pay", "mixed"):
            old = next((r for r in r12["workloads"]
                        if r["shape"] == shape), None)
            new = find(shape, 2, True, True, True)
            if old and new:
                r12_cmp[shape] = {
                    "r12_tail_wait_p50_ms": old.get("tail_wait_p50_ms"),
                    "r16_tail_wait_p50_ms":
                        new.get("grid_tail_wait_p50_ms"),
                    "r16_tail_encode_hits": new.get("tail_encode_hits"),
                }
    except (OSError, ValueError, KeyError) as e:
        r12_cmp["unavailable"] = str(e)

    pool_w4 = find("pool", 4, True)
    mixed_w4_fee = find("mixed", 4, True)
    mixed_w4_nofee = find("mixed", 4, True, fee=False)

    out = {
        "metric": "parallel_apply_native_ab_r16",
        "host": {"cpu_count": os.cpu_count()},
        "workloads": rows,
        "aborts_total": total_aborts,
        "parity": parity,
        "scaling": scaling,
        "regression_notes": regression_notes,
        "vs_r14": r14_cmp,
        "vs_r12_pipelined_tail": r12_cmp,
        "headline": {
            "pool_w4_native_p50_ms": pool_w4["grid_close_p50_ms"],
            "pool_native_hit_rate": pool_w4["native_hit_rate"],
            "pool_native_declines":
                pool_w4["apply_stats"]["native_declines"],
            "mixed_w4_fee_on_p50_ms":
                mixed_w4_fee["grid_close_p50_ms"],
            "mixed_w4_fee_off_p50_ms":
                mixed_w4_nofee["grid_close_p50_ms"],
            "mixed_w4_fee_on_vs_off_pct": vs(mixed_w4_fee,
                                             mixed_w4_nofee),
            "mixed_w4_fee_phase_on_ms":
                mixed_w4_fee["grid_fee_phase_p50_ms"],
            "mixed_w4_fee_phase_off_ms":
                mixed_w4_nofee["grid_fee_phase_p50_ms"],
        },
        "honest_breakdown": {
            "fee_kernel": "the whole fee/seqnum phase is ONE "
                          "GIL-released charge_fees call (packed "
                          "source-account snapshot in, packed account "
                          "deltas + pre-encoded feeProcessing changes "
                          "out); any unsupported account shape "
                          "declines the WHOLE batch to the Python "
                          "loop — the fee_batch counters per row "
                          "attribute it.",
            "pool_quoting": "a live constant-product pool on a hop "
                            "pair now quotes IN-KERNEL (deposit/"
                            "withdraw stay host-side) — the r14 "
                            "decline-if-live-pool cliff is gone; the "
                            "pool shape routes EVERY path payment "
                            "through live pools and must keep hit "
                            "rate >= 0.9.",
            "tail_encode": "the commit tail's tx-history row encode "
                           "runs as one GIL-released pack_many call "
                           "on the sequential path; pipelined arms "
                           "overlap the (now shorter) tail with the "
                           "next close — tail_wait vs r12 above.",
            "scaling_caveat": "workers=4/workers=1 speedups under 2x "
                              "are flagged in regression_notes, not "
                              "hidden: single-cluster shapes (pathpay,"
                              " pool collapse to one conflict "
                              "component) apply inline, so their win "
                              "is the GIL-free strip, not "
                              "parallelism; and on a host.cpu_count=1 "
                              "rig NO worker count can beat another "
                              "on wall-clock — the honest r16 win is "
                              "grid_vs_seq_pct (fewer Python "
                              "bytecodes per close), which holds at "
                              "every worker count including 0.",
        },
    }
    path = os.path.join(REPO, "PARALLEL_APPLY_r16.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _note(f"persisted {path}")
    print(json.dumps({"metric": out["metric"],
                      "aborts_total": total_aborts,
                      "parity_identical":
                          parity["hashes_and_meta_identical"],
                      "headline": out["headline"],
                      "regression_notes": regression_notes,
                      "workloads": [
                          {k: r[k] for k in ("shape", "workers",
                                             "native", "fee_kernel",
                                             "pipelined",
                                             "seq_close_p50_ms",
                                             "grid_close_p50_ms",
                                             "grid_vs_seq_pct",
                                             "native_hit_rate")}
                          for r in rows]}))


if __name__ == "__main__":
    main()
