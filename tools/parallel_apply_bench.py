#!/usr/bin/env python
"""Native-apply A/B grid (ISSUE 6 acceptance): pay-heavy, mixed and
adversarial-ring 1000-tx closes through the full node close path, over
a native-on/off x workers 0/2/4 grid — each grid arm alternates with a
plain-sequential close IN THE SAME SESSION so ledger-state drift (book
growth, bucket spills) hits both arms equally.  Persists
PARALLEL_APPLY_r10.json.

r09 closed with the honest GIL verdict: the footprint->cluster->
executor machinery was bit-identical but LOST wall clock (+25% pay,
+16% mixed) because CPython time-slices the cluster workers.  This rev
measures the closing bracket: the GIL-free native apply kernel
(native/apply_kernel.cpp) applying kernel-eligible clusters with the
GIL RELEASED — native-on arms should now sit BELOW their sequential
baselines, while the native-off arms reproduce r09's overhead.

Env knobs: BENCH_CLOSES (per arm, default 8), BENCH_CLOSE_TXS
(default 1000), BENCH_DEX_PCT (default 30).
"""
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _note(msg):
    print(f"[parallel-apply-bench] {msg}", file=sys.stderr, flush=True)


def bench_workload(shape: str, pattern: str, n_closes: int,
                   close_txs: int, dex_pct: int, workers: int,
                   native: bool) -> dict:
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        UPGRADE_DESIRED_MAX_TX_SET_SIZE=max(100, close_txs),
        DEFERRED_GC=True,
        PARALLEL_APPLY_WORKERS=workers,
        NATIVE_APPLY=native,
        # workers<2 has no pool: the kernel applies clusters inline on
        # the close thread (the sequential-strip half of the claim)
        NATIVE_APPLY_INLINE=native and workers < 2))
    app.start()
    app.herder.manual_close()  # applies the max-tx-set-size upgrade
    lg = LoadGenerator(app)
    lg.payment_pattern = pattern
    lg.create_accounts(close_txs)
    if shape == "mixed":
        lg.setup_dex()
    arms = {"sequential": [], "grid": []}
    phases = {"sequential": [], "grid": []}
    plan_rows = []
    for i in range(2 * n_closes):
        arm = "grid" if i % 2 else "sequential"
        app.parallel_apply.enabled = (arm == "grid")
        envs = (lg.generate_mixed(close_txs, dex_percent=dex_pct)
                if shape == "mixed" else lg.generate_payments(close_txs))
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == close_txs, f"only {admitted} admitted"
        t0 = time.perf_counter()
        app.herder.manual_close()
        arms[arm].append((time.perf_counter() - t0) * 1000.0)
        phases[arm].append(dict(app.ledger_manager.last_close_phases))
        if arm == "grid":
            plan_rows.append(dict(app.parallel_apply.last_plan_stats))
    stats = {k: v for k, v in app.parallel_apply.stats.items()
             if not isinstance(v, list)}
    stats["escape_reasons"] = app.parallel_apply.stats["escapes"][-4:]
    stats["decline_reasons"] = \
        app.parallel_apply.stats["native_decline_reasons"][-4:]
    app.graceful_stop()

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 2)

    def p50(xs):
        return round(statistics.median(xs), 2) if xs else None

    def phase_p50(arm, name):
        vals = [row.get(name, 0.0) for row in phases[arm]
                if isinstance(row.get(name, 0.0), (int, float))]
        return round(statistics.median(vals), 2) if vals else None

    seq_p50, grid_p50 = p50(arms["sequential"]), p50(arms["grid"])
    clusters = stats["native_hits"] + stats["native_declines"] + \
        stats["native_off"]
    row = {
        "shape": shape,
        "pattern": pattern,
        "close_txs": close_txs,
        "closes_per_arm": n_closes,
        "workers": workers,
        "native": native,
        "seq_close_p50_ms": seq_p50,
        "grid_close_p50_ms": grid_p50,
        "grid_close_p99_ms": pct(arms["grid"], 0.99),
        "seq_close_p99_ms": pct(arms["sequential"], 0.99),
        "grid_vs_seq_pct": (
            round((grid_p50 - seq_p50) / seq_p50 * 100.0, 1)
            if seq_p50 else None),
        "seq_apply_p50_ms": phase_p50("sequential", "apply"),
        "grid_apply_p50_ms": phase_p50("grid", "apply"),
        "grid_plan_p50_ms": phase_p50("grid", "plan"),
        "native_hit_rate": (
            round(stats["native_hits"] / clusters, 4) if clusters else None),
        "apply_stats": stats,
    }
    if plan_rows:
        def med(key):
            vals = [r.get(key) for r in plan_rows
                    if isinstance(r.get(key), (int, float))]
            return round(statistics.median(vals), 2) if vals else None

        row["plan"] = {
            "clusters_p50": med("clusters"),
            "kernel_clusters_p50": med("kernel_clusters"),
            "max_width_p50": med("max_width"),
            "conflict_rate_p50": med("conflict_rate"),
            "preplanned": any(r.get("preplanned") for r in plan_rows),
            "unplanned_reasons": sorted({
                r["unplanned"] for r in plan_rows if "unplanned" in r}),
        }
    _note(f"{shape}/{pattern} w={workers} native={int(native)}: "
          f"seq p50 {seq_p50}ms  grid p50 {grid_p50}ms "
          f"({row['grid_vs_seq_pct']}%)  aborts={stats['aborts']} "
          f"hit_rate={row['native_hit_rate']}")
    return row


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n_closes = int(os.environ.get("BENCH_CLOSES", "8"))
    close_txs = int(os.environ.get("BENCH_CLOSE_TXS", "1000"))
    dex_pct = int(os.environ.get("BENCH_DEX_PCT", "30"))

    grid = [(0, True), (2, True), (4, True), (2, False), (4, False)]
    rows = []
    for shape in ("pay", "mixed"):
        for workers, native in grid:
            rows.append(bench_workload(shape, "pairs", n_closes,
                                       close_txs, dex_pct, workers,
                                       native))
    # the adversarial shape: one fully-connected payment ring — a
    # single conflict cluster.  r09's planner refused it; the kernel
    # turns it into an inline native apply of the whole strip.
    for workers, native in ((0, True), (2, True)):
        rows.append(bench_workload("pay", "ring", max(3, n_closes // 2),
                                   close_txs, dex_pct, workers, native))

    total_aborts = sum(r["apply_stats"]["aborts"] for r in rows)

    def find(shape, workers, native):
        for r in rows:
            if (r["shape"], r["workers"], r["native"]) == \
                    (shape, workers, native):
                return r
        return None

    headline = find("mixed", 4, True)
    out = {
        "metric": "parallel_apply_native_ab_r10",
        "workloads": rows,
        "aborts_total": total_aborts,
        "headline": {
            "mixed_w4_native_p50_ms": headline["grid_close_p50_ms"],
            "mixed_w4_seq_baseline_p50_ms": headline["seq_close_p50_ms"],
            "mixed_w4_native_vs_seq_pct": headline["grid_vs_seq_pct"],
            "native_hit_rate": headline["native_hit_rate"],
        },
        "honest_breakdown": {
            "kernel": "kernel-eligible clusters (native payments, "
                      "offerID=0 manage_sell_offer incl. crossings) "
                      "apply inside native/apply_kernel.cpp with the "
                      "GIL RELEASED — workers finally overlap; "
                      "ineligible or unexpected state declines the "
                      "cluster back to the Python reference apply "
                      "(native_hits/declines in apply_stats).",
            "parity": "header/bucket hashes and meta bytes are "
                      "bit-identical native-vs-Python across workers "
                      "0/2/4 and PYTHONHASHSEED values "
                      "(tests/test_native_apply.py); the kernel "
                      "round-trip-verifies every entry it parses and "
                      "implements success paths only.",
            "invariants": "configured invariant checkers still run on "
                          "every Python-applied cluster; kernel-applied "
                          "clusters rely on the kernel's own decline "
                          "guards (exact-shape parse + bounds checks) — "
                          "state bytes are identical either way.",
            "native_off_arms": "the native=false columns reproduce "
                               "r09's GIL verdict for comparison: same "
                               "machinery, Python workers, wall-clock "
                               "loss.",
        },
    }
    path = os.path.join(REPO, "PARALLEL_APPLY_r10.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _note(f"persisted {path}")
    print(json.dumps({"metric": out["metric"],
                      "aborts_total": total_aborts,
                      "headline": out["headline"],
                      "workloads": [
                          {k: r[k] for k in ("shape", "pattern",
                                             "workers", "native",
                                             "seq_close_p50_ms",
                                             "grid_close_p50_ms",
                                             "grid_vs_seq_pct",
                                             "native_hit_rate")}
                          for r in rows]}))


if __name__ == "__main__":
    main()
