#!/usr/bin/env python
"""Replay a persisted fuzz repro artifact and check replay identity.

A repro artifact (``traces/FUZZ_REPRO_*.json``, written by the fuzz
campaign's minimizer) carries a minimized schedule plus the failure
class and fingerprint it is expected to reproduce.  This tool re-runs
the schedule under the full oracle stack and compares:

- exit 0: the run failed with the SAME class and the SAME failure
  fingerprint (replay identity holds),
- exit 1: the run passed, or failed differently (the repro rotted),
- exit 2: the artifact itself is invalid (corrupted JSON, oversized,
  unknown schema, schedule fails validation).

Usage:
    python -m tools.fuzz_repro traces/FUZZ_REPRO_fork_<id>.json
    python -m tools.fuzz_repro --json <file>     # machine-readable
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellar_core_tpu.simulation.fuzz.minimize import verify_repro  # noqa: E402
from stellar_core_tpu.simulation.fuzz.schedule import (  # noqa: E402
    ScheduleError, load_schedule, schedule_id)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="replay a fuzz repro artifact and check its "
                    "failure class + fingerprint")
    ap.add_argument("repro", help="path to a FUZZ_REPRO_*.json artifact")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as JSON")
    args = ap.parse_args()

    try:
        # strict loader: size cap, JSON parse, schedule validation
        doc = load_schedule(args.repro)
        if not isinstance(doc, dict) or "fuzz_repro_schema" not in doc:
            raise ScheduleError(
                f"{args.repro}: not a fuzz repro artifact "
                f"(missing fuzz_repro_schema)")
        sched = doc["schedule"]
    except (OSError, ValueError) as e:  # ScheduleError is a ValueError
        print(f"INVALID: {e}", file=sys.stderr)
        return 2

    sid = schedule_id(sched)
    if not args.json:
        print(f"replaying schedule {sid} "
              f"(expect {doc['expect']['failure_class']!r}) ...")
    verdict = verify_repro(doc)
    if args.json:
        print(json.dumps(verdict, indent=1, sort_keys=True))
    else:
        exp, got = verdict["expected"], verdict["got"]
        print(f"expected: {exp['failure_class']} "
              f"{exp['failure_fingerprint'][:16]}")
        print(f"got:      {got['failure_class']} "
              f"{(got['failure_fingerprint'] or '-')[:16]}")
        print("REPRODUCED" if verdict["reproduced"] else "NOT REPRODUCED")
    return 0 if verdict["reproduced"] else 1


if __name__ == "__main__":
    sys.exit(main())
