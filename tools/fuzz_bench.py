#!/usr/bin/env python
"""Fuzz-campaign bench: throughput, fleet scale, and the vectorized
quorum A/B — the evidence file for the seeded fault-schedule fuzzer.

Persists ``FUZZ_BENCH_r20.json`` with:

- campaign throughput (schedules/hour) and corpus/novelty stats for
  the ``smoke`` and ``default`` generation profiles,
- fleet-scale runs: one generated ``fleet``-profile schedule at 50 and
  at 100 validators, reporting wall seconds per virtual second,
- the vectorized-vs-scalar quorum A/B: the SAME slice-evaluation
  workload (every node of a tiered network evaluating ``is_quorum``
  over drifting vote sets, exactly the per-slot shape SCP produces)
  timed in one session with ``scp/qset_vector`` enabled then disabled
  — the acceptance gate wants >= 2x at 50+ validators,
- the known-bad proof: the injected fork schedule is found (fails),
  ddmin-minimized to its essential events, persisted to ``traces/``,
  and the artifact replays to the same failure fingerprint.

Usage:
    python -m tools.fuzz_bench                  # full bench (~10 min)
    python -m tools.fuzz_bench --smoke --out /tmp/fuzz_smoke.json
    python -m tools.fuzz_bench --skip-fleet     # skip 50/100-validator runs

``--smoke`` is the verify_green gate: a budget-capped campaign on the
smoke profile (core-4 + one tiered net), the known-bad minimize +
replay proof, and a reduced A/B — red (exit 1) on any oracle failure
other than schedules that legitimately reproduce, or on a
non-reproducing minimized artifact.
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellar_core_tpu.scp import local_node as LN  # noqa: E402
from stellar_core_tpu.scp import qset_vector  # noqa: E402
from stellar_core_tpu.simulation.fuzz import (  # noqa: E402
    FuzzCampaign, known_bad_schedule, load_schedule, minimize_schedule,
    run_schedule, schedule_id, write_repro,
)
from stellar_core_tpu.simulation.fuzz import schedule as S  # noqa: E402
from stellar_core_tpu.simulation.fuzz.minimize import verify_repro  # noqa: E402
from stellar_core_tpu.simulation.simulation import _ids, _seeds  # noqa: E402

OUT = "FUZZ_BENCH_r20.json"


# ---------------------------------------------------------------------------
# vectorized-vs-scalar slice-evaluation A/B
# ---------------------------------------------------------------------------

def _tiered_qsets(n_orgs: int, per_org: int):
    """Per-node hierarchical_quorum qsets: each validator owns its OWN
    qset object with the same symmetric structure — exactly what
    ``Simulation.add_node`` + ``Slot.qset_from_statement`` produce (a
    node resolves every matching statement hash to its own cached
    object, so objects are uniform within a call but distinct across
    nodes)."""
    ids = _ids(_seeds(n_orgs * per_org))
    orgs = [ids[o * per_org:(o + 1) * per_org] for o in range(n_orgs)]

    def mk():
        inner = [LN.make_qset(per_org - (per_org - 1) // 3, members)
                 for members in orgs]
        return LN.make_qset(n_orgs - (n_orgs - 1) // 3, [], inner)

    return ids, {nid: mk() for nid in ids}


def bench_slice_eval(n_orgs: int, per_org: int, rounds: int = 40) -> dict:
    """Time the per-slot quorum workload: each round drifts the vote
    set (an org is late, then shows up), then EVERY node evaluates
    ``is_quorum`` over it with its own qset objects — N evaluations of
    the same member set per phase, the exact shape
    ``Slot._host_is_quorum`` produces across a sim's nodes within one
    slot.

    Each arm gets one untimed warm-up pass: the A/B compares
    steady-state cost, which is what a schedule pays — a run closes
    hundreds of slots after the first crank has warmed the memos (the
    scalar arm has no cross-call caches, so warm-up only levels the
    field)."""
    n = n_orgs * per_org
    ids, qsets = _tiered_qsets(n_orgs, per_org)

    def workload() -> int:
        verdicts = 0
        for r in range(rounds):
            # drifting membership: a rotating org is late, then shows up
            absent = set(ids[(r % n_orgs) * per_org:
                             (r % n_orgs) * per_org + per_org])
            for grow in (absent, set()):
                members = {i for i in ids if i not in grow}
                for nid in ids:  # every node evaluates this vote set
                    own = qsets[nid]
                    verdicts += LN.is_quorum(
                        members, lambda _m, q=own: q, local_qset=own)
        return verdicts

    results = {}
    for arm, enabled in (("vectorized", True), ("scalar", False)):
        qset_vector.clear_caches()
        qset_vector.set_enabled(enabled)
        try:
            warm = workload()
            t0 = time.perf_counter()
            verdicts = workload()
            wall = time.perf_counter() - t0
        finally:
            qset_vector.set_enabled(True)
        assert warm == verdicts
        results[arm] = {"wall_s": round(wall, 4), "verdicts": verdicts}
    results["evaluations"] = rounds * 2 * n
    results["speedup"] = round(
        results["scalar"]["wall_s"]
        / max(results["vectorized"]["wall_s"], 1e-9), 2)
    results["verdicts_agree"] = (
        results["scalar"]["verdicts"] == results["vectorized"]["verdicts"])
    results["vector_stats"] = dict(qset_vector.stats)
    return results


# ---------------------------------------------------------------------------
# fleet-scale schedule runs
# ---------------------------------------------------------------------------

def _fleet_schedule(seed0: int, n_orgs: int, per_org: int) -> dict:
    """First fleet-profile schedule at/after ``seed0`` whose sampled
    topology matches the requested tier (generation is cheap; running
    is not)."""
    for seed in range(seed0, seed0 + 512):
        sched = S.generate_schedule(seed, "fleet")
        topo = sched["topology"]
        if topo.get("n_orgs") == n_orgs and topo.get("per_org") == per_org:
            return sched
    raise RuntimeError(
        f"no fleet schedule with {n_orgs}x{per_org} in 512 seeds")


def bench_fleet(seed0: int, n_orgs: int, per_org: int) -> dict:
    sched = _fleet_schedule(seed0, n_orgs, per_org)
    t0 = time.perf_counter()
    res = run_schedule(sched)
    wall = time.perf_counter() - t0
    rep = res.get("report") or {}
    virtual = rep.get("virtual_elapsed_s") or float(sched["duration"])
    out = {
        "validators": n_orgs * per_org,
        "schedule_id": res["schedule_id"],
        "seed": sched["seed"],
        "events": [e["kind"] for e in sched["events"]],
        "ok": res["ok"],
        "failure_class": res["failure_class"],
        "wall_s": round(wall, 2),
        "virtual_s": virtual,
        "wall_s_per_virtual_s": round(wall / max(virtual, 1e-9), 3),
        "ledgers_closed": rep.get("ledgers_closed"),
        "time_to_heal_s": rep.get("time_to_heal_s"),
    }
    return out


# ---------------------------------------------------------------------------
# the known-bad proof
# ---------------------------------------------------------------------------

def prove_schedule(sched: dict, traces_dir: str,
                   minimize_budget: int = 32) -> dict:
    """Run one failing schedule through the full pipeline — found ->
    ddmin-minimized -> persisted to ``traces_dir`` -> replayed from the
    artifact — and report every stage's verdict."""
    found = run_schedule(sched)
    proof = {
        "schedule_id": schedule_id(sched),
        "found": not found["ok"],
        "failure_class": found["failure_class"],
        "events_before": len(sched["events"]),
    }
    if found["ok"]:
        return proof
    mini, stats = minimize_schedule(
        sched, target_class=found["failure_class"],
        max_runs=minimize_budget)
    proof.update({
        "events_after": len(mini["events"]),
        "minimized_events": [e["kind"] for e in mini["events"]],
        "oracle_runs": stats["oracle_runs"],
        "minimized_reproduces": stats["reproduces"],
    })
    if not stats["reproduces"]:
        return proof
    path = write_repro(mini, dict(stats["final_result"], ok=False),
                       out_dir=traces_dir,
                       minimized_from=schedule_id(sched))
    verdict = verify_repro(load_schedule(path))
    proof.update({
        "repro_path": path,
        "replay_reproduced": verdict["reproduced"],
        "failure_fingerprint":
            verdict["expected"]["failure_fingerprint"],
    })
    return proof


def prove_known_bad(traces_dir: str, minimize_budget: int = 32) -> dict:
    return prove_schedule(known_bad_schedule(), traces_dir,
                          minimize_budget)


def real_finding_schedule() -> dict:
    """An ACTUAL bug the chaos grammar surfaced (not an injected
    canary): on a deliberately-unsafe core-4 (threshold 2 — quorums
    need not intersect), equivocating+silencing one node while an
    honest node is partitioned away forks the network, and a node then
    applies a tx set built on the OTHER branch — ledger close dies
    with ``tx set prev hash mismatch`` (crash:RuntimeError),
    deterministically.  The full bench minimizes it and persists the
    repro to ``traces/`` like any campaign finding."""
    sched = {
        "fuzz_schema": S.SCHEMA_VERSION,
        "seed": 14,
        "profile": "real-finding",
        "topology": {"kind": "core", "n": 4, "threshold": 2},
        "duration": 14.0,
        "converge_timeout": 20.0,
        "events": [
            {"t": 2.0, "kind": "equivocate", "victim": 2},
            {"t": 2.0, "kind": "silence", "victim": 2},
            {"t": 3.0, "kind": "partition", "groups": [[3], [0, 1]]},
        ],
        "traffic": [],
    }
    S.validate_schedule(sched)
    return sched


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description="fuzz campaign bench")
    ap.add_argument("--smoke", action="store_true",
                    help="budget-capped verify_green gate")
    ap.add_argument("--out", default=None, help="output JSON path")
    ap.add_argument("--seed0", type=int, default=9000)
    ap.add_argument("--skip-fleet", action="store_true")
    ap.add_argument("--skip-ab", action="store_true")
    args = ap.parse_args()
    out_path = args.out or OUT

    doc = {"bench": "fuzz_campaign", "revision": "r20",
           "smoke": bool(args.smoke)}
    problems = []
    t_start = time.perf_counter()

    with tempfile.TemporaryDirectory(prefix="fuzz-bench-") as tmp:
        traces_dir = tmp if args.smoke else "traces"

        # 1. campaign throughput + corpus stats
        profiles = (("smoke", 4),) if args.smoke else \
            (("smoke", 6), ("default", 8))
        doc["campaigns"] = {}
        for profile, count in profiles:
            camp = FuzzCampaign(
                seed0=args.seed0, profile=profile, schedules=count,
                wall_budget_s=180.0 if args.smoke else 900.0,
                corpus_dir=os.path.join(tmp, f"corpus-{profile}"),
                traces_dir=traces_dir,
                minimize_budget=16 if args.smoke else 32,
                log=lambda s: print(s, flush=True))
            summary = camp.run()
            doc["campaigns"][profile] = summary
            for f in summary["failures"]:
                if f.get("non_reproducing"):
                    problems.append(
                        f"campaign[{profile}] seed {f['seed']}: minimized "
                        f"schedule does not reproduce "
                        f"{f['failure_class']!r}")
                else:
                    # a reproducing minimized failure is a FINDING —
                    # the bench records it; the smoke gate stays green
                    # only for the known-bad class the fuzzer plants,
                    # anything else is a real red flag
                    problems.append(
                        f"campaign[{profile}] seed {f['seed']}: oracle "
                        f"failure {f['failure_class']!r} "
                        f"(repro: {f.get('repro_path')})")

        # 2. known-bad: found -> minimized -> replayed
        print("[bench] known-bad proof", flush=True)
        doc["known_bad"] = prove_known_bad(
            traces_dir, minimize_budget=16 if args.smoke else 32)
        kb = doc["known_bad"]
        if not kb["found"]:
            problems.append("known-bad schedule did not fail its oracles")
        elif not kb.get("minimized_reproduces"):
            problems.append("known-bad minimized schedule does not "
                            "reproduce the failure")
        elif not kb.get("replay_reproduced"):
            problems.append("known-bad repro artifact does not replay "
                            "to the same fingerprint")

        # 2b. the real finding: the crash bug the grammar surfaced,
        # minimized + persisted like any campaign discovery
        if not args.smoke:
            print("[bench] real finding (tx set prev hash mismatch)",
                  flush=True)
            doc["real_finding"] = prove_schedule(
                real_finding_schedule(), traces_dir, minimize_budget=32)
            rf = doc["real_finding"]
            if not rf["found"]:
                problems.append(
                    "real-finding schedule did not fail its oracles")
            elif not rf.get("replay_reproduced"):
                problems.append(
                    "real-finding repro artifact does not replay")

        # 3. vectorized-vs-scalar A/B at 50+ validators
        if not args.skip_ab:
            print("[bench] slice-eval A/B", flush=True)
            doc["slice_eval_ab"] = {
                "50": bench_slice_eval(10, 5,
                                       rounds=10 if args.smoke else 40),
            }
            if not args.smoke:
                doc["slice_eval_ab"]["100"] = bench_slice_eval(
                    20, 5, rounds=20)
            for tier, ab in doc["slice_eval_ab"].items():
                if not ab["verdicts_agree"]:
                    problems.append(
                        f"A/B at {tier}: vectorized and scalar verdicts "
                        f"disagree")
                if ab["speedup"] < 2.0:
                    problems.append(
                        f"A/B at {tier}: speedup {ab['speedup']}x < 2x")

        # 4. fleet-scale schedules (50 and 100 validators)
        if not args.skip_fleet and not args.smoke:
            print("[bench] fleet 50", flush=True)
            doc["fleet"] = {"50": bench_fleet(args.seed0, 10, 5)}
            print("[bench] fleet 100", flush=True)
            doc["fleet"]["100"] = bench_fleet(args.seed0, 20, 5)
            for tier, f in doc["fleet"].items():
                if not f["ok"]:
                    problems.append(
                        f"fleet {tier}-validator schedule "
                        f"{f['schedule_id']} failed: {f['failure_class']}")

    doc["wall_s"] = round(time.perf_counter() - t_start, 1)
    doc["problems"] = problems
    doc["green"] = not problems
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"[bench] wrote {out_path} "
          f"({'GREEN' if doc['green'] else 'RED'}, {doc['wall_s']}s)")
    for p in problems:
        print(f"  PROBLEM: {p}")
    return 0 if doc["green"] else 1


if __name__ == "__main__":
    sys.exit(main())
