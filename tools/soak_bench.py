#!/usr/bin/env python
"""Sustained-load soak bench (ROADMAP item 4 / ISSUE 12 acceptance):
minutes of timer-driven rate-mode load against ONE disk-backed
standalone node on a REAL-TIME clock, measured by the two telemetry
subsystems this PR adds — the tx-lifecycle tracker (admission ->
durable-commit latency percentiles per stage) and the vitals sampler
(RSS/fd/thread/queue/GC drift with least-squares slopes and the SLO
watchdog).  Persists SOAK_BENCH_r13.json.

What a passing soak proves (and short same-session A/Bs cannot):

- the node SUSTAINS the offered tx/s: admitted ~= submitted, applied
  tx/s tracks the rate, the queue neither ages out nor bans;
- end-to-end admission -> externalize -> apply -> durable-commit
  latency percentiles stay flat (reported per stage, p50/p99);
- nothing drifts: RSS and fd slopes ~= 0 over the whole run, GC pauses
  bounded (histogram reported), zero SLO watchdog breaches;
- the telemetry itself is free enough to leave on: tracker+sampler
  disabled-cost A/B must stay <1% of close p50, and ledger/bucket
  hashes AND meta bytes are bit-identical telemetry on vs off.

Usage:
    python tools/soak_bench.py                   # full run (~4 min)
    python tools/soak_bench.py --smoke           # ~30 s verify_green gate
    python tools/soak_bench.py --rate 150 --duration 300 --out X.json
"""
import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "SOAK_BENCH_r13.json")


def _note(msg):
    print(f"[soak-bench] {msg}", file=sys.stderr, flush=True)


def _p(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(q * len(xs)))], 3)


def _mk_soak_app(node_dir: str, ledger_interval: float, tx_set_size: int,
                 vitals_jsonl: str):
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.config import Config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    os.makedirs(os.path.join(node_dir, "buckets"), exist_ok=True)
    cfg = Config(
        RUN_STANDALONE=True,
        MANUAL_CLOSE=False,               # timer-driven closes: the soak
        EXP_LEDGER_TIMESPAN_SECONDS=ledger_interval,
        ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True,  # loadgen modes
        DATABASE=os.path.join(node_dir, "node.db"),
        BUCKET_DIR_PATH_REAL=os.path.join(node_dir, "buckets"),
        TESTING_UPGRADE_MAX_TX_SET_SIZE=tx_set_size,
        CRYPTO_BACKEND="cpu",
        SCP_TALLY_BACKEND="host",
        DEFERRED_GC=True,
        PIPELINED_CLOSE=True,             # the production close shape
        PARALLEL_APPLY_WORKERS=2,
        SLOW_CLOSE_THRESHOLD_SECONDS=0.0,
        VITALS_ENABLED=True,
        VITALS_PERIOD_SECONDS=1.0,
        VITALS_JSONL=vitals_jsonl,
        UNSAFE_QUORUM=True,
    )
    app = Application(VirtualClock(ClockMode.REAL_TIME), cfg)
    app.start()
    return app


def _seed(app, lg, accounts: int, slice_txs: int) -> None:
    """Bulk-seed the pool, then fold every slice into the bucket tier
    through real closes (the pipeline_bench discipline) so the soak
    reads production-shaped state, not a warm sql-ahead overlay."""
    lg.create_accounts(accounts)
    for lo in range(0, accounts, slice_txs):
        accts = lg.accounts[lo:lo + slice_txs]
        envs = lg.generate_payments(len(accts), accounts=accts)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == len(accts), "seeding fold under-admitted"
        # immediate close instead of waiting out the cadence timer
        # (trigger re-arms it, so cancel the pending one first)
        app.herder.trigger_timer.cancel()
        app.herder.trigger_next_ledger()


def soak_run(rate: float, duration: float, accounts: int,
             ledger_interval: float) -> dict:
    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    node_dir = tempfile.mkdtemp(prefix="soak-bench-")
    vitals_jsonl = os.path.join(node_dir, "vitals.jsonl")
    tx_set_size = max(200, int(rate * ledger_interval * 4))
    app = _mk_soak_app(node_dir, ledger_interval, tx_set_size,
                       vitals_jsonl)
    lm = app.ledger_manager
    lg = LoadGenerator(app)
    # seeding stays out of the latency rollups
    app.txtracer.enabled = False
    _seed(app, lg, accounts, min(accounts, tx_set_size))
    lm.pipeline.drain()
    seed_lcl = lm.last_closed_seq()
    seeded_rows = app.database.execute(
        "SELECT COUNT(*) FROM txhistory").fetchone()[0]
    app.txtracer.enabled = True

    close_totals = []
    app.herder.on_externalized.append(
        lambda seq, sv: close_totals.append(
            lm.last_close_phases.get("total")))
    _note(f"measuring: {rate} tx/s for {duration}s "
          f"({ledger_interval}s ledgers, {accounts} accounts, "
          f"lcl {seed_lcl})")
    clock = app.clock
    lg.start_rate_run("pay", rate=rate, duration=duration)
    deadline = clock.now() + duration + 2 * ledger_interval
    while clock.now() < deadline:
        app.crank(block=True)
    lg.stop_rate_run()
    lm.pipeline.drain()

    rate_status = lg.rate_status()
    applied = app.database.execute(
        "SELECT COUNT(*) FROM txhistory").fetchone()[0] - seeded_rows
    ledgers = lm.last_closed_seq() - seed_lcl
    tx_report = app.txtracer.report(last=4)
    vit_report = app.vitals.report()
    queue_left = app.herder.tx_queue.size()
    app.graceful_stop()
    shutil.rmtree(node_dir, ignore_errors=True)

    breaches = vit_report["slo"]["breaches"]
    totals = [t for t in close_totals if isinstance(t, (int, float))]
    row = {
        "config": {"rate_tx_s": rate, "duration_s": duration,
                   "accounts": accounts,
                   "ledger_interval_s": ledger_interval,
                   "pipelined_close": True, "workers": 2},
        "sustained": {
            "submitted": rate_status["submitted"],
            "admit_status_counts": rate_status["status_counts"],
            "submitted_tx_s": round(
                rate_status["submitted"] / duration, 2),
            "applied_txs": applied,
            "applied_tx_s": round(applied / duration, 2),
            "ledgers_closed": ledgers,
            "queue_left": queue_left,
        },
        "close_ms": {"p50": _p(totals, 0.5), "p99": _p(totals, 0.99),
                     "samples": len(totals)},
        "tx_latency": tx_report["latency"],
        "tx_tracker": {k: tx_report[k] for k in
                       ("seen", "tracked", "completed", "stride",
                        "decimations")},
        "vitals": {
            "samples": vit_report["samples"],
            "slopes_per_s": vit_report["slopes_per_s"],
            "slopes_tail_per_s": vit_report["slopes_tail_per_s"],
            "rss_slope_mb_s": round(
                vit_report["slopes_per_s"]["rss_bytes"] / 1e6, 4),
            "rss_slope_tail_mb_s": round(
                vit_report["slopes_tail_per_s"]["rss_bytes"] / 1e6, 4),
            "fd_slope_per_s": vit_report["slopes_per_s"]["open_fds"],
            "latest": vit_report["latest"],
            "gc_pause": vit_report["gc_pause"],
        },
        "slo": {"breaches": breaches,
                "watchdog_green": not any(breaches.values())},
    }
    _note(f"sustained {row['sustained']['applied_tx_s']} tx/s applied "
          f"over {ledgers} ledgers; close p50 {row['close_ms']['p50']}ms; "
          f"rss slope {row['vitals']['rss_slope_mb_s']} MB/s "
          f"(tail {row['vitals']['rss_slope_tail_mb_s']}); "
          f"breaches {breaches}")
    return row


def disabled_cost(closes: int = 10, txs: int = 200) -> dict:
    """Two cost numbers for the telemetry subsystems:

    - ``disabled_pct`` (the acceptance gate, <1% of close p50): the
      per-close cost of the DISABLED hook sites — one attribute check
      per admission and per stage-stamp call — microbenchmarked and
      scaled against the measured close p50, the same per-call
      discipline PR 4 used for disabled spans.  The vitals sampler
      contributes zero here by construction: disabled, it owns no
      timer and touches no hot path.
    - ``enabled_overhead_pct`` (reported for honesty — the always-on
      price): same-session alternating close-phase A/B with the
      tracker stamping + one vitals sample per close vs both off."""
    from time import perf_counter

    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=max(200, txs)))
    app.start()
    lm = app.ledger_manager
    lg = LoadGenerator(app)
    lg.create_accounts(txs)
    app.herder.manual_close()
    arms = {"off": [], "on": []}
    for i in range(2 * closes):
        arm = "on" if i % 2 else "off"
        app.txtracer.enabled = arm == "on"
        envs = lg.generate_payments(txs)
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == txs
        if arm == "on":
            app.vitals.sample_once()
        app.herder.manual_close()
        arms[arm].append(lm.last_close_phases["total"])

    # disabled hook-site microbench: what every close pays when the
    # tracker is OFF — txs admissions + the 6 stage-stamp calls
    app.txtracer.enabled = False

    class _F:
        def full_hash(self):
            return b"\x00" * 32

    frames = [_F() for _ in range(txs)]
    reps = 200
    t0 = perf_counter()
    for _ in range(reps):
        for f in frames:
            app.txtracer.on_admit(b"\x00" * 32)
        for stage in ("txset", "nominate", "externalize", "apply",
                      "commit"):
            app.txtracer.stamp_frames(frames, stage)
    disabled_ms_per_close = (perf_counter() - t0) / reps * 1000.0
    app.graceful_stop()

    off_p50 = round(statistics.median(arms["off"]), 3)
    on_p50 = round(statistics.median(arms["on"]), 3)
    enabled_overhead = (round((on_p50 - off_p50) / off_p50 * 100.0, 2)
                        if off_p50 else None)
    disabled_pct = (round(disabled_ms_per_close / off_p50 * 100.0, 4)
                    if off_p50 else None)
    _note(f"cost: disabled hooks {disabled_ms_per_close * 1000:.1f}us"
          f"/close = {disabled_pct}% of close p50 {off_p50}ms; "
          f"enabled A/B {off_p50}->{on_p50}ms "
          f"({enabled_overhead:+}%)")
    return {"closes_per_arm": closes, "close_txs": txs,
            "off_close_p50_ms": off_p50, "on_close_p50_ms": on_p50,
            "disabled_us_per_close": round(
                disabled_ms_per_close * 1000.0, 2),
            "disabled_pct": disabled_pct,
            "enabled_overhead_pct": enabled_overhead}


def parity_pass() -> dict:
    """Telemetry on vs off over the deterministic mixed workload:
    every per-close (ledger hash, bucket hash, meta bytes) must match
    — the stamps are observational or they are a consensus bug."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from tests.test_txtrace import run_telemetry_workload

    on = run_telemetry_workload(True, pipelined=True)
    off = run_telemetry_workload(False, pipelined=True)
    ok = len(on) == len(off) and all(
        a[0] == b[0] and a[1] == b[1] and a[2] == b[2]
        for a, b in zip(on, off))
    _note(f"parity: {len(on)} closes, identical={ok}")
    if not ok:
        raise SystemExit("telemetry on/off parity FAILED")
    return {"closes": len(on), "hashes_identical": ok,
            "meta_bytes_identical": ok}


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=100.0)
    ap.add_argument("--duration", type=float, default=200.0)
    ap.add_argument("--accounts", type=int, default=3000)
    ap.add_argument("--ledger-interval", type=float, default=1.0)
    ap.add_argument("--smoke", action="store_true",
                    help="~30 s gate shape: shorter run, lower rate")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()
    if args.smoke:
        args.rate, args.duration, args.accounts = 40.0, 30.0, 400

    row = soak_run(args.rate, args.duration, args.accounts,
                   args.ledger_interval)
    cost = disabled_cost()
    parity = parity_pass()
    doc = {
        "bench": "sustained-load soak",
        "rev": "r13",
        "device": "cpu-fallback",
        "smoke": bool(args.smoke),
        **row,
        "disabled_cost": cost,
        "parity": parity,
        "notes": (
            "one disk-backed standalone node, REAL_TIME clock, "
            "timer-driven closes, loadgen rate mode; latency = "
            "tx-lifecycle tracker stage histograms (ms; e2e = "
            "admission->durable-commit, commit stamped on the tail "
            "worker against the originating ledger); vitals slopes = "
            "least-squares over the sampler ring; disabled_cost = "
            "alternating same-session close-phase A/B; parity = "
            "per-close header/bucket hashes AND meta bytes, telemetry "
            "on vs off"),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    _note(f"persisted {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
