#!/usr/bin/env python
"""ASCII flame summary for persisted flight-recorder traces, plus a
slot-timeline renderer for SCP forensics.

The slow-close watchdog (stellar_core_tpu/utils/tracing.py) persists
Chrome ``trace_event`` JSON; chrome://tracing / Perfetto render it, but
the container has no browser.  This renders the same file as an
indented tree with proportional bars plus a top-self-time table.

``--slots`` switches to the consensus-forensics view: the input is
either one node's ``scp?slot=N`` / ``scp`` endpoint body or a
network-wide ``FORENSICS_*.json`` dump (simulation/chaos.py).  Events
from every node merge into one per-slot timeline, ordered by virtual
time, with the first-divergence attribution and equivocation evidence
printed up top — a failing chaos schedule read as a story.

Usage: python tools/trace_view.py <trace.json> [--width N] [--top K]
       python tools/trace_view.py --slots <FORENSICS_*.json|scp.json>
           [--slot N] [--node HEX8]
"""
import argparse
import json
import sys
from typing import Dict, List, Optional


class Node:
    __slots__ = ("name", "ts", "dur", "tid", "args", "children")

    def __init__(self, ev: dict):
        self.name = ev.get("name", "?")
        self.ts = float(ev.get("ts", 0.0))        # µs
        self.dur = float(ev.get("dur", 0.0))      # µs
        self.tid = ev.get("tid", 0)
        self.args = ev.get("args", {})
        self.children: List["Node"] = []


def build_forest(events: List[dict]) -> List[Node]:
    """Parent by explicit span ids (the recorder exports them in args);
    events without a resolvable parent become roots."""
    nodes: Dict[int, Node] = {}
    order: List[Node] = []
    for ev in events:
        n = Node(ev)
        sid = ev.get("args", {}).get("span_id")
        if sid is not None:
            nodes[sid] = n
        order.append(n)
    roots: List[Node] = []
    for n in order:
        pid = n.args.get("parent_id")
        parent = nodes.get(pid) if pid is not None else None
        if parent is None or parent is n:
            roots.append(n)
        else:
            parent.children.append(n)
    for n in order:
        n.children.sort(key=lambda c: c.ts)
    roots.sort(key=lambda c: c.ts)
    return roots


def render_tree(roots: List[Node], width: int) -> List[str]:
    total = max((r.dur for r in roots), default=0.0) or 1.0
    lines: List[str] = []

    def walk(n: Node, depth: int, main_tid) -> None:
        bar = "#" * max(1, int(round(n.dur / total * width))) \
            if n.dur > 0 else ""
        cross = "" if n.tid == main_tid else \
            f"  [thread {n.args.get('thread', n.tid)}]"
        pct = n.dur / total * 100.0
        lines.append(f"{'  ' * depth}{n.name:<{44 - 2 * min(depth, 10)}}"
                     f"{n.dur / 1000.0:10.3f}ms {pct:5.1f}% "
                     f"{bar}{cross}")
        for c in n.children:
            walk(c, depth + 1, main_tid)

    for r in roots:
        walk(r, 0, r.tid)
    return lines


def self_time_table(events: List[dict], top: int) -> List[str]:
    by_sid = {ev["args"]["span_id"]: ev
              for ev in events if ev.get("args", {}).get("span_id")}
    selfs = {sid: float(ev.get("dur", 0.0))
             for sid, ev in by_sid.items()}
    for ev in events:
        pid = ev.get("args", {}).get("parent_id")
        parent = by_sid.get(pid)
        # same-thread children only: cross-thread children (the bucket
        # worker merges) run concurrently with their parent
        if parent is not None and parent.get("tid") == ev.get("tid"):
            selfs[pid] -= float(ev.get("dur", 0.0))
    by_name: Dict[str, List[float]] = {}
    for ev in events:
        sid = ev.get("args", {}).get("span_id")
        if sid is None:
            continue
        slot = by_name.setdefault(ev.get("name", "?"), [0.0, 0])
        slot[0] += selfs.get(sid, 0.0)
        slot[1] += 1
    lines = ["", f"top {top} spans by self time:",
             f"  {'span':<36}{'self':>12}{'count':>8}"]
    ranked = sorted(by_name.items(), key=lambda kv: (-kv[1][0], kv[0]))
    for name, (self_us, count) in ranked[:top]:
        lines.append(f"  {name:<36}{self_us / 1000.0:10.3f}ms"
                     f"{count:8d}")
    return lines


def render(trace: dict, width: int = 40, top: int = 10) -> str:
    events = trace.get("traceEvents", [])
    meta = trace.get("metadata", {})
    head = []
    if meta:
        head.append(f"ledger {meta.get('ledger', '?')}: "
                    f"{meta.get('duration_ms', '?')}ms over "
                    f"{len(events)} spans")
        if meta.get("truncated_spans"):
            head.append(f"  ({meta['truncated_spans']} oldest spans "
                        "truncated from the ring)")
    lines = head + render_tree(build_forest(events), width)
    lines += self_time_table(events, top)
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# propagation view (network observatory)
# ---------------------------------------------------------------------------

def _obs_body(doc: dict) -> dict:
    """Accept a raw observatory snapshot, the `network-observatory`
    endpoint body ({"observatory": ...}), or a NET_OBS bench tier doc
    carrying an "observatory" key."""
    if isinstance(doc.get("observatory"), dict):
        return doc["observatory"]
    return doc


def render_propagation(doc: dict, item: Optional[str] = None,
                       top: int = 8) -> str:
    """Per-item hop tree + coverage timeline from a merged observatory
    snapshot (browser-less).  ``item`` filters to hashes with that hex
    prefix; otherwise the ``top`` most recent items render."""
    snap = _obs_body(doc)
    lines: List[str] = []
    nodes = snap.get("nodes", [])
    lines.append(f"{len(nodes)} nodes, {snap.get('n_items', 0)} "
                 "flood items")
    prop = snap.get("propagation", {})
    for key in ("ttfd", "time_to_50pct", "time_to_90pct"):
        s = prop.get(key)
        if s:
            lines.append(
                f"  {key:<14} n={s['n']:<6} "
                f"p50={s['p50'] * 1000.0:9.3f}ms "
                f"p90={s['p90'] * 1000.0:9.3f}ms "
                f"max={s['max'] * 1000.0:9.3f}ms")

    items = snap.get("items", {})
    sel = sorted(items.items(), key=lambda kv: (
        kv[1]["deliveries"][0]["t"] if kv[1].get("deliveries") else 0.0,
        kv[0]))
    if item is not None:
        sel = [(h, it) for h, it in sel if h.startswith(item)]
    else:
        sel = sel[-top:]

    for h, it in sel:
        lines.append("")
        lines.append(
            f"item {h[:16]} [{it.get('kind', '?')}] "
            f"origin={it.get('origin') or '?'} "
            f"coverage={it.get('coverage')} "
            f"dups={it.get('dups_total', 0)}")
        delv = it.get("deliveries", [])
        if not delv:
            continue
        t0 = delv[0]["t"]
        by_parent: Dict[Optional[str], List[dict]] = {}
        node_set = {d["node"] for d in delv}
        for d in delv:
            by_parent.setdefault(d.get("from"), []).append(d)
        emitted = set()

        def walk(d: dict, depth: int) -> None:
            if d["node"] in emitted:
                return
            emitted.add(d["node"])
            mark = "*" if depth == 0 else "+"
            src = f"  (from {d['from']})" \
                if depth == 0 and d.get("from") else ""
            lines.append(f"  {'  ' * depth}{mark} {d['node']} "
                         f"+{(d['t'] - t0) * 1000.0:.3f}ms{src}")
            for c in by_parent.get(d["node"], []):
                walk(c, depth + 1)

        for d in delv:
            # roots: the origin (from=None) or deliveries whose sending
            # peer has no record of its own (sampled out / evicted)
            if d.get("from") is None or d["from"] not in node_set:
                walk(d, 0)
        for d in delv:  # anything the tree missed renders flat
            walk(d, 0)
        n = len(nodes) or len(delv)
        steps = " ".join(
            f"{i + 1}/{n}@{(d['t'] - t0) * 1000.0:.1f}ms"
            for i, d in enumerate(delv))
        lines.append(f"  coverage: {steps}")

    links = snap.get("links", {})
    if links:
        lines.append("")
        lines.append("link redundancy (dup / (uniq + dup)):")
        for k in sorted(links):
            row = links[k]
            lines.append(f"  {k:<22} uniq={row['unique']:<7}"
                         f"dup={row['duplicate']:<7}"
                         f"r={row['redundancy']}")
    cadence = snap.get("close_cadence", {})
    if cadence:
        lines.append("")
        lines.append("close cadence (lcl, lag behind head):")
        for n8 in sorted(cadence):
            row = cadence[n8]
            lines.append(f"  {n8:<10} lcl={row['lcl']:<8}"
                         f"lag={row['lag']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# slot-timeline view (consensus forensics)
# ---------------------------------------------------------------------------

def _short(v, n: int = 12):
    """Truncate long value tags for display (full tags live in the
    JSON)."""
    if isinstance(v, str) and len(v) > n:
        return v[:n] + ".."
    if isinstance(v, list):
        return [_short(x, n) for x in v]
    if isinstance(v, dict):
        return {k: _short(x, n) for k, x in v.items()}
    return v


def _event_detail(ev: dict) -> str:
    parts = []
    for k in sorted(ev):
        if k in ("t", "kind"):
            continue
        parts.append(f"{k}={_short(ev[k])}")
    return " ".join(parts)


def _node_timelines(doc: dict) -> Dict[str, dict]:
    """Normalize the three accepted shapes to {node: timeline export}:
    a FORENSICS dump ('timelines'), a full `scp` body ('timeline' with
    ring summary is NOT enough — needs slots), or one node's
    `scp?slot=N` body ('timeline' carrying slot events)."""
    if "timelines" in doc:
        return doc["timelines"]
    tl = doc.get("timeline", {})
    if "events" in tl:  # scp?slot=N single-slot body
        return {"local": {"slots": {str(tl.get("slot", doc.get("slot", 0))):
                                    {"events": tl.get("events", []),
                                     "dropped": tl.get("dropped", 0)}}}}
    if isinstance(tl.get("slots"), dict):  # a raw SCPTimeline.export()
        return {"local": tl}
    # the full `scp` body's timeline is a ring SUMMARY ("slots" is a
    # list of indices, no events) — nothing renderable
    if "slots" in doc and isinstance(doc.get("slots"), dict) and any(
            "events" in v for v in doc["slots"].values()):
        return {"local": doc}
    return {}


def render_slots(doc: dict, slot: Optional[int] = None,
                 node: Optional[str] = None) -> str:
    lines: List[str] = []
    fd = doc.get("first_divergence")
    if fd:
        lines.append(f"FIRST DIVERGENCE: slot {fd.get('slot')} via "
                     f"{fd.get('via')} -> node {fd.get('node')}")
    for e in doc.get("equivocations", []):
        wit = {w for s in e.get("statements", [])
               for w in s.get("witnesses", [])}
        lines.append(
            f"EQUIVOCATION: node {e['node']} slot {e['slot']} "
            f"[{e['proto']}] {e.get('conflicting_pairs', 0)} conflicting "
            f"pair(s), witnessed by {', '.join(sorted(wit))}")
    if doc.get("reason"):
        lines.append(f"reason: {doc['reason']}")
    if lines:
        lines.append("")

    timelines = _node_timelines(doc)
    if node is not None:
        timelines = {n: t for n, t in timelines.items()
                     if n.startswith(node)}
    # merge: slot -> [(t, node, kind, detail)]
    merged: Dict[int, List[tuple]] = {}
    order = 0
    for n8 in sorted(timelines):
        for slot_str, slot_doc in sorted(
                timelines[n8].get("slots", {}).items(), key=lambda kv:
                int(kv[0])):
            s = int(slot_str)
            if slot is not None and s != slot:
                continue
            for ev in slot_doc.get("events", []):
                order += 1
                merged.setdefault(s, []).append(
                    (float(ev.get("t", 0.0)), order, n8,
                     ev.get("kind", "?"), _event_detail(ev)))
            if slot_doc.get("dropped"):
                merged.setdefault(s, []).append(
                    (float("inf"), order, n8, "(truncated)",
                     f"dropped={slot_doc['dropped']} oldest events"))
    if not merged:
        lines.append("no slot timeline events in this file")
        return "\n".join(lines)
    for s in sorted(merged):
        lines.append(f"== slot {s} ==")
        lines.append(f"  {'t(s)':>10}  {'node':<10}{'event':<24}detail")
        for t, _o, n8, kind, detail in sorted(merged[s]):
            ts = "" if t == float("inf") else f"{t:10.3f}"
            lines.append(f"  {ts:>10}  {n8:<10}{kind:<24}{detail}")
        lines.append("")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace_event JSON file, or a "
                                  "FORENSICS_*.json / scp endpoint body "
                                  "with --slots")
    ap.add_argument("--width", type=int, default=40,
                    help="flame bar width in columns")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the self-time table")
    ap.add_argument("--slots", action="store_true",
                    help="render a consensus slot timeline instead of "
                         "a flame tree")
    ap.add_argument("--slot", type=int, default=None,
                    help="with --slots: only this slot")
    ap.add_argument("--node", default=None,
                    help="with --slots: only nodes whose hex8 id "
                         "starts with this prefix")
    ap.add_argument("--propagation", action="store_true",
                    help="render per-item flood hop trees + coverage "
                         "timelines from a network-observatory snapshot")
    ap.add_argument("--item", default=None,
                    help="with --propagation: only items whose hash "
                         "starts with this hex prefix")
    args = ap.parse_args()
    try:
        with open(args.trace, encoding="utf-8") as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        print(f"trace_view: cannot read {args.trace}: {e}",
              file=sys.stderr)
        return 2
    if args.slots:
        print(render_slots(trace, slot=args.slot, node=args.node))
    elif args.propagation:
        print(render_propagation(trace, item=args.item, top=args.top))
    else:
        print(render(trace, width=args.width, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
