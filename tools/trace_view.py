#!/usr/bin/env python
"""ASCII flame summary for persisted flight-recorder traces.

The slow-close watchdog (stellar_core_tpu/utils/tracing.py) persists
Chrome ``trace_event`` JSON; chrome://tracing / Perfetto render it, but
the container has no browser.  This renders the same file as an
indented tree with proportional bars plus a top-self-time table.

Usage: python tools/trace_view.py <trace.json> [--width N] [--top K]
"""
import argparse
import json
import sys
from typing import Dict, List, Optional


class Node:
    __slots__ = ("name", "ts", "dur", "tid", "args", "children")

    def __init__(self, ev: dict):
        self.name = ev.get("name", "?")
        self.ts = float(ev.get("ts", 0.0))        # µs
        self.dur = float(ev.get("dur", 0.0))      # µs
        self.tid = ev.get("tid", 0)
        self.args = ev.get("args", {})
        self.children: List["Node"] = []


def build_forest(events: List[dict]) -> List[Node]:
    """Parent by explicit span ids (the recorder exports them in args);
    events without a resolvable parent become roots."""
    nodes: Dict[int, Node] = {}
    order: List[Node] = []
    for ev in events:
        n = Node(ev)
        sid = ev.get("args", {}).get("span_id")
        if sid is not None:
            nodes[sid] = n
        order.append(n)
    roots: List[Node] = []
    for n in order:
        pid = n.args.get("parent_id")
        parent = nodes.get(pid) if pid is not None else None
        if parent is None or parent is n:
            roots.append(n)
        else:
            parent.children.append(n)
    for n in order:
        n.children.sort(key=lambda c: c.ts)
    roots.sort(key=lambda c: c.ts)
    return roots


def render_tree(roots: List[Node], width: int) -> List[str]:
    total = max((r.dur for r in roots), default=0.0) or 1.0
    lines: List[str] = []

    def walk(n: Node, depth: int, main_tid) -> None:
        bar = "#" * max(1, int(round(n.dur / total * width))) \
            if n.dur > 0 else ""
        cross = "" if n.tid == main_tid else \
            f"  [thread {n.args.get('thread', n.tid)}]"
        pct = n.dur / total * 100.0
        lines.append(f"{'  ' * depth}{n.name:<{44 - 2 * min(depth, 10)}}"
                     f"{n.dur / 1000.0:10.3f}ms {pct:5.1f}% "
                     f"{bar}{cross}")
        for c in n.children:
            walk(c, depth + 1, main_tid)

    for r in roots:
        walk(r, 0, r.tid)
    return lines


def self_time_table(events: List[dict], top: int) -> List[str]:
    by_sid = {ev["args"]["span_id"]: ev
              for ev in events if ev.get("args", {}).get("span_id")}
    selfs = {sid: float(ev.get("dur", 0.0))
             for sid, ev in by_sid.items()}
    for ev in events:
        pid = ev.get("args", {}).get("parent_id")
        parent = by_sid.get(pid)
        # same-thread children only: cross-thread children (the bucket
        # worker merges) run concurrently with their parent
        if parent is not None and parent.get("tid") == ev.get("tid"):
            selfs[pid] -= float(ev.get("dur", 0.0))
    by_name: Dict[str, List[float]] = {}
    for ev in events:
        sid = ev.get("args", {}).get("span_id")
        if sid is None:
            continue
        slot = by_name.setdefault(ev.get("name", "?"), [0.0, 0])
        slot[0] += selfs.get(sid, 0.0)
        slot[1] += 1
    lines = ["", f"top {top} spans by self time:",
             f"  {'span':<36}{'self':>12}{'count':>8}"]
    ranked = sorted(by_name.items(), key=lambda kv: (-kv[1][0], kv[0]))
    for name, (self_us, count) in ranked[:top]:
        lines.append(f"  {name:<36}{self_us / 1000.0:10.3f}ms"
                     f"{count:8d}")
    return lines


def render(trace: dict, width: int = 40, top: int = 10) -> str:
    events = trace.get("traceEvents", [])
    meta = trace.get("metadata", {})
    head = []
    if meta:
        head.append(f"ledger {meta.get('ledger', '?')}: "
                    f"{meta.get('duration_ms', '?')}ms over "
                    f"{len(events)} spans")
        if meta.get("truncated_spans"):
            head.append(f"  ({meta['truncated_spans']} oldest spans "
                        "truncated from the ring)")
    lines = head + render_tree(build_forest(events), width)
    lines += self_time_table(events, top)
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="Chrome trace_event JSON file")
    ap.add_argument("--width", type=int, default=40,
                    help="flame bar width in columns")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the self-time table")
    args = ap.parse_args()
    try:
        with open(args.trace, encoding="utf-8") as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        print(f"trace_view: cannot read {args.trace}: {e}",
              file=sys.stderr)
        return 2
    print(render(trace, width=args.width, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
