#!/usr/bin/env python3
"""fleet_scrape: poll N real-TCP nodes' admin endpoints into one JSONL
stream + a fleet summary (the ROADMAP item-4 soak's aggregation path —
sims get the in-process network-observatory endpoint instead).

Each round, every node is polled for `/info`, `/metrics`, `/vitals` and
`/flood` (the r19 hop-record report); one JSONL line is written per node
per round.  After the last round a summary document is computed over the
final round: per-node ledger height / close p50 / flood dedup totals,
fleet-level height spread and per-link redundancy.

    python tools/fleet_scrape.py --nodes 127.0.0.1:11626,127.0.0.1:11628 \
        --rounds 10 --interval 2 --out fleet.jsonl

A node that fails to answer gets an "error" field in its line and is
excluded from the summary (listed under "unreachable") — a soak must
keep scraping through individual node restarts.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def fetch_json(base: str, path: str, timeout: float = 5.0) -> dict:
    """GET http://<base>/<path> and decode the JSON body."""
    with urllib.request.urlopen(f"http://{base}/{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


def scrape_node(base: str, timeout: float = 5.0,
                fetch=fetch_json) -> dict:
    """One node's round: info + metrics + vitals + flood report.
    ``fetch`` is injectable so tests can drive this without sockets."""
    doc = {"node": base}
    try:
        doc["info"] = fetch(base, "info", timeout)["info"]
        doc["metrics"] = fetch(base, "metrics", timeout)["metrics"]
    except Exception as e:
        doc["error"] = f"{type(e).__name__}: {e}"
        return doc
    # vitals/flood are best-effort: vitals may be disabled on the rig
    for path, key in (("vitals", "vitals"), ("flood?last=4", "flood")):
        try:
            doc[key] = fetch(base, path, timeout)[key]
        except Exception as e:
            doc[f"{key}_error"] = f"{type(e).__name__}: {e}"
    return doc


def _node_summary(doc: dict) -> dict:
    m = doc.get("metrics", {})

    def _count(name):
        return m.get(name, {}).get("count", 0)

    close = m.get("ledger.ledger.close", {})
    out = {
        "ledger": doc.get("info", {}).get("ledger", {}).get("num", 0),
        "close_p50_s": close.get("p50"),
        "close_count": close.get("count", 0),
        "flood_unique": _count("overlay.flood.unique"),
        "flood_duplicate": _count("overlay.flood.duplicate"),
    }
    flood = doc.get("flood")
    if flood:
        out["links"] = flood.get("links", {})
        out["trace_stats"] = {k: flood[k] for k in
                              ("stride", "tracked", "live", "retired")
                              if k in flood}
    return out


def summarize(round_docs: list) -> dict:
    """Fleet summary over one round's node documents."""
    nodes = {}
    unreachable = []
    for doc in round_docs:
        if "error" in doc:
            unreachable.append({"node": doc["node"],
                                "error": doc["error"]})
            continue
        nodes[doc["node"]] = _node_summary(doc)
    heights = [n["ledger"] for n in nodes.values()]
    uniq = sum(n["flood_unique"] for n in nodes.values())
    dup = sum(n["flood_duplicate"] for n in nodes.values())
    links = {}
    for base, n in sorted(nodes.items()):
        for pid8, row in n.get("links", {}).items():
            links[f"{base}<-{pid8}"] = row
    return {
        "nodes": nodes,
        "unreachable": unreachable,
        "fleet": {
            "n_reachable": len(nodes),
            "ledger_min": min(heights) if heights else 0,
            "ledger_max": max(heights) if heights else 0,
            "ledger_spread": (max(heights) - min(heights))
            if heights else 0,
            "flood_unique_total": uniq,
            "flood_duplicate_total": dup,
            "flood_redundancy": round(dup / (uniq + dup), 4)
            if uniq + dup else 0.0,
        },
        "links": links,
    }


def run(bases: list, rounds: int, interval: float, out_path: str,
        timeout: float = 5.0, fetch=fetch_json, sleep=time.sleep,
        now=time.time) -> dict:
    """The scrape loop; returns the final summary (also appended to the
    JSONL as a {"summary": ...} line)."""
    last_round = []
    with open(out_path, "w") as f:
        for r in range(rounds):
            t = now()
            last_round = []
            for base in bases:
                doc = scrape_node(base, timeout, fetch=fetch)
                doc["t"] = round(t, 3)
                doc["round"] = r
                last_round.append(doc)
                f.write(json.dumps(doc, sort_keys=True) + "\n")
            f.flush()
            if r + 1 < rounds:
                sleep(interval)
        summary = summarize(last_round)
        f.write(json.dumps({"summary": summary}, sort_keys=True) + "\n")
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="poll N nodes' admin endpoints into JSONL + summary")
    ap.add_argument("--nodes", required=True,
                    help="comma-separated host:http_port list")
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between rounds")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--out", default="fleet.jsonl")
    args = ap.parse_args(argv)

    bases = [b.strip() for b in args.nodes.split(",") if b.strip()]
    summary = run(bases, args.rounds, args.interval, args.out,
                  timeout=args.timeout)
    print(json.dumps(summary, indent=1, sort_keys=True))
    fleet = summary["fleet"]
    print(f"# {fleet['n_reachable']}/{len(bases)} nodes, "
          f"ledgers {fleet['ledger_min']}..{fleet['ledger_max']}, "
          f"redundancy {fleet['flood_redundancy']}", file=sys.stderr)
    return 0 if fleet["n_reachable"] == len(bases) else 1


if __name__ == "__main__":
    sys.exit(main())
