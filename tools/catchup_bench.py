#!/usr/bin/env python
"""Catchup bench (r17): a cold node joins a LIVE simulated network at
the 1M-entry tier, trailing 1000+ ledgers, while closes keep arriving.

Three phases:
  1. seed    — a core-2 validator network closes ~1000 ledgers carrying
               1M create-account entries through real transactions (so
               complete-mode replay reproduces bit-identical buckets;
               loadgen's bulk path would bypass the bucket list) and
               publishes checkpoints to a local archive.
  2. minimal — a cold node joins mid-traffic, catches up via verified
               bucket apply + buffered-live-ledger drain; measures
               time-to-synced, bucket-apply MB/s, verify/apply/replay
               phase split.
  3. complete — a second cold node joins with CATCHUP_COMPLETE=True and
               replays every ledger from genesis; measures ledgers/s
               replayed.  Acceptance: minimal time-to-synced beats it
               by >= 5x, and both joiners end bit-identical (header
               hash + bucketListHash) to the validators.

Usage: python tools/catchup_bench.py [--smoke] [--entries N]
           [--per-close N] [--out PATH]
--smoke runs a small tier (fast CI sanity; no 5x assertion).
"""
import argparse
import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small tier: quick correctness pass")
    ap.add_argument("--entries", type=int, default=None)
    ap.add_argument("--per-close", type=int, default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.smoke:
        n_entries = args.entries or 12_000
        per_close = args.per_close or 400
        out_path = args.out or "/tmp/CATCHUP_BENCH_smoke.json"
    else:
        n_entries = args.entries or 1_000_000
        per_close = args.per_close or 1_000
        out_path = args.out or os.path.join(REPO,
                                            "CATCHUP_BENCH_r17.json")
    assert per_close % 100 == 0, "per_close must be a multiple of 100"

    import tempfile

    from stellar_core_tpu.crypto import SecretKey, sha256
    from stellar_core_tpu.simulation.simulation import Simulation
    from tests.test_catchup import SimAccount

    work_dir = tempfile.mkdtemp(prefix="catchup-bench-")
    arch_dir = os.path.join(work_dir, "archive")

    # -- build the publisher network ------------------------------------
    sim = Simulation(network_passphrase="catchup bench network")
    seeds = [sha256(b"catchup-bench-%d" % i) for i in range(2)]
    ids = [SecretKey(s).public_key().raw for s in seeds]
    qset = {"threshold": 2, "validators": ids}
    common = dict(
        INVARIANT_CHECKS=[],  # measuring catchup, not the checkers
        TESTING_UPGRADE_MAX_TX_SET_SIZE=2 * per_close,
    )
    for i, s in enumerate(seeds):
        kw = dict(common)
        if i == 0:
            kw["HISTORY_ARCHIVES"] = [("bench", arch_dir)]
        sim.add_node(s, qset,
                     node_dir=os.path.join(work_dir, f"v{i}"), **kw)
    sim.add_connection(ids[0], ids[1])
    sim.start_all_nodes()
    for _ in range(200):
        if sim.crank() == 0:
            break

    apps = [sim.nodes[i] for i in ids]
    app_a = apps[0]
    root = SimAccount(app_a, SecretKey(app_a.config.network_id()))
    state = {"made": 0, "seq": root.loaded_seq()}

    def inject(n_new):
        """n_new create-account ops from the root account, <=100 per tx
        (account ids are raw hashes: the ledger doesn't care, and the
        bench shouldn't pay 1M pure-python curve derivations)."""
        while n_new > 0:
            batch = min(100, n_new)
            ops = []
            for _ in range(batch):
                dest = sha256(b"bench-acct-%d" % state["made"])
                ops.append(root.op_create_account(dest, 10**7))
                state["made"] += 1
            state["seq"] += 1
            env = root.tx(ops, seq=state["seq"])
            rc = app_a.herder.recv_transaction(env)
            assert rc == 0, f"tx rejected: {rc}"
            n_new -= batch

    def close_validators(traffic):
        if traffic:
            inject(traffic)
        target = max(a.ledger_manager.last_closed_seq()
                     for a in apps) + 1
        for a in apps:
            a.herder.trigger_next_ledger()
        ok = sim.crank_until(
            lambda: all(a.ledger_manager.last_closed_seq() >= target
                        for a in apps), timeout=300)
        assert ok, f"validators stuck closing {target}"

    # -- phase 1: seed -------------------------------------------------
    n_seed_ledgers = (n_entries + per_close - 1) // per_close
    print(f"[seed] {n_entries} entries over {n_seed_ledgers} ledgers "
          f"({per_close}/close) ...", flush=True)
    t0 = time.time()
    remaining = n_entries
    for k in range(n_seed_ledgers):
        close_validators(min(per_close, remaining))
        remaining -= per_close
        if (k + 1) % 100 == 0:
            print(f"[seed] {k + 1}/{n_seed_ledgers} ledgers, "
                  f"{state['made']} entries, "
                  f"{time.time() - t0:.0f}s, rss {rss_mb():.0f}MB",
                  flush=True)
    seed_s = time.time() - t0
    lcl_after_seed = app_a.ledger_manager.last_closed_seq()
    print(f"[seed] done: lcl={lcl_after_seed} in {seed_s:.1f}s "
          f"({state['made'] / seed_s:.0f} entries/s)", flush=True)

    # -- cold join ------------------------------------------------------
    def join_cold(tag, **extra_cfg):
        """Add a cold node to the live net and drive it to synced while
        the validators keep closing (light traffic).  Returns (joiner,
        node id, wall seconds to synced, trailing gap at join, live
        closes during catchup)."""
        seed = sha256(b"catchup-bench-joiner-" + tag.encode())
        kw = dict(common)
        kw.update(extra_cfg)
        kw["HISTORY_ARCHIVES"] = [("bench", arch_dir)]
        trailing = app_a.ledger_manager.last_closed_seq() - 1
        t_start = time.time()
        joiner = sim.add_node(
            seed, {"threshold": 2, "validators": list(ids)},
            node_dir=os.path.join(work_dir, f"joiner-{tag}"), **kw)
        joiner.start()
        jid = joiner.config.node_id()
        for vid in ids:
            sim.add_connection(jid, vid)
        for _ in range(200):
            if sim.crank() == 0:
                break

        def synced():
            return (joiner.ledger_manager.last_closed_seq() >=
                    app_a.ledger_manager.last_closed_seq())

        live = 0
        while not synced():
            close_validators(20)  # the network does not stop for you
            live += 1
            sim.crank_until(synced, timeout=10.0)
            assert live < 4000, (
                f"joiner {tag} stuck: "
                f"{joiner.catchup_manager.status()}")
        dt = time.time() - t_start
        # bit-identity: header chain AND bucket list, every shared seq
        sim.assert_no_forks([ids[0], ids[1], jid])
        assert (joiner.ledger_manager.last_closed_hash() ==
                app_a.ledger_manager.last_closed_hash())
        assert (joiner.bucket_manager.get_bucket_list_hash() ==
                app_a.bucket_manager.get_bucket_list_hash())
        return joiner, jid, dt, trailing, live

    def phase_split(app):
        out = {}
        for name in ("verify", "apply", "replay"):
            t = app.metrics.timer(f"catchup.phase.{name}")
            out[name + "_s"] = round(t.mean * t.count, 3)
        return out

    # -- phase 2: minimal ----------------------------------------------
    print("[minimal] cold node joining live network ...", flush=True)
    min_app, min_id, min_s, min_trailing, min_live = join_cold("minimal")
    applied_bytes = min_app.metrics.counter(
        "catchup.bucket.applied-bytes").count
    applied_entries = min_app.metrics.counter(
        "catchup.bucket.applied-entries").count
    min_phases = phase_split(min_app)
    apply_s = max(min_phases["apply_s"], 1e-9)
    minimal = {
        "trailing_ledgers_at_join": min_trailing,
        "time_to_synced_s": round(min_s, 2),
        "live_closes_during_catchup": min_live,
        "catchup_runs": min_app.catchup_manager.catchup_runs,
        "bucket_applied_bytes": applied_bytes,
        "bucket_applied_entries": applied_entries,
        "bucket_apply_mb_s": round(applied_bytes / 2**20 / apply_s, 2),
        "chain_headers_verified": min_app.metrics.counter(
            "catchup.chain.verified").count,
        "phase_split": min_phases,
        "bit_identical": True,
    }
    print(f"[minimal] synced in {min_s:.1f}s "
          f"(trailing {min_trailing}, "
          f"{minimal['bucket_apply_mb_s']} MB/s apply)", flush=True)

    # -- phase 3: complete ----------------------------------------------
    print("[complete] cold node replaying full history ...", flush=True)
    cmp_app, cmp_id, cmp_s, cmp_trailing, cmp_live = join_cold(
        "complete", CATCHUP_COMPLETE=True)
    replayed = cmp_app.metrics.counter("catchup.ledger.replayed").count
    cmp_phases = phase_split(cmp_app)
    replay_s = max(cmp_phases["replay_s"], 1e-9)
    complete = {
        "trailing_ledgers_at_join": cmp_trailing,
        "time_to_synced_s": round(cmp_s, 2),
        "live_closes_during_catchup": cmp_live,
        "catchup_runs": cmp_app.catchup_manager.catchup_runs,
        "ledgers_replayed": replayed,
        "replay_ledgers_per_s": round(replayed / replay_s, 2),
        "phase_split": cmp_phases,
        "bit_identical": True,
    }
    print(f"[complete] synced in {cmp_s:.1f}s "
          f"({replayed} ledgers replayed, "
          f"{complete['replay_ledgers_per_s']}/s)", flush=True)

    speedup = cmp_s / max(min_s, 1e-9)
    result = {
        "tier": "smoke" if args.smoke else "1M",
        "n_entries": state["made"],
        "seed_ledgers": n_seed_ledgers,
        "entries_per_close": per_close,
        "seed_seconds": round(seed_s, 1),
        "seed_entries_per_s": round(state["made"] / seed_s, 1),
        "final_lcl": app_a.ledger_manager.last_closed_seq(),
        "minimal": minimal,
        "complete": complete,
        "minimal_speedup_vs_complete": round(speedup, 2),
        "rss_mb": round(rss_mb(), 1),
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(f"[done] speedup {speedup:.1f}x -> {out_path}", flush=True)
    if not args.smoke:
        assert min_trailing >= 1000, \
            f"joiner only trailed {min_trailing} ledgers"
        assert speedup >= 5.0, \
            f"minimal catchup only {speedup:.1f}x faster than complete"
    return 0


if __name__ == "__main__":
    sys.exit(main())
