"""Crypto foundation tests (ref test model: src/crypto/test/CryptoTests.cpp)."""
import hashlib

import pytest

from stellar_core_tpu.crypto import (
    SecretKey,
    sha256,
    hkdf_expand,
    verify_sig,
    sign,
    encode_ed25519_public_key,
    decode_ed25519_public_key,
    encode_ed25519_seed,
    decode_ed25519_seed,
)
from stellar_core_tpu.crypto import ed25519 as ed
from stellar_core_tpu.crypto import ed25519_ref as ref
from stellar_core_tpu.crypto.shorthash import siphash24


def test_sha256_vector():
    # FIPS 180-2 test vector
    assert (
        sha256(b"abc").hex()
        == "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
    )


def test_siphash24_reference_vector():
    # Reference vector from the SipHash paper, appendix A:
    # key = 000102...0f, input = 000102...0e (15 bytes)
    key = bytes(range(16))
    data = bytes(range(15))
    assert siphash24(key, data) == 0xA129CA6149BE45E5


# RFC 8032 §7.1 TEST 1-3
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.mark.parametrize("seed,pub,msg,sig", RFC8032_VECTORS)
def test_rfc8032_sign_verify(seed, pub, msg, sig):
    seed, pub, msg, sig = (
        bytes.fromhex(seed),
        bytes.fromhex(pub),
        bytes.fromhex(msg),
        bytes.fromhex(sig),
    )
    sk = SecretKey(seed)
    assert sk.public_key().raw == pub
    assert sk.sign(msg) == sig
    assert verify_sig(pub, sig, msg)
    assert not verify_sig(pub, sig, msg + b"x")
    # pure-python spec agrees
    assert ref.verify(pub, sig, msg)
    assert not ref.verify(pub, sig, msg + b"x")


def test_ref_rejects_bad_s():
    seed, pub, msg, sig = (bytes.fromhex(x) for x in RFC8032_VECTORS[2])
    bad = sig[:32] + int.to_bytes(ref.L, 32, "little")  # S = L (non-canonical)
    assert not ref.verify(pub, bad, msg)
    assert not verify_sig(pub, bad, msg)


def test_ref_random_differential():
    """Pure-python spec vs OpenSSL on random valid/corrupt signatures."""
    import os
    import random

    for i in range(20):
        sk = SecretKey(sha256(b"diff%d" % i))
        msg = os.urandom(32)
        sig = sk.sign(msg)
        pub = sk.public_key().raw
        assert ref.verify(pub, sig, msg) == ed.raw_verify(pub, sig, msg) == True
        # corrupt one byte
        k = random.randrange(64)
        bad = bytearray(sig)
        bad[k] ^= 0x40
        bad = bytes(bad)
        assert ref.verify(pub, bad, msg) == ed.raw_verify(pub, bad, msg)


def test_verify_cache():
    ed.clear_verify_cache()
    sk = SecretKey.from_seed_str("cache")
    msg = b"hello"
    sig = sk.sign(msg)
    pub = sk.public_key().raw
    assert verify_sig(pub, sig, msg)
    h0, m0 = ed.verify_cache_stats()
    assert verify_sig(pub, sig, msg)
    h1, m1 = ed.verify_cache_stats()
    assert h1 == h0 + 1 and m1 == m0


def test_strkey_roundtrip():
    sk = SecretKey.from_seed_str("strkey")
    pub = sk.public_key().raw
    g = encode_ed25519_public_key(pub)
    assert g.startswith("G")
    assert decode_ed25519_public_key(g) == pub
    s = encode_ed25519_seed(sk.seed)
    assert s.startswith("S")
    assert decode_ed25519_seed(s) == sk.seed


def test_strkey_known_vector():
    # Well-known Stellar vector: seed/pubkey pair from stellar docs (SEP-23 era)
    g = "GDW6AUTBXTOC7FIKUO5BOO3OGLK4SF7ZPOBLMQHMZDI45J2Z6VXRB5NR"
    raw = decode_ed25519_public_key(g)
    assert encode_ed25519_public_key(raw) == g
    with pytest.raises(ValueError):
        decode_ed25519_public_key(g[:-1] + ("A" if g[-1] != "A" else "B"))


def test_strkey_rejects_wrong_version():
    sk = SecretKey.from_seed_str("ver")
    s = encode_ed25519_seed(sk.seed)
    with pytest.raises(ValueError):
        decode_ed25519_public_key(s)


def test_hkdf_expand_shape():
    out = hkdf_expand(b"\x01" * 32, b"info", 64)
    assert len(out) == 64
    assert hkdf_expand(b"\x01" * 32, b"info", 64) == out


def test_sign_function():
    sk = SecretKey.from_seed_str("fn")
    assert sign(sk.seed, b"m") == sk.sign(b"m")


def test_ed25519_ref_double_scalar_matches_naive():
    """double_scalar_mult ladder == separate scalar mults then add."""
    sk = SecretKey.from_seed_str("dsm")
    a = ref.decode_point(sk.public_key().raw)
    na = ref.point_neg(a)
    s, h = 0xDEADBEEF1234, 0xFEEDFACE5678
    combined = ref.double_scalar_mult(s, h, na)
    separate = ref.point_add(
        ref.scalar_mult(s, ref.to_extended(ref.B)), ref.scalar_mult(h, na)
    )
    assert ref.encode_point(combined) == ref.encode_point(separate)
