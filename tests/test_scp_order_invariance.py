"""Regression for the P0 determinism findings (ISSUE 3): SCP tallies and
nomination must be invariant under dict-insertion-order permutation of
the envelope maps AND under PYTHONHASHSEED variation of set iteration
order.

Before this PR, ``NominationProtocol.nominate`` iterated the
``round_leaders`` SET in hash order while ``_get_new_value_from_nomination``
skipped values already voted — a loop-carried pick, so with several
equal-priority leaders proposing OVERLAPPING values the voted set
depended on PYTHONHASHSEED.  The subprocess test below reconstructs
exactly that scenario and pins the emitted votes across seeds.
"""
import itertools
import os
import subprocess
import sys

from stellar_core_tpu.scp import SCP, make_qset, qset_hash

from tests.test_scp import TestDriver, V, X, PREV, prepare_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_scp():
    qset = make_qset(4, V)
    driver = TestDriver(qset)
    scp = SCP(driver, V[0], True, qset)
    return scp, driver, qset_hash(qset)


def test_federated_tally_invariant_under_envelope_order():
    """Same envelope set, every insertion order -> same accept/ratify
    verdicts (host tally path)."""
    verdicts = set()
    for perm in itertools.permutations(range(1, 4)):
        scp, driver, qh = mk_scp()
        slot = scp.get_slot(1)
        envelopes = {}
        for i in perm:
            envelopes[V[i]] = prepare_env(V[i], 1, qh, (1, X),
                                          prepared=(1, X))
        envelopes[V[0]] = prepare_env(V[0], 1, qh, (1, X))

        def voted(st):
            return True

        def accepted(st):
            return st.pledges.value.prepared is not None

        verdicts.add((
            slot.federated_accept(voted, accepted, envelopes),
            slot.federated_ratify(voted, envelopes),
        ))
    assert verdicts == {(True, True)}


def test_tensor_tally_build_invariant_under_envelope_order():
    """TallyEngine._build's cache key + node order must not depend on
    the envelope map's insertion order."""
    from stellar_core_tpu.scp.tally import TallyEngine

    keys = set()
    orders = set()
    for perm in itertools.permutations(range(4)):
        scp, driver, qh = mk_scp()
        slot = scp.get_slot(1)
        slot.tally = TallyEngine(slot, "tensor")
        envelopes = {}
        for i in perm:
            envelopes[V[i]] = prepare_env(V[i], 1, qh, (1, X))
        t = slot.tally._build(envelopes)
        assert t is not None
        _, _, node_order = t
        keys.add(slot.tally._cache_key)
        orders.add(tuple(node_order))
    assert len(keys) == 1
    assert len(orders) == 1


# ---------------------------------------------------------------------------
# the multi-leader nomination P0, across hash seeds
# ---------------------------------------------------------------------------

# Three equal-top-priority leaders propose OVERLAPPING value pairs; the
# leader-echo pick skips values already voted, so the voted set is a
# function of leader iteration order.  Emits the final votes (the
# nomination statement sorts them, but the SET content is what varied).
_NOMINATION_WORKER = """
import hashlib
import sys

sys.path.insert(0, {repo!r})

from stellar_core_tpu.scp import SCP, make_qset, qset_hash
from tests.test_scp import TestDriver, V, PREV, nominate_env

LEADERS = set(V[1:4])
A = hashlib.sha256(b"val-a").digest()
B = hashlib.sha256(b"val-b").digest()
C = hashlib.sha256(b"val-c").digest()

qset = make_qset(4, V)
driver = TestDriver(qset)
driver.compute_hash_node = (
    lambda slot_index, prev, is_priority, round_num, node_id:
    (2**63 if node_id in LEADERS else 1) if is_priority else 0)
scp = SCP(driver, V[0], True, qset)
slot = scp.get_slot(1)
qh = qset_hash(qset)
nom = slot.nomination
proposals = {{V[1]: [A, B], V[2]: [B, C], V[3]: [C, A]}}
for node in V[1:4]:
    nom.latest_nominations[node] = nominate_env(
        node, 1, qh, proposals[node])
slot.nominate(A, PREV, False)
for v in sorted(nom.votes):
    print(v.hex())
"""


def test_nomination_votes_invariant_under_hashseed():
    """The emitted nomination vote set must be identical no matter how
    PYTHONHASHSEED orders the round_leaders set."""
    outputs = set()
    runs = []
    for seed in ("0", "1", "7", "4242"):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = seed
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _NOMINATION_WORKER.format(repo=REPO)],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=300)
        assert proc.returncode == 0, proc.stderr[-4000:]
        out = proc.stdout.strip()
        assert out, "worker emitted no votes"
        outputs.add(out)
        runs.append((seed, out))
    assert len(outputs) == 1, (
        "nomination votes depend on PYTHONHASHSEED:\n" + "\n".join(
            f"  seed {s}: {o.replace(chr(10), ' ')}" for s, o in runs))
