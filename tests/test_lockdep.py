"""Runtime lockdep witness tests (ISSUE 18: the dynamic half of the
detlint v3 concurrency layer, utils/lockdep.py).

What must hold:
- disabled (the default) is FREE: register_lock returns the raw lock
  object untouched and guard_fields is a no-op;
- enabled, two locks ever taken in opposite orders fail fast with both
  witness chains (including transitively: A->B->C then C..A);
- wrapped RLocks stay reentrant and never record self-edges;
- ``# guarded-by:`` annotations become assert-held WRITE hooks: a
  guarded field assigned without its lock held raises GuardViolation,
  construction writes before guard_fields() stay exempt;
- a real pipelined-close node runs CLEAN under the witness (no
  inversions, no guard violations) while actually exercising it;
- the enabled witness is cheap enough that the measured per-close
  cost (probe-scale acquire + guard-check counts) stays under 1% of
  the close p50 the same probe measures (tools/pipeline_bench.py
  --lockdep-probe; re-derived here from micro-benchmarks with the
  probe's counts so the gate runs without a bench).
"""
import threading
import time

import pytest

from stellar_core_tpu.utils import lockdep
from stellar_core_tpu.utils.lockdep import (GuardViolation,
                                            LockOrderInversion,
                                            WitnessLock, register_lock)


@pytest.fixture
def witness(monkeypatch):
    """Arm the witness in-process (the tier-1 environment runs with
    LOCKDEP unset) and drop the order graph afterwards so tests stay
    independent."""
    monkeypatch.setattr(lockdep, "LOCKDEP_ENABLED", True)
    lockdep.reset()
    yield lockdep
    lockdep.reset()


# -- disabled cost ---------------------------------------------------------

def test_disabled_register_returns_raw_lock(monkeypatch):
    """LOCKDEP off: the raw lock object comes back untouched — zero
    wrapper, zero per-acquire cost (better than the <=1-attr-check
    budget).  Forced off so the contract also holds inside the
    LOCKDEP=1 smoke run."""
    monkeypatch.setattr(lockdep, "LOCKDEP_ENABLED", False)
    lk = threading.Lock()
    assert register_lock(lk, "x") is lk
    rlk = threading.RLock()
    assert register_lock(rlk, "y") is rlk


def test_disabled_guard_fields_noop(monkeypatch):
    monkeypatch.setattr(lockdep, "LOCKDEP_ENABLED", False)

    class Plain:
        def __init__(self):
            self._lock = register_lock(threading.Lock(), "plain")
            self.val = 0  # guarded-by: _lock
            lockdep.guard_fields(self)

    p = Plain()
    p.val = 7  # no lock held, no descriptor, no complaint
    assert p.val == 7
    assert not isinstance(type(p).__dict__.get("val"),
                          lockdep._GuardedField)


# -- order witnessing ------------------------------------------------------

def test_inversion_detected_with_chain(witness):
    a = register_lock(threading.Lock(), "A")
    b = register_lock(threading.Lock(), "B")
    assert isinstance(a, WitnessLock)
    with a:
        with b:
            pass
    with pytest.raises(LockOrderInversion) as ei:
        with b:
            with a:
                pass
    msg = str(ei.value)
    assert "'A'" in msg and "'B'" in msg
    assert "A -> B" in msg  # the established-order witness chain
    assert lockdep.stats()["inversions"] == 1


def test_transitive_inversion_chain(witness):
    a = register_lock(threading.Lock(), "A")
    b = register_lock(threading.Lock(), "B")
    c = register_lock(threading.Lock(), "C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    # no direct A..C edge exists; the cycle is only visible through the
    # transitive order graph, and the full chain must be in the message
    with pytest.raises(LockOrderInversion) as ei:
        with c:
            with a:
                pass
    assert "A -> B -> C" in str(ei.value)


def test_consistent_order_is_clean(witness):
    a = register_lock(threading.Lock(), "A")
    b = register_lock(threading.Lock(), "B")
    for _ in range(3):
        with a:
            with b:
                pass
    st = lockdep.stats()
    assert st["inversions"] == 0
    assert st["edges"] == 1  # recorded once, fast-pathed after


def test_rlock_reentry_no_self_edge(witness):
    r = register_lock(threading.RLock(), "R")
    with r:
        with r:
            assert r.held_by_me()
    st = lockdep.stats()
    assert st["edges"] == 0  # reentry records no order edge
    assert st["inversions"] == 0


def test_cross_thread_orders_merge(witness):
    """The order graph is process-wide: thread 1 establishes A->B,
    thread 2's B->A attempt must trip even though thread 2 never saw
    the first ordering itself."""
    a = register_lock(threading.Lock(), "A")
    b = register_lock(threading.Lock(), "B")

    def establish():
        with a:
            with b:
                pass

    t = threading.Thread(target=establish)
    t.start()
    t.join()
    caught = []

    def invert():
        try:
            with b:
                with a:
                    pass
        except LockOrderInversion as e:
            caught.append(e)

    t2 = threading.Thread(target=invert)
    t2.start()
    t2.join()
    assert len(caught) == 1
    assert "thread" in str(caught[0])


# -- guarded-field enforcement --------------------------------------------

class Guarded:
    """Module-level so inspect.getsource sees the same ``# guarded-by:``
    lines detlint reads."""

    def __init__(self):
        self._lock = register_lock(threading.Lock(), "guarded.box")
        self.count = 0    # guarded-by: _lock
        self.label = ""   # guarded-by: _lock
        self.unguarded = 0
        lockdep.guard_fields(self)

    def bump(self):
        with self._lock:
            self.count += 1


def test_guard_violation_on_unlocked_write(witness):
    g = Guarded()
    g.bump()
    assert g.count == 1
    with pytest.raises(GuardViolation) as ei:
        g.count = 99
    msg = str(ei.value)
    assert "Guarded.count" in msg and "guarded.box" in msg
    assert lockdep.stats()["guard_violations"] == 1
    # the failed write must not have landed
    assert g.count == 1


def test_guarded_write_under_lock_passes(witness):
    g = Guarded()
    with g._lock:
        g.count = 5
        g.label = "ok"
    assert g.count == 5 and g.label == "ok"
    assert lockdep.stats()["guard_violations"] == 0


def test_unguarded_field_and_construction_exempt(witness):
    # __init__ writes happen before guard_fields() arms the instance,
    # and un-annotated fields never get a descriptor
    g = Guarded()
    g.unguarded = 42  # no annotation, no check
    assert g.unguarded == 42
    g2 = Guarded()    # second instance constructs through the armed
    assert g2.count == 0  # descriptors without tripping


def test_reads_unchecked(witness):
    # read-side races are a documented relaxation (COVERAGE.md): the
    # close pipeline reads benign-stale fields lock-free by design
    g = Guarded()
    assert g.count == 0  # no lock held, no complaint


# -- a real node under the witness ----------------------------------------

def test_pipelined_close_clean_under_witness(witness):
    """A pipelined-close node (close tail on a worker, guarded fields
    armed in Database/ClosePipeline/TxLifecycleTracker/...) must run
    CLEAN: zero inversions, zero guard violations — while the witness
    demonstrably saw traffic (acquires > 0, guard checks > 0)."""
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        PIPELINED_CLOSE=True, PIPELINED_CLOSE_EAGER_DRAIN=False))
    app.start()
    try:
        for _ in range(4):
            app.herder.manual_close()
        app.ledger_manager.pipeline.drain()
    finally:
        app.graceful_stop()
    st = lockdep.stats()
    assert st["acquires"] > 0, "witness saw no lock traffic"
    assert st["guard_checks"] > 0, "no guarded-field writes checked"
    assert st["inversions"] == 0
    assert st["guard_violations"] == 0


# -- overhead gate ---------------------------------------------------------

def _per_op(fn, n):
    fn(n // 10)  # warm
    t0 = time.perf_counter()
    fn(n)
    return (time.perf_counter() - t0) / n


def test_witness_overhead_under_one_percent_of_close_p50(witness):
    """The acceptance bound, bench-free: per-acquire overhead and
    per-guard-check cost from in-process micro-benchmarks, scaled by
    the per-close counts the pipeline probe measures at smoke scale
    (~480 acquires + ~390 guard checks per 120-tx close, close p50
    ~110 ms — tools/pipeline_bench.py --lockdep-probe), must land
    under 1% with real headroom.  The authoritative end-to-end figure
    is verify_green --lockdep-smoke; this keeps a regression from
    landing silently between smoke runs."""
    raw = threading.Lock()
    wit = register_lock(threading.Lock(), "bench.overhead")

    def loop(lk):
        def run(n):
            for _ in range(n):
                with lk:
                    pass
        return run

    n = 100000
    acq_over_us = max(
        0.0, (_per_op(loop(wit), n) - _per_op(loop(raw), n)) * 1e6)

    g = Guarded()

    def checks(n):
        with g._lock:
            for i in range(n):
                g.count = i

    chk_us = _per_op(checks, n) * 1e6
    # probe-scale per-close counts x measured per-op cost, vs the
    # probe's ~110ms close p50; 1% = 1.1ms.  Measured ~0.62ms on the
    # dev box — assert the same formula with CI-noise headroom.
    per_close_ms = (480 * acq_over_us + 390 * chk_us) / 1000.0
    assert per_close_ms < 1.65, (
        f"witness cost {per_close_ms:.2f}ms/close "
        f"(acquire +{acq_over_us:.2f}us, check {chk_us:.2f}us) — "
        f"over 1.5x the 1%-of-close-p50 budget")
