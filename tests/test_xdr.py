"""XDR runtime + schema tests (ref test model: xdrpp round-trip tests and
src/util/test/XDRStreamTests.cpp)."""
import pytest

from stellar_core_tpu.xdr import XdrError, xdr_sha256
from stellar_core_tpu.xdr import runtime as R
from stellar_core_tpu.xdr import types as T


def test_primitive_encodings():
    assert R.Int.encode(1) == b"\x00\x00\x00\x01"
    assert R.Int.encode(-1) == b"\xff\xff\xff\xff"
    assert R.Uint.encode(2**32 - 1) == b"\xff\xff\xff\xff"
    assert R.Hyper.encode(-2) == b"\xff" * 7 + b"\xfe"
    assert R.Uhyper.encode(2**64 - 1) == b"\xff" * 8
    assert R.Bool.encode(True) == b"\x00\x00\x00\x01"
    with pytest.raises(XdrError):
        R.Int.encode(2**31)
    with pytest.raises(XdrError):
        R.Uint.encode(-1)


def test_opaque_padding():
    assert R.Opaque(3).encode(b"abc") == b"abc\x00"
    assert R.VarOpaque().encode(b"abcde") == (
        b"\x00\x00\x00\x05abcde\x00\x00\x00"
    )
    # nonzero padding rejected on decode
    with pytest.raises(XdrError):
        R.Opaque(3).decode(b"abcX")
    assert R.Opaque(3).decode(b"abc\x00") == b"abc"


def test_var_opaque_max_enforced():
    with pytest.raises(XdrError):
        R.VarOpaque(4).encode(b"abcde")
    data = b"\x00\x00\x00\x05abcde\x00\x00\x00"
    with pytest.raises(XdrError):
        R.VarOpaque(4).decode(data)


def test_optional():
    t = R.Option(R.Int)
    assert t.encode(None) == b"\x00\x00\x00\x00"
    assert t.encode(7) == b"\x00\x00\x00\x01\x00\x00\x00\x07"
    assert t.decode(t.encode(None)) is None
    assert t.decode(t.encode(7)) == 7


def test_struct_union_roundtrip():
    v = T.Price.make(n=3, d=7)
    assert T.Price.decode(T.Price.encode(v)) == v
    m = T.Memo.make(T.MemoType.MEMO_ID, 42)
    assert T.Memo.decode(T.Memo.encode(m)) == m
    with pytest.raises(XdrError):
        T.Memo.make(99, None)  # unknown discriminant


def test_enum_rejects_unknown_value_on_decode():
    bad = b"\x00\x00\x00\x63"  # 99
    with pytest.raises(XdrError):
        T.MemoType.decode(bad)


def _example_account_entry():
    key = b"\x07" * 32
    return T.AccountEntry.make(
        accountID=T.account_id(key),
        balance=10**9,
        seqNum=2**33,
        numSubEntries=2,
        inflationDest=None,
        flags=T.AUTH_REQUIRED_FLAG,
        homeDomain=b"example.com",
        thresholds=b"\x01\x00\x01\x02",
        signers=[T.Signer.make(
            key=T.SignerKey.make(
                T.SignerKeyType.SIGNER_KEY_TYPE_ED25519, b"\x09" * 32),
            weight=5)],
        ext=T.AccountEntry.fields[9][1].make(0),
    )


def test_ledger_entry_roundtrip():
    acc = _example_account_entry()
    le = T.LedgerEntry.make(
        lastModifiedLedgerSeq=17,
        data=T.LedgerEntryData.make(T.LedgerEntryType.ACCOUNT, acc),
        ext=T.LedgerEntry.fields[2][1].make(0),
    )
    b = T.LedgerEntry.encode(le)
    assert T.LedgerEntry.decode(b) == le
    # canonical: re-encode of decode is byte-identical
    assert T.LedgerEntry.encode(T.LedgerEntry.decode(b)) == b


def test_transaction_envelope_roundtrip():
    key = b"\x03" * 32
    acc = T.muxed_account(key)
    pay = T.PaymentOp.make(
        destination=acc,
        asset=T.Asset.make(T.AssetType.ASSET_TYPE_NATIVE),
        amount=5_0000000,
    )
    op = T.Operation.make(
        sourceAccount=None,
        body=T.OperationBody.make(T.OperationType.PAYMENT, pay),
    )
    tx = T.Transaction.make(
        sourceAccount=acc,
        fee=100,
        seqNum=7,
        cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
        memo=T.MEMO_NONE_VALUE,
        operations=[op],
        ext=T.Transaction.fields[6][1].make(0),
    )
    env = T.TransactionEnvelope.make(
        T.EnvelopeType.ENVELOPE_TYPE_TX,
        T.TransactionV1Envelope.make(
            tx=tx,
            signatures=[T.DecoratedSignature.make(
                hint=b"\x03\x03\x03\x03", signature=b"\x05" * 64)],
        ),
    )
    b = T.TransactionEnvelope.encode(env)
    assert T.TransactionEnvelope.decode(b) == env
    assert len(xdr_sha256(T.TransactionEnvelope, env)) == 32


def test_scp_statement_roundtrip():
    st = T.SCPStatement.make(
        nodeID=T.account_id(b"\x01" * 32),
        slotIndex=9,
        pledges=T.SCPStatementPledges.make(
            T.SCPStatementType.SCP_ST_NOMINATE,
            T.SCPNomination.make(
                quorumSetHash=b"\x02" * 32,
                votes=[b"v1", b"v2"],
                accepted=[],
            ),
        ),
    )
    env = T.SCPEnvelope.make(statement=st, signature=b"\x04" * 64)
    b = T.SCPEnvelope.encode(env)
    assert T.SCPEnvelope.decode(b) == env


def test_recursive_quorum_set():
    def nid(i):
        return T.account_id(bytes([i]) * 32)

    qs = T.SCPQuorumSet.make(
        threshold=2,
        validators=[nid(1)],
        innerSets=[T.SCPQuorumSet.make(
            threshold=1, validators=[nid(2), nid(3)], innerSets=[])],
    )
    b = T.SCPQuorumSet.encode(qs)
    assert T.SCPQuorumSet.decode(b) == qs


def test_ledger_header_roundtrip():
    sv = T.StellarValue.make(
        txSetHash=b"\x0a" * 32,
        closeTime=123456,
        upgrades=[],
        ext=T.StellarValue.fields[3][1].make(
            T.StellarValueType.STELLAR_VALUE_BASIC),
    )
    hdr = T.LedgerHeader.make(
        ledgerVersion=19,
        previousLedgerHash=b"\x0b" * 32,
        scpValue=sv,
        txSetResultHash=b"\x0c" * 32,
        bucketListHash=b"\x0d" * 32,
        ledgerSeq=100,
        totalCoins=10**15,
        feePool=500,
        inflationSeq=0,
        idPool=99,
        baseFee=100,
        baseReserve=5000000,
        maxTxSetSize=1000,
        skipList=[b"\x00" * 32] * 4,
        ext=T.LedgerHeader.fields[14][1].make(0),
    )
    b = T.LedgerHeader.encode(hdr)
    assert T.LedgerHeader.decode(b) == hdr


def test_trailing_bytes_rejected():
    b = T.Price.encode(T.Price.make(n=1, d=2))
    with pytest.raises(XdrError):
        T.Price.decode(b + b"\x00\x00\x00\x00")


def test_transaction_result_roundtrip():
    res = T.TransactionResult.make(
        feeCharged=100,
        result=T.TransactionResult.fields[1][1].make(
            T.TransactionResultCode.txSUCCESS,
            [T.OperationResult.make(
                T.OperationResultCode.opINNER,
                T.OperationResultTr.make(
                    T.OperationType.PAYMENT,
                    T.PaymentResult.make(
                        T.PaymentResultCode.PAYMENT_SUCCESS)))],
        ),
        ext=T.TransactionResult.fields[2][1].make(0),
    )
    b = T.TransactionResult.encode(res)
    assert T.TransactionResult.decode(b) == res


def test_adversarial_nesting_depth_bounded():
    # a ~400-level-deep SCPQuorumSet must fail with XdrError, not
    # RecursionError (wire-facing decode contract)
    inner = T.SCPQuorumSet.make(threshold=1, validators=[], innerSets=[])
    for _ in range(400):
        inner = T.SCPQuorumSet.make(
            threshold=1, validators=[], innerSets=[inner])
    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(10000)
    try:
        data = T.SCPQuorumSet.encode(inner)
    finally:
        sys.setrecursionlimit(old)
    with pytest.raises(XdrError):
        T.SCPQuorumSet.decode(data)


def test_native_encoder_differential():
    """The C schema-VM packer (native/xdr_pack.c) must be byte-identical
    to the Python combinator walk on every type it compiled — checked on
    decoded wire samples AND error behavior."""
    import pytest

    from stellar_core_tpu.xdr import types as T
    from stellar_core_tpu.xdr.runtime import XdrError

    if not T.NATIVE_ENCODE:
        pytest.skip("native encoder unavailable")

    def py_encode(t, v):
        out = []
        t.pack(v, out)
        return b"".join(out)

    from stellar_core_tpu.crypto import SecretKey, sha256
    from stellar_core_tpu.transactions import utils as U

    sk = SecretKey(sha256(b"native-diff"))
    pub = sk.public_key().raw
    samples = [
        (T.LedgerEntry, U.make_account_entry(pub, 12345, seq_num=7)),
        (T.LedgerEntry, U.make_trustline_entry(
            pub, U.make_asset(b"USD", pub), balance=55)),
        (T.Price, T.Price.make(n=3, d=7)),
        (T.Asset, U.asset_native()),
        (T.SCPQuorumSet, T.SCPQuorumSet.make(
            threshold=2, validators=[T.account_id(pub)], innerSets=[
                T.SCPQuorumSet.make(threshold=1,
                                    validators=[T.account_id(pub)],
                                    innerSets=[])])),
        (T.ClaimPredicate, T.ClaimPredicate.make(
            T.ClaimPredicateType.CLAIM_PREDICATE_OR, [
                T.ClaimPredicate.make(
                    T.ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL),
                T.ClaimPredicate.make(
                    T.ClaimPredicateType
                    .CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME, 99)])),
    ]
    for t, v in samples:
        enc = t.encode(v)
        assert enc == py_encode(t, v)
        # round-trip through decode and re-encode both ways
        v2 = t.decode(enc)
        assert t.encode(v2) == py_encode(t, v2) == enc
    # error parity: bad sizes/ranges still raise XdrError
    with pytest.raises(XdrError):
        T.Price.encode(T.Price.make(n=2**31, d=1))
    with pytest.raises(XdrError):
        T.Hash.encode(b"short")
