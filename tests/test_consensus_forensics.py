"""Consensus forensics (ISSUE 14): the per-slot SCP timeline recorder
(scp/timeline.py), the quorum-health monitor (herder/quorum_health.py),
bounded-cardinality metric families (MetricsRegistry.bounded_name),
and the chaos engine's network-wide fork forensics.

The load-bearing contracts:

* the recorder is INERT — telemetry-on and telemetry-off closes are
  bit-identical (ledger hash, bucket hash, encoded meta bytes);
* cross-node timeline merges detect equivocation (two mutually
  unordered statements from one node for one slot) and the induced
  fork's FORENSICS_*.json names the Byzantine node and the forked
  slot, byte-identically across same-seed reruns;
* adversarial label mixes (hostile op shapes, peer churn) cannot grow
  the /metrics payload without bound.
"""
import hashlib
import json

import pytest

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.scp.timeline import (
    SCPTimeline, find_equivocations, is_newer_summary,
    summaries_equivocate, value_tag,
)
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.utils.metrics import MetricsRegistry, render_prometheus
from stellar_core_tpu.xdr import types as T


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# timeline ring
# ---------------------------------------------------------------------------

def test_disabled_timeline_records_nothing():
    tl = SCPTimeline()  # bare recorder: disabled, inert
    assert not tl.enabled
    tl.record(1, "env", {"from": "aa"})
    assert tl.slots() == []
    assert tl.export()["slots"] == {}


def test_per_slot_ring_drops_oldest_and_counts():
    tl = SCPTimeline(clock=FakeClock(), enabled=True, per_slot=8)
    for i in range(11):
        tl.record(5, "env", {"i": i})
    doc = tl.export(5)
    assert doc["recorded"] and doc["dropped"] == 3
    assert [e["i"] for e in doc["events"]] == list(range(3, 11))


def test_slot_ring_evicts_oldest_slot():
    tl = SCPTimeline(clock=FakeClock(), enabled=True, max_slots=3)
    for s in (1, 2, 3, 4, 5):
        tl.record(s, "nom.round", {"round": 1})
    assert tl.slots() == [3, 4, 5]
    assert tl.dropped_slots == 2
    assert tl.export(1)["recorded"] is False


def test_event_carries_clock_time():
    clk = FakeClock()
    tl = SCPTimeline(clock=clk, enabled=True)
    clk.t = 1.25
    tl.record(7, "timer.arm", {"timer": "nom"})
    assert tl.export(7)["events"][0]["t"] == 1.25


# ---------------------------------------------------------------------------
# statement summaries: order + equivocation detection
# ---------------------------------------------------------------------------

def _nom(votes, accepted=()):
    return {"type": "NOMINATE", "votes": list(votes),
            "accepted": list(accepted)}


def _prep(b, p=None, pp=None, nC=0, nH=0):
    return {"type": "PREPARE", "b": b, "p": p, "pp": pp,
            "nC": nC, "nH": nH}


def test_nomination_summary_order():
    older, newer = _nom(["aa"]), _nom(["aa", "bb"], ["aa"])
    assert is_newer_summary(older, newer) is True
    assert is_newer_summary(newer, older) is False
    assert is_newer_summary(older, older) is False  # equal: not newer
    assert not summaries_equivocate(older, newer)


def test_ballot_phase_rank_orders_summaries():
    prep = _prep([1, "aa"])
    conf = {"type": "CONFIRM", "b": [1, "aa"], "nP": 1, "nC": 1, "nH": 1}
    ext = {"type": "EXTERNALIZE", "c": [1, "aa"], "nH": 1}
    assert is_newer_summary(prep, conf) is True
    assert is_newer_summary(conf, ext) is True
    assert is_newer_summary(ext, conf) is False
    assert not summaries_equivocate(prep, conf)


def test_disjoint_nominations_equivocate():
    a, b = _nom(["aa"]), _nom(["bb"])
    assert is_newer_summary(a, b) is False
    assert is_newer_summary(b, a) is False
    assert summaries_equivocate(a, b)


def test_cross_protocol_pairs_never_equivocate():
    assert is_newer_summary(_nom(["aa"]), _prep([1, "aa"])) is None
    assert not summaries_equivocate(_nom(["aa"]), _prep([1, "aa"]))


def _export(events_by_slot):
    return {"slots": {str(s): {"dropped": 0, "events": evs}
                      for s, evs in events_by_slot.items()}}


def test_find_equivocations_names_emitter_and_witnesses():
    twin_a = {"kind": "env", "t": 1.0, "from": "badc0ffe",
              "st": _nom(["aa"]), "fp": "f1"}
    twin_b = {"kind": "env", "t": 1.1, "from": "badc0ffe",
              "st": _nom(["bb"]), "fp": "f2"}
    # each honest half saw a different twin; one witness saw both
    out = find_equivocations({
        "n1": _export({4: [twin_a]}),
        "n2": _export({4: [twin_b]}),
        "n3": _export({4: [twin_a, twin_b]}),
    })
    assert len(out) == 1
    e = out[0]
    assert (e["slot"], e["node"], e["proto"]) == (4, "badc0ffe", "nom")
    assert e["conflicting_pairs"] == 1
    wit = {w for s in e["statements"] for w in s["witnesses"]}
    assert wit == {"n1", "n2", "n3"}


def test_find_equivocations_ignores_honest_progressions():
    older = {"kind": "env", "t": 1.0, "from": "cafe0001",
             "st": _nom(["aa"]), "fp": "f1"}
    newer = {"kind": "env", "t": 1.5, "from": "cafe0001",
             "st": _nom(["aa", "bb"], ["aa"]), "fp": "f2"}
    assert find_equivocations({"n1": _export({4: [older, newer]})}) == []


def test_value_tag_is_order_preserving_prefix():
    assert value_tag(None) is None
    v = bytes(range(64))
    assert value_tag(v) == v[:40].hex()


# ---------------------------------------------------------------------------
# inertness: telemetry-on vs telemetry-off closes are bit-identical
# ---------------------------------------------------------------------------

def _close_fingerprints(**cfg_kw):
    """(ledger hash, bucket hash, encoded meta) per close over a real
    payment workload, plus the recorder's event count at the end."""
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                     test_config(**cfg_kw))
    app.start()
    handler = CommandHandler(app)
    prints = []

    def close():
        app.herder.manual_close()
        meta = app._meta_stream[-1] if app._meta_stream else None
        prints.append((
            app.ledger_manager.last_closed_hash(),
            app.bucket_manager.get_bucket_list_hash(),
            T.LedgerCloseMeta.encode(meta) if meta is not None else b""))

    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "20"})
    assert code == 200, body
    close()
    for _ in range(3):
        code, body = handler.handle("generateload",
                                    {"mode": "pay", "txs": "30"})
        assert code == 200, body
        close()
    tl = app.herder.scp.timeline
    events = sum(len(b.events) for b in tl._slots.values())
    app.graceful_stop()
    return prints, events


def test_recorder_on_off_closes_bit_identical():
    on, on_events = _close_fingerprints(SCP_TIMELINE_ENABLED=True)
    off, off_events = _close_fingerprints(SCP_TIMELINE_ENABLED=False)
    assert on_events > 0, "enabled recorder captured nothing"
    assert off_events == 0, "disabled recorder captured events"
    assert len(on) == len(off) >= 4
    for i, (a, b) in enumerate(zip(on, off)):
        assert a[0] == b[0], f"ledger hash diverged at close {i}"
        assert a[1] == b[1], f"bucket hash diverged at close {i}"
        assert a[2] == b[2], f"meta bytes diverged at close {i}"
    assert any(len(m) > 200 for _, _, m in on)


# ---------------------------------------------------------------------------
# the scp / quorum-health endpoints
# ---------------------------------------------------------------------------

def _closed_node():
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    app.start()
    handler = CommandHandler(app)
    code, _ = handler.handle("generateload",
                             {"mode": "create", "accounts": "10"})
    assert code == 200
    app.herder.manual_close()
    app.herder.manual_close()
    return app, handler


def test_scp_endpoint_serves_slot_timeline():
    app, handler = _closed_node()
    try:
        tl = app.herder.scp.timeline
        assert tl.slots(), "no recorded slots after two closes"
        code, body = handler.handle("scp", {"slot": str(tl.slots()[-1])})
        assert code == 200
        evs = body["timeline"]["events"]
        kinds = {e["kind"] for e in evs}
        # the full consensus story of one slot: nomination, ballot,
        # timers, inbound envelopes with verdicts
        assert {"nom.round", "ballot.bump", "env"} <= kinds
        assert {"ballot.externalize"} <= kinds
        assert all(e["ok"] for e in evs if e["kind"] == "env")
        code, body = handler.handle("scp", {})
        assert code == 200 and body["timeline"]["enabled"]
        assert body["timeline"]["slots"] == tl.slots()
        # the full body's timeline is a ring SUMMARY (slot list, no
        # events) — the slot renderer must not crash on it
        from tools.trace_view import render_slots

        assert "no slot timeline events" in render_slots(body)
        code, _ = handler.handle("scp", {"slot": "bogus"})
        assert code == 400
        for bad in ("0", "-3", "junk"):
            code, _ = handler.handle("scp", {"limit": bad})
            assert code == 400, f"limit={bad} accepted"
    finally:
        app.graceful_stop()


def test_quorum_health_endpoint_and_metrics():
    app, handler = _closed_node()
    try:
        # the monitor ran on every close (standalone: qset == self)
        qh = app.herder.quorum_health
        assert qh.evaluations >= 2
        assert qh.last["available"] is True
        assert qh.last["heard_fraction"] == 1.0
        assert not qh.last["silent_v_blocking"]
        snap = app.metrics.snapshot()
        assert snap["quorum.health.available"]["value"] == 1.0
        code, body = handler.handle(
            "quorum-health", {"intersection": "true"})
        assert code == 200
        rep = body["quorum_health"]
        assert rep["enabled"] and rep["intersection"]["ok"] is True
        assert snap["quorum.health.evaluations"]["count"] >= 2
    finally:
        app.graceful_stop()


def test_quorum_health_degraded_before_hearing_peers():
    """Core-4 threshold-3 qset, nothing heard yet: the local slice is
    unsatisfiable from {self} and the silent set is v-blocking."""
    from stellar_core_tpu.simulation.simulation import core

    sim = core(4)
    nid = sorted(sim.nodes)[0]
    qh = sim.nodes[nid].herder.quorum_health
    rep = qh.evaluate(1)
    assert rep["qset_members"] == 4 and rep["heard"] == 1
    assert rep["available"] is False
    assert rep["silent_v_blocking"] is True
    assert len(rep["silent"]) == 3
    m = sim.nodes[nid].metrics.snapshot()
    assert m["quorum.health.available"]["value"] == 0.0
    assert m["quorum.health.silent-v-blocking"]["value"] == 1.0


def test_vitals_slo_quorum_availability_breach():
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                     test_config(VITALS_ENABLED=True))
    app.start()
    try:
        app.vitals.sample_once()
        assert app.vitals.breach_counts().get("quorum-availability") \
            is None
        app.metrics.counter("quorum.health.evaluations").inc()
        app.metrics.gauge("quorum.health.available").set(0.0)
        app.vitals.sample_once()
        assert app.vitals.breach_counts()["quorum-availability"] == 1
    finally:
        app.graceful_stop()


# ---------------------------------------------------------------------------
# bounded-cardinality metric families
# ---------------------------------------------------------------------------

def test_bounded_name_admits_then_overflows():
    reg = MetricsRegistry()
    assert reg.bounded_name("fam", "a", cap=2) == "fam.a"
    assert reg.bounded_name("fam", "b", cap=2) == "fam.b"
    assert reg.bounded_name("fam", "c", cap=2) == "fam.other"
    # admitted members stay admitted; the cap is on DISTINCT members
    assert reg.bounded_name("fam", "a", cap=2) == "fam.a"
    reg.reset()
    assert reg.bounded_name("fam", "c", cap=2) == "fam.c"


def test_bounded_name_sanitizes_hostile_members():
    reg = MetricsRegistry()
    assert reg.bounded_name("fam", "op code\nevil", cap=4) == \
        "fam.op_code_evil"
    assert reg.bounded_name("fam", "", cap=4) == "fam.unknown"


def test_metrics_payload_bounded_under_adversarial_op_mix():
    """An adversarial mix minting 500 distinct (op, reason) pairs must
    not grow the registry — or the /metrics payload — past the cap."""
    reg = MetricsRegistry()
    for i in range(500):
        reg.counter(reg.bounded_name(
            "apply.native.decline", f"op{i}.why{i % 7}", cap=48)).inc()
    names = [n for n in reg._metrics
             if n.startswith("apply.native.decline")]
    assert len(names) == 49  # 48 admitted + the "other" bucket
    assert reg._metrics["apply.native.decline.other"].count == 500 - 48
    exposition = render_prometheus(reg)
    # payload growth is the admitted family only, not the mix size
    assert exposition.count("apply_native_decline") < 200
    before = len(exposition)
    for i in range(500, 1000):
        reg.counter(reg.bounded_name(
            "apply.native.decline", f"op{i}.x", cap=48)).inc()
    assert len(render_prometheus(reg)) == before


def test_peer_gauge_export_is_bounded_by_family_cap():
    """overlay.peer.* gauges ride bounded_name too: peer churn past
    the cap lands in the per-family `other` member."""
    reg = MetricsRegistry()
    for i in range(40):
        reg.gauge(reg.bounded_name(
            "overlay.peer.queue_depth", f"{i:08x}", cap=17)).set(1.0)
    fam = [n for n in reg._metrics
           if n.startswith("overlay.peer.queue_depth")]
    assert len(fam) == 18


def test_peer_gauge_export_churn_zeroes_stale_and_folds_overflow():
    """Peer churn against export_peer_gauges: a disconnected peer's
    gauges drop to zero (not freeze at last values), and a churned-in
    peer past the admission cap folds into the `other` roll-up
    (instead of overwriting it)."""
    from stellar_core_tpu.overlay.manager import OverlayManager

    om = OverlayManager.__new__(OverlayManager)
    om.app = type("A", (), {})()
    om.app.metrics = reg = MetricsRegistry()
    om._exported_peer_gauges = set()
    om.PEER_VITALS_CAP = 2
    vit = {"aaaa0001": {"queue_depth": 3.0},
           "bbbb0002": {"queue_depth": 5.0}}
    om.peer_vitals = lambda cap=None: dict(vit)
    om.export_peer_gauges()
    assert reg._metrics["overlay.peer.queue_depth.aaaa0001"].value == 3.0
    assert reg._metrics["overlay.peer.queue_depth.bbbb0002"].value == 5.0
    # churn: bbbb disconnects, cccc arrives past the (full) cap, and
    # peer_vitals itself already rolled dddd+eeee up into `other`
    vit = {"aaaa0001": {"queue_depth": 7.0},
           "cccc0003": {"queue_depth": 11.0},
           "other": {"peers": 2, "queue_depth": 13.0}}
    om.export_peer_gauges()
    assert reg._metrics["overlay.peer.queue_depth.aaaa0001"].value == 7.0
    assert reg._metrics["overlay.peer.queue_depth.bbbb0002"].value == 0.0
    assert "overlay.peer.queue_depth.cccc0003" not in reg._metrics
    assert reg._metrics["overlay.peer.queue_depth.other"].value == 24.0


# ---------------------------------------------------------------------------
# forensics: scenario inertness + induced-fork attribution
# ---------------------------------------------------------------------------

def _scenario_fingerprint(tmpdir, seed=11, **kw):
    from stellar_core_tpu.simulation.chaos import run_standard_scenario
    from stellar_core_tpu.simulation.simulation import core

    rep = run_standard_scenario(
        lambda: core(4, persist_dir=str(tmpdir), MANUAL_CLOSE=False, **kw),
        "partition_heal", seed=seed, n_nodes=4, duration=15.0)
    return rep["fingerprint"]


def test_forensics_on_off_scenario_fingerprints_identical(tmp_path):
    """Satellite: a chaos scenario with forensics recording on (twice)
    and off (once) produces bit-identical per-node ledger-hash
    sequences — recording is inert at network scale too."""
    on1 = _scenario_fingerprint(tmp_path / "a", SCP_TIMELINE_ENABLED=True)
    on2 = _scenario_fingerprint(tmp_path / "b", SCP_TIMELINE_ENABLED=True)
    off = _scenario_fingerprint(tmp_path / "c", SCP_TIMELINE_ENABLED=False)
    assert on1 == on2 == off


def test_induced_fork_dump_names_byzantine_node(tmp_path):
    """Acceptance: the core-4 fork probe's FORENSICS_*.json must
    attribute the first divergence to the equivocating node via
    conflicting-statement evidence, and a same-seed rerun must
    reproduce the dump byte-for-byte."""
    from stellar_core_tpu.simulation.chaos import run_induced_fork
    from stellar_core_tpu.simulation.simulation import core

    digests, reports = [], []
    for run in ("a", "b"):
        d = tmp_path / run
        d.mkdir()
        rep, path = run_induced_fork(
            lambda: core(4, threshold=2, persist_dir=str(d),
                         MANUAL_CLOSE=False),
            seed=14, duration=40.0, forensics_dir=str(d))
        digests.append(hashlib.sha256(
            open(path, "rb").read()).hexdigest())
        reports.append(rep)
    assert digests[0] == digests[1], "same-seed dump not byte-identical"
    rep = reports[0]
    byz = rep["nodes"]["byzantine"]
    fd = rep["first_divergence"]
    assert len(byz) == 1
    assert fd["via"] == "equivocation"
    assert fd["node"] in byz, \
        f"divergence blamed {fd['node']}, byzantine was {byz}"
    assert fd["slot"] <= rep["divergence"]["slot"]
    # every equivocation group names the same (only) Byzantine node
    assert {e["node"] for e in rep["equivocations"]} == set(byz)
    # and the dump round-trips through the trace_view renderer
    from tools.trace_view import render_slots

    text = render_slots(json.loads(json.dumps(rep)))
    assert f"FIRST DIVERGENCE: slot {fd['slot']}" in text
    assert f"EQUIVOCATION: node {fd['node']}" in text
    assert "== slot" in text


def test_oracle_failure_dumps_forensics(tmp_path):
    """A failing oracle inside run_scenario must leave a readable
    FORENSICS_*.json behind and name the artifact in the raise."""
    from stellar_core_tpu.simulation.chaos import run_scenario
    from stellar_core_tpu.simulation.simulation import core

    events = [(30.0, "never-fires", lambda chaos: None)]
    with pytest.raises(AssertionError) as ei:
        run_scenario(
            lambda: core(4, persist_dir=str(tmp_path / "n"),
                         MANUAL_CLOSE=False),
            seed=5, events=events, duration=6.0, label="unfired_script",
            forensics_dir=str(tmp_path))
    assert "[forensics]" in str(ei.value)
    dumps = list(tmp_path.glob("FORENSICS_unfired_script_seed5.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["forensics_schema"] == 1
    assert doc["reason"].startswith("[unfired_script] only 0/1")
    assert doc["timelines"], "dump carries no per-node timelines"
    # no fork in this failure mode: divergence stays unattributed
    assert doc["divergence"] is None
