"""QuorumTracker: transitive quorum closure, expand/rebuild semantics
(ref src/herder/QuorumTracker.{h,cpp})."""
from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.herder.quorum_tracker import QuorumTracker
from stellar_core_tpu.scp.local_node import make_qset

from stellar_core_tpu.simulation.simulation import core


def _settle(sim, rounds=200):
    for _ in range(rounds):
        if sim.crank() == 0:
            break


def _ids(n):
    return [SecretKey(sha256(b"qt-%d" % i)).public_key().raw
            for i in range(n)]


def test_local_qset_seeds_the_closure():
    a, b, c = _ids(3)
    qt = QuorumTracker(a, make_qset(2, [a, b, c]))
    assert qt.is_node_definitely_in_quorum(b)
    assert qt.is_node_definitely_in_quorum(c)
    assert not qt.is_node_definitely_in_quorum(_ids(4)[3])
    assert qt.nodes_missing_qsets() == {b, c}
    # distance-1 nodes name themselves as closest validator
    assert qt.quorum[b].distance == 1
    assert qt.quorum[b].closest_validators == {b}


def test_expand_extends_two_hops():
    a, b, c, d = _ids(4)
    qt = QuorumTracker(a, make_qset(1, [a, b]))
    # b's qset pulls in c and d transitively
    assert qt.expand(b, make_qset(2, [c, d]))
    assert qt.is_node_definitely_in_quorum(c)
    assert qt.is_node_definitely_in_quorum(d)
    assert qt.quorum[c].distance == 2
    assert qt.quorum[c].closest_validators == {b}
    # re-announcing the identical qset is fine; a different one is not
    assert qt.expand(b, make_qset(2, [c, d]))
    assert not qt.expand(b, make_qset(1, [c]))
    # out-of-closure nodes are a successful no-op (never tracked,
    # never a rebuild trigger — ref expand returning true there)
    e = _ids(5)[4]
    assert qt.expand(e, make_qset(1, [e]))
    assert not qt.is_node_definitely_in_quorum(e)


def test_rebuild_resolves_through_lookup():
    a, b, c = _ids(3)
    qsets = {b: make_qset(1, [c])}
    qt = QuorumTracker(a, make_qset(1, [a, b]))
    qt.rebuild(qsets.get, make_qset(1, [a, b]))
    assert qt.is_node_definitely_in_quorum(c)
    assert qt.qset_map().keys() == {a, b}
    assert qt.nodes_missing_qsets() == {c}


def test_live_sim_tracks_peers():
    """In a 4-node core sim every node's tracker should learn all four
    qsets once consensus runs."""
    sim = core(4, threshold=3)
    sim.start_all_nodes()
    _settle(sim)
    for _ in range(2):
        assert sim.close_ledger()
    for app in sim.nodes.values():
        qt = app.herder.quorum_tracker
        assert len(qt.qset_map()) == 4
        assert not qt.nodes_missing_qsets()
