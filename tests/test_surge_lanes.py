"""Surge-pricing DEX lane (ROADMAP item 2, scoped): a per-lane op limit
for order-book traffic on top of the total ledger capacity
(ref SurgePricingUtils.h DexLimitingLaneConfig / MAX_DEX_TX_OPERATIONS).

Consensus-visible trimming, so ordering stays exact ``Fraction`` math
and per-account sequence chains stay intact across lanes.
"""
from stellar_core_tpu.herder.tx_set import (
    TxSetFrame, is_dex_tx, surge_pricing_filter,
)
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.transactions.frame import tx_frame_from_envelope
from stellar_core_tpu.xdr import types as T

from .txtest import NETWORK_ID, TestLedger


def _op_sell(acct, selling, buying, amount, pn=1, pd=1):
    return acct.op(T.OperationType.MANAGE_SELL_OFFER,
                   T.ManageSellOfferOp.make(
                       selling=selling, buying=buying, amount=amount,
                       price=T.Price.make(n=pn, d=pd), offerID=0))


def _mk(ledger, n_pay, n_dex, pay_fee=100, dex_fee=100):
    """n_pay one-op payments + n_dex one-op offers, each from its own
    account; returns (frames, accounts)."""
    root = ledger.root()
    iz = root.create("lane-iz", 10**9)
    usd = U.make_asset(b"USD", iz.account_id)
    xlm = U.asset_native()
    frames = []
    for i in range(n_pay):
        a = root.create(f"lane-p{i}", 10**9)
        frames.append(tx_frame_from_envelope(NETWORK_ID, a.tx(
            [a.op_payment(root.account_id, 5)], fee=pay_fee)))
    for i in range(n_dex):
        a = root.create(f"lane-d{i}", 10**9)
        frames.append(tx_frame_from_envelope(NETWORK_ID, a.tx(
            [_op_sell(a, xlm, usd, 100)], fee=dex_fee)))
    return frames


def test_is_dex_tx_classification():
    ledger = TestLedger()
    frames = _mk(ledger, 1, 1)
    assert [is_dex_tx(f) for f in frames] == [False, True]


def test_no_trim_when_under_both_caps():
    ledger = TestLedger()
    frames = _mk(ledger, 3, 3)
    kept = surge_pricing_filter(frames, max_ops=10, max_dex_ops=5)
    assert len(kept) == 6


def test_dex_lane_caps_dex_without_touching_classic():
    ledger = TestLedger()
    # DEX txs bid HIGHER fees: without a lane they would crowd the set
    frames = _mk(ledger, 4, 4, pay_fee=100, dex_fee=1000)
    kept = surge_pricing_filter(frames, max_ops=6, max_dex_ops=2)
    dex_kept = [f for f in kept if is_dex_tx(f)]
    pay_kept = [f for f in kept if not is_dex_tx(f)]
    assert len(dex_kept) == 2  # lane-limited despite higher fees
    assert len(pay_kept) == 4  # classic fills the remaining capacity
    # and the two admitted DEX txs are the highest-fee ones by the
    # exact-rational ordering (all equal fees here -> hash tie-break,
    # just assert count + determinism)
    again = surge_pricing_filter(frames, max_ops=6, max_dex_ops=2)
    assert [f.full_hash() for f in again] == \
        [f.full_hash() for f in kept]


def test_dex_lane_triggers_trim_even_under_total_capacity():
    ledger = TestLedger()
    frames = _mk(ledger, 2, 4)
    kept = surge_pricing_filter(frames, max_ops=100, max_dex_ops=3)
    assert sum(1 for f in kept if is_dex_tx(f)) == 3
    assert sum(1 for f in kept if not is_dex_tx(f)) == 2


def test_lane_trim_keeps_seq_chains_intact():
    """A source with payment(seq n) then offer(seq n+1): dropping the
    offer for lane capacity must not strand a gap, and a kept offer
    pulls its cheaper predecessor in."""
    ledger = TestLedger()
    root = ledger.root()
    iz = root.create("chain-iz", 10**9)
    usd = U.make_asset(b"USD", iz.account_id)
    xlm = U.asset_native()
    a = root.create("chain-a", 10**9)
    b = root.create("chain-b", 10**9)
    pay_a = tx_frame_from_envelope(NETWORK_ID, a.tx(
        [a.op_payment(root.account_id, 5)], fee=100))
    offer_a = tx_frame_from_envelope(NETWORK_ID, a.tx(
        [_op_sell(a, xlm, usd, 100)], fee=5000))
    offer_b = tx_frame_from_envelope(NETWORK_ID, b.tx(
        [_op_sell(b, xlm, usd, 100)], fee=200))
    kept = surge_pricing_filter([pay_a, offer_a, offer_b],
                                max_ops=10, max_dex_ops=1)
    # offer_a (highest rate) pulls pay_a; offer_b exceeds the DEX lane
    ids = {id(f) for f in kept}
    assert id(offer_a) in ids and id(pay_a) in ids
    assert id(offer_b) not in ids
    # chain order: pay_a (lower seq) before offer_a
    assert kept.index(pay_a) < kept.index(offer_a)


def test_make_from_transactions_threads_the_lane_limit():
    ledger = TestLedger()
    frames = _mk(ledger, 2, 3)
    lcl_hash = b"\x11" * 32
    ts = TxSetFrame.make_from_transactions(
        NETWORK_ID, lcl_hash, frames, ledger.root_txn,
        max_size=100, base_fee=100, max_dex_ops=2)
    assert sum(1 for f in ts.frames if is_dex_tx(f)) == 2
    assert sum(1 for f in ts.frames if not is_dex_tx(f)) == 2


def test_config_knob_validates():
    from stellar_core_tpu.main.config import Config, ConfigError, \
        test_config

    cfg = test_config(MAX_DEX_TX_OPERATIONS=50)
    cfg.validate()
    try:
        test_config(MAX_DEX_TX_OPERATIONS=-1).validate()
    except ConfigError:
        pass
    else:
        raise AssertionError("negative MAX_DEX_TX_OPERATIONS accepted")
