"""Bucket-based fast catchup: the parallel Work-DAG sync subsystem
(r17 tentpole; ref src/catchup tests + HistoryTests CatchupSimulation).

Covers: work-system backoff/abort/parallelism primitives; minimal vs
complete mode bit-identity against the live network; corrupted-bucket
and broken-header-chain rejection; mid-catchup archive failure retried
with backoff; buffered-live-ledger drain while a (chaos-degraded)
network keeps closing; and a seed-determinism rerun of the whole
cold-join scenario."""
import gzip
import os
import threading

import pytest

from stellar_core_tpu.catchup import CatchupConfiguration, CatchupWork
from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.history import HistoryArchive, checkpoint_name
from stellar_core_tpu.history.archive import category_path
from stellar_core_tpu.simulation.simulation import Simulation
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.work.work import (
    BasicWork, BatchWork, State, ThreadedWork, Work, WorkerPool)
from stellar_core_tpu.xdr import types as T

from .test_history_catchup import (NodeAccount, close_ledgers_with_traffic,
                                   make_node)


# -- work-system primitives (the parallel-DAG upgrade) -----------------------


def test_retry_backoff_waits_for_the_clock():
    """A failed work with retry_backoff must NOT re-run until the clock
    passes the (exponential) backoff deadline — no hot-spinning a sick
    archive."""
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)

    class Flaky(BasicWork):
        def __init__(self):
            super().__init__("flaky", max_retries=3, clock=clock,
                             retry_backoff=1.0)
            self.attempts = 0

        def on_run(self):
            self.attempts += 1
            return State.SUCCESS if self.attempts == 3 else State.FAILURE

    w = Flaky()
    w.start()
    w.crank()
    assert w.attempts == 1 and w.state == State.RUNNING
    for _ in range(50):  # cranks without advancing time: no retry
        w.crank()
    assert w.attempts == 1
    clock.set_current_virtual_time(clock.now() + 1.01)
    w.crank()
    assert w.attempts == 2  # first backoff (1s) elapsed
    for _ in range(50):
        w.crank()
    # second backoff doubles to 2s: +1.01 is not enough
    clock.set_current_virtual_time(clock.now() + 1.01)
    for _ in range(50):
        w.crank()
    assert w.attempts == 2
    clock.set_current_virtual_time(clock.now() + 2.01)
    w.crank()
    assert w.attempts == 3 and w.state == State.SUCCESS


def test_threaded_batch_actually_overlaps():
    """BatchWork over ThreadedWork children keeps several on_io calls in
    flight at once on the pool — the whole point of the parallel DAG."""
    pool = WorkerPool(max_workers=4)
    lock = threading.Lock()
    live = {"cur": 0, "max": 0}

    class Sleeper(ThreadedWork):
        def on_io(self):
            import time

            with lock:
                live["cur"] += 1
                live["max"] = max(live["max"], live["cur"])
            time.sleep(0.02)
            with lock:
                live["cur"] -= 1
            return True

    works = [Sleeper(f"s{i}", pool) for i in range(6)]
    batch = BatchWork("batch", iter(works), batch_size=4)
    batch.start()
    for _ in range(10000):
        if batch.done:
            break
        batch.crank()
    pool.shutdown()
    assert batch.state == State.SUCCESS
    assert live["max"] >= 2, f"no overlap: max in flight {live['max']}"


def test_abort_propagates_through_the_dag():
    class Spin(BasicWork):
        def on_run(self):
            return State.RUNNING

    class Parent(Work):
        def do_work(self):
            return State.SUCCESS

    p = Parent("p", max_retries=0)
    p.start()
    kids = [p.add_work(Spin(f"k{i}", max_retries=0)) for i in range(3)]
    p.crank()
    p.abort()
    for _ in range(10):
        p.crank()
    assert p.state == State.ABORTED
    assert all(k.state == State.ABORTED for k in kids)


def test_batch_failure_aborts_in_flight_siblings():
    class Spin(BasicWork):
        def on_run(self):
            return State.RUNNING

    class Fail(BasicWork):
        def on_run(self):
            return State.FAILURE

    spin = Spin("spin", max_retries=0)
    fail = Fail("fail", max_retries=0)
    batch = BatchWork("b", iter([spin, fail]), batch_size=2)
    batch.start()
    for _ in range(10):
        if batch.done:
            break
        batch.crank()
    assert batch.state == State.FAILURE
    assert spin.state == State.ABORTED  # not orphaned mid-flight


# -- live-network cold-join harness ------------------------------------------


class SimAccount(NodeAccount):
    """NodeAccount signing for the simulation's network passphrase."""

    def network_id(self):
        return self.app.config.network_id()


def _settle(sim, rounds=200):
    for _ in range(rounds):
        if sim.crank() == 0:
            break


def _publisher_net(arch_dir):
    """core-2 net (A publishes to the archive)."""
    sim = Simulation(network_passphrase="catchup test net")
    seeds = [sha256(b"catchup-sim-%d" % i) for i in range(2)]
    ids = [SecretKey(s).public_key().raw for s in seeds]
    qset = {"threshold": 2, "validators": ids}
    for i, s in enumerate(seeds):
        kw = {}
        if i == 0:
            kw["HISTORY_ARCHIVES"] = [("test", str(arch_dir))]
        sim.add_node(s, qset, **kw)
    sim.add_connection(ids[0], ids[1])
    sim.start_all_nodes()
    _settle(sim)
    return sim, ids


def _close_net(sim, ids, n, start_name=0):
    """n consensus rounds on the validators only (a trailing joiner may
    be mid-catchup), a create-account tx in each odd one."""
    apps = [sim.nodes[i] for i in ids]
    app_a = apps[0]
    for k in range(n):
        if k % 2 == 1:
            root = SimAccount(app_a,
                              SecretKey(app_a.config.network_id()))
            dest = SecretKey(sha256(b"dest-%d-%d" % (start_name, k)))
            env = root.tx([root.op_create_account(
                dest.public_key().raw, 10**9)])
            assert app_a.herder.recv_transaction(env) == 0
        target = max(a.ledger_manager.last_closed_seq()
                     for a in apps) + 1
        for a in apps:
            a.herder.trigger_next_ledger()
        assert sim.crank_until(
            lambda: all(a.ledger_manager.last_closed_seq() >= target
                        for a in apps), timeout=60), \
            f"validators failed to close {target}"


def _join_cold(sim, ids, arch_dir, tag, **config_kw):
    """Add a cold watcher trusting the validators (not in their qsets),
    wired into the live net with archive access."""
    seed = sha256(b"catchup-joiner-" + tag)
    qset = {"threshold": 2, "validators": list(ids)}
    app = sim.add_node(seed, qset,
                       HISTORY_ARCHIVES=[("test", str(arch_dir))],
                       **config_kw)
    app.start()
    jid = app.config.node_id()
    for vid in ids:
        sim.add_connection(jid, vid)
    _settle(sim)
    return app, jid


def _converge(sim, joiner, ref_app, ids, timeout=60.0, nudges=24):
    """Crank until the joiner reaches the reference LCL.  If it misses a
    close (lossy links) the validators keep closing — a real network
    does not go quiet, and a small trailing gap only resolves once live
    closes cross the next checkpoint."""
    def caught_up():
        return (joiner.ledger_manager.last_closed_seq() >=
                ref_app.ledger_manager.last_closed_seq())

    if sim.crank_until(caught_up, timeout=timeout):
        return
    for n in range(nudges):
        _close_net(sim, ids, 1, start_name=1000 + n)
        if sim.crank_until(caught_up, timeout=timeout):
            return
    raise AssertionError(
        f"joiner stuck at {joiner.ledger_manager.last_closed_seq()} vs "
        f"{ref_app.ledger_manager.last_closed_seq()}; "
        f"status={joiner.catchup_manager.status()}")


def _cold_join_scenario(tmp_path, joiner_kw, pre=18, live=14,
                        chaos_drop=0.0):
    """Publisher net closes ``pre`` ledgers, a cold node joins, the net
    keeps closing ``live`` more WHILE the joiner catches up.  Returns
    (sim, joiner app, validator A app, joiner id, validator ids)."""
    arch_dir = tmp_path / "archive"
    sim, ids = _publisher_net(arch_dir)
    _close_net(sim, ids, pre)
    joiner, jid = _join_cold(
        sim, ids, arch_dir,
        b"j-" + str(sorted(joiner_kw.items())).encode(), **joiner_kw)
    if chaos_drop > 0.0:
        from stellar_core_tpu.simulation.chaos import ChaosEngine

        chaos = ChaosEngine(sim, seed=7)
        chaos.set_link(jid, ids[0], drop=chaos_drop)
    _close_net(sim, ids, live, start_name=1)
    _converge(sim, joiner, sim.nodes[ids[0]], ids)
    return sim, joiner, sim.nodes[ids[0]], jid, ids


# -- acceptance scenarios ----------------------------------------------------


def test_cold_join_minimal_bit_identity(tmp_path):
    """A cold node trailing past a checkpoint joins the LIVE net via
    bucket apply + buffered drain and ends bit-identical to the
    validators (header hash AND bucketListHash, every shared seq)."""
    sim, joiner, app_a, jid, ids = _cold_join_scenario(
        tmp_path, joiner_kw={})
    st = joiner.catchup_manager.status()
    assert st["runs"] >= 1 and st["failures"] == 0
    # minimal mode went through the bucket path, not replay-from-genesis
    assert joiner.metrics.counter(
        "catchup.bucket.applied-entries").count > 0
    assert joiner.metrics.counter("catchup.chain.verified").count > 0
    assert joiner.ledger_manager.last_closed_hash() == \
        app_a.ledger_manager.last_closed_hash()
    assert joiner.bucket_manager.get_bucket_list_hash() == \
        app_a.bucket_manager.get_bucket_list_hash()
    sim.assert_no_forks([ids[0], ids[1], jid])
    # and the joiner keeps following the live net afterwards
    _close_net(sim, ids, 2, start_name=2)
    _converge(sim, joiner, app_a, ids)
    sim.assert_no_forks([ids[0], jid])


def test_cold_join_complete_mode_matches_minimal(tmp_path):
    """CATCHUP_COMPLETE replays every ledger instead of assuming buckets
    — and must land on the exact same state."""
    sim, joiner, app_a, jid, ids = _cold_join_scenario(
        tmp_path, joiner_kw={"CATCHUP_COMPLETE": True})
    st = joiner.catchup_manager.status()
    assert st["runs"] >= 1
    # complete mode replayed through close_ledger, no bucket assume
    assert joiner.metrics.counter("catchup.ledger.replayed").count > 0
    assert joiner.metrics.counter(
        "catchup.bucket.applied-entries").count == 0
    assert joiner.ledger_manager.last_closed_hash() == \
        app_a.ledger_manager.last_closed_hash()
    assert joiner.bucket_manager.get_bucket_list_hash() == \
        app_a.bucket_manager.get_bucket_list_hash()
    sim.assert_no_forks([ids[0], jid])


def test_cold_join_trailing_past_validity_bracket(tmp_path):
    """Regression: a joiner trailing MORE than LEDGER_VALIDITY_BRACKET
    ledgers must still ingest live SCP traffic.  The bracket's upper
    bound anchors on the tracked consensus slot, not the parked LCL —
    the old lcl-anchored bound silently discarded every live envelope
    once the trail exceeded 100 ledgers, so the node never buffered
    anything and catchup never even started (found by the 1M-tier
    bench, where the joiner trails 1000+)."""
    from stellar_core_tpu.herder.herder import LEDGER_VALIDITY_BRACKET

    sim, joiner, app_a, jid, ids = _cold_join_scenario(
        tmp_path, joiner_kw={}, pre=LEDGER_VALIDITY_BRACKET + 10, live=6)
    assert joiner.metrics.counter("herder.scp.discarded").count == 0
    assert joiner.catchup_manager.status()["runs"] >= 1
    assert joiner.ledger_manager.last_closed_hash() == \
        app_a.ledger_manager.last_closed_hash()
    assert joiner.bucket_manager.get_bucket_list_hash() == \
        app_a.bucket_manager.get_bucket_list_hash()
    sim.assert_no_forks([ids[0], ids[1], jid])


def test_buffered_drain_under_lossy_network(tmp_path):
    """The drain scenario with chaos-engine packet loss on the joiner's
    link to the publisher: catchup + buffering still converge (retries
    and the second validator cover the gaps)."""
    sim, joiner, app_a, jid, ids = _cold_join_scenario(
        tmp_path, joiner_kw={}, chaos_drop=0.2)
    assert joiner.catchup_manager.status()["runs"] >= 1
    assert joiner.ledger_manager.last_closed_hash() == \
        app_a.ledger_manager.last_closed_hash()
    sim.assert_no_forks([ids[0], ids[1], jid])


def test_seed_determinism_rerun(tmp_path):
    """The whole cold-join scenario rerun from scratch produces a
    bit-identical header chain — pool-thread scheduling must never leak
    into consensus state."""
    chains = []
    for run in ("one", "two"):
        d = tmp_path / run
        d.mkdir()
        sim, joiner, app_a, jid, ids = _cold_join_scenario(
            d, joiner_kw={}, pre=12, live=12)
        chains.append(sim.header_chain(jid))
    assert chains[0] == chains[1]


# -- rejection + retry paths -------------------------------------------------


def _published_archive(tmp_path, n=20):
    arch_dir = tmp_path / "archive"
    app = make_node(tmp_path, archive_dir=arch_dir)
    close_ledgers_with_traffic(app, n)
    cp = app.history_manager.latest_checkpoint_at_or_before(
        app.ledger_manager.last_closed_seq())
    return app, arch_dir, cp


def _run_catchup(app, work, max_cranks=4000):
    app.work_scheduler.schedule(work)
    for _ in range(max_cranks):
        # nudge virtual time so clock-based retry backoffs elapse
        app.clock.set_current_virtual_time(app.clock.now() + 0.01)
        app.crank(block=False)
        if work.done:
            break
    return work.state


def test_corrupted_bucket_rejected(tmp_path):
    """A bucket whose bytes don't hash to their content address must
    fail catchup (after retries), leaving the node's state untouched."""
    app_a, arch_dir, cp = _published_archive(tmp_path)
    has = HistoryArchive("t", str(arch_dir)).get_checkpoint_has(cp)
    victim = next(h for h in has.all_bucket_hashes() if h != "00" * 32)
    path = os.path.join(str(arch_dir),
                        category_path("bucket", victim, ".xdr.gz"))
    with open(path, "wb") as f:
        f.write(gzip.compress(b"\x00garbage\xff" * 64))

    app_b = make_node(tmp_path, archive_dir=arch_dir)
    work = CatchupWork(app_b, app_b.history_manager.archives[0],
                       CatchupConfiguration(cp))
    assert _run_catchup(app_b, work) == State.FAILURE
    assert app_b.ledger_manager.last_closed_seq() == 1  # untouched
    # the root still serves reads (not left detached mid-apply)
    assert app_b.ledger_manager.last_closed_header() is not None


def test_broken_header_chain_rejected(tmp_path):
    """A tampered header file (hash chain broken) must fail verification
    even though every file downloaded fine."""
    app_a, arch_dir, cp = _published_archive(tmp_path)
    arch = HistoryArchive("t", str(arch_dir))
    blob = arch.get_xdr_gz("ledger", checkpoint_name(cp))
    from stellar_core_tpu.xdr.runtime import Reader

    r = Reader(blob)
    entries = []
    while not r.done():
        entries.append(T.LedgerHeaderHistoryEntry.unpack(r))
    # forge the middle entry's close time and restamp ITS hash so the
    # per-entry check passes — only the chain link can catch it
    from stellar_core_tpu.xdr import xdr_sha256

    mid = entries[len(entries) // 2]
    mid.header.scpValue.closeTime += 12345
    mid.hash = xdr_sha256(T.LedgerHeader, mid.header)
    forged = b"".join(T.LedgerHeaderHistoryEntry.encode(e)
                      for e in entries)
    arch.put_xdr_gz("ledger", checkpoint_name(cp), forged)

    app_b = make_node(tmp_path, archive_dir=arch_dir)
    work = CatchupWork(app_b, app_b.history_manager.archives[0],
                       CatchupConfiguration(cp))
    assert _run_catchup(app_b, work) == State.FAILURE
    assert app_b.ledger_manager.last_closed_seq() == 1


class _FlakyArchive(HistoryArchive):
    """Fails the first ``fail_n`` fetches of every bucket, then serves
    normally — the mid-catchup transient-archive-failure model."""

    def __init__(self, name, root, fail_n=2):
        super().__init__(name, root)
        self.fail_n = fail_n
        self.attempts = {}
        self.failures_injected = 0

    def get_bucket(self, hash_hex):
        n = self.attempts.get(hash_hex, 0)
        self.attempts[hash_hex] = n + 1
        if hash_hex != "00" * 32 and n < self.fail_n:
            self.failures_injected += 1
            return None
        return super().get_bucket(hash_hex)


def test_archive_failure_retried_with_backoff(tmp_path):
    """Transient bucket-fetch failures mid-catchup are retried (with the
    clock-based backoff) and the catchup still succeeds."""
    app_a, arch_dir, cp = _published_archive(tmp_path)
    app_b = make_node(tmp_path, archive_dir=arch_dir)
    flaky = _FlakyArchive("flaky", str(arch_dir), fail_n=2)
    work = CatchupWork(app_b, flaky, CatchupConfiguration(cp),
                       retry_backoff=0.05)
    assert _run_catchup(app_b, work, max_cranks=20000) == State.SUCCESS
    assert flaky.failures_injected > 0
    assert app_b.ledger_manager.last_closed_seq() == cp
    # bit-identical to the publisher's archived state AT the checkpoint
    blob = HistoryArchive("t", str(arch_dir)).get_xdr_gz(
        "ledger", checkpoint_name(cp))
    from stellar_core_tpu.xdr.runtime import Reader

    r = Reader(blob)
    last = None
    while not r.done():
        last = T.LedgerHeaderHistoryEntry.unpack(r)
    assert app_b.ledger_manager.last_closed_hash() == last.hash
    assert app_b.bucket_manager.get_bucket_list_hash() == \
        last.header.bucketListHash
