"""ISSUE 16: one planner pass, N GIL-free kernel calls — the batched
fee/seqnum phase, in-kernel pool quoting, and the native tail encode.

The consensus property is the same one every native crossing carries:
for ANY tx set, closes with each r16 feature engaged must produce
byte-identical ledger header hash, bucket-list hash and tx meta versus
that feature forced off (``NATIVE_FEE=0`` / ``NATIVE_POOL_QUOTE=0`` /
``NATIVE_TAIL_ENCODE=0``), across worker counts (0 inline / 2 / 4) and
across PYTHONHASHSEED values (subprocess arms).  A fee batch the kernel
cannot charge (any unsupported source-account shape) must decline the
WHOLE batch — fee charging is strictly sequential, a repeat source has
to see the prior tx's post-image — and still match bytes.
"""
import os
import subprocess
import sys

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.simulation.load_generator import LoadGenerator
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

from .test_parallel_apply import (
    _assert_identical, _close_and_fingerprint, _run_workload,
)


def _fee_metrics(app):
    return {n: m.count for n, m in app.metrics._metrics.items()
            if n.startswith(("apply.native.fee", "apply.native.tail"))}


def _capture(box):
    def hook(app):
        box["app"] = app
    return hook


# -- fee phase in-kernel -----------------------------------------------------

def test_fee_batch_on_off_bit_identical_across_worker_counts():
    """Mixed pay/DEX workload, batched fee kernel vs NATIVE_FEE=0, at
    workers 0/2/4 — identical fingerprints, and the fee batch actually
    engages (hit > 0, no declines on the clean workload)."""
    base, _ = _run_workload(0, NATIVE_APPLY=False, NATIVE_FEE=False)
    for workers in (0, 2, 4):
        box = {}
        fps, _ = _run_workload(workers, NATIVE_APPLY=True,
                               app_hook=_capture(box))
        _assert_identical(base, fps, f"fee batch workers={workers}")
        mets = _fee_metrics(box["app"])
        assert mets.get("apply.native.fee.hit", 0) > 0, \
            f"fee kernel never engaged at workers={workers}: {mets}"
        assert mets.get("apply.native.fee.decline", 0) == 0, mets


def test_fee_batch_repeat_sources_see_running_balance():
    """80 txs per close over 40 accounts guarantees repeat fee sources:
    each charge must see the PRIOR charge's post-image (running balance,
    bumped seqnum, accumulated feePool) — the reason the batch is
    all-or-nothing.  A different seed than the worker-count sweep keeps
    the coverage independent."""
    base, _ = _run_workload(0, seed=23, n_closes=3,
                            NATIVE_APPLY=False, NATIVE_FEE=False)
    box = {}
    fps, _ = _run_workload(0, seed=23, n_closes=3, NATIVE_APPLY=True,
                           app_hook=_capture(box))
    _assert_identical(base, fps, "repeat-source fee batch")
    mets = _fee_metrics(box["app"])
    assert mets.get("apply.native.fee.hit", 0) > 0, mets


def test_unsupported_account_declines_whole_fee_batch_and_matches():
    """Fee charging is strictly sequential, so ONE unsupported source
    account (extra signer) must push the whole batch to the reference
    loop — bytes identical, and the decline taxonomy names the
    account-shape guard."""
    from .test_native_apply import _extra_signer_workload

    base, _ = _extra_signer_workload(0, NATIVE_APPLY=False,
                                     NATIVE_FEE=False)
    box = {}
    fps, _ = _extra_signer_workload(2, app_hook=_capture(box))
    _assert_identical(base, fps, "fee batch whole-batch decline")
    mets = _fee_metrics(box["app"])
    assert mets.get("apply.native.fee.decline", 0) > 0, mets
    assert mets.get(
        "apply.native.fee.decline.unsupported_account_shape", 0) > 0, \
        mets


# -- pool quoting in-kernel --------------------------------------------------

def _pool_workload(workers, n_closes=2, txs=30, app_hook=None, **kw):
    """payment_pattern="pool": every tx is a path payment whose hops
    cross LIVE constant-product pools (no maker books — the empty book
    loses arbitration, so the pool is the venue)."""
    kw.setdefault("NATIVE_APPLY", True)
    if workers == 0 and kw["NATIVE_APPLY"]:
        # no worker pool: the kernel engages via the inline native path
        kw.setdefault("NATIVE_APPLY_INLINE", True)
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=300,
        PARALLEL_APPLY_WORKERS=workers, **kw))
    app.start()
    if app_hook is not None:
        app_hook(app)
    lg = LoadGenerator(app)
    lg.create_accounts(12)
    lg.setup_pool(hops=2)
    fps = []
    for _ in range(n_closes):
        envs = lg.generate_payments(txs)
        assert sum(1 for e in envs
                   if app.herder.recv_transaction(e) == 0) == len(envs)
        _close_and_fingerprint(app, fps)
    stats = dict(app.parallel_apply.stats)
    pool_ids = list(lg.pool_ids)
    app.graceful_stop()
    return fps, stats, pool_ids


def test_pool_workload_native_across_worker_counts():
    """The r16 coverage-cliff fix: a live pool on a hop no longer
    declines the cluster.  Kernel quotes it, matches forced-Python at
    workers 0/2/4, and the native hit rate stays clean (no declines)."""
    base, base_stats, _ = _pool_workload(0, NATIVE_APPLY=False)
    assert base_stats["native_hits"] == 0
    for workers in (0, 2, 4):
        fps, stats, _ = _pool_workload(workers)
        _assert_identical(base, fps, f"pool workers={workers}")
        assert stats["native_hits"] > 0, (workers, stats)
        assert stats["native_declines"] == 0, (workers, stats)


def test_pool_reserves_move_and_pool_atom_lands_in_meta():
    """The pool crossing is visible state: reserves move off the seeded
    1:1 point and the tx meta carries CLAIM_ATOM_TYPE_LIQUIDITY_POOL
    atoms (union disc 2 followed by the poolID) — asserted on the
    native arm, so it pins real kernel pool crossings, not book
    fallbacks."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn, key_bytes
    from stellar_core_tpu.transactions import liquidity_pool as LP

    box = {}
    fps, stats, pool_ids = _pool_workload(2, app_hook=_capture(box))
    assert stats["native_hits"] > 0, stats
    app = box["app"]
    moved = 0
    with LedgerTxn(app.ledger_manager.root) as ltx:
        for pid in pool_ids:
            e = ltx.get(key_bytes(LP.pool_key(pid)))
            assert e is not None, "seeded pool vanished"
            cp = e.data.value.body.value
            if cp.reserveA != cp.reserveB:
                moved += 1
        ltx.rollback()
    assert moved > 0, "no pool reserves moved off the seed point"
    meta_b = b"".join(fp[2] for fp in fps)
    assert any(b"\x00\x00\x00\x02" + pid in meta_b
               for pid in pool_ids), \
        "no CLAIM_ATOM_TYPE_LIQUIDITY_POOL atom in the close meta"


# -- native tail encode ------------------------------------------------------

def test_tail_encode_on_off_bit_identical():
    """Sequential close path (workers=0, Python apply — the arm where
    ``encoded_rows is None`` and the commit tail still encodes per-row):
    one batched ``pack_many`` crossing vs the per-row Python loop,
    identical bytes, and the batch actually engages."""
    base, _ = _run_workload(0, NATIVE_APPLY=False,
                            NATIVE_TAIL_ENCODE=False)
    box = {}
    fps, _ = _run_workload(0, NATIVE_APPLY=False,
                           app_hook=_capture(box))
    _assert_identical(base, fps, "native tail encode")
    mets = _fee_metrics(box["app"])
    assert mets.get("apply.native.tail_encode.hit", 0) > 0, mets


def test_all_three_kill_switches_off_matches_all_on():
    """Belt and braces: every r16 feature off at once vs everything on
    at workers=4, same pool workload, same bytes — the combined
    kill-switch arm an operator would actually reach for."""
    base, _, _ = _pool_workload(0, NATIVE_APPLY=False, NATIVE_FEE=False,
                                NATIVE_POOL_QUOTE=False,
                                NATIVE_TAIL_ENCODE=False)
    fps, stats, _ = _pool_workload(4)
    _assert_identical(base, fps, "all-on vs all-off")
    assert stats["native_hits"] > 0, stats


def test_generateload_mode_pool_admin_endpoint():
    """``generateload?mode=pool`` seeds the pools on first call (no
    staged closes) and every submitted tx is admitted; closing the
    ledger drives the pool hops through the native kernel."""
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=300,
        PARALLEL_APPLY_WORKERS=2, NATIVE_APPLY=True))
    app.start()
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "12"})
    assert code == 200, body
    app.herder.manual_close()
    code, body = handler.handle("generateload",
                                {"mode": "pool", "txs": "25"})
    assert code == 200, body
    assert body["status_counts"] == {0: 25}, body
    app.herder.manual_close()
    stats = dict(app.parallel_apply.stats)
    assert stats["native_hits"] > 0, stats
    assert stats["native_declines"] == 0, stats
    app.graceful_stop()


# -- metrics boot presence ---------------------------------------------------

def test_fee_counters_present_from_boot():
    """The /metrics scrape must carry the r16 counters before any
    traffic — JSON and Prometheus both — so dashboards and alerts can
    key on them from node boot."""
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config())
    app.start()
    handler = CommandHandler(app)
    code, body = handler.handle("metrics", {})
    assert code == 200
    snap = body["metrics"]
    for name in ("apply.native.fee.hit", "apply.native.fee.decline",
                 "apply.native.tail_encode.hit"):
        assert name in snap, sorted(k for k in snap
                                    if k.startswith("apply."))
    code, raw = handler.handle("metrics", {"format": "prometheus"})
    assert code == 200
    text = raw.data.decode()
    assert "apply_native_fee_hit" in text
    assert "apply_native_fee_decline" in text
    assert "apply_native_tail_encode_hit" in text
    app.graceful_stop()


# -- hashseed invariance (subprocess arms) -----------------------------------

_HASHSEED_WORKER = """
import hashlib
import sys

sys.path.insert(0, {repo!r})
from tests.test_native_fee import _pool_workload

fps, stats, _ = _pool_workload({workers}, n_closes=2, txs=20)
assert stats["native_hits"] > 0, stats
for lh, bh, meta in fps:
    print(lh.hex(), bh.hex(), hashlib.sha256(meta).hexdigest())
"""


def test_pool_and_fee_closes_bit_identical_under_hashseed():
    """The full r16 stack (fee batch + pool quote + tail encode, all
    default-on) closes bit-identically under different PYTHONHASHSEED
    values, at workers 0/2/4 — the subprocess arm the acceptance
    criteria pin (tests, not just bench)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for workers in (0, 2, 4):
        outputs = []
        for seed in ("0", "4242"):
            env = dict(os.environ)
            env["PYTHONHASHSEED"] = seed
            env["JAX_PLATFORMS"] = "cpu"
            proc = subprocess.run(
                [sys.executable, "-c", _HASHSEED_WORKER.format(
                    repo=repo, workers=workers)],
                capture_output=True, text=True, cwd=repo, env=env,
                timeout=600)
            assert proc.returncode == 0, proc.stderr[-4000:]
            lines = proc.stdout.strip().splitlines()
            assert len(lines) == 2, proc.stdout
            outputs.append(lines)
        a, b = outputs
        for i, (la, lb) in enumerate(zip(a, b)):
            assert la == lb, (
                f"workers={workers} close {i} diverged across "
                f"PYTHONHASHSEED:\n  seed 0   : {la}\n"
                f"  seed 4242: {lb}")
