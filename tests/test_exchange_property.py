"""Property-based exchangeV10 crossing tests (ROADMAP item 5; ISSUE 3
satellite): the crossing machinery is the hardest bit-identical surface
and until now had only example-based coverage.

Seeded random sweeps over (price, amounts) assert the protocol's
crossing invariants (ref src/transactions/OfferExchange.cpp design
essay :87-163):

* value conservation — the executed trade never creates value: when
  wheat stays (offer partially filled, taker exhausted) the price error
  must favor wheat (sheepSend*d >= wheatReceive*n); when sheep stays it
  must favor sheep (sheepSend*d <= wheatReceive*n);
* bounds — neither side exceeds its stated capacity;
* rounding direction for the strict path-payment modes;
* offer exhaustion — wheat_stays=False means the wheat side's
  constraint is actually used up (within one rounding unit);
* adjustOffer idempotence — adjusting an already-adjusted offer is a
  fixed point (ref adjustOffer comment: "adjusting any offer twice
  yields the same offer as adjusting it once").

A few hundred cases run in tier-1; the 10k-case sweep is slow-marked.
"""
import random

import pytest

from stellar_core_tpu.transactions.offer_exchange import (
    ExchangeError, RoundingType, adjust_offer_amount,
    calculate_offer_value, exchange_v10,
)
from stellar_core_tpu.xdr import types as T

INT64_MAX = 2**63 - 1
INT32_MAX = 2**31 - 1


def _price(rng):
    return T.Price.make(n=rng.randint(1, INT32_MAX),
                        d=rng.randint(1, INT32_MAX))


def _amount(rng):
    # mix of magnitudes: tiny, mid, huge (rounding stress lives at the
    # extremes)
    pick = rng.random()
    if pick < 0.35:
        return rng.randint(1, 100)
    if pick < 0.8:
        return rng.randint(1, 10**9)
    return rng.randint(1, INT64_MAX)


def _small_price(rng):
    return T.Price.make(n=rng.randint(1, 1000), d=rng.randint(1, 1000))


def _check_invariants(price, mws, mwr, mss, msr, round_, res):
    n, d = price.n, price.d
    wr, ss = res.num_wheat_received, res.num_sheep_send
    # bounds
    assert 0 <= wr <= min(mwr, mws)
    assert 0 <= ss <= min(msr, mss)
    if wr > 0 and ss > 0:
        # no value created: the stayed side is never favored against
        lhs = ss * d          # sheep paid, in wheat-value units
        rhs = wr * n          # wheat received, in wheat-value units
        if res.wheat_stays:
            assert lhs >= rhs, "wheat stayed but sheep was favored"
        else:
            assert lhs <= rhs, "sheep stayed but wheat was favored"
        if round_ == RoundingType.NORMAL:
            # 1% relative price error bound (checkPriceErrorBound with
            # can_favor_wheat=False): |100*n*wr - 100*d*ss| <= n*wr,
            # i.e. 100*|lhs - rhs| <= rhs in this function's units —
            # nonzero NORMAL results must have passed the bound
            assert abs(lhs - rhs) * 100 <= rhs, \
                "NORMAL-mode trade crossed outside the 1% price bound"
    if not res.wheat_stays and wr > 0:
        # offer exhausted: the wheat-side constraint is used up — the
        # remaining wheat value is below one price unit
        wheat_value = calculate_offer_value(n, d, mws, msr)
        assert wheat_value - wr * n < n + d, \
            "sheep stayed but wheat value left on the table"


def _run_cases(seed, cases, price_fn):
    rng = random.Random(seed)
    executed = 0
    zeroed = 0
    errors = 0
    for _ in range(cases):
        price = price_fn(rng)
        mws, mwr = _amount(rng), _amount(rng)
        mss, msr = _amount(rng), _amount(rng)
        round_ = rng.choice(list(RoundingType))
        try:
            res = exchange_v10(price, mws, mwr, mss, msr, round_)
        except ExchangeError:
            # legal outcome (overflow / out-of-bounds / price error in
            # strict modes) — must be an exception, never bad numbers
            errors += 1
            continue
        _check_invariants(price, mws, mwr, mss, msr, round_, res)
        if res.num_wheat_received > 0:
            executed += 1
        else:
            zeroed += 1
    # the sweep must actually exercise the machinery, not error out
    assert executed > cases // 4, (executed, zeroed, errors)
    return executed, zeroed, errors


def test_exchange_v10_invariants_sweep_tier1():
    """~600 cases: 300 full-range + 300 small-price (the small grid hits
    the rounding-fairness branches far more often)."""
    _run_cases(0xE10, 300, _price)
    _run_cases(0xE11, 300, _small_price)


def test_strict_send_uses_all_sheep_when_capacity_allows():
    """PATH_PAYMENT_STRICT_SEND with an unbounded offer must send
    exactly min(maxSheepSend, maxSheepReceive) when wheat stays."""
    rng = random.Random(0xE12)
    hit = 0
    for _ in range(300):
        price = _small_price(rng)
        mss = rng.randint(1, 10**6)
        try:
            res = exchange_v10(price, INT64_MAX, INT64_MAX, mss,
                               INT64_MAX,
                               RoundingType.PATH_PAYMENT_STRICT_SEND)
        except ExchangeError:
            continue
        if res.wheat_stays:
            assert res.num_sheep_send == mss
            hit += 1
    assert hit > 200


def test_adjust_offer_amount_is_idempotent():
    rng = random.Random(0xE13)
    for _ in range(300):
        price = _small_price(rng)
        mws = _amount(rng)
        msr = _amount(rng)
        try:
            once = adjust_offer_amount(price, mws, msr)
        except ExchangeError:
            continue
        if once == 0:
            continue
        twice = adjust_offer_amount(price, once, msr)
        assert twice == once, (price.n, price.d, mws, msr, once, twice)


def test_exchange_v10_normal_zero_result_means_price_error():
    """NORMAL mode zeroes a trade rather than crossing at >1% price
    error; a zeroed trade must come from a tiny wheat/sheep value."""
    rng = random.Random(0xE14)
    seen_zero = 0
    for _ in range(500):
        price = T.Price.make(n=rng.randint(1, 50), d=rng.randint(1, 50))
        mws, msr = rng.randint(1, 5), rng.randint(1, 5)
        try:
            res = exchange_v10(price, mws, INT64_MAX, INT64_MAX, msr,
                               RoundingType.NORMAL)
        except ExchangeError:
            continue
        if res.num_wheat_received == 0 and res.num_sheep_send == 0:
            seen_zero += 1
    # the small grid must actually produce some zeroed crossings —
    # that's the branch the property protects
    assert seen_zero > 0


@pytest.mark.slow
def test_exchange_v10_invariants_sweep_10k():
    _run_cases(0xE15, 5000, _price)
    _run_cases(0xE16, 5000, _small_price)
