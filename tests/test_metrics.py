"""Metrics registry coverage (ISSUE 4 satellite): type-collision
assert, snapshot key ordering, time_scope on exception, the
deterministic stride-decimation Histogram reservoir, Meter decay on
read, and the Prometheus exposition round-trip."""
import re

import pytest

from stellar_core_tpu.utils.metrics import (
    Histogram, Meter, MetricsRegistry, render_prometheus,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_type_collision_asserts():
    reg = MetricsRegistry()
    reg.counter("scp.envelope.receive")
    with pytest.raises(AssertionError):
        reg.timer("scp.envelope.receive")
    with pytest.raises(AssertionError):
        reg.meter("scp.envelope.receive")


def test_snapshot_key_ordering_is_stable():
    reg = MetricsRegistry()
    for name in ("z.last.metric", "a.first.metric", "m.middle.metric",
                 "a.first.aaa"):
        reg.counter(name).inc()
    keys = list(reg.snapshot())
    assert keys == sorted(keys)
    # registration order must not matter
    reg2 = MetricsRegistry()
    for name in ("a.first.aaa", "m.middle.metric", "a.first.metric",
                 "z.last.metric"):
        reg2.counter(name).inc()
    assert list(reg2.snapshot()) == keys


def test_time_scope_records_on_exception():
    reg = MetricsRegistry()
    t = reg.timer("ledger.ledger.close")
    with pytest.raises(RuntimeError):
        with t.time_scope():
            raise RuntimeError("close blew up")
    assert t.count == 1
    assert t.max >= 0


# ---------------------------------------------------------------------------
# deterministic histogram reservoir
# ---------------------------------------------------------------------------

def test_histogram_reservoir_is_deterministic_and_bounded():
    h1, h2 = Histogram(), Histogram()
    for i in range(10_000):
        v = float((i * 37) % 1000)
        h1.update(v)
        h2.update(v)
    assert h1.summary() == h2.summary()
    assert len(h1._samples) <= Histogram.MAX_SAMPLES
    assert len(h1._samples) >= Histogram.MAX_SAMPLES // 2
    assert h1.count == 10_000


def test_histogram_stride_decimation_keeps_systematic_sample():
    h = Histogram()
    n = 5000
    for i in range(n):
        h.update(float(i))
    # the reservoir is exactly the multiples of the final stride
    assert h._samples == [float(i) for i in range(0, n, h._stride)]
    # percentiles stay sane on the systematic sample
    assert h.summary()["p50"] == pytest.approx(n / 2, rel=0.05)
    assert h.min == 0.0 and h.max == float(n - 1)


def test_histogram_module_has_no_random_import():
    import inspect

    import stellar_core_tpu.utils.metrics as M

    src = inspect.getsource(M)
    assert "import random" not in src


# ---------------------------------------------------------------------------
# meter decay on read
# ---------------------------------------------------------------------------

def test_meter_rate_decays_to_zero_when_idle():
    clk = FakeClock()
    m = Meter(clock=clk)
    for _ in range(100):
        clk.t += 1.0
        m.mark()
    busy_rate = m.one_minute_rate
    assert busy_rate > 0.5  # ~1/s
    clk.t += 60.0
    decayed = m.one_minute_rate
    assert decayed < busy_rate * 0.5
    clk.t += 600.0
    assert m.one_minute_rate < 1e-4
    # reading must not mutate: the stored rate recovers on new marks
    clk.t += 1.0
    m.mark()
    assert m.one_minute_rate > 1e-4


def test_meter_never_marked_reads_zero():
    assert Meter(clock=FakeClock()).one_minute_rate == 0.0


# ---------------------------------------------------------------------------
# prometheus exposition round-trip
# ---------------------------------------------------------------------------

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ([-+0-9.eEinfa]+)$")


def _parse(text):
    """Minimal text-format parser: {name: {labels_str: value}} plus the
    TYPE declarations."""
    samples, types = {}, {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _, _, name, typ = ln.split(" ")
            types[name] = typ
            continue
        m = _SAMPLE.match(ln)
        assert m, f"unparseable exposition line: {ln!r}"
        samples.setdefault(m.group(1), {})[m.group(2) or ""] = \
            float(m.group(3))
    return samples, types


def test_prometheus_round_trip():
    clk = FakeClock()
    reg = MetricsRegistry(clk)
    reg.counter("ledger.ledger.count").set_count(41)
    mt = reg.meter("overlay.message.read")
    clk.t += 1.0
    mt.mark(7)
    tm = reg.timer("ledger.ledger.close")
    for v in (0.010, 0.020, 0.030):
        clk.t += 1.0
        tm.update(v)
    reg.histogram("herder.pending.txs").update(12.0)
    text = render_prometheus(reg)
    samples, types = _parse(text)
    assert samples["ledger_ledger_count"][""] == 41
    assert types["ledger_ledger_count"] == "counter"
    assert samples["overlay_message_read_total"][""] == 7
    assert types["ledger_ledger_close_seconds"] == "summary"
    assert samples["ledger_ledger_close_seconds"]['{quantile="0.5"}'] \
        == pytest.approx(0.020)
    assert samples["ledger_ledger_close_seconds_count"][""] == 3
    assert samples["ledger_ledger_close_seconds_sum"][""] == \
        pytest.approx(0.060, rel=1e-3)
    assert samples["herder_pending_txs"]['{quantile="0.5"}'] == 12.0
    # every line parses (the format-level gate)
    for ln in text.splitlines():
        if ln:
            assert ln.startswith("# TYPE ") or _SAMPLE.match(ln)


def test_prometheus_name_sanitization():
    reg = MetricsRegistry()
    reg.counter("bucket.merge.sync-fallback").inc(3)
    samples, _ = _parse(render_prometheus(reg))
    assert samples["bucket_merge_sync_fallback"][""] == 3


# ---------------------------------------------------------------------------
# gauges + derived-rate exposition (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

def test_gauge_snapshot_and_prometheus():
    reg = MetricsRegistry()
    reg.gauge("vitals.rss_bytes").set(123456)
    reg.gauge("ledger.prefetch.hit-rate").set(0.75)
    snap = reg.snapshot()
    assert snap["vitals.rss_bytes"] == {"type": "gauge",
                                        "value": 123456.0}
    samples, types = _parse(render_prometheus(reg))
    assert types["vitals_rss_bytes"] == "gauge"
    assert samples["vitals_rss_bytes"][""] == 123456.0
    assert samples["ledger_prefetch_hit_rate"][""] == 0.75
    # re-registering under a different type stays a loud assert
    with pytest.raises(AssertionError):
        reg.counter("vitals.rss_bytes")


def test_prometheus_peer_label_round_trip():
    """Per-peer families (ISSUE 19 satellite): overlay.peer.* and
    floodtrace.link.* counters/gauges expose as one metric per family
    with a {peer="..."} label, one TYPE line per family — while the
    JSON snapshot keeps the dotted per-peer names byte-unchanged."""
    reg = MetricsRegistry()
    reg.counter("floodtrace.link.unique.ab12cd34").set_count(5)
    reg.counter("floodtrace.link.unique.ff00ff00").set_count(2)
    reg.counter("floodtrace.link.duplicate.ab12cd34").set_count(3)
    reg.gauge("overlay.peer.queue_depth.ab12cd34").set(7)
    reg.counter("overlay.peer.unique_recv.other").set_count(11)
    reg.counter("overlay.flood.unique").set_count(9)  # outside the families
    text = render_prometheus(reg)
    samples, types = _parse(text)
    assert samples["floodtrace_link_unique"]['{peer="ab12cd34"}'] == 5
    assert samples["floodtrace_link_unique"]['{peer="ff00ff00"}'] == 2
    assert samples["floodtrace_link_duplicate"]['{peer="ab12cd34"}'] == 3
    assert types["floodtrace_link_unique"] == "counter"
    assert samples["overlay_peer_queue_depth"]['{peer="ab12cd34"}'] == 7
    assert types["overlay_peer_queue_depth"] == "gauge"
    # the bounded_name roll-up member rides the same label
    assert samples["overlay_peer_unique_recv"]['{peer="other"}'] == 11
    # a name that merely STARTS with the family prefix but has no
    # member segment stays unlabeled
    assert samples["overlay_flood_unique"][""] == 9
    # exactly one TYPE line per labeled family
    lines = text.splitlines()
    assert sum(1 for ln in lines
               if ln == "# TYPE floodtrace_link_unique counter") == 1
    # JSON snapshot keeps dotted names (byte-compat with pre-r19 JSON)
    snap = reg.snapshot()
    assert snap["floodtrace.link.unique.ab12cd34"] == \
        {"type": "counter", "count": 5}
    assert "floodtrace_link_unique" not in snap


def test_every_rate1m_sample_has_a_gauge_type_line():
    """Every derived one-minute-rate sample (Meter AND Timer) must be
    preceded by its own `# TYPE ... gauge` declaration — a rate sample
    without one inherits the neighboring counter/summary type in strict
    Prometheus parsers."""
    clk = FakeClock()
    reg = MetricsRegistry(clk)
    m = reg.meter("overlay.message.read")
    clk.t += 1.0
    m.mark(3)
    t = reg.timer("ledger.ledger.close")
    clk.t += 1.0
    t.update(0.02)
    lines = render_prometheus(reg).splitlines()
    declared = {ln.split()[2]: ln.split()[3] for ln in lines
                if ln.startswith("# TYPE ")}
    rate_names = [ln.split()[0] for ln in lines
                  if not ln.startswith("#") and
                  ln.split()[0].endswith("_rate1m")]
    assert len(rate_names) == 2  # one per meter, one per timer
    for name in rate_names:
        assert declared.get(name) == "gauge", (name, declared)
