"""Apply-path determinism guard (ROADMAP item 5, ISSUE r7 satellite):
closing the same tx sets from the same snapshot must produce
bit-identical ledger hashes AND bit-identical meta streams.

The bucket tier has carried a repeated-run guard since PR 1
(test_bucket_list.py); this is the same discipline for the transaction
apply machinery — fee processing, hash-shuffled apply order, DEX
crossing, meta emission — whose nondeterminism would fork a validator
quorum even when each node's bucket merges are individually sound.

ISSUE 3 extension: the same workload must also close bit-identically
under DIFFERENT ``PYTHONHASHSEED`` values (two subprocesses), so
hash-seed-dependent set/dict iteration feeding consensus data is caught
at runtime as well as statically (detlint det-unsorted-iter).
"""
import os
import subprocess
import sys

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T


def _run_mixed_workload():
    """One full node lifecycle over a deterministic mixed workload:
    account seeding, DEX seeding (issuer/trustlines/funding), then mixed
    payment+offer closes — all REAL transactions.  Returns the per-close
    fingerprint: (ledger hash, bucket hash, encoded meta bytes)."""
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=200))
    app.start()
    handler = CommandHandler(app)
    fingerprints = []

    def close():
        app.herder.manual_close()
        meta = app._meta_stream[-1] if app._meta_stream else None
        fingerprints.append((
            app.ledger_manager.last_closed_hash(),
            app.bucket_manager.get_bucket_list_hash(),
            T.LedgerCloseMeta.encode(meta) if meta is not None else b""))

    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "30"})
    assert code == 200, body
    close()
    for _ in range(3):  # issuer, trustlines, funding
        code, body = handler.handle("generateload",
                                    {"mode": "mixed", "txs": "60"})
        assert code == 200, body
        close()
    for _ in range(4):
        code, body = handler.handle(
            "generateload", {"mode": "mixed", "txs": "60", "dexpct": "45"})
        assert code == 200, body
        assert body["status_counts"] == {0: 60}, body
        close()
    app.graceful_stop()
    return fingerprints


def test_same_tx_sets_close_bit_identical_twice():
    run1 = _run_mixed_workload()
    run2 = _run_mixed_workload()
    assert len(run1) == len(run2) >= 8
    for i, (a, b) in enumerate(zip(run1, run2)):
        assert a[0] == b[0], f"ledger hash diverged at close {i}"
        assert a[1] == b[1], f"bucket list hash diverged at close {i}"
        assert a[2] == b[2], f"tx meta diverged at close {i}"
    # the workload actually exercised the apply path (nonempty metas)
    assert any(len(m) > 200 for _, _, m in run1)


_HASHSEED_WORKER = """
import hashlib
import sys

sys.path.insert(0, {repo!r})
from tests.test_apply_determinism import _run_mixed_workload

for lh, bh, meta in _run_mixed_workload():
    print(lh.hex(), bh.hex(), hashlib.sha256(meta).hexdigest())
"""


def test_close_bit_identical_under_hashseed_variation():
    """Two subprocesses with different PYTHONHASHSEED values close the
    same deterministic workload; every per-close fingerprint (ledger
    hash, bucket hash, meta digest) must match.  PYTHONHASHSEED changes
    bytes/str hashing, hence set iteration order — exactly the axis the
    sorted-iteration fixes in scp/ and herder/ pin down."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outputs = []
    for seed in ("0", "4242"):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = seed
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _HASHSEED_WORKER.format(repo=repo)],
            capture_output=True, text=True, cwd=repo, env=env,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-4000:]
        lines = proc.stdout.strip().splitlines()
        assert len(lines) >= 8, proc.stdout
        outputs.append(lines)
    a, b = outputs
    assert len(a) == len(b)
    for i, (la, lb) in enumerate(zip(a, b)):
        assert la == lb, (
            f"close {i} fingerprint diverged across PYTHONHASHSEED "
            f"values:\n  seed 0   : {la}\n  seed 4242: {lb}")
