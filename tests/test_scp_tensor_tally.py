"""Live SCP with the tensor tally path (ops/quorum.py) — multi-node
networks externalize with every federated accept/ratify routed through the
batched kernels AND differential-checked against the host oracle
("both" mode raises TallyMismatch on any divergence).
(VERDICT r2 next-round task #4; BASELINE config #5.)"""
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.simulation.simulation import Simulation, _ids, _seeds


def _tensor_sim(n: int, threshold=None) -> Simulation:
    sim = Simulation(network_passphrase="tensor tally net")
    seeds = _seeds(n)
    ids = _ids(seeds)
    thr = threshold if threshold is not None else n - (n - 1) // 3
    qset = {"threshold": thr, "validators": ids}
    for s in seeds:
        sim.add_node(s, qset, SCP_TALLY_BACKEND="both")
    for i in range(n):
        for j in range(i + 1, n):
            sim.add_connection(ids[i], ids[j])
    return sim


def _tally_stats(sim):
    tallies = fallbacks = 0
    for app in sim.nodes.values():
        for slot in app.herder.scp.slots.values():
            if slot.tally is not None:
                tallies += slot.tally.tensor_tallies
                fallbacks += slot.tally.host_fallbacks
    return tallies, fallbacks


def test_core4_externalizes_with_tensor_tallies():
    sim = _tensor_sim(4)
    sim.start_all_nodes()
    for _ in range(3):
        assert sim.close_ledger()
    sim.assert_in_sync()
    tallies, fallbacks = _tally_stats(sim)
    assert tallies > 0, "tensor path never engaged"
    assert fallbacks == 0


def test_cycle6_externalizes_with_tensor_tallies():
    sim = Simulation(network_passphrase="tensor tally net")
    seeds = _seeds(6)
    ids = _ids(seeds)
    for i, s in enumerate(seeds):
        neighbors = [ids[i], ids[(i - 1) % 6], ids[(i + 1) % 6]]
        sim.add_node(s, {"threshold": 2, "validators": neighbors},
                     SCP_TALLY_BACKEND="both")
    for i in range(6):
        sim.add_connection(ids[i], ids[(i + 1) % 6])
    sim.start_all_nodes()
    for _ in range(2):
        assert sim.close_ledger()
    sim.assert_in_sync()
    tallies, _ = _tally_stats(sim)
    assert tallies > 0


def test_inner_set_qsets_tensor_path():
    """Org-grouped (2-level) quorum sets exercise the inner-set tensor
    columns: 3 orgs x 2 validators, threshold 2-of-3 orgs, each org
    2-of-2."""
    sim = Simulation(network_passphrase="tensor tally net")
    seeds = _seeds(6)
    ids = _ids(seeds)
    orgs = [(2, [ids[0], ids[1]]), (2, [ids[2], ids[3]]),
            (2, [ids[4], ids[5]])]
    inner_specs = [{"threshold": t, "validators": v} for t, v in orgs]
    qset = {"threshold": 2, "validators": [], "inner_sets": inner_specs}
    for s in seeds:
        sim.add_node(s, qset, SCP_TALLY_BACKEND="both")
    for i in range(6):
        for j in range(i + 1, 6):
            sim.add_connection(ids[i], ids[j])
    sim.start_all_nodes()
    assert sim.close_ledger()
    sim.assert_in_sync()
    tallies, fallbacks = _tally_stats(sim)
    assert tallies > 0 and fallbacks == 0
