"""Pipelined close determinism + contract tests (ISSUE 11 tentpole).

The pipeline moves ledger N's commit/meta/tx-history/gc tail onto a
worker while N+1 begins, behind a write-ahead read overlay and a
strict depth-1 barrier.  None of that may change a single consensus
byte: header hashes, bucket hashes AND meta bytes must be identical
pipeline-on vs pipeline-off, under hash-seed variation, with the tail
genuinely overlapping (eager drain off) and with the kill switch.
"""
import os
import subprocess
import sys
import threading

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T


def _mk_app(pipelined, eager=None, **kw):
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=200,
        PIPELINED_CLOSE=pipelined,
        PIPELINED_CLOSE_EAGER_DRAIN=eager,
        **kw))
    app.start()
    return app


def run_workload(pipelined, eager=None, dex=True, **kw):
    """Deterministic mixed workload through the full node close path;
    returns per-close (ledger hash, bucket hash, meta bytes)
    fingerprints.  With ``eager=False`` the tail genuinely overlaps the
    next close's admission + close work; fingerprints are read from
    memory (always current) and the meta stream after a final drain."""
    app = _mk_app(pipelined, eager=eager, **kw)
    handler = CommandHandler(app)
    hashes = []

    def close():
        app.herder.manual_close()
        hashes.append((app.ledger_manager.last_closed_hash(),
                       app.bucket_manager.get_bucket_list_hash()))

    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "24"})
    assert code == 200, body
    close()
    for _ in range(2):  # issuer, trustlines, funding
        code, body = handler.handle("generateload",
                                    {"mode": "mixed", "txs": "48"})
        assert code == 200, body
        close()
    for _ in range(3):
        params = {"mode": "mixed", "txs": "48"}
        if dex:
            params["dexpct"] = "40"
        code, body = handler.handle("generateload", params)
        assert code == 200, body
        close()
    app.ledger_manager.pipeline.drain()
    metas = [T.LedgerCloseMeta.encode(m) for m in app._meta_stream]
    stats = dict(app.ledger_manager.pipeline.stats)
    app.graceful_stop()
    assert len(metas) == len(hashes)
    return [h + (m,) for h, m in zip(hashes, metas)], stats


def _assert_identical(a, b, label):
    assert len(a) == len(b)
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra[0] == rb[0], f"[{label}] ledger hash diverged @ {i}"
        assert ra[1] == rb[1], f"[{label}] bucket hash diverged @ {i}"
        assert ra[2] == rb[2], f"[{label}] meta bytes diverged @ {i}"


def test_pipeline_on_off_bit_identical_mixed():
    """The acceptance gate: header/bucket hashes AND meta bytes are
    bit-identical pipeline-on (true overlap, eager drain off) vs
    pipeline-off, over a mixed payment+DEX workload."""
    off, _ = run_workload(False)
    on, stats = run_workload(True, eager=False)
    _assert_identical(off, on, "pipeline on/off")
    assert stats["tails"] == len(on)
    assert stats["tail_failures"] == 0
    # the footprint prefetch actually engaged and fed the close
    assert stats["prefetch_staged"] > 0
    assert stats["prefetch_adopted"] > 0


def test_kill_switch_parity_and_eager_drain():
    """PIPELINED_CLOSE=0 (kill switch) and the eager-drain test-rig
    mode both reproduce the same bytes as the overlapping pipeline."""
    on, _ = run_workload(True, eager=False)
    eager, st = run_workload(True, eager=None)  # MANUAL_CLOSE -> drain
    _assert_identical(on, eager, "eager drain")
    assert st["eager_drains"] == len(eager)
    killed, st2 = run_workload(False)
    _assert_identical(on, killed, "kill switch")
    assert st2["tails"] == 0


def test_overlay_serves_next_close_reads_while_tail_held():
    """The write-ahead overlay: with ledger N's tail parked on the
    worker (test hold hook), N's delta must be visible through the
    root (point gets, header, offer scans) while SQL still holds N-1;
    releasing the hold makes SQL catch up and drops the overlay."""
    app = _mk_app(True, eager=False)
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "8"})
    assert code == 200, body
    app.herder.manual_close()
    app.ledger_manager.pipeline.drain()
    lm = app.ledger_manager
    root = lm.root
    seq_before = lm.last_closed_seq()
    durable_before = app.database.execute(
        "SELECT MAX(ledgerseq) FROM ledgerheaders").fetchone()[0]

    hold = threading.Event()
    lm.pipeline._hold = hold
    try:
        code, body = handler.handle("generateload",
                                    {"mode": "pay", "txs": "8"})
        assert code == 200, body
        app.herder.manual_close()
        # memory state is at N; durable state still at N-1
        assert lm.last_closed_seq() == seq_before + 1
        assert root._pending, "write-ahead overlay not installed"
        durable_mid = app.database.execute(
            "SELECT MAX(ledgerseq) FROM ledgerheaders").fetchone()[0]
        assert durable_mid == durable_before
        # a key from the sealed delta reads back through the overlay
        kb = sorted(root._pending)[0]
        assert root.get(kb) == root._pending[kb]
        assert root.header().ledgerSeq == seq_before + 1
    finally:
        lm.pipeline._hold = None
        hold.set()
    lm.pipeline.drain()
    assert not root._pending, "overlay must drop once the tail commits"
    durable_after = app.database.execute(
        "SELECT MAX(ledgerseq) FROM ledgerheaders").fetchone()[0]
    assert durable_after == seq_before + 1
    app.graceful_stop()


def test_depth_one_barrier_blocks_next_seal():
    """Strict depth-1: with N's tail held, close N+1 must block at its
    seal (never producing a second uncommitted ledger) until N's tail
    commits durably."""
    app = _mk_app(True, eager=False)
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "8"})
    assert code == 200, body
    app.herder.manual_close()
    app.ledger_manager.pipeline.drain()
    lm = app.ledger_manager

    hold = threading.Event()
    lm.pipeline._hold = hold
    code, body = handler.handle("generateload", {"mode": "pay",
                                                 "txs": "8"})
    assert code == 200, body
    app.herder.manual_close()
    seq_n = lm.last_closed_seq()

    done = threading.Event()

    def close_next():
        # the next close runs up to its seal, then barriers on N's tail
        handler.handle("generateload", {"mode": "pay", "txs": "8"})
        app.herder.manual_close()
        done.set()

    t = threading.Thread(target=close_next, daemon=True)
    t.start()
    # the barrier must hold N+1's seal while N's tail is parked
    assert not done.wait(0.3), "close N+1 sealed before N was durable"
    durable = app.database.execute(
        "SELECT MAX(ledgerseq) FROM ledgerheaders").fetchone()[0]
    assert durable <= seq_n - 1
    lm.pipeline._hold = None
    hold.set()
    assert done.wait(30.0), "close N+1 never completed after release"
    t.join()
    lm.pipeline.drain()
    assert lm.last_closed_seq() == seq_n + 1
    durable = app.database.execute(
        "SELECT MAX(ledgerseq) FROM ledgerheaders").fetchone()[0]
    assert durable == seq_n + 1
    app.graceful_stop()


def test_tail_failure_is_sticky_and_loud():
    """A failed tail must fail the NEXT close's barrier (the node must
    not keep closing over a commit that never became durable)."""
    import pytest

    from stellar_core_tpu.ledger.close_pipeline import (StagedTail,
                                                        TailFailure)

    # the forced failure below is the TEST SUBJECT — keep it out of the
    # session stats file verify_green's pipelined smoke aggregates (a
    # real failure there must stay a red flag)
    app = _mk_app(True, eager=False, PIPELINED_CLOSE_STATS_FILE=None)
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "4"})
    assert code == 200, body
    pipeline = app.ledger_manager.pipeline
    pipeline.drain()

    class Boom(StagedTail):
        def live_hashes(self):
            raise RuntimeError("forced tail failure")

    st = Boom(seq=999999, delta={}, header=None, lcl_hash=b"\x00" * 32,
              apply_order=[], tx_result_metas=[], encoded_rows=None,
              tx_set=None, upgrade_metas=[], phases={},
              parent_token=None, level_hashes=[], sql_ahead_hex=[],
              buckets=[])
    pipeline.submit_tail(st)
    with pytest.raises(TailFailure):
        pipeline.barrier()
    with pytest.raises(TailFailure):
        pipeline.barrier()  # sticky: stays red until intervention
    assert pipeline.stats["tail_failures"] == 1
    # shutdown logs (not raises) so teardown still completes
    app.graceful_stop()


def test_footprint_prefetch_warms_the_close():
    """Nomination-time exact-key prefetch: the herder's trigger stages
    the candidates' declared keys through the bucket tier on a worker
    and adopts them before the preplan; the trigger/close path then
    performs zero SQL point reads (bucket tier + overlay serve it)."""
    app = _mk_app(True, eager=False)
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "16"})
    assert code == 200, body
    app.herder.manual_close()
    # fold the direct-seeded accounts off the sql-ahead overlay into
    # the buckets (a close writing them), then chill the cache so the
    # next trigger's prefetch has real bucket work
    code, body = handler.handle("generateload", {"mode": "pay",
                                                 "txs": "16"})
    assert code == 200, body
    app.herder.manual_close()
    app.ledger_manager.pipeline.drain()
    root = app.ledger_manager.root
    assert root.bucket_reads_enabled
    # admit first (admission's fee checks warm the sources), then chill
    # the cache so the TRIGGER's staged prefetch is what re-warms it
    code, body = handler.handle("generateload", {"mode": "pay",
                                                 "txs": "16"})
    assert code == 200, body
    root._entry_cache.clear()
    sql_before = root.reads_from_sql
    app.herder.manual_close()
    stats = app.ledger_manager.pipeline.stats
    assert stats["prefetch_staged"] >= 1
    assert stats["prefetch_keys"] > 0
    assert stats["prefetch_adopted"] > 0, \
        "staged prefetch never warmed the cache"
    assert root.reads_from_sql == sql_before, \
        "close-thread SQL point reads with the bucket tier on"
    app.graceful_stop()


_HASHSEED_WORKER = """
import hashlib
import sys

sys.path.insert(0, {repo!r})
from tests.test_pipelined_close import run_workload

for lh, bh, meta in run_workload(True, eager=False)[0]:
    print(lh.hex(), bh.hex(), hashlib.sha256(meta).hexdigest())
"""


def test_pipelined_close_bit_identical_under_hashseed_variation():
    """Two subprocesses under different PYTHONHASHSEED values run the
    pipelined (overlapping) workload; every per-close fingerprint must
    match — the same discipline test_apply_determinism pins for the
    apply path, extended over the staged tail."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outputs = []
    for seed in ("0", "4242"):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = seed
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PIPELINED_CLOSE", None)
        proc = subprocess.run(
            [sys.executable, "-c", _HASHSEED_WORKER.format(repo=repo)],
            capture_output=True, text=True, cwd=repo, env=env,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-4000:]
        lines = proc.stdout.strip().splitlines()
        assert len(lines) >= 6, proc.stdout
        outputs.append(lines)
    a, b = outputs
    assert a == b, "pipelined close fingerprints diverged across " \
        "PYTHONHASHSEED values"


def test_restart_from_pipelined_state(tmp_path):
    """A node that closed ledgers with the pipeline on (drained) must
    restart from its on-disk state exactly like a synchronous node:
    hash-verified bucket restore, same LCL."""
    node_dir = tmp_path / "node"
    node_dir.mkdir()
    kw = dict(DATABASE=str(node_dir / "node.db"),
              BUCKET_DIR_PATH_REAL=str(node_dir / "buckets"))
    app = _mk_app(True, eager=False, **kw)
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "8"})
    assert code == 200, body
    app.herder.manual_close()
    code, body = handler.handle("generateload", {"mode": "pay",
                                                 "txs": "8"})
    assert code == 200, body
    app.herder.manual_close()
    seq = app.ledger_manager.last_closed_seq()
    lcl = app.ledger_manager.last_closed_hash()
    app.graceful_stop()  # drains the tail, then tears down

    app2 = _mk_app(True, eager=False, **kw)
    assert app2.ledger_manager.last_closed_seq() == seq
    assert app2.ledger_manager.last_closed_hash() == lcl
    assert app2.ledger_manager.root.bucket_reads_enabled
    app2.graceful_stop()
