"""Upgrade validation + voting (ref src/herder/Upgrades.cpp
isValidForApply :511, createUpgradesFor :79; test model
src/herder/test/UpgradesTests.cpp)."""
from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.herder.upgrades import (
    INVALID, VALID, XDR_INVALID, create_upgrades_for, is_valid_for_apply,
)
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

UT = T.LedgerUpgradeType


def raw(t, v):
    return T.LedgerUpgrade.encode(T.LedgerUpgrade.make(t, v))


def header(version=19, base_fee=100, reserve=5000000):
    from .txtest import genesis_header

    h = genesis_header()
    return h._replace(ledgerVersion=version, baseFee=base_fee,
                      baseReserve=reserve)


class TestIsValidForApply:
    def test_version_must_be_monotonic_and_supported(self):
        cfg = test_config()
        h = header(version=18)
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_VERSION, 19),
                                  h, cfg)[0] == VALID
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_VERSION, 18),
                                  h, cfg)[0] == INVALID  # not monotonic
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_VERSION, 25),
                                  h, cfg)[0] == INVALID  # unsupported

    def test_zero_fee_and_reserve_rejected(self):
        cfg = test_config()
        h = header()
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_BASE_FEE, 0),
                                  h, cfg)[0] == INVALID
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_BASE_RESERVE, 0),
                                  h, cfg)[0] == INVALID
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_BASE_FEE, 200),
                                  h, cfg)[0] == VALID

    def test_flags_mask(self):
        cfg = test_config()
        h = header()
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_FLAGS, 0x7),
                                  h, cfg)[0] == VALID
        assert is_valid_for_apply(raw(UT.LEDGER_UPGRADE_FLAGS, 0x8),
                                  h, cfg)[0] == INVALID

    def test_garbage_is_xdr_invalid(self):
        cfg = test_config()
        assert is_valid_for_apply(b"\xff\xff\xff", header(),
                                  cfg)[0] == XDR_INVALID


class TestVotingAndApply:
    def test_configured_upgrade_applies_through_consensus(self):
        cfg = test_config(UPGRADE_DESIRED_BASE_FEE=250)
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        app.start()
        assert app.ledger_manager.last_closed_header().baseFee == 100
        app.herder.manual_close()
        assert app.ledger_manager.last_closed_header().baseFee == 250
        # once applied, the node stops proposing it
        ups = create_upgrades_for(
            app.ledger_manager.last_closed_header(), cfg)
        assert ups == []

    def test_invalid_remote_upgrade_skipped(self):
        """A zero base-fee upgrade in an externalized value is skipped;
        the close succeeds and the fee is unchanged."""
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                          test_config())
        app.start()
        from stellar_core_tpu.herder.tx_set import TxSetFrame
        from stellar_core_tpu.ledger.ledger_manager import LedgerCloseData

        lm = app.ledger_manager
        ts = TxSetFrame(app.config.network_id(), lm.last_closed_hash(), [])
        sv = T.StellarValue.make(
            txSetHash=ts.contents_hash(),
            closeTime=lm.last_closed_header().scpValue.closeTime + 1,
            upgrades=[raw(UT.LEDGER_UPGRADE_BASE_FEE, 0),
                      raw(UT.LEDGER_UPGRADE_MAX_TX_SET_SIZE, 500)],
            ext=T.StellarValue.fields[3][1].make(
                T.StellarValueType.STELLAR_VALUE_BASIC))
        lm.close_ledger(LedgerCloseData(2, ts, sv))
        hdr = lm.last_closed_header()
        assert hdr.baseFee == 100          # invalid upgrade skipped
        assert hdr.maxTxSetSize == 500     # valid one applied
