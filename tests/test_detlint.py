"""Tier-1 gate for the detlint static analyzer (ISSUE 3 tentpole).

Two jobs: (1) the repo itself must be CLEAN — zero unbaselined
findings, no stale baseline entries, no un-justified baseline entries —
so the gate self-enforces on every future PR; (2) the analyzer must
actually catch the bug classes it claims to (seeded injections into
real module source must go red), or a green gate means nothing.
"""
import subprocess
import sys

from tools.lint import (
    lint_repo, lint_sources, load_baseline, match_baseline,
)
from tools.lint.engine import REPO

TALLY = "stellar_core_tpu/scp/tally.py"
OPS = "stellar_core_tpu/ops/injected_kernel.py"
BUCKET = "stellar_core_tpu/bucket/injected.py"


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# the gate itself
# ---------------------------------------------------------------------------

def test_repo_has_zero_unbaselined_findings():
    findings = lint_repo()
    baseline = load_baseline()
    fresh, pinned, stale = match_baseline(findings, baseline)
    assert not fresh, "unbaselined detlint findings:\n" + "\n".join(
        f.render() for f in fresh)
    assert not stale, (
        "stale baseline entries (finding fixed? remove them):\n"
        + "\n".join(str(e) for e in stale))


def test_baseline_entries_are_justified():
    for entry in load_baseline():
        j = entry.get("justification", "")
        assert j and not j.startswith("TODO"), (
            f"baseline entry without a real justification: {entry}")


def test_baseline_shrank_after_sanctioned_tracing_api():
    """ISSUE 4 satellite: the flight recorder's sanctioned timing APIs
    (utils.tracing.span/stopwatch, Timer.time_scope) replaced every raw
    perf_counter read in consensus modules, so the 18 det-wallclock
    baseline entries of ISSUE 3 are gone.  The baseline must only ever
    shrink or stay equal from here."""
    assert len(load_baseline()) == 0


def test_sanctioned_instrumentation_needs_no_baseline():
    """Instrumenting a consensus module through the sanctioned APIs
    produces zero findings — adding a span must never require a new
    det-wallclock baseline entry."""
    src = '''
from stellar_core_tpu.utils.tracing import span, stopwatch


def close_ledger(tracer, metrics, stats):
    with tracer.span("ledger.close"):
        with metrics.timer("ledger.ledger.close").time_scope():
            pass
    with stopwatch() as sw:
        pass
    stats["spill_wait_s"] += sw.seconds
'''
    assert not lint_sources({TALLY: src})


def test_sanctioned_call_matcher():
    from tools.lint.determinism import is_sanctioned_timing_call

    assert is_sanctioned_timing_call(
        "stellar_core_tpu.utils.tracing.span")
    assert is_sanctioned_timing_call(
        "stellar_core_tpu.utils.tracing.stopwatch")
    assert is_sanctioned_timing_call("tracing.span")
    assert is_sanctioned_timing_call("self.metrics.timer.time_scope")
    assert not is_sanctioned_timing_call("time.perf_counter")
    assert not is_sanctioned_timing_call("time.time")
    assert not is_sanctioned_timing_call(None)


def test_strict_cli_exits_zero_on_clean_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--strict"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# acceptance: a seeded nondeterminism bug in scp/tally.py goes red
# ---------------------------------------------------------------------------

def _tally_source():
    with open(f"{REPO}/{TALLY}", encoding="utf-8") as fh:
        return fh.read()


def test_injected_unsorted_items_feeding_hash_is_caught():
    src = _tally_source() + '''

def _fingerprint(envelopes):
    import hashlib
    h = hashlib.sha256()
    for n, env in envelopes.items():
        h.update(n)
    return h.digest()
'''
    findings = lint_sources({TALLY: src})
    hits = [f for f in findings if f.rule == "det-unsorted-iter"
            and f.context == "_fingerprint"]
    assert hits, [f.render() for f in findings]
    # and it is UNBASELINED (strict would exit nonzero)
    fresh, _, _ = match_baseline(findings, load_baseline())
    assert any(f.context == "_fingerprint" for f in fresh)


def test_injected_wallclock_read_is_caught():
    src = _tally_source() + '''

def _stamp(slot):
    import time
    return time.time()
'''
    findings = lint_sources({TALLY: src})
    assert any(f.rule == "det-wallclock" and f.context == "_stamp"
               for f in findings), [f.render() for f in findings]


def test_current_tally_module_is_clean():
    findings = lint_sources({TALLY: _tally_source()})
    assert not findings, [f.render() for f in findings]


# ---------------------------------------------------------------------------
# determinism rules, unit-level
# ---------------------------------------------------------------------------

def test_pragma_suppresses_finding():
    src = '''
import time


def close_time():
    # detlint: allow(det-wallclock)
    return time.time()
'''
    assert not lint_sources({TALLY: src})


def test_float_on_fee_is_caught_and_floordiv_is_not():
    src = '''
def rate(fee_bid, ops):
    return fee_bid / ops
'''
    findings = lint_sources({TALLY: src})
    assert _rules(findings) == {"det-float-consensus"}
    src_ok = src.replace(" / ", " // ")
    assert not lint_sources({TALLY: src_ok})


def test_set_comprehension_and_sorted_consumer_are_exempt():
    src = '''
def tally(envelopes, pred):
    voted = {n for n, env in envelopes.items() if pred(env)}
    order = sorted(n for n in voted)
    total = sum(len(n) for n in voted)
    h = sha256(b"".join(order))
    return h, total
'''
    assert not lint_sources({TALLY: src})


def test_unsorted_iteration_without_sink_is_not_flagged():
    src = '''
def count(envelopes):
    n = 0
    for k, v in envelopes.items():
        n += 1
    return n
'''
    assert not lint_sources({TALLY: src})


def test_jit_host_effect_is_caught():
    src = '''
import os
from functools import partial

import jax


@partial(jax.jit, static_argnames=())
def kernel(x):
    if os.environ.get("DEBUG"):
        print("tracing", x)
    return x * 2


def host_helper(x):
    print(x)  # not jitted: fine
    return x
'''
    findings = lint_sources({OPS: src})
    assert all(f.rule == "det-jit-host-effect" for f in findings)
    assert {f.context for f in findings} == {"kernel"}
    assert len(findings) >= 2  # the environ read and the print


# ---------------------------------------------------------------------------
# lock-discipline rules
# ---------------------------------------------------------------------------

_LOCKED_MODULE = '''
import threading

_lock = threading.Lock()
_shared = set()  # guarded-by: _lock


def good():
    with _lock:
        _shared.add(1)


def bad():
    _shared.add(2)


class Pipeline:
    def __init__(self):
        self._mu = threading.Lock()
        self._outputs = set()  # guarded-by: _mu
        self._outputs.add(0)  # __init__ is construction: exempt

    def good(self):
        with self._mu:
            self._outputs.discard(1)

    def bad(self):
        self._outputs |= {2}
'''


def test_lock_unguarded_write_is_caught():
    findings = lint_sources({BUCKET: _LOCKED_MODULE})
    assert all(f.rule == "lock-unguarded-write" for f in findings)
    assert {(f.context, f.line_text) for f in findings} == {
        ("bad", "_shared.add(2)"),
        ("Pipeline.bad", "self._outputs |= {2}"),
    }, [f.render() for f in findings]


def test_lock_order_inversion_is_caught():
    src = '''
import threading

_a_lock = threading.Lock()
_b_lock = threading.Lock()


def forward():
    with _a_lock:
        with _b_lock:
            pass


def backward():
    with _b_lock:
        with _a_lock:
            pass
'''
    findings = lint_sources({BUCKET: src})
    assert any(f.rule == "lock-order" for f in findings), \
        [f.render() for f in findings]
    src_consistent = src.replace(
        "with _b_lock:\n        with _a_lock:",
        "with _a_lock:\n        with _b_lock:")
    assert not any(f.rule == "lock-order"
                   for f in lint_sources({BUCKET: src_consistent}))


def test_lock_unknown_guard_is_caught():
    src = '''
_shared = set()  # guarded-by: _phantom_lock


def touch():
    _shared.add(1)
'''
    findings = lint_sources({BUCKET: src})
    assert "lock-unknown-guard" in _rules(findings)


def test_repo_lock_annotations_are_honoured():
    """The real bucket pipeline / native loader / device probe carry
    guarded-by annotations and every mutation is inside its lock."""
    findings = [f for f in lint_repo()
                if f.rule.startswith("lock-")]
    assert not findings, [f.render() for f in findings]
