"""Tier-1 gate for the detlint static analyzer (ISSUE 3 tentpole).

Two jobs: (1) the repo itself must be CLEAN — zero unbaselined
findings, no stale baseline entries, no un-justified baseline entries —
so the gate self-enforces on every future PR; (2) the analyzer must
actually catch the bug classes it claims to (seeded injections into
real module source must go red), or a green gate means nothing.
"""
import subprocess
import sys

from tools.lint import (
    lint_repo, lint_sources, load_baseline, match_baseline,
)
from tools.lint.engine import REPO

TALLY = "stellar_core_tpu/scp/tally.py"
OPS = "stellar_core_tpu/ops/injected_kernel.py"
BUCKET = "stellar_core_tpu/bucket/injected.py"


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# the gate itself
# ---------------------------------------------------------------------------

def test_repo_has_zero_unbaselined_findings():
    findings = lint_repo()
    baseline = load_baseline()
    fresh, pinned, stale = match_baseline(findings, baseline)
    assert not fresh, "unbaselined detlint findings:\n" + "\n".join(
        f.render() for f in fresh)
    assert not stale, (
        "stale baseline entries (finding fixed? remove them):\n"
        + "\n".join(str(e) for e in stale))


def test_baseline_entries_are_justified():
    for entry in load_baseline():
        j = entry.get("justification", "")
        assert j and not j.startswith("TODO"), (
            f"baseline entry without a real justification: {entry}")


def test_baseline_shrank_after_sanctioned_tracing_api():
    """ISSUE 4 satellite: the flight recorder's sanctioned timing APIs
    (utils.tracing.span/stopwatch, Timer.time_scope) replaced every raw
    perf_counter read in consensus modules, so the 18 det-wallclock
    baseline entries of ISSUE 3 are gone.  The baseline must only ever
    shrink or stay equal from here."""
    assert len(load_baseline()) == 0


def test_sanctioned_instrumentation_needs_no_baseline():
    """Instrumenting a consensus module through the sanctioned APIs
    produces zero findings — adding a span must never require a new
    det-wallclock baseline entry."""
    src = '''
from stellar_core_tpu.utils.tracing import span, stopwatch


def close_ledger(tracer, metrics, stats):
    with tracer.span("ledger.close"):
        with metrics.timer("ledger.ledger.close").time_scope():
            pass
    with stopwatch() as sw:
        pass
    stats["spill_wait_s"] += sw.seconds
'''
    assert not lint_sources({TALLY: src})


def test_sanctioned_call_matcher():
    from tools.lint.determinism import is_sanctioned_timing_call

    assert is_sanctioned_timing_call(
        "stellar_core_tpu.utils.tracing.span")
    assert is_sanctioned_timing_call(
        "stellar_core_tpu.utils.tracing.stopwatch")
    assert is_sanctioned_timing_call("tracing.span")
    assert is_sanctioned_timing_call("self.metrics.timer.time_scope")
    assert not is_sanctioned_timing_call("time.perf_counter")
    assert not is_sanctioned_timing_call("time.time")
    assert not is_sanctioned_timing_call(None)


def test_strict_cli_exits_zero_on_clean_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--strict"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# acceptance: a seeded nondeterminism bug in scp/tally.py goes red
# ---------------------------------------------------------------------------

def _tally_source():
    with open(f"{REPO}/{TALLY}", encoding="utf-8") as fh:
        return fh.read()


def test_injected_unsorted_items_feeding_hash_is_caught():
    src = _tally_source() + '''

def _fingerprint(envelopes):
    import hashlib
    h = hashlib.sha256()
    for n, env in envelopes.items():
        h.update(n)
    return h.digest()
'''
    findings = lint_sources({TALLY: src})
    hits = [f for f in findings if f.rule == "det-unsorted-iter"
            and f.context == "_fingerprint"]
    assert hits, [f.render() for f in findings]
    # and it is UNBASELINED (strict would exit nonzero)
    fresh, _, _ = match_baseline(findings, load_baseline())
    assert any(f.context == "_fingerprint" for f in fresh)


def test_injected_wallclock_read_is_caught():
    src = _tally_source() + '''

def _stamp(slot):
    import time
    return time.time()
'''
    findings = lint_sources({TALLY: src})
    assert any(f.rule == "det-wallclock" and f.context == "_stamp"
               for f in findings), [f.render() for f in findings]


def test_current_tally_module_is_clean():
    findings = lint_sources({TALLY: _tally_source()})
    assert not findings, [f.render() for f in findings]


def test_telemetry_readback_is_caught():
    """ISSUE 14: any data flow FROM the slot-timeline recorder INTO
    consensus code (reading its state, returning it, passing it on)
    breaks the telemetry-on/off bit-identity contract."""
    src = _tally_source() + '''

def _leak_state(slot):
    tl = slot.scp.timeline
    return tl.export()


def _leak_as_argument(slot, fn):
    fn(slot.scp.timeline)


def _leak_len(slot):
    return len(slot.scp.timeline._slots)
'''
    findings = lint_sources({TALLY: src})
    hits = {f.context for f in findings
            if f.rule == "det-telemetry-readback"}
    assert {"_leak_state", "_leak_as_argument", "_leak_len"} <= hits, \
        [f.render() for f in findings]
    # and they are UNBASELINED (strict would exit nonzero)
    fresh, _, _ = match_baseline(findings, load_baseline())
    assert any(f.rule == "det-telemetry-readback" for f in fresh)


def test_telemetry_writeonly_shapes_are_clean():
    """The instrumented call-site shapes — alias, .enabled / is-None
    guard, bare .record(...) statement, verdict write into the event
    dict — must NOT be flagged."""
    src = _tally_source() + '''

def _record_ok(slot, kind):
    tl = slot.scp.timeline
    if tl.enabled:
        ev = {"from": "aa"}
        tl.record(slot.slot_index, kind, ev)
        ev["ok"] = True
    if tl is not None:
        slot.scp.timeline.record(slot.slot_index, "env")
'''
    findings = lint_sources({TALLY: src})
    assert not any(f.rule == "det-telemetry-readback" for f in findings), \
        [f.render() for f in findings]


# ---------------------------------------------------------------------------
# determinism rules, unit-level
# ---------------------------------------------------------------------------

def test_pragma_suppresses_finding():
    src = '''
import time


def close_time():
    # detlint: allow(det-wallclock)
    return time.time()
'''
    assert not lint_sources({TALLY: src})


def test_float_on_fee_is_caught_and_floordiv_is_not():
    src = '''
def rate(fee_bid, ops):
    return fee_bid / ops
'''
    findings = lint_sources({TALLY: src})
    assert _rules(findings) == {"det-float-consensus"}
    src_ok = src.replace(" / ", " // ")
    assert not lint_sources({TALLY: src_ok})


def test_set_comprehension_and_sorted_consumer_are_exempt():
    src = '''
def tally(envelopes, pred):
    voted = {n for n, env in envelopes.items() if pred(env)}
    order = sorted(n for n in voted)
    total = sum(len(n) for n in voted)
    h = sha256(b"".join(order))
    return h, total
'''
    assert not lint_sources({TALLY: src})


def test_unsorted_iteration_without_sink_is_not_flagged():
    src = '''
def count(envelopes):
    n = 0
    for k, v in envelopes.items():
        n += 1
    return n
'''
    assert not lint_sources({TALLY: src})


def test_jit_host_effect_is_caught():
    src = '''
import os
from functools import partial

import jax


@partial(jax.jit, static_argnames=())
def kernel(x):
    if os.environ.get("DEBUG"):
        print("tracing", x)
    return x * 2


def host_helper(x):
    print(x)  # not jitted: fine
    return x
'''
    findings = lint_sources({OPS: src})
    assert all(f.rule == "det-jit-host-effect" for f in findings)
    assert {f.context for f in findings} == {"kernel"}
    assert len(findings) >= 2  # the environ read and the print


# ---------------------------------------------------------------------------
# lock-discipline rules
# ---------------------------------------------------------------------------

_LOCKED_MODULE = '''
import threading

_lock = threading.Lock()
_shared = set()  # guarded-by: _lock


def good():
    with _lock:
        _shared.add(1)


def bad():
    _shared.add(2)


class Pipeline:
    def __init__(self):
        self._mu = threading.Lock()
        self._outputs = set()  # guarded-by: _mu
        self._outputs.add(0)  # __init__ is construction: exempt

    def good(self):
        with self._mu:
            self._outputs.discard(1)

    def bad(self):
        self._outputs |= {2}
'''


def test_lock_unguarded_write_is_caught():
    findings = lint_sources({BUCKET: _LOCKED_MODULE})
    assert all(f.rule == "lock-unguarded-write" for f in findings)
    assert {(f.context, f.line_text) for f in findings} == {
        ("bad", "_shared.add(2)"),
        ("Pipeline.bad", "self._outputs |= {2}"),
    }, [f.render() for f in findings]


def test_lock_order_inversion_is_caught():
    src = '''
import threading

_a_lock = threading.Lock()
_b_lock = threading.Lock()


def forward():
    with _a_lock:
        with _b_lock:
            pass


def backward():
    with _b_lock:
        with _a_lock:
            pass
'''
    findings = lint_sources({BUCKET: src})
    assert any(f.rule == "lock-order" for f in findings), \
        [f.render() for f in findings]
    src_consistent = src.replace(
        "with _b_lock:\n        with _a_lock:",
        "with _a_lock:\n        with _b_lock:")
    assert not any(f.rule == "lock-order"
                   for f in lint_sources({BUCKET: src_consistent}))


def test_lock_unknown_guard_is_caught():
    src = '''
_shared = set()  # guarded-by: _phantom_lock


def touch():
    _shared.add(1)
'''
    findings = lint_sources({BUCKET: src})
    assert "lock-unknown-guard" in _rules(findings)


def test_repo_lock_annotations_are_honoured():
    """The real bucket pipeline / native loader / device probe carry
    guarded-by annotations and every mutation is inside its lock."""
    findings = [f for f in lint_repo()
                if f.rule.startswith("lock-")]
    assert not findings, [f.render() for f in findings]


# ---------------------------------------------------------------------------
# detlint v2: interprocedural determinism taint (ISSUE 9 tentpole)
# ---------------------------------------------------------------------------

SCP_HELPER = "stellar_core_tpu/scp/injected_helpers.py"
SCP_SINK = "stellar_core_tpu/scp/injected_sink.py"
KERNEL = "stellar_core_tpu/native/apply_kernel.cpp"


def _kernel_source():
    with open(f"{REPO}/{KERNEL}", encoding="utf-8") as fh:
        return fh.read()


def test_interproc_taint_through_helper_is_caught_with_chain():
    """The acceptance shape: an unsorted-iteration helper WITHOUT a
    sink (invisible to every v1 rule) feeding a hash through one
    intermediate call in another module of scp/."""
    helper = '''
def collect(envelopes):
    out = []
    for node, env in envelopes.items():
        out.append(env)
    return out
'''
    sink = '''
from .injected_helpers import collect


def vote_hash(envelopes):
    import hashlib
    h = hashlib.sha256()
    for env in collect(envelopes):
        h.update(env)
    return h.digest()
'''
    # v1 alone is blind: the helper has no sink, the sink fn has no
    # unsorted iteration
    v1 = [f for f in lint_sources({SCP_HELPER: helper})
          if f.rule != "det-interproc-taint"]
    assert not v1, [f.render() for f in v1]

    findings = lint_sources({SCP_HELPER: helper, SCP_SINK: sink})
    hits = [f for f in findings if f.rule == "det-interproc-taint"]
    assert hits, [f.render() for f in findings]
    f = hits[0]
    assert f.file == SCP_SINK and f.context == "vote_hash"
    # the full source->sink chain is in the message
    assert "vote_hash -> collect" in f.message
    assert "unsorted .items() iteration" in f.message
    assert "injected_helpers.py:4" in f.message
    # ...and it is unbaselined (strict goes red)
    fresh, _, _ = match_baseline(findings, load_baseline())
    assert any(x.rule == "det-interproc-taint" for x in fresh)


def test_interproc_wallclock_chain_across_two_hops():
    helper = '''
import time


def jitter():
    return time.time() % 1.0


def mix(values):
    return [v + jitter() for v in values]
'''
    sink = '''
from .injected_helpers import mix


def emit(values, driver):
    driver.emit_envelope(mix(values))
'''
    findings = lint_sources({SCP_HELPER: helper, SCP_SINK: sink})
    hits = [f for f in findings if f.rule == "det-interproc-taint"]
    assert hits, [f.render() for f in findings]
    assert "emit -> mix -> jitter" in hits[0].message
    assert "wallclock time.time()" in hits[0].message


def test_interproc_source_pragma_kills_all_chains():
    helper = '''
import time


def jitter():
    # detlint: allow(det-wallclock)
    return time.time() % 1.0
'''
    sink = '''
from .injected_helpers import jitter


def emit(values, driver):
    driver.emit_envelope([jitter() for _ in values])
'''
    findings = [f for f in lint_sources({SCP_HELPER: helper,
                                         SCP_SINK: sink})
                if f.rule == "det-interproc-taint"]
    assert not findings, [f.render() for f in findings]


def test_interproc_sink_pragma_and_baseline_round_trip():
    helper = '''
import time


def jitter():
    return time.time() % 1.0
'''
    sink = '''
from .injected_helpers import jitter


def emit(values, driver):
    # detlint: allow(det-interproc-taint)
    driver.emit_envelope([jitter() for _ in values])
'''
    taint = [f for f in lint_sources({SCP_HELPER: helper,
                                      SCP_SINK: sink})
             if f.rule == "det-interproc-taint"]
    assert not taint
    # baseline round-trip: the same finding pinned by identity
    sink_nopragma = sink.replace(
        "    # detlint: allow(det-interproc-taint)\n", "")
    findings = lint_sources({SCP_HELPER: helper, SCP_SINK: sink_nopragma})
    taint = [f for f in findings if f.rule == "det-interproc-taint"]
    assert taint
    entry = {"rule": taint[0].rule, "file": taint[0].file,
             "context": taint[0].context,
             "line_text": taint[0].line_text, "justification": "test"}
    fresh, pinned, stale = match_baseline(taint, [entry])
    assert not fresh and pinned and not stale


def test_interproc_id_source_and_sanctioned_modules():
    helper = '''
def cache_key(obj):
    return id(obj)
'''
    sink = '''
from .injected_helpers import cache_key


def digest(objs):
    import hashlib
    return hashlib.sha256(bytes(cache_key(o) % 256 for o in objs)).digest()
'''
    findings = [f for f in lint_sources({SCP_HELPER: helper,
                                         SCP_SINK: sink})
                if f.rule == "det-interproc-taint"]
    assert findings and "id id()" in findings[0].message
    # sanctioned module: the same source in utils/tracing.py is exempt
    from tools.lint.callgraph import SANCTIONED_MODULES

    assert "stellar_core_tpu/utils/tracing.py" in SANCTIONED_MODULES


def test_interproc_depth_bound_is_enforced():
    """A chain longer than MAX_TAINT_DEPTH edges does not propagate —
    the documented blind spot, pinned so it changes consciously."""
    from tools.lint.callgraph import MAX_TAINT_DEPTH

    hops = MAX_TAINT_DEPTH + 1
    parts = ["import time", "", "",
             "def h0():", "    return time.time()", ""]
    for i in range(1, hops):
        parts += [f"def h{i}():", f"    return h{i - 1}()", ""]
    parts += ["def over(driver):",
              f"    driver.emit_envelope(h{hops - 1}())"]
    src = "\n".join(parts)
    findings = [f for f in lint_sources({SCP_HELPER: src})
                if f.rule == "det-interproc-taint"]
    assert not findings, [f.render() for f in findings]
    # one hop fewer: caught
    src_ok = src.replace(f"emit_envelope(h{hops - 1}())",
                         f"emit_envelope(h{hops - 2}())")
    findings = [f for f in lint_sources({SCP_HELPER: src_ok})
                if f.rule == "det-interproc-taint"]
    assert findings


# ---------------------------------------------------------------------------
# detlint v2: native-kernel auditor
# ---------------------------------------------------------------------------

def test_injected_constant_drift_is_caught():
    """Acceptance: a one-character drift in apply_kernel.cpp fails the
    gate (neither present in the shipped tree)."""
    drifted = _kernel_source().replace("MAX_OFFERS_TO_CROSS = 1000",
                                       "MAX_OFFERS_TO_CROSS = 1001")
    findings = lint_sources({KERNEL: drifted})
    hits = [f for f in findings if f.rule == "native-lockstep"]
    assert hits, [f.render() for f in findings]
    assert "max-offers-to-cross" in hits[0].message
    assert "1001 != 1000" in hits[0].message
    fresh, _, _ = match_baseline(findings, load_baseline())
    assert any(f.rule == "native-lockstep" for f in fresh)


def test_issue13_kernel_constant_drift_is_caught():
    """The ISSUE-13 constants (path hop cap, trustline flag masks,
    liability XDR tags) are lockstep-pinned: a one-character C++ edit
    on any of them is red."""
    for frm, to, name in (
            ("MAX_PATH_HOPS = 6", "MAX_PATH_HOPS = 7", "max-path-hops"),
            ("TL_CLAWBACK_FLAG = 4", "TL_CLAWBACK_FLAG = 5",
             "trustline-clawback-flag"),
            ("TL_V1_EXT_V2 = 2", "TL_V1_EXT_V2 = 3",
             "trustline-v1-ext-v2-tag"),
            ("OP_CHANGE_TRUST = 6", "OP_CHANGE_TRUST = 7",
             "op-change-trust")):
        drifted = _kernel_source().replace(frm, to)
        assert drifted != _kernel_source(), frm
        hits = [f for f in lint_sources({KERNEL: drifted})
                if f.rule == "native-lockstep"]
        assert hits, f"{name}: drift must fail the gate"
        assert any(name in f.message for f in hits), \
            [f.render() for f in hits]


def test_issue16_kernel_constant_drift_is_caught():
    """The ISSUE-16 constants (pool constant-product fee/rounding, the
    fee phase's op floor, seqnum account-ext tags, pool XDR tags) are
    lockstep-pinned: a one-character C++ edit on any of them is red."""
    for frm, to, name in (
            ("POOL_FEE_V18 = 30", "POOL_FEE_V18 = 31", "pool-fee-v18"),
            ("POOL_MAX_BPS = 10000", "POOL_MAX_BPS = 10001",
             "pool-max-bps"),
            ("FEE_OPS_FLOOR = 1", "FEE_OPS_FLOOR = 0", "fee-ops-floor"),
            ("ACC_EXT_V3 = 3", "ACC_EXT_V3 = 4", "account-v2-ext-v3-tag"),
            ("LE_LIQUIDITY_POOL = 5", "LE_LIQUIDITY_POOL = 6",
             "le-liquidity-pool"),
            ("w.u32(2); /* CLAIM_ATOM_TYPE_LIQUIDITY_POOL",
             "w.u32(3); /* CLAIM_ATOM_TYPE_LIQUIDITY_POOL",
             "claim-atom-liquidity-pool")):
        drifted = _kernel_source().replace(frm, to)
        assert drifted != _kernel_source(), frm
        hits = [f for f in lint_sources({KERNEL: drifted})
                if f.rule == "native-lockstep"]
        assert hits, f"{name}: drift must fail the gate"
        assert any(name in f.message for f in hits), \
            [f.render() for f in hits]


def test_issue16_python_pool_rounding_drift_is_caught():
    """The pool math's Python twin (liquidity_pool.py's basis-point
    denominator) is pinned too — the kernel quote must divide by the
    very same constant."""
    path = "stellar_core_tpu/transactions/liquidity_pool.py"
    with open(f"{REPO}/{path}", encoding="utf-8") as fh:
        src = fh.read()
    drifted = src.replace("f = 10000 - fee_bps", "f = 10001 - fee_bps")
    assert drifted != src
    findings = [f for f in lint_sources({path: drifted})
                if f.rule == "native-lockstep"]
    assert findings, "python-side pool drift must fail the gate"
    assert any("pool-max-bps" in f.message and f.file == path
               for f in findings), [f.render() for f in findings]


def test_python_side_constant_drift_is_caught():
    """The same entry fails when the PYTHON twin drifts instead."""
    path = "stellar_core_tpu/transactions/utils.py"
    with open(f"{REPO}/{path}", encoding="utf-8") as fh:
        src = fh.read()
    drifted = src.replace("MAX_OFFERS_TO_CROSS = 1000",
                          "MAX_OFFERS_TO_CROSS = 999")
    findings = [f for f in lint_sources({path: drifted})
                if f.rule == "native-lockstep"]
    assert findings, "python-side drift must fail the gate"
    assert any("999 != 1000" in f.message and f.file == path
               for f in findings)


def test_stale_lockstep_manifest_pattern_is_itself_a_finding():
    renamed = _kernel_source().replace("MAX_OFFERS_TO_CROSS",
                                       "MAX_OFFERS_CROSSED")
    findings = [f for f in lint_sources({KERNEL: renamed})
                if f.rule == "native-lockstep"]
    assert any("no longer matches" in f.message for f in findings)


def test_injected_py_call_inside_allow_threads_is_caught():
    """Acceptance: Py* under Py_BEGIN_ALLOW_THREADS fails the gate."""
    bad = _kernel_source().replace(
        "    try {\n        for (auto &kv : c.store)",
        "    PyErr_Clear();\n    try {\n        for (auto &kv : c.store)")
    findings = [f for f in lint_sources({KERNEL: bad})
                if f.rule == "native-gil-api"]
    assert findings, "Py* in an allow-threads region must be caught"
    assert "PyErr_Clear" in findings[0].message
    # ...and a // pragma suppresses a justified one
    ok = _kernel_source().replace(
        "    try {\n        for (auto &kv : c.store)",
        "    PyErr_Clear(); // detlint: allow(native-gil-api)\n"
        "    try {\n        for (auto &kv : c.store)")
    findings = [f for f in lint_sources({KERNEL: ok})
                if f.rule == "native-gil-api"]
    assert not findings, [f.render() for f in findings]


def test_block_threads_window_is_exempt():
    bad = _kernel_source().replace(
        "    try {\n        for (auto &kv : c.store)",
        "    Py_BLOCK_THREADS;\n    PyErr_Clear();\n"
        "    Py_UNBLOCK_THREADS;\n"
        "    try {\n        for (auto &kv : c.store)")
    findings = [f for f in lint_sources({KERNEL: bad})
                if f.rule == "native-gil-api"]
    assert not findings, [f.render() for f in findings]


def test_unchecked_allocator_is_caught_and_checked_is_not():
    bad = _kernel_source().replace(
        "    PyObject *deltas = PyList_New((Py_ssize_t)delta_keys.size());\n"
        "    if (!deltas)\n        return NULL;",
        "    PyObject *deltas = PyList_New((Py_ssize_t)delta_keys.size());")
    findings = [f for f in lint_sources({KERNEL: bad})
                if f.rule == "native-null-unchecked"]
    assert findings, "removing the NULL check must surface a finding"
    assert "deltas" in findings[0].message
    # the shipped kernel (checks intact) is clean
    clean = [f for f in lint_sources({KERNEL: _kernel_source()})
             if f.rule == "native-null-unchecked"]
    assert not clean, [f.render() for f in clean]


def test_comments_naming_py_functions_do_not_trip_the_auditor():
    src = '''
#include <Python.h>
/* PyBytes_AsStringAndSize would segfault on NULL — see glue below */
static PyObject *f(PyObject *s, PyObject *a) {
    Py_BEGIN_ALLOW_THREADS;
    // PyErr_SetString is NOT legal here
    int x = 1;
    Py_END_ALLOW_THREADS;
    return NULL;
}
'''
    findings = [f for f in lint_sources(
        {"stellar_core_tpu/native/injected.cpp": src})
        if f.rule in ("native-gil-api", "native-null-unchecked")]
    assert not findings, [f.render() for f in findings]


def test_srchash_sidecar_audit(tmp_path):
    from tools.lint.native import SO_SOURCES, check_srchash

    ndir = tmp_path / "stellar_core_tpu" / "native"
    ndir.mkdir(parents=True)
    for srcs in SO_SOURCES.values():
        for s in srcs:
            (ndir / s).write_text("int x;\n")
    (ndir / "_xdrpack.so").write_bytes(b"\x7fELF-fake")
    # missing sidecar -> finding
    findings = check_srchash(str(tmp_path))
    assert any(f.rule == "native-srchash" and "missing" in f.message
               for f in findings)
    # stale sidecar -> finding
    (ndir / "_xdrpack.so.srchash").write_text("0" * 64)
    findings = check_srchash(str(tmp_path))
    assert any(f.rule == "native-srchash" and "stale" in f.message
               for f in findings)
    # current sidecar -> clean
    import hashlib
    h = hashlib.sha256()
    h.update((ndir / "xdr_pack.c").read_bytes())
    (ndir / "_xdrpack.so.srchash").write_text(h.hexdigest())
    findings = check_srchash(str(tmp_path))
    assert not findings, [f.render() for f in findings]
    # unknown .so -> finding (no auditable contract)
    (ndir / "_mystery.so").write_bytes(b"??")
    findings = check_srchash(str(tmp_path))
    assert any("unknown native library" in f.message for f in findings)


def test_shipped_tree_sidecars_are_current():
    from tools.lint.native import check_srchash

    findings = check_srchash(REPO)
    assert not findings, [f.render() for f in findings]


# ---------------------------------------------------------------------------
# detlint v2: exception-safety & resource rules
# ---------------------------------------------------------------------------

def test_swallow_except_rules():
    src = '''
def bare(raw):
    try:
        return decode(raw)
    except:
        return None


def silent(raw):
    try:
        return decode(raw)
    except Exception:
        pass


def acts(raw):
    try:
        return decode(raw)
    except Exception:
        log.warning("bad value")
        return None


def narrow(raw):
    try:
        return decode(raw)
    except ValueError:
        pass
'''
    findings = lint_sources({TALLY: src})
    assert {(f.rule, f.context) for f in findings} == {
        ("safety-swallow-except", "bare"),
        ("safety-swallow-except", "silent"),
    }, [f.render() for f in findings]
    # pragma round-trip
    ok = src.replace("    except:",
                     "    # detlint: allow(safety-swallow-except)\n"
                     "    except:").replace(
        "    except Exception:\n        pass",
        "    # detlint: allow(safety-swallow-except)\n"
        "    except Exception:\n        pass", 1)
    assert not lint_sources({TALLY: ok}), \
        [f.render() for f in lint_sources({TALLY: ok})]


def test_resource_ctx_rule():
    src = '''
import os


def good(path):
    with open(path, "rb") as f:
        return f.read()


def bad(path):
    f = open(path, "rb")
    data = f.read()
    f.close()
    return data


class Cache:
    def keeps(self, path):
        fd = os.open(path, os.O_RDONLY)
        self._fd = fd
        return fd
'''
    findings = lint_sources({BUCKET: src})
    assert {(f.rule, f.context) for f in findings} == {
        ("safety-resource-ctx", "bad"),
    }, [f.render() for f in findings]


def test_mutable_default_rule():
    src = '''
def tally(votes, seen=set()):
    return votes


def fine(votes, seen=None):
    return votes
'''
    findings = lint_sources({TALLY: src})
    assert [f.rule for f in findings] == ["safety-mutable-default"]
    assert findings[0].context == "tally"


# ---------------------------------------------------------------------------
# detlint v2: --changed incremental mode
# ---------------------------------------------------------------------------

def test_changed_mode_reuses_cache_and_matches_cold_run(tmp_path):
    from tools.lint.cache import lint_changed

    pkg = tmp_path / "stellar_core_tpu" / "scp"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "x.py").write_text(
        "import time\n\n\ndef stamp():\n    return time.time()\n")
    cpath = str(tmp_path / "cache.json")
    f1, s1 = lint_changed(root=str(tmp_path), path=cpath)
    assert s1["reused"] == 0 and len(s1["changed"]) == 2
    f2, s2 = lint_changed(root=str(tmp_path), path=cpath)
    assert not s2["changed"] and s2["reused"] == 2
    # warm run is finding-identical to the cold run
    assert [f.render() for f in f1] == [f.render() for f in f2]
    assert any(f.rule == "det-wallclock" for f in f2)
    # edit the file: only it re-analyzes, the finding goes away
    (pkg / "x.py").write_text("def stamp(clock):\n    return clock.now()\n")
    f3, s3 = lint_changed(root=str(tmp_path), path=cpath)
    assert s3["changed"] == ["stellar_core_tpu/scp/x.py"]
    assert not any(f.rule == "det-wallclock" for f in f3)


def test_changed_mode_on_repo_matches_full_run(tmp_path):
    """--changed against the real tree reports exactly what the cold
    full run reports (zero, per the gate) — strict on --changed is
    sound."""
    from tools.lint.cache import lint_changed

    cpath = str(tmp_path / "cache.json")
    cold, _ = lint_changed(root=REPO, path=cpath)
    warm, stats = lint_changed(root=REPO, path=cpath)
    assert not stats["changed"]
    assert [f.render() for f in cold] == [f.render() for f in warm]
    full = lint_repo()
    assert [f.render() for f in full] == [f.render() for f in cold]


def test_verify_green_lint_only_gate():
    proc = subprocess.run(
        [sys.executable, "tools/verify_green.py", "--lint-only"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LINT GREEN" in proc.stdout


# ---------------------------------------------------------------------------
# regressions for what the v2 first full run surfaced and this PR fixed
# ---------------------------------------------------------------------------

def test_value_tx_set_hashes_skips_malformed_but_propagates_bugs():
    """safety-swallow-except fix in herder.py: the decode guard eats
    XdrError (hostile/torn peer bytes) but no longer masks runtime
    bugs behind 'except Exception'."""
    from stellar_core_tpu.herder import herder as H
    from stellar_core_tpu.scp import statement as S
    from stellar_core_tpu.xdr import XdrError, types as T

    class FakeStatement:
        pass

    st = FakeStatement()
    orig_pt, orig_nv = S.pledge_type, S.nomination_values
    S.pledge_type = lambda s: S.ST_NOMINATE
    S.nomination_values = lambda s: [b"\x00garbage-not-xdr"]
    try:
        assert H._value_tx_set_hashes(st) == []
        # a NON-decode error must stay loud
        orig_decode = T.StellarValue.decode
        T.StellarValue.decode = staticmethod(
            lambda v: (_ for _ in ()).throw(RuntimeError("driver bug")))
        try:
            import pytest
            with pytest.raises(RuntimeError):
                H._value_tx_set_hashes(st)
        finally:
            T.StellarValue.decode = orig_decode
    finally:
        S.pledge_type, S.nomination_values = orig_pt, orig_nv
    assert issubclass(XdrError, Exception)


def test_unprotect_future_logs_instead_of_silent_swallow(caplog):
    """safety-swallow-except fix in bucket_list.py: a failed staged
    merge no longer disappears without a trace at GC-unprotect time."""
    import logging
    import threading
    from concurrent.futures import Future

    from stellar_core_tpu.bucket.bucket_list import BucketList

    bl = object.__new__(BucketList)
    bl._bg_lock = threading.Lock()
    bl._bg_outputs = {"aa"}
    fut = Future()
    fut.set_exception(RuntimeError("merge exploded"))
    with caplog.at_level(logging.DEBUG,
                         logger="stellar_core_tpu.Bucket"):
        bl._unprotect_future(fut)  # must not raise
    assert any("staged merge failed" in r.message for r in caplog.records)
    assert bl._bg_outputs == {"aa"}  # protection entry intact

    class BadBucket:
        def hash(self):
            raise RuntimeError("no hash")

        def __repr__(self):
            return "<BadBucket>"

    with caplog.at_level(logging.WARNING,
                         logger="stellar_core_tpu.Bucket"):
        bl._unprotect(BadBucket())  # must not raise
    assert any("has no hash" in r.message for r in caplog.records)


def test_merge_table_narrowed_guard(tmp_path, monkeypatch):
    """safety-swallow-except fix in disk_bucket.py: unreadable files
    still fall back to the Python tier; unexpected error types
    propagate instead of being silently converted into a fallback."""
    import pytest

    from stellar_core_tpu.bucket import disk_bucket as DB

    b = object.__new__(DB.DiskBucket)
    b.path = str(tmp_path / "nope.bucket")
    b.size_bytes = 123
    b.count = 1
    monkeypatch.setattr(DB, "_read_sidecar", lambda *a, **k: None)
    monkeypatch.setattr(DB, "_scan_tables",
                        lambda p: (_ for _ in ()).throw(OSError("gone")))
    assert b.merge_table() is None
    monkeypatch.setattr(DB, "_scan_tables",
                        lambda p: (_ for _ in ()).throw(TypeError("bug")))
    with pytest.raises(TypeError):
        b.merge_table()


# ---------------------------------------------------------------------------
# review-pass regressions: gate soundness of the cold run and the cache
# ---------------------------------------------------------------------------

def test_unparseable_file_goes_red_in_cold_run():
    """A SyntaxError'd consensus file must be a finding, not silence —
    the cold full run (the CI gate) and --changed agree on the verdict."""
    findings = lint_sources(
        {"stellar_core_tpu/scp/broken.py": "def f(:\n    pass\n"})
    assert [f.rule for f in findings] == ["parse-error"]


def test_cache_invalidated_when_lint_rules_change(tmp_path):
    """Cached findings were computed BY the rule sources — a cache
    stamped by different tools must be dropped wholesale, or --changed
    --strict could stay green where a cold run goes red."""
    import json

    from tools.lint.cache import lint_changed

    pkg = tmp_path / "stellar_core_tpu" / "scp"
    pkg.mkdir(parents=True)
    (pkg / "x.py").write_text("import time\n\n\ndef s():\n"
                              "    return time.time()\n")
    cpath = tmp_path / "cache.json"
    _, s1 = lint_changed(root=str(tmp_path), path=str(cpath))
    assert s1["reused"] == 0
    _, s2 = lint_changed(root=str(tmp_path), path=str(cpath))
    assert s2["reused"] == 1
    # simulate a pulled commit that changed a rule module: the recorded
    # tools fingerprint no longer matches
    data = json.loads(cpath.read_text())
    data["tools_sha256"] = "0" * 64
    cpath.write_text(json.dumps(data))
    _, s3 = lint_changed(root=str(tmp_path), path=str(cpath))
    assert s3["reused"] == 0, "stale-rules cache must be dropped"


def test_srchash_reverse_audit_catches_stale_source_map(tmp_path):
    from tools.lint.native import SO_SOURCES, check_srchash

    ndir = tmp_path / "stellar_core_tpu" / "native"
    ndir.mkdir(parents=True)
    for srcs in SO_SOURCES.values():
        for s in srcs:
            (ndir / s).write_text("int x;\n")
    assert not check_srchash(str(tmp_path))
    (ndir / "apply_kernel.cpp").unlink()
    findings = check_srchash(str(tmp_path))
    assert any("missing source apply_kernel.cpp" in f.message
               for f in findings)


def test_changed_mode_parity_on_a_tree_with_findings(tmp_path):
    """Cache/cold parity proven on a tree that actually HAS findings of
    several families (per-file, interproc, native, srchash) — a cache
    path that drops findings cannot pass this."""
    from tools.lint.cache import lint_changed
    from tools.lint.engine import lint_repo as cold_run

    pkg = tmp_path / "stellar_core_tpu"
    (pkg / "scp").mkdir(parents=True)
    (pkg / "native").mkdir()
    (pkg / "scp" / "helpers.py").write_text(
        "def collect(envelopes):\n"
        "    out = []\n"
        "    for node, env in envelopes.items():\n"
        "        out.append(env)\n"
        "    return out\n")
    (pkg / "scp" / "sink.py").write_text(
        "import time\n\n"
        "from .helpers import collect\n\n\n"
        "def vote_hash(envelopes, h):\n"
        "    try:\n"
        "        for env in collect(envelopes):\n"
        "            h.update(env)\n"
        "    except Exception:\n"
        "        pass\n"
        "    return h.digest()\n\n\n"
        "def stamp():\n"
        "    return time.time()\n")
    (pkg / "native" / "injected.cpp").write_text(
        "#include <Python.h>\n"
        "static PyObject *f(PyObject *s) {\n"
        "    Py_BEGIN_ALLOW_THREADS;\n"
        "    PyErr_Clear();\n"
        "    Py_END_ALLOW_THREADS;\n"
        "    return NULL;\n"
        "}\n")
    (pkg / "native" / "_xdrpack.so").write_bytes(b"fake")  # no sidecar

    cold = cold_run(root=str(tmp_path))
    warm_cold, _ = lint_changed(root=str(tmp_path),
                                path=str(tmp_path / "c.json"))
    warm, stats = lint_changed(root=str(tmp_path),
                               path=str(tmp_path / "c.json"))
    assert not stats["changed"], "second run must be all cache hits"
    rules = {f.rule for f in cold}
    assert {"det-interproc-taint", "safety-swallow-except",
            "det-wallclock", "native-gil-api",
            "native-srchash"} <= rules, sorted(rules)
    assert [f.render() for f in cold] \
        == [f.render() for f in warm_cold] \
        == [f.render() for f in warm]


def test_txtrace_vitals_sanctioned_observation_only():
    """ISSUE 12 satellite: utils/txtrace.py and utils/vitals.py hold
    the lifecycle/vitals wallclock reads and are sanctioned like
    tracing.py — not taint sources AND cut as carriers — while the
    identical helper inside a consensus dir still fires the taint rule
    (proving the sanction, not the depth bound, is load-bearing)."""
    from tools.lint.callgraph import SANCTIONED_MODULES

    TXTRACE = "stellar_core_tpu/utils/txtrace.py"
    VITALS = "stellar_core_tpu/utils/vitals.py"
    assert TXTRACE in SANCTIONED_MODULES
    assert VITALS in SANCTIONED_MODULES

    helper = '''
import time


def stamp():
    return time.time()
'''
    sink = '''
from ..utils.txtrace import stamp


def vote_hash(values):
    import hashlib
    h = hashlib.sha256()
    for v in values:
        h.update(v + bytes([int(stamp()) % 7]))
    return h.digest()
'''
    # a wallclock read INSIDE txtrace.py is observation-only: no chain
    findings = lint_sources({TXTRACE: helper, SCP_SINK: sink})
    assert not [f for f in findings
                if f.rule == "det-interproc-taint"], \
        [f.render() for f in findings]

    # the SAME helper in scp/ is a live source: the sanction cut it
    sink_scp = sink.replace("from ..utils.txtrace import stamp",
                            "from .injected_helpers import stamp")
    findings = lint_sources({SCP_HELPER: helper, SCP_SINK: sink_scp})
    hits = [f for f in findings if f.rule == "det-interproc-taint"]
    assert hits, [f.render() for f in findings]
    assert "wallclock time.time()" in hits[0].message

    # carrier laundering is cut too: a consensus source wrapped by a
    # txtrace function never reaches a consensus sink as a chain (the
    # documented sanctioned-module blind spot, now pinned for txtrace)
    carrier = '''
from ..scp.injected_helpers import stamp


def wrap():
    return stamp()
'''
    sink_carrier = sink.replace(
        "from ..utils.txtrace import stamp",
        "from ..utils.txtrace import wrap").replace("stamp()", "wrap()")
    findings = lint_sources({SCP_HELPER: helper, TXTRACE: carrier,
                             SCP_SINK: sink_carrier})
    assert not [f for f in findings
                if f.rule == "det-interproc-taint"], \
        [f.render() for f in findings]


# ---------------------------------------------------------------------------
# detlint v3: whole-program concurrency analysis (ISSUE 18 tentpole)
# ---------------------------------------------------------------------------

LEDGER_A = "stellar_core_tpu/ledger/injected_a.py"
LEDGER_B = "stellar_core_tpu/ledger/injected_b.py"

_ENGINE_SRC = '''
from concurrent.futures import ThreadPoolExecutor


class Engine:
    def __init__(self):
        self.pool = ThreadPoolExecutor(max_workers=1,
                                       thread_name_prefix="close-tail")
        self.counter = 0

    def work(self):
        self.counter += 1

    def kick(self):
        self.pool.submit(self.work)

    def tick(self):
        self.counter += 1
'''


def test_conc_unguarded_shared_from_submit_reached_function():
    """A field written both from a submit-reached function (the
    worker:close-tail context inferred through the executor's
    thread_name_prefix) and from a main-context method, with no
    '# guarded-by:' annotation, goes red — and the finding names both
    contexts."""
    hits = [f for f in lint_sources({LEDGER_A: _ENGINE_SRC})
            if f.rule == "conc-unguarded-shared"]
    assert hits, "no conc-unguarded-shared finding"
    assert any("worker:close-tail" in f.message and "main" in f.message
               for f in hits), [f.render() for f in hits]


def test_conc_unguarded_shared_guard_annotation_is_clean():
    src = _ENGINE_SRC.replace(
        "        self.counter = 0",
        "        self._lock = __import__('threading').Lock()\n"
        "        self.counter = 0  # guarded-by: _lock")
    hits = [f for f in lint_sources({LEDGER_A: src})
            if f.rule == "conc-unguarded-shared"]
    assert not hits, [f.render() for f in hits]


def test_conc_class_confinement_pragma_and_baseline_round_trip():
    # class-line pragma: the whole class's fields are exempt
    src = _ENGINE_SRC.replace(
        "class Engine:",
        "class Engine:  # detlint: allow(conc-unguarded-shared)")
    hits = [f for f in lint_sources({LEDGER_A: src})
            if f.rule == "conc-unguarded-shared"]
    assert not hits, [f.render() for f in hits]
    # baseline round-trip: the unpragma'd finding pins by identity
    hits = [f for f in lint_sources({LEDGER_A: _ENGINE_SRC})
            if f.rule == "conc-unguarded-shared"]
    entry = {"rule": hits[0].rule, "file": hits[0].file,
             "context": hits[0].context,
             "line_text": hits[0].line_text, "justification": "test"}
    fresh, pinned, stale = match_baseline([hits[0]], [entry])
    assert not fresh and pinned and not stale


def test_conc_shipped_baseline_is_empty():
    """ISSUE 18 satellite 1: conc-unguarded-shared ships with an EMPTY
    baseline — every hit in the tree was fixed or justified with a
    pragma, none parked.  Pinned here so it stays that way."""
    assert not [e for e in load_baseline()
                if str(e.get("rule", "")).startswith("conc-")]


def test_conc_thread_affine_sqlite_from_worker_context():
    src = '''
import sqlite3
from concurrent.futures import ThreadPoolExecutor


class Store:
    def __init__(self):
        self.conn = sqlite3.connect(":memory:")
        self.pool = ThreadPoolExecutor(max_workers=1,
                                       thread_name_prefix="bucket-merge")

    def flush(self):
        self.conn.execute("DELETE FROM t")

    def kick(self):
        self.pool.submit(self.flush)
'''
    hits = [f for f in lint_sources({LEDGER_A: src})
            if f.rule == "conc-thread-affine-call"]
    assert hits, "no conc-thread-affine-call finding"
    assert any("sqlite-conn" in f.message
               and "worker:bucket-merge" in f.message for f in hits), \
        [f.render() for f in hits]


def test_conc_cross_file_lock_cycle_with_chain():
    """Opposite-order acquisition split across two files, visible only
    interprocedurally (each file alone is clean): the conc-lock-cycle
    finding carries the full ring and per-edge witness chain."""
    src_a = '''
import threading


class Alpha:
    def __init__(self):
        self._alock = threading.Lock()

    def enter_alpha(self):
        with self._alock:
            pass

    def do_alpha(self, beta):
        with self._alock:
            beta.enter_beta()
'''
    src_b = '''
import threading


class Beta:
    def __init__(self):
        self._block = threading.Lock()

    def enter_beta(self):
        with self._block:
            pass

    def do_beta(self, alpha):
        with self._block:
            alpha.enter_alpha()
'''
    hits = [f for f in lint_sources({LEDGER_A: src_a, LEDGER_B: src_b})
            if f.rule == "conc-lock-cycle"]
    assert hits, "no conc-lock-cycle finding"
    msg = hits[0].message
    assert "_alock" in msg and "_block" in msg, msg
    assert "->" in msg       # the ring
    assert "injected" in msg  # per-edge witness carries file:line
    # each file alone is clean — the cycle exists only package-wide
    for solo in (src_a, src_b):
        assert not [f for f in lint_sources({LEDGER_A: solo})
                    if f.rule == "conc-lock-cycle"]


def test_conc_interproc_exoneration_of_v1_unguarded_write():
    """The v1 lexical rule flags a guarded write outside a with-lock
    block; the whole-program pass exonerates it when EVERY caller holds
    the declared lock at the call site (held-at-entry intersection)."""
    src = '''
import threading


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self.seen = 0  # guarded-by: _lock

    def stamp(self):
        with self._lock:
            self._finish()

    def _finish(self):
        self.seen += 1
'''
    from tools.lint import locks as locks_rule
    from tools.lint.engine import _parse_file

    info = _parse_file(LEDGER_A, src)
    assert any(f.rule == "lock-unguarded-write"
               for f in locks_rule.check([info])), \
        "lexical rule should flag the helper write"
    # ...but the whole-program run discharges it interprocedurally
    hits = [f for f in lint_sources({LEDGER_A: src})
            if f.rule == "lock-unguarded-write"]
    assert not hits, [f.render() for f in hits]


def test_conc_changed_cache_parity_on_findings_bearing_tree(tmp_path):
    """Cold vs --changed cache parity when concurrency findings EXIST:
    the conc summaries round-trip through the cache json and the
    global pass reproduces the same findings from cached per-file
    facts (the satellite-4 fingerprint/parity contract)."""
    from tools.lint.cache import lint_changed

    pkg = tmp_path / "stellar_core_tpu" / "ledger"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "eng.py").write_text(_ENGINE_SRC)
    cpath = str(tmp_path / "cache.json")
    cold, s1 = lint_changed(root=str(tmp_path), path=cpath)
    assert s1["reused"] == 0
    warm, s2 = lint_changed(root=str(tmp_path), path=cpath)
    assert not s2["changed"] and s2["reused"] == 2
    assert [f.render() for f in cold] == [f.render() for f in warm]
    assert any(f.rule == "conc-unguarded-shared" for f in warm)


def test_conc_threads_dump_cli():
    """--threads inventory: thread roots + runs-on histogram, and the
    real tree resolves the known worker pools."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--threads"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "worker:close-tail" in out
    assert "worker:bucket-merge" in out
