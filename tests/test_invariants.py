"""Invariant checkers: LiabilitiesMatchOffers positive/negative coverage
(ref src/invariant/LiabilitiesMatchOffers.cpp; the other checkers get
their coverage from every close in the standalone/sim suites, which run
with INVARIANT_CHECKS=[".*"])."""
import pytest

from stellar_core_tpu.invariant.manager import (
    InvariantDoesNotHold, LiabilitiesMatchOffers,
)
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

from .test_standalone_node import NodeAccount, root_account
from .txtest import TestAccount, sha256
from stellar_core_tpu.crypto import SecretKey


@pytest.fixture()
def app():
    a = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    a.start()
    return a


def _usd(issuer: bytes):
    return U.asset_alphanum4(b"USD", issuer)


def test_offers_keep_liabilities_in_sync_through_closes(app):
    """Trust + payment + resting offer + crossing offer all close with
    LiabilitiesMatchOffers active (it is in the ".*" test config)."""
    assert any(inv.NAME == LiabilitiesMatchOffers.NAME
               for inv in app.invariants.invariants)
    root = root_account(app)
    issuer = NodeAccount(app, SecretKey(sha256(b"li-issuer")))
    trader = NodeAccount(app, SecretKey(sha256(b"li-trader")))
    for acct in (issuer, trader):
        env = root.tx([root.op_create_account(acct.account_id, 10 ** 10)])
        assert app.herder.recv_transaction(env) == 0
        app.herder.manual_close()
    usd = _usd(issuer.account_id)

    env = trader.tx([trader.op_change_trust(usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    env = issuer.tx([issuer.op_payment(trader.account_id, 5000, usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()

    # resting sell offer: 1000 USD at 2 XLM/USD => selling liabilities
    # 1000 on the USD trustline, buying 2000 native on the account
    env = trader.tx([trader.op(
        T.OperationType.MANAGE_SELL_OFFER,
        T.ManageSellOfferOp.make(
            selling=usd, buying=U.asset_native(), amount=1000,
            price=T.Price.make(n=2, d=1), offerID=0))])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()

    with LedgerTxn(app.ledger_manager.root) as ltx:
        tl = ltx.load_trustline(trader.account_id, usd)
        acc = ltx.load_account(trader.account_id)
        ltx.rollback()
    assert U.trustline_liabilities(tl.data.value) == (0, 1000)
    assert U.account_liabilities(acc.data.value) == (2000, 0)

    # root crosses it fully; liabilities drop back to zero
    env = root.tx([root.op_change_trust(usd), root.op(
        T.OperationType.MANAGE_BUY_OFFER,
        T.ManageBuyOfferOp.make(
            selling=U.asset_native(), buying=usd, buyAmount=1000,
            price=T.Price.make(n=2, d=1), offerID=0))])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    with LedgerTxn(app.ledger_manager.root) as ltx:
        tl = ltx.load_trustline(trader.account_id, usd)
        acc = ltx.load_account(trader.account_id)
        ltx.rollback()
    assert U.trustline_liabilities(tl.data.value) == (0, 0)
    assert U.account_liabilities(acc.data.value) == (0, 0)


def test_full_revocation_pulls_offers(app):
    """Revoking trustline auth deletes the trustor's offers in the asset
    and releases their liabilities (ref removeOffersByAccountAndAsset)."""
    root = root_account(app)
    issuer = NodeAccount(app, SecretKey(sha256(b"rv-issuer")))
    trader = NodeAccount(app, SecretKey(sha256(b"rv-trader")))
    for acct in (issuer, trader):
        env = root.tx([root.op_create_account(acct.account_id, 10 ** 10)])
        assert app.herder.recv_transaction(env) == 0
        app.herder.manual_close()
    # issuer requires+may revoke auth
    env = issuer.tx([issuer.op_set_options(
        set_flags=T.AUTH_REQUIRED_FLAG | T.AUTH_REVOCABLE_FLAG)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    usd = _usd(issuer.account_id)
    env = trader.tx([trader.op_change_trust(usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    env = issuer.tx([issuer.op(
        T.OperationType.SET_TRUST_LINE_FLAGS,
        T.SetTrustLineFlagsOp.make(
            trustor=T.account_id(trader.account_id), asset=usd,
            clearFlags=0, setFlags=T.AUTHORIZED_FLAG)),
        issuer.op_payment(trader.account_id, 5000, usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    env = trader.tx([trader.op(
        T.OperationType.MANAGE_SELL_OFFER,
        T.ManageSellOfferOp.make(
            selling=usd, buying=U.asset_native(), amount=1000,
            price=T.Price.make(n=1, d=1), offerID=0))])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()

    env = issuer.tx([issuer.op(
        T.OperationType.SET_TRUST_LINE_FLAGS,
        T.SetTrustLineFlagsOp.make(
            trustor=T.account_id(trader.account_id), asset=usd,
            clearFlags=T.AUTHORIZED_FLAG, setFlags=0))])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()

    with LedgerTxn(app.ledger_manager.root) as ltx:
        offers = ltx.offers_by_account(trader.account_id)
        tl = ltx.load_trustline(trader.account_id, usd)
        acc = ltx.load_account(trader.account_id)
        ltx.rollback()
    assert offers == []
    assert U.trustline_liabilities(tl.data.value) == (0, 0)
    assert U.account_liabilities(acc.data.value) == (0, 0)
    assert acc.data.value.numSubEntries == 1  # trustline only


def test_revocation_redeems_pool_shares(app):
    """Revoking auth on an asset redeems pool-share trustlines using it
    into unconditional claimable balances (ref CAP-38
    removeOffersAndPoolShareTrustLines)."""
    import stellar_core_tpu.transactions.liquidity_pool as LP

    root = root_account(app)
    issuer = NodeAccount(app, SecretKey(sha256(b"ps-issuer")))
    trader = NodeAccount(app, SecretKey(sha256(b"ps-trader")))
    for acct in (issuer, trader):
        env = root.tx([root.op_create_account(acct.account_id, 10 ** 10)])
        assert app.herder.recv_transaction(env) == 0
        app.herder.manual_close()
    env = issuer.tx([issuer.op_set_options(
        set_flags=T.AUTH_REQUIRED_FLAG | T.AUTH_REVOCABLE_FLAG)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    usd = _usd(issuer.account_id)
    native = U.asset_native()
    env = trader.tx([trader.op_change_trust(usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    env = issuer.tx([issuer.op(
        T.OperationType.SET_TRUST_LINE_FLAGS,
        T.SetTrustLineFlagsOp.make(
            trustor=T.account_id(trader.account_id), asset=usd,
            clearFlags=0, setFlags=T.AUTHORIZED_FLAG)),
        issuer.op_payment(trader.account_id, 100000, usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    env = trader.tx([trader.op_change_trust_pool(native, usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    params = T.LiquidityPoolParameters.make(
        T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
        T.LiquidityPoolConstantProductParameters.make(
            assetA=native, assetB=usd, fee=T.LIQUIDITY_POOL_FEE_V18))
    pool_id = LP.pool_id_from_params(params)
    env = trader.tx([trader.op_pool_deposit(pool_id, 40000, 20000)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()

    env = issuer.tx([issuer.op(
        T.OperationType.SET_TRUST_LINE_FLAGS,
        T.SetTrustLineFlagsOp.make(
            trustor=T.account_id(trader.account_id), asset=usd,
            clearFlags=T.AUTHORIZED_FLAG, setFlags=0))])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()

    with LedgerTxn(app.ledger_manager.root) as ltx:
        ps_tl = LP.load_pool_share_trustline(
            ltx, trader.account_id, pool_id)
        pool = LP.load_pool(ltx, pool_id)
        cbs = [e for e in ltx.entries_by_key_prefix(
            T.LedgerEntryType.encode(T.LedgerEntryType.CLAIMABLE_BALANCE))
            if e.data.value.claimants[0].value.destination.value
            == trader.account_id]
        usd_tl = ltx.load_trustline(trader.account_id, usd)
        ltx.rollback()
    assert ps_tl is None           # pool-share trustline redeemed
    assert pool is None            # sole participant -> pool deleted
    assert len(cbs) == 2           # one claimable balance per pool asset
    amounts = sorted((T.Asset.encode(e.data.value.asset) ==
                      T.Asset.encode(usd), e.data.value.amount)
                     for e in cbs)
    assert amounts[0][1] == 40000  # native side
    assert amounts[1][1] == 20000  # USD side
    assert LP.tl_pool_use_count(usd_tl.data.value) == 0


def test_native_sell_offer_capped_to_post_reserve_capacity(app):
    """Selling more native than is spendable rests a capped offer whose
    liabilities respect the reserve that the offer itself consumes
    (ref doApply v14+ up-front subentry reservation)."""
    root = root_account(app)
    issuer = NodeAccount(app, SecretKey(sha256(b"cap-issuer")))
    seller = NodeAccount(app, SecretKey(sha256(b"cap-seller")))
    base_reserve = app.ledger_manager.last_closed_header().baseReserve
    # seller: 2 base reserves (account) + 1 (trustline) + 1 (offer) + fees
    funding = base_reserve * 4 + 10 ** 7
    for acct, amt in ((issuer, 10 ** 10), (seller, funding)):
        env = root.tx([root.op_create_account(acct.account_id, amt)])
        assert app.herder.recv_transaction(env) == 0
        app.herder.manual_close()
    usd = _usd(issuer.account_id)
    env = seller.tx([seller.op_change_trust(usd)])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()

    # oversized: the full offer's selling liabilities exceed the
    # available balance (incl. the offer's own reserve) -> UNDERFUNDED
    env = seller.tx([seller.op(
        T.OperationType.MANAGE_SELL_OFFER,
        T.ManageSellOfferOp.make(
            selling=U.asset_native(), buying=usd,
            amount=funding,
            price=T.Price.make(n=1, d=1), offerID=0))])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    tp = app._meta_stream[-1].value.txProcessing[0]
    opres = tp.result.result.result.value[0]
    code = opres.value.value.type
    assert code == (T.ManageSellOfferResultCode
                    .MANAGE_SELL_OFFER_UNDERFUNDED)

    # exactly-fitting: spendable balance after the offer's own reserve
    with LedgerTxn(app.ledger_manager.root) as ltx:
        acc = ltx.load_account(seller.account_id)
        hdr = ltx.header()
        ltx.rollback()
    acc_v = acc.data.value
    spendable = acc_v.balance - U.min_balance(
        hdr, acc_v._replace(numSubEntries=acc_v.numSubEntries + 1))
    env = seller.tx([seller.op(
        T.OperationType.MANAGE_SELL_OFFER,
        T.ManageSellOfferOp.make(
            selling=U.asset_native(), buying=usd,
            amount=spendable - 100,  # leave room for this tx's fee
            price=T.Price.make(n=1, d=1), offerID=0))])
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    with LedgerTxn(app.ledger_manager.root) as ltx:
        offers = ltx.offers_by_account(seller.account_id)
        acc = ltx.load_account(seller.account_id)
        hdr = ltx.header()
        ltx.rollback()
    assert len(offers) == 1
    acc_v = acc.data.value
    _, selling = U.account_liabilities(acc_v)
    assert selling == offers[0].data.value.amount > 0
    # balance covers reserve (incl. the offer subentry) + liabilities
    assert acc_v.balance - selling >= U.min_balance(hdr, acc_v)


def test_liabilities_desync_is_caught(app):
    """Hand-inject an offer without liability bookkeeping: the checker
    must report the drift."""
    root = root_account(app)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        acc_entry = ltx.load_account(root.account_id)
        acc = acc_entry.data.value
        offer = U.wrap_entry(
            T.LedgerEntryType.OFFER,
            T.OfferEntry.make(
                sellerID=T.account_id(root.account_id),
                offerID=991,
                selling=U.asset_native(),
                buying=_usd(root.account_id),
                amount=500,
                price=T.Price.make(n=1, d=1),
                flags=0,
                ext=T.OfferEntry.fields[7][1].make(0)))
        ltx.put(offer)
        msg = LiabilitiesMatchOffers().check_on_tx_apply(ltx, None, True)
        ltx.rollback()
    assert "out of sync" in msg


def test_unauthorized_trustline_with_liabilities_is_caught(app):
    root = root_account(app)
    issuer = SecretKey(sha256(b"li-auth-issuer")).public_key().raw
    usd = _usd(issuer)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        tl_val = T.TrustLineEntry.make(
            accountID=T.account_id(root.account_id),
            asset=T.TrustLineAsset.make(usd.type, usd.value),
            balance=100,
            limit=10 ** 9,
            flags=0,  # NOT authorized
            ext=T.TrustLineEntry.fields[5][1].make(0))
        tl_val = U.set_trustline_liabilities(tl_val, 10, 0)
        tl = U.wrap_entry(T.LedgerEntryType.TRUSTLINE, tl_val)
        ltx.put(tl)
        msg = LiabilitiesMatchOffers().check_on_tx_apply(ltx, None, True)
        ltx.rollback()
    assert "unauthorized" in msg


def test_orderbook_dust_crossing_is_tolerated():
    """exchangeV10's 1% price-error bound refuses micro trades, so a
    small taker remainder can REST at a technically-crossing price;
    the always-on OrderBookIsNotCrossed must tolerate that dust state
    (the engine is required to accept such closes — found by the
    parallel-apply randomized workload, ISSUE 5)."""
    from .txtest import TestLedger

    lg = TestLedger()
    root = lg.root()
    iz = root.create("ob-iz", 10**10)
    alice = root.create("ob-alice", 10**10)
    bob = root.create("ob-bob", 10**10)
    load = U.asset_alphanum4(b"LOAD", iz.account_id)
    xlm = U.asset_native()
    for who in (alice, bob):
        who.apply(who.tx([who.op_change_trust(load)]))
        iz.apply(iz.tx([iz.op_payment(who.account_id, 10**7, load)]))

    def op_sell(acct, selling, buying, amount, pn, pd):
        return acct.op(T.OperationType.MANAGE_SELL_OFFER,
                       T.ManageSellOfferOp.make(
                           selling=selling, buying=buying, amount=amount,
                           price=T.Price.make(n=pn, d=pd), offerID=0))

    # alice rests selling native 47 @ 92/100; bob's 11-unit LOAD sale at
    # 101/100 price-crosses it but rounds to an 8.7% price error -> the
    # exchange refuses (0,0) and bob's remainder rests.  Both applies
    # run with the invariant active (TestAccount.apply checks nothing,
    # so invoke the checker directly on a delta holding both offers).
    ok, _ = alice.apply(alice.tx([op_sell(alice, xlm, load, 47, 92, 100)]))
    assert ok
    ok, _ = bob.apply(bob.tx([op_sell(bob, load, xlm, 11, 101, 100)]))
    assert ok
    from stellar_core_tpu.invariant.manager import OrderBookIsNotCrossed

    with LedgerTxn(lg.root_txn) as ltx:
        # touch both offers into a delta so the checker scans the pair
        for e in ltx.entries_by_key_prefix(
                T.LedgerEntryType.encode(T.LedgerEntryType.OFFER)):
            ltx.put(e)
        msg = OrderBookIsNotCrossed().check_on_tx_apply(ltx, None, True)
        ltx.rollback()
    assert msg == "", msg


def test_orderbook_executable_crossing_is_flagged():
    """A crossed book whose best offers CAN trade (within the price
    error bound) must still fault."""
    from .txtest import TestLedger

    lg = TestLedger()
    root = lg.root()
    iz = root.create("ox-iz", 10**10)
    a = root.create("ox-a", 10**10)
    b = root.create("ox-b", 10**10)
    load = U.asset_alphanum4(b"LOAD", iz.account_id)
    xlm = U.asset_native()

    def offer(seller, oid, selling, buying, amount, pn, pd):
        return U.wrap_entry(
            T.LedgerEntryType.OFFER,
            T.OfferEntry.make(
                sellerID=T.account_id(seller.account_id), offerID=oid,
                selling=selling, buying=buying, amount=amount,
                price=T.Price.make(n=pn, d=pd), flags=0,
                ext=T.OfferEntry.fields[7][1].make(0)))

    from stellar_core_tpu.invariant.manager import OrderBookIsNotCrossed

    with LedgerTxn(lg.root_txn) as ltx:
        # 100 @ 1/2 each way: p_fwd * p_rev = 1/4 < 1 and a 100<->200
        # trade is exact (0% price error) -> executable cross
        ltx.put(offer(a, 901, xlm, load, 100, 1, 2))
        ltx.put(offer(b, 902, load, xlm, 100, 1, 2))
        msg = OrderBookIsNotCrossed().check_on_tx_apply(ltx, None, True)
        ltx.rollback()
    assert "book crossed" in msg and "executable" in msg
