"""SurveyManager over a relayed topology + ProcessManager
(ref src/overlay/SurveyManager.h, src/process/ProcessManagerImpl.cpp)."""
import os

from stellar_core_tpu.process import ProcessManager, RunCommandWork
from stellar_core_tpu.simulation.simulation import Simulation, _ids, _seeds
from stellar_core_tpu.work.work import State


def _line_sim(n=3):
    """A -- B -- C line: surveys from A to C must relay through B."""
    sim = Simulation(network_passphrase="survey net")
    seeds = _seeds(n)
    ids = _ids(seeds)
    qset = {"threshold": 2, "validators": ids}
    for s in seeds:
        sim.add_node(s, qset)
    for i in range(n - 1):
        sim.add_connection(ids[i], ids[i + 1])
    return sim, ids


class TestSurvey:
    def test_survey_relays_and_returns_topology(self):
        sim, ids = _line_sim()
        sim.start_all_nodes()
        sim.crank_for(2.0)
        a = sim.nodes[ids[0]]
        c = sim.nodes[ids[2]]
        sm = a.overlay_manager.survey_manager
        assert sm.start_survey(ids[2])
        sim.crank_for(3.0)
        assert ids[2] in sm.results, "survey response never arrived"
        topo = sm.results[ids[2]]
        # C has exactly one authenticated peer (B)
        assert topo["total_inbound"] == 1
        assert topo["inbound_peers"] == [ids[1].hex()[:8]]

    def test_survey_throttled(self):
        sim, ids = _line_sim()
        sim.start_all_nodes()
        sim.crank_for(1.0)
        sm = sim.nodes[ids[0]].overlay_manager.survey_manager
        assert sm.start_survey(ids[2])
        assert not sm.start_survey(ids[2])  # throttled

    def test_tampered_request_dropped(self):
        sim, ids = _line_sim()
        sim.start_all_nodes()
        sim.crank_for(1.0)
        from stellar_core_tpu.xdr import overlay_types as O
        from stellar_core_tpu.xdr import types as T

        b = sim.nodes[ids[1]]
        sm_b = b.overlay_manager.survey_manager
        req = O.SurveyRequestMessage.make(
            surveyorPeerID=T.account_id(ids[0]),
            surveyedPeerID=T.account_id(ids[1]),
            ledgerNum=1,
            encryptionKey=T.Curve25519Public.make(key=b"\x05" * 32),
            commandType=O.SurveyMessageCommandType.SURVEY_TOPOLOGY)
        forged = O.SignedSurveyRequestMessage.make(
            requestSignature=b"\x00" * 64, request=req)
        before = len(sm_b._seen)
        sm_b.relay_or_process_request(None, forged)
        assert len(sm_b._seen) == before  # bad signature: ignored

    @staticmethod
    def _live_line_sim(n=3):
        """_line_sim with real (non-manual) closes, so SCP flood
        traffic runs and the flood-dedup vitals accumulate."""
        sim = Simulation(network_passphrase="survey net")
        seeds = _seeds(n)
        ids = _ids(seeds)
        qset = {"threshold": 2, "validators": ids}
        for s in seeds:
            sim.add_node(s, qset, MANUAL_CLOSE=False)
        for i in range(n - 1):
            sim.add_connection(ids[i], ids[i + 1])
        return sim, ids

    def test_survey_collects_remote_peer_vitals(self):
        """ISSUE 14 satellite: the survey response carries the surveyed
        node's per-peer vitals (flood dedup, traffic, seconds
        connected), so a surveying node can read REMOTE peer stats."""
        sim, ids = self._live_line_sim()
        sim.start_all_nodes()
        # long enough for consensus flood traffic (SCP envelopes) to
        # rack up unique + duplicate flood receives on every link
        sim.crank_for(8.0)
        a = sim.nodes[ids[0]]
        sm = a.overlay_manager.survey_manager
        assert sm.start_survey(ids[2])
        sim.crank_for(3.0)
        assert ids[2] in sm.results, "survey response never arrived"
        peers = sm.results[ids[2]]["peers"]
        # C's only authenticated peer is B, and the stats are B's as
        # seen FROM C — matching C's own local peer vitals
        assert [p["id"] for p in peers] == [ids[1].hex()[:8]]
        p = peers[0]
        c_local = sim.nodes[ids[2]].overlay_manager \
            .peer_vitals()[ids[1].hex()[:8]]
        assert p["unique_flood_recv"] > 0
        assert p["bytes_read"] > 0 and p["bytes_written"] > 0
        assert p["seconds_connected"] >= 8
        # the response is C's snapshot at answer time; C's local
        # counters kept growing during the extra cranking.  (A line
        # topology has no redundant flood paths, so the duplicate
        # counters stay 0 — uniques must be positive.)
        assert 0 < p["unique_flood_recv"] <= c_local["unique_flood_recv"]
        assert 0 < p["unique_flood_bytes"] <= c_local["unique_flood_bytes"]
        for key in ("duplicate_flood_recv", "duplicate_flood_bytes"):
            assert p[key] <= c_local[key], key

    def test_peer_vitals_bounded_rollup(self):
        """peer_vitals past the cap merge into one `other` bucket."""
        sim, ids = self._live_line_sim()
        sim.start_all_nodes()
        sim.crank_for(5.0)
        om = sim.nodes[ids[1]].overlay_manager  # B: two peers (A, C)
        full = om.peer_vitals()
        assert set(full) == {ids[0].hex()[:8], ids[2].hex()[:8]}
        assert all(v["unique_flood_recv"] > 0 for v in full.values())
        capped = om.peer_vitals(cap=1)
        assert set(capped) == {sorted(full)[0], "other"}
        other = capped["other"]
        spill = full[sorted(full)[1]]
        assert other["peers"] == 1
        assert other["unique_flood_recv"] == spill["unique_flood_recv"]
        assert other["bytes_read"] == spill["bytes_read"]


class TestProcessManager:
    def test_run_and_reap(self, tmp_path):
        pm = ProcessManager()
        marker = tmp_path / "touched"
        exits = []
        pm.run_command(f"touch {marker}", exits.append)
        pm.wait_all()
        assert exits and exits[0].ok
        assert marker.exists()

    def test_failure_status(self):
        pm = ProcessManager()
        exits = []
        pm.run_command("false", exits.append)
        pm.wait_all()
        assert exits and not exits[0].ok

    def test_concurrency_cap(self, tmp_path):
        pm = ProcessManager(max_concurrent=2)
        for i in range(6):
            pm.run_command(f"touch {tmp_path}/f{i}")
        assert len(pm.running) <= 2
        pm.wait_all()
        assert pm.total_spawned == 6
        assert len(os.listdir(tmp_path)) == 6

    def test_run_command_work(self, tmp_path):
        pm = ProcessManager()
        w = RunCommandWork(pm, f"touch {tmp_path}/via-work")
        w.start()
        for _ in range(10000):
            w.crank()
            if w.state not in (State.RUNNING, State.WAITING):
                break
        assert w.state == State.SUCCESS
        assert (tmp_path / "via-work").exists()
