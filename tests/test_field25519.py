"""Randomized + edge-case tests for the GF(2^255-19) limb layer.

Every op is checked against python-int arithmetic mod p (the same oracle role
libsodium's ref10 plays for the reference — SURVEY.md §7 "hard parts")."""
import numpy as np
import pytest

import jax.numpy as jnp

from stellar_core_tpu.ops import field25519 as F

P = F.P

EDGE = [0, 1, 2, 19, P - 1, P - 2, P - 19, 2**255 - 19 - 1, 2**252, 7]


def _rand_ints(n, rng):
    return [int.from_bytes(rng.bytes(32), "little") % P for _ in range(n)]


def _batch(vals):
    return jnp.asarray(np.stack([F.int_to_limbs(v) for v in vals]))


def test_roundtrip_int_limbs():
    rng = np.random.default_rng(1)
    for v in EDGE + _rand_ints(20, rng):
        assert F.limbs_to_int(F.int_to_limbs(v)) == v % P


def test_add_sub_mul_random():
    rng = np.random.default_rng(2)
    avals = EDGE + _rand_ints(40, rng)
    bvals = list(reversed(EDGE)) + _rand_ints(40, rng)
    bvals = bvals[: len(avals)]
    a, b = _batch(avals), _batch(bvals)
    got_add = np.asarray(F.freeze(F.add(a, b)))
    got_sub = np.asarray(F.freeze(F.sub(a, b)))
    got_mul = np.asarray(F.freeze(F.mul(a, b)))
    for i, (x, y) in enumerate(zip(avals, bvals)):
        assert F.limbs_to_int(got_add[i]) == (x + y) % P, f"add {i}"
        assert F.limbs_to_int(got_sub[i]) == (x - y) % P, f"sub {i}"
        assert F.limbs_to_int(got_mul[i]) == (x * y) % P, f"mul {i}"


def test_mul_chain_stays_safe():
    # repeated mul/add/sub chains must keep limbs in the mul-safe envelope
    rng = np.random.default_rng(3)
    vals = _rand_ints(8, rng)
    x = _batch(vals)
    ref = vals
    for step in range(30):
        x2 = F.mul(x, x)
        x = F.sub(F.add(x2, x), x2)  # == x, but exercises add/sub bounds
        x = F.mul(x, x2)
        ref = [(v * v * v) % P for v in ref]
        assert np.abs(np.asarray(x)[..., 1:]).max() <= F.MUL_SAFE
        assert np.abs(np.asarray(x)[..., 0]).max() <= F.MUL_SAFE_0
    frozen = np.asarray(F.freeze(x))
    for i, v in enumerate(ref):
        assert F.limbs_to_int(frozen[i]) == v


def test_freeze_negative_and_redundant():
    # hand-built redundant/signed limb vectors
    rng = np.random.default_rng(4)
    raws = np.stack(
        [
            np.full(F.NLIMBS, -8000, dtype=np.int32),
            np.full(F.NLIMBS, 8000, dtype=np.int32),
            np.concatenate([[27000], np.full(F.NLIMBS - 1, 8191)]).astype(np.int32),
            rng.integers(-8192, 8192, F.NLIMBS).astype(np.int32),
            np.zeros(F.NLIMBS, dtype=np.int32),
        ]
    )
    frozen = np.asarray(F.freeze(jnp.asarray(raws)))
    for i in range(raws.shape[0]):
        want = sum(int(raws[i, j]) << (12 * j) for j in range(F.NLIMBS)) % P
        assert F.limbs_to_int(frozen[i]) == want
        assert frozen[i].min() >= 0 and frozen[i].max() <= F.MASK


def test_inv_and_pow22523():
    rng = np.random.default_rng(5)
    vals = [v for v in EDGE if v != 0] + _rand_ints(10, rng)
    x = _batch(vals)
    got_inv = np.asarray(F.freeze(F.inv(x)))
    got_pow = np.asarray(F.freeze(F.pow22523(x)))
    for i, v in enumerate(vals):
        assert F.limbs_to_int(got_inv[i]) == pow(v, P - 2, P)
        assert F.limbs_to_int(got_pow[i]) == pow(v, (P - 5) // 8, P)


def test_bytes_roundtrip():
    rng = np.random.default_rng(6)
    vals = EDGE + _rand_ints(10, rng)
    b = np.stack(
        [np.frombuffer(int.to_bytes(v, 32, "little"), dtype=np.uint8) for v in vals]
    )
    limbs = F.from_bytes(jnp.asarray(b))
    for i, v in enumerate(vals):
        assert F.limbs_to_int(np.asarray(limbs)[i]) == v % P
    # to_bytes produces the canonical little-endian encoding
    out = np.asarray(F.to_bytes(limbs))
    for i, v in enumerate(vals):
        assert out[i].tobytes() == int.to_bytes(v % P, 32, "little")


def test_eq_parity():
    vals = [5, P - 5, 5, 0, 1]
    x = _batch(vals)
    y = _batch([5, 5, P - 5, 0, P - 1])
    got = np.asarray(F.eq(x, y))
    assert got.tolist() == [True, False, False, True, False]
    par = np.asarray(F.parity(_batch([2, 3, P - 1, P - 2])))
    assert par.tolist() == [0, 1, (P - 1) & 1, (P - 2) & 1]
