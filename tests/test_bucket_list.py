"""BucketList LSM tests (ref model: src/bucket/test/BucketListTests.cpp)."""
import pytest

from stellar_core_tpu.bucket import (
    Bucket, BucketList, level_should_spill, level_size,
)
from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.xdr import types as T


def acct(i: int, balance=100):
    return U.make_account_entry(bytes([i % 256, i // 256]) * 16, balance)


def kb_of(entry) -> bytes:
    return key_bytes(entry_to_key(entry))


def test_level_shape_matches_reference():
    # ref BucketList.cpp:208-217 levelSize = 4^(level+1)
    assert level_size(0) == 4
    assert level_size(1) == 16
    assert level_size(10) == 4**11
    # ref levelShouldSpill: half-size cadence
    assert level_should_spill(2, 0)
    assert not level_should_spill(3, 0)
    assert level_should_spill(8, 1)
    assert not level_should_spill(4, 1)


def test_hash_changes_and_is_deterministic():
    def run():
        bl = BucketList()
        h = []
        for seq in range(2, 10):
            e = acct(seq)
            h.append(bl.add_batch(seq, [(kb_of(e), e, False)]))
        return h

    h1, h2 = run(), run()
    assert h1 == h2
    assert len(set(h1)) == len(h1)  # every close moves the hash


def test_get_entry_and_delete():
    bl = BucketList()
    e = acct(1, balance=55)
    kb = kb_of(e)
    bl.add_batch(2, [(kb, e, False)])
    got = bl.get_entry(kb)
    assert got is not None and got.data.value.balance == 55
    bl.add_batch(3, [(kb, None, True)])
    assert bl.get_entry(kb) is None


def test_deleted_entry_stays_dead_across_spills():
    """Regression (review finding): update-then-delete of an entry that
    spilled to a deeper level must keep its tombstone — the update must not
    be INITENTRY or the tombstone annihilates and the old entry
    resurrects."""
    bl = BucketList()
    e = acct(7, balance=10)
    kb = kb_of(e)
    bl.add_batch(2, [(kb, e, False)])    # create (INIT)
    # push enough ledgers for level-0 spills to carry it deeper
    for seq in range(3, 11):
        filler = acct(100 + seq)
        bl.add_batch(seq, [(kb_of(filler), filler, False)])
    # update (existed_before=True -> LIVEENTRY), then delete
    e2 = acct(7, balance=99)
    bl.add_batch(11, [(kb, e2, True)])
    bl.add_batch(12, [(kb, None, True)])
    assert bl.get_entry(kb) is None
    # keep spilling: still dead at every depth
    for seq in range(13, 40):
        filler = acct(200 + seq)
        bl.add_batch(seq, [(kb_of(filler), filler, False)])
        assert bl.get_entry(kb) is None
    assert kb not in bl.all_live_entries()


def test_create_delete_annihilates():
    bl = BucketList()
    e = acct(9)
    kb = kb_of(e)
    bl.add_batch(2, [(kb, e, False)])
    bl.add_batch(3, [(kb, None, True)])
    assert bl.get_entry(kb) is None
    # merged level-0 curr should not carry a tombstone for a same-level
    # create+delete once they meet in a merge
    merged = Bucket.merge(bl.levels[0].curr, bl.levels[0].snap)
    kinds = [en.type for k, en in merged.entries if k == kb]
    # either annihilated already or DEAD-over-INIT pending a merge
    assert kinds in ([], [T.BucketEntryType.DEADENTRY])


def test_all_live_entries_flatten():
    bl = BucketList()
    entries = [acct(i, balance=i * 10 + 10) for i in range(1, 30)]
    for seq, e in enumerate(entries, start=2):
        bl.add_batch(seq, [(kb_of(e), e, False)])
    live = bl.all_live_entries()
    assert len(live) == len(entries)
    for e in entries:
        assert live[kb_of(e)].data.value.balance == \
            e.data.value.balance


def test_delete_recreate_delete_stays_dead():
    """Regression (review finding): INIT over DEAD must become LIVE so the
    second deletion keeps a tombstone instead of annihilating."""
    bl = BucketList()
    e = acct(3, balance=1)
    kb = kb_of(e)
    bl.add_batch(2, [(kb, e, False)])     # create
    # spill deep
    for seq in range(3, 20):
        f = acct(50 + seq)
        bl.add_batch(seq, [(kb_of(f), f, False)])
    bl.add_batch(20, [(kb, None, True)])  # delete
    e2 = acct(3, balance=2)
    bl.add_batch(21, [(kb, e2, False)])   # recreate
    assert bl.get_entry(kb).data.value.balance == 2
    bl.add_batch(22, [(kb, None, True)])  # delete again
    for seq in range(23, 60):
        f = acct(90 + seq)
        bl.add_batch(seq, [(kb_of(f), f, False)])
        assert bl.get_entry(kb) is None, seq
    assert kb not in bl.all_live_entries()


def test_background_merges_identical_hash_chain():
    """FutureBucket-style background merges must produce the SAME hash at
    every close as the synchronous path, and a restart mid-window (fresh
    list, no staged futures) must continue the identical chain."""
    from concurrent.futures import ThreadPoolExecutor

    from stellar_core_tpu.bucket.bucket_list import Bucket

    ex = ThreadPoolExecutor(max_workers=2)
    sync_bl = BucketList()
    bg_bl = BucketList(executor=ex)
    restored = None  # created mid-stream from bg_bl's serialized state
    hashes = []
    for seq in range(1, 130):
        changes = [(kb_of(acct(seq * 7 + j)), acct(seq * 7 + j), False)
                   for j in range(3)]
        # delete one key every few ledgers to exercise DEAD merges
        if seq % 5 == 0:
            e = acct((seq - 1) * 7)
            changes.append((kb_of(e), None, True))
        h1 = sync_bl.add_batch(seq, list(changes))
        h2 = bg_bl.add_batch(seq, list(changes))
        assert h1 == h2, f"divergence at seq {seq}"
        if restored is not None:
            h3 = restored.add_batch(seq, list(changes))
            assert h3 == h1, f"restart divergence at seq {seq}"
        if seq == 63:
            # restart mid-window: serialize bg_bl (as a HAS + bucket
            # store would), restore a fresh list — staged futures are
            # gone, exactly like a process restart — and re-attach the
            # executor so new futures stage from here on
            store = {}
            for lv in bg_bl.levels:
                for b in (lv.curr, lv.snap):
                    store[b.hash().hex()] = b.serialize()
            restored = BucketList.restore(
                bg_bl.level_hashes(),
                lambda hh: store.get(hh))
            restored.executor = ex
            assert restored.hash() == h1
            assert not restored._futures
        hashes.append(h1)
    assert len(set(hashes)) == len(hashes)  # every close moved the hash
    ex.shutdown(wait=True)


def test_bucket_manager_background_default_on(tmp_path):
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    assert app.bucket_manager.executor is not None
    assert app.bucket_manager.bucket_list.executor is not None
    app.graceful_stop()

    app2 = Application(
        VirtualClock(ClockMode.VIRTUAL_TIME),
        test_config(BACKGROUND_BUCKET_MERGES=False))
    assert app2.bucket_manager.executor is None


def test_disk_tier_bitwise_parity(tmp_path):
    """Disk-backed deep levels (DiskBucket + streaming merges) must give
    the SAME cumulative hash, lookups, and live set as the in-memory
    tier, and restore from the content-addressed files."""
    import random

    from stellar_core_tpu.bucket.bucket_list import BucketList
    from stellar_core_tpu.bucket.disk_bucket import DiskBucket

    rng = random.Random(5)
    mem = BucketList()
    disk = BucketList(disk_dir=str(tmp_path), disk_level=1)
    keys = []
    live = {}
    for seq in range(2, 200):
        changes = []
        for _ in range(4):
            i = rng.randrange(60)
            entry = acct(i, balance=seq * 10 + i)
            kb = kb_of(entry)
            existed = kb in live
            if existed and rng.random() < 0.25:
                changes.append((kb, None, True))
                live.pop(kb, None)
            else:
                changes.append((kb, entry, existed))
                live[kb] = entry
            keys.append(kb)
        h1 = mem.add_batch(seq, list(changes))
        h2 = disk.add_batch(seq, list(changes))
        assert h1 == h2, f"hash diverged at seq {seq}"
    # deep levels actually went to disk
    assert any(
        isinstance(b, DiskBucket) and not b.is_empty()
        for lv in disk.levels[1:] for b in (lv.curr, lv.snap))
    # lookups agree between tiers and with the model
    for kb in set(keys):
        assert disk.get_entry(kb) == mem.get_entry(kb)
    got = dict(disk.iter_live_entries())
    want = mem.all_live_entries()
    assert got == want
    # restore from level hashes + files reproduces the hash
    def loader(hh):
        import os
        p = tmp_path / f"bucket-{hh}.xdr"
        if p.exists():
            return p.read_bytes()
        # shallow (in-memory) buckets: reserialize from the live list
        for lv in disk.levels:
            for b in (lv.curr, lv.snap):
                if b.hash().hex() == hh:
                    return b.serialize()
        return None

    restored = BucketList.restore(disk.level_hashes(), loader,
                                  disk_dir=str(tmp_path), disk_level=1)
    assert restored.hash() == disk.hash()


def test_merge_pipeline_no_sync_fallback(tmp_path):
    """Tier-1 fail-fast guard for the async merge pipeline: across
    level-0/1/2 spill boundaries (including every-4th coincident spills)
    a background-merge list must (a) never run a non-trivial merge
    inline — sync_fallback_merges stays 0 — and (b) produce the exact
    sync hash chain.  Two identical async runs must also agree, the
    determinism guard for backgrounded merges."""
    from concurrent.futures import ThreadPoolExecutor

    def changes(seq, n=6):
        out = []
        for j in range(n):
            e = acct(seq * 50 + j, balance=seq)
            out.append((kb_of(e), e, False))
        return out

    ex = ThreadPoolExecutor(max_workers=2)
    bg1 = BucketList(executor=ex, disk_dir=str(tmp_path / "a"),
                     disk_level=2)
    bg2 = BucketList(executor=ex, disk_dir=str(tmp_path / "b"),
                     disk_level=2)
    sync = BucketList()
    for seq in range(2, 140):  # crosses spills at levels 0, 1 and 2
        ch = changes(seq)
        h1 = bg1.add_batch(seq, list(ch))
        h2 = bg2.add_batch(seq, list(ch))
        hs = sync.add_batch(seq, list(ch))
        assert h1 == h2 == hs, f"divergence at seq {seq}"
    assert bg1.stats["sync_fallback_merges"] == 0
    assert bg2.stats["sync_fallback_merges"] == 0
    assert bg1.stats["resolved_merges"] > 0
    # coincident spills were exercised (seq range covers several
    # level-0-with-level-1 and level-1-with-level-2 co-spills)
    assert bg1.stats["staged_merges"] > bg1.stats["resolved_merges"] / 2
    ex.shutdown(wait=True)


@pytest.mark.slow
def test_scale_close_latency_bounded(tmp_path):
    """BUCKET_SCALE methodology at reduced scale: with background merges
    + the native streaming kernel, no close may stall on a deep-level
    merge (sync fallback = 0) and the worst close stays bounded; two
    identical runs produce the identical final hash."""
    import time
    from concurrent.futures import ThreadPoolExecutor

    from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
    from stellar_core_tpu.transactions import utils as U

    def one_run(root):
        ex = ThreadPoolExecutor(max_workers=2)
        bl = BucketList(executor=ex, disk_dir=str(root), disk_level=2)
        times = []
        made = 0
        seq = 1
        while made < 60_000:
            seq += 1
            ch = []
            for j in range(2000):
                i = made + j
                e = U.make_account_entry(
                    i.to_bytes(4, "big") * 8, 10_000_000 + i)
                ch.append((key_bytes(entry_to_key(e)), e, False))
            made += len(ch)
            t0 = time.perf_counter()
            bl.add_batch(seq, ch)
            times.append(time.perf_counter() - t0)
        h = bl.hash()
        stats = dict(bl.stats)
        ex.shutdown(wait=True)
        return h, times, stats

    h1, times1, stats1 = one_run(tmp_path / "r1")
    h2, _, stats2 = one_run(tmp_path / "r2")
    assert h1 == h2  # bucket-hash determinism with background merges on
    assert stats1["sync_fallback_merges"] == 0
    assert stats2["sync_fallback_merges"] == 0
    assert stats1["resolved_merges"] > 0
    # worst close must not look like an inline deep-level merge: generous
    # CI bound, but orders below the pre-pipeline 40s stall
    assert max(times1) < 5.0, f"close stalled {max(times1):.1f}s"


def test_disk_tier_survives_process_kill(tmp_path):
    """Crash-safety: a node with disk-backed buckets killed with SIGKILL
    mid-run must restore its bucket list (and hash chain) from the
    content-addressed store on restart (ref: crash-safe ordering of
    close steps, LedgerManagerImpl.cpp:873-889)."""
    import os
    import signal
    import subprocess
    import sys
    import time
    import urllib.request

    import json as _json

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from stellar_core_tpu.crypto import SecretKey, sha256
    from stellar_core_tpu.crypto.strkey import (
        encode_ed25519_public_key, encode_ed25519_seed,
    )

    seed = sha256(b"kill-restore-node")
    sk = SecretKey(seed)
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    http_port = free_port()
    conf = tmp_path / "node.toml"
    conf.write_text(f"""
network_passphrase = "kill restore net"
node_seed = "{encode_ed25519_seed(seed)}"
peer_port = {free_port()}
http_port = {http_port}
known_peers = []
manual_close = true
run_standalone = true
database = "node.db"
invariant_checks = [".*"]
crypto_backend = "cpu"
scp_tally_backend = "host"
disk_bucket_level = 1

[quorum_set]
threshold = 1
validators = ["{encode_ed25519_public_key(sk.public_key().raw)}"]
""")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")

    def http(path, timeout=10.0):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/{path}",
                timeout=timeout) as r:
            return _json.load(r)

    def wait_http(deadline=30.0):
        end = time.time() + deadline
        while time.time() < end:
            try:
                return http("info")
            except Exception:
                time.sleep(0.25)
        raise TimeoutError("node did not serve /info")

    subprocess.run(
        [sys.executable, "-m", "stellar_core_tpu", "--conf", str(conf),
         "new-db"], cwd=tmp_path, env=env, capture_output=True,
        timeout=120)
    log = open(tmp_path / "node.log", "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "stellar_core_tpu", "--conf", str(conf),
         "run"], cwd=tmp_path, env=env, stdout=log, stderr=log)
    try:
        wait_http()
        http(f"generateload?mode=create&accounts=30", timeout=30)
        for _ in range(12):  # cross several level-0/1 disk spills
            http("generateload?mode=pay&txs=30", timeout=30)
            http("manualclose", timeout=30)
        info = http("info")
        seq_before = info["info"]["ledger"]["num"]
        hash_before = info["info"]["ledger"]["hash"]
        assert any((tmp_path / "buckets").glob("bucket-*.xdr"))
    finally:
        proc.kill()   # SIGKILL: no graceful shutdown
        proc.wait(10)

    log2 = open(tmp_path / "node2.log", "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "stellar_core_tpu", "--conf", str(conf),
         "run"], cwd=tmp_path, env=env, stdout=log2, stderr=log2)
    try:
        info = wait_http()
        assert info["info"]["ledger"]["num"] == seq_before
        assert info["info"]["ledger"]["hash"] == hash_before
        # the chain continues from the restored state
        http("generateload?mode=pay&txs=20", timeout=30)
        http("manualclose", timeout=30)
        assert http("info")["info"]["ledger"]["num"] == seq_before + 1
    finally:
        proc.terminate()
        proc.wait(10)
    # offline self-check over the restored store
    r = subprocess.run(
        [sys.executable, "-m", "stellar_core_tpu", "--conf", str(conf),
         "self-check"], cwd=tmp_path, env=env, capture_output=True,
        text=True, timeout=180)
    assert '"ok": true' in r.stdout, r.stdout[-500:]
