"""xdrquery filter language over decoded XDR values
(ref src/util/xdrquery — SURVEY.md §2.15)."""
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.utils.xdrquery import (
    QueryError, compile_query, query_entries,
)


def entries():
    a = U.make_account_entry(sha256(b"qa"), 5_000_000_000, seq_num=7)
    b = U.make_account_entry(sha256(b"qb"), 100, seq_num=1)
    usd = U.make_asset(b"USD", sha256(b"qi"))
    t = U.make_trustline_entry(sha256(b"qa"), usd, balance=42)
    return [a, b, t]


def test_account_balance_filter():
    out = query_entries(entries(), "data.account.balance > 1000000")
    assert len(out) == 1
    assert out[0].data.value.balance == 5_000_000_000


def test_union_arm_selects_type():
    out = query_entries(entries(), "data.trustLine.balance == 42")
    assert len(out) == 1


def test_boolean_operators():
    q = ("data.account.balance > 0 && data.account.seqNum >= 7 "
         "|| data.trustLine.balance == 42")
    assert len(query_entries(entries(), q)) == 2


def test_bytes_vs_hex_literal():
    target = sha256(b"qb").hex()
    out = query_entries(entries(),
                        f"data.account.accountID.value == '{target}'")
    assert len(out) == 1
    assert out[0].data.value.balance == 100


def test_missing_path_fails_row():
    assert query_entries(entries(), "data.offer.amount > 0") == []


def test_not_and_parens():
    out = query_entries(entries(),
                        "!(data.account.balance > 1000) && "
                        "data.account.seqNum == 1")
    assert len(out) == 1


def test_syntax_error():
    with pytest.raises(QueryError):
        compile_query("data.account.balance >")
    with pytest.raises(QueryError):
        compile_query("balance ??? 3")
