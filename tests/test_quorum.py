"""Quorum-set tensor math vs a direct recursive reference.

Model: the reference's SCP unit tests (src/scp/test/SCPUnitTests.cpp)
exercise isQuorumSlice/isVBlocking/isQuorum over hand-built nested quorum
sets; here the same properties check the tensorised kernels in
stellar_core_tpu.ops.quorum against a plain-python evaluator.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from stellar_core_tpu.ops import quorum as Q


# plain-python reference semantics (2-level qsets)

def ref_slice(qset, s):
    thr, vals, inners = qset
    hits = sum(1 for v in vals if v in s)
    hits += sum(
        1
        for ithr, ivals in inners
        if ithr > 0 and sum(1 for v in ivals if v in s) >= ithr
    )
    return hits >= thr


def ref_vblocking(qset, s):
    thr, vals, inners = qset
    if thr == 0:
        return False
    universe_minus = lambda members: [v for v in members if v not in s]
    avail = len(universe_minus(vals))
    avail += sum(
        1
        for ithr, ivals in inners
        if ithr > 0 and len(universe_minus(ivals)) >= ithr
    )
    return avail < thr


def ref_max_quorum(qsets, members):
    cur = set(members)
    while True:
        nxt = {n for n in cur if ref_slice(qsets[n], cur)}
        if nxt == cur:
            return nxt
        cur = nxt


NODES = list(range(6))
# a mix of flat and nested qsets over 6 nodes
QSETS = [
    (2, [0, 1, 2], []),
    (3, [0, 1, 2, 3], []),
    (2, [1], [(2, [2, 3, 4]), (1, [5])]),
    (1, [], [(3, [0, 1, 2, 3])]),
    (4, [0, 1, 2, 3, 4], []),
    (2, [4, 5], [(2, [0, 1])]),
]


def qt():
    return Q.build_qset_tensor(QSETS, NODES)


def all_subsets():
    for mask in range(64):
        yield {i for i in NODES if mask >> i & 1}


def subset_matrix():
    m = np.zeros((64, 6), np.bool_)
    for mask in range(64):
        for i in NODES:
            m[mask, i] = bool(mask >> i & 1)
    return jnp.asarray(m)


def test_is_quorum_slice_matches_reference():
    t = qt()
    sets = subset_matrix()
    # batch over nodes: evaluate node i's qset against all 64 subsets
    got = np.asarray(Q.is_quorum_slice(t, jnp.broadcast_to(sets, (6, 64, 6))))
    for i, qset in enumerate(QSETS):
        for mask, s in enumerate(all_subsets()):
            assert got[i, mask] == ref_slice(qset, s), (i, s)


def test_is_v_blocking_matches_reference():
    t = qt()
    sets = subset_matrix()
    got = np.asarray(Q.is_v_blocking(t, jnp.broadcast_to(sets, (6, 64, 6))))
    for i, qset in enumerate(QSETS):
        for mask, s in enumerate(all_subsets()):
            assert got[i, mask] == ref_vblocking(qset, s), (i, s)


def test_contract_to_maximal_quorum():
    t = qt()
    for mask, s in enumerate(all_subsets()):
        members = jnp.asarray([i in s for i in NODES])
        got = np.asarray(Q.contract_to_maximal_quorum(t, members))
        want = ref_max_quorum(QSETS, s)
        assert {i for i in NODES if got[i]} == want, s


def test_threshold_zero_never_blocks():
    t = Q.build_qset_tensor([(0, [], [])], [0])
    local = Q.QSetTensor(
        t.top_mem[0], t.top_thr[0], t.inner_mem[0], t.inner_thr[0]
    )
    sets = jnp.asarray([[True]])
    assert not bool(Q.is_v_blocking(local, sets)[0])


def _local(t, i=0):
    return Q.QSetTensor(
        t.top_mem[i], t.top_thr[i], t.inner_mem[i], t.inner_thr[i]
    )


def test_federated_ratify_simple_majority():
    # 4 nodes, 3-of-4 everywhere: a 3-node voted set ratifies, 2-node doesn't
    nodes = list(range(4))
    t = Q.build_qset_tensor([(3, nodes, []) for _ in nodes], nodes)
    voted = jnp.asarray(
        [[True, True, True, False], [True, True, False, False]]
    )
    got = np.asarray(Q.federated_ratify(_local(t), t, voted))
    assert got.tolist() == [True, False]


def test_federated_ratify_requires_local_slice():
    # Disjoint quorum among remote voters must NOT ratify for the local node
    # (ref LocalNode::isQuorum filters with the local qset).  Nodes 0,1 form
    # a 2-of-{0,1} quorum; local node 3 needs 2-of-{2,3}.
    nodes = list(range(4))
    qsets = [(2, [0, 1], []), (2, [0, 1], []),
             (2, [2, 3], []), (2, [2, 3], [])]
    t = Q.build_qset_tensor(qsets, nodes)
    voted = jnp.asarray([[True, True, False, False]])
    local3 = _local(t, 3)
    assert not bool(Q.federated_ratify(local3, t, voted)[0])
    # ...and federated_accept must not fire off that phantom quorum either
    accepted = jnp.zeros_like(voted)
    assert not bool(Q.federated_accept(local3, t, voted, accepted)[0])
    # but for node 0 (whose slice is inside {0,1}) it DOES ratify
    assert bool(Q.federated_ratify(_local(t, 0), t, voted)[0])


def test_federated_accept_vblocking_path():
    # accept via v-blocking acceptance even when vote-quorum is absent
    nodes = list(range(4))
    t = Q.build_qset_tensor([(3, nodes, []) for _ in nodes], nodes)
    local = Q.QSetTensor(
        t.top_mem[0], t.top_thr[0], t.inner_mem[0], t.inner_thr[0]
    )
    # v-blocking for 3-of-4 is any 2 nodes
    accepted = jnp.asarray([[False, True, True, False]])
    voted = jnp.asarray([[False, False, False, False]])
    got = np.asarray(Q.federated_accept(local, t, voted, accepted))
    assert got.tolist() == [True]
    # single accepter is not v-blocking and no quorum voted
    accepted2 = jnp.asarray([[False, True, False, False]])
    got2 = np.asarray(Q.federated_accept(local, t, voted, accepted2))
    assert got2.tolist() == [False]


def test_tensor_is_quorum_matches_host_oracle():
    # disjoint sub-quorum case from the review: members={0,1,2} contract to
    # {0,1}; local 0 accepts (its slice is inside), local 3 must not
    from stellar_core_tpu.scp import make_qset
    from stellar_core_tpu.scp import local_node as LN

    nodes = list(range(4))
    plain = [(2, [0, 1], []), (2, [0, 1], []),
             (2, [2, 3], []), (2, [2, 3], [])]
    t = Q.build_qset_tensor(plain, nodes)
    members = jnp.asarray([True, True, True, False])
    got0 = bool(Q.is_quorum(_local(t, 0), t, members))
    got3 = bool(Q.is_quorum(_local(t, 3), t, members))

    ids = [bytes([i + 1]) * 32 for i in nodes]
    qsets = {
        ids[i]: make_qset(thr, [ids[v] for v in vals])
        for i, (thr, vals, _) in enumerate(plain)
    }
    mem_ids = {ids[0], ids[1], ids[2]}
    want0 = LN.is_quorum(mem_ids, qsets.get, local_qset=qsets[ids[0]])
    want3 = LN.is_quorum(mem_ids, qsets.get, local_qset=qsets[ids[3]])
    assert (got0, got3) == (want0, want3) == (True, False)


def test_qset_to_plain_depth_fallback():
    from stellar_core_tpu.scp import make_qset
    from stellar_core_tpu.scp.local_node import qset_to_plain
    from stellar_core_tpu.xdr import types as T

    a, b = b"\x01" * 32, b"\x02" * 32
    two = T.SCPQuorumSet.make(
        threshold=1, validators=[T.account_id(a)],
        innerSets=[make_qset(1, [b])])
    assert qset_to_plain(two) is not None
    three = T.SCPQuorumSet.make(
        threshold=1, validators=[],
        innerSets=[T.SCPQuorumSet.make(
            threshold=1, validators=[T.account_id(a)],
            innerSets=[make_qset(1, [b])])])
    assert qset_to_plain(three) is None
