"""Transaction-lifecycle telemetry tests (ISSUE 12 tentpole part 1).

The tracker follows sampled txs across subsystems (overlay recv ->
admit -> txset -> nominate -> externalize -> apply -> durable commit).
It is OBSERVATIONAL: ledger/bucket hashes AND meta bytes must be
bit-identical with tracking on vs off, under PIPELINED_CLOSE on/off and
under PYTHONHASHSEED variation; sampling must be a deterministic
function of the admission sequence (stride decimation, never hash order
or a PRNG); and the pipelined tail's commit stamp must land on the
ORIGINATING ledger even though it runs during the next one.
"""
import json
import os
import subprocess
import sys

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.utils.txtrace import STAGES, TxLifecycleTracker
from stellar_core_tpu.xdr import types as T


class _Frame:
    """Minimal frame stub: the tracker only calls full_hash()."""

    def __init__(self, h: bytes):
        self._h = h

    def full_hash(self) -> bytes:
        return self._h


def _hashes(n):
    return [b"%032d" % i for i in range(n)]


# -- unit: sampling + bounding ----------------------------------------------

def test_stride_decimation_deterministic_and_bounded():
    """Which txs get tracked is a pure function of the admission
    sequence; the live map never exceeds max_live and the stride
    doubles on every decimation (the PR-4 Histogram discipline)."""
    def run():
        tr = TxLifecycleTracker(max_live=16, ring=8)
        for h in _hashes(300):
            tr.on_admit(h)
        return list(tr._live), tr._stride, tr.stats()

    live_a, stride_a, stats_a = run()
    live_b, stride_b, stats_b = run()
    assert live_a == live_b and stride_a == stride_b
    assert stats_a == stats_b
    assert len(live_a) <= 16
    assert stride_a >= 2 and stats_a["decimations"] >= 1
    assert stats_a["seen"] == 300


def test_completed_ring_is_bounded():
    tr = TxLifecycleTracker(max_live=64, ring=4)
    for h in _hashes(20):
        tr.on_admit(h)
        tr.stamp_frames([_Frame(h)], "apply")
        tr.stamp_frames([_Frame(h)], "commit", seq=7)
    assert tr.stats()["completed"] == 20
    assert len(tr._ring) == 4  # ring kept the LAST 4 only


def test_disabled_and_untracked_stamps_are_noops():
    tr = TxLifecycleTracker(enabled=False)
    tr.on_admit(b"x" * 32)
    tr.stamp_frames([_Frame(b"x" * 32)], "commit", seq=1)
    assert tr.stats()["seen"] == 0 and tr.stats()["completed"] == 0
    tr2 = TxLifecycleTracker()
    # never admitted -> every stamp is a dict-probe no-op
    tr2.stamp_frames([_Frame(b"y" * 32)], "apply")
    tr2.stamp_frames([_Frame(b"y" * 32)], "commit", seq=1)
    assert tr2.stats()["completed"] == 0


def test_stage_deltas_skip_missing_stages():
    """A tx that entered via a peer-proposed set has no txset/nominate
    stamps; deltas pair only the PRESENT stages."""
    tr = TxLifecycleTracker()
    h = b"z" * 32
    tr.on_admit(h)
    f = _Frame(h)
    tr.stamp_frames([f], "externalize")
    tr.stamp_frames([f], "apply")
    tr.stamp_frames([f], "commit", seq=3)
    names = sorted(n for n in tr.metrics._metrics
                   if n.startswith("txtrace.stage."))
    assert names == ["txtrace.stage.admit_to_externalize",
                     "txtrace.stage.apply_to_commit",
                     "txtrace.stage.externalize_to_apply"]
    rec = tr.report()["recent"][-1]
    assert rec["ledger"] == 3
    ms = rec["stages_ms"]
    assert ms["admit"] <= ms["externalize"] <= ms["apply"] <= ms["commit"]


# -- through the real node ---------------------------------------------------

def _mk_app(**kw):
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=200, **kw))
    app.start()
    return app


def test_lifecycle_through_real_closes():
    app = _mk_app()
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "16"})
    assert code == 200, body
    app.herder.manual_close()
    code, body = handler.handle("generateload",
                                {"mode": "pay", "txs": "32"})
    assert code == 200, body
    app.herder.manual_close()
    rep = app.txtracer.report()
    assert rep["completed"] >= 32
    rec = rep["recent"][-1]
    assert rec["ledger"] == app.ledger_manager.last_closed_seq()
    ms = rec["stages_ms"]
    # the full self-proposed pipeline, stamps in monotonic order —
    # including the r16 "fee" stage (stamped whether the batched fee
    # kernel or the per-tx reference loop charged the tx)
    for a, b in zip(("admit", "txset", "nominate", "externalize",
                     "fee", "apply", "commit"),
                    ("txset", "nominate", "externalize", "fee",
                     "apply", "commit", "commit")):
        assert ms[a] <= ms[b], (a, b, ms)
    assert rep["latency"]["txtrace.e2e.admit_to_commit"]["count"] >= 32
    assert rep["latency"]["txtrace.stage.fee_to_apply"]["count"] >= 32
    app.graceful_stop()


def test_pipelined_commit_stamp_lands_on_originating_ledger():
    """The PR-9 cross-close discipline: with the tail genuinely
    overlapping (eager drain off), the commit stamp runs during ledger
    N+1 but the completed record carries N."""
    app = _mk_app(PIPELINED_CLOSE=True, PIPELINED_CLOSE_EAGER_DRAIN=False)
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "12"})
    assert code == 200, body
    app.herder.manual_close()
    seqs = []
    for _ in range(3):
        code, body = handler.handle("generateload",
                                    {"mode": "pay", "txs": "12"})
        assert code == 200, body
        app.herder.manual_close()
        seqs.append(app.ledger_manager.last_closed_seq())
    app.ledger_manager.pipeline.drain()
    rep = app.txtracer.report(last=64)
    got = {r["ledger"] for r in rep["recent"]}
    assert set(seqs) <= got, (seqs, got)
    assert app.ledger_manager.pipeline.stats["tails"] >= 3
    app.graceful_stop()


def test_overlay_recv_stamp_feeds_recv_to_commit():
    """A tx arriving via the overlay path gets the recv stage; the
    recv->admit and recv->commit rollups appear."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.simulation.simulation import pair

    sim = pair()
    sim.start_all_nodes()
    assert sim.close_ledger()
    a, b = list(sim.nodes.values())
    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    lg = LoadGenerator(a)
    root_env = lg.create_account_envelopes(4)
    for env in root_env:
        assert a.herder.recv_transaction(env) == 0

    def _accounts_exist():
        with LedgerTxn(a.ledger_manager.root) as ltx:
            e = ltx.load_account(lg.accounts[0].public_key().raw)
            ltx.rollback()
        return e is not None

    # the round leader may pick the other node's (empty) proposal, so
    # a queued tx can take an extra round to land
    for _ in range(4):
        assert sim.close_ledger()
        if _accounts_exist():
            break
    assert _accounts_exist()
    # pay txs flood a -> b; b's tracker sees them via overlay recv
    envs = lg.generate_payments(8)
    for env in envs:
        assert a.herder.recv_transaction(env) == 0
    for _ in range(4):
        assert sim.close_ledger()
        if b.txtracer.stats()["completed"] >= 1:
            break
    rep_b = b.txtracer.report()
    assert rep_b["completed"] >= 1
    assert "txtrace.e2e.recv_to_commit" in rep_b["latency"]
    assert "txtrace.stage.recv_to_admit" in rep_b["latency"]
    for app in sim.nodes.values():
        app.stop_node()


# -- observational bit-identity ----------------------------------------------

def run_telemetry_workload(telemetry: bool, pipelined: bool = False,
                           **kw):
    """Deterministic mixed workload through the full close path with
    the lifecycle tracker + vitals sampling on or off; returns per-close
    (ledger hash, bucket hash, meta bytes).  Shared with
    tools/soak_bench.py's parity pass."""
    app = _mk_app(
        TX_LIFECYCLE_TRACKING=telemetry,
        PIPELINED_CLOSE=pipelined,
        PIPELINED_CLOSE_EAGER_DRAIN=False if pipelined else None,
        **kw)
    handler = CommandHandler(app)
    out = []

    def close():
        if telemetry:
            app.vitals.sample_once()
        app.herder.manual_close()
        out.append((app.ledger_manager.last_closed_hash(),
                    app.bucket_manager.get_bucket_list_hash()))

    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "20"})
    assert code == 200, body
    close()
    for _ in range(2):  # issuer, trustlines, funding
        code, body = handler.handle("generateload",
                                    {"mode": "mixed", "txs": "40"})
        assert code == 200, body
        close()
    for _ in range(3):
        code, body = handler.handle(
            "generateload", {"mode": "mixed", "txs": "40",
                             "dexpct": "40"})
        assert code == 200, body
        close()
    app.ledger_manager.pipeline.drain()
    metas = [T.LedgerCloseMeta.encode(m) for m in app._meta_stream]
    app.graceful_stop()
    assert len(metas) == len(out)
    return [h + (m,) for h, m in zip(out, metas)]


def _assert_identical(a, b, label):
    assert len(a) == len(b)
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra[0] == rb[0], f"[{label}] ledger hash diverged @ {i}"
        assert ra[1] == rb[1], f"[{label}] bucket hash diverged @ {i}"
        assert ra[2] == rb[2], f"[{label}] meta bytes diverged @ {i}"


def test_hashes_and_meta_identical_telemetry_on_off():
    """The acceptance gate: stamps are observational — bytes identical
    with telemetry on vs off, sequential AND pipelined close."""
    base_on = run_telemetry_workload(True)
    base_off = run_telemetry_workload(False)
    _assert_identical(base_on, base_off, "sequential")
    pipe_on = run_telemetry_workload(True, pipelined=True)
    pipe_off = run_telemetry_workload(False, pipelined=True)
    _assert_identical(pipe_on, pipe_off, "pipelined")
    # and the pipeline itself stays bit-identical with telemetry on
    _assert_identical(base_on, pipe_on, "seq-vs-pipe")


_HASHSEED_WORKER = """
import hashlib
import sys

sys.path.insert(0, {repo!r})
from tests.test_txtrace import run_telemetry_workload

for lh, bh, meta in run_telemetry_workload(True, pipelined=True):
    print(lh.hex(), bh.hex(), hashlib.sha256(meta).hexdigest())
"""


def test_telemetry_bit_stable_under_hashseed_variation():
    """PYTHONHASHSEED 0 vs 4242 with telemetry ON and the pipeline ON:
    every per-close fingerprint must match — tracking must not smuggle
    hash-order anywhere consensus-visible."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outputs = []
    for seed in ("0", "4242"):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = seed
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _HASHSEED_WORKER.format(repo=repo)],
            capture_output=True, text=True, cwd=repo, env=env,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-4000:]
        lines = proc.stdout.strip().splitlines()
        assert len(lines) >= 6, proc.stdout
        outputs.append(lines)
    a, b = outputs
    assert a == b, "telemetry-on close fingerprints diverged across " \
                   "PYTHONHASHSEED values"


# -- endpoint ---------------------------------------------------------------

def test_tx_latency_endpoint_roundtrip():
    app = _mk_app()
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "8"})
    assert code == 200, body
    app.herder.manual_close()
    code, body = handler.handle("tx/latency", {"last": "4"})
    assert code == 200
    rep = body["tx_latency"]
    assert rep["enabled"] is True and rep["completed"] >= 1
    assert len(rep["recent"]) <= 4
    for s in rep["latency"].values():
        assert set(s) == {"count", "p50_ms", "p99_ms", "mean_ms",
                          "max_ms"}
    json.dumps(body)  # the HTTP layer serializes this verbatim
    # prometheus exposition carries the same histograms
    code, prom = handler.handle("metrics", {"format": "prometheus"})
    assert code == 200
    assert "txtrace_e2e_admit_to_commit" in prom.data.decode()
    app.graceful_stop()
