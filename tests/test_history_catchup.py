"""History publish + archive catchup + restart persistence
(ref test models: src/history/test/HistoryTests.cpp CatchupSimulation,
src/history/test/HistoryTestsUtils.h tempdir archives)."""
import os

import pytest

from stellar_core_tpu.catchup import CatchupConfiguration, CatchupWork
from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.herder.tx_set import TxSetFrame
from stellar_core_tpu.history import HistoryArchive, checkpoint_name
from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.work.work import State
from stellar_core_tpu.xdr import types as T
from stellar_core_tpu.xdr import xdr_sha256

from .txtest import TestAccount


class NodeAccount(TestAccount):
    def __init__(self, app, secret):
        self.app = app
        self.secret = secret
        self.account_id = secret.public_key().raw

    @property
    def ledger(self):
        class _L:
            root_txn = self.app.ledger_manager.root
        return _L()


def make_node(tmp_path, name="node", archive_dir=None, db=None,
              bucket_dir=None):
    kw = dict(ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True)
    if archive_dir is not None:
        kw["HISTORY_ARCHIVES"] = [("test", str(archive_dir))]
    if db is not None:
        kw["DATABASE"] = str(db)
    if bucket_dir is not None:
        kw["BUCKET_DIR_PATH_REAL"] = str(bucket_dir)
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config(**kw))
    app.start()
    return app


def close_ledgers_with_traffic(app, n, start_name=0):
    """Close n ledgers, a create-account tx in each odd one."""
    root = NodeAccount(app, SecretKey(app.config.network_id()))
    for i in range(n):
        if i % 2 == 1:
            dest = SecretKey(sha256(b"dest-%d-%d" % (start_name, i)))
            env = root.tx([root.op_create_account(
                dest.public_key().raw, 10**9)])
            assert app.herder.recv_transaction(env) == 0
        app.herder.manual_close()


class TestPublish:
    def test_checkpoints_published(self, tmp_path):
        arch_dir = tmp_path / "archive"
        app = make_node(tmp_path, archive_dir=arch_dir)
        assert app.history_manager.checkpoint_frequency() == 8
        close_ledgers_with_traffic(app, 20)
        # checkpoints at 7 and 15 published
        archive = HistoryArchive("test", str(arch_dir))
        has = archive.get_root_has()
        assert has is not None and has.current_ledger == 15
        for cp in (7, 15):
            blob = archive.get_xdr_gz("ledger", checkpoint_name(cp))
            assert blob
            from stellar_core_tpu.xdr.runtime import Reader

            r = Reader(blob)
            entries = []
            while not r.done():
                entries.append(T.LedgerHeaderHistoryEntry.unpack(r))
            # chain verifies and stored hashes are correct
            for e in entries:
                assert xdr_sha256(T.LedgerHeader, e.header) == e.hash
            for a, b in zip(entries, entries[1:]):
                assert b.header.previousLedgerHash == a.hash
            assert archive.get_xdr_gz("transactions",
                                      checkpoint_name(cp)) is not None
            assert archive.get_xdr_gz("scp",
                                      checkpoint_name(cp)) is not None
        # every HAS bucket is retrievable
        for hh in has.all_bucket_hashes():
            assert archive.get_bucket(hh) is not None

    def test_publish_queue_survives_crash(self, tmp_path):
        """Queueing is derived from committed headers: a node that closed a
        checkpoint re-publishes on restart (ref publish retry after crash,
        LedgerManagerImpl.cpp:877-881)."""
        arch_dir = tmp_path / "archive"
        db = tmp_path / "node.db"
        bdir = tmp_path / "buckets"
        app = make_node(tmp_path, archive_dir=arch_dir, db=db,
                        bucket_dir=bdir)
        close_ledgers_with_traffic(app, 9)  # checkpoint 7 published
        archive = HistoryArchive("test", str(arch_dir))
        assert archive.get_root_has().current_ledger == 7


class TestDelayedPublish:
    def test_delayed_republish_keeps_checkpoint_usable(self, tmp_path):
        """A publish delayed past further closes (crash-retry) must stamp
        the HAS with the bucket state AT the checkpoint, not the current
        one — else minimal catchup to it breaks forever."""
        arch_dir = tmp_path / "archive"
        app = make_node(tmp_path, archive_dir=arch_dir)
        hm = app.history_manager
        real_publish = hm.publish_queued_history
        hm.publish_queued_history = lambda: None  # simulate pre-publish crash
        close_ledgers_with_traffic(app, 12)  # checkpoint 7 queued, unpublished
        archive = HistoryArchive("test", str(arch_dir))
        assert archive.get_root_has() is None
        hm.publish_queued_history = real_publish
        hm.publish_queued_history()  # delayed: bucket list has moved on
        has = archive.get_checkpoint_has(7)
        assert has is not None
        # the published HAS matches the archived header's bucketListHash
        blob = archive.get_xdr_gz("ledger", checkpoint_name(7))
        from stellar_core_tpu.xdr.runtime import Reader

        r = Reader(blob)
        hdr = None
        while not r.done():
            e = T.LedgerHeaderHistoryEntry.unpack(r)
            if e.header.ledgerSeq == 7:
                hdr = e.header
        from stellar_core_tpu.bucket.bucket_list import BucketList

        bl = BucketList.restore(
            [(b["curr"], b["snap"]) for b in has.buckets],
            archive.get_bucket)
        assert bl.hash() == hdr.bucketListHash

        # and a fresh node can minimal-catchup to it
        app_b = make_node(tmp_path, archive_dir=arch_dir)
        work = CatchupWork(app_b, app_b.history_manager.archives[0],
                          CatchupConfiguration(7))
        work.start()
        for _ in range(100):
            work.crank()
            if work.state not in (State.RUNNING, State.WAITING):
                break
        assert work.state == State.SUCCESS
        assert app_b.ledger_manager.last_closed_seq() == 7


class TestRestart:
    def test_stop_start_continues_hash_chain(self, tmp_path):
        db = tmp_path / "node.db"
        bdir = tmp_path / "buckets"
        app = make_node(tmp_path, db=db, bucket_dir=bdir)
        close_ledgers_with_traffic(app, 10)
        lcl_seq = app.ledger_manager.last_closed_seq()
        lcl_hash = app.ledger_manager.last_closed_hash()
        bl_hash = app.bucket_manager.get_bucket_list_hash()
        app.database.close()
        del app

        app2 = make_node(tmp_path, db=db, bucket_dir=bdir)
        assert app2.ledger_manager.last_closed_seq() == lcl_seq
        assert app2.ledger_manager.last_closed_hash() == lcl_hash
        assert app2.bucket_manager.get_bucket_list_hash() == bl_hash
        # chain continues across the restart
        close_ledgers_with_traffic(app2, 3, start_name=1)
        hdr = app2.ledger_manager.last_closed_header()
        assert hdr.ledgerSeq == lcl_seq + 3

    def test_restart_without_bucket_dir_still_boots(self, tmp_path):
        """A persistent-DB node without an on-disk bucket store must still
        restart (degraded: bucket list rebuilt empty; archives are its
        rejoin path) — regression for the unconditional restore."""
        db = tmp_path / "node.db"
        app = make_node(tmp_path, db=db)
        close_ledgers_with_traffic(app, 5)
        seq = app.ledger_manager.last_closed_seq()
        app.database.close()
        del app
        app2 = make_node(tmp_path, db=db)
        assert app2.ledger_manager.last_closed_seq() == seq

    def test_restart_detects_bucket_corruption(self, tmp_path):
        db = tmp_path / "node.db"
        bdir = tmp_path / "buckets"
        app = make_node(tmp_path, db=db, bucket_dir=bdir)
        close_ledgers_with_traffic(app, 6)
        app.database.close()
        # corrupt every persisted bucket
        for name in os.listdir(bdir):
            p = os.path.join(bdir, name)
            with open(p, "r+b") as f:
                f.seek(8)
                f.write(b"\xff\xff\xff\xff")
        with pytest.raises(RuntimeError):
            make_node(tmp_path, db=db, bucket_dir=bdir)


class TestCatchup:
    def _publisher(self, tmp_path, n_ledgers):
        arch_dir = tmp_path / "archive"
        app = make_node(tmp_path, archive_dir=arch_dir)
        close_ledgers_with_traffic(app, n_ledgers)
        return app, arch_dir

    def test_catchup_work_minimal(self, tmp_path):
        app_a, arch_dir = self._publisher(tmp_path, 34)
        lcl_a = app_a.ledger_manager.last_closed_seq()
        cp = app_a.history_manager.latest_checkpoint_at_or_before(lcl_a)

        app_b = make_node(tmp_path, archive_dir=arch_dir)
        archive = app_b.history_manager.archives[0]
        work = CatchupWork(app_b, archive, CatchupConfiguration(cp))
        app_b.work_scheduler.schedule(work)
        for _ in range(1000):
            app_b.work_scheduler.crank()
            if work.state not in (State.RUNNING, State.WAITING):
                break
        assert work.state == State.SUCCESS
        assert app_b.ledger_manager.last_closed_seq() == cp
        # B's header hash matches A's archived chain
        row_a = app_a.database.execute(
            "SELECT data FROM ledgerheaders WHERE ledgerseq=?",
            (cp,)).fetchone()
        want = xdr_sha256(T.LedgerHeader, T.LedgerHeader.decode(row_a[0]))
        assert app_b.ledger_manager.last_closed_hash() == want
        # full state equality: bucket hashes agree at the checkpoint
        has = archive.get_checkpoint_has(cp)
        assert app_b.bucket_manager.get_bucket_list_hash() == \
            T.LedgerHeader.decode(row_a[0]).bucketListHash

    def test_node_rejoins_via_buffered_gap(self, tmp_path):
        """The VERDICT r2 done-gate: node goes away, network advances 30+
        ledgers, node rejoins from the archive + live buffer and matches
        hashes."""
        app_a, arch_dir = self._publisher(tmp_path, 34)
        lm_a = app_a.ledger_manager

        app_b = make_node(tmp_path, archive_dir=arch_dir)
        # B receives only the recent externalized values (as if it had been
        # offline): replay A's meta stream tail through B's catchup manager
        cp = app_a.history_manager.latest_checkpoint_at_or_before(
            lm_a.last_closed_seq())
        metas = [m.value for m in app_a._meta_stream
                 if m.value.ledgerHeader.header.ledgerSeq > cp]
        assert metas, "need post-checkpoint ledgers to buffer"
        for m in metas:
            seq = m.ledgerHeader.header.ledgerSeq
            frame = TxSetFrame.make_from_wire(
                app_b.config.network_id(), m.txSet)
            app_b.catchup_manager.buffer_externalized(
                seq, frame, m.ledgerHeader.header.scpValue)
        # catchup runs ASYNC on B's work scheduler (r17): crank B until
        # the work completes and the buffer drains
        for _ in range(20000):
            if app_b.catchup_manager.catchup_runs >= 1 and \
                    not app_b.catchup_manager.buffered:
                break
            app_b.crank(block=True)
        assert app_b.catchup_manager.catchup_runs >= 1
        assert app_b.ledger_manager.last_closed_seq() == \
            lm_a.last_closed_seq()
        assert app_b.ledger_manager.last_closed_hash() == \
            lm_a.last_closed_hash()
        assert app_b.bucket_manager.get_bucket_list_hash() == \
            app_a.bucket_manager.get_bucket_list_hash()
        # and B keeps closing ledgers normally afterwards
        close_b = NodeAccount(app_b, SecretKey(app_b.config.network_id()))
        env = close_b.tx([close_b.op_create_account(
            SecretKey(sha256(b"post-rejoin")).public_key().raw, 10**9)])
        assert app_b.herder.recv_transaction(env) == 0
        app_b.herder.manual_close()
        assert app_b.ledger_manager.last_closed_seq() == \
            lm_a.last_closed_seq() + 1

    def test_catchup_replay_mode_verifies_results(self, tmp_path):
        """COMPLETE catchup replays every tx set and must reproduce the
        exact archived header hashes (the bit-identical-results gate at
        ledger granularity)."""
        app_a, arch_dir = self._publisher(tmp_path, 18)
        lcl_a = app_a.ledger_manager.last_closed_seq()
        cp = app_a.history_manager.latest_checkpoint_at_or_before(lcl_a)

        app_b = make_node(tmp_path, archive_dir=arch_dir)
        archive = app_b.history_manager.archives[0]
        work = CatchupWork(
            app_b, archive,
            CatchupConfiguration(cp, CatchupConfiguration.COMPLETE))
        app_b.work_scheduler.schedule(work)
        for _ in range(1000):
            app_b.work_scheduler.crank()
            if work.state not in (State.RUNNING, State.WAITING):
                break
        assert work.state == State.SUCCESS
        assert app_b.ledger_manager.last_closed_seq() == cp
        row_a = app_a.database.execute(
            "SELECT data FROM ledgerheaders WHERE ledgerseq=?",
            (cp,)).fetchone()
        assert app_b.ledger_manager.last_closed_hash() == \
            xdr_sha256(T.LedgerHeader, T.LedgerHeader.decode(row_a[0]))
        # the replay must NOT clobber the archive it read: the publisher's
        # scp history for checkpoint 7 survives (regression: replayed
        # closes used to re-publish empty scp blobs over it)
        assert archive.get_xdr_gz("scp", checkpoint_name(7))


class TestRestartScpState:
    def test_scp_state_restored_on_boot(self, tmp_path):
        """A restarted validator re-serves its latest externalize
        statements (ref Herder::restoreSCPState)."""
        db = tmp_path / "scp.db"
        app = make_node(tmp_path, db=db)
        close_ledgers_with_traffic(app, 4)
        last = app.ledger_manager.last_closed_seq()
        app.database.close()
        del app
        app2 = make_node(tmp_path, db=db)
        msgs = app2.herder.scp.get_latest_messages_send(last)
        assert msgs, "no SCP state restored for the last slot"
        # and boot did NOT replay/advance anything
        assert app2.ledger_manager.last_closed_seq() == last


class TestMetaStreamFile:
    def test_meta_stream_written_and_parsable(self, tmp_path):
        path = tmp_path / "meta.xdr"
        app = make_node(tmp_path)
        app.config.METADATA_OUTPUT_STREAM = str(path)
        close_ledgers_with_traffic(app, 3)
        data = path.read_bytes()
        frames = 0
        while data:
            n = int.from_bytes(data[:4], "big")
            T.LedgerCloseMeta.decode(data[4:4 + n])
            data = data[4 + n:]
            frames += 1
        assert frames == 3


class TestCommandArchive:
    """Remote-transport archives: get/put shell command templates run as
    subprocesses through ProcessManager + RunCommandWork (VERDICT r3
    missing #1; ref src/history/readme.md:8-30 — the operator's transport
    is an arbitrary command, not library file I/O)."""

    def test_publish_and_catchup_via_command_templates(self, tmp_path):
        remote = tmp_path / "remote-store"
        remote.mkdir()
        # put: stage into the "remote" store via cp run in a subprocess;
        # install -D creates parent dirs like the reference's mkdir cmd
        put_tpl = (f"install -D {{0}} {remote}/{{1}}")
        get_tpl = (f"cp {remote}/{{1}} {{0}}")

        kw = dict(ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True,
                  HISTORY_ARCHIVES=[{"name": "cmd", "put": put_tpl}])
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                          test_config(**kw))
        app.start()
        from stellar_core_tpu.history.archive import CommandArchive

        assert isinstance(app.history_manager.archives[0], CommandArchive)
        close_ledgers_with_traffic(app, 9)  # checkpoint 7 published

        # the remote store was populated by subprocess transfers only
        assert (remote / ".well-known" / "stellar-history.json").exists()

        # a fresh node catches up reading through the get template
        kw_b = dict(ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True,
                    HISTORY_ARCHIVES=[{"name": "cmd", "get": get_tpl}])
        app_b = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                            test_config(**kw_b))
        app_b.start()
        work = CatchupWork(app_b, app_b.history_manager.archives[0],
                           CatchupConfiguration(7))
        work.start()
        for _ in range(200):
            work.crank()
            if work.state not in (State.RUNNING, State.WAITING):
                break
        assert work.state == State.SUCCESS
        assert app_b.ledger_manager.last_closed_seq() == 7

    def test_failed_get_returns_none(self, tmp_path):
        from stellar_core_tpu.history.archive import CommandArchive
        from stellar_core_tpu.process.process_manager import ProcessManager

        arch = CommandArchive("bad", get_cmd="false {0} {1}",
                              process_manager=ProcessManager(),
                              tmp_dir=str(tmp_path))
        assert arch.get_file("anything") is None
        assert arch.get_root_has() is None
