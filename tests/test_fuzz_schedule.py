"""Fault-schedule fuzzer tests: schedule IR determinism (in-process
and across PYTHONHASHSEED subprocesses), validation/rejection
hardening, ddmin minimization, repro artifacts, and — in the slow
tier — the real known-bad fork end to end plus loadgen traffic as a
first-class scenario phase."""
import copy
import json
import os
import subprocess
import sys

import pytest

from stellar_core_tpu.simulation.fuzz import schedule as S
from stellar_core_tpu.simulation.fuzz.executor import (
    novelty_signature, run_schedule)
from stellar_core_tpu.simulation.fuzz.minimize import (
    minimize_schedule, verify_repro, write_repro)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# schedule IR: generation determinism + canonical bytes
# ---------------------------------------------------------------------------

def test_generate_schedule_deterministic_in_process():
    for seed in (0, 1, 7, 99):
        a = S.generate_schedule(seed, "default")
        b = S.generate_schedule(seed, "default")
        assert S.canonical_bytes(a) == S.canonical_bytes(b)
        assert S.schedule_id(a) == S.schedule_id(b)


def test_generate_schedule_seeds_differ():
    ids = {S.schedule_id(S.generate_schedule(s, "default"))
           for s in range(12)}
    assert len(ids) == 12, "seeds must explore distinct schedules"


def test_generated_schedules_validate():
    for profile in S.PROFILES:
        for seed in range(15):
            sched = S.generate_schedule(seed, profile)
            S.validate_schedule(sched)  # must not raise
            n = S.topology_size(sched["topology"])
            ts = [e["t"] for e in sched["events"]]
            assert ts == sorted(ts), "events must be time-ordered"
            for e in sched["events"]:
                for k in ("victim", "attacker"):
                    if k in e:
                        assert 0 <= e[k] < n
                for g in e.get("groups", []):
                    assert all(0 <= v < n for v in g)


def test_schedule_bytes_stable_across_hashseed_subprocesses():
    """The generator must be a pure function of the seed: canonical
    schedule bytes identical under PYTHONHASHSEED=0 and 4242 (set-
    iteration or dict-order leaks would diverge here)."""
    prog = (
        "from stellar_core_tpu.simulation.fuzz import schedule as S\n"
        "from stellar_core_tpu.crypto import sha256\n"
        "h = sha256(b''.join(S.canonical_bytes(\n"
        "    S.generate_schedule(s, p))\n"
        "    for p in sorted(S.PROFILES) for s in range(8)))\n"
        "print(h.hex())\n")
    digests = []
    for hashseed in ("0", "4242"):
        env = dict(os.environ, PYTHONHASHSEED=hashseed,
                   JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", prog], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=180)
        assert out.returncode == 0, out.stderr
        digests.append(out.stdout.strip())
    assert digests[0] == digests[1]


# ---------------------------------------------------------------------------
# validation + repro-file hardening
# ---------------------------------------------------------------------------

def test_validate_rejects_bad_schedules():
    good = S.known_bad_schedule()
    cases = [
        ("unknown kind", lambda s: s["events"].append(
            {"t": 1.0, "kind": "meteor-strike"})),
        ("victim out of range", lambda s: s["events"].append(
            {"t": 1.0, "kind": "crash", "victim": 99})),
        ("negative time", lambda s: s["events"].append(
            {"t": -1.0, "kind": "heal"})),
        ("event past duration", lambda s: s["events"].append(
            {"t": 1e9, "kind": "heal"})),
        ("bad schema", lambda s: s.update(fuzz_schema=99)),
        ("bad topology", lambda s: s.update(
            topology={"kind": "torus", "n": 4})),
        ("overlapping traffic", lambda s: s.update(traffic=[
            {"t": 1.0, "duration": 5.0, "mode": "pay", "rate": 2.0},
            {"t": 3.0, "duration": 5.0, "mode": "pay", "rate": 2.0}])),
        ("bad traffic mode", lambda s: s.update(traffic=[
            {"t": 1.0, "duration": 2.0, "mode": "ddos", "rate": 2.0}])),
    ]
    for what, mutate in cases:
        sched = copy.deepcopy(good)
        mutate(sched)
        with pytest.raises(S.ScheduleError):
            S.validate_schedule(sched)
        assert what  # document intent


def test_load_schedule_rejects_corrupted_file(tmp_path):
    p = tmp_path / "bad.json"
    p.write_bytes(b'{"fuzz_schema": 1, "seed": truncated')
    with pytest.raises(S.ScheduleError, match="corrupted"):
        S.load_schedule(str(p))
    p.write_bytes(b"\xff\xfe not utf8 \x80")
    with pytest.raises(S.ScheduleError, match="corrupted"):
        S.load_schedule(str(p))


def test_load_schedule_rejects_oversized_file(tmp_path):
    p = tmp_path / "big.json"
    p.write_bytes(b'{"pad": "' + b"A" * S.MAX_SCHEDULE_BYTES + b'"}')
    with pytest.raises(S.ScheduleError, match="oversized"):
        S.load_schedule(str(p))


def test_load_schedule_rejects_invalid_schedule(tmp_path):
    p = tmp_path / "invalid.json"
    p.write_text(json.dumps({"fuzz_schema": 1, "seed": 1}))
    with pytest.raises(S.ScheduleError):
        S.load_schedule(str(p))


def test_save_load_round_trip(tmp_path):
    sched = S.generate_schedule(3, "smoke")
    path = S.save_schedule(sched, str(tmp_path / "s.json"))
    loaded = S.load_schedule(path)
    assert S.canonical_bytes(loaded) == S.canonical_bytes(sched)


# ---------------------------------------------------------------------------
# ddmin + repro artifacts against a synthetic oracle (fast tier)
# ---------------------------------------------------------------------------

def _fake_run(sched):
    """Synthetic oracle: 'forks' iff the equivocator AND the partition
    both survive in the schedule — the minimal failing core the ddmin
    must find under the chaff."""
    kinds = [e["kind"] for e in sched.get("events", [])]
    bad = "equivocate" in kinds and "partition" in kinds
    fp = "fp-" + S.schedule_id(sched) if bad else None
    return {"ok": not bad, "schedule_id": S.schedule_id(sched),
            "failure_class": "fork" if bad else None,
            "failure_fingerprint": fp,
            "novelty": "n-" + S.schedule_id(sched),
            "error": "synthetic fork" if bad else None}


def test_ddmin_minimizes_known_bad_to_essentials():
    kb = S.known_bad_schedule()  # 3 essential events + 4 chaff
    assert len(kb["events"]) == 7
    mini, stats = minimize_schedule(kb, run=_fake_run, max_runs=64)
    kinds = sorted(e["kind"] for e in mini["events"])
    assert kinds == ["equivocate", "partition"], \
        f"ddmin left non-essential events: {mini['events']}"
    assert stats["reproduces"] is True
    assert stats["atoms_before"] == 7
    assert stats["atoms_after"] == 2
    assert stats["oracle_runs"] <= 64
    # parameter shrinking kicked in: duration collapsed to the tail
    assert mini["duration"] < kb["duration"]


def test_minimize_rejects_passing_schedule():
    sched = S.known_bad_schedule(noise=False)
    sched["events"] = [{"t": 1.0, "kind": "heal"}]
    with pytest.raises(ValueError, match="passes its oracles"):
        minimize_schedule(sched, run=_fake_run, max_runs=8)


def test_repro_round_trip_and_tamper_detection(tmp_path):
    kb = S.known_bad_schedule(noise=False)
    res = _fake_run(kb)
    path = write_repro(kb, res, out_dir=str(tmp_path))
    doc = S.load_schedule(path)
    verdict = verify_repro(doc, run=_fake_run)
    assert verdict["reproduced"] is True
    # a tampered expectation must fail closed
    doc["expect"]["failure_fingerprint"] = "0" * 64
    assert verify_repro(doc, run=_fake_run)["reproduced"] is False
    # unknown repro schema is rejected, not guessed at
    doc["fuzz_repro_schema"] = 99
    with pytest.raises(S.ScheduleError):
        verify_repro(doc, run=_fake_run)


def test_novelty_signature_quantizes():
    sched = S.known_bad_schedule()
    a = {"ok": True, "failure_class": None,
         "report": {"ledgers_closed": 12, "time_to_heal_s": 3.1,
                    "counters": {"drops": 4}}}
    b = {"ok": True, "failure_class": None,
         "report": {"ledgers_closed": 13, "time_to_heal_s": 3.4,
                    "counters": {"drops": 9}}}
    c = {"ok": False, "failure_class": "fork",
         "report": {"ledgers_closed": 12, "time_to_heal_s": 3.1,
                    "counters": {"drops": 4}}}
    assert novelty_signature(sched, a) == novelty_signature(sched, b), \
        "near-identical behavior must collide"
    assert novelty_signature(sched, a) != novelty_signature(sched, c), \
        "a failure is always novel against a pass"


# ---------------------------------------------------------------------------
# real-executor tier (slow): the known-bad fork, replay identity,
# and traffic as a first-class scenario phase
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_known_bad_forks_and_minimizes_for_real(tmp_path):
    kb = S.known_bad_schedule()
    first = run_schedule(kb)
    assert first["ok"] is False
    assert first["failure_class"] == "fork"
    # same-seed rerun: identical failure fingerprint (replay identity)
    again = run_schedule(kb)
    assert again["failure_fingerprint"] == first["failure_fingerprint"]
    mini, stats = minimize_schedule(
        kb, target_class="fork", max_runs=32)
    assert stats["reproduces"] is True
    kinds = sorted(e["kind"] for e in mini["events"])
    assert kinds == ["equivocate", "partition", "silence"], kinds
    path = write_repro(mini, dict(stats["final_result"], ok=False),
                       out_dir=str(tmp_path),
                       minimized_from=S.schedule_id(kb))
    verdict = verify_repro(S.load_schedule(path))
    assert verdict["reproduced"] is True


@pytest.mark.slow
def test_run_fingerprint_stable_across_hashseed_subprocesses():
    """The executor's failure fingerprint is a pure function of the
    schedule: two fresh processes with different PYTHONHASHSEED values
    must reproduce it byte-for-byte."""
    prog = (
        "import json\n"
        "from stellar_core_tpu.simulation.fuzz import schedule as S\n"
        "from stellar_core_tpu.simulation.fuzz.executor "
        "import run_schedule\n"
        "kb = S.known_bad_schedule(noise=False)\n"
        "kb['duration'] = 6.0\n"
        "r = run_schedule(kb)\n"
        "print(json.dumps({'class': r['failure_class'],\n"
        "                  'fp': r['failure_fingerprint']}))\n")
    rows = []
    for hashseed in ("0", "4242"):
        env = dict(os.environ, PYTHONHASHSEED=hashseed,
                   JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", prog], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        rows.append(json.loads(out.stdout.strip().splitlines()[-1]))
    assert rows[0]["class"] == "fork"
    assert rows[0] == rows[1]


@pytest.mark.slow
def test_traffic_phase_is_first_class_scenario_event():
    """Loadgen rate mode composes with chaos inside run_scenario: the
    schedule's traffic phase runs THROUGH a lag fault, and the traffic
    oracle's accounting (every submit has an admission status; queue
    counters surfaced) holds."""
    sched = {
        "fuzz_schema": S.SCHEMA_VERSION,
        "seed": 5,
        "profile": "test",
        "topology": {"kind": "core", "n": 4},
        "duration": 12.0,
        "converge_timeout": 60.0,
        "events": [
            {"t": 2.0, "kind": "lag", "victim": 2, "latency": 0.3},
            {"t": 8.0, "kind": "unlag", "victim": 2},
        ],
        "traffic": [
            {"t": 1.0, "duration": 8.0, "mode": "pay", "rate": 4.0},
        ],
    }
    S.validate_schedule(sched)
    res = run_schedule(sched)
    assert res["ok"], res.get("error")
    traffic = res["report"]["traffic"]
    assert len(traffic["phases"]) == 1
    phase = traffic["phases"][0]
    assert phase["submitted"] > 0
    assert phase["submitted"] == sum(phase["status_counts"].values())
    assert traffic["submitted_total"] == phase["submitted"]
    # tx-queue overload counters are surfaced (aging/surge evidence)
    assert set(traffic["queue"]) == {"pending", "banned"}
    # same-seed rerun reproduces the run fingerprint, traffic included
    res2 = run_schedule(sched)
    assert res2["fingerprint"] == res["fingerprint"]


# -- the real finding's fix: item-fetch retry -------------------------------


def test_fetch_retry_survives_dropped_request():
    """Regression for the fuzzer's first real catch (smoke seed 9002):
    flaky links + traffic wedged a whole tiered network at one slot
    because a dropped GET_TX_SET request (or reply) stalled its
    ItemTracker forever — fetch_items asked ONE peer and only advanced
    on an explicit DONT_HAVE.  The fix is the reference's
    Tracker::tryNextPeer retry timer: re-ask on a virtual-clock
    cadence, wrap around when every peer has been asked, give up only
    after MAX_FETCH_RETRIES (later envelopes restart the fetch)."""
    from types import SimpleNamespace

    from stellar_core_tpu.overlay.manager import OverlayManager
    from stellar_core_tpu.utils.clock import VirtualClock
    from stellar_core_tpu.utils.metrics import MetricsRegistry

    clock = VirtualClock()
    app = SimpleNamespace(clock=clock, metrics=MetricsRegistry(),
                          floodtracer=None, database=None,
                          config=SimpleNamespace())
    om = OverlayManager(app)
    sent = []
    peer = SimpleNamespace(peer_id=b"\x01" * 32,
                           send_message=lambda m: sent.append(m))
    om.authenticated[peer.peer_id] = peer
    h = b"\x77" * 32

    om.fetch_items([h])
    first_ask = len(sent)
    assert first_ask == 2  # GET_TX_SET + GET_SCP_QUORUMSET (both lost)

    # the wire dropped everything: the retry timer must re-ask
    clock.crank_until(lambda: False, timeout=3 * om.FETCH_RETRY_S)
    retries = app.metrics.counter("overlay.fetch.retry").count
    assert retries >= 2
    assert len(sent) > first_ask, "retry never re-asked the peer"
    assert h in om.trackers

    # the item finally arrives: the tracker dies and the timer goes
    # quiet (no further asks, counter frozen)
    om.trackers.pop(h)
    quiet0 = len(sent)
    clock.crank_until(lambda: False, timeout=3 * om.FETCH_RETRY_S)
    assert len(sent) == quiet0
    assert app.metrics.counter("overlay.fetch.retry").count == retries

    # an unanswerable item gives up after the cap instead of pinning a
    # timer forever
    om.fetch_items([h])
    clock.crank_until(
        lambda: h not in om.trackers,
        timeout=(om.MAX_FETCH_RETRIES + 2) * om.FETCH_RETRY_S)
    assert h not in om.trackers
