"""libsodium edge-case vectors: small-order / non-canonical / malleable
inputs must get the same verdict from the executable spec, the CPU backend
(OpenSSL + blacklist prefilter), the XLA kernel, and the Pallas kernel
(interpret mode) — pinning the whole framework to libsodium
crypto_sign_verify_detached semantics (ref src/crypto/SecretKey.cpp:428-459;
VERDICT r2 weak #4)."""
import numpy as np
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.crypto import ed25519 as ed
from stellar_core_tpu.crypto import ed25519_ref as ref


def _valid_triple(i=0):
    sk = SecretKey(sha256(b"edge%d" % i))
    msg = sha256(b"edge-msg%d" % i)
    return sk.public_key().raw, sk.sign(msg), msg


def _vectors():
    """(pubkey, sig, msg, label) edge inputs.  Expected verdicts come from
    the spec; the point of the test is four-way agreement."""
    out = []
    pk, sig, msg = _valid_triple()
    out.append((pk, sig, msg, "valid"))
    out.append((pk, sig[:-1] + bytes([sig[-1] ^ 1]), msg, "bad-sig"))

    # small-order A (all 10 blacklist encodings), structurally valid sig
    for j, enc in enumerate(ref.SMALL_ORDER_ENCODINGS):
        out.append((enc, sig, msg, f"small-order-A-{j}"))
    # small-order R
    for j, enc in enumerate(ref.SMALL_ORDER_ENCODINGS):
        out.append((pk, enc + sig[32:], msg, f"small-order-R-{j}"))

    # non-canonical A: y >= p (y = p + 1 -> encodes like (0,1)+p)
    nc = int.to_bytes(ref.P + 1, 32, "little")
    out.append((nc, sig, msg, "non-canonical-A"))
    out.append((pk, nc + sig[32:], msg, "non-canonical-R"))

    # s >= L (malleability): s' = s + L
    s = int.from_bytes(sig[32:], "little")
    s_mall = int.to_bytes(s + ref.L, 32, "little")
    out.append((pk, sig[:32] + s_mall, msg, "malleable-s"))

    # off-curve A (y with no valid x)
    y = 2
    while ref._recover_x(y, 0) is not None:
        y += 1
    out.append((int.to_bytes(y, 32, "little"), sig, msg, "off-curve-A"))
    return out


VECTORS = _vectors()


def test_spec_verdicts():
    """Sanity: the spec rejects every malformed vector and accepts the
    valid one."""
    for pk, sig, msg, label in VECTORS:
        got = ref.verify(pk, sig, msg)
        assert got == (label == "valid"), label


def test_cpu_backend_matches_spec():
    for pk, sig, msg, label in VECTORS:
        assert ed.raw_verify(pk, sig, msg) == ref.verify(pk, sig, msg), label


def test_xla_kernel_matches_spec():
    from stellar_core_tpu.ops.ed25519_kernel import verify_batch

    n = len(VECTORS)
    pk = np.frombuffer(b"".join(v[0] for v in VECTORS),
                       np.uint8).reshape(n, 32)
    sg = np.frombuffer(b"".join(v[1] for v in VECTORS),
                       np.uint8).reshape(n, 64)
    mg = np.frombuffer(b"".join(v[2] for v in VECTORS),
                       np.uint8).reshape(n, 32)
    got = np.asarray(verify_batch(pk, sg, mg))
    for (pkb, sig, msg, label), g in zip(VECTORS, got):
        assert bool(g) == ref.verify(pkb, sig, msg), label


@pytest.mark.slow
def test_pallas_kernel_matches_spec_interpret():
    from stellar_core_tpu.ops.ed25519_pallas import verify_batch

    n = len(VECTORS)
    pk = np.frombuffer(b"".join(v[0] for v in VECTORS),
                       np.uint8).reshape(n, 32)
    sg = np.frombuffer(b"".join(v[1] for v in VECTORS),
                       np.uint8).reshape(n, 64)
    mg = np.frombuffer(b"".join(v[2] for v in VECTORS),
                       np.uint8).reshape(n, 32)
    got = np.asarray(verify_batch(pk, sg, mg, interpret=True))
    for (pkb, sig, msg, label), g in zip(VECTORS, got):
        assert bool(g) == ref.verify(pkb, sig, msg), label


def test_torsion_subgroup_structure():
    """The generated blacklist covers the full 8-torsion subgroup."""
    pts = ref._torsion_points()
    assert len(pts) == 8
    for pt in pts:
        assert ref._is_identity(ref.scalar_mult(8, ref.to_extended(pt)))
    # contains identity and (0,-1)
    assert (0, 1) in pts and (0, ref.P - 1) in pts
    # 10 encodings: 8 canonical + 2 extra -0 sign variants
    assert len(ref.SMALL_ORDER_ENCODINGS) == 10
