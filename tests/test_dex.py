"""DEX tests: exchangeV10 math + order-book crossing + path payments
(ref models: src/transactions/test/{ExchangeTests,OfferTests,
PathPaymentTests}.cpp)."""
import pytest

from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.transactions.offer_exchange import (
    RoundingType, exchange_v10, adjust_offer_amount,
)
from stellar_core_tpu.xdr import types as T

from tests.txtest import BASE_FEE, BASE_RESERVE, TestLedger

INT64_MAX = U.INT64_MAX


@pytest.fixture()
def ledger():
    return TestLedger()


@pytest.fixture()
def root(ledger):
    return ledger.root()


def price(n, d):
    return T.Price.make(n=n, d=d)


# -- exchangeV10 math --------------------------------------------------------


def test_exchange_v10_exact_price():
    # book sells 100 wheat at 1 sheep/wheat; taker sends 30 sheep
    res = exchange_v10(price(1, 1), 100, INT64_MAX, 30, INT64_MAX)
    assert res.num_wheat_received == 30
    assert res.num_sheep_send == 30
    assert res.wheat_stays


def test_exchange_v10_full_take():
    res = exchange_v10(price(1, 1), 100, INT64_MAX, 500, INT64_MAX)
    assert res.num_wheat_received == 100
    assert res.num_sheep_send == 100
    assert not res.wheat_stays


def test_exchange_v10_rounding_favors_stayer():
    # price 3/2 sheep per wheat, taker sends 5 sheep for at most
    # floor(5*2/3)=3 wheat; wheat stays -> wheat seller favored
    res = exchange_v10(price(3, 2), 1000, INT64_MAX, 5, INT64_MAX)
    assert res.wheat_stays
    # wheat seller gets at least the fair price
    assert res.num_sheep_send * 2 >= res.num_wheat_received * 3


def test_exchange_v10_price_error_bound():
    # tiny exchange at an extreme price: >1% error must cancel the trade
    res = exchange_v10(price(1000001, 1000000), 1, INT64_MAX, 1, INT64_MAX,
                       RoundingType.NORMAL)
    # 1-for-1 at ~1.000001 has relative error ~1e-6: fine
    assert res.num_wheat_received in (0, 1)
    res2 = exchange_v10(price(3, 1), 1, INT64_MAX, 1, INT64_MAX)
    # taker would need to send 3 sheep for 1 wheat but only has 1:
    # 0-or-cancelled
    assert res2.num_wheat_received == 0


def test_adjust_offer_caps_to_capacity():
    assert adjust_offer_amount(price(1, 1), 100, 40) == 40
    assert adjust_offer_amount(price(2, 1), 100, 100) == 50
    assert adjust_offer_amount(price(1, 1), 0, 100) == 0


# -- order-book crossing through ops -----------------------------------------


def op_sell(acct, selling, buying, amount, p, offer_id=0):
    return acct.op(T.OperationType.MANAGE_SELL_OFFER,
                   T.ManageSellOfferOp.make(
                       selling=selling, buying=buying, amount=amount,
                       price=p, offerID=offer_id))


def op_buy(acct, selling, buying, buy_amount, p, offer_id=0):
    return acct.op(T.OperationType.MANAGE_BUY_OFFER,
                   T.ManageBuyOfferOp.make(
                       selling=selling, buying=buying,
                       buyAmount=buy_amount, price=p, offerID=offer_id))


def _mk_market(root):
    issuer = root.create("dex-issuer", 1000 * BASE_RESERVE)
    alice = root.create("dex-alice", 1000 * BASE_RESERVE)
    bob = root.create("dex-bob", 1000 * BASE_RESERVE)
    usd = U.make_asset(b"USD", issuer.account_id)
    for who in (alice, bob):
        who.apply(who.tx([who.op_change_trust(usd)]))
    issuer.apply(issuer.tx([issuer.op_payment(
        alice.account_id, 10_000, asset=usd)]))
    issuer.apply(issuer.tx([issuer.op_payment(
        bob.account_id, 10_000, asset=usd)]))
    return issuer, alice, bob, usd


def _usd_balance(root, who, usd):
    with LedgerTxn(root.ledger.root_txn) as ltx:
        tl = ltx.load_trustline(who.account_id, usd)
        ltx.rollback()
    return tl.data.value.balance


def test_offer_create_and_rest(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    # alice sells 1000 USD for XLM at 2 XLM/USD
    ok, result = alice.apply(alice.tx([op_sell(
        alice, usd, xlm, 1000, price(2, 1))]))
    success = result.result.value[0].value.value.value
    assert success.offer.type == T.ManageOfferEffect.MANAGE_OFFER_CREATED
    offer = success.offer.value
    assert offer.amount == 1000
    # resting offer is in the book
    with LedgerTxn(root.ledger.root_txn) as ltx:
        best = ltx.best_offer(T.Asset.encode(usd), T.Asset.encode(xlm))
        ltx.rollback()
    assert best is not None and best.data.value.offerID == offer.offerID


def test_offer_crossing_full_fill(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    alice_usd0 = _usd_balance(root, alice, usd)
    bob_usd0 = _usd_balance(root, bob, usd)
    alice_xlm0 = alice.balance()

    # alice sells 1000 USD at 2 XLM per USD
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 1000, price(2, 1))]))
    # bob sells 2000 XLM for USD at 0.5 USD/XLM (the exact reciprocal)
    ok, result = bob.apply(bob.tx([op_sell(
        bob, xlm, usd, 2000, price(1, 2))]))
    success = result.result.value[0].value.value.value
    assert len(success.offersClaimed) == 1
    atom = success.offersClaimed[0].value
    assert atom.amountSold == 1000      # USD sold by alice's offer
    assert atom.amountBought == 2000    # XLM paid by bob
    assert success.offer.type == T.ManageOfferEffect.MANAGE_OFFER_DELETED
    # balances moved both ways
    assert _usd_balance(root, alice, usd) == alice_usd0 - 1000
    assert _usd_balance(root, bob, usd) == bob_usd0 + 1000
    assert alice.balance() == alice_xlm0 + 2000 - BASE_FEE


def test_offer_partial_fill_rests(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 100, price(1, 1))]))
    # bob wants much more USD than alice offers
    ok, result = bob.apply(bob.tx([op_sell(
        bob, xlm, usd, 500, price(1, 1))]))
    success = result.result.value[0].value.value.value
    assert success.offer.type == T.ManageOfferEffect.MANAGE_OFFER_CREATED
    assert success.offer.value.amount == 400  # 500 - 100 crossed
    assert _usd_balance(root, bob, usd) == 10_000 + 100


def test_no_cross_when_prices_dont_meet(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    # alice asks 2 XLM per USD; bob bids only 1 XLM per USD
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 100, price(2, 1))]))
    ok, result = bob.apply(bob.tx([op_sell(
        bob, xlm, usd, 100, price(1, 1))]))
    success = result.result.value[0].value.value.value
    assert len(success.offersClaimed) == 0
    assert success.offer.type == T.ManageOfferEffect.MANAGE_OFFER_CREATED


def test_cannot_cross_own_offer(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 100, price(1, 1))]))
    ok, result = alice.apply(alice.tx([op_sell(
        alice, xlm, usd, 100, price(1, 1))]), expect_success=False)
    code = result.result.value[0].value.value.type
    assert code == T.ManageSellOfferResultCode.MANAGE_SELL_OFFER_CROSS_SELF


def test_delete_offer(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    ok, result = alice.apply(alice.tx([op_sell(
        alice, usd, xlm, 100, price(1, 1))]))
    oid = result.result.value[0].value.value.value.offer.value.offerID
    ok, result = alice.apply(alice.tx([op_sell(
        alice, usd, xlm, 0, price(1, 1), offer_id=oid)]))
    success = result.result.value[0].value.value.value
    assert success.offer.type == T.ManageOfferEffect.MANAGE_OFFER_DELETED
    with LedgerTxn(root.ledger.root_txn) as ltx:
        assert ltx.best_offer(T.Asset.encode(usd),
                              T.Asset.encode(xlm)) is None
        ltx.rollback()


def test_manage_buy_offer_crosses(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 1000, price(2, 1))]))
    # bob buys exactly 300 USD paying XLM at up to 2 XLM/USD
    ok, result = bob.apply(bob.tx([op_buy(
        bob, xlm, usd, 300, price(2, 1))]))
    success = result.result.value[0].value.value.value
    assert len(success.offersClaimed) == 1
    assert success.offersClaimed[0].value.amountSold == 300
    assert _usd_balance(root, bob, usd) == 10_000 + 300
    # CAP-0006: nothing rests after the buy amount is filled
    assert success.offer.type == T.ManageOfferEffect.MANAGE_OFFER_DELETED


def test_passive_offer_no_cross_at_equal_price(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 100, price(1, 1))]))
    env = bob.tx([bob.op(T.OperationType.CREATE_PASSIVE_SELL_OFFER,
                         T.CreatePassiveSellOfferOp.make(
                             selling=xlm, buying=usd, amount=100,
                             price=price(1, 1)))])
    ok, result = bob.apply(env)
    success = result.result.value[0].value.value.value
    assert len(success.offersClaimed) == 0  # equal price + passive: no cross
    assert success.offer.type == T.ManageOfferEffect.MANAGE_OFFER_CREATED


def test_path_payment_strict_receive(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    # book: alice sells USD for XLM at 1:1
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 5000, price(1, 1))]))
    # bob sends XLM, carol receives exactly 700 USD
    carol = root.create("dex-carol", 100 * BASE_RESERVE)
    carol.apply(carol.tx([carol.op_change_trust(usd)]))
    env = bob.tx([bob.op(T.OperationType.PATH_PAYMENT_STRICT_RECEIVE,
                         T.PathPaymentStrictReceiveOp.make(
                             sendAsset=xlm, sendMax=1000,
                             destination=T.muxed_account(carol.account_id),
                             destAsset=usd, destAmount=700, path=[]))])
    ok, result = bob.apply(env)
    assert _usd_balance(root, carol, usd) == 700


def test_path_payment_strict_send(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    alice.apply(alice.tx([op_sell(alice, usd, xlm, 5000, price(1, 1))]))
    carol = root.create("dex-carol2", 100 * BASE_RESERVE)
    carol.apply(carol.tx([carol.op_change_trust(usd)]))
    env = bob.tx([bob.op(T.OperationType.PATH_PAYMENT_STRICT_SEND,
                         T.PathPaymentStrictSendOp.make(
                             sendAsset=xlm, sendAmount=800,
                             destination=T.muxed_account(carol.account_id),
                             destAsset=usd, destMin=700, path=[]))])
    ok, result = bob.apply(env)
    got = _usd_balance(root, carol, usd)
    assert got >= 700  # at 1:1 bob's 800 XLM buys ~800 USD


def test_path_payment_too_few_offers(root):
    issuer, alice, bob, usd = _mk_market(root)
    xlm = U.asset_native()
    carol = root.create("dex-carol3", 100 * BASE_RESERVE)
    carol.apply(carol.tx([carol.op_change_trust(usd)]))
    env = bob.tx([bob.op(T.OperationType.PATH_PAYMENT_STRICT_RECEIVE,
                         T.PathPaymentStrictReceiveOp.make(
                             sendAsset=xlm, sendMax=1000,
                             destination=T.muxed_account(carol.account_id),
                             destAsset=usd, destAmount=700, path=[]))])
    ok, result = bob.apply(env, expect_success=False)
    code = result.result.value[0].value.value.type
    assert code == T.PathPaymentStrictReceiveResultCode.\
        PATH_PAYMENT_STRICT_RECEIVE_TOO_FEW_OFFERS
