"""Native C++ bucket merge vs the Python oracle
(native/bucket_merge.cpp; differential + randomized)."""
import random

import pytest

from stellar_core_tpu.bucket.bucket_list import (
    BET, Bucket, BucketList, _native_merge,
)
from stellar_core_tpu.native import get_lib
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.crypto import sha256
from stellar_core_tpu.xdr import types as T


def _entry(i: int, etype):
    acc_entry = U.make_account_entry(sha256(b"nm-%d" % i), 100 + i)
    from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes

    kb = key_bytes(entry_to_key(acc_entry))
    if etype == BET.DEADENTRY:
        e = T.BucketEntry.make(BET.DEADENTRY, T.LedgerKey.decode(kb))
    else:
        e = T.BucketEntry.make(etype, acc_entry)
    return kb, e


def _bucket(pairs):
    return Bucket(sorted(pairs, key=lambda p: p[0]))


def test_native_lib_builds():
    assert get_lib() is not None, "g++ build of the native tier failed"


@pytest.mark.parametrize("seed", range(6))
def test_native_matches_python_oracle(seed):
    rng = random.Random(seed)
    ids = list(range(400))
    new_pairs = [_entry(i, rng.choice([BET.LIVEENTRY, BET.DEADENTRY,
                                       BET.INITENTRY]))
                 for i in rng.sample(ids, 250)]
    old_pairs = [_entry(i, rng.choice([BET.LIVEENTRY, BET.DEADENTRY,
                                       BET.INITENTRY]))
                 for i in rng.sample(ids, 250)]
    newer, older = _bucket(new_pairs), _bucket(old_pairs)
    native = _native_merge(newer, older)
    assert native is not None
    python = Bucket._merge_py(newer, older)
    assert len(native) == len(python)
    for (ka, ea), (kb, eb) in zip(native, python):
        assert ka == kb
        assert ea.type == eb.type
        assert T.BucketEntry.encode(ea) == T.BucketEntry.encode(eb)


def test_merged_bucket_hash_identical():
    new_pairs = [_entry(i, BET.INITENTRY) for i in range(0, 300, 2)]
    old_pairs = [_entry(i, BET.LIVEENTRY) for i in range(0, 300, 3)]
    newer, older = _bucket(new_pairs), _bucket(old_pairs)
    via_native = Bucket(_native_merge(newer, older))
    via_python = Bucket(Bucket._merge_py(newer, older))
    assert via_native.hash() == via_python.hash()


@pytest.mark.parametrize("seed", range(4))
def test_native_stream_merge_matches_python_oracle(seed, tmp_path):
    """The GIL-free file-to-file kernel (bucket_merge_stream) must be
    byte-identical to the Python streaming merge: same output stream,
    same native-computed sha256, same sidecar-reopened state — for
    disk x disk AND memory x disk input tiers."""
    import random

    from stellar_core_tpu.bucket.bucket_list import _merge_entry
    from stellar_core_tpu.bucket.disk_bucket import (
        DiskBucket, merge_disk_native, merge_stream,
    )

    rng = random.Random(seed)
    ids = list(range(500))
    new_pairs = sorted(_entry(i, rng.choice([BET.LIVEENTRY, BET.DEADENTRY,
                                             BET.INITENTRY]))
                       for i in rng.sample(ids, 260))
    old_pairs = sorted(_entry(i, rng.choice([BET.LIVEENTRY, BET.DEADENTRY,
                                             BET.INITENTRY]))
                       for i in rng.sample(ids, 260))
    src = tmp_path / "src"
    out = tmp_path / "out"
    dn = DiskBucket.from_entries(str(src), new_pairs)
    do = DiskBucket.from_entries(str(src), old_pairs)
    native = merge_disk_native(str(out), dn, do)
    assert native is not None, "native stream merge unavailable"
    oracle = merge_stream(str(out), iter(new_pairs), iter(old_pairs),
                          _merge_entry)
    assert native.hash() == oracle.hash()
    assert len(native) == len(oracle)
    with open(native.path, "rb") as f1, open(oracle.path, "rb") as f2:
        assert f1.read() == f2.read()
    # mixed tier: in-memory newer against the disk older
    mixed = merge_disk_native(str(out), _bucket(new_pairs), do)
    assert mixed is not None and mixed.hash() == oracle.hash()
    # sidecar-indexed reopen reproduces count + hash + lookups
    reopened = DiskBucket.open(native.path)
    assert reopened.hash() == native.hash()
    assert len(reopened) == len(native)
    for kb, _ in new_pairs[:25]:
        a, b = reopened.get(kb), oracle.get(kb)
        assert (a is None) == (b is None)
        if a is not None:
            assert T.BucketEntry.encode(a) == T.BucketEntry.encode(b)
