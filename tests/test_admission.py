"""Flagship admission pipeline: single-device and sharded-mesh coverage.

The driver validates ``__graft_entry__.dryrun_multichip`` externally; this
suite exercises the same path in-process (conftest forces an 8-device virtual
CPU mesh) and differential-checks ``admission_step`` outputs against the host
oracles: the CPU ed25519 backend (ref src/crypto/SecretKey.cpp:428 seam) and
the recursive quorum evaluator (ref src/scp/LocalNode.h:58-78 seam).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stellar_core_tpu.models.admission import (
    AdmissionBatch,
    admission_step,
    dryrun_sharded,
    example_batch,
)
from stellar_core_tpu.ops import quorum as Q


def test_dryrun_sharded_8_devices():
    assert len(jax.devices()) >= 8
    dryrun_sharded(8)


def test_admission_step_matches_host_oracles():
    from stellar_core_tpu.crypto import ed25519 as ed

    (batch,) = example_batch(n_sigs=8, n_nodes=4)
    sig_ok, accept, ratify = jax.jit(admission_step)(batch)

    def cpu_verify(b):
        pk, sg, mg = (np.asarray(x) for x in (b.pubkeys, b.sigs, b.msgs))
        return np.asarray(
            [
                ed.raw_verify(pk[i].tobytes(), sg[i].tobytes(), mg[i].tobytes())
                for i in range(pk.shape[0])
            ]
        )

    # differential vs the CPU backend (ref src/crypto/SecretKey.cpp:428 seam)
    np.testing.assert_array_equal(np.asarray(sig_ok), cpu_verify(batch))
    assert np.asarray(sig_ok).all()

    # flip one byte: kernel and CPU backend must agree on the rejection too
    bad_sigs = np.asarray(batch.sigs).copy()
    bad_sigs[0, 0] ^= 0xFF
    bad = batch._replace(sigs=jnp.asarray(bad_sigs))
    sig_ok2, _, _ = jax.jit(admission_step)(bad)
    np.testing.assert_array_equal(np.asarray(sig_ok2), cpu_verify(bad))
    assert not bool(sig_ok2[0]) and np.asarray(sig_ok2[1:]).all()

    # quorum tallies vs plain-python recursive reference over the 3-of-4 net
    n_nodes = 4
    qsets = [(3, list(range(n_nodes)), []) for _ in range(n_nodes)]

    def ref_slice(qset, s):
        thr, vals, _ = qset
        return sum(1 for v in vals if v in s) >= thr

    def ref_max_quorum(members):
        cur = set(members)
        while True:
            nxt = {n for n in cur if ref_slice(qsets[n], cur)}
            if nxt == cur:
                return nxt
            cur = nxt

    voted = np.asarray(batch.voted)
    accepted = np.asarray(batch.accepted)
    for c in range(voted.shape[0]):
        va = {i for i in range(n_nodes) if voted[c, i] or accepted[c, i]}
        q = ref_max_quorum(va)
        want_ratify = bool(q) and ref_slice(qsets[0], q)
        acc_set = {i for i in range(n_nodes) if accepted[c, i]}
        # v-blocking for 3-of-4: any 2 nodes
        want_accept = len(acc_set) >= 2 or want_ratify
        assert bool(ratify[c]) == want_ratify, c
        assert bool(accept[c]) == want_accept, c


def test_sharded_matches_unsharded():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    (batch,) = example_batch(n_sigs=16, n_nodes=4)
    want = jax.jit(admission_step)(batch)

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    dp = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    sharded = AdmissionBatch(
        jax.device_put(batch.pubkeys, dp),
        jax.device_put(batch.sigs, dp),
        jax.device_put(batch.msgs, dp),
        Q.QSetTensor(*(jax.device_put(t, rep) for t in batch.qset)),
        Q.QSetTensor(*(jax.device_put(t, rep) for t in batch.local_qset)),
        jax.device_put(batch.voted, rep),
        jax.device_put(batch.accepted, rep),
    )
    got = jax.jit(admission_step, out_shardings=(dp, rep, rep))(sharded)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
