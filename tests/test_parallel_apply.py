"""Parallel transaction apply (ISSUE 5 tentpole): bit-identity of the
footprint-planned concurrent executor against sequential apply, the
planner's clustering rules, and the speculation guard's escape-abort
fallback.

The property at stake is consensus-critical: for ANY tx set, parallel
and sequential apply must produce byte-identical ledger header hash,
bucket-list hash and tx meta — across worker counts AND Python hash
seeds.  The adversarial case (a deliberately under-declared footprint)
must abort to the sequential path and STILL match.
"""
import json
import os
import random
import subprocess
import sys

import pytest

from stellar_core_tpu.apply import footprint as fp_mod
from stellar_core_tpu.apply.planner import plan_parallel_apply
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.simulation.load_generator import LoadGenerator
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

from .txtest import NETWORK_ID, TestAccount, TestLedger


def _mk_app(workers, **kw):
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=300,
        PARALLEL_APPLY_WORKERS=workers, **kw))
    app.start()
    return app


def _close_and_fingerprint(app, fps):
    app.herder.manual_close()
    meta = app._meta_stream[-1] if app._meta_stream else None
    fps.append((
        app.ledger_manager.last_closed_hash(),
        app.bucket_manager.get_bucket_list_hash(),
        T.LedgerCloseMeta.encode(meta) if meta is not None else b""))


def _run_workload(workers, seed=7, n_closes=5, txs=80, pattern="pairs",
                  app_hook=None, **kw):
    """Seeded randomized mixed/DEX/conflicting workload through the
    full node close path; returns (fingerprints, apply stats).
    ``app_hook(app)`` runs on the started app before any load — the
    seam for injecting test invariant checkers and the like."""
    app = _mk_app(workers, **kw)
    if app_hook is not None:
        app_hook(app)
    lg = LoadGenerator(app)
    lg.payment_pattern = pattern
    lg.create_accounts(40)
    lg.setup_dex()
    rng = random.Random(seed)
    fps = []
    # random payments over the FULL account pool form one giant
    # connected component (n edges >> n/2 nodes) and the planner
    # rightly refuses; group the accounts so independent components
    # exist — conflicts stay real WITHIN each group, and the DEX group
    # exercises crossing + book materialization
    groups = [lg.accounts[g:g + 5] for g in range(0, 40, 5)]

    def reverse_offer(src, amount, pn, pd):
        # sell LOAD for native — the opposite direction of loadgen's
        # offer_envelope, so books CROSS and crossings settle against
        # resting sellers (the book-materialization surface)
        from stellar_core_tpu.transactions import utils as U

        op = T.Operation.make(
            sourceAccount=None,
            body=T.OperationBody.make(
                T.OperationType.MANAGE_SELL_OFFER,
                T.ManageSellOfferOp.make(
                    selling=lg.dex_asset, buying=U.asset_native(),
                    amount=amount, price=T.Price.make(n=pn, d=pd),
                    offerID=0)))
        return lg._sign_tx(src, [op], 100)

    for _ in range(n_closes):
        envs = []
        for i in range(txs):
            grp = groups[rng.randrange(len(groups))]
            src = grp[rng.randrange(len(grp))]
            roll = rng.random()
            if roll < 0.15:
                envs.append(lg.offer_envelope(
                    src, 5 + rng.randrange(50),
                    90 + rng.randrange(20), 100))
            elif roll < 0.30:
                envs.append(reverse_offer(
                    src, 5 + rng.randrange(50),
                    90 + rng.randrange(20), 100))
            else:
                dest = grp[rng.randrange(len(grp))].public_key().raw
                envs.append(lg.payment_envelope(
                    src, dest, 1 + rng.randrange(500)))
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted > 0
        _close_and_fingerprint(app, fps)
    stats = dict(app.parallel_apply.stats)
    app.graceful_stop()
    return fps, stats


def _assert_identical(a, b, what):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        assert x[0] == y[0], f"{what}: ledger hash diverged at close {i}"
        assert x[1] == y[1], f"{what}: bucket hash diverged at close {i}"
        assert x[2] == y[2], f"{what}: tx meta diverged at close {i}"


# -- the bit-identity property -----------------------------------------------

def test_parallel_matches_sequential_across_worker_counts():
    baseline, base_stats = _run_workload(0)
    assert base_stats["parallel_closes"] == 0
    for workers in (2, 4):
        fps, stats = _run_workload(workers)
        _assert_identical(baseline, fps, f"workers={workers}")
        assert stats["parallel_closes"] > 0, \
            f"workers={workers} never engaged the parallel path: {stats}"
        assert stats["aborts"] == 0, stats


def test_parallel_matches_sequential_more_seeds():
    for seed in (11, 42):
        seq, _ = _run_workload(0, seed=seed, n_closes=3)
        par, stats = _run_workload(4, seed=seed, n_closes=3)
        _assert_identical(seq, par, f"seed={seed}")
        assert stats["parallel_closes"] > 0, stats


def test_ring_pattern_conflicts_collapse_to_sequential():
    """The ring payment graph is one conflict component: the planner
    must refuse (single cluster), not parallelize wrongly."""
    seq, _ = _run_workload(0, pattern="ring", n_closes=2)
    par, stats = _run_workload(2, pattern="ring", n_closes=2)
    _assert_identical(seq, par, "ring")


def test_kill_switch_disables_parallel():
    fps, stats = _run_workload(2, n_closes=2, PARALLEL_APPLY=False)
    assert stats["parallel_closes"] == 0
    seq, _ = _run_workload(0, n_closes=2)
    _assert_identical(seq, fps, "kill switch")


# -- PYTHONHASHSEED variation (subprocess) -----------------------------------

_HASHSEED_WORKER = """
import hashlib
import sys

sys.path.insert(0, {repo!r})
from tests.test_apply_determinism import _run_mixed_workload

for lh, bh, meta in _run_mixed_workload():
    print(lh.hex(), bh.hex(), hashlib.sha256(meta).hexdigest())
"""


@pytest.mark.slow
def test_parallel_close_bit_identical_under_hashseed_variation():
    """The determinism guard's mixed workload, parallel apply ON, under
    PYTHONHASHSEED 0 vs 4242 — per-close fingerprints must match."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outputs = []
    for seed in ("0", "4242"):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = seed
        env["JAX_PLATFORMS"] = "cpu"
        env["PARALLEL_APPLY_WORKERS"] = "2"
        proc = subprocess.run(
            [sys.executable, "-c", _HASHSEED_WORKER.format(repo=repo)],
            capture_output=True, text=True, cwd=repo, env=env,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-4000:]
        lines = proc.stdout.strip().splitlines()
        assert len(lines) >= 8, proc.stdout
        outputs.append(lines)
    a, b = outputs
    assert a == b, "parallel close fingerprints diverged across hash seeds"


# -- the speculation guard ---------------------------------------------------

def _paylike_workload(workers):
    """Deterministic pairs-pattern payment closes; returns
    (fingerprints, stats, app) with the app still running."""
    app = _mk_app(workers)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    lg.create_accounts(40)
    fps = []
    for _ in range(3):
        envs = lg.generate_payments(80)
        assert sum(1 for env in envs
                   if app.herder.recv_transaction(env) == 0) == 80
        _close_and_fingerprint(app, fps)
    return fps, dict(app.parallel_apply.stats), app


def test_footprint_escape_aborts_to_sequential_and_matches():
    """Adversarial case: payments under-declare their destination.  The
    executor must catch the escape at runtime, abort the parallel
    attempt, replay sequentially, and still produce the sequential
    fingerprints — with the abort surfaced in metrics/Prometheus."""
    baseline, _, base_app = _paylike_workload(0)
    base_app.graceful_stop()

    real_handler = fp_mod.OP_FOOTPRINTS[T.OperationType.PAYMENT]

    def lying_payment_footprint(fp, opf, ctx):
        pass  # declares NOTHING beyond source accounts

    fp_mod.OP_FOOTPRINTS[T.OperationType.PAYMENT] = lying_payment_footprint
    try:
        fps, stats, app = _paylike_workload(2)
        assert stats["aborts"] > 0, f"no abort despite lying footprints: " \
            f"{stats}"
        assert stats["escapes"], stats
        assert "undeclared key access" in stats["escapes"][-1]
        # surfaced in metrics + Prometheus exposition
        assert app.metrics.counter("apply.parallel.abort").count \
            == stats["aborts"]
        handler = CommandHandler(app)
        code, body = handler.handle("metrics", {"format": "prometheus"})
        assert code == 200
        text = body.data.decode()
        assert "apply_parallel_abort" in text.replace(".", "_") or \
            "apply.parallel.abort" in text
        app.graceful_stop()
    finally:
        fp_mod.OP_FOOTPRINTS[T.OperationType.PAYMENT] = real_handler
    _assert_identical(baseline, fps, "escape-abort")


def test_cluster_spans_reach_the_trace_endpoint():
    """A parallel close's per-cluster spans (worker threads,
    cross-thread parent tokens) must land in trace?ledger=N —
    ledger.apply.cluster for Python clusters, ledger.apply.cluster.native
    for kernel-applied ones (payments are kernel-eligible)."""
    app = _mk_app(2)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    lg.create_accounts(20)
    fps = []
    envs = lg.generate_payments(40)
    assert sum(1 for env in envs
               if app.herder.recv_transaction(env) == 0) == 40
    _close_and_fingerprint(app, fps)
    assert app.parallel_apply.stats["parallel_closes"] == 1
    seq = app.ledger_manager.last_closed_seq()
    handler = CommandHandler(app)
    code, body = handler.handle("trace", {"ledger": str(seq)})
    assert code == 200
    trace = json.loads(body.data.decode())
    cluster_events = [e for e in trace["traceEvents"]
                      if e["name"] in ("ledger.apply.cluster",
                                       "ledger.apply.cluster.native",
                                       "ledger.apply.cluster.native.batch")]
    assert cluster_events, "no cluster spans in the close trace"
    # cross-thread parenting: cluster spans parent into the apply span
    by_id = {e["args"]["span_id"]: e for e in trace["traceEvents"]}
    apply_ids = {e["args"]["span_id"] for e in trace["traceEvents"]
                 if e["name"] == "ledger.close.apply"}
    for ev in cluster_events:
        assert ev["args"]["parent_id"] in apply_ids
        parent = by_id[ev["args"]["parent_id"]]
        assert parent["tid"] != ev["tid"], \
            "cluster span should run on a worker thread"
    app.graceful_stop()


# -- planner unit tests ------------------------------------------------------

def _frames(*envs):
    from stellar_core_tpu.transactions.frame import tx_frame_from_envelope

    return [tx_frame_from_envelope(NETWORK_ID, env) for env in envs]


def _plan(ledger, frames):
    with LedgerTxn(ledger.root_txn) as ltx:
        plan, stats = plan_parallel_apply(frames, ltx)
        ltx.rollback()
    return plan, stats


def test_planner_disjoint_payments_split():
    lg = TestLedger()
    root = lg.root()
    a = root.create("pa", 10**9)
    b = root.create("pb", 10**9)
    c = root.create("pc", 10**9)
    d = root.create("pd", 10**9)
    plan, stats = _plan(lg, _frames(
        a.tx([a.op_payment(b.account_id, 100)]),
        c.tx([c.op_payment(d.account_id, 100)])))
    assert plan is not None and stats["clusters"] == 2
    assert stats["max_width"] == 1


def test_planner_shared_destination_merges():
    lg = TestLedger()
    root = lg.root()
    a = root.create("qa", 10**9)
    b = root.create("qb", 10**9)
    c = root.create("qc", 10**9)
    plan, stats = _plan(lg, _frames(
        a.tx([a.op_payment(c.account_id, 100)]),
        b.tx([b.op_payment(c.account_id, 100)])))
    assert plan is None and stats["unplanned"] == "single cluster"


def _op_sell(acct, selling, buying, amount, pn=1, pd=1):
    return acct.op(T.OperationType.MANAGE_SELL_OFFER,
                   T.ManageSellOfferOp.make(
                       selling=selling, buying=buying, amount=amount,
                       price=T.Price.make(n=pn, d=pd), offerID=0))


def test_planner_offer_creators_share_the_idpool_cluster():
    from stellar_core_tpu.transactions import utils as U

    lg = TestLedger()
    root = lg.root()
    a = root.create("ra", 10**9)
    b = root.create("rb", 10**9)
    c = root.create("rc", 10**9)
    d = root.create("rd", 10**9)
    # issuers never send txs here, so issuer READS don't merge clusters
    iz1 = root.create("riz1", 10**9)
    iz2 = root.create("riz2", 10**9)
    usd = U.make_asset(b"USD", iz1.account_id)
    eur = U.make_asset(b"EUR", iz2.account_id)
    xlm = U.asset_native()
    plan, stats = _plan(lg, _frames(
        b.tx([b.op_change_trust(usd)]),
        # two offers on DIFFERENT books still merge: both allocate from
        # header.idPool, whose values are consensus-visible
        a.tx([_op_sell(a, xlm, usd, 100)]),
        c.tx([_op_sell(c, xlm, eur, 100)]),
        d.tx([d.op_payment(root.account_id, 5)]),
    ))
    assert plan is not None, stats
    widths = sorted(len(cl.indices) for cl in plan.clusters)
    assert stats["clusters"] == 3, stats
    assert widths == [1, 1, 2], (stats, widths)
    # the two offer txs must share one cluster (idPool serialization)
    offer_cluster = [cl for cl in plan.clusters
                     if set(cl.indices) >= {1, 2}]
    assert offer_cluster, [cl.indices for cl in plan.clusters]
    # intra-cluster canonical order preserved
    for cl in plan.clusters:
        assert cl.indices == sorted(cl.indices)


def test_planner_imprecise_op_declines():
    lg = TestLedger()
    root = lg.root()
    a = root.create("sa", 10**9)
    issuer = root.create("si", 10**9)
    env = issuer.tx([issuer.op(
        T.OperationType.ALLOW_TRUST,
        T.AllowTrustOp.make(
            trustor=T.account_id(a.account_id),
            asset=T.AssetCode.make(T.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
                                   b"IMP\x00"),
            authorize=1))])
    plan, stats = _plan(lg, _frames(
        env, a.tx([a.op_payment(root.account_id, 5)])))
    assert plan is None
    assert "allow_trust" in stats["unplanned"]


def test_detlint_scope_covers_apply_package():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.lint.engine import CONSENSUS_DIRS

    assert "apply" in CONSENSUS_DIRS
