"""Native GIL-free apply kernel (ISSUE 6 tentpole): bit-identity of
kernel-applied clusters against the Python reference apply, the
decline-to-Python fallback, the packed-delta merge tier, and the
NATIVE_APPLY kill switch.

The consensus property: for ANY tx set, closes with the native kernel
engaged must produce byte-identical ledger header hash, bucket-list
hash and tx meta versus forced-Python apply — across worker counts
(0 inline / 2 / 4), and across PYTHONHASHSEED values (subprocess).
A kernel-ineligible tx inside an otherwise-eligible set must route its
cluster (and only its cluster) through the Python path and STILL
match.
"""
import hashlib
import os
import subprocess
import sys

import pytest

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.simulation.load_generator import LoadGenerator
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

from .test_parallel_apply import (
    _assert_identical, _close_and_fingerprint, _run_workload,
)


def test_kernel_builds():
    from stellar_core_tpu.native import get_apply_kernel

    assert get_apply_kernel() is not None, \
        "apply kernel failed to build (g++ is baked into the image)"


# -- the bit-identity property (native vs forced-Python) ---------------------

def test_native_matches_python_across_worker_counts():
    """Randomized pay/mixed/crossing workload: kernel on vs
    NATIVE_APPLY=0 at workers 2 and 4 — identical fingerprints, and the
    kernel must actually engage (hits > 0, declines accounted)."""
    python_fps, python_stats = _run_workload(2, NATIVE_APPLY=False)
    assert python_stats["native_hits"] == 0
    for workers in (2, 4):
        fps, stats = _run_workload(workers, NATIVE_APPLY=True)
        _assert_identical(python_fps, fps, f"native workers={workers}")
        assert stats["native_hits"] > 0, \
            f"kernel never engaged at workers={workers}: {stats}"
        assert stats["aborts"] == 0, stats


def test_native_inline_workers0_matches_sequential():
    """NATIVE_APPLY_INLINE engages planner+kernel with NO worker pool:
    clusters apply natively on the close thread, sequentially — faster
    payment strips without a single thread hop, same bytes."""
    seq, seq_stats = _run_workload(0, n_closes=3)
    assert seq_stats["parallel_closes"] == 0
    fps, stats = _run_workload(0, n_closes=3, NATIVE_APPLY=True,
                               NATIVE_APPLY_INLINE=True)
    _assert_identical(seq, fps, "inline native")
    assert stats["parallel_closes"] > 0, stats
    assert stats["native_hits"] > 0, stats


def test_kill_switch_restores_pure_python_path():
    seq, _ = _run_workload(0, n_closes=2)
    fps, stats = _run_workload(2, n_closes=2, NATIVE_APPLY=False)
    _assert_identical(seq, fps, "NATIVE_APPLY=0")
    assert stats["native_hits"] == 0
    assert stats["native_declines"] == 0


# -- decline paths -----------------------------------------------------------

def _mk_app(workers, **kw):
    # these tests are ABOUT the kernel: force it on via config so the
    # suite stays meaningful (and green) under verify_green's
    # NATIVE_APPLY=0 fallback-smoke environment — the Python arms
    # always pass NATIVE_APPLY=False explicitly
    kw.setdefault("NATIVE_APPLY", True)
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        TESTING_UPGRADE_MAX_TX_SET_SIZE=300,
        PARALLEL_APPLY_WORKERS=workers, **kw))
    app.start()
    return app


def _bounded_payment(lg, src, dest, amount):
    """A payment with time-bound preconditions: applies fine but is NOT
    kernel-shaped (PRECOND_TIME stays host-side)."""
    from stellar_core_tpu.crypto import sha256
    from stellar_core_tpu.transactions import utils as U
    from stellar_core_tpu.transactions.signature_checker import \
        signature_hint

    op = T.Operation.make(
        sourceAccount=None,
        body=T.OperationBody.make(
            T.OperationType.PAYMENT,
            T.PaymentOp.make(destination=T.muxed_account(dest),
                             asset=U.asset_native(), amount=amount)))
    tx = T.Transaction.make(
        sourceAccount=T.muxed_account(src.public_key().raw),
        fee=100,
        seqNum=lg._next_seq(src),
        cond=T.Preconditions.make(
            T.PreconditionType.PRECOND_TIME,
            T.TimeBounds.make(minTime=0, maxTime=0)),
        memo=T.MEMO_NONE_VALUE,
        operations=[op],
        ext=T.Transaction.fields[6][1].make(0))
    payload = T.TransactionSignaturePayload.make(
        networkId=lg.network_id,
        taggedTransaction=T.TransactionSignaturePayload.fields[1][1]
        .make(T.EnvelopeType.ENVELOPE_TYPE_TX, tx))
    h = sha256(T.TransactionSignaturePayload.encode(payload))
    sig = T.DecoratedSignature.make(
        hint=signature_hint(src.public_key().raw),
        signature=src.sign(h))
    return T.TransactionEnvelope.make(
        T.EnvelopeType.ENVELOPE_TYPE_TX,
        T.TransactionV1Envelope.make(tx=tx, signatures=[sig]))


def _ineligible_mid_cluster_workload(workers, **kw):
    """Pairs payments with ONE structurally-ineligible tx injected: its
    cluster must fall to the Python path while the rest stay native."""
    app = _mk_app(workers, **kw)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    lg.create_accounts(40)
    fps = []
    for _ in range(3):
        envs = lg.generate_payments(60)
        # the injected tx shares account 0's pair-cluster mid-set
        envs.append(_bounded_payment(
            lg, lg.accounts[0], lg.accounts[1].public_key().raw, 7))
        admitted = sum(1 for env in envs
                       if app.herder.recv_transaction(env) == 0)
        assert admitted == len(envs)
        _close_and_fingerprint(app, fps)
    stats = dict(app.parallel_apply.stats)
    app.graceful_stop()
    return fps, stats


def test_ineligible_tx_mid_cluster_falls_back_and_matches():
    seq, _ = _ineligible_mid_cluster_workload(0)
    fps, stats = _ineligible_mid_cluster_workload(2)
    _assert_identical(seq, fps, "ineligible mid-cluster")
    assert stats["native_hits"] > 0, stats
    # the bounded tx's cluster was never offered to the kernel
    assert stats["native_off"] > 0, stats
    assert stats["aborts"] == 0, stats


def _extra_signer_workload(workers, app_hook=None, **kw):
    """State-level decline: an account grows a second signer, so later
    payments from it are kernel-SHAPED but the kernel's account parse
    refuses (signers stay host-side) — decline, Python fallback, same
    bytes."""
    from stellar_core_tpu.crypto import sha256

    app = _mk_app(workers, **kw)
    if app_hook is not None:
        app_hook(app)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    lg.create_accounts(20)
    signer_key = sha256(b"native-apply-extra-signer")
    src = lg.accounts[0]
    op = T.Operation.make(
        sourceAccount=None,
        body=T.OperationBody.make(
            T.OperationType.SET_OPTIONS,
            T.SetOptionsOp.make(
                inflationDest=None, clearFlags=None, setFlags=None,
                masterWeight=None, lowThreshold=None, medThreshold=None,
                highThreshold=None, homeDomain=None,
                signer=T.Signer.make(
                    key=T.SignerKey.make(
                        T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                        signer_key),
                    weight=1))))
    env = lg._sign_tx(src, [op], 100)
    assert app.herder.recv_transaction(env) == 0
    fps = []
    _close_and_fingerprint(app, fps)
    for _ in range(2):
        envs = lg.generate_payments(40)
        admitted = sum(1 for e in envs
                       if app.herder.recv_transaction(e) == 0)
        assert admitted == len(envs)
        _close_and_fingerprint(app, fps)
    stats = dict(app.parallel_apply.stats)
    app.graceful_stop()
    return fps, stats


def test_unsupported_account_state_declines_and_matches():
    seq, _ = _extra_signer_workload(0)
    fps, stats = _extra_signer_workload(2)
    _assert_identical(seq, fps, "extra-signer decline")
    assert stats["native_declines"] > 0, stats
    assert any("unsupported account shape" in r
               for r in stats["native_decline_reasons"]), \
        stats["native_decline_reasons"]
    assert stats["native_hits"] > 0, stats


def test_single_cluster_ring_goes_native_inline():
    """The adversarial ring (one conflict component) used to force a
    planner refusal; with the kernel it becomes a single-cluster native
    plan applied inline on the close thread."""
    seq, _ = _run_workload(0, pattern="ring", n_closes=2)
    fps, stats = _run_workload(2, pattern="ring", n_closes=2,
                               NATIVE_APPLY=True)
    _assert_identical(seq, fps, "ring native")
    assert stats["native_hits"] > 0, stats


# -- ISSUE 13: kernel-complete apply (credit / trust / path / modify) --------

def _hit_rate(stats) -> float:
    clusters = stats["native_hits"] + stats["native_declines"] + \
        stats["native_off"]
    return stats["native_hits"] / clusters if clusters else 0.0


def _credit_workload(workers, n_closes=3, txs=60, **kw):
    """Credit-asset payments over disjoint pairs + changeTrust
    create/update salt — the credit-heavy shape real traffic has."""
    app = _mk_app(workers, **kw)
    lg = LoadGenerator(app)
    lg.create_accounts(40)
    lg.setup_credit()
    fps = []
    for _ in range(n_closes):
        envs = lg.generate_credit_mix(txs, trust_pct=15)
        assert sum(1 for e in envs
                   if app.herder.recv_transaction(e) == 0) == len(envs)
        _close_and_fingerprint(app, fps)
    stats = dict(app.parallel_apply.stats)
    hits = {name: m.count for name, m in app.metrics._metrics.items()
            if name.startswith("apply.native.hit.")}
    app.graceful_stop()
    return fps, stats, hits


def test_credit_mix_goes_native_and_matches():
    seq, _, _ = _credit_workload(0, NATIVE_APPLY=False)
    fps, stats, hits = _credit_workload(2)
    _assert_identical(seq, fps, "credit mix")
    assert stats["aborts"] == 0, stats
    # declines on the credit mix are now bugs, not expected coverage
    # gaps (the ISSUE-13 acceptance gate)
    assert _hit_rate(stats) >= 0.9, stats
    assert hits.get("apply.native.hit.payment", 0) > 0, hits
    assert hits.get("apply.native.hit.trust", 0) > 0, hits


def test_changetrust_delete_goes_native_and_matches():
    """Trustline create (close 1) then delete via limit=0 (close 2) —
    the subentry-reserve round trip, in-kernel both ways."""
    def run(workers, native):
        app = _mk_app(workers, NATIVE_APPLY=native)
        lg = LoadGenerator(app)
        lg.create_accounts(10)
        lg.setup_credit()
        fps = []
        for limit in (10**9, 0):
            envs = [lg.changetrust_envelope(sk, lg.credit2_asset, limit)
                    for sk in lg.accounts]
            assert sum(1 for e in envs
                       if app.herder.recv_transaction(e) == 0) == len(envs)
            _close_and_fingerprint(app, fps)
        stats = dict(app.parallel_apply.stats)
        app.graceful_stop()
        return fps, stats

    seq, _ = run(0, False)
    fps, stats = run(2, True)
    _assert_identical(seq, fps, "changetrust delete")
    assert stats["native_hits"] > 0, stats
    assert stats["native_declines"] == 0, stats


def _pathpay_workload(workers, hops=2, n_closes=3, txs=40, **kw):
    app = _mk_app(workers, **kw)
    lg = LoadGenerator(app)
    lg.create_accounts(24)
    maker_envs = lg.setup_path(hops=hops, makers=4)
    assert sum(1 for e in maker_envs
               if app.herder.recv_transaction(e) == 0) == len(maker_envs)
    fps = []
    _close_and_fingerprint(app, fps)
    for _ in range(n_closes):
        envs = lg.generate_path_payments(txs)
        assert sum(1 for e in envs
                   if app.herder.recv_transaction(e) == 0) == len(envs)
        _close_and_fingerprint(app, fps)
    stats = dict(app.parallel_apply.stats)
    hits = {name: m.count for name, m in app.metrics._metrics.items()
            if name.startswith("apply.native.hit.")}
    app.graceful_stop()
    return fps, stats, hits


def test_path_payments_go_native_and_match():
    """2-hop strict-send + strict-receive chains over seeded books:
    the whole close is one book-pair cluster, applied natively inline;
    bytes identical to forced-Python."""
    seq, _, _ = _pathpay_workload(0, NATIVE_APPLY=False)
    fps, stats, hits = _pathpay_workload(2)
    _assert_identical(seq, fps, "path payments")
    assert stats["aborts"] == 0, stats
    assert _hit_rate(stats) >= 0.9, stats
    assert hits.get("apply.native.hit.pathpay", 0) > 0, hits


def test_three_hop_path_payments_match():
    seq, _, _ = _pathpay_workload(0, hops=3, n_closes=2,
                                  NATIVE_APPLY=False)
    fps, stats, _ = _pathpay_workload(2, hops=3, n_closes=2)
    _assert_identical(seq, fps, "3-hop path payments")
    assert stats["native_hits"] > 0, stats


def test_live_pool_on_hop_goes_native_and_matches():
    """A LIVE liquidity pool on a hop pair quotes IN-KERNEL (r16):
    the constant-product-vs-book arbitration runs inside the crossing
    loop, bytes identical to the Python reference.  NATIVE_POOL_QUOTE=0
    is the kill switch — the old decline-if-live-pool screen returns,
    Python adjudicates, same bytes, taxonomy names the guard."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.transactions import liquidity_pool as LP
    from stellar_core_tpu.transactions import utils as U

    def seed_pool(app, lg):
        # a constant-product pool on the (native, PATHA) hop pair,
        # bulk-written like the rest of the perf-rig seeding
        a_native = U.asset_native()
        a_credit = lg.path_assets[0]
        a, b = ((a_native, a_credit)
                if LP.compare_assets(a_native, a_credit) < 0
                else (a_credit, a_native))
        params = T.LiquidityPoolParameters.make(
            T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
            T.LiquidityPoolConstantProductParameters.make(
                assetA=a, assetB=b, fee=T.LIQUIDITY_POOL_FEE_V18))
        pool_id = LP.pool_id_from_params(params)
        cp = T.LiquidityPoolEntry.fields[1][1].arms[
            T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT][1].make(
            params=params.value, reserveA=10**10, reserveB=10**10,
            totalPoolShares=10**10, poolSharesTrustLineCount=1)
        lp = T.LiquidityPoolEntry.make(
            liquidityPoolID=pool_id,
            body=T.LiquidityPoolEntry.fields[1][1].make(
                T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT, cp))
        with LedgerTxn(app.ledger_manager.root) as ltx:
            ltx.put(U.wrap_entry(T.LedgerEntryType.LIQUIDITY_POOL, lp))
            ltx.commit()

    def run(workers, native, **kw):
        app = _mk_app(workers, NATIVE_APPLY=native, **kw)
        lg = LoadGenerator(app)
        lg.create_accounts(12)
        maker_envs = lg.setup_path(hops=2, makers=2)
        assert sum(1 for e in maker_envs
                   if app.herder.recv_transaction(e) == 0) == \
            len(maker_envs)
        fps = []
        _close_and_fingerprint(app, fps)
        seed_pool(app, lg)
        envs = lg.generate_path_payments(20)
        assert sum(1 for e in envs
                   if app.herder.recv_transaction(e) == 0) == len(envs)
        _close_and_fingerprint(app, fps)
        stats = dict(app.parallel_apply.stats)
        app.graceful_stop()
        return fps, stats

    seq, _ = run(0, False)
    fps, stats = run(2, True)
    _assert_identical(seq, fps, "pool-on-hop native")
    assert stats["native_hits"] > 0, stats
    # kill switch: with pool quoting forced off the old host screen
    # declines the cluster and the Python reference adjudicates —
    # bytes still identical
    fps_off, stats_off = run(2, True, NATIVE_POOL_QUOTE=False)
    _assert_identical(seq, fps_off, "pool-on-hop decline (quote off)")
    assert stats_off["native_declines"] > 0, stats_off
    assert any("liquidity pool on hop" in r
               for r in stats_off["native_decline_reasons"]), \
        stats_off["native_decline_reasons"]


def test_offer_modify_delete_go_native_and_match():
    """offerID!=0: modify re-posts at the same id (UPDATED effect),
    amount=0 deletes — the resting offer loads from the packed
    snapshot, old liabilities release, the crossing loop re-runs."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

    def run(workers, native):
        app = _mk_app(workers, NATIVE_APPLY=native)
        lg = LoadGenerator(app)
        lg.payment_pattern = "pairs"
        lg.create_accounts(12)
        lg.setup_dex()
        fps = []
        # close 1: everyone posts a resting offer (exact-ratio amounts
        # keep the 1% price-error threshold out of the picture)
        envs = [lg.offer_envelope(sk, 100, 120 + i, 100)
                for i, sk in enumerate(lg.accounts)]
        assert sum(1 for e in envs
                   if app.herder.recv_transaction(e) == 0) == len(envs)
        _close_and_fingerprint(app, fps)
        ids = {}
        with LedgerTxn(app.ledger_manager.root) as ltx:
            for sk in lg.accounts:
                offers = list(ltx.offers_by_account(sk.public_key().raw))
                assert len(offers) == 1
                ids[sk.public_key().raw] = offers[0].data.value.offerID
            ltx.rollback()
        # close 2: modify every offer (new amount + price, same id)
        envs = [lg.offer_envelope(sk, 200, 140 + i, 100,
                                  offer_id=ids[sk.public_key().raw])
                for i, sk in enumerate(lg.accounts)]
        assert sum(1 for e in envs
                   if app.herder.recv_transaction(e) == 0) == len(envs)
        _close_and_fingerprint(app, fps)
        # close 3: half delete (amount=0), half modify again
        envs = []
        for i, sk in enumerate(lg.accounts):
            oid = ids[sk.public_key().raw]
            if i % 2 == 0:
                envs.append(lg.offer_envelope(sk, 0, 1, 1, offer_id=oid))
            else:
                envs.append(lg.offer_envelope(sk, 100, 150, 100,
                                              offer_id=oid))
        assert sum(1 for e in envs
                   if app.herder.recv_transaction(e) == 0) == len(envs)
        _close_and_fingerprint(app, fps)
        stats = dict(app.parallel_apply.stats)
        app.graceful_stop()
        return fps, stats

    seq, _ = run(0, False)
    fps, stats = run(2, True)
    _assert_identical(seq, fps, "offer modify/delete")
    assert stats["native_hits"] > 0, stats
    assert stats["native_declines"] == 0, stats
    assert stats["aborts"] == 0, stats


def test_decline_taxonomy_reaches_metrics():
    """A decline increments apply.native.decline.<op>.<reason> so a
    decline storm names its coverage gap in /metrics."""
    app = _mk_app(2)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    lg.create_accounts(8)
    from stellar_core_tpu.crypto import sha256

    signer_key = sha256(b"decline-taxonomy-signer")
    op = T.Operation.make(
        sourceAccount=None,
        body=T.OperationBody.make(
            T.OperationType.SET_OPTIONS,
            T.SetOptionsOp.make(
                inflationDest=None, clearFlags=None, setFlags=None,
                masterWeight=None, lowThreshold=None, medThreshold=None,
                highThreshold=None, homeDomain=None,
                signer=T.Signer.make(
                    key=T.SignerKey.make(
                        T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                        signer_key),
                    weight=1))))
    assert app.herder.recv_transaction(
        lg._sign_tx(lg.accounts[0], [op], 100)) == 0
    app.herder.manual_close()
    envs = lg.generate_payments(16)
    assert sum(1 for e in envs
               if app.herder.recv_transaction(e) == 0) == len(envs)
    app.herder.manual_close()
    stats = dict(app.parallel_apply.stats)
    assert stats["native_declines"] > 0, stats
    breakout = {name: m.count
                for name, m in app.metrics._metrics.items()
                if name.startswith("apply.native.decline.")}
    assert sum(breakout.values()) == stats["native_declines"], \
        (breakout, stats["native_declines"])
    assert any("unsupported_account_shape" in name
               for name in breakout), breakout
    app.graceful_stop()


# -- metrics / observability -------------------------------------------------

def test_native_counters_reach_metrics_and_stats_line(tmp_path):
    stats_file = str(tmp_path / "apply_stats.jsonl")
    app = _mk_app(2, PARALLEL_APPLY_STATS_FILE=stats_file)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    lg.create_accounts(20)
    envs = lg.generate_payments(40)
    assert sum(1 for e in envs
               if app.herder.recv_transaction(e) == 0) == 40
    fps = []
    _close_and_fingerprint(app, fps)
    stats = dict(app.parallel_apply.stats)
    assert stats["native_hits"] > 0
    assert app.metrics.counter("apply.native.hit").count == \
        stats["native_hits"]
    app.graceful_stop()
    import json

    with open(stats_file) as f:
        line = json.loads(f.readline())
    assert line["native_hits"] == stats["native_hits"]
    assert line["native"] is True


def test_native_cluster_spans_reach_the_trace_endpoint():
    import json

    from stellar_core_tpu.main.http_server import CommandHandler

    app = _mk_app(2)
    lg = LoadGenerator(app)
    lg.payment_pattern = "pairs"
    lg.create_accounts(20)
    envs = lg.generate_payments(40)
    assert sum(1 for e in envs
               if app.herder.recv_transaction(e) == 0) == 40
    fps = []
    _close_and_fingerprint(app, fps)
    seq = app.ledger_manager.last_closed_seq()
    handler = CommandHandler(app)
    code, body = handler.handle("trace", {"ledger": str(seq)})
    assert code == 200
    trace = json.loads(body.data.decode())
    # small kernel-eligible clusters coalesce into batched crossings
    # (ROADMAP 2d); a lone trailing cluster still spans per-cluster
    native_events = [e for e in trace["traceEvents"]
                     if e["name"] in ("ledger.apply.cluster.native",
                                      "ledger.apply.cluster.native.batch")]
    assert native_events, "no native cluster spans in the close trace"
    assert all(e["args"].get("outcome") == "hit" for e in native_events)
    batch_events = [e for e in native_events
                    if e["name"].endswith(".batch")]
    assert batch_events, "expected a batched kernel crossing"
    assert all(e["args"]["clusters"] >= 2 for e in batch_events)
    assert app.parallel_apply.stats["batched_clusters"] >= 2
    app.graceful_stop()


# -- the pre-pack host screen ------------------------------------------------

def test_account_screen_declines_before_packing():
    """The persistent account-shape declines (extra signers, inflation
    destination) are caught on the decoded snapshot entry BEFORE the
    cluster pays the snapshot/book encode — same refusal the kernel's
    parse would raise post-pack, minus the per-close packing tax."""
    from types import SimpleNamespace

    from stellar_core_tpu.apply.native_apply import (KernelDecline,
                                                     _screen_account)
    from stellar_core_tpu.crypto import sha256
    from stellar_core_tpu.ledger.ledger_txn import account_key_bytes
    from stellar_core_tpu.transactions import utils as U

    aid = b"\x11" * 32
    kb = account_key_bytes(aid)
    snapshot = SimpleNamespace(
        store={kb: U.make_account_entry(aid, 500, seq_num=1)})
    _screen_account(snapshot, aid, 0)  # clean shape: no refusal
    _screen_account(snapshot, b"\x99" * 32, 0)  # absent: kernel's call

    signer = T.Signer.make(
        key=T.SignerKey.make(T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                             sha256(b"screen-signer")),
        weight=1)
    snapshot.store[kb] = U.make_account_entry(
        aid, 500, seq_num=1, signers=[signer])
    with pytest.raises(KernelDecline, match="unsupported account shape"):
        _screen_account(snapshot, aid, 3)

    snapshot.store[kb] = U.make_account_entry(
        aid, 500, seq_num=1, inflationDest=T.account_id(b"\x22" * 32))
    with pytest.raises(KernelDecline, match="unsupported account shape"):
        _screen_account(snapshot, aid, 3)


# -- the packed-value tier ---------------------------------------------------

def test_packed_entry_encodes_without_decode_and_decodes_on_touch():
    from stellar_core_tpu.ledger.packed import LazyUnion, PackedEntry
    from stellar_core_tpu.transactions import utils as U

    entry = U.make_account_entry(b"\x07" * 32, 12345, seq_num=99)
    eb = T.LedgerEntry.encode(entry)
    pe = PackedEntry(eb)
    # encode path: memo hit, no field materialization
    assert T.LedgerEntry.encode(pe) == eb
    assert "data" not in pe.__dict__
    # field access materializes once and matches the decoded value
    assert pe.data.value.balance == 12345
    assert pe.lastModifiedLedgerSeq == entry.lastModifiedLedgerSeq
    assert pe._replace(lastModifiedLedgerSeq=7).lastModifiedLedgerSeq == 7

    meta = T.TransactionMeta.make(2, T.TransactionMetaV2.make(
        txChangesBefore=[], operations=[], txChangesAfter=[]))
    mb = T.TransactionMeta.encode(meta)
    lazy = LazyUnion(T.TransactionMeta, mb)
    assert T.TransactionMeta.encode(lazy) == mb
    assert lazy.type == 2
    assert lazy.value.operations == []


# -- PYTHONHASHSEED variation (subprocess) -----------------------------------

_HASHSEED_WORKER = """
import hashlib
import sys

sys.path.insert(0, {repo!r})
from tests.test_apply_determinism import _run_mixed_workload

for lh, bh, meta in _run_mixed_workload():
    print(lh.hex(), bh.hex(), hashlib.sha256(meta).hexdigest())
"""


@pytest.mark.slow
def test_native_close_bit_identical_under_hashseed_variation():
    """Mixed workload with the kernel engaged under PYTHONHASHSEED 0 vs
    4242, cross-checked against a forced-Python run: all three must
    produce the same per-close fingerprints."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outputs = []
    for seed, native in (("0", "1"), ("4242", "1"), ("0", "0")):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = seed
        env["JAX_PLATFORMS"] = "cpu"
        env["PARALLEL_APPLY_WORKERS"] = "2"
        env["NATIVE_APPLY"] = native
        proc = subprocess.run(
            [sys.executable, "-c", _HASHSEED_WORKER.format(repo=repo)],
            capture_output=True, text=True, cwd=repo, env=env,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-4000:]
        lines = proc.stdout.strip().splitlines()
        assert len(lines) >= 8, proc.stdout
        outputs.append(lines)
    assert outputs[0] == outputs[1], \
        "native close fingerprints diverged across hash seeds"
    assert outputs[0] == outputs[2], \
        "native close fingerprints diverged from forced-Python apply"


# -- detlint scope (satellite) -----------------------------------------------

def test_detlint_covers_native_apply_and_kernel_handle():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.lint.engine import CONSENSUS_DIRS, REPO, _parse_file

    assert "apply" in CONSENSUS_DIRS  # native_apply.py rides the scope
    # the kernel handle in the native loader must stay lock-annotated:
    # detlint's guarded-by audit only bites on annotated fields
    rel = "stellar_core_tpu/native/__init__.py"
    with open(os.path.join(REPO, rel)) as f:
        info = _parse_file(rel, f.read())
    guarded = set()
    for line, lock in info.guards.items():
        text = info.line_text(line)
        guarded.add(text.split("=")[0].strip().split(":")[0].strip())
    assert "_applykernel_mod" in guarded, guarded
    assert "_applykernel_tried" in guarded, guarded


# -- post-apply invariant pass over kernel deltas (ISSUE 7 satellite) --------

def test_native_invariant_pass_arms_with_checks_configured():
    """INVARIANT_CHECKS configured (test_config defaults to [".*"])
    must arm the post-apply cluster-delta pass whenever the kernel can
    engage; an empty checker list must not (the lazy-decode opt-out)."""
    app = _mk_app(2)
    assert app.parallel_apply.native_invariants is True
    app.graceful_stop()
    app = _mk_app(2, INVARIANT_CHECKS=[])
    assert app.parallel_apply.native_invariants is False
    app.graceful_stop()


def test_native_cluster_invariant_violation_aborts_to_python():
    """A violation seen ONLY at cluster granularity (frame is None —
    modeling a kernel-side divergence the per-op Python path does not
    reproduce) must abort the parallel attempt; the sequential replay's
    bytes win and the close completes bit-identical to forced-Python."""
    from stellar_core_tpu.invariant.manager import Invariant

    class NativeOnlyTrip(Invariant):
        NAME = "NativeOnlyTrip"

        def check_on_tx_apply(self, ltx, frame, ok):
            return "tripped on a kernel delta" if frame is None else ""

    seq, _ = _run_workload(2, n_closes=2, NATIVE_APPLY=False)
    apps = []

    def arm(app):
        app.invariants.invariants.append(NativeOnlyTrip())
        apps.append(app)

    fps, stats = _run_workload(2, n_closes=2, NATIVE_APPLY=True,
                               app_hook=arm)
    _assert_identical(seq, fps, "native invariant abort")
    assert stats["aborts"] > 0, stats
    assert apps[0].metrics.counter(
        "apply.native.invariant-fail").count > 0


def test_native_invariant_violation_reproduced_crashes_close():
    """When the sequential replay REPRODUCES the violation it is a real
    bug, not kernel divergence: the close must crash safety-first."""
    from stellar_core_tpu.invariant.manager import (
        Invariant, InvariantDoesNotHold)

    class AlwaysTrip(Invariant):
        NAME = "AlwaysTrip"

        def check_on_tx_apply(self, ltx, frame, ok):
            return "always fails"

    app = _mk_app(2)
    lg = LoadGenerator(app)
    lg.create_accounts(10)
    envs = lg.generate_payments(5)
    for env in envs:
        assert app.herder.recv_transaction(env) == 0
    app.invariants.invariants.append(AlwaysTrip())
    with pytest.raises(InvariantDoesNotHold):
        app.herder.manual_close()
    app.graceful_stop()
