"""Cross-peer SCP signature-batch admission (ISSUE 7 satellite,
ROADMAP 4 companion): flooded envelopes received within one crank
verify as ONE padded batch (overlay/manager.py _drain_scp_inbox)
instead of per-envelope inside SCP.

The property: verdicts are identical either way — batching is a pure
dispatch-shape change.  Consensus must close the same ledgers with the
same hashes with OVERLAY_SIG_BATCH on and off, forged signatures must
still be rejected through the batched path, and the batch counters
must surface in /metrics.
"""
from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.simulation import core
from stellar_core_tpu.xdr import types as T

from .test_simulation import settle


def _run_network(n_rounds=3, **config_kw):
    """core-3 network closing ``n_rounds`` ledgers; returns (sim,
    per-round ledger hashes)."""
    sim = core(3, **config_kw)
    sim.start_all_nodes()
    settle(sim)
    hashes = []
    for _ in range(n_rounds):
        assert sim.close_ledger()
        sim.assert_in_sync()
        hashes.append(sim.ledger_hashes()[0])
    return sim, hashes


def test_sigbatch_engages_and_counters_surface():
    """Default-on batching: a consensus round floods envelopes, so every
    node must have verified at least one multi-envelope batch, counted
    under overlay.sigbatch.* in the metrics registry."""
    sim, _ = _run_network()
    for app in sim.nodes.values():
        batches = app.metrics.counter("overlay.sigbatch.batches").count
        envs = app.metrics.counter("overlay.sigbatch.envelopes").count
        assert batches > 0, "sig batching never engaged"
        assert envs >= batches
        snap = app.metrics.snapshot()
        assert snap["overlay.sigbatch.batches"]["count"] == batches


def test_sigbatch_off_parity():
    """OVERLAY_SIG_BATCH=0 restores the per-envelope path; the network
    must close the exact same ledger hashes (verdict identity)."""
    _, batched = _run_network()
    sim_off, direct = _run_network(OVERLAY_SIG_BATCH=False)
    for app in sim_off.nodes.values():
        assert app.metrics.counter(
            "overlay.sigbatch.batches").count == 0
    assert batched == direct


def test_verify_triples_matches_scalar_verdicts():
    """_verify_triples is the batch chokepoint: good and forged
    signatures interleaved must come back [True, False, ...] exactly
    like scalar verify_sig."""
    sim = core(2)
    app = next(iter(sim.nodes.values()))
    om = app.overlay_manager
    sk = SecretKey(b"\x07" * 32)
    msg_a, msg_b = sha256(b"batch a"), sha256(b"batch b")
    good_a = (sk.public_key().raw, sk.sign(msg_a), msg_a)
    good_b = (sk.public_key().raw, sk.sign(msg_b), msg_b)
    forged = (sk.public_key().raw, sk.sign(msg_a), msg_b)
    assert om._verify_triples([good_a, forged, good_b]) == \
        [True, False, True]


def test_forged_envelope_rejected_through_batch_path():
    """End-to-end through the drain: a properly-signed envelope primes a
    True verdict; tampering the signature primes False and SCP refuses
    the envelope — the batch path must never weaken admission."""
    sim = core(2)
    sim.start_all_nodes()
    settle(sim)
    assert sim.close_ledger()
    a, b = list(sim.nodes)
    app = sim.nodes[a]
    om, driver = app.overlay_manager, app.herder.driver
    # a real envelope from the other validator, captured post-consensus
    slot_idx = max(app.herder.scp.slots)
    env = next(
        e for e in app.herder.scp.get_current_state_envelopes(slot_idx)
        if e.statement.nodeID.value == b)
    good = driver.envelope_sig_triple(env)
    forged_env = T.SCPEnvelope.make(statement=env.statement,
                                    signature=bytes(64))
    forged = driver.envelope_sig_triple(forged_env)
    om._scp_inbox.extend([env, forged_env])
    om._drain_scp_inbox()
    assert driver._sig_verdicts[good] is True
    assert driver._sig_verdicts[forged] is False
    assert driver.verify_envelope(forged_env) is False
    assert driver.verify_envelope(env) is True


def test_sigbatch_skips_out_of_bracket_envelopes():
    """Stale/far-future envelopes are discarded unverified by the
    herder; the drain must not spend batch slots on them."""
    sim = core(2)
    sim.start_all_nodes()
    settle(sim)
    assert sim.close_ledger()
    app = next(iter(sim.nodes.values()))
    om, herder = app.overlay_manager, app.herder
    slot_idx = max(herder.scp.slots)
    env = herder.scp.get_current_state_envelopes(slot_idx)[0]
    far_future = env.statement.slotIndex + 10_000
    stale = T.SCPEnvelope.make(
        statement=env.statement._replace(slotIndex=far_future),
        signature=env.signature)
    before = app.metrics.counter("overlay.sigbatch.envelopes").count
    om._scp_inbox.append(stale)
    om._drain_scp_inbox()
    triple = herder.driver.envelope_sig_triple(stale)
    assert triple not in herder.driver._sig_verdicts
    assert app.metrics.counter("herder.scp.discarded").count > 0
