"""Consensus failure detection + out-of-sync recovery: a node cut off from
its quorum flips TRACKING -> NOT_TRACKING on the stuck timeout, then
recovers via GET_SCP_STATE after reconnecting
(ref HerderImpl.cpp:432 outOfSyncRecovery, Herder.cpp:9
CONSENSUS_STUCK_TIMEOUT_SECONDS; VERDICT r2 next-round task #10)."""
from stellar_core_tpu.herder.herder import HerderState
from stellar_core_tpu.overlay.peer import make_loopback_pair
from stellar_core_tpu.simulation.simulation import Simulation, _ids, _seeds


def _live_sim(n=3, threshold=2, archive_dir=None):
    sim = Simulation(network_passphrase="recovery net")
    seeds = _seeds(n)
    ids = _ids(seeds)
    qset = {"threshold": threshold, "validators": ids}
    kw = {}
    if archive_dir is not None:
        # one shared archive: publishes are content-addressed and
        # deterministic across nodes, rejoiners catch up from it
        kw["HISTORY_ARCHIVES"] = [("shared", str(archive_dir))]
    for s in seeds:
        sim.add_node(s, qset, MANUAL_CLOSE=False, **kw)
    for i in range(n):
        for j in range(i + 1, n):
            sim.add_connection(ids[i], ids[j])
    return sim, ids


def _disconnect(app):
    for p in list(app.overlay_manager.authenticated.values()):
        partner = p.partner
        p.close("test disconnect")
        partner.close("test disconnect")


def test_cut_off_node_goes_not_tracking_and_recovers(tmp_path):
    sim, ids = _live_sim(archive_dir=tmp_path / "archive")
    sim.start_all_nodes()
    a, b, c = (sim.nodes[i] for i in ids)

    # the network closes ledgers on its own cadence
    assert sim.crank_until(
        lambda: sim.have_all_externalized(3), timeout=120)

    _disconnect(c)
    seq_at_cut = c.ledger_manager.last_closed_seq()

    # A+B (threshold 2) keep closing; C starves and flips NOT_TRACKING
    # once the stuck window passes
    assert sim.crank_until(
        lambda: c.herder.state == HerderState.NOT_TRACKING, timeout=200)
    assert c.herder.lost_sync_count == 1
    assert a.ledger_manager.last_closed_seq() > seq_at_cut
    assert c.ledger_manager.last_closed_seq() <= seq_at_cut + 1

    # reconnect: the out-of-sync recovery timer asks peers for SCP state,
    # C applies the missed recent slots and resumes tracking
    make_loopback_pair(a, c)
    make_loopback_pair(b, c)
    assert sim.crank_until(
        lambda: c.herder.state == HerderState.TRACKING, timeout=200)
    target = a.ledger_manager.last_closed_seq()
    assert sim.crank_until(
        lambda: c.ledger_manager.last_closed_seq() >= target, timeout=200)
    # hashes agree at the shared height
    h_c = c.ledger_manager.last_closed_hash()
    row = a.database.execute(
        "SELECT data FROM ledgerheaders WHERE ledgerseq=?",
        (c.ledger_manager.last_closed_seq(),)).fetchone()
    from stellar_core_tpu.xdr import types as T, xdr_sha256

    assert h_c == xdr_sha256(T.LedgerHeader, T.LedgerHeader.decode(row[0]))


def test_healthy_network_never_loses_sync():
    sim, ids = _live_sim()
    sim.start_all_nodes()
    assert sim.crank_until(
        lambda: sim.have_all_externalized(4), timeout=200)
    for app in sim.nodes.values():
        assert app.herder.state == HerderState.TRACKING
        assert app.herder.lost_sync_count == 0
