"""Standalone manual-close node: the minimum end-to-end slice
(BASELINE config #1; SURVEY.md §7 stage 5).

Submit txs -> TransactionQueue -> trigger -> TxSetFrame -> SCP (self
quorum) -> externalize -> closeLedger -> state/bucket hashes advance.
"""
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.herder.tx_queue import TransactionQueue
from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

from tests.txtest import TestAccount


@pytest.fixture()
def app():
    a = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    a.start()
    return a


class NodeAccount(TestAccount):
    """TestAccount bound to an Application's ledger root."""

    def __init__(self, app, secret):
        self.app = app
        self.secret = secret
        self.account_id = secret.public_key().raw

    @property
    def ledger(self):
        class _L:
            root_txn = self.app.ledger_manager.root
        return _L()


def root_account(app) -> NodeAccount:
    return NodeAccount(app, SecretKey(app.config.network_id()))


def test_boot_creates_genesis(app):
    info = app.get_json_info()
    assert info["ledger"]["num"] == 1
    assert info["state"] == "Synced!"


def test_manual_close_advances_empty_ledgers(app):
    h0 = app.ledger_manager.last_closed_hash()
    assert app.herder.manual_close() == 2
    assert app.herder.manual_close() == 3
    assert app.ledger_manager.last_closed_hash() != h0
    # header chain links correctly
    hdr = app.ledger_manager.last_closed_header()
    assert hdr.ledgerSeq == 3


def test_submit_and_close_payment(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"node-dest"))
    env = root.tx([root.op_create_account(
        dest.public_key().raw, 10**9)])
    res = app.herder.recv_transaction(env)
    assert res == TransactionQueue.ADD_STATUS_PENDING
    assert app.herder.tx_queue.size() == 1

    app.herder.manual_close()
    # tx applied: destination exists with the balance
    with LedgerTxn(app.ledger_manager.root) as ltx:
        e = ltx.load_account(dest.public_key().raw)
        ltx.rollback()
    assert e is not None
    assert e.data.value.balance == 10**9
    # queue drained post close
    assert app.herder.tx_queue.size() == 0


def test_duplicate_submission_rejected(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"node-dup")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    assert app.herder.recv_transaction(env) == \
        TransactionQueue.ADD_STATUS_PENDING
    assert app.herder.recv_transaction(env) == \
        TransactionQueue.ADD_STATUS_DUPLICATE


def test_seq_gap_try_again_later(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"node-gap")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)],
                  seq=root.next_seq() + 5)
    assert app.herder.recv_transaction(env) == \
        TransactionQueue.ADD_STATUS_TRY_AGAIN_LATER


def test_chained_txs_one_ledger(app):
    root = root_account(app)
    a = SecretKey(sha256(b"chain-a"))
    b = SecretKey(sha256(b"chain-b"))
    seq = root.next_seq()
    env1 = root.tx([root.op_create_account(a.public_key().raw, 10**9)],
                   seq=seq)
    env2 = root.tx([root.op_create_account(b.public_key().raw, 10**9)],
                   seq=seq + 1)
    assert app.herder.recv_transaction(env1) == 0
    assert app.herder.recv_transaction(env2) == 0
    app.herder.manual_close()
    with LedgerTxn(app.ledger_manager.root) as ltx:
        assert ltx.load_account(a.public_key().raw) is not None
        assert ltx.load_account(b.public_key().raw) is not None
        ltx.rollback()


def test_bucket_list_hash_advances_and_is_deterministic():
    def run():
        app = Application(
            VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
        app.start()
        root = root_account(app)
        dest = SecretKey(sha256(b"det-dest")).public_key().raw
        env = root.tx([root.op_create_account(dest, 10**9)])
        app.herder.recv_transaction(env)
        app.herder.manual_close()
        return (app.ledger_manager.last_closed_hash(),
                app.bucket_manager.get_bucket_list_hash())

    h1, b1 = run()
    h2, b2 = run()
    assert h1 == h2 and b1 == b2
    assert b1 != b"\x00" * 32
    # header carries the bucket hash
    # (fresh app for state inspection)


def test_tx_history_rows_written(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"hist-dest")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    app.herder.recv_transaction(env)
    app.herder.manual_close()
    rows = app.database.execute(
        "SELECT ledgerseq, txindex FROM txhistory").fetchall()
    assert len(rows) == 1
    assert rows[0][0] == 2


def test_meta_stream_emitted(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"meta-dest")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    app.herder.recv_transaction(env)
    app.herder.manual_close()
    assert len(app._meta_stream) >= 1
    meta = app._meta_stream[-1].value
    assert meta.ledgerHeader.header.ledgerSeq == 2
    assert len(meta.txProcessing) == 1
    # round-trips through XDR
    b = T.LedgerCloseMeta.encode(app._meta_stream[-1])
    assert T.LedgerCloseMeta.decode(b) is not None


def test_invariants_run_during_close(app):
    # the test config enables all invariants; a normal close passes them
    root = root_account(app)
    dest = SecretKey(sha256(b"inv-dest")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    app.herder.recv_transaction(env)
    app.herder.manual_close()  # would raise InvariantDoesNotHold on breach
    assert app.invariants.invariants  # non-empty set actually ran
