"""Standalone manual-close node: the minimum end-to-end slice
(BASELINE config #1; SURVEY.md §7 stage 5).

Submit txs -> TransactionQueue -> trigger -> TxSetFrame -> SCP (self
quorum) -> externalize -> closeLedger -> state/bucket hashes advance.
"""
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.herder.tx_queue import TransactionQueue
from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

from tests.txtest import TestAccount


@pytest.fixture()
def app():
    a = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    a.start()
    return a


class NodeAccount(TestAccount):
    """TestAccount bound to an Application's ledger root."""

    def __init__(self, app, secret):
        self.app = app
        self.secret = secret
        self.account_id = secret.public_key().raw

    @property
    def ledger(self):
        class _L:
            root_txn = self.app.ledger_manager.root
        return _L()


def root_account(app) -> NodeAccount:
    return NodeAccount(app, SecretKey(app.config.network_id()))


def test_boot_creates_genesis(app):
    info = app.get_json_info()
    assert info["ledger"]["num"] == 1
    assert info["state"] == "Synced!"


def test_manual_close_advances_empty_ledgers(app):
    h0 = app.ledger_manager.last_closed_hash()
    assert app.herder.manual_close() == 2
    assert app.herder.manual_close() == 3
    assert app.ledger_manager.last_closed_hash() != h0
    # header chain links correctly
    hdr = app.ledger_manager.last_closed_header()
    assert hdr.ledgerSeq == 3


def test_submit_and_close_payment(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"node-dest"))
    env = root.tx([root.op_create_account(
        dest.public_key().raw, 10**9)])
    res = app.herder.recv_transaction(env)
    assert res == TransactionQueue.ADD_STATUS_PENDING
    assert app.herder.tx_queue.size() == 1

    app.herder.manual_close()
    # tx applied: destination exists with the balance
    with LedgerTxn(app.ledger_manager.root) as ltx:
        e = ltx.load_account(dest.public_key().raw)
        ltx.rollback()
    assert e is not None
    assert e.data.value.balance == 10**9
    # queue drained post close
    assert app.herder.tx_queue.size() == 0


def test_duplicate_submission_rejected(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"node-dup")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    assert app.herder.recv_transaction(env) == \
        TransactionQueue.ADD_STATUS_PENDING
    assert app.herder.recv_transaction(env) == \
        TransactionQueue.ADD_STATUS_DUPLICATE


def test_seq_gap_try_again_later(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"node-gap")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)],
                  seq=root.next_seq() + 5)
    assert app.herder.recv_transaction(env) == \
        TransactionQueue.ADD_STATUS_TRY_AGAIN_LATER


def test_chained_txs_one_ledger(app):
    root = root_account(app)
    a = SecretKey(sha256(b"chain-a"))
    b = SecretKey(sha256(b"chain-b"))
    seq = root.next_seq()
    env1 = root.tx([root.op_create_account(a.public_key().raw, 10**9)],
                   seq=seq)
    env2 = root.tx([root.op_create_account(b.public_key().raw, 10**9)],
                   seq=seq + 1)
    assert app.herder.recv_transaction(env1) == 0
    assert app.herder.recv_transaction(env2) == 0
    app.herder.manual_close()
    with LedgerTxn(app.ledger_manager.root) as ltx:
        assert ltx.load_account(a.public_key().raw) is not None
        assert ltx.load_account(b.public_key().raw) is not None
        ltx.rollback()


def test_bucket_list_hash_advances_and_is_deterministic():
    def run():
        app = Application(
            VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
        app.start()
        root = root_account(app)
        dest = SecretKey(sha256(b"det-dest")).public_key().raw
        env = root.tx([root.op_create_account(dest, 10**9)])
        app.herder.recv_transaction(env)
        app.herder.manual_close()
        return (app.ledger_manager.last_closed_hash(),
                app.bucket_manager.get_bucket_list_hash())

    h1, b1 = run()
    h2, b2 = run()
    assert h1 == h2 and b1 == b2
    assert b1 != b"\x00" * 32
    # header carries the bucket hash
    # (fresh app for state inspection)


def test_tx_history_rows_written(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"hist-dest")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    app.herder.recv_transaction(env)
    app.herder.manual_close()
    rows = app.database.execute(
        "SELECT ledgerseq, txindex FROM txhistory").fetchall()
    assert len(rows) == 1
    assert rows[0][0] == 2


def test_meta_stream_emitted(app):
    root = root_account(app)
    dest = SecretKey(sha256(b"meta-dest")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    app.herder.recv_transaction(env)
    app.herder.manual_close()
    assert len(app._meta_stream) >= 1
    meta = app._meta_stream[-1].value
    assert meta.ledgerHeader.header.ledgerSeq == 2
    assert len(meta.txProcessing) == 1
    # round-trips through XDR
    b = T.LedgerCloseMeta.encode(app._meta_stream[-1])
    assert T.LedgerCloseMeta.decode(b) is not None


def test_invariants_run_during_close(app):
    # the test config enables all invariants; a normal close passes them
    root = root_account(app)
    dest = SecretKey(sha256(b"inv-dest")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    app.herder.recv_transaction(env)
    app.herder.manual_close()  # would raise InvariantDoesNotHold on breach
    assert app.invariants.invariants  # non-empty set actually ran


def test_queue_limiter_evicts_cheapest(app):
    """Global mempool cap: when full, a higher-fee tx evicts the
    cheapest tail; a lower-or-equal-fee tx is refused
    (ref src/herder/TxQueueLimiter.h)."""
    root = root_account(app)
    q = app.herder.tx_queue

    # zero capacity: nothing fits and nothing can be evicted
    app.config.TRANSACTION_QUEUE_SIZE_MULTIPLIER = 0
    a = SecretKey(sha256(b"lim-a"))
    env = root.tx([root.op_create_account(a.public_key().raw, 10 ** 10)])
    assert app.herder.recv_transaction(env) == \
        TransactionQueue.ADD_STATUS_TRY_AGAIN_LATER

    # restore capacity, set up three funded accounts
    app.config.TRANSACTION_QUEUE_SIZE_MULTIPLIER = 4
    accs = []
    for i in range(3):
        acct = NodeAccount(app, SecretKey(sha256(b"lim-%d" % i)))
        env = root.tx([root.op_create_account(acct.account_id, 10 ** 10)])
        assert app.herder.recv_transaction(env) == 0
        app.herder.manual_close()
        accs.append(acct)

    # narrow the global cap to 2 ops and fill it
    q._capacity_ops = lambda: 2
    dest = root.account_id
    cheap = accs[0].tx([accs[0].op_payment(dest, 1)], fee=100)
    mid = accs[1].tx([accs[1].op_payment(dest, 1)], fee=150)
    assert app.herder.recv_transaction(cheap) == 0
    assert app.herder.recv_transaction(mid) == 0
    assert q.size() == 2

    # not pricier than the cheapest queued: refused
    low = accs[2].tx([accs[2].op_payment(dest, 1)], fee=100)
    assert app.herder.recv_transaction(low) == \
        TransactionQueue.ADD_STATUS_TRY_AGAIN_LATER
    # pricier: evicts the cheapest, which gets banned
    rich = accs[2].tx([accs[2].op_payment(dest, 1)], fee=500)
    assert app.herder.recv_transaction(rich) == 0
    assert q.size() == 2
    from stellar_core_tpu.transactions.frame import tx_frame_from_envelope
    evicted = tx_frame_from_envelope(app.config.network_id(), cheap)
    assert q.is_banned(evicted.full_hash())

    # queue now holds mid(150) + rich(500); accs[2]'s next tx must evict
    # the OTHER account's tail, never break its own chain
    tail_seq = q.accounts[accs[2].account_id].frames[-1].seq_num()
    rich2 = accs[2].tx([accs[2].op_payment(dest, 1)], fee=9999,
                       seq=tail_seq + 1)
    assert app.herder.recv_transaction(rich2) == 0
    assert q.size() == 2
    assert len(q.accounts[accs[2].account_id].frames) == 2
    evicted_mid = tx_frame_from_envelope(app.config.network_id(), mid)
    assert q.is_banned(evicted_mid.full_hash())

    # all-or-nothing: a 2-op newcomer that cannot fully fit must leave
    # the queue untouched (nothing evicted, nothing banned)
    before = q.size()
    acct4 = NodeAccount(app, SecretKey(sha256(b"lim-4")))
    env = root.tx([root.op_create_account(acct4.account_id, 10 ** 10)])
    del q._capacity_ops
    assert app.herder.recv_transaction(env) == 0
    app.herder.manual_close()
    q._capacity_ops = lambda: 2
    # queue drained by the close; refill with one cheap + try a 2-op tx
    # worth less per-op than what must be displaced
    c1 = accs[0].tx([accs[0].op_payment(dest, 1)], fee=400)
    c2 = accs[1].tx([accs[1].op_payment(dest, 1)], fee=100)
    assert app.herder.recv_transaction(c1) == 0
    assert app.herder.recv_transaction(c2) == 0
    big = acct4.tx([acct4.op_payment(dest, 1),
                    acct4.op_payment(dest, 2)], fee=400)  # 200/op
    assert app.herder.recv_transaction(big) == \
        TransactionQueue.ADD_STATUS_TRY_AGAIN_LATER
    assert q.size() == 2  # c1 + c2 both intact
    c2f = tx_frame_from_envelope(app.config.network_id(), c2)
    assert not q.is_banned(c2f.full_hash())


def test_tx_set_retention_bounded(app):
    """r13 soak finding: every close adds its proposal's TxSetFrame to
    PendingEnvelopes, and nothing pruned the map — a node under
    sustained traffic leaked one full tx set per ledger forever.  Tx
    sets now age out on the SCP slot-retention line."""
    from stellar_core_tpu.herder.herder import SCP_EXTRA_LOOKBACK_LEDGERS

    pe = app.herder.pending_envelopes
    for _ in range(20):
        app.herder.manual_close()
    window = max(SCP_EXTRA_LOOKBACK_LEDGERS,
                 app.config.MAX_SLOTS_TO_REMEMBER)
    assert len(pe.tx_sets) <= window + 1, len(pe.tx_sets)
    assert len(pe._tx_set_seen) == len(pe.tx_sets)
    assert pe.pending == {}


def test_tx_set_retention_follows_referencing_slot(app):
    """Review hardening on the r13 pruning: a tx set fetched for a
    FAR-FUTURE slot while the node is behind must survive the catchup
    closes in between — retention keys on the highest referencing
    slot, not the LCL when the set arrived (else value_externalized
    would crash on 'externalized value with unknown tx set')."""
    from types import SimpleNamespace

    from stellar_core_tpu.herder.herder import SCP_EXTRA_LOOKBACK_LEDGERS
    from stellar_core_tpu.herder.tx_set import TxSetFrame

    pe = app.herder.pending_envelopes
    lm = app.ledger_manager
    future_slot = lm.last_closed_seq() + 40

    # a pending envelope for the future slot is waiting on the fetch
    ts = TxSetFrame(app.config.network_id(), lm.last_closed_hash(), [])
    h = ts.contents_hash()
    pe.pending[h] = []  # fetch outstanding, no deliverable envelopes
    pe.add_tx_set(ts)
    pe.note_referenced(h, future_slot)  # a slot statement names it

    window = max(SCP_EXTRA_LOOKBACK_LEDGERS,
                 app.config.MAX_SLOTS_TO_REMEMBER)
    # catchup-era pruning between now and the future slot keeps it
    pe.prune_below(future_slot - 5)
    assert h in pe.tx_sets
    # ...and it ages out once the referencing slot itself is purged
    pe.prune_below(future_slot + window)
    assert h not in pe.tx_sets

    # the add path itself absorbs waiting envelopes' slots
    ts2 = TxSetFrame(app.config.network_id(), b"\x01" * 32, [])
    h2 = ts2.contents_hash()
    env = SimpleNamespace(statement=SimpleNamespace(
        slotIndex=future_slot, nodeID=None))
    pe.pending[h2] = [env]
    delivered = []
    app.herder.deliver_ready_envelope = lambda e: delivered.append(e)
    pe.add_tx_set(ts2)
    assert delivered == [env]
    assert pe._tx_set_seen[h2] == future_slot
