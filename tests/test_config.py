"""Config validation pass + TOML loading (ref Config::load rejecting
unknown fields and validateConfig's quorum-safety rules)."""
import pytest

from stellar_core_tpu.crypto import SecretKey, blake2, sha256
from stellar_core_tpu.crypto.strkey import (
    encode_ed25519_public_key, encode_ed25519_seed,
)
from stellar_core_tpu.main.config import (
    Config, ConfigError, test_config as make_config,
)


def _vkeys(n):
    return [SecretKey(sha256(b"cfg-%d" % i)).public_key().raw
            for i in range(n)]


def test_valid_config_passes():
    make_config().validate()


def test_bad_ports_rejected():
    with pytest.raises(ConfigError, match="PEER_PORT"):
        make_config(PEER_PORT=70000).validate()
    with pytest.raises(ConfigError, match="must differ"):
        make_config(PEER_PORT=11625, HTTP_PORT=11625).validate()
    # 0 / None are listener-disable sentinels, not errors
    make_config(PEER_PORT=0, HTTP_PORT=None).validate()


def test_bad_invariant_regex_rejected():
    with pytest.raises(ConfigError, match="INVARIANT_CHECKS"):
        make_config(INVARIANT_CHECKS=["("]).validate()


def test_unsafe_quorum_threshold_rejected():
    # 4 validators tolerating f=1 need threshold >= 3
    qs = {"threshold": 2, "validators": _vkeys(4)}
    with pytest.raises(ConfigError, match="unsafe"):
        make_config(QUORUM_SET=qs, UNSAFE_QUORUM=False).validate()
    make_config(QUORUM_SET=qs).validate()  # test default is UNSAFE_QUORUM
    make_config(QUORUM_SET={"threshold": 3, "validators": _vkeys(4)},
                UNSAFE_QUORUM=False).validate()


def test_failure_safety_override():
    # explicit FAILURE_SAFETY=0 makes threshold n required
    qs = {"threshold": 3, "validators": _vkeys(4)}
    with pytest.raises(ConfigError, match="unsafe"):
        make_config(QUORUM_SET=qs, FAILURE_SAFETY=0,
                    UNSAFE_QUORUM=False).validate()


def test_duplicate_validator_rejected():
    k = _vkeys(1)[0]
    with pytest.raises(ConfigError, match="duplicate"):
        make_config(QUORUM_SET={"threshold": 2,
                                "validators": [k, k]}).validate()


def test_validator_without_quorum_set_rejected():
    with pytest.raises(ConfigError, match="QUORUM_SET"):
        Config(NODE_IS_VALIDATOR=True, RUN_STANDALONE=False,
               NODE_SEED=sha256(b"x")).validate()


def test_toml_unknown_key_rejected(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text('no_such_knob = 1\n')
    with pytest.raises(ConfigError, match="unknown configuration key"):
        Config.from_toml(str(p))


def test_toml_roundtrip_validates(tmp_path):
    seed = sha256(b"toml-node")
    pub = SecretKey(seed).public_key().raw
    p = tmp_path / "node.toml"
    p.write_text(f"""
network_passphrase = "toml test net"
node_seed = "{encode_ed25519_seed(seed)}"
peer_port = 17001
http_port = 17002
max_slots_to_remember = 24
catchup_complete = true
preferred_peers = ["127.0.0.1:17003"]

[quorum_set]
threshold = 1
validators = ["{encode_ed25519_public_key(pub)}"]
""")
    cfg = Config.from_toml(str(p))
    assert cfg.MAX_SLOTS_TO_REMEMBER == 24
    assert cfg.CATCHUP_COMPLETE is True
    assert cfg.PREFERRED_PEERS == ["127.0.0.1:17003"]


def test_blake2_vectors():
    # RFC 7693 appendix A reduced to digest_size=32 is not published;
    # pin against hashlib's own blake2b-256 and check basic properties
    assert len(blake2(b"")) == 32
    assert blake2(b"abc") != blake2(b"abd")
    assert blake2(b"abc") == blake2(b"abc")
    # known blake2b-256("abc") test vector (public, widely published)
    assert blake2(b"abc").hex() == (
        "bddd813c634239723171ef3fee98579b94964e3bb1cb3e427262c8c068d52319")


class TestAutoBackends:
    def test_auto_resolves_on_application_construction(self, monkeypatch):
        """CRYPTO_BACKEND/SCP_TALLY_BACKEND default to "auto" and resolve
        via the device probe at Application construction (VERDICT r3 #2:
        a TPU-native node needs no env flags to use the TPU)."""
        from stellar_core_tpu.main import Application, test_config
        from stellar_core_tpu.main.config import Config
        from stellar_core_tpu.utils import device
        from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

        assert Config().CRYPTO_BACKEND == "auto"
        assert Config().SCP_TALLY_BACKEND == "auto"

        monkeypatch.setattr(device, "device_available", lambda **kw: True)
        cfg = test_config(CRYPTO_BACKEND="auto", SCP_TALLY_BACKEND="auto")
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        assert app.config.CRYPTO_BACKEND == "tpu"
        assert app.config.SCP_TALLY_BACKEND == "tensor"

        monkeypatch.setattr(device, "device_available", lambda **kw: False)
        cfg2 = test_config(CRYPTO_BACKEND="auto", SCP_TALLY_BACKEND="auto")
        app2 = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
        assert app2.config.CRYPTO_BACKEND == "cpu"
        assert app2.config.SCP_TALLY_BACKEND == "host"

    def test_explicit_override_respected(self):
        from stellar_core_tpu.main import Application, test_config
        from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

        cfg = test_config()  # pins cpu/host: no probe, no resolution
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        assert app.config.CRYPTO_BACKEND == "cpu"
        assert app.config.SCP_TALLY_BACKEND == "host"
