"""SCP protocol tests with a scripted fake driver.

Model: src/scp/test/SCPTests.cpp — subclass the driver (no app), script
envelope sequences from simulated peers, assert on emitted statements and
state transitions.  5-node network (v0 = local), threshold 4 ("core5").
"""
import hashlib

import pytest

from stellar_core_tpu.scp import (
    SCP, SCPDriver, ValidationLevel, Phase, make_qset, qset_hash,
)
from stellar_core_tpu.scp.statement import (
    ST_PREPARE, ST_CONFIRM, ST_EXTERNALIZE, ST_NOMINATE,
)
from stellar_core_tpu.xdr import types as T

V = [bytes([i + 1]) * 32 for i in range(5)]  # node ids v0..v4
X = hashlib.sha256(b"value-x").digest()
Y = hashlib.sha256(b"value-y").digest()
PREV = hashlib.sha256(b"prev").digest()


class TestDriver(SCPDriver):
    __test__ = False

    def __init__(self, qset):
        self.qset = qset
        self.qsets = {qset_hash(qset): qset}
        self.emitted = []
        self.externalized = {}
        self.timers = {}
        self.priority_node = V[0]

    # values
    def validate_value(self, slot_index, value, nomination):
        return ValidationLevel.FULLY_VALIDATED

    def combine_candidates(self, slot_index, candidates):
        # deterministic: lexicographically largest candidate
        return max(candidates)

    # envelopes
    def sign_envelope(self, env):
        env.signature = b"\x01" * 64

    def verify_envelope(self, env):
        return True

    def emit_envelope(self, env):
        self.emitted.append(env)

    def get_qset(self, h):
        return self.qsets.get(h)

    # deterministic leader election: priority_node always wins
    def compute_hash_node(self, slot_index, prev, is_priority, round_num,
                          node_id):
        if is_priority:
            return 2**63 if node_id == self.priority_node else 1
        return 0  # everyone is within the neighborhood

    def setup_timer(self, slot_index, timer_id, timeout, cb):
        self.timers[(slot_index, timer_id)] = (timeout, cb)

    def value_externalized(self, slot_index, value):
        self.externalized[slot_index] = value


def mk_scp():
    qset = make_qset(4, V)
    driver = TestDriver(qset)
    scp = SCP(driver, V[0], True, qset)
    return scp, driver, qset_hash(qset)


def pledges(type_, arm_value):
    return T.SCPStatementPledges.make(type_, arm_value)


def envelope(node, slot, pl):
    st = T.SCPStatement.make(
        nodeID=T.account_id(node), slotIndex=slot, pledges=pl)
    return T.SCPEnvelope.make(statement=st, signature=b"\x01" * 64)


def prepare_env(node, slot, qh, ballot, prepared=None, prepared_prime=None,
                nC=0, nH=0):
    arm = T.SCPStatementPledges.arms[ST_PREPARE][1].make(
        quorumSetHash=qh,
        ballot=T.SCPBallot.make(counter=ballot[0], value=ballot[1]),
        prepared=None if prepared is None else T.SCPBallot.make(
            counter=prepared[0], value=prepared[1]),
        preparedPrime=None if prepared_prime is None else T.SCPBallot.make(
            counter=prepared_prime[0], value=prepared_prime[1]),
        nC=nC, nH=nH,
    )
    return envelope(node, slot, pledges(ST_PREPARE, arm))


def confirm_env(node, slot, qh, ballot, nPrepared, nCommit, nH):
    arm = T.SCPStatementPledges.arms[ST_CONFIRM][1].make(
        ballot=T.SCPBallot.make(counter=ballot[0], value=ballot[1]),
        nPrepared=nPrepared, nCommit=nCommit, nH=nH, quorumSetHash=qh,
    )
    return envelope(node, slot, pledges(ST_CONFIRM, arm))


def externalize_env(node, slot, qh, commit, nH):
    arm = T.SCPStatementPledges.arms[ST_EXTERNALIZE][1].make(
        commit=T.SCPBallot.make(counter=commit[0], value=commit[1]),
        nH=nH, commitQuorumSetHash=qh,
    )
    return envelope(node, slot, pledges(ST_EXTERNALIZE, arm))


def nominate_env(node, slot, qh, votes, accepted=()):
    arm = T.SCPNomination.make(
        quorumSetHash=qh, votes=sorted(votes), accepted=sorted(accepted))
    return envelope(node, slot, pledges(ST_NOMINATE, arm))


def last_emitted(driver, type_):
    for env in reversed(driver.emitted):
        if env.statement.pledges.type == type_:
            return env
    return None


# ---------------------------------------------------------------------------


def test_ballot_protocol_prepare_to_externalize():
    scp, driver, qh = mk_scp()
    slot = scp.get_slot(1)
    b1 = (1, X)

    # start: bump to ballot (1, X); v0 emits PREPARE b=(1,X)
    assert slot.bump_state(X, True)
    env = last_emitted(driver, ST_PREPARE)
    assert env is not None
    p = env.statement.pledges.value
    assert (p.ballot.counter, p.ballot.value) == b1
    assert p.prepared is None

    # quorum votes prepare(1,X) -> v0 accepts prepared(1,X)
    for v in V[1:4]:
        scp.receive_envelope(prepare_env(v, 1, qh, b1))
    env = last_emitted(driver, ST_PREPARE)
    p = env.statement.pledges.value
    assert p.prepared is not None
    assert (p.prepared.counter, p.prepared.value) == b1
    assert p.nH == 0

    # quorum accepts prepared(1,X) -> v0 confirms prepared: h=c=(1,X)
    for v in V[1:4]:
        scp.receive_envelope(prepare_env(v, 1, qh, b1, prepared=b1))
    env = last_emitted(driver, ST_PREPARE)
    p = env.statement.pledges.value
    assert p.nH == 1 and p.nC == 1

    # quorum votes commit [1,1] -> accept commit -> phase CONFIRM
    for v in V[1:4]:
        scp.receive_envelope(
            prepare_env(v, 1, qh, b1, prepared=b1, nC=1, nH=1))
    env = last_emitted(driver, ST_CONFIRM)
    assert env is not None
    c = env.statement.pledges.value
    assert (c.ballot.counter, c.ballot.value) == b1
    assert c.nPrepared == 1 and c.nCommit == 1 and c.nH == 1
    assert slot.ballot.phase == Phase.CONFIRM

    # quorum confirms commit -> externalize
    for v in V[1:4]:
        scp.receive_envelope(confirm_env(v, 1, qh, b1, 1, 1, 1))
    assert slot.ballot.phase == Phase.EXTERNALIZE
    assert driver.externalized[1] == X
    env = last_emitted(driver, ST_EXTERNALIZE)
    e = env.statement.pledges.value
    assert (e.commit.counter, e.commit.value) == (1, X)
    assert e.nH == 1


def test_ballot_protocol_rejects_stale_statements():
    scp, driver, qh = mk_scp()
    scp.get_slot(1)
    e1 = prepare_env(V[1], 1, qh, (2, X))
    assert scp.receive_envelope(e1).name == "VALID"
    # same statement again -> stale
    assert scp.receive_envelope(e1).name == "INVALID"
    # lower ballot -> stale
    e0 = prepare_env(V[1], 1, qh, (1, X))
    assert scp.receive_envelope(e0).name == "INVALID"


def test_ballot_protocol_vblocking_bump():
    scp, driver, qh = mk_scp()
    slot = scp.get_slot(1)
    slot.bump_state(X, True)
    # v-blocking set (2 nodes of 4-of-5) ahead at counter 3 -> local bumps
    for v in V[1:3]:
        scp.receive_envelope(prepare_env(v, 1, qh, (3, X)))
    assert slot.ballot.current[0] == 3


def test_externalize_statement_short_circuit():
    # EXTERNALIZE from a quorum drives a fresh node straight to externalize
    scp, driver, qh = mk_scp()
    slot = scp.get_slot(1)
    for v in V[1:]:
        scp.receive_envelope(externalize_env(v, 1, qh, (1, X), 1))
    assert slot.ballot.phase == Phase.EXTERNALIZE
    assert driver.externalized[1] == X


def test_nomination_to_ballot():
    scp, driver, qh = mk_scp()
    slot = scp.get_slot(1)

    # v0 is leader (driver priority): nominate X -> emits NOMINATE votes=[X]
    assert scp.nominate(1, X, PREV)
    env = last_emitted(driver, ST_NOMINATE)
    assert env is not None
    assert list(env.statement.pledges.value.votes) == [X]

    # quorum votes X -> v0 accepts X -> emits NOMINATE accepted=[X]
    for v in V[1:4]:
        scp.receive_envelope(nominate_env(v, 1, qh, [X]))
    env = last_emitted(driver, ST_NOMINATE)
    assert list(env.statement.pledges.value.accepted) == [X]

    # quorum accepts X -> candidate -> combine -> ballot protocol starts
    for v in V[1:4]:
        scp.receive_envelope(nominate_env(v, 1, qh, [X], accepted=[X]))
    assert slot.nomination.candidates == {X}
    env = last_emitted(driver, ST_PREPARE)
    assert env is not None
    p = env.statement.pledges.value
    assert (p.ballot.counter, p.ballot.value) == (1, X)


def test_nomination_echoes_leader_votes():
    scp, driver, qh = mk_scp()
    driver.priority_node = V[1]  # v1 is the round leader
    slot = scp.get_slot(1)

    # nominate own value: not leader, nothing to propose yet
    scp.nominate(1, X, PREV)
    assert last_emitted(driver, ST_NOMINATE) is None

    # leader proposes Y -> v0 echoes it
    scp.receive_envelope(nominate_env(V[1], 1, qh, [Y]))
    env = last_emitted(driver, ST_NOMINATE)
    assert env is not None
    assert list(env.statement.pledges.value.votes) == [Y]
    assert slot.nomination.votes == {Y}


def test_nomination_non_leader_values_ignored():
    scp, driver, qh = mk_scp()
    driver.priority_node = V[1]
    scp.nominate(1, X, PREV)
    # non-leader v2 proposes Y: must not be echoed
    scp.receive_envelope(nominate_env(V[2], 1, qh, [Y]))
    assert last_emitted(driver, ST_NOMINATE) is None


def test_timer_armed_on_quorum_heard():
    from stellar_core_tpu.scp import BALLOT_TIMER

    scp, driver, qh = mk_scp()
    slot = scp.get_slot(1)
    slot.bump_state(X, True)
    for v in V[1:4]:
        scp.receive_envelope(prepare_env(v, 1, qh, (1, X)))
    # quorum at counter >= 1 heard -> ballot timer armed
    assert slot.ballot.heard_from_quorum
    timeout, cb = driver.timers[(1, BALLOT_TIMER)]
    assert timeout > 0 and cb is not None
    # firing the timer abandons the ballot -> counter bumps
    cb()
    assert slot.ballot.current[0] == 2


def test_bad_qset_hash_rejected():
    scp, driver, qh = mk_scp()
    unknown = b"\x77" * 32
    res = scp.receive_envelope(prepare_env(V[1], 1, unknown, (1, X)))
    assert res.name == "INVALID"
