"""Node-vitals sampler tests (ISSUE 12 tentpole part 2): bounded ring,
exact slope math, GC-pause capture via gc.callbacks (registered AND
unregistered — the callback is process-global), SLO watchdog edges,
and the vitals endpoint / Prometheus gauge surfaces.
"""
import gc
import json

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.utils.vitals import least_squares_slope


def _mk_app(**kw):
    kw.setdefault("VITALS_ENABLED", True)
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config(**kw))
    app.start()
    return app


# -- unit --------------------------------------------------------------------

def test_least_squares_slope_exact():
    assert least_squares_slope([]) == 0.0
    assert least_squares_slope([(0.0, 5.0)]) == 0.0
    # v = 3t + 1 exactly
    pts = [(float(t), 3.0 * t + 1.0) for t in range(10)]
    assert abs(least_squares_slope(pts) - 3.0) < 1e-9
    # flat series -> 0, degenerate time axis -> 0
    assert least_squares_slope([(1.0, 7.0), (2.0, 7.0)]) == 0.0
    assert least_squares_slope([(1.0, 1.0), (1.0, 9.0)]) == 0.0


def test_sample_ring_bounded_with_expected_gauges():
    app = _mk_app(VITALS_RING_SAMPLES=5)
    for _ in range(12):
        sample = app.vitals.sample_once()
    assert len(app.vitals.ring) == 5  # bounded
    assert app.vitals.samples_taken == 12
    expected = {"t", "rss_bytes", "open_fds", "threads",
                "tx_queue_depth", "tx_queue_age_max",
                "pipeline_tail_depth", "bucket_entries",
                "bucket_disk_bytes", "verify_cache_hit_rate",
                "prefetch_hit_rate", "gc_pending"}
    assert set(sample) == expected
    assert sample["rss_bytes"] > 0 and sample["threads"] >= 1
    # every numeric gauge mirrored into the registry
    for k in expected - {"t"}:
        assert app.metrics._metrics[f"vitals.{k}"].value == sample[k]
    app.graceful_stop()


def test_periodic_timer_populates_ring_on_crank():
    app = _mk_app(VITALS_PERIOD_SECONDS=0.5)
    app.clock.crank_until(lambda: len(app.vitals.ring) >= 4, timeout=10)
    assert len(app.vitals.ring) >= 4
    app.graceful_stop()


def test_gc_pause_recorded_and_callback_unregistered():
    app = _mk_app()
    n0 = len(gc.callbacks)
    gc.collect()
    h = app.metrics._metrics.get("vitals.gc.pause")
    assert h is not None and h.count >= 1
    assert app.metrics.counter("vitals.gc.gen2.collections").count >= 1
    app.graceful_stop()
    # process-global callback list back to its pre-node population
    assert len(gc.callbacks) == n0 - 1
    assert app.vitals._on_gc not in gc.callbacks


def test_jsonl_persistence(tmp_path):
    path = str(tmp_path / "vitals.jsonl")
    app = _mk_app(VITALS_JSONL=path)
    for _ in range(3):
        app.vitals.sample_once()
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 3
    row = json.loads(lines[-1])
    assert row["rss_bytes"] > 0
    app.graceful_stop()


# -- SLO watchdog ------------------------------------------------------------

def _synthetic_sample(t, rss, age=0):
    return {"t": float(t), "rss_bytes": float(rss), "open_fds": 10,
            "threads": 2, "tx_queue_depth": 0, "tx_queue_age_max": age,
            "pipeline_tail_depth": 0, "bucket_entries": 0,
            "bucket_disk_bytes": 0, "verify_cache_hit_rate": 0.0,
            "prefetch_hit_rate": 0.0, "gc_pending": 0}


def test_slo_memory_slope_breach_counts_and_warns_once_per_episode():
    app = _mk_app(SLO_MAX_MEMORY_SLOPE_MB_S=1.0)
    v = app.vitals
    # 10 MB/s synthetic growth over 2x warmup samples (the slope SLO
    # fits the newest HALF, so sustained growth must still trip it)
    for t in range(20):
        v.ring.append(_synthetic_sample(t, 100e6 + t * 10e6))
    v._check_slos(v.ring[-1])
    v._check_slos(v.ring[-1])
    assert app.metrics.counter("slo.breach.memory-slope").count == 2
    assert v._slo_active["memory-slope"] is True
    # flat series ends the episode
    v.ring.clear()
    for t in range(20):
        v.ring.append(_synthetic_sample(t, 100e6))
    v._check_slos(v.ring[-1])
    assert v._slo_active["memory-slope"] is False
    assert app.metrics.counter("slo.breach.memory-slope").count == 2
    # a startup transient followed by flat steady state must NOT breach
    # (the tail fit excludes the fill phase)
    v.ring.clear()
    for t in range(20):
        rss = 100e6 + (t * 50e6 if t < 8 else 8 * 50e6)
        v.ring.append(_synthetic_sample(t, rss))
    v._check_slos(v.ring[-1])
    assert app.metrics.counter("slo.breach.memory-slope").count == 2
    app.graceful_stop()


def test_slo_queue_age_and_close_p99():
    app = _mk_app(SLO_MAX_QUEUE_AGE=2, SLO_MAX_CLOSE_P99_SECONDS=0.001)
    v = app.vitals
    v._check_slos(_synthetic_sample(0, 1e6, age=3))
    assert app.metrics.counter("slo.breach.queue-age").count == 1
    # close-p99: needs warmup count on the ledger close timer
    t = app.metrics.timer("ledger.ledger.close")
    for _ in range(8):
        t.update(0.5)  # 500ms >> the 1ms ceiling
    v._check_slos(_synthetic_sample(1, 1e6))
    assert app.metrics.counter("slo.breach.close-p99").count == 1
    rep = v.report()
    assert rep["slo"]["breaches"]["queue-age"] == 1
    assert rep["slo"]["breaches"]["close-p99"] == 1
    app.graceful_stop()


def test_slo_disabled_by_zero_ceilings():
    app = _mk_app(SLO_MAX_MEMORY_SLOPE_MB_S=0.0,
                  SLO_MAX_CLOSE_P99_SECONDS=0.0, SLO_MAX_QUEUE_AGE=0)
    v = app.vitals
    for t in range(10):
        v.ring.append(_synthetic_sample(t, 100e6 + t * 50e6, age=9))
    v._check_slos(v.ring[-1])
    assert not v.breach_counts()
    app.graceful_stop()


# -- surfaces ----------------------------------------------------------------

def test_vitals_endpoint_roundtrip_and_prometheus_gauges():
    app = _mk_app()
    handler = CommandHandler(app)
    code, body = handler.handle("vitals", {"sample": "true"})
    assert code == 200
    rep = body["vitals"]
    assert rep["enabled"] is True and rep["samples"] >= 1
    assert rep["latest"]["rss_bytes"] > 0
    assert set(rep["slopes_per_s"]) >= {"rss_bytes", "open_fds"}
    json.dumps(body)  # serializable verbatim
    code, prom = handler.handle("metrics", {"format": "prometheus"})
    text = prom.data.decode()
    assert "# TYPE vitals_rss_bytes gauge" in text
    assert "vitals_open_fds" in text
    app.graceful_stop()


def test_vitals_disabled_is_inert_but_reportable():
    app = _mk_app(VITALS_ENABLED=False)
    assert app.vitals._timer is None and not app.vitals._gc_registered
    handler = CommandHandler(app)
    code, body = handler.handle("vitals", {})
    assert code == 200
    assert body["vitals"]["enabled"] is False
    assert body["vitals"]["samples"] == 0
    app.graceful_stop()


def test_full_collect_freezes_long_lived_state(monkeypatch):
    """ISSUE 18 satellite: after the seq%64 FULL collection the close
    path freezes survivors (adopted buckets, indexes, XDR caches) into
    the permanent generation so later gen-2 sweeps traverse only the
    delta — the SOAK_BENCH_r13 427ms-p99 fix.  Young-gen closes must
    NOT freeze, and GC_FREEZE_LONG_LIVED=False must opt out."""
    from stellar_core_tpu.ledger import ledger_manager as lm_mod

    calls = []
    app = _mk_app(DEFERRED_GC=True)
    monkeypatch.setattr(gc, "freeze", lambda: calls.append(True))
    try:
        lm = app.ledger_manager
        monkeypatch.setattr(lm_mod, "_LAST_GC_SEQ", -1)
        lm._post_close_gc(63)      # young-gen close: no freeze
        assert not calls
        lm._post_close_gc(64)      # checkpoint close: full collect + freeze
        assert len(calls) == 1
        lm._post_close_gc(64)      # same seq: process-wide dedup, no repeat
        assert len(calls) == 1
        app.config.GC_FREEZE_LONG_LIVED = False
        lm._post_close_gc(128)     # opted out: full collect, no freeze
        assert len(calls) == 1
    finally:
        app.graceful_stop()
