"""QuorumIntersectionChecker vs brute force on small graphs
(ref test model: src/herder/test/QuorumIntersectionTests.cpp — hand-built
and randomized topologies)."""
import itertools
import random

import pytest

from stellar_core_tpu.herder.quorum_intersection import (
    check_quorum_intersection, tarjan_scc, _contract_host,
)
from stellar_core_tpu.scp import local_node as LN


def ids(n):
    return [bytes([i]) * 32 for i in range(n)]


def qset(threshold, validators, inner=()):
    return LN.make_qset(threshold, validators,
                        [LN.make_qset(t, v) for t, v in inner])


def brute_force_disjoint(qmap):
    """Exhaustive reference: every subset that is a quorum, against every
    other; disjoint pair -> False."""
    nodes = sorted(qmap)
    quorums = []
    for r in range(1, len(nodes) + 1):
        for combo in itertools.combinations(nodes, r):
            s = set(combo)
            if all(LN.is_quorum_slice(qmap[n], s) for n in s):
                quorums.append(s)
    for a in quorums:
        for b in quorums:
            if not (a & b):
                return False
    return True


class TestTarjan:
    def test_two_components(self):
        a, b, c, d = ids(4)
        edges = {a: {b}, b: {a}, c: {d}, d: {c}}
        sccs = tarjan_scc([a, b, c, d], edges)
        assert sorted(map(len, sccs)) == [2, 2]

    def test_chain_is_singletons(self):
        a, b, c = ids(3)
        edges = {a: {b}, b: {c}, c: set()}
        sccs = tarjan_scc([a, b, c], edges)
        assert sorted(map(len, sccs)) == [1, 1, 1]


class TestChecker:
    def test_healthy_core4_intersects(self):
        n = ids(4)
        qmap = {x: qset(3, n) for x in n}
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok and res.scc_size == 4

    def test_split_network_detected(self):
        n = ids(6)
        left, right = n[:3], n[3:]
        qmap = {x: qset(2, left) for x in left}
        qmap.update({x: qset(2, right) for x in right})
        res = check_quorum_intersection(qmap, use_device=False)
        assert not res.ok
        q1, q2 = res.split
        assert not (q1 & q2)
        # each side really is a quorum
        assert all(LN.is_quorum_slice(qmap[x], q1) for x in q1)
        assert all(LN.is_quorum_slice(qmap[x], q2) for x in q2)

    def test_majority_threshold_boundary(self):
        # threshold n/2 exactly: two disjoint halves are quorums
        n = ids(4)
        qmap = {x: qset(2, n) for x in n}
        res = check_quorum_intersection(qmap, use_device=False)
        assert not res.ok
        # threshold n/2+1: any two quorums share a node
        qmap = {x: qset(3, n) for x in n}
        assert check_quorum_intersection(qmap, use_device=False).ok

    def test_inner_set_orgs(self):
        n = ids(6)
        orgs = [(2, n[0:2]), (2, n[2:4]), (2, n[4:6])]
        qmap = {x: qset(2, [], orgs) for x in n}
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok == brute_force_disjoint(qmap)

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_vs_brute_force(self, seed):
        rng = random.Random(seed)
        n_nodes = rng.randint(3, 7)
        nodes = ids(n_nodes)
        qmap = {}
        for x in nodes:
            k = rng.randint(1, n_nodes)
            members = rng.sample(nodes, k)
            thr = rng.randint(1, k)
            qmap[x] = qset(thr, members)
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok == brute_force_disjoint(qmap), \
            f"seed {seed}: checker {res.ok}"

    def test_device_path_matches_host(self):
        """The batched-contraction device scan agrees with the host scan
        (runs on whatever jax backend the test session has)."""
        for seed in range(4):
            rng = random.Random(100 + seed)
            n_nodes = rng.randint(3, 6)
            nodes = ids(n_nodes)
            qmap = {}
            for x in nodes:
                k = rng.randint(1, n_nodes)
                members = rng.sample(nodes, k)
                qmap[x] = qset(rng.randint(1, k), members)
            host = check_quorum_intersection(qmap, use_device=False)
            dev = check_quorum_intersection(qmap, use_device=True)
            assert host.ok == dev.ok, f"seed {100 + seed}"

    def test_contract_host_fixpoint(self):
        n = ids(4)
        qmap = {x: qset(3, n) for x in n}
        assert _contract_host(set(n), qmap) == set(n)
        assert _contract_host(set(n[:2]), qmap) == set()

    def test_herder_endpoint(self):
        from stellar_core_tpu.main import Application, test_config
        from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                          test_config())
        app.start()
        res = app.herder.check_quorum_intersection()
        assert res.ok  # standalone self-quorum trivially intersects
