"""QuorumIntersectionChecker vs brute force on small graphs
(ref test model: src/herder/test/QuorumIntersectionTests.cpp — hand-built
and randomized topologies)."""
import itertools
import random

import pytest

from stellar_core_tpu.herder.quorum_intersection import (
    check_quorum_intersection, tarjan_scc, _contract_host,
)
from stellar_core_tpu.scp import local_node as LN


def ids(n):
    return [bytes([i]) * 32 for i in range(n)]


def qset(threshold, validators, inner=()):
    return LN.make_qset(threshold, validators,
                        [LN.make_qset(t, v) for t, v in inner])


def brute_force_disjoint(qmap):
    """Exhaustive reference: every subset that is a quorum, against every
    other; disjoint pair -> False."""
    nodes = sorted(qmap)
    quorums = []
    for r in range(1, len(nodes) + 1):
        for combo in itertools.combinations(nodes, r):
            s = set(combo)
            if all(LN.is_quorum_slice(qmap[n], s) for n in s):
                quorums.append(s)
    for a in quorums:
        for b in quorums:
            if not (a & b):
                return False
    return True


class TestTarjan:
    def test_two_components(self):
        a, b, c, d = ids(4)
        edges = {a: {b}, b: {a}, c: {d}, d: {c}}
        sccs = tarjan_scc([a, b, c, d], edges)
        assert sorted(map(len, sccs)) == [2, 2]

    def test_chain_is_singletons(self):
        a, b, c = ids(3)
        edges = {a: {b}, b: {c}, c: set()}
        sccs = tarjan_scc([a, b, c], edges)
        assert sorted(map(len, sccs)) == [1, 1, 1]


class TestChecker:
    def test_healthy_core4_intersects(self):
        n = ids(4)
        qmap = {x: qset(3, n) for x in n}
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok and res.scc_size == 4

    def test_split_network_detected(self):
        n = ids(6)
        left, right = n[:3], n[3:]
        qmap = {x: qset(2, left) for x in left}
        qmap.update({x: qset(2, right) for x in right})
        res = check_quorum_intersection(qmap, use_device=False)
        assert not res.ok
        q1, q2 = res.split
        assert not (q1 & q2)
        # each side really is a quorum
        assert all(LN.is_quorum_slice(qmap[x], q1) for x in q1)
        assert all(LN.is_quorum_slice(qmap[x], q2) for x in q2)

    def test_majority_threshold_boundary(self):
        # threshold n/2 exactly: two disjoint halves are quorums
        n = ids(4)
        qmap = {x: qset(2, n) for x in n}
        res = check_quorum_intersection(qmap, use_device=False)
        assert not res.ok
        # threshold n/2+1: any two quorums share a node
        qmap = {x: qset(3, n) for x in n}
        assert check_quorum_intersection(qmap, use_device=False).ok

    def test_inner_set_orgs(self):
        n = ids(6)
        orgs = [(2, n[0:2]), (2, n[2:4]), (2, n[4:6])]
        qmap = {x: qset(2, [], orgs) for x in n}
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok == brute_force_disjoint(qmap)

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_vs_brute_force(self, seed):
        rng = random.Random(seed)
        n_nodes = rng.randint(3, 7)
        nodes = ids(n_nodes)
        qmap = {}
        for x in nodes:
            k = rng.randint(1, n_nodes)
            members = rng.sample(nodes, k)
            thr = rng.randint(1, k)
            qmap[x] = qset(thr, members)
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok == brute_force_disjoint(qmap), \
            f"seed {seed}: checker {res.ok}"

    def test_device_path_matches_host(self):
        """The batched-contraction device scan agrees with the host scan
        (runs on whatever jax backend the test session has)."""
        for seed in range(4):
            rng = random.Random(100 + seed)
            n_nodes = rng.randint(3, 6)
            nodes = ids(n_nodes)
            qmap = {}
            for x in nodes:
                k = rng.randint(1, n_nodes)
                members = rng.sample(nodes, k)
                qmap[x] = qset(rng.randint(1, k), members)
            host = check_quorum_intersection(qmap, use_device=False)
            dev = check_quorum_intersection(qmap, use_device=True)
            assert host.ok == dev.ok, f"seed {100 + seed}"

    def test_contract_host_fixpoint(self):
        n = ids(4)
        qmap = {x: qset(3, n) for x in n}
        assert _contract_host(set(n), qmap) == set(n)
        assert _contract_host(set(n[:2]), qmap) == set()

    def test_org_topology_36_nodes_scales(self):
        """12 orgs x 3 validators (the shape of the real network): the
        pruned enumeration must finish fast where the old exhaustive scan
        capped out at 20 nodes (ref MinQuorumEnumerator early exits)."""
        import time

        n = ids(36)
        orgs = [(2, n[3 * i:3 * i + 3]) for i in range(12)]
        qmap = {x: qset(9, [], orgs) for x in n}
        t0 = time.monotonic()
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok and res.scc_size == 36
        assert time.monotonic() - t0 < 30

    def test_org_topology_split_detected_at_scale(self):
        """Two halves of a 24-node network each trusting only their own
        orgs: a disjoint quorum pair must be found, not just timeout."""
        n = ids(24)
        left_orgs = [(2, n[3 * i:3 * i + 3]) for i in range(4)]
        right_orgs = [(2, n[3 * i:3 * i + 3]) for i in range(4, 8)]
        qmap = {x: qset(3, [], left_orgs) for x in n[:12]}
        qmap.update({x: qset(3, [], right_orgs) for x in n[12:]})
        res = check_quorum_intersection(qmap, use_device=False)
        assert not res.ok
        q1, q2 = res.split
        assert q1 and q2 and not (q1 & q2)
        assert all(LN.is_quorum_slice(qmap[x], q1) for x in q1)
        assert all(LN.is_quorum_slice(qmap[x], q2) for x in q2)

    @pytest.mark.parametrize("seed", range(12))
    def test_org_reduction_vs_brute_force(self, seed):
        """Randomized pure-org topologies (incl. weak orgs where
        2*t <= |org|, which two disjoint quorums may share) must agree
        with brute force — exercises the symmetric-org reduction."""
        rng = random.Random(1000 + seed)
        n_orgs = rng.randint(2, 3)
        sizes = [rng.randint(2, 3) for _ in range(n_orgs)]
        nodes = ids(sum(sizes))
        orgs, i = [], 0
        for s in sizes:
            orgs.append((rng.randint(1, s), nodes[i:i + s]))
            i += s
        thr = rng.randint(1, n_orgs)
        qmap = {x: qset(thr, [], orgs) for x in nodes}
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok == brute_force_disjoint(qmap), f"seed {1000 + seed}"
        if not res.ok:
            q1, q2 = res.split
            assert q1 and q2 and not (q1 & q2)
            assert all(LN.is_quorum_slice(qmap[x], q1) for x in q1)
            assert all(LN.is_quorum_slice(qmap[x], q2) for x in q2)

    @pytest.mark.parametrize("seed", range(8))
    def test_native_vs_python_enumerator(self, seed):
        """The native branch-and-bound and the Python/device frontier
        enumerator walk the same pruned tree and must agree (asymmetric
        qsets so the org reduction does not short-circuit)."""
        rng = random.Random(2000 + seed)
        n_nodes = rng.randint(3, 7)
        nodes = ids(n_nodes)
        qmap = {}
        for x in nodes:
            k = rng.randint(1, n_nodes)
            members = rng.sample(nodes, k)
            qmap[x] = qset(rng.randint(1, k), members)
        nat = check_quorum_intersection(qmap, use_device=False,
                                        use_native=True)
        py = check_quorum_intersection(qmap, use_device=False,
                                       use_native=False)
        assert nat.ok == py.ok == brute_force_disjoint(qmap), \
            f"seed {2000 + seed}"

    def test_interrupt_flag_aborts(self):
        """An already-set interrupt aborts the enumerator up front
        (ref QuorumIntersectionChecker::InterruptedException)."""
        import threading

        from stellar_core_tpu.herder.quorum_intersection import (
            InterruptedError_,
        )

        n = ids(8)
        qmap = {x: qset(5, n) for x in n}
        flag = threading.Event()
        flag.set()
        with pytest.raises(InterruptedError_):
            check_quorum_intersection(qmap, use_device=False,
                                      interrupt=flag)

    def test_call_budget_reports_unknown(self):
        """An exhausted max_calls budget yields ok=None/aborted=True —
        never a false verdict (asymmetric qset defeats the org
        reduction; budget of 1 call can't complete any scan)."""
        rng = random.Random(7)
        n = ids(8)
        qmap = {}
        for i, x in enumerate(n):
            members = rng.sample(n, 5 + (i % 3))
            qmap[x] = qset(3 + (i % 2), members)
        res = check_quorum_intersection(qmap, use_device=False,
                                        max_calls=1)
        assert res.ok is None and res.aborted
        res_py = check_quorum_intersection(qmap, use_device=False,
                                           use_native=False, max_calls=1)
        assert res_py.ok is None and res_py.aborted

    def test_deep_nested_qsets_use_host_walk(self):
        """>2-level quorum sets fall back to the exact recursive host
        contraction and still get the pruned enumeration."""
        n = ids(6)
        # depth-3: inner set containing an inner set
        deep_inner = LN.make_qset(1, n[4:6],
                                  [LN.make_qset(2, n[2:4])])
        q = LN.make_qset(2, n[0:2], [deep_inner])
        qmap = {x: q for x in n}
        res = check_quorum_intersection(qmap, use_device=False)
        assert res.ok == brute_force_disjoint(qmap)

    def test_herder_endpoint(self):
        from stellar_core_tpu.main import Application, test_config
        from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                          test_config())
        app.start()
        res = app.herder.check_quorum_intersection()
        assert res.ok  # standalone self-quorum trivially intersects
