"""Domain test fixtures: TestAccount + tx builders
(ref model: src/test/TestAccount.h, TxTests.cpp op builders)."""
from __future__ import annotations

from typing import List, Optional

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.ledger import LedgerTxn, LedgerTxnRoot, open_database
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.transactions.signature_checker import signature_hint
from stellar_core_tpu.xdr import types as T

NETWORK_PASSPHRASE = b"Test SDF Network ; September 2015"
NETWORK_ID = sha256(NETWORK_PASSPHRASE)

BASE_FEE = 100
BASE_RESERVE = 5000000
GENESIS_BALANCE = 10**17  # ~10B XLM in stroops


def genesis_header(ledger_seq=1, close_time=1000, protocol_version=19):
    sv = T.StellarValue.make(
        txSetHash=b"\x00" * 32, closeTime=close_time, upgrades=[],
        ext=T.StellarValue.fields[3][1].make(
            T.StellarValueType.STELLAR_VALUE_BASIC))
    return T.LedgerHeader.make(
        ledgerVersion=protocol_version,
        previousLedgerHash=b"\x00" * 32,
        scpValue=sv,
        txSetResultHash=b"\x00" * 32,
        bucketListHash=b"\x00" * 32,
        ledgerSeq=ledger_seq,
        totalCoins=10**18,
        feePool=0,
        inflationSeq=0,
        idPool=0,
        baseFee=BASE_FEE,
        baseReserve=BASE_RESERVE,
        maxTxSetSize=100,
        skipList=[b"\x00" * 32] * 4,
        ext=T.LedgerHeader.fields[14][1].make(0),
    )


class TestLedger:
    """In-memory root + genesis account.  ``protocol_version`` pins the
    genesis header's ledgerVersion so the hard-coded v19 version gates
    can be exercised at every gated protocol (ROADMAP item 3 /
    tests/test_protocol_versions.py)."""

    def __init__(self, protocol_version: int = 19):
        self.protocol_version = protocol_version
        self.db = open_database(":memory:")
        self.root_txn = LedgerTxnRoot(self.db)
        self.root_key = SecretKey(sha256(b"genesis-root"))
        hdr = genesis_header(protocol_version=protocol_version)
        with LedgerTxn(self.root_txn) as ltx:
            ltx.set_header(hdr)
            # bootstrap: write header first so put() can stamp seq
            ltx.commit()
        with LedgerTxn(self.root_txn) as ltx:
            ltx.put(U.make_account_entry(
                self.root().account_id, GENESIS_BALANCE, seq_num=0))
            ltx.commit()

    def root(self) -> "TestAccount":
        return TestAccount(self, self.root_key)

    def header(self):
        return self.root_txn.header()


class TestAccount:
    """Keypair + auto seq-num bookkeeping (ref TestAccount)."""

    def __init__(self, ledger: TestLedger, secret: SecretKey):
        self.ledger = ledger
        self.secret = secret
        self.account_id = secret.public_key().raw

    @classmethod
    def from_name(cls, ledger: TestLedger, name: str) -> "TestAccount":
        return cls(ledger, SecretKey(sha256(name.encode())))

    def network_id(self) -> bytes:
        """Override when the account signs for a non-default network."""
        return NETWORK_ID

    def loaded_seq(self) -> int:
        with LedgerTxn(self.ledger.root_txn) as ltx:
            e = ltx.load_account(self.account_id)
            ltx.rollback()
        return e.data.value.seqNum if e is not None else 0

    def next_seq(self) -> int:
        return self.loaded_seq() + 1

    # -- op builders (ref TxTests.cpp) -------------------------------------

    def op(self, body_type, body_value=None, source=None):
        return T.Operation.make(
            sourceAccount=(None if source is None
                           else T.muxed_account(source)),
            body=T.OperationBody.make(body_type, body_value))

    def op_create_account(self, dest: bytes, balance: int):
        return self.op(T.OperationType.CREATE_ACCOUNT,
                       T.CreateAccountOp.make(
                           destination=T.account_id(dest),
                           startingBalance=balance))

    def op_payment(self, dest: bytes, amount: int, asset=None):
        return self.op(T.OperationType.PAYMENT, T.PaymentOp.make(
            destination=T.muxed_account(dest),
            asset=asset or U.asset_native(),
            amount=amount))

    def op_change_trust(self, asset, limit=U.INT64_MAX):
        return self.op(T.OperationType.CHANGE_TRUST, T.ChangeTrustOp.make(
            line=T.ChangeTrustAsset.make(asset.type, asset.value),
            limit=limit))

    def op_bump_seq(self, to: int):
        return self.op(T.OperationType.BUMP_SEQUENCE,
                       T.BumpSequenceOp.make(bumpTo=to))

    def op_manage_data(self, name: bytes, value: Optional[bytes]):
        return self.op(T.OperationType.MANAGE_DATA, T.ManageDataOp.make(
            dataName=name, dataValue=value))

    def op_set_options(self, **kw):
        return self.op(T.OperationType.SET_OPTIONS, T.SetOptionsOp.make(
            inflationDest=kw.get("inflation_dest"),
            clearFlags=kw.get("clear_flags"),
            setFlags=kw.get("set_flags"),
            masterWeight=kw.get("master_weight"),
            lowThreshold=kw.get("low"),
            medThreshold=kw.get("med"),
            highThreshold=kw.get("high"),
            homeDomain=kw.get("home_domain"),
            signer=kw.get("signer")))

    def op_merge(self, dest: bytes):
        return self.op(T.OperationType.ACCOUNT_MERGE,
                       T.muxed_account(dest))

    def op_create_claimable_balance(self, asset, amount, claimants):
        """claimants: list of (dest_account_id, ClaimPredicate|None)."""
        cls = []
        for dest, pred in claimants:
            if pred is None:
                pred = T.ClaimPredicate.make(
                    T.ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL)
            cls.append(T.Claimant.make(
                T.ClaimantType.CLAIMANT_TYPE_V0,
                T.Claimant.arms[T.ClaimantType.CLAIMANT_TYPE_V0][1].make(
                    destination=T.account_id(dest), predicate=pred)))
        return self.op(T.OperationType.CREATE_CLAIMABLE_BALANCE,
                       T.CreateClaimableBalanceOp.make(
                           asset=asset, amount=amount, claimants=cls))

    def op_claim_claimable_balance(self, balance_id):
        return self.op(T.OperationType.CLAIM_CLAIMABLE_BALANCE,
                       T.ClaimClaimableBalanceOp.make(balanceID=balance_id))

    def op_clawback_claimable_balance(self, balance_id):
        return self.op(T.OperationType.CLAWBACK_CLAIMABLE_BALANCE,
                       T.ClawbackClaimableBalanceOp.make(
                           balanceID=balance_id))

    def op_begin_sponsoring(self, sponsored_id: bytes, source=None):
        return self.op(T.OperationType.BEGIN_SPONSORING_FUTURE_RESERVES,
                       T.BeginSponsoringFutureReservesOp.make(
                           sponsoredID=T.account_id(sponsored_id)),
                       source=source)

    def op_end_sponsoring(self, source=None):
        return self.op(T.OperationType.END_SPONSORING_FUTURE_RESERVES,
                       None, source=source)

    def op_revoke_sponsorship_key(self, ledger_key, source=None):
        return self.op(
            T.OperationType.REVOKE_SPONSORSHIP,
            T.RevokeSponsorshipOp.make(
                T.RevokeSponsorshipType.REVOKE_SPONSORSHIP_LEDGER_ENTRY,
                ledger_key), source=source)

    def op_revoke_sponsorship_signer(self, account_id, signer_key,
                                     source=None):
        arm = T.RevokeSponsorshipOp.arms[
            T.RevokeSponsorshipType.REVOKE_SPONSORSHIP_SIGNER][1]
        return self.op(
            T.OperationType.REVOKE_SPONSORSHIP,
            T.RevokeSponsorshipOp.make(
                T.RevokeSponsorshipType.REVOKE_SPONSORSHIP_SIGNER,
                arm.make(accountID=T.account_id(account_id),
                         signerKey=signer_key)), source=source)

    def op_change_trust_pool(self, asset_a, asset_b, limit=U.INT64_MAX):
        params = T.LiquidityPoolParameters.make(
            T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
            T.LiquidityPoolConstantProductParameters.make(
                assetA=asset_a, assetB=asset_b,
                fee=T.LIQUIDITY_POOL_FEE_V18))
        return self.op(T.OperationType.CHANGE_TRUST, T.ChangeTrustOp.make(
            line=T.ChangeTrustAsset.make(
                T.AssetType.ASSET_TYPE_POOL_SHARE, params),
            limit=limit))

    def op_pool_deposit(self, pool_id, max_a, max_b,
                        min_price=(1, 10**7), max_price=(10**7, 1)):
        return self.op(T.OperationType.LIQUIDITY_POOL_DEPOSIT,
                       T.LiquidityPoolDepositOp.make(
                           liquidityPoolID=pool_id,
                           maxAmountA=max_a, maxAmountB=max_b,
                           minPrice=T.Price.make(n=min_price[0],
                                                 d=min_price[1]),
                           maxPrice=T.Price.make(n=max_price[0],
                                                 d=max_price[1])))

    def op_pool_withdraw(self, pool_id, amount, min_a=0, min_b=0):
        return self.op(T.OperationType.LIQUIDITY_POOL_WITHDRAW,
                       T.LiquidityPoolWithdrawOp.make(
                           liquidityPoolID=pool_id, amount=amount,
                           minAmountA=min_a, minAmountB=min_b))

    def fee_bump(self, inner_env, fee: Optional[int] = None,
                 fee_source: Optional["TestAccount"] = None):
        """Wrap a v1 envelope in a fee-bump signed by fee_source (default:
        self)."""
        src = fee_source or self
        inner_ops = len(inner_env.value.tx.operations)
        fb = T.FeeBumpTransaction.make(
            feeSource=T.muxed_account(src.account_id),
            fee=fee if fee is not None else BASE_FEE * (inner_ops + 1) * 2,
            innerTx=T.FeeBumpTransaction.fields[2][1].make(
                T.EnvelopeType.ENVELOPE_TYPE_TX, inner_env.value),
            ext=T.FeeBumpTransaction.fields[3][1].make(0))
        payload = T.TransactionSignaturePayload.make(
            networkId=src.network_id(),
            taggedTransaction=T.TransactionSignaturePayload.fields[1][1]
            .make(T.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, fb))
        h = sha256(T.TransactionSignaturePayload.encode(payload))
        sig = T.DecoratedSignature.make(
            hint=signature_hint(src.secret.public_key().raw),
            signature=src.secret.sign(h))
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
            T.FeeBumpTransactionEnvelope.make(tx=fb, signatures=[sig]))

    # -- tx builder ---------------------------------------------------------

    def tx(self, ops: List, fee: Optional[int] = None,
           seq: Optional[int] = None, cond=None,
           extra_signers: List[SecretKey] = ()):
        tx = T.Transaction.make(
            sourceAccount=T.muxed_account(self.account_id),
            fee=fee if fee is not None else BASE_FEE * len(ops),
            seqNum=seq if seq is not None else self.next_seq(),
            cond=cond or T.Preconditions.make(
                T.PreconditionType.PRECOND_NONE),
            memo=T.MEMO_NONE_VALUE,
            operations=ops,
            ext=T.Transaction.fields[6][1].make(0),
        )
        payload = T.TransactionSignaturePayload.make(
            networkId=self.network_id(),
            taggedTransaction=T.TransactionSignaturePayload.fields[1][1]
            .make(T.EnvelopeType.ENVELOPE_TYPE_TX, tx))
        h = sha256(T.TransactionSignaturePayload.encode(payload))
        sigs = []
        for sk in [self.secret, *extra_signers]:
            sigs.append(T.DecoratedSignature.make(
                hint=signature_hint(sk.public_key().raw),
                signature=sk.sign(h)))
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=tx, signatures=sigs))

    # -- execution helpers ---------------------------------------------------

    def apply(self, env, expect_success=True):
        """processFeeSeqNum + apply against the root, like one-tx ledger
        close; returns (ok, result).  Handles fee-bump envelopes too.
        Each apply advances the ledger seq first, like a real close —
        starting seqnums (ledgerSeq << 32) and merge SEQNUM_TOO_FAR
        semantics depend on it."""
        from stellar_core_tpu.transactions.frame import \
            tx_frame_from_envelope

        frame = tx_frame_from_envelope(NETWORK_ID, env)
        with LedgerTxn(self.ledger.root_txn) as ltx:
            hdr = ltx.header()
            ltx.set_header(hdr._replace(ledgerSeq=hdr.ledgerSeq + 1))
            frame.process_fee_seq_num(ltx, base_fee=BASE_FEE)
            ok, result, meta = frame.apply(ltx)
            ltx.commit()
        if expect_success:
            assert ok, result
        return ok, result

    def entry(self, key):
        with LedgerTxn(self.ledger.root_txn) as ltx:
            e = ltx.load(key)
            ltx.rollback()
        return e

    def account_entry(self):
        with LedgerTxn(self.ledger.root_txn) as ltx:
            e = ltx.load_account(self.account_id)
            ltx.rollback()
        return e

    def check_valid(self, env):
        from stellar_core_tpu.transactions import TransactionFrame

        frame = TransactionFrame(NETWORK_ID, env)
        with LedgerTxn(self.ledger.root_txn) as ltx:
            res = frame.check_valid(ltx)
            ltx.rollback()
        return res

    def balance(self) -> int:
        with LedgerTxn(self.ledger.root_txn) as ltx:
            e = ltx.load_account(self.account_id)
            ltx.rollback()
        return e.data.value.balance if e is not None else -1

    def exists(self) -> bool:
        with LedgerTxn(self.ledger.root_txn) as ltx:
            e = ltx.load_account(self.account_id)
            ltx.rollback()
        return e is not None

    def create(self, name: str, balance: int) -> "TestAccount":
        """Create a funded child account."""
        child = TestAccount.from_name(self.ledger, name)
        env = self.tx([self.op_create_account(child.account_id, balance)])
        self.apply(env)
        return child
