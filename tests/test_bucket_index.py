"""BucketListDB read subsystem (ISSUE r7 tentpole): per-bucket bloom
filters + exact key/offset indexes (bucket/index.py), the bloom-first
BucketList point-read path, and the SQL-free LedgerTxnRoot read mode.
"""
import os

import numpy as np
import pytest

from stellar_core_tpu.bucket.bucket_list import Bucket, BucketList
from stellar_core_tpu.bucket.disk_bucket import DiskBucket, _sidecar_path
from stellar_core_tpu.bucket.index import (
    BloomFilter, DICT_MAX, MemBucketIndex, load_disk_index,
    read_sidecar_bloom, sidecar_bloom_offset,
)
from stellar_core_tpu.ledger.ledger_txn import (
    LedgerTxn, entry_to_key, key_bytes,
)
from stellar_core_tpu.transactions import utils as U


def _entry(i: int, balance=None):
    e = U.make_account_entry(i.to_bytes(4, "big") * 8,
                             balance if balance is not None
                             else 10_000_000 + i)
    return key_bytes(entry_to_key(e)), e


def _changes(lo, hi):
    return [(kb, e, False) for kb, e in (_entry(i) for i in range(lo, hi))]


# -- bloom filter ------------------------------------------------------------

def test_bloom_native_python_bit_identical():
    keys = [b"key-%05d" % i for i in range(3000)]
    py = BloomFilter.build(keys)  # pure-python loop
    klen = np.array([len(k) for k in keys], np.int32)
    koff = np.zeros(len(keys), np.int64)
    np.cumsum(klen[:-1], out=koff[1:])
    nat = BloomFilter.build_from_table(b"".join(keys), koff, klen)
    assert (py.words == nat.words).all()


def test_bloom_no_false_negatives_and_low_fpr():
    keys = [b"present-%06d" % i for i in range(10_000)]
    bf = BloomFilter.build(keys)
    assert all(bf.may_contain(k) for k in keys)
    misses = sum(bf.may_contain(b"absent-%06d" % i) for i in range(20_000))
    assert misses / 20_000 < 0.05  # blocked bloom at ~10.7 bits/key


def test_bloom_round_trip():
    bf = BloomFilter.build([b"a", b"bb", b"ccc"])
    rt = BloomFilter.from_bytes(bf.to_bytes())
    assert (rt.words == bf.words).all()
    assert BloomFilter.from_bytes(b"garbage") is None


# -- in-memory index ---------------------------------------------------------

def test_mem_index_exact_dict_and_bloom_shapes():
    entries = [_entry(i) for i in range(100)]
    b = Bucket([(kb, _mk_live(e)) for kb, e in entries])
    idx = b.ensure_index()
    assert isinstance(idx, MemBucketIndex)
    for kb, e in entries:
        assert idx.may_contain(kb)
        assert idx.find(b, kb) is not None
    absent = _entry(5000)[0]
    assert not idx.may_contain(absent)
    # the large shape: force the bloom+bisect branch via DICT_MAX
    keys = tuple(kb for kb, _ in entries)
    big = MemBucketIndex.__new__(MemBucketIndex)
    big._pos = None
    big.bloom = BloomFilter.build(keys)
    assert all(big.may_contain(kb) for kb in keys)
    assert big.find(b, keys[3]) is not None
    assert big.find(b, absent) is None
    assert DICT_MAX >= 1024  # small test buckets stay on the dict path


def _mk_live(e):
    from stellar_core_tpu.xdr import types as T

    return T.BucketEntry.make(T.BucketEntryType.LIVEENTRY, e)


# -- disk index --------------------------------------------------------------

@pytest.fixture()
def disk_bucket(tmp_path):
    entries = [(kb, _mk_live(e)) for kb, e in
               sorted(_entry(i) for i in range(500))]
    return DiskBucket.from_entries(str(tmp_path), iter(entries)), entries


def test_disk_bucket_index_exact_lookup(disk_bucket):
    db, entries = disk_bucket
    idx = db.ensure_index()
    assert idx is not None and idx.count == 500
    for kb, e in entries[::17]:
        assert idx.may_contain(kb)
        got = db.get(kb)
        assert got is not None and got.value == e.value
    assert db.get(_entry(10_000)[0]) is None


def test_disk_index_persisted_and_memmapped(disk_bucket, tmp_path):
    db, entries = disk_bucket
    sp = _sidecar_path(db.path)
    assert sidecar_bloom_offset(sp) is not None
    assert read_sidecar_bloom(sp) is not None
    idx = load_disk_index(sp, db.count)
    assert idx is not None
    # memmapped arrays: resident cost is just the bloom words
    assert idx.resident_bytes == idx.bloom.nbytes
    kb = entries[123][0]
    assert idx.entry_span(kb) is not None
    # reopen (restart path): index reloads from the persisted sidecar
    db2 = DiskBucket.open(db.path, db.hash())
    assert db2.ensure_index() is not None
    assert db2.get(kb).value == entries[123][1].value


def test_legacy_sidecar_upgrades_in_place(disk_bucket):
    """A PR-1 sidecar (entry table, no bloom section) is upgraded the
    first time an index is requested."""
    db, entries = disk_bucket
    sp = _sidecar_path(db.path)
    off = sidecar_bloom_offset(sp)
    with open(sp, "rb") as f:
        legacy = f.read(off)  # strip the bloom section
    with open(sp, "wb") as f:
        f.write(legacy)
    assert read_sidecar_bloom(sp) is None
    db2 = DiskBucket.open(db.path, db.hash())
    idx = db2.ensure_index()
    assert idx is not None
    assert read_sidecar_bloom(sp) is not None  # persisted back
    assert db2.get(entries[7][0]) is not None


def test_batch_lower_bound_matches_scalar(disk_bucket):
    db, entries = disk_bucket
    idx = db.ensure_index()
    probes = [kb for kb, _ in entries[::13]] + [b"\x00", b"\xff" * 40]
    batch = idx.positions_batch(probes)
    for kb, pos in zip(probes, batch):
        assert idx.position(kb) == int(pos)


# -- bucket list read path ---------------------------------------------------

def test_point_reads_probe_one_bucket_not_all(tmp_path):
    from concurrent.futures import ThreadPoolExecutor

    ex = ThreadPoolExecutor(max_workers=2)
    bl = BucketList(executor=ex, disk_dir=str(tmp_path), disk_level=2)
    seq = 1
    for batch in range(16):
        seq += 1
        bl.add_batch(seq, _changes(batch * 250, (batch + 1) * 250))
    ex.shutdown(wait=True)
    n_buckets = sum(1 for _ in bl._buckets_shallow_first())
    assert n_buckets >= 4
    base = dict(bl.stats)
    for i in range(0, 4000, 29):
        kb, e = _entry(i)
        got = bl.get_entry(kb)
        assert got is not None and got.data.value.balance == \
            e.data.value.balance
    reads = bl.stats["point_reads"] - base["point_reads"]
    probes = bl.stats["bucket_probes"] - base["bucket_probes"]
    assert probes / reads < 1.5  # bloom-first: ~1 probe per read
    # linear scan for comparison: probes grow with bucket count
    bl.index_enabled = False
    base = dict(bl.stats)
    for i in range(0, 4000, 29):
        assert bl.get_entry(_entry(i)[0]) is not None
    lin_probes = bl.stats["bucket_probes"] - base["bucket_probes"]
    lin_reads = bl.stats["point_reads"] - base["point_reads"]
    assert lin_probes / lin_reads > 2 * (probes / reads)


def test_get_entries_matches_get_entry(tmp_path):
    bl = BucketList(disk_dir=str(tmp_path), disk_level=2)
    seq = 1
    for batch in range(8):
        seq += 1
        bl.add_batch(seq, _changes(batch * 200, (batch + 1) * 200))
    probes = [_entry(i)[0] for i in range(0, 2000, 7)]
    batch_res = bl.get_entries(probes)
    for kb in probes:
        assert batch_res[kb] == bl.get_entry(kb)
    # deleted entries answer None from both paths
    kb_dead, e_dead = _entry(3)
    bl.add_batch(seq + 1, [(kb_dead, None, True)])
    assert bl.get_entry(kb_dead) is None
    assert bl.get_entries([kb_dead])[kb_dead] is None


def test_index_does_not_change_hash_chain(tmp_path):
    def run(indexed):
        bl = BucketList(disk_dir=str(tmp_path / ("i" if indexed else "n")),
                        disk_level=2)
        bl.index_enabled = indexed
        hashes = []
        for batch in range(8):
            hashes.append(bl.add_batch(
                batch + 2, _changes(batch * 100, (batch + 1) * 100)))
        for i in range(0, 800, 11):
            bl.get_entry(_entry(i)[0])
        hashes.append(bl.hash())
        return hashes

    assert run(True) == run(False)


# -- LedgerTxnRoot BucketListDB mode ----------------------------------------

def _node():
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.main.http_server import CommandHandler
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    app.start()
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "20"})
    assert code == 200, body
    app.herder.manual_close()
    return app


def test_root_point_reads_skip_sql():
    app = _node()
    root = app.ledger_manager.root
    assert root.bucket_reads_enabled
    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    kbs = [key_bytes(entry_to_key(U.make_account_entry(
        LoadGenerator.account_key(i).public_key().raw, 0)))
        for i in range(20)]
    root.clear_entry_cache()
    q0 = app.database.queries
    b0 = root.reads_from_buckets
    for kb in kbs:
        assert root.get(kb) is not None
    assert app.database.queries == q0, "point reads must not touch SQL"
    assert root.reads_from_buckets - b0 == len(kbs)
    # negative lookups are SQL-free too
    absent = key_bytes(entry_to_key(U.make_account_entry(b"\xfe" * 32, 0)))
    assert root.get(absent) is None
    assert app.database.queries == q0
    # prefetch feeds from the bucket tier in one batch
    root.clear_entry_cache()
    q0 = app.database.queries
    assert root.prefetch(kbs) == len(kbs)
    assert app.database.queries == q0


def test_root_bucket_reads_match_sql_reads():
    app = _node()
    root = app.ledger_manager.root
    rows = app.database.execute(
        "SELECT key FROM ledgerentries").fetchall()
    assert rows
    from stellar_core_tpu.xdr import types as T

    for (kb,) in rows:
        via_bucket = root.get(kb)
        row = app.database.execute(
            "SELECT entry FROM ledgerentries WHERE key = ?",
            (kb,)).fetchone()
        via_sql = T.LedgerEntry.decode(row[0])
        assert via_bucket == via_sql, kb.hex()


def test_direct_commits_visible_via_overlay():
    """Writes that bypass the close path (test-rig bulk seeding) never
    reach the buckets; the sql-ahead overlay must keep them readable."""
    app = _node()
    root = app.ledger_manager.root
    kb, e = _entry(990_001)
    with LedgerTxn(root) as ltx:
        ltx.put(e)
        ltx.commit()
    root._entry_cache.clear()  # drop the write-through cache copy only
    got = root.get(kb)
    assert got is not None
    assert root.reads_from_overlay > 0
    # after the NEXT close touches the key, buckets serve it
    assert kb in root._sql_ahead


def test_bucket_reads_gated_on_restore(tmp_path):
    """A restarted node only serves bucket reads when the restored list
    hash-verifies; without a bucket store it stays on SQL."""
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    from stellar_core_tpu.main.http_server import CommandHandler

    db = str(tmp_path / "node.db")
    bdir = str(tmp_path / "buckets")
    cfg = dict(DATABASE=db, BUCKET_DIR_PATH_REAL=bdir)
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config(**cfg))
    app.start()
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "5"})
    assert code == 200, body
    app.herder.manual_close()
    app.graceful_stop()
    app.database.close()

    # restart WITH the bucket store: hash-verified restore -> bucket reads
    app2 = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                       test_config(**cfg))
    app2.start()
    root2 = app2.ledger_manager.root
    assert root2.bucket_reads_enabled
    from stellar_core_tpu.simulation.load_generator import LoadGenerator

    kb = key_bytes(entry_to_key(U.make_account_entry(
        LoadGenerator.account_key(0).public_key().raw, 0)))
    q0 = app2.database.queries
    assert root2.get(kb) is not None  # served from restored buckets
    assert app2.database.queries == q0
    app2.graceful_stop()
    app2.database.close()

    # restart WITHOUT a bucket store configured: the bucket list cannot
    # be restored, SQL keeps serving (bucket reads stay gated off)
    app3 = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                       test_config(DATABASE=db))
    app3.start()
    root3 = app3.ledger_manager.root
    assert not root3.bucket_reads_enabled
    q0 = app3.database.queries
    assert root3.get(kb) is not None
    assert app3.database.queries > q0  # SQL path
    app3.graceful_stop()


def test_restart_keeps_sql_only_entries_readable(tmp_path):
    """The genesis root account is a direct (non-close) commit; with only
    EMPTY closes it never enters the buckets.  A restart must keep it
    readable in BucketListDB mode — the sql-ahead overlay's key list is
    persisted with the bucket state and reloaded on boot."""
    from stellar_core_tpu.crypto import SecretKey
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    cfg = dict(DATABASE=str(tmp_path / "n.db"),
               BUCKET_DIR_PATH_REAL=str(tmp_path / "b"))
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config(**cfg))
    app.start()
    root_kb = key_bytes(entry_to_key(U.make_account_entry(
        SecretKey(app.config.network_id()).public_key().raw, 0)))
    app.herder.manual_close()  # empty close: nothing folds into buckets
    app.herder.manual_close()
    assert root_kb in app.ledger_manager.root._sql_ahead
    app.graceful_stop()
    app.database.close()

    app2 = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                       test_config(**cfg))
    app2.start()
    root2 = app2.ledger_manager.root
    assert root2.bucket_reads_enabled
    assert root_kb in root2._sql_ahead
    got = root2.get(root_kb)
    assert got is not None and got.data.value.balance > 0
    # and the node can actually accept a root-sourced tx after restart
    from tests.test_standalone_node import root_account

    env = root_account(app2).tx([root_account(app2).op_create_account(
        SecretKey(b"\x11" * 32).public_key().raw, 10**9)])
    assert app2.herder.recv_transaction(env) == 0
    app2.herder.manual_close()
    app2.graceful_stop()


def test_bucketlist_db_config_off_keeps_sql():
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config(BUCKETLIST_DB=False))
    app.start()
    root = app.ledger_manager.root
    assert not root.bucket_reads_enabled
    q0 = app.database.queries
    root.get(b"\x00" * 8)
    assert app.database.queries > q0
