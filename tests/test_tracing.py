"""Flight recorder acceptance (ISSUE 4 tentpole).

(1) A deliberately slowed close (the ARTIFICIALLY_SLEEP_IN_CLOSE test
hook) trips the slow-close watchdog, which persists Chrome trace_event
JSON; the file is loaded back and validated: nested spans cover >= 95%
of the close's wall time, and the bucket worker-pool spans parent
correctly ACROSS THREADS back to the close root.
(2) /trace, /trace/summary and /metrics?format=prometheus surface the
same data over the admin API; the default /metrics JSON stays
byte-identical for existing consumers.
(3) The span ring's eviction bounds hold under concurrent writers.
"""
import json
import re
import threading

import pytest

from stellar_core_tpu.main import Application
from stellar_core_tpu.main import test_config as _test_config
from stellar_core_tpu.main.http_server import CommandHandler, RawBody
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.utils.tracing import (
    Tracer, chrome_trace, summarize_ring,
)


def make_app(**kw):
    a = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                    _test_config(**kw))
    a.start()
    return a


# ---------------------------------------------------------------------------
# the watchdog end-to-end: slow close -> persisted chrome trace
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def slow_close(tmp_path_factory):
    """Close a few normal ledgers, then one deliberately slowed SPILL
    close; return (app, persisted trace dict, CloseRecord)."""
    tmp = tmp_path_factory.mktemp("traces")
    app = make_app(SLOW_CLOSE_THRESHOLD_SECONDS=0.1,
                   TRACE_DIR=str(tmp))
    # warm up past genesis; land on an odd seq so the NEXT close (even)
    # spills level 0 and stages background merges on the worker pool
    while app.herder.manual_close() % 2 == 0:
        pass
    app.config.ARTIFICIALLY_SLEEP_IN_CLOSE_FOR_TESTING = 0.4
    slow_seq = app.herder.manual_close()
    app.config.ARTIFICIALLY_SLEEP_IN_CLOSE_FOR_TESTING = 0.0
    assert slow_seq % 2 == 0, "slow close must be a spill close"
    traces = dict(app.tracer.slow_close_traces)
    assert slow_seq in traces, "watchdog did not fire"
    with open(traces[slow_seq], encoding="utf-8") as f:
        trace = json.load(f)
    rec = app.tracer.get_close(slow_seq)
    assert rec is not None
    return app, trace, rec


def test_watchdog_persists_trace_with_root_span(slow_close):
    _, trace, rec = slow_close
    assert trace["metadata"]["ledger"] == rec.seq
    names = {ev["name"] for ev in trace["traceEvents"]}
    assert "ledger.close" in names
    assert "ledger.close.test_delay" in names
    # every event is a complete event with span identity in args
    for ev in trace["traceEvents"]:
        assert ev["ph"] == "X"
        assert ev["args"]["span_id"]


def test_slow_close_spans_cover_95_percent_of_wall_time(slow_close):
    _, trace, rec = slow_close
    events = trace["traceEvents"]
    root = next(ev for ev in events
                if ev["args"]["span_id"] == rec.root_id)
    assert root["name"] == "ledger.close"
    children_dur = sum(ev["dur"] for ev in events
                      if ev["args"]["parent_id"] == rec.root_id)
    assert children_dur >= 0.95 * root["dur"], (
        f"direct children cover {children_dur / root['dur']:.1%} "
        f"of the close")


def test_bucket_worker_spans_parent_across_threads(slow_close):
    _, trace, rec = slow_close
    events = trace["traceEvents"]
    by_id = {ev["args"]["span_id"]: ev for ev in events}
    root = by_id[rec.root_id]
    bg = [ev for ev in events
          if ev["name"] == "bucket.merge.background"]
    assert bg, "no worker-pool merge spans in the slow close's record"
    cross = [ev for ev in bg if ev["tid"] != root["tid"]]
    assert cross, "merge spans did not run on a worker thread"
    for ev in cross:
        # the parent chain must resolve WITHIN the record back to the
        # close root: worker span -> ledger.close.bucket -> ledger.close
        chain = [ev["name"]]
        cur = ev
        for _ in range(10):
            pid = cur["args"]["parent_id"]
            assert pid in by_id, f"dangling parent for chain {chain}"
            cur = by_id[pid]
            chain.append(cur["name"])
            if cur["args"]["span_id"] == rec.root_id:
                break
        assert chain[-1] == "ledger.close", chain
        assert "ledger.close.bucket" in chain, chain


def test_watchdog_logs_one_line_summary(tmp_path, caplog):
    import logging

    app = make_app(SLOW_CLOSE_THRESHOLD_SECONDS=0.05,
                   TRACE_DIR=str(tmp_path))
    app.config.ARTIFICIALLY_SLEEP_IN_CLOSE_FOR_TESTING = 0.15
    with caplog.at_level(logging.WARNING,
                         logger="stellar_core_tpu.Perf"):
        seq = app.herder.manual_close()
    msgs = [r.getMessage() for r in caplog.records
            if "slow close" in r.getMessage()]
    assert any(f"ledger {seq}" in m and "trace persisted" in m
               for m in msgs), msgs


def test_trace_view_renders_persisted_trace(slow_close):
    from tools.trace_view import render

    _, trace, _ = slow_close
    out = render(trace)
    assert "ledger.close" in out
    assert "top 10 spans by self time" in out
    assert "bucket.merge.background" in out


# ---------------------------------------------------------------------------
# admin API surface
# ---------------------------------------------------------------------------

def test_trace_endpoint_serves_chrome_json(slow_close):
    app, _, rec = slow_close
    handler = CommandHandler(app)
    status, body = handler.handle("/trace", {"ledger": str(rec.seq)})
    assert status == 200
    assert isinstance(body, RawBody)
    assert body.content_type == "application/json"
    doc = json.loads(body.data)
    assert doc["metadata"]["ledger"] == rec.seq
    assert doc["traceEvents"]
    # latest close when ledger omitted; 404 with the retained list for
    # an evicted one
    status, body = handler.handle("/trace", {})
    assert status == 200
    status, body = handler.handle("/trace", {"ledger": "999999"})
    assert status == 404
    assert "retained_closes" in body


def test_trace_summary_endpoint(slow_close):
    app, _, rec = slow_close
    handler = CommandHandler(app)
    status, body = handler.handle("/trace/summary", {"k": "5"})
    assert status == 200
    assert rec.seq in body["closes_retained"]
    tops = body["top_spans_by_self_time"]
    assert tops and len(tops) <= 5
    assert {"name", "self_ms", "count"} <= set(tops[0])
    # the deliberate delay dominates self time across the ring
    assert tops[0]["name"] == "ledger.close.test_delay"
    assert any(t["ledger"] == rec.seq
               for t in body["slow_close_traces"])


_PROM_LINE = re.compile(
    r"^(# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*(?: .*)?"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})? [-+0-9.eEinfa]+)$")


def test_metrics_prometheus_exposition(slow_close):
    app, _, _ = slow_close
    handler = CommandHandler(app)
    status, body = handler.handle("/metrics", {"format": "prometheus"})
    assert status == 200
    assert isinstance(body, RawBody)
    assert body.content_type.startswith("text/plain")
    text = body.data.decode()
    lines = [ln for ln in text.splitlines() if ln]
    assert lines
    for ln in lines:
        assert _PROM_LINE.match(ln), f"bad exposition line: {ln!r}"
    # span-derived timers (fed per close by the flight recorder) are in
    # the scrape
    assert "span_ledger_close_seconds" in text
    assert "span_ledger_close_apply_seconds" in text


def test_metrics_default_json_is_unchanged(slow_close):
    """The JSON format must stay byte-identical for existing consumers:
    same metric rendering per type, same top-level shape, no
    prometheus-related keys leaking in."""
    app, _, _ = slow_close
    handler = CommandHandler(app)
    status, body = handler.handle("/metrics", {})
    assert status == 200
    assert not isinstance(body, RawBody)
    snap = body["metrics"]
    # the pre-existing rendering contract, per metric type
    c = snap["ledger.ledger.count"]
    assert c == {"type": "counter", "count": c["count"]}
    t = snap["ledger.ledger.close"]
    assert set(t) == {"type", "count", "min", "max", "mean", "p50",
                      "p75", "p99", "rate1m"}
    assert t["type"] == "timer"
    # the ad-hoc analysis blocks are still present and JSON-typed
    for key in ("ledger.close.phases", "bucket.merge.pipeline",
                "bucket.read.path", "ledger.prefetch.hit-rate"):
        assert key in snap
    json.dumps(body)  # whole body remains JSON-serializable


# ---------------------------------------------------------------------------
# ring-buffer bounds + disabled cost
# ---------------------------------------------------------------------------

def test_pending_ring_bounded_under_concurrent_writers():
    tr = Tracer(enabled=True, max_pending=512)
    stop = threading.Event()

    def writer(i):
        k = 0
        while not stop.is_set() and k < 2000:
            with tr.span(f"w{i}.spin", k=k):
                pass
            k += 1

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    stop.set()
    assert tr.pending_count() <= 512
    # a commit drains the bounded pending set into one close record
    with tr.span("ledger.close") as root:
        pass
    rec = tr.commit_close(42, root)
    assert rec is not None
    assert len(rec.spans) <= 512 + 1
    assert tr.pending_count() == 0


def test_close_ring_evicts_oldest_closes():
    tr = Tracer(enabled=True, ring_closes=3)
    for seq in range(10, 16):
        with tr.span("ledger.close", ledger=seq) as root:
            pass
        tr.commit_close(seq, root)
    assert [r.seq for r in tr.closes()] == [13, 14, 15]
    assert tr.get_close(10) is None
    assert tr.get_close(14).seq == 14
    assert tr.get_close().seq == 15


def test_disabled_tracer_records_nothing_but_still_measures():
    tr = Tracer(enabled=False)
    with tr.span("x") as sp:
        sum(range(1000))
    assert sp.seconds > 0
    assert tr.pending_count() == 0
    assert tr.commit_close(1, sp) is None
    assert tr.current_id() is None


def test_disabled_close_still_produces_phase_breakdown():
    app = make_app(TRACING_ENABLED=False)
    app.herder.manual_close()
    phases = app.ledger_manager.last_close_phases
    assert phases["total"] > 0
    for key in ("verify", "fee", "apply", "bucket", "commit", "gc"):
        assert key in phases
    assert app.tracer.closes() == []


def test_cross_thread_parenting_via_explicit_token():
    tr = Tracer(enabled=True)
    seen = {}

    def worker(token):
        with tr.span("child.bg", parent=token) as sp:
            pass
        seen["span"] = sp

    with tr.span("root") as root:
        t = threading.Thread(target=worker, args=(tr.current_id(),))
        t.start()
        t.join()
    assert seen["span"].parent_id == root.span_id
    assert seen["span"].tid != root.tid


# ---------------------------------------------------------------------------
# analysis helpers
# ---------------------------------------------------------------------------

def test_self_time_summary_subtracts_children():
    tr = Tracer(enabled=True)
    with tr.span("outer") as outer:
        with tr.span("inner"):
            sum(range(20000))
    rec = tr.commit_close(1, outer)
    tops = summarize_ring([rec], k=2)
    by_name = {t["name"]: t for t in tops}
    assert by_name["inner"]["self_ms"] > by_name["outer"]["self_ms"]
    doc = chrome_trace(rec)
    assert len(doc["traceEvents"]) == 2


# ---------------------------------------------------------------------------
# cross-CLOSE parenting (pipelined close tail; ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def test_cross_close_token_routes_late_spans_to_their_ledger():
    """A span tagged close_seq=N that finishes AFTER commit_close(N)
    (the pipelined tail running during ledger N+1) must land in N's
    ring record, not leak into N+1's pending drain."""
    tr = Tracer(enabled=True)
    with tr.span("ledger.close", ledger=7) as root7:
        pass
    rec7 = tr.commit_close(7, root7)
    # the deferred tail finishes later, from another thread
    done = threading.Event()

    def tail(token):
        with tr.span("ledger.close.commit", parent=token, close_seq=7):
            pass
        done.set()

    t = threading.Thread(target=tail, args=(root7.span_id,))
    t.start()
    assert done.wait(5.0)
    t.join()
    names7 = [sp.name for sp in rec7.spans]
    assert "ledger.close.commit" in names7
    # ...and the NEXT close's record stays clean of N's tail
    with tr.span("ledger.close", ledger=8) as root8:
        pass
    rec8 = tr.commit_close(8, root8)
    assert "ledger.close.commit" not in [sp.name for sp in rec8.spans]
    # the routed span still parents into N's root
    tail_span = next(sp for sp in rec7.spans
                     if sp.name == "ledger.close.commit")
    assert tail_span.parent_id == root7.span_id


def test_cross_close_token_before_commit_falls_into_pending():
    """A close-tagged span finishing BEFORE its close record exists
    (fast tail) stays in the pending deque and is drained into the
    right record by commit_close."""
    tr = Tracer(enabled=True)
    with tr.span("ledger.close", ledger=3) as root3:
        with tr.span("ledger.close.commit", parent=tr.current_id(),
                     close_seq=3):
            pass
    rec3 = tr.commit_close(3, root3)
    assert "ledger.close.commit" in [sp.name for sp in rec3.spans]


def test_pipelined_tail_spans_land_in_their_close_record():
    """End to end: with the pipeline overlapping (eager drain off), the
    deferred commit/meta/gc spans of ledger N appear in trace?ledger=N
    and nowhere else — proving the overlap is observable per ledger."""
    app = make_app(PIPELINED_CLOSE=True,
                   PIPELINED_CLOSE_EAGER_DRAIN=False)
    seqs = [app.herder.manual_close() for _ in range(3)]
    app.ledger_manager.pipeline.drain()
    handler = CommandHandler(app)
    for seq in seqs:
        code, body = handler.handle("trace", {"ledger": str(seq)})
        assert code == 200
        trace = json.loads(body.data.decode())
        names = [e["name"] for e in trace["traceEvents"]]
        for tail_name in ("ledger.close.commit", "ledger.close.meta",
                          "ledger.close.gc"):
            assert names.count(tail_name) == 1, (seq, tail_name, names)
        # tail spans parent into THIS close's root
        by_id = {e["args"]["span_id"]: e for e in trace["traceEvents"]}
        root_ids = {e["args"]["span_id"] for e in trace["traceEvents"]
                    if e["name"] == "ledger.close"}
        commit_ev = next(e for e in trace["traceEvents"]
                         if e["name"] == "ledger.close.commit")
        assert commit_ev["args"]["parent_id"] in root_ids
        assert by_id[commit_ev["args"]["parent_id"]]["tid"] != \
            commit_ev["tid"], "tail must run on the worker thread"
    app.graceful_stop()
