"""Protocol-version gate smoke matrix (ROADMAP item 3; ISSUE 3
satellite): rerun a representative slice of the transaction tests at
every gated protocol version, so the repo's hard-pinned v19 version
gates are actually EXECUTED on both sides at least once per run.

``for_all_versions(v_from, v_to)`` mirrors the reference's
for_all_versions test helper (src/test/TestUtils.h): it parametrizes a
test over every gated version in the closed range.  The version list is
the set of protocols where this codebase (or an upgrade rule it
implements) changes behavior:

* v11  — last protocol where INFLATION is a supported op (< 12 gate,
         transactions/operations/account_ops.py)
* v12  — INFLATION becomes opNOT_SUPPORTED
* v17/v18 — LEDGER_UPGRADE_FLAGS validity flips (herder/upgrades.py)
* v19  — the production pin (BumpSequence v3 ext, PRECOND_V2 et al.)
"""
import pytest

from stellar_core_tpu.herder import upgrades as UP
from stellar_core_tpu.xdr import types as T

from tests.txtest import BASE_RESERVE, TestLedger

GATED_VERSIONS = (11, 12, 17, 18, 19)

TC = T.TransactionResultCode
OC = T.OperationResultCode


def for_all_versions(v_from: int, v_to: int):
    """Parametrize a test over every gated protocol version in
    [v_from, v_to] (the ``protocol_version`` fixture argument)."""
    versions = [v for v in GATED_VERSIONS if v_from <= v <= v_to]
    assert versions, f"no gated versions in [{v_from}, {v_to}]"
    return pytest.mark.parametrize(
        "protocol_version", versions,
        ids=[f"v{v}" for v in versions])


@pytest.fixture()
def ledger(protocol_version):
    return TestLedger(protocol_version=protocol_version)


@pytest.fixture()
def root(ledger):
    return ledger.root()


def op_result_code(result, i=0):
    return result.result.value[i].value.value.type


# -- representative tx slice, all versions ----------------------------------

@for_all_versions(11, 19)
def test_create_account_and_payment(root, protocol_version):
    a = root.create("alice", 10 * BASE_RESERVE)
    b = root.create("bob", 10 * BASE_RESERVE)
    start_a, start_b = a.balance(), b.balance()
    a.apply(a.tx([a.op_payment(b.account_id, 1000000)]))
    assert a.balance() == start_a - 1000000 - 100
    assert b.balance() == start_b + 1000000


@for_all_versions(11, 19)
def test_seqnum_progression_and_bad_seq(root, protocol_version):
    a = root.create("alice", 100 * BASE_RESERVE)
    start = a.loaded_seq()
    assert start == root.ledger.header().ledgerSeq << 32
    a.apply(a.tx([a.op_bump_seq(0)]))
    assert a.loaded_seq() == start + 1
    env = a.tx([a.op_bump_seq(0)], seq=start + 1)
    assert a.check_valid(env).code == TC.txBAD_SEQ


@for_all_versions(11, 19)
def test_trustline_payment_flow(root, protocol_version):
    from stellar_core_tpu.ledger import LedgerTxn
    from stellar_core_tpu.transactions import utils as U

    issuer = root.create("issuer", 100 * BASE_RESERVE)
    alice = root.create("alice2", 100 * BASE_RESERVE)
    usd = U.make_asset(b"USD", issuer.account_id)
    alice.apply(alice.tx([alice.op_change_trust(usd)]))
    issuer.apply(issuer.tx([issuer.op_payment(
        alice.account_id, 500, asset=usd)]))
    alice.apply(alice.tx([alice.op_payment(
        issuer.account_id, 200, asset=usd)]))
    with LedgerTxn(root.ledger.root_txn) as ltx:
        tl = ltx.load_trustline(alice.account_id, usd)
        ltx.rollback()
    assert tl.data.value.balance == 300


@for_all_versions(11, 19)
def test_account_merge(root, protocol_version):
    a = root.create("alice7", 100 * BASE_RESERVE)
    b = root.create("bob7", 100 * BASE_RESERVE)
    bal_a, bal_b = a.balance(), b.balance()
    a.apply(a.tx([a.op_merge(b.account_id)]))
    assert not a.exists()
    assert b.balance() == bal_b + bal_a - 100


@for_all_versions(11, 19)
def test_all_or_nothing_apply(root, protocol_version):
    from stellar_core_tpu.crypto import SecretKey, sha256

    a = root.create("alice8", 100 * BASE_RESERVE)
    b = root.create("bob8", 100 * BASE_RESERVE)
    bal_b = b.balance()
    ghost = SecretKey(sha256(b"ghost8")).public_key().raw
    ok, result = a.apply(a.tx([
        a.op_payment(b.account_id, 1000),
        a.op_payment(ghost, 1000),
    ]), expect_success=False)
    assert not ok
    assert result.result.type == TC.txFAILED
    assert b.balance() == bal_b


@for_all_versions(11, 19)
def test_dex_offer_crossing(root, protocol_version):
    """exchangeV10 semantics are version-independent in this range —
    assert the crossing actually runs at every version."""
    from stellar_core_tpu.transactions import utils as U

    issuer = root.create("issuerX", 100 * BASE_RESERVE)
    alice = root.create("aliceX", 100 * BASE_RESERVE)
    bob = root.create("bobX", 100 * BASE_RESERVE)
    usd = U.make_asset(b"USD", issuer.account_id)
    for who in (alice, bob):
        who.apply(who.tx([who.op_change_trust(usd)]))
    issuer.apply(issuer.tx([issuer.op_payment(
        bob.account_id, 10_000, asset=usd)]))
    # bob sells 1000 USD at 1:1 for XLM; alice buys with XLM
    sell = T.ManageSellOfferOp.make(
        selling=usd, buying=U.asset_native(), amount=1000,
        price=T.Price.make(n=1, d=1), offerID=0)
    bob.apply(bob.tx([bob.op(T.OperationType.MANAGE_SELL_OFFER, sell)]))
    buy = T.ManageSellOfferOp.make(
        selling=U.asset_native(), buying=usd, amount=600,
        price=T.Price.make(n=1, d=1), offerID=0)
    ok, result = alice.apply(alice.tx([
        alice.op(T.OperationType.MANAGE_SELL_OFFER, buy)]))
    assert ok
    claimed = result.result.value[0].value.value.value.offersClaimed
    assert sum(c.value.amountBought for c in claimed) == 600


# -- the gates themselves ----------------------------------------------------

@for_all_versions(11, 11)
def test_inflation_supported_before_v12(root, protocol_version):
    ok, result = root.apply(
        root.tx([root.op(T.OperationType.INFLATION)]),
        expect_success=False)
    # supported: reaches do_apply (NOT_TIME), not opNOT_SUPPORTED
    assert result.result.value[0].type == OC.opINNER
    assert op_result_code(result) == \
        T.InflationResultCode.INFLATION_NOT_TIME


@for_all_versions(12, 19)
def test_inflation_not_supported_from_v12(root, protocol_version):
    ok, result = root.apply(
        root.tx([root.op(T.OperationType.INFLATION)]),
        expect_success=False)
    assert not ok
    assert result.result.value[0].type == OC.opNOT_SUPPORTED


@for_all_versions(11, 19)
def test_flags_upgrade_gate(ledger, protocol_version):
    """LEDGER_UPGRADE_FLAGS is valid-for-apply only at v18+
    (herder/upgrades.py mirrors Upgrades::isValidForApply)."""
    from stellar_core_tpu.main.config import test_config

    header = ledger.header()
    assert header.ledgerVersion == protocol_version
    raw = T.LedgerUpgrade.encode(T.LedgerUpgrade.make(
        T.LedgerUpgradeType.LEDGER_UPGRADE_FLAGS, 0))
    cfg = test_config()
    validity, _ = UP.is_valid_for_apply(raw, header, cfg)
    if protocol_version >= 18:
        assert validity == UP.VALID
    else:
        assert validity == UP.INVALID


@for_all_versions(11, 19)
def test_version_upgrade_gate(ledger, protocol_version):
    """A VERSION upgrade must move forward and stay within the node's
    supported protocol."""
    from stellar_core_tpu.main.config import test_config

    header = ledger.header()
    cfg = test_config()
    for target, want_valid in (
            (protocol_version, False),        # no-op: not an upgrade
            (protocol_version - 1, False),    # downgrade
            (19, protocol_version < 19),      # forward within support
            (20, False)):                     # beyond supported
        raw = T.LedgerUpgrade.encode(T.LedgerUpgrade.make(
            T.LedgerUpgradeType.LEDGER_UPGRADE_VERSION, target))
        validity, _ = UP.is_valid_for_apply(raw, header, cfg)
        assert (validity == UP.VALID) == want_valid, \
            (protocol_version, target, validity)
