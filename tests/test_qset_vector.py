"""Differential tests for the vectorized quorum evaluator
(scp/qset_vector.py): bitwise-identical verdicts against the scalar
oracle, the deep-qset fallback, cross-call memo sharing, and the kill
switch."""
import random

import pytest

from stellar_core_tpu.scp import local_node as LN
from stellar_core_tpu.scp import qset_vector


def _ids(n):
    return [bytes([i]) * 32 for i in range(n)]


@pytest.fixture(autouse=True)
def _vector_state():
    """Force the vector path on (min 2 nodes) and restore everything."""
    qset_vector.clear_caches()
    old_enabled = qset_vector.set_enabled(True)
    old_min = qset_vector.set_min_nodes(2)
    yield
    qset_vector.set_enabled(old_enabled)
    qset_vector.set_min_nodes(old_min)
    qset_vector.clear_caches()


def _scalar_is_quorum(members, get_qset, local_qset=None):
    old = qset_vector.set_enabled(False)
    try:
        return LN.is_quorum(members, get_qset, local_qset=local_qset)
    finally:
        qset_vector.set_enabled(old)


def _random_qset(rng, ids):
    """A random 2-level qset over a subset of ids."""
    pool = rng.sample(ids, rng.randint(2, len(ids)))
    n_inner = rng.randint(0, 2)
    inner = []
    for _ in range(n_inner):
        members = rng.sample(ids, rng.randint(1, 4))
        inner.append(LN.make_qset(
            rng.randint(1, len(members)), members))
    split = rng.randint(0, len(pool))
    top = pool[:split]
    thr = rng.randint(1, max(1, len(top) + len(inner)))
    return LN.make_qset(thr, top, inner)


def test_differential_random_qsets():
    """400 random member-set/qset-map trials: the vector path must be
    verdict-identical to the scalar oracle, including unknown qsets
    and a local_qset check."""
    rng = random.Random(1234)
    ids = _ids(16)
    mismatches = 0
    for trial in range(400):
        qsets = {}
        shared = _random_qset(rng, ids)
        for nid in ids:
            if rng.random() < 0.1:
                qsets[nid] = None  # unknown qset
            elif rng.random() < 0.6:
                qsets[nid] = shared  # realistic: most nodes share one
            else:
                qsets[nid] = _random_qset(rng, ids)
        members = set(rng.sample(ids, rng.randint(2, len(ids))))
        local = shared if rng.random() < 0.5 else None
        get_qset = qsets.get
        want = _scalar_is_quorum(members, get_qset, local)
        got = LN.is_quorum(members, get_qset, local_qset=local)
        assert got == want, (
            f"trial {trial}: vector={got} scalar={want}")
    assert mismatches == 0
    # the vector path actually ran (not everything fell back)
    assert qset_vector.stats["verdict_misses"] > 0


def test_deep_qset_falls_back_to_scalar():
    """A 3-level qset is outside the vectorized shape: the fast path
    must return None (fallback), and is_quorum must still be right."""
    ids = _ids(6)
    innermost = LN.make_qset(1, ids[4:6])
    inner = LN.make_qset(1, [], [innermost])
    deep = LN.make_qset(2, ids[0:2], [inner])
    get_qset = {nid: deep for nid in ids}.get
    assert qset_vector.vector_is_quorum(
        set(ids), get_qset, None) is None
    assert LN.is_quorum(set(ids), get_qset) == \
        _scalar_is_quorum(set(ids), get_qset)
    assert qset_vector.stats["fallback_deep"] > 0


def test_memo_sharing_across_calls():
    """Two nodes evaluating the same vote set reuse one verdict; a
    structurally-equal but distinct qset object reuses the same pack
    (the cross-node sharing the module exists for)."""
    ids = _ids(8)
    q1 = LN.make_qset(5, ids)
    q2 = LN.make_qset(5, ids)  # equal structure, different object
    members = set(ids[:6])
    LN.is_quorum(members, {nid: q1 for nid in ids}.get)
    misses0 = qset_vector.stats["verdict_misses"]
    packs0 = qset_vector.stats["pack_builds"]
    hits0 = qset_vector.stats["verdict_hits"]
    LN.is_quorum(members, {nid: q2 for nid in ids}.get)
    assert qset_vector.stats["verdict_hits"] == hits0 + 1
    assert qset_vector.stats["verdict_misses"] == misses0
    assert qset_vector.stats["pack_builds"] == packs0


def test_kill_switch_and_min_nodes():
    ids = _ids(8)
    q = LN.make_qset(5, ids)
    get_qset = {nid: q for nid in ids}.get
    members = set(ids)
    qset_vector.set_enabled(False)
    calls0 = qset_vector.stats["calls"]
    assert LN.is_quorum(members, get_qset) is True
    assert qset_vector.stats["calls"] == calls0  # never entered
    qset_vector.set_enabled(True)
    qset_vector.set_min_nodes(100)  # small sets stay scalar
    assert LN.is_quorum(members, get_qset) is True
    assert qset_vector.stats["calls"] == calls0
    qset_vector.set_min_nodes(2)
    assert LN.is_quorum(members, get_qset) is True
    assert qset_vector.stats["calls"] == calls0 + 1


def test_tiered_topology_shape():
    """The hierarchical_quorum shape (orgs as inner sets, empty top
    validators) — the fleet fuzzing workload — stays exact at 50
    validators, including v-blocking-style partial member sets."""
    rng = random.Random(7)
    n_orgs, per_org = 10, 5
    ids = _ids(n_orgs * per_org)
    orgs = [ids[o * per_org:(o + 1) * per_org] for o in range(n_orgs)]
    inner = [LN.make_qset(per_org - (per_org - 1) // 3, members)
             for members in orgs]
    qset = LN.make_qset(n_orgs - (n_orgs - 1) // 3, [], inner)
    get_qset = {nid: qset for nid in ids}.get
    for _ in range(25):
        members = set(rng.sample(ids, rng.randint(10, len(ids))))
        want = _scalar_is_quorum(members, get_qset, qset)
        got = LN.is_quorum(members, get_qset, local_qset=qset)
        assert got == want
