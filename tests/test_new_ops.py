"""Claimable balances, sponsorship, liquidity pools, fee-bump — the 8 ops
added in round 3 plus FeeBumpTransactionFrame (ref test models:
src/transactions/test/{ClaimableBalanceTests,RevokeSponsorshipTests,
LiquidityPoolDepositTests,FeeBumpTransactionTests}.cpp)."""
import pytest

from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.transactions import liquidity_pool as LP
from stellar_core_tpu.transactions import sponsorship as SP
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.xdr import types as T

from .txtest import BASE_RESERVE, TestLedger

TC = T.TransactionResultCode


@pytest.fixture
def ledger():
    return TestLedger()


def op_code(result, i=0):
    """Per-op inner result code of op i."""
    return result.result.value[i].value.value.type


def cb_key(balance_id):
    return T.LedgerKey.make(
        T.LedgerEntryType.CLAIMABLE_BALANCE,
        T.LedgerKey.arms[T.LedgerEntryType.CLAIMABLE_BALANCE][1].make(
            balanceID=balance_id))


# ---------------------------------------------------------------------------
# claimable balances
# ---------------------------------------------------------------------------

class TestClaimableBalance:
    def test_create_claim_native(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            U.asset_native(), 10**8, [(b.account_id, None)])]))
        bid = res.result.value[0].value.value.value  # balanceID
        # creator sponsors the entry reserve
        acc = a.account_entry().data.value
        assert U.num_sponsoring(acc) == 1
        entry = a.entry(cb_key(bid))
        assert entry is not None
        assert SP.entry_sponsor(entry) == a.account_id

        before = b.balance()
        b.apply(b.tx([b.op_claim_claimable_balance(bid)]))
        assert b.balance() == before + 10**8 - 100  # minus fee
        assert a.entry(cb_key(bid)) is None
        assert U.num_sponsoring(a.account_entry().data.value) == 0

    def test_claim_wrong_account(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        c = root.create("carol", 10**9)
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            U.asset_native(), 10**8, [(b.account_id, None)])]))
        bid = res.result.value[0].value.value.value
        ok, res = c.apply(c.tx([c.op_claim_claimable_balance(bid)]),
                          expect_success=False)
        assert not ok
        C = T.ClaimClaimableBalanceResultCode
        assert op_code(res) == C.CLAIM_CLAIMABLE_BALANCE_CANNOT_CLAIM

    def test_predicate_absolute_time(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        # expires before the ledger close time (1000): not claimable
        pred = T.ClaimPredicate.make(
            T.ClaimPredicateType.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME, 500)
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            U.asset_native(), 10**8, [(b.account_id, pred)])]))
        bid = res.result.value[0].value.value.value
        ok, res = b.apply(b.tx([b.op_claim_claimable_balance(bid)]),
                          expect_success=False)
        C = T.ClaimClaimableBalanceResultCode
        assert op_code(res) == C.CLAIM_CLAIMABLE_BALANCE_CANNOT_CLAIM

    def test_predicate_relative_becomes_absolute(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        pred = T.ClaimPredicate.make(
            T.ClaimPredicateType.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME, 600)
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            U.asset_native(), 10**8, [(b.account_id, pred)])]))
        bid = res.result.value[0].value.value.value
        entry = a.entry(cb_key(bid))
        stored = entry.data.value.claimants[0].value.predicate
        PT = T.ClaimPredicateType
        assert stored.type == PT.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME
        assert stored.value == 1000 + 600  # close_time + rel
        b.apply(b.tx([b.op_claim_claimable_balance(bid)]))

    def test_create_credit_and_clawback(self, ledger):
        root = ledger.root()
        issuer = root.create("issuer", 10**9)
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        # enable clawback on the issuer account
        issuer.apply(issuer.tx([issuer.op_set_options(
            set_flags=T.AUTH_CLAWBACK_ENABLED_FLAG
            | T.AUTH_REVOCABLE_FLAG)]))
        usd = U.make_asset(b"USD", issuer.account_id)
        a.apply(a.tx([a.op_change_trust(usd)]))
        b.apply(b.tx([b.op_change_trust(usd)]))
        issuer.apply(issuer.tx([issuer.op_payment(a.account_id, 10**7,
                                                  usd)]))
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            usd, 10**6, [(b.account_id, None)])]))
        bid = res.result.value[0].value.value.value
        entry = a.entry(cb_key(bid))
        cb = entry.data.value
        assert cb.ext.type == 1  # clawback-enabled ext
        ok, res = issuer.apply(issuer.tx(
            [issuer.op_clawback_claimable_balance(bid)]))
        assert a.entry(cb_key(bid)) is None

    def test_create_requires_trust_and_funds(self, ledger):
        root = ledger.root()
        issuer = root.create("issuer", 10**9)
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        usd = U.make_asset(b"USD", issuer.account_id)
        C = T.CreateClaimableBalanceResultCode
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            usd, 10**6, [(b.account_id, None)])]), expect_success=False)
        assert op_code(res) == C.CREATE_CLAIMABLE_BALANCE_NO_TRUST
        a.apply(a.tx([a.op_change_trust(usd)]))
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            usd, 10**6, [(b.account_id, None)])]), expect_success=False)
        assert op_code(res) == C.CREATE_CLAIMABLE_BALANCE_UNDERFUNDED

    def test_malformed(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        C = T.CreateClaimableBalanceResultCode
        # duplicate claimants
        ok, res = a.apply(a.tx([a.op_create_claimable_balance(
            U.asset_native(), 10**6,
            [(a.account_id, None), (a.account_id, None)])]),
            expect_success=False)
        assert op_code(res) == C.CREATE_CLAIMABLE_BALANCE_MALFORMED


# ---------------------------------------------------------------------------
# sponsorship
# ---------------------------------------------------------------------------

def account_key(account_id):
    return T.LedgerKey.make(
        T.LedgerEntryType.ACCOUNT,
        T.LedgerKey.arms[T.LedgerEntryType.ACCOUNT][1].make(
            accountID=T.account_id(account_id)))


def trustline_key(account_id, asset):
    return T.LedgerKey.make(
        T.LedgerEntryType.TRUSTLINE,
        T.LedgerKey.arms[T.LedgerEntryType.TRUSTLINE][1].make(
            accountID=T.account_id(account_id),
            asset=U.to_trustline_asset(asset)))


class TestSponsorship:
    def _sponsored_create(self, ledger, balance=0):
        """Sponsor (A) pays the reserve for a brand-new account (C)."""
        root = ledger.root()
        a = root.create("sponsor", 10**9)
        from stellar_core_tpu.crypto import SecretKey, sha256
        from .txtest import TestAccount

        c = TestAccount(ledger, SecretKey(sha256(b"newacct")))
        env = a.tx([
            a.op_begin_sponsoring(c.account_id),
            a.op_create_account(c.account_id, balance),
            a.op_end_sponsoring(source=c.account_id),
        ], extra_signers=[c.secret])
        a.apply(env)
        return root, a, c

    def test_sponsored_account_creation_zero_balance(self, ledger):
        root, a, c = self._sponsored_create(ledger, balance=0)
        assert c.exists()
        acc = c.account_entry()
        assert U.num_sponsored(acc.data.value) == 2
        assert SP.entry_sponsor(acc) == a.account_id
        assert U.num_sponsoring(a.account_entry().data.value) == 2

    def test_unclosed_sponsorship_fails_tx(self, ledger):
        root = ledger.root()
        a = root.create("sponsor", 10**9)
        b = root.create("other", 10**9)
        env = a.tx([a.op_begin_sponsoring(b.account_id)])
        ok, res = a.apply(env, expect_success=False)
        assert not ok
        assert res.result.type == TC.txBAD_SPONSORSHIP

    def test_end_without_begin(self, ledger):
        root = ledger.root()
        a = root.create("acc", 10**9)
        ok, res = a.apply(a.tx([a.op_end_sponsoring()]),
                          expect_success=False)
        C = T.EndSponsoringFutureReservesResultCode
        assert op_code(res) == C.END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED

    def test_sponsored_trustline_and_revoke_remove(self, ledger):
        root = ledger.root()
        sponsor = root.create("sponsor", 10**9)
        issuer = root.create("issuer", 10**9)
        a = root.create("alice", 10**9)
        usd = U.make_asset(b"USD", issuer.account_id)
        env = sponsor.tx([
            sponsor.op_begin_sponsoring(a.account_id),
            a.op_change_trust(usd, source=None) if False else
            sponsor.op(T.OperationType.CHANGE_TRUST, T.ChangeTrustOp.make(
                line=T.ChangeTrustAsset.make(usd.type, usd.value),
                limit=U.INT64_MAX), source=a.account_id),
            sponsor.op_end_sponsoring(source=a.account_id),
        ], extra_signers=[a.secret])
        sponsor.apply(env)
        tl = a.entry(trustline_key(a.account_id, usd))
        assert SP.entry_sponsor(tl) == sponsor.account_id
        assert U.num_sponsoring(sponsor.account_entry().data.value) == 1
        assert U.num_sponsored(a.account_entry().data.value) == 1

        # sponsor revokes (removes) the sponsorship: alice takes the reserve
        ok, res = sponsor.apply(sponsor.tx([
            sponsor.op_revoke_sponsorship_key(
                trustline_key(a.account_id, usd))]))
        tl = a.entry(trustline_key(a.account_id, usd))
        assert SP.entry_sponsor(tl) is None
        assert U.num_sponsoring(sponsor.account_entry().data.value) == 0
        assert U.num_sponsored(a.account_entry().data.value) == 0

    def test_revoke_not_sponsor(self, ledger):
        root = ledger.root()
        issuer = root.create("issuer", 10**9)
        a = root.create("alice", 10**9)
        b = root.create("mallory", 10**9)
        usd = U.make_asset(b"USD", issuer.account_id)
        a.apply(a.tx([a.op_change_trust(usd)]))
        ok, res = b.apply(b.tx([b.op_revoke_sponsorship_key(
            trustline_key(a.account_id, usd))]), expect_success=False)
        C = T.RevokeSponsorshipResultCode
        assert op_code(res) == C.REVOKE_SPONSORSHIP_NOT_SPONSOR

    def test_sponsored_signer(self, ledger):
        root = ledger.root()
        sponsor = root.create("sponsor", 10**9)
        a = root.create("alice", 10**9)
        skey = T.SignerKey.make(T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                                b"\x42" * 32)
        env = sponsor.tx([
            sponsor.op_begin_sponsoring(a.account_id),
            sponsor.op(T.OperationType.SET_OPTIONS, T.SetOptionsOp.make(
                inflationDest=None, clearFlags=None, setFlags=None,
                masterWeight=None, lowThreshold=None, medThreshold=None,
                highThreshold=None, homeDomain=None,
                signer=T.Signer.make(key=skey, weight=1)),
                source=a.account_id),
            sponsor.op_end_sponsoring(source=a.account_id),
        ], extra_signers=[a.secret])
        sponsor.apply(env)
        acc = a.account_entry().data.value
        assert U.num_sponsored(acc) == 1
        assert U.num_sponsoring(sponsor.account_entry().data.value) == 1
        sids = SP.signer_sponsoring_ids(acc)
        assert len(sids) == 1 and sids[0].value == sponsor.account_id

        # removing the signer releases the sponsor's reserve
        a.apply(a.tx([a.op_set_options(
            signer=T.Signer.make(key=skey, weight=0))]))
        assert U.num_sponsoring(sponsor.account_entry().data.value) == 0
        assert U.num_sponsored(a.account_entry().data.value) == 0

    def test_begin_recursive_rejected(self, ledger):
        root = ledger.root()
        a = root.create("aa", 10**9)
        b = root.create("bb", 10**9)
        c = root.create("cc", 10**9)
        # a sponsors b; while active, b tries to sponsor c => RECURSIVE
        env = a.tx([
            a.op_begin_sponsoring(b.account_id),
            a.op_begin_sponsoring(c.account_id, source=b.account_id),
            a.op_end_sponsoring(source=b.account_id),
        ], extra_signers=[b.secret])
        ok, res = a.apply(env, expect_success=False)
        C = T.BeginSponsoringFutureReservesResultCode
        assert op_code(res, 1) == \
            C.BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE


# ---------------------------------------------------------------------------
# liquidity pools
# ---------------------------------------------------------------------------

class TestLiquidityPool:
    def _setup_pool(self, ledger):
        root = ledger.root()
        issuer = root.create("issuer", 10**10)
        a = root.create("alice", 10**10)
        usd = U.make_asset(b"USD", issuer.account_id)
        a.apply(a.tx([a.op_change_trust(usd)]))
        issuer.apply(issuer.tx([issuer.op_payment(a.account_id, 10**9,
                                                  usd)]))
        xlm = U.asset_native()
        params = T.LiquidityPoolParameters.make(
            T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
            T.LiquidityPoolConstantProductParameters.make(
                assetA=xlm, assetB=usd, fee=T.LIQUIDITY_POOL_FEE_V18))
        pool_id = LP.pool_id_from_params(params)
        a.apply(a.tx([a.op_change_trust_pool(xlm, usd)]))
        return root, issuer, a, usd, pool_id

    def test_pool_trustline_creates_pool(self, ledger):
        root, issuer, a, usd, pool_id = self._setup_pool(ledger)
        pool = a.entry(LP.pool_key(pool_id))
        assert pool is not None
        cp = LP.constant_product(pool)
        assert cp.poolSharesTrustLineCount == 1
        assert cp.reserveA == 0 and cp.reserveB == 0
        # pool-share trustline costs 2 subentries
        assert a.account_entry().data.value.numSubEntries == 3  # usd + 2
        # underlying USD trustline got a use count
        tl = a.entry(trustline_key(a.account_id, usd))
        assert LP.tl_pool_use_count(tl.data.value) == 1

    def test_deposit_withdraw_round_trip(self, ledger):
        root, issuer, a, usd, pool_id = self._setup_pool(ledger)
        a.apply(a.tx([a.op_pool_deposit(pool_id, 4 * 10**6, 10**6)]))
        pool = a.entry(LP.pool_key(pool_id))
        cp = LP.constant_product(pool)
        assert cp.reserveA == 4 * 10**6 and cp.reserveB == 10**6
        assert cp.totalPoolShares == 2 * 10**6  # sqrt(4e6 * 1e6)
        tl_pool = a.entry(LP.pool_share_trustline_key(a.account_id,
                                                      pool_id))
        assert tl_pool.data.value.balance == 2 * 10**6

        # second deposit follows the existing ratio
        a.apply(a.tx([a.op_pool_deposit(pool_id, 4 * 10**6, 10**6)]))
        cp = LP.constant_product(a.entry(LP.pool_key(pool_id)))
        assert cp.reserveA == 8 * 10**6 and cp.reserveB == 2 * 10**6
        assert cp.totalPoolShares == 4 * 10**6

        # withdraw half
        a.apply(a.tx([a.op_pool_withdraw(pool_id, 2 * 10**6)]))
        cp = LP.constant_product(a.entry(LP.pool_key(pool_id)))
        assert cp.reserveA == 4 * 10**6 and cp.reserveB == 10**6
        assert cp.totalPoolShares == 2 * 10**6

    def test_deposit_bad_price(self, ledger):
        root, issuer, a, usd, pool_id = self._setup_pool(ledger)
        C = T.LiquidityPoolDepositResultCode
        ok, res = a.apply(a.tx([a.op_pool_deposit(
            pool_id, 4 * 10**6, 10**6,
            min_price=(5, 1), max_price=(6, 1))]), expect_success=False)
        assert op_code(res) == C.LIQUIDITY_POOL_DEPOSIT_BAD_PRICE

    def test_delete_pool_trustline_deletes_pool(self, ledger):
        root, issuer, a, usd, pool_id = self._setup_pool(ledger)
        xlm = U.asset_native()
        a.apply(a.tx([a.op_change_trust_pool(xlm, usd, limit=0)]))
        assert a.entry(LP.pool_key(pool_id)) is None
        tl = a.entry(trustline_key(a.account_id, usd))
        assert LP.tl_pool_use_count(tl.data.value) == 0
        assert a.account_entry().data.value.numSubEntries == 1

    def test_cannot_delete_used_trustline(self, ledger):
        root, issuer, a, usd, pool_id = self._setup_pool(ledger)
        C = T.ChangeTrustResultCode
        # zero the USD balance so the only deletion blocker is the pool's
        # liquidityPoolUseCount
        a.apply(a.tx([a.op_payment(issuer.account_id, 10**9, usd)]))
        ok, res = a.apply(a.tx([a.op_change_trust(usd, limit=0)]),
                          expect_success=False)
        assert op_code(res) == C.CHANGE_TRUST_CANNOT_DELETE

    def test_swap_math_invariants(self):
        # constant-product: k never decreases across a swap
        for (ra, rb, amt) in [(10**7, 10**7, 10**5), (10**9, 10**5, 10**4),
                              (3, 10**12, 1)]:
            out = LP.swap_out_given_in(ra, rb, amt, 30)
            if out is not None:
                assert (ra + amt) * (rb - out) >= ra * rb
            back = LP.swap_in_given_out(ra, rb, 10**3, 30)
            if back is not None:
                assert (ra + back) * (rb - 10**3) >= ra * rb


# ---------------------------------------------------------------------------
# fee bump
# ---------------------------------------------------------------------------

class TestFeeBump:
    def test_fee_bump_applies_inner(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        payer = root.create("payer", 10**9)
        inner = a.tx([a.op_payment(b.account_id, 10**6)])
        env = payer.fee_bump(inner, fee_source=payer)

        a_before, b_before, p_before = a.balance(), b.balance(), \
            payer.balance()
        ok, res = payer.apply(env)
        assert res.result.type == TC.txFEE_BUMP_INNER_SUCCESS
        assert b.balance() == b_before + 10**6
        assert a.balance() == a_before - 10**6  # no fee charged to inner
        assert payer.balance() < p_before  # payer paid the fee
        # inner result pair carries the inner hash
        pair = res.result.value
        from stellar_core_tpu.transactions.fee_bump import \
            FeeBumpTransactionFrame
        from .txtest import NETWORK_ID

        frame = FeeBumpTransactionFrame(NETWORK_ID, env)
        assert pair.transactionHash == frame.inner_hash()

    def test_fee_bump_inner_failure_wrapped(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        payer = root.create("payer", 10**9)
        inner = a.tx([a.op_payment(b.account_id, 10**15)])  # underfunded
        env = payer.fee_bump(inner, fee_source=payer)
        ok, res = payer.apply(env, expect_success=False)
        assert not ok
        assert res.result.type == TC.txFEE_BUMP_INNER_FAILED

    def test_fee_bump_check_valid_fee_rules(self, ledger):
        root = ledger.root()
        a = root.create("alice", 10**9)
        b = root.create("bob", 10**9)
        payer = root.create("payer", 10**9)
        from stellar_core_tpu.transactions.fee_bump import \
            FeeBumpTransactionFrame
        from .txtest import NETWORK_ID

        inner = a.tx([a.op_payment(b.account_id, 10**6)], fee=200)
        # outer fee below min fee for 2 "ops": rejected
        env = payer.fee_bump(inner, fee=150, fee_source=payer)
        frame = FeeBumpTransactionFrame(NETWORK_ID, env)
        with LedgerTxn(ledger.root_txn) as ltx:
            res = frame.check_valid(ltx)
            ltx.rollback()
        assert res.code == TC.txINSUFFICIENT_FEE

        # outer fee rate below inner fee rate: rejected
        env = payer.fee_bump(inner, fee=250, fee_source=payer)
        frame = FeeBumpTransactionFrame(NETWORK_ID, env)
        with LedgerTxn(ledger.root_txn) as ltx:
            res = frame.check_valid(ltx)
            ltx.rollback()
        assert res.code == TC.txINSUFFICIENT_FEE

        # healthy fee-bump validates
        env = payer.fee_bump(inner, fee=500, fee_source=payer)
        frame = FeeBumpTransactionFrame(NETWORK_ID, env)
        with LedgerTxn(ledger.root_txn) as ltx:
            res = frame.check_valid(ltx)
            ltx.rollback()
        assert res.ok


# ---------------------------------------------------------------------------
# pool path payments (convertWithOffersAndPools)
# ---------------------------------------------------------------------------

class TestPoolPathPayment:
    def _setup(self, ledger):
        root = ledger.root()
        issuer = root.create("ppp-issuer", 10**11)
        lp = root.create("ppp-lp", 10**11)
        src = root.create("ppp-src", 10**11)
        dst = root.create("ppp-dst", 10**11)
        usd = U.make_asset(b"USD", issuer.account_id)
        for acc in (lp, src, dst):
            acc.apply(acc.tx([acc.op_change_trust(usd)]))
        issuer.apply(issuer.tx([issuer.op_payment(lp.account_id, 10**10,
                                                  usd)]))
        xlm = U.asset_native()
        lp.apply(lp.tx([lp.op_change_trust_pool(xlm, usd)]))
        params = T.LiquidityPoolParameters.make(
            T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
            T.LiquidityPoolConstantProductParameters.make(
                assetA=xlm, assetB=usd, fee=T.LIQUIDITY_POOL_FEE_V18))
        pool_id = LP.pool_id_from_params(params)
        # pool at 1 XLM : 1 USD with deep reserves
        lp.apply(lp.tx([lp.op_pool_deposit(pool_id, 10**9, 10**9)]))
        return root, issuer, lp, src, dst, usd, pool_id

    def _pp_strict_send(self, acc, dest, send_asset, send_amount,
                        dest_asset, dest_min, path=()):
        return acc.op(T.OperationType.PATH_PAYMENT_STRICT_SEND,
                      T.PathPaymentStrictSendOp.make(
                          sendAsset=send_asset, sendAmount=send_amount,
                          destination=T.muxed_account(dest),
                          destAsset=dest_asset, destMin=dest_min,
                          path=list(path)))

    def test_empty_book_routes_through_pool(self, ledger):
        root, issuer, lp, src, dst, usd, pool_id = self._setup(ledger)
        ok, res = src.apply(src.tx([self._pp_strict_send(
            src, dst.account_id, U.asset_native(), 10**6, usd, 1)]))
        success = res.result.value[0].value.value
        atoms = success.value.offers
        assert len(atoms) == 1
        assert atoms[0].type == T.ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL
        la = atoms[0].value
        assert la.amountBought == 10**6          # XLM into the pool
        # ~0.3% fee on a deep 1:1 pool
        assert 0.996 * 10**6 < la.amountSold <= 10**6
        # pool reserves moved
        pool = src.entry(LP.pool_key(pool_id))
        cp = LP.constant_product(pool)
        assert cp.reserveA == 10**9 + 10**6
        assert cp.reserveB == 10**9 - la.amountSold
        # destination got the USD
        tl = dst.entry(trustline_key(dst.account_id, usd))
        assert tl.data.value.balance == la.amountSold

    def test_better_book_price_beats_pool(self, ledger):
        root, issuer, lp, src, dst, usd, pool_id = self._setup(ledger)
        # a seller offering USD at a better-than-pool price (1 USD per
        # 0.5 XLM => the taker gets 2 USD per XLM, pool gives ~1)
        seller = root.create("ppp-seller", 10**11)
        seller.apply(seller.tx([seller.op_change_trust(usd)]))
        issuer.apply(issuer.tx([issuer.op_payment(
            seller.account_id, 10**9, usd)]))
        sell = seller.op(T.OperationType.MANAGE_SELL_OFFER,
                         T.ManageSellOfferOp.make(
                             selling=usd, buying=U.asset_native(),
                             amount=10**8,
                             price=T.Price.make(n=1, d=2), offerID=0))
        seller.apply(seller.tx([sell]))
        ok, res = src.apply(src.tx([self._pp_strict_send(
            src, dst.account_id, U.asset_native(), 10**6, usd, 1)]))
        atoms = res.result.value[0].value.value.value.offers
        assert len(atoms) == 1
        assert atoms[0].type == T.ClaimAtomType.CLAIM_ATOM_TYPE_ORDER_BOOK
        assert atoms[0].value.amountSold == 2 * 10**6  # 2 USD per XLM
        # pool untouched
        cp = LP.constant_product(src.entry(LP.pool_key(pool_id)))
        assert cp.reserveA == 10**9 and cp.reserveB == 10**9

    def test_strict_receive_through_pool(self, ledger):
        root, issuer, lp, src, dst, usd, pool_id = self._setup(ledger)
        op = src.op(T.OperationType.PATH_PAYMENT_STRICT_RECEIVE,
                    T.PathPaymentStrictReceiveOp.make(
                        sendAsset=U.asset_native(), sendMax=2 * 10**6,
                        destination=T.muxed_account(dst.account_id),
                        destAsset=usd, destAmount=10**6, path=[]))
        ok, res = src.apply(src.tx([op]))
        atoms = res.result.value[0].value.value.value.offers
        assert len(atoms) == 1
        assert atoms[0].type == T.ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL
        la = atoms[0].value
        assert la.amountSold == 10**6            # exact USD out
        assert 10**6 < la.amountBought < 1.005 * 10**6  # XLM in + fee
        # pool invariant k did not decrease
        cp = LP.constant_product(src.entry(LP.pool_key(pool_id)))
        assert cp.reserveA * cp.reserveB >= 10**18
