"""Network observatory (ISSUE 19 layers 2+3): fleet-merged propagation
views, the same-seed determinism contract (byte-identical snapshots and
hop records), crank wall attribution, and the tracing on/off
consensus-inertness gate on a chaos scenario."""
import json

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.simulation import core
from stellar_core_tpu.simulation.chaos import run_standard_scenario

from tests.test_simulation import _node_account, settle


def _flooded_sim(trace_on: bool = True):
    """core-3 sim with a tx flooded through consensus and two closes —
    enough traffic for hop records, coverage and cadence views."""
    sim = core(3, FLOOD_TRACE_ENABLED=trace_on)
    sim.attach_observatory()
    sim.start_all_nodes()
    settle(sim)
    app0 = list(sim.nodes.values())[0]
    root = _node_account(app0, SecretKey(app0.config.network_id()))
    dest = SecretKey(sha256(b"observatory"))
    env = root.tx([root.op_create_account(dest.public_key().raw, 10**9)])
    assert app0.herder.recv_transaction(env) == 0
    settle(sim)
    assert sim.close_ledger()
    assert sim.close_ledger()
    settle(sim)
    return sim


def test_observatory_snapshot_shape():
    sim = _flooded_sim()
    snap = sim.observatory.snapshot()
    assert len(snap["nodes"]) == 3
    assert snap["n_items"] >= 1
    tx_items = [it for it in snap["items"].values()
                if it["kind"] == "tx"]
    assert tx_items, "the flooded tx never reached the merged view"
    it = tx_items[0]
    # full coverage on a healthy 3-mesh, with a known origin
    assert it["coverage"] == 1.0
    assert it["origin"] in snap["nodes"]
    assert it["t50"] is not None and it["t90"] is not None
    prop = snap["propagation"]
    assert prop["time_to_90pct"] is not None
    assert prop["time_to_90pct"]["n"] >= 1
    # per-link redundancy rows are keyed node<-peer
    assert snap["links"]
    for key in snap["links"]:
        to, _, frm = key.partition("<-")
        assert to in snap["nodes"] and frm in snap["nodes"]
    # every node reports a close cadence
    assert sorted(snap["close_cadence"]) == snap["nodes"]
    # summary() is snapshot() minus the per-item bulk
    summ = sim.observatory.summary()
    assert "items" not in summ
    assert summ["propagation"] == prop


def test_observatory_endpoint_serves_merged_view():
    from stellar_core_tpu.main.http_server import CommandHandler

    sim = _flooded_sim()
    app = list(sim.nodes.values())[1]
    status, body = CommandHandler(app).handle("network-observatory", {})
    assert status == 200
    assert body["observatory"]["n_items"] >= 1
    # flood?hash= round-trips a merged item through one node's tracker
    h = next(h for h, it in body["observatory"]["items"].items()
             if it["kind"] == "tx")
    served = [a for a in sim.nodes.values()
              if CommandHandler(a).handle("flood", {"hash": h})[0] == 200]
    assert served, "no node serves the flooded item's hop record"
    rec = CommandHandler(served[0]).handle(
        "flood", {"hash": h})[1]["flood"]
    assert rec["hash"] == h
    # a node without an observatory refuses with a pointer to the
    # fleet-scrape path
    app2 = list(sim.nodes.values())[0]
    app2._observatory = None
    assert CommandHandler(app2).handle(
        "network-observatory", {})[0] == 400


def test_same_seed_rerun_is_byte_identical():
    """The determinism satellite: two identically-driven sims produce
    byte-identical hop records AND observatory snapshots (virtual-clock
    stamps, stride sampling and merge order are all deterministic)."""
    blobs = []
    for _ in range(2):
        sim = _flooded_sim()
        exports = {nid.hex()[:8]: app.floodtracer.export()
                   for nid, app in sim.nodes.items()}
        blobs.append((
            json.dumps(sim.observatory.snapshot(), sort_keys=True),
            json.dumps(exports, sort_keys=True)))
    assert blobs[0][0] == blobs[1][0]
    assert blobs[0][1] == blobs[1][1]


def test_crank_profiler_attributes_sim_wall():
    sim = core(3)
    sim.attach_observatory()
    sim.enable_crank_profiler()
    sim.start_all_nodes()
    settle(sim)
    assert sim.close_ledger()
    rep = sim.crank_report()
    assert rep is not None
    assert rep["cranks"] > 0
    assert sum(rep["events"].values()) > 0
    assert rep["measured_wall_s"] > 0
    # a consensus round through the overlay touches all three planes
    for bucket in ("overlay", "consensus", "ledger"):
        assert rep["buckets_s"].get(bucket, 0.0) > 0.0, \
            (bucket, rep["buckets_s"])
    assert 0.0 <= rep["attributed_pct"] <= 100.0


def test_fleet_scrape_socket_free(tmp_path):
    """tools/fleet_scrape.py against injected fetchers: JSONL lines per
    node per round, unreachable nodes quarantined, fleet roll-up math."""
    from tools import fleet_scrape

    docs = {
        "n1:11626": {
            "info": {"info": {"ledger": {"num": 42}}},
            "metrics": {"metrics": {
                "ledger.ledger.close": {"p50": 0.02, "count": 40},
                "overlay.flood.unique": {"count": 30},
                "overlay.flood.duplicate": {"count": 10}}},
            "vitals": {"vitals": {"samples": 5}},
            "flood?last=4": {"flood": {
                "stride": 1, "tracked": 9, "live": 4, "retired": 5,
                "links": {"ab12cd34": {"unique": 3, "duplicate": 1,
                                       "dup_ratio": 0.25}}}},
        },
        "n2:11626": {
            "info": {"info": {"ledger": {"num": 40}}},
            "metrics": {"metrics": {
                "overlay.flood.unique": {"count": 10},
                "overlay.flood.duplicate": {"count": 10}}},
        },
    }

    def fetch(base, path, timeout):
        if base == "dead:1":
            raise OSError("connection refused")
        body = docs[base].get(path)
        if body is None:
            raise KeyError(path)
        return body

    out = tmp_path / "fleet.jsonl"
    summary = fleet_scrape.run(
        ["n1:11626", "n2:11626", "dead:1"], rounds=2, interval=0.0,
        out_path=str(out), fetch=fetch, sleep=lambda s: None,
        now=lambda: 1000.0)
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(lines) == 2 * 3 + 1  # rounds x nodes + summary
    assert lines[-1]["summary"] == summary
    fleet = summary["fleet"]
    assert fleet["n_reachable"] == 2
    assert fleet["ledger_min"] == 40 and fleet["ledger_max"] == 42
    assert fleet["ledger_spread"] == 2
    assert fleet["flood_unique_total"] == 40
    assert fleet["flood_redundancy"] == round(20 / 60, 4)
    assert summary["unreachable"][0]["node"] == "dead:1"
    n1 = summary["nodes"]["n1:11626"]
    assert n1["close_p50_s"] == 0.02
    assert n1["trace_stats"]["tracked"] == 9
    assert summary["links"]["n1:11626<-ab12cd34"]["dup_ratio"] == 0.25
    # vitals/flood failures are best-effort, not fatal
    n2 = summary["nodes"]["n2:11626"]
    assert n2["flood_unique"] == 10 and "links" not in n2


def test_tracing_on_off_fingerprints_identical(tmp_path):
    """Inertness on a chaos run: flood tracing on vs off must leave the
    partition_heal scenario's per-node ledger-hash fingerprint
    untouched (the full hashes+meta digest gate is the netobs bench)."""
    fps = []
    for d, on in (("on", True), ("off", False)):
        rep = run_standard_scenario(
            lambda: core(4, persist_dir=str(tmp_path / d),
                         MANUAL_CLOSE=False, FLOOD_TRACE_ENABLED=on),
            "partition_heal", seed=11, n_nodes=4, duration=12.0)
        assert rep["fork_check"] == "pass"
        fps.append(rep["fingerprint"])
    assert fps[0] == fps[1]
