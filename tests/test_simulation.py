"""Multi-node consensus networks over the loopback overlay
(ref test model: src/simulation tests + HerderTests' multi-node cases).
"""
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.overlay.peer import PeerState
from stellar_core_tpu.simulation import Simulation, core, cycle, pair
from stellar_core_tpu.xdr import types as T
from stellar_core_tpu.xdr import overlay_types as O

from tests.txtest import TestAccount


def _node_account(app, secret):
    class _Acct(TestAccount):
        def __init__(self, app, secret):
            self.app = app
            self.secret = secret
            self.account_id = secret.public_key().raw

        def network_id(self):
            return self.app.config.network_id()

        @property
        def ledger(self):
            class _L:
                root_txn = self.app.ledger_manager.root
            return _L()

    return _Acct(app, secret)


def settle(sim, rounds=200):
    for _ in range(rounds):
        if sim.crank() == 0:
            break


def test_pair_handshake_and_close():
    sim = pair()
    sim.start_all_nodes()
    settle(sim)
    for app in sim.nodes.values():
        assert app.overlay_manager.connection_count() == 1
    assert sim.close_ledger()
    sim.assert_in_sync()


def test_core4_runs_many_rounds():
    sim = core(4)
    sim.start_all_nodes()
    settle(sim)
    for expected in range(2, 7):
        assert sim.close_ledger(), f"round {expected} stuck"
        sim.assert_in_sync()
        assert all(a.ledger_manager.last_closed_seq() == expected
                   for a in sim.nodes.values())


def test_cycle6_topology_converges():
    sim = cycle(6)
    sim.start_all_nodes()
    settle(sim)
    assert sim.close_ledger(timeout=200)
    sim.assert_in_sync()


def test_transaction_floods_and_applies_network_wide():
    sim = core(3)
    sim.start_all_nodes()
    settle(sim)

    # submit a tx at node 0: root creates an account
    apps = list(sim.nodes.values())
    app0 = apps[0]
    root_sk = SecretKey(app0.config.network_id())

    root = _node_account(app0, root_sk)
    dest = SecretKey(sha256(b"simdest"))
    env = root.tx([root.op_create_account(dest.public_key().raw, 10**9)])
    assert app0.herder.recv_transaction(env) == 0
    settle(sim)  # flood
    # every node's queue has it
    for app in apps:
        assert app.herder.tx_queue.size() == 1

    assert sim.close_ledger()
    sim.assert_in_sync()
    # the account exists on ALL nodes
    for app in apps:
        with LedgerTxn(app.ledger_manager.root) as ltx:
            e = ltx.load_account(dest.public_key().raw)
            ltx.rollback()
        assert e is not None and e.data.value.balance == 10**9


def test_node_crash_quorum_still_closes():
    # 4 nodes threshold 3: one silent node must not stop the network
    sim = core(4)
    sim.start_all_nodes()
    settle(sim)
    apps = list(sim.nodes.values())
    dead = apps[3]
    dead.overlay_manager.shutdown()  # drops all its connections
    settle(sim)
    live = apps[:3]
    target = max(a.ledger_manager.last_closed_seq() for a in live) + 1
    for a in live:
        a.herder.trigger_next_ledger()
    ok = sim.crank_until(
        lambda: all(a.ledger_manager.last_closed_seq() >= target
                    for a in live), 120)
    assert ok
    hashes = {a.ledger_manager.last_closed_hash() for a in live}
    assert len(hashes) == 1


def test_wrong_network_rejected():
    sim = pair()
    other = Simulation(network_passphrase="some other network")
    seed = sha256(b"intruder")
    from stellar_core_tpu.crypto import SecretKey as SK

    nid = SK(seed).public_key().raw
    intruder = other.add_node(seed, {"threshold": 1, "validators": [nid]})
    # wire intruder into sim's clock so messages actually flow
    intruder.clock = sim.clock
    sim.start_all_nodes()
    other.start_all_nodes()
    from stellar_core_tpu.overlay.peer import make_loopback_pair

    a_id = list(sim.nodes)[0]
    p1, p2 = make_loopback_pair(intruder, sim.nodes[a_id])
    settle(sim)
    assert p1.state == PeerState.CLOSING or \
        intruder.overlay_manager.connection_count() == 0


def test_mac_tamper_closes_connection():
    sim = pair()
    sim.start_all_nodes()
    settle(sim)
    a, b = list(sim.nodes.values())
    peer_ab = list(a.overlay_manager.authenticated.values())[0]
    # inject damage on the authenticated link, then force traffic
    peer_ab.set_damage(damage=1.0)
    peer_ab.send_message(O.StellarMessage.make(
        O.MessageType.GET_SCP_STATE, 0))
    settle(sim)
    # receiving side must have dropped the connection (mac failure)
    assert b.overlay_manager.connection_count() == 0


def test_flood_dedup():
    sim = core(3)
    sim.start_all_nodes()
    settle(sim)
    apps = list(sim.nodes.values())
    app0 = apps[0]
    before = {id(a): a.herder.tx_queue.size() for a in apps}
    root_sk = SecretKey(app0.config.network_id())

    root = _node_account(app0, root_sk)
    dest = SecretKey(sha256(b"dedup")).public_key().raw
    env = root.tx([root.op_create_account(dest, 10**9)])
    app0.herder.recv_transaction(env)
    settle(sim)
    # each node processed the tx exactly once despite the full mesh
    for a in apps:
        assert a.herder.tx_queue.size() == 1


def test_loadgen_modes_and_generateload_route():
    """PRETEND + MIXED_TXS load shapes flow through the real tx queue and
    close; the generateload HTTP handler drives them (ref
    LoadGenerator.h:28-36, CommandHandler.cpp:125)."""
    from stellar_core_tpu.main import Application, test_config
    from stellar_core_tpu.main.http_server import CommandHandler
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.utils.clock import ClockMode, VirtualClock

    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config(
        UPGRADE_DESIRED_MAX_TX_SET_SIZE=300))
    app.start()
    app.herder.manual_close()
    handler = CommandHandler(app)
    # staged seeding: create -> close -> trustlines -> close -> funding
    # -> close -> load (every stage is REAL transactions so the bucket
    # commitment covers the seeded state)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": "50"})
    assert code == 200, body
    app.herder.manual_close()
    for _ in range(3):  # issuer, trustlines, funding stages
        code, body = handler.handle(
            "generateload", {"mode": "mixed", "txs": "120"})
        assert code == 200 and "note" in body, body
        app.herder.manual_close()
        assert app.herder.tx_queue.size() == 0
    code, body = handler.handle(
        "generateload", {"mode": "mixed", "txs": "120", "dexpct": "40"})
    assert code == 200, body
    assert body["status_counts"] == {0: 120}
    seq_before = app.ledger_manager.last_closed_seq()
    app.herder.manual_close()
    assert app.ledger_manager.last_closed_seq() == seq_before + 1
    assert app.herder.tx_queue.size() == 0
    # offers actually made it into the book
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

    with LedgerTxn(app.ledger_manager.root) as ltx:
        n_offers = app.database.execute(
            "SELECT COUNT(*) FROM offers").fetchone()[0]
        ltx.rollback()
    assert n_offers > 0
    code, body = handler.handle("generateload",
                                {"mode": "pretend", "txs": "40"})
    assert code == 200, body
    assert body["status_counts"] == {0: 40}
    app.herder.manual_close()
    assert app.herder.tx_queue.size() == 0
