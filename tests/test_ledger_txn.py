"""LedgerTxn nesting semantics (ref model: src/ledger/test/
LedgerTxnTests.cpp)."""
import pytest

from stellar_core_tpu.ledger import (
    LedgerTxn, LedgerTxnError, LedgerTxnRoot, entry_to_key, open_database,
)
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.xdr import types as T

from tests.txtest import TestLedger


@pytest.fixture()
def ledger():
    return TestLedger()


def acct(i: int, balance=10**9):
    return U.make_account_entry(bytes([i]) * 32, balance)


def test_nested_commit_and_rollback(ledger):
    root = ledger.root_txn
    with LedgerTxn(root) as l1:
        l1.put(acct(1))
        with LedgerTxn(l1) as l2:
            l2.put(acct(2))
            l2.rollback()
        with LedgerTxn(l1) as l3:
            l3.put(acct(3))
            l3.commit()
        l1.commit()
    with LedgerTxn(root) as chk:
        assert chk.load_account(b"\x01" * 32) is not None
        assert chk.load_account(b"\x02" * 32) is None
        assert chk.load_account(b"\x03" * 32) is not None
        chk.rollback()


def test_single_child_enforced(ledger):
    with LedgerTxn(ledger.root_txn) as l1:
        l2 = LedgerTxn(l1)
        with pytest.raises(LedgerTxnError):
            LedgerTxn(l1)
        l2.rollback()
        l1.rollback()


def test_erase_and_shadowing(ledger):
    root = ledger.root_txn
    with LedgerTxn(root) as l1:
        l1.put(acct(1))
        l1.commit()
    with LedgerTxn(root) as l1:
        e = l1.load_account(b"\x01" * 32)
        l1.erase(entry_to_key(e))
        assert l1.load_account(b"\x01" * 32) is None
        with LedgerTxn(l1) as l2:
            # child sees parent's delta
            assert l2.load_account(b"\x01" * 32) is None
            l2.put(acct(1, balance=5))
            l2.commit()
        assert l1.load_account(b"\x01" * 32).data.value.balance == 5
        l1.rollback()
    # rollback: original survives
    with LedgerTxn(root) as chk:
        assert chk.load_account(b"\x01" * 32).data.value.balance == 10**9
        chk.rollback()


def test_changes_meta(ledger):
    root = ledger.root_txn
    with LedgerTxn(root) as l1:
        l1.put(acct(1))
        l1.commit()
    with LedgerTxn(root) as l1:
        e = l1.load_account(b"\x01" * 32)
        l1.put(e._replace(data=T.LedgerEntryData.make(
            T.LedgerEntryType.ACCOUNT,
            e.data.value._replace(balance=42))))
        l1.put(acct(2))
        changes = l1.changes()
        l1.rollback()
    CT = T.LedgerEntryChangeType
    kinds = [c.type for c in changes]
    assert kinds.count(CT.LEDGER_ENTRY_STATE) == 1
    assert kinds.count(CT.LEDGER_ENTRY_UPDATED) == 1
    assert kinds.count(CT.LEDGER_ENTRY_CREATED) == 1


def test_erase_nonexistent_raises(ledger):
    with LedgerTxn(ledger.root_txn) as l1:
        e = acct(9)
        with pytest.raises(LedgerTxnError):
            l1.erase(entry_to_key(e))
        l1.rollback()


def test_last_modified_stamping(ledger):
    with LedgerTxn(ledger.root_txn) as l1:
        l1.put(acct(1))
        got = l1.load_account(b"\x01" * 32)
        assert got.lastModifiedLedgerSeq == l1.header().ledgerSeq
        l1.rollback()


def test_best_offer_with_uncommitted_overrides(ledger):
    root = ledger.root_txn
    seller = b"\x05" * 32
    usd = U.make_asset(b"USD", b"\x06" * 32)
    xlm = U.asset_native()

    def offer(oid, n, d):
        oe = T.OfferEntry.make(
            sellerID=T.account_id(seller), offerID=oid,
            selling=usd, buying=xlm, amount=100,
            price=T.Price.make(n=n, d=d), flags=0,
            ext=T.OfferEntry.fields[7][1].make(0))
        return U.wrap_entry(T.LedgerEntryType.OFFER, oe)

    with LedgerTxn(root) as l1:
        l1.put(offer(1, 2, 1))  # price 2.0
        l1.put(offer(2, 1, 1))  # price 1.0 (best)
        l1.commit()
    sell_b, buy_b = T.Asset.encode(usd), T.Asset.encode(xlm)
    with LedgerTxn(root) as l1:
        best = l1.best_offer(sell_b, buy_b)
        assert best.data.value.offerID == 2
        # shadow the best offer in the open txn
        l1.erase(entry_to_key(best))
        best2 = l1.best_offer(sell_b, buy_b)
        assert best2.data.value.offerID == 1
        # add an even better uncommitted offer
        l1.put(offer(3, 1, 2))  # price 0.5
        best3 = l1.best_offer(sell_b, buy_b)
        assert best3.data.value.offerID == 3
        l1.rollback()


def test_entry_cache_and_prefetch():
    """Root entry cache: prefetch bulk-loads (incl. negative results),
    get() hits the cache, and commits write through — deletes included
    (ref LedgerTxnRoot::prefetch + EntryCache)."""
    from stellar_core_tpu.ledger.ledger_txn import key_bytes

    ledger = TestLedger()
    root = ledger.root_txn
    accounts = [U.make_account_entry(bytes([i]) * 32, 10 ** 9, seq_num=1)
                for i in range(1, 6)]
    with LedgerTxn(root) as ltx:
        for e in accounts:
            ltx.put(e)
        ltx.commit()
    keys = [entry_to_key(e) for e in accounts]
    kbs = [key_bytes(k) for k in keys]
    missing_kb = key_bytes(entry_to_key(
        U.make_account_entry(b"\x77" * 32, 1, seq_num=1)))

    root.clear_entry_cache()
    root.cache_hits = root.cache_misses = 0
    assert root.prefetch(kbs + [missing_kb]) == 6
    for kb in kbs:
        assert root.get(kb) is not None
    assert root.get(missing_kb) is None  # cached negative
    assert root.cache_misses == 0
    assert root.cache_hits == 6
    assert root.prefetch_hit_rate() == 1.0

    # write-through: a committed delete must evict the stale positive
    with LedgerTxn(root) as ltx:
        ltx.erase(keys[0])
        ltx.commit()
    assert root.get(kbs[0]) is None
