"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is tested on
a virtual 8-device CPU mesh (mirrors the reference's strategy of testing
multi-node behavior in one process — SURVEY.md §4.2, ref
src/simulation/Simulation.h:29).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's TPU-tunnel plugin force-overrides jax_platforms to
# "axon,cpu" from sitecustomize, which would make every CPU test try to claim
# the (single) TPU tunnel.  Pin the config back to cpu before any jax op runs.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import random

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _reseed_prngs():
    """Deterministic PRNG re-seeding per test (ref: src/test/test.cpp:57-72)."""
    random.seed(12345)
    np.random.seed(12345)
    yield


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running (kernel interpret / multiprocess)")
