"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is tested on
a virtual 8-device CPU mesh (mirrors the reference's strategy of testing
multi-node behavior in one process — SURVEY.md §4.2, ref
src/simulation/Simulation.h:29).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's TPU-tunnel plugin force-overrides jax_platforms to
# "axon,cpu" from sitecustomize, which would make every CPU test try to claim
# the (single) TPU tunnel.  Pin the config back to cpu before any jax op runs.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The suite's slow tail is XLA kernel compilation on host CPU (~8.5 of
# 10 minutes measured via --durations); the persistent compile cache the
# node tier already uses (utils/device.enable_compilation_cache) makes
# every run after the first skip lowering+compile entirely.  The cache
# only affects compile TIME, never kernel results.
from stellar_core_tpu.utils.device import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

import random

import numpy as np
import pytest

# suite hygiene (VERDICT r4 weak #8): the suite's slow tail is XLA
# kernel COMPILATION on host CPU (~8.5 of 10 minutes measured via
# --durations), not the multi-node sims.  Markers let the inner loop
# pick its lane:
#   pytest -m "not device"          -> ~100s, skips kernel-compile tests
#   pytest -m "not device and not sim" -> fastest correctness loop
# CI/driver runs keep the full default (no -m).
_SIM_HEAVY = {
    "test_tcp_node", "test_history_catchup", "test_simulation",
    "test_consensus_recovery", "test_survey_process",
    "test_standalone_node", "test_peer_manager",
}
_DEVICE_HEAVY = {
    "test_scp_tensor_tally", "test_admission", "test_ed25519_edge",
    "test_ed25519_kernel", "test_field25519",
}


def pytest_configure(config):
    # single hook: a second pytest_configure def would silently shadow
    # this one (that bug left sim/device unregistered until ISSUE 3)
    config.addinivalue_line(
        "markers", "sim: multi-node / subprocess simulation tests")
    config.addinivalue_line(
        "markers", "device: jit/pallas kernel tests dominated by XLA "
                   "compilation on host CPU")
    config.addinivalue_line(
        "markers", "slow: long-running (kernel interpret / multiprocess)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in _SIM_HEAVY:
            item.add_marker(pytest.mark.sim)
        if mod in _DEVICE_HEAVY:
            item.add_marker(pytest.mark.device)


@pytest.fixture(autouse=True)
def _reseed_prngs():
    """Deterministic PRNG re-seeding per test (ref: src/test/test.cpp:57-72)."""
    random.seed(12345)
    np.random.seed(12345)
    yield
