"""Golden tx-result/meta regression gate (component #38; modeled on the
reference's test-tx-meta-baseline-current/ corpus, ref src/test/ +
check-nondet).

A fixed scenario suite closes ledgers; for each scenario the sha256 of the
XDR TransactionResultSet, the concatenated tx metas, and the final ledger
header are recorded.  The committed GOLDEN.json pins them: any change to
apply-path semantics that alters results bit-for-bit fails here and forces
a deliberate baseline regeneration (GOLDEN_REGEN=1 pytest ...).

The reference corpus itself is keyed to the reference's own Catch2 cases
and cannot be replayed without them; this gate applies the same
bit-identical discipline to this framework's canonical scenarios.
"""
import hashlib
import json
import os

import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.transactions import liquidity_pool as LP
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

from .txtest import TestAccount

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden",
                           "GOLDEN.json")


class NodeAccount(TestAccount):
    def __init__(self, app, secret):
        self.app = app
        self.secret = secret
        self.account_id = secret.public_key().raw

    @property
    def ledger(self):
        class _L:
            root_txn = self.app.ledger_manager.root
        return _L()


def _app():
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), test_config())
    app.start()
    return app


def _digest(app, from_seq: int) -> dict:
    """Scenario digest: results + metas + final header."""
    rows = app.database.execute(
        "SELECT ledgerseq, txindex, txresult, txmeta FROM txhistory "
        "WHERE ledgerseq >= ? ORDER BY ledgerseq, txindex",
        (from_seq,)).fetchall()
    hres = hashlib.sha256()
    hmeta = hashlib.sha256()
    for _, _, res, meta in rows:
        hres.update(res)
        hmeta.update(meta)
    return {
        "results": hres.hexdigest(),
        "metas": hmeta.hexdigest(),
        "header": app.ledger_manager.last_closed_hash().hex(),
        "n_txs": len(rows),
    }


def scenario_payments(app):
    root = NodeAccount(app, SecretKey(app.config.network_id()))
    a = NodeAccount(app, SecretKey(sha256(b"g-alice")))
    b = NodeAccount(app, SecretKey(sha256(b"g-bob")))
    seq = root.next_seq()
    app.herder.recv_transaction(root.tx(
        [root.op_create_account(a.account_id, 10**10)], seq=seq))
    app.herder.recv_transaction(root.tx(
        [root.op_create_account(b.account_id, 10**10)], seq=seq + 1))
    app.herder.manual_close()
    app.herder.recv_transaction(a.tx([a.op_payment(b.account_id, 10**7)]))
    app.herder.recv_transaction(b.tx([b.op_payment(a.account_id, 3)]))
    app.herder.manual_close()
    # a failing payment (underfunded) is part of the baseline too
    app.herder.recv_transaction(a.tx(
        [a.op_payment(b.account_id, 10**17)]))
    app.herder.manual_close()


def scenario_trust_and_dex(app):
    root = NodeAccount(app, SecretKey(app.config.network_id()))
    issuer = NodeAccount(app, SecretKey(sha256(b"g-issuer")))
    m1 = NodeAccount(app, SecretKey(sha256(b"g-m1")))
    m2 = NodeAccount(app, SecretKey(sha256(b"g-m2")))
    seq = root.next_seq()
    for i, acc in enumerate((issuer, m1, m2)):
        app.herder.recv_transaction(root.tx(
            [root.op_create_account(acc.account_id, 10**10)], seq=seq + i))
    app.herder.manual_close()
    usd = U.make_asset(b"USD", issuer.account_id)
    app.herder.recv_transaction(m1.tx([m1.op_change_trust(usd)]))
    app.herder.recv_transaction(m2.tx([m2.op_change_trust(usd)]))
    app.herder.manual_close()
    app.herder.recv_transaction(issuer.tx(
        [issuer.op_payment(m1.account_id, 10**9, usd)]))
    app.herder.manual_close()
    # cross an offer: m1 sells USD for XLM, m2 buys
    sell = m1.op(T.OperationType.MANAGE_SELL_OFFER,
                 T.ManageSellOfferOp.make(
                     selling=usd, buying=U.asset_native(),
                     amount=10**6, price=T.Price.make(n=2, d=1),
                     offerID=0))
    app.herder.recv_transaction(m1.tx([sell]))
    app.herder.manual_close()
    buy = m2.op(T.OperationType.MANAGE_SELL_OFFER,
                T.ManageSellOfferOp.make(
                    selling=U.asset_native(), buying=usd,
                    amount=3 * 10**6, price=T.Price.make(n=1, d=2),
                    offerID=0))
    app.herder.recv_transaction(m2.tx([buy]))
    app.herder.manual_close()


def scenario_sponsorship_cb_pool(app):
    root = NodeAccount(app, SecretKey(app.config.network_id()))
    sp = NodeAccount(app, SecretKey(sha256(b"g-sponsor")))
    issuer = NodeAccount(app, SecretKey(sha256(b"g-poolissuer")))
    a = NodeAccount(app, SecretKey(sha256(b"g-pool-a")))
    seq = root.next_seq()
    for i, acc in enumerate((sp, issuer, a)):
        app.herder.recv_transaction(root.tx(
            [root.op_create_account(acc.account_id, 10**10)], seq=seq + i))
    app.herder.manual_close()
    # sponsored zero-balance account
    newbie = NodeAccount(app, SecretKey(sha256(b"g-newbie")))
    env = sp.tx([
        sp.op_begin_sponsoring(newbie.account_id),
        sp.op_create_account(newbie.account_id, 0),
        sp.op_end_sponsoring(source=newbie.account_id),
    ], extra_signers=[newbie.secret])
    app.herder.recv_transaction(env)
    app.herder.manual_close()
    # claimable balance lifecycle
    env = a.tx([a.op_create_claimable_balance(
        U.asset_native(), 5 * 10**6, [(sp.account_id, None)])])
    app.herder.recv_transaction(env)
    app.herder.manual_close()
    row = app.database.execute(
        "SELECT txresult FROM txhistory WHERE ledgerseq=?",
        (app.ledger_manager.last_closed_seq(),)).fetchone()
    bid = T.TransactionResultPair.decode(
        row[0]).result.result.value[0].value.value.value
    app.herder.recv_transaction(sp.tx([sp.op_claim_claimable_balance(bid)]))
    app.herder.manual_close()
    # pool lifecycle + fee bump
    usd = U.make_asset(b"PUSD", issuer.account_id)
    app.herder.recv_transaction(a.tx([a.op_change_trust(usd)]))
    app.herder.manual_close()
    app.herder.recv_transaction(issuer.tx(
        [issuer.op_payment(a.account_id, 10**9, usd)]))
    app.herder.manual_close()
    app.herder.recv_transaction(a.tx(
        [a.op_change_trust_pool(U.asset_native(), usd)]))
    app.herder.manual_close()
    params = T.LiquidityPoolParameters.make(
        T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
        T.LiquidityPoolConstantProductParameters.make(
            assetA=U.asset_native(), assetB=usd,
            fee=T.LIQUIDITY_POOL_FEE_V18))
    pool_id = LP.pool_id_from_params(params)
    app.herder.recv_transaction(a.tx(
        [a.op_pool_deposit(pool_id, 4 * 10**6, 10**6)]))
    app.herder.manual_close()
    inner = a.tx([a.op_payment(sp.account_id, 1234)])
    app.herder.recv_transaction(sp.fee_bump(inner, fee_source=sp))
    app.herder.manual_close()


def scenario_revocation(app):
    """Offer liabilities + full auth revocation: pulled offers and
    CAP-38 pool-share redemption into claimable balances."""
    root = NodeAccount(app, SecretKey(app.config.network_id()))
    issuer = NodeAccount(app, SecretKey(sha256(b"g-rv-issuer")))
    trader = NodeAccount(app, SecretKey(sha256(b"g-rv-trader")))
    seq = root.next_seq()
    for i, acc in enumerate((issuer, trader)):
        app.herder.recv_transaction(root.tx(
            [root.op_create_account(acc.account_id, 10**10)],
            seq=seq + i))
    app.herder.manual_close()
    app.herder.recv_transaction(issuer.tx([issuer.op_set_options(
        set_flags=T.AUTH_REQUIRED_FLAG | T.AUTH_REVOCABLE_FLAG)]))
    app.herder.manual_close()
    usd = U.make_asset(b"RUSD", issuer.account_id)
    app.herder.recv_transaction(trader.tx([trader.op_change_trust(usd)]))
    app.herder.manual_close()
    app.herder.recv_transaction(issuer.tx([
        issuer.op(T.OperationType.SET_TRUST_LINE_FLAGS,
                  T.SetTrustLineFlagsOp.make(
                      trustor=T.account_id(trader.account_id), asset=usd,
                      clearFlags=0, setFlags=T.AUTHORIZED_FLAG)),
        issuer.op_payment(trader.account_id, 10**6, usd)]))
    app.herder.manual_close()
    # a resting offer (liabilities acquired) + a pool-share deposit
    app.herder.recv_transaction(trader.tx([trader.op(
        T.OperationType.MANAGE_SELL_OFFER,
        T.ManageSellOfferOp.make(
            selling=usd, buying=U.asset_native(), amount=1000,
            price=T.Price.make(n=3, d=2), offerID=0))]))
    app.herder.manual_close()
    app.herder.recv_transaction(trader.tx(
        [trader.op_change_trust_pool(U.asset_native(), usd)]))
    app.herder.manual_close()
    params = T.LiquidityPoolParameters.make(
        T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
        T.LiquidityPoolConstantProductParameters.make(
            assetA=U.asset_native(), assetB=usd,
            fee=T.LIQUIDITY_POOL_FEE_V18))
    app.herder.recv_transaction(trader.tx([trader.op_pool_deposit(
        LP.pool_id_from_params(params), 3 * 10**5, 10**5)]))
    app.herder.manual_close()
    # full revocation: offer pulled, pool shares parked in CBs
    app.herder.recv_transaction(issuer.tx([issuer.op(
        T.OperationType.SET_TRUST_LINE_FLAGS,
        T.SetTrustLineFlagsOp.make(
            trustor=T.account_id(trader.account_id), asset=usd,
            clearFlags=T.AUTHORIZED_FLAG, setFlags=0))]))
    app.herder.manual_close()


SCENARIOS = {
    "payments": scenario_payments,
    "trust_and_dex": scenario_trust_and_dex,
    "sponsorship_cb_pool_feebump": scenario_sponsorship_cb_pool,
    "revocation": scenario_revocation,
}


def _compute_all() -> dict:
    out = {}
    for name, fn in SCENARIOS.items():
        app = _app()
        fn(app)
        out[name] = _digest(app, from_seq=2)
    return out


def test_golden_baseline():
    computed = _compute_all()
    if os.environ.get("GOLDEN_REGEN") == "1":
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as f:
            json.dump(computed, f, indent=1, sort_keys=True)
        pytest.skip("baseline regenerated")
    if not os.path.exists(GOLDEN_PATH):
        pytest.fail("GOLDEN.json missing — run with GOLDEN_REGEN=1")
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    assert computed == golden, (
        "tx results/meta diverged from the golden baseline; if the change "
        "is intentional, regenerate with GOLDEN_REGEN=1")


def test_baseline_is_deterministic():
    """Two independent runs must produce identical digests (the
    check-nondet discipline)."""
    assert _compute_all() == _compute_all()
