"""Flood-propagation tracker (ISSUE 19 tentpole, layer 1): the
deterministic sampling gate, bounded live map + retirement ring,
per-link dedup attribution with the reconnect reset (satellite fix),
and the end-to-end hop records a flooding sim actually produces."""
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.simulation import core
from stellar_core_tpu.utils.floodtrace import FloodPropagationTracker
from stellar_core_tpu.utils.metrics import MetricsRegistry

from tests.test_simulation import _node_account, settle


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t


def _tracker(**kw):
    clk = FakeClock()
    ft = FloodPropagationTracker(metrics=MetricsRegistry(),
                                 now=clk.now, **kw)
    return ft, clk


def _h(i: int) -> bytes:
    return sha256(i.to_bytes(4, "big"))


# ---------------------------------------------------------------------------
# sampling gate + bounded memory
# ---------------------------------------------------------------------------

def test_identical_drive_produces_identical_exports():
    """The determinism contract: hop records are a pure function of the
    stamp sequence (no PRNG, no wallclock — the injected clock is the
    only time source)."""
    outs = []
    for _ in range(2):
        ft, clk = _tracker(max_live=8, ring=4)
        for i in range(40):
            clk.t += 0.25
            ft.note_recv(_h(i), "aa" * 4, True, "tx", i)
            ft.note_recv(_h(i), "bb" * 4, False, "tx", i)
            ft.note_forward(_h(i), 3)
        ft.retire([_h(i) for i in range(20)])
        outs.append((ft.export(), ft.stats(),
                     ft.report(last=8)))
    assert outs[0] == outs[1]


def test_decimation_bounds_live_map_and_doubles_stride():
    ft, clk = _tracker(max_live=8)
    for i in range(100):
        clk.t += 0.1
        ft.note_origin(_h(i), "tx", i)
    st = ft.stats()
    assert st["live"] < 8
    assert st["stride"] > 1 and st["stride"] & (st["stride"] - 1) == 0
    assert st["decimations"] >= 1
    assert st["seen"] == 100
    # the survivors are a systematic sample: re-driving the same
    # sequence keeps the same survivor set
    ft2, clk2 = _tracker(max_live=8)
    for i in range(100):
        clk2.t += 0.1
        ft2.note_origin(_h(i), "tx", i)
    assert sorted(ft.export()) == sorted(ft2.export())


def test_retire_moves_records_to_ring_and_lookup_still_finds_them():
    ft, clk = _tracker(max_live=64, ring=4)
    for i in range(3):
        clk.t += 1.0
        ft.note_recv(_h(i), "aa" * 4, True, "tx", i)
    ft.retire([_h(0), _h(1)])
    st = ft.stats()
    assert st["retired"] == 2 and st["live"] == 1
    rec = ft.lookup(_h(0))
    assert rec is not None and rec["hash"] == _h(0).hex()
    # the ring is bounded: retiring more than maxlen drops the oldest
    for i in range(3, 10):
        clk.t += 1.0
        ft.note_recv(_h(i), "aa" * 4, True, "tx", i)
    ft.retire([_h(i) for i in range(3, 10)])
    assert ft.lookup(_h(0)) is None  # evicted from the 4-deep ring
    assert ft.lookup(_h(9)) is not None


def test_disabled_tracker_is_inert():
    ft, clk = _tracker()
    ft.enabled = False
    clk.t += 1.0
    ft.note_recv(_h(1), "aa" * 4, True, "tx", 1)
    ft.note_origin(_h(2), "tx", 1)
    ft.note_forward(_h(1), 5)
    ft.retire([_h(1)])
    assert ft.export() == {}
    assert ft.stats()["seen"] == 0
    assert ft.metrics.snapshot() == {}


# ---------------------------------------------------------------------------
# duplicate attribution + the reconnect reset (satellite fix)
# ---------------------------------------------------------------------------

def test_duplicate_attribution_and_lag():
    ft, clk = _tracker()
    clk.t = 10.0
    ft.note_recv(_h(1), "11" * 4, True, "tx", 5)
    clk.t = 10.3
    ft.note_recv(_h(1), "22" * 4, False, "tx", 5)
    clk.t = 10.9
    ft.note_recv(_h(1), "22" * 4, False, "tx", 5)
    rec = ft.lookup(_h(1))
    assert rec["from"] == "11" * 4 and rec["origin"] is False
    assert rec["dups"] == 2
    assert rec["dup_links"] == {"22" * 4: 2}
    assert rec["dup_first_lag"] == pytest.approx(0.3)
    links = ft.report(last=0)["links"]
    assert links["11" * 4]["unique"] == 1
    assert links["11" * 4]["dup_ratio"] == 0.0
    assert links["22" * 4]["duplicate"] == 2
    assert links["22" * 4]["dup_ratio"] == 1.0


def test_forget_link_resets_per_connection_counters():
    """The reconnect-churn fix: a link's unique/duplicate counters
    describe the CURRENT connection only."""
    ft, clk = _tracker()
    for i in range(4):
        clk.t += 1.0
        ft.note_recv(_h(i), "aa" * 4, True, "tx", i)
        ft.note_recv(_h(i), "aa" * 4, False, "tx", i)
    assert ft.report(last=0)["links"]["aa" * 4]["unique"] == 4
    ft.forget_link("aa" * 4)
    links = ft.report(last=0)["links"]
    assert links["aa" * 4]["unique"] == 0
    assert links["aa" * 4]["duplicate"] == 0
    # and the NEXT connection's traffic counts from zero, not four
    clk.t += 1.0
    ft.note_recv(_h(99), "aa" * 4, True, "tx", 9)
    assert ft.report(last=0)["links"]["aa" * 4]["unique"] == 1


# ---------------------------------------------------------------------------
# end-to-end: a flooding sim writes hop records at every node
# ---------------------------------------------------------------------------

def _submit_create_account(app, salt: bytes):
    root = _node_account(app, SecretKey(app.config.network_id()))
    dest = SecretKey(sha256(salt))
    env = root.tx([root.op_create_account(dest.public_key().raw, 10**9)])
    assert app.herder.recv_transaction(env) == 0


def test_sim_flood_produces_hop_records_network_wide():
    sim = core(3, FLOOD_TRACE_ENABLED=True)
    sim.start_all_nodes()
    settle(sim)
    apps = list(sim.nodes.values())
    _submit_create_account(apps[0], b"floodtrace e2e")
    settle(sim)

    pid0 = apps[0].config.node_id().hex()[:8]
    for app in apps[1:]:
        recs = list(app.floodtracer.export().values())
        tx_recs = [r for r in recs if r["kind"] == "tx"]
        assert tx_recs, "relayed tx left no hop record"
        rec = tx_recs[0]
        assert rec["origin"] is False and rec["from"] is not None
        # full mesh of 3: the second copy arrives as a duplicate
        assert rec["dups"] >= 1
    # the origin node records hop zero
    origin_recs = [r for r in apps[0].floodtracer.export().values()
                   if r["kind"] == "tx"]
    assert origin_recs and origin_recs[0]["origin"] is True
    assert origin_recs[0]["from"] is None
    assert origin_recs[0]["fanout"] >= 2
    # per-link attribution shows node 0 feeding at least one peer
    fed = [app for app in apps[1:]
           if pid0 in app.floodtracer.report(last=0)["links"]]
    assert fed, "no peer attributes traffic to the origin's link"


def test_peer_reconnect_resets_link_attribution_in_sim():
    """Satellite fix, end-to-end: dropping a connection zeroes BOTH the
    floodgate have-state and the tracker's per-link counters, so the
    re-dialed link re-floods and its dup-rate attribution restarts."""
    sim = core(3, FLOOD_TRACE_ENABLED=True)
    sim.start_all_nodes()
    settle(sim)
    ids = list(sim.nodes)
    apps = [sim.nodes[i] for i in ids]
    pid0 = ids[0].hex()[:8]

    _submit_create_account(apps[0], b"pre-reconnect")
    settle(sim)
    pre = apps[1].floodtracer.report(last=0)["links"].get(pid0, {})
    assert pre.get("unique", 0) + pre.get("duplicate", 0) >= 1
    # apply tx 1 so the root's seqnum advances for the second submit
    assert sim.close_ledger()
    settle(sim)

    # drop the 0<->1 connection: both overlay managers run peer_closed
    for p in sim.link_peers(ids[0], ids[1]):
        p.close("test reconnect")
    settle(sim)
    links = apps[1].floodtracer.report(last=0)["links"]
    assert links[pid0].get("unique", 0) == 0
    assert links[pid0].get("duplicate", 0) == 0

    # re-dial and flood again: the NEW connection counts from zero
    sim.add_connection(ids[0], ids[1])
    settle(sim)
    assert apps[1].overlay_manager.connection_count() == 2
    _submit_create_account(apps[0], b"post-reconnect")
    settle(sim)
    post = apps[1].floodtracer.report(last=0)["links"][pid0]
    assert post.get("unique", 0) + post.get("duplicate", 0) >= 1
    # the re-flood reached every node regardless of the churn
    for app in apps:
        assert app.herder.tx_queue.size() == 1
