"""Fuzz harnesses must survive their corpora crash-free
(ref src/test/FuzzerImpl + docs/fuzzing.md; VERDICT r2 component #37)."""
from stellar_core_tpu.fuzzing import OverlayFuzzer, TxFuzzer, XdrFuzzer


def test_tx_fuzzer_survives():
    crashes = TxFuzzer(seed=1).run(300)
    assert crashes == []


def test_tx_fuzzer_other_seeds():
    for seed in (7, 42):
        assert TxFuzzer(seed=seed).run(150) == []


def test_overlay_fuzzer_survives():
    crashes = OverlayFuzzer(seed=3).run(300)
    assert crashes == []


def test_xdr_fuzzer_survives():
    crashes = XdrFuzzer(seed=5).run(2000)
    assert crashes == []
